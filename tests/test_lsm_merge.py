"""LSM merge compaction: merge_build must be bit-identical to a full build
(reference: lambda-architecture compaction — SURVEY.md §2.11)."""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.index.z3 import Z3Index
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000
SPEC = "name:String,dtg:Date,*geom:Point"


def _table(sft, n, seed, fid_base=0):
    rng = np.random.default_rng(seed)
    recs = [
        {
            "name": f"n{i % 4}",
            "dtg": T0 + int(rng.integers(0, 21 * 86_400_000)),
            "geom": Point(float(rng.uniform(-180, 180)), float(rng.uniform(-90, 90))),
        }
        for i in range(n)
    ]
    return FeatureTable.from_records(sft, recs, [f"f.{fid_base + i}" for i in range(n)])


class TestMergeBuild:
    def test_identical_to_full_build(self):
        sft = parse_spec("m", SPEC)
        main = _table(sft, 20_000, seed=1)
        delta = _table(sft, 1_500, seed=2, fid_base=20_000)
        prev = Z3Index(sft)
        prev.build(main)
        combined = FeatureTable.concat([main, delta])

        full = Z3Index(sft)
        full_perm = full.build(combined)

        inc = Z3Index(sft)
        inc_perm = inc.merge_build(combined, prev, len(main))

        np.testing.assert_array_equal(inc_perm, full_perm)
        np.testing.assert_array_equal(inc.bins, full.bins)
        np.testing.assert_array_equal(inc.zs, full.zs)
        np.testing.assert_array_equal(inc.offsets, full.offsets)
        np.testing.assert_array_equal(inc.bin_values, full.bin_values)
        np.testing.assert_array_equal(inc.bin_starts, full.bin_starts)

    def test_tie_stability(self):
        # identical (bin, z) keys in main and delta: main rows must sort
        # first, matching the stable full sort over [main | delta]
        sft = parse_spec("m", SPEC)
        recs = [{"name": "a", "dtg": T0, "geom": Point(10.0, 10.0)}] * 5
        main = FeatureTable.from_records(sft, recs, [f"a.{i}" for i in range(5)])
        delta = FeatureTable.from_records(sft, recs, [f"b.{i}" for i in range(5)])
        prev = Z3Index(sft)
        prev.build(main)
        combined = FeatureTable.concat([main, delta])
        inc = Z3Index(sft)
        inc_perm = inc.merge_build(combined, prev, 5)
        full = Z3Index(sft)
        full_perm = full.build(combined)
        np.testing.assert_array_equal(inc_perm, full_perm)
        np.testing.assert_array_equal(inc_perm, np.arange(10))

    def test_empty_prev_falls_back(self):
        sft = parse_spec("m", SPEC)
        delta = _table(sft, 100, seed=3)
        prev = Z3Index(sft)  # never built
        inc = Z3Index(sft)
        perm = inc.merge_build(delta, prev, 0)
        full = Z3Index(sft)
        np.testing.assert_array_equal(perm, full.build(delta))


class TestXZ3MergeBuild:
    def test_identical_to_full_build(self):
        from geomesa_tpu.geometry.types import LineString
        from geomesa_tpu.index.z3 import XZ3Index

        sft = parse_spec("x", "dtg:Date,*geom:LineString")
        rng = np.random.default_rng(4)

        def lines(n, base):
            recs = []
            for i in range(n):
                x0, y0 = rng.uniform(-170, 170), rng.uniform(-80, 80)
                recs.append({
                    "dtg": T0 + int(rng.integers(0, 21 * 86_400_000)),
                    "geom": LineString([[x0, y0], [x0 + 1, y0 + 0.5]]),
                })
            return FeatureTable.from_records(sft, recs, [f"{base}.{i}" for i in range(n)])

        main = lines(5000, "m")
        delta = lines(400, "d")
        prev = XZ3Index(sft)
        prev.build(main)
        combined = FeatureTable.concat([main, delta])
        full = XZ3Index(sft)
        full_perm = full.build(combined)
        inc = XZ3Index(sft)
        inc_perm = inc.merge_build(combined, prev, len(main))
        np.testing.assert_array_equal(inc_perm, full_perm)
        np.testing.assert_array_equal(inc.codes, full.codes)
        np.testing.assert_array_equal(inc.bins, full.bins)


class TestStoreCompactionParity:
    @pytest.mark.parametrize("backend", ["oracle", "tpu"])
    def test_incremental_compaction_queries(self, backend):
        sft = parse_spec("s", SPEC)
        ds = DataStore(backend=backend)
        ds.create_schema(sft)
        rng = np.random.default_rng(7)
        # several write+compact cycles exercise merge_build repeatedly
        total = 0
        for cycle in range(4):
            n = 3000
            recs = [
                {
                    "name": f"n{i % 4}",
                    "dtg": T0 + int(rng.integers(0, 21 * 86_400_000)),
                    "geom": Point(float(rng.uniform(-60, 60)), float(rng.uniform(-60, 60))),
                }
                for i in range(n)
            ]
            ds.write("s", recs, fids=[f"c{cycle}.{i}" for i in range(n)])
            ds.compact("s")
            total += n
        r = ds.query("s", "BBOX(geom, -20, -20, 20, 20) AND dtg DURING "
                          "2017-07-03T00:00:00Z/2017-07-12T00:00:00Z")
        # referee: fresh store built in one shot from the same rows
        ref = DataStore(backend="oracle")
        ref.create_schema(parse_spec("s", SPEC))
        ref.write("s", ds._state("s").table)
        r2 = ref.query("s", "BBOX(geom, -20, -20, 20, 20) AND dtg DURING "
                            "2017-07-03T00:00:00Z/2017-07-12T00:00:00Z")
        assert ds.stats_count("s") == total
        assert r.count == r2.count
        assert sorted(r.table.fids) == sorted(r2.table.fids)
