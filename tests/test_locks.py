"""Cross-process catalog locking (DistributedLocking role)."""

import multiprocessing as mp
import time

import pytest

from geomesa_tpu.utils.locks import LockTimeout, catalog_lock


def _hold_lock(path, hold_s, started, release):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from geomesa_tpu.utils.locks import catalog_lock as cl

    with cl(path):
        started.set()
        release.wait(hold_s)


class TestCatalogLock:
    def test_reentrant_sequential(self, tmp_path):
        p = str(tmp_path / "cat")
        with catalog_lock(p):
            pass
        with catalog_lock(p):  # released cleanly, reacquirable
            pass
        assert (tmp_path / "cat" / ".geomesa.lock").exists()

    def test_cross_process_exclusion(self, tmp_path):
        p = str(tmp_path / "cat")
        ctx = mp.get_context("spawn")
        started = ctx.Event()
        release = ctx.Event()
        proc = ctx.Process(target=_hold_lock, args=(p, 30.0, started, release))
        proc.start()
        try:
            assert started.wait(60), "holder never acquired"
            # the lock is genuinely held by the other PROCESS
            with pytest.raises(LockTimeout):
                with catalog_lock(p, timeout_s=0.3, poll_s=0.05):
                    pass
            release.set()
            proc.join(timeout=30)
            # and acquirable again once the holder exits
            t0 = time.monotonic()
            with catalog_lock(p, timeout_s=10.0):
                pass
            assert time.monotonic() - t0 < 10.0
        finally:
            release.set()
            proc.join(timeout=10)
            if proc.is_alive():
                proc.terminate()

    def test_timeout_error_message(self, tmp_path):
        p = str(tmp_path / "cat")
        ctx = mp.get_context("spawn")
        started = ctx.Event()
        release = ctx.Event()
        proc = ctx.Process(target=_hold_lock, args=(p, 30.0, started, release))
        proc.start()
        try:
            assert started.wait(60)
            with pytest.raises(LockTimeout, match="could not lock"):
                with catalog_lock(p, timeout_s=0.2):
                    pass
        finally:
            release.set()
            proc.join(timeout=10)
            if proc.is_alive():
                proc.terminate()
