"""Known-good J002 fixture: the sanctioned readback seams."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def stays_on_device(x):
    return jnp.asarray(x) * jnp.float32(2.0)


def batched_readback(n):
    dev = jnp.arange(n)
    parts = []
    for i in range(8):
        parts.append(dev + i)  # device work accumulates on device
    return np.asarray(jnp.stack(parts))  # ONE post-loop sync


def host_math_in_loop(rows):
    total = 0
    for r in rows:
        total += int(np.asarray(r).sum())  # numpy-only: no device sync
    return total
