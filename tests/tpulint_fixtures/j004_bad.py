"""Known-bad J004 fixture: 64-bit dtypes on the device path."""

import jax
import jax.numpy as jnp
import numpy as np


def widen_keys(z):
    return z.astype(jnp.int64)  # J004 line 9


def device_alloc(n):
    return jnp.zeros(n, dtype="float64")  # J004 line 13 (string spelling)


_SENTINEL = jnp.uint64(0xFFFFFFFFFFFFFFFF)  # J004 line 16


@jax.jit
def traced_np_widen(x):
    return x.astype(np.int64)  # J004 line 21 (np 64-bit inside tracing)
