"""Known-bad C001 fixture: lock-discipline violations."""

import threading


class SloppyCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._items = []

    def bump(self):
        with self._lock:
            self._n += 1

    def bump_unlocked(self):
        self._n += 1  # C001 line 17: locked at line 14, bare here

    def add(self, v):
        with self._lock:
            self._items.append(v)

    def add_unlocked(self, v):
        self._items.append(v)  # C001 line 24


class OrderSwap:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._x = 0

    def forward(self):
        with self._a:
            with self._b:
                self._x += 1

    def backward(self):
        with self._b:
            with self._a:  # C001: AB/BA order inversion
                self._x += 1
