"""Known-good J004 fixture: the 32-bit device contract and its host seam."""

import jax
import jax.numpy as jnp
import numpy as np


def device_math(z):
    return z.astype(jnp.int32) * jnp.float32(0.5)


def emulated_u64_or(hi, lo, other_hi, other_lo):
    # the sanctioned wide-key idiom: two uint32 words per 64-bit value
    u = jnp.uint32
    return (hi | other_hi) & u(0xFFFFFFFF), (lo | other_lo) & u(0xFFFFFFFF)


def host_keys(millis):
    # HOST numpy math is allowed to be 64-bit — the contract guards the
    # device side of the seam, not the planner
    return np.asarray(millis, dtype=np.int64)


@jax.jit
def traced_narrow(x):
    return x.sum(dtype=jnp.int32)
