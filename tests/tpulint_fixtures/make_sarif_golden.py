"""Regenerate sarif_golden.json + sarif_multi_golden.json (run from the
repo root after an INTENTIONAL rule-registry or report-layout change)::

    GEOMESA_TPU_NO_JAX=1 python tests/tpulint_fixtures/make_sarif_golden.py

``sarif_golden.json`` pins the single-run document (``--format sarif``);
``sarif_multi_golden.json`` pins the ``--all-prongs`` one-run-per-prong
document — tpulint, tpurace, tpuflow, tpusync in that order, each with
only its own rule metadata.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from geomesa_tpu.analysis import LintConfig, lint_source  # noqa: E402
from geomesa_tpu.analysis.flow import analyze_flow_paths  # noqa: E402
from geomesa_tpu.analysis.race import analyze_race_paths  # noqa: E402
from geomesa_tpu.analysis.report import (  # noqa: E402
    render_json,
    render_json_multi,
)
from geomesa_tpu.analysis.sync import analyze_sync_paths  # noqa: E402


def main() -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    rel = "tests/tpulint_fixtures/j003_bad.py"
    cfg = LintConfig(j002_paths=("",), j004_paths=("",), c001_paths=("",))
    with open(os.path.join(here, "j003_bad.py"), encoding="utf-8") as f:
        src = f.read()
    doc = json.loads(render_json(lint_source(src, rel, cfg)))
    out = os.path.join(here, "sarif_golden.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"wrote {out}")

    # repo-relative target so result URIs stay portable in the golden
    multi = json.loads(render_json_multi([
        ("tpulint", lint_source(src, rel, cfg)),
        ("tpurace", analyze_race_paths([rel], cfg)),
        ("tpuflow", analyze_flow_paths([rel], cfg)),
        ("tpusync", analyze_sync_paths([rel], cfg)),
    ]))
    out = os.path.join(here, "sarif_multi_golden.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(multi, f, indent=1)
        f.write("\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
