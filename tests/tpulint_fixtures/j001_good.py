"""Known-good J001 fixture: static/structural branching inside jit."""

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def shape_branch(x):
    if x.shape[0] > 128:  # shapes are static under tracing
        return x[:128]
    return x


@partial(jax.jit, static_argnames=("mode",))
def static_arg_branch(x, mode):
    if mode == "abs":  # static argument: trace-time branch is intended
        return jnp.abs(x)
    return x


@jax.jit
def device_select(x):
    return jnp.where(x > 0, x, -x)  # the J001-clean spelling


def host_branch(x):
    if x.sum() > 0:  # not traced: plain numpy control flow is fine
        return x
    return -x
