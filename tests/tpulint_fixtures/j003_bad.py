"""Known-bad J003 fixture: jit wrappers rebuilt per call / bad static specs."""

import jax


def immediate_invoke(f, x):
    return jax.jit(f)(x)  # J003 line 7: wrapper discarded after one call


def uncached_factory(scale):
    @jax.jit  # J003 line 11: enclosing factory is not memoized
    def step(x):
        return x * scale

    return step


def rebind_per_call(f, x):
    g = jax.jit(f)  # J003 line 19: fresh wrapper every call
    return g(x)


def jit_in_loop(fns):
    steps = []
    for f in fns:
        steps.append(jax.jit(f))  # J003 line 26: wrapper per iteration
    return steps


unhashable_spec = jax.jit(
    lambda x, n: x[:n],
    static_argnums=[1],  # J003 line 32: mutable (unhashable) spec literal
)
