"""Known-good C001 fixture: consistent lock discipline."""

import threading


class DisciplinedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # construction is single-threaded: no lock needed
        self._items = []

    def bump(self):
        with self._lock:
            self._n += 1
            self._bump_extra_locked()

    def _bump_extra_locked(self):
        # *_locked suffix: caller holds the lock (repo convention)
        self._n += 1

    def add(self, v):
        with self._lock:
            self._items.append(v)

    def snapshot(self):
        with self._lock:
            return list(self._items)


class OneOrder:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._x = 0

    def first(self):
        with self._a:
            with self._b:
                self._x += 1

    def second(self):
        with self._a:
            with self._b:
                self._x -= 1
