"""W001 known-good twin (lint prong): the waiver suppresses a REAL J003
(mutable static_argnums literal), so it is live."""
import jax

g = jax.jit(lambda x: x, static_argnums=[0])  # tpulint: disable=J003
