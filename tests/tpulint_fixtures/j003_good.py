"""Known-good J003 fixture: module-level jits and memoized factories."""

from functools import lru_cache, partial

import jax


@jax.jit
def module_level_step(x):
    return x + 1


@partial(jax.jit, static_argnums=(1,))
def hashable_static_spec(x, n):
    return x[:n]


@lru_cache(maxsize=None)
def cached_factory(scale):
    @jax.jit
    def step(x):
        return x * scale

    return step


def make_inner_step(scale):
    # uncached layer of the cached_*/make_* idiom: reached only through
    # cached_wrapper below, so the jit is built a bounded number of times
    @jax.jit
    def step(x):
        return x * scale

    return step


@lru_cache(maxsize=None)
def cached_wrapper(scale):
    return make_inner_step(scale)
