"""Known-bad J001 fixture: Python control flow on traced values.

Never imported by tests — tpulint parses it; jax need not be installed.
"""

import jax
import jax.numpy as jnp


@jax.jit
def branch_on_tracer(x):
    if x.sum() > 0:  # J001 line 12
        return x
    return -x


@jax.jit
def loop_on_tracer(x):
    while jnp.any(x > 0):  # J001 line 19
        x = x - 1
    return x


@jax.jit
def assert_on_tracer(x):
    assert jnp.all(x > 0)  # J001 line 26
    return x


@jax.jit
def branch_on_derived(x):
    m = jnp.abs(x)
    total = m.sum()
    if total > 1.0:  # J001 line 34 (taint flows through locals)
        return m
    return x
