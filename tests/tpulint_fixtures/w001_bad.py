"""W001 known-bad (lint prong): the J003 waiver suppresses nothing."""
import jax


def double(x):
    return x + x  # tpulint: disable=J003


def use(x):
    return jax.numpy.sum(double(x))
