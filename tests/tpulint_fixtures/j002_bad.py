"""Known-bad J002 fixture: host<->device syncs where they hurt."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def trace_time_sync(x):
    s = float(x.sum())  # J002 line 10: tracer -> host at trace time
    return x * s


@jax.jit
def trace_time_asarray(x):
    host = np.asarray(x)  # J002 line 16
    return jnp.asarray(host)


def hot_loop_readback(n):
    dev = jnp.arange(n)
    total = 0.0
    for _ in range(8):
        total += float(dev.sum())  # J002 line 24: sync per iteration
    return total


def hot_loop_item(n):
    dev = jnp.arange(n)
    out = []
    while len(out) < 4:
        out.append(dev.max().item())  # J002 line 32
    return out
