"""Distributed row retrieval for EXTENDED geometries: XZ2/XZ3 stores run the
mesh bbox-overlap select (kind="bboxes"), parity vs the oracle."""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import LineString, Polygon
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.backends import TpuBackend
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000


def _track(rng, cx, cy, n=8):
    ang = rng.uniform(0, 2 * np.pi)
    step_x = np.cos(ang) * 0.05
    step_y = np.sin(ang) * 0.05
    pts = np.stack(
        [cx + step_x * np.arange(n) + rng.normal(0, 0.01, n),
         cy + step_y * np.arange(n) + rng.normal(0, 0.01, n)], axis=1)
    return LineString(pts)


def _stores(n=3000, with_dtg=True, seed=5):
    rng = np.random.default_rng(seed)
    spec = "name:String," + ("dtg:Date," if with_dtg else "") + \
        "*geom:LineString;geomesa.xz.precision='12'" + \
        (",geomesa.z3.interval='week'" if with_dtg else "")
    sft_t = parse_spec("trk", spec)
    recs = []
    for i in range(n):
        cx = float(rng.uniform(-170, 170))
        cy = float(rng.uniform(-80, 80))
        rec = {"name": f"t{i}", "geom": _track(rng, cx, cy)}
        if with_dtg:
            rec["dtg"] = T0 + int(rng.integers(0, 6 * 86_400_000))
        recs.append(rec)
    fids = [f"t{i}" for i in range(n)]
    table = FeatureTable.from_records(sft_t, recs, fids)
    tpu = DataStore(backend="tpu")
    tpu.create_schema(sft_t)
    tpu.write("trk", table)
    tpu.compact("trk")
    oracle = DataStore(backend="oracle")
    oracle.create_schema(parse_spec("trk", spec))
    oracle.write("trk", table)
    return tpu, oracle


QUERIES = [
    "BBOX(geom, -20, -15, 10, 15)",
    "BBOX(geom, 100, 20, 140, 60)",
    "INTERSECTS(geom, POLYGON ((0 0, 40 0, 40 30, 0 30, 0 0)))",
]


class TestBboxMeshSelect:
    def test_device_state_is_bbox_kind(self):
        tpu, _ = _stores(n=300)
        st = tpu._state("trk")
        kinds = {k: (v.kind if v is not None else None)
                 for k, v in st.backend_state.items()}
        assert "bboxes" in kinds.values()  # xz index rides the mesh now

    def test_parity_vs_oracle(self):
        tpu, oracle = _stores()
        for q in QUERIES:
            got = set(tpu.query("trk", q).table.fids)
            want = set(oracle.query("trk", q).table.fids)
            assert got == want, f"{q}: {len(got ^ want)} differ"
        # no device failover happened: the mesh path really served these
        assert tpu.metrics.counter("store.query.device_failovers").count == 0

    def test_parity_with_time_predicate(self):
        tpu, oracle = _stores()
        q = ("BBOX(geom, -60, -40, 60, 40) AND dtg DURING "
             "2020-09-14T00:00:00Z/2020-09-16T00:00:00Z")
        got = set(tpu.query("trk", q).table.fids)
        want = set(oracle.query("trk", q).table.fids)
        assert got == want

    def test_parity_without_dtg(self):
        tpu, oracle = _stores(with_dtg=False)
        for q in QUERIES[:2]:
            got = set(tpu.query("trk", q).table.fids)
            want = set(oracle.query("trk", q).table.fids)
            assert got == want

    def test_polygon_store(self):
        rng = np.random.default_rng(9)
        spec = "name:String,*geom:Polygon;geomesa.xz.precision='10'"
        sft = parse_spec("pg", spec)
        recs = []
        for i in range(500):
            cx = float(rng.uniform(-160, 160))
            cy = float(rng.uniform(-70, 70))
            w, h = rng.uniform(0.2, 2.0, 2)
            recs.append({"name": f"p{i}", "geom": Polygon(
                [[cx - w, cy - h], [cx + w, cy - h], [cx + w, cy + h],
                 [cx - w, cy + h]])})
        table = FeatureTable.from_records(sft, recs, [f"p{i}" for i in range(500)])
        tpu = DataStore(backend="tpu")
        tpu.create_schema(sft)
        tpu.write("pg", table)
        oracle = DataStore(backend="oracle")
        oracle.create_schema(parse_spec("pg", spec))
        oracle.write("pg", table)
        q = "INTERSECTS(geom, POLYGON ((-10 -10, 30 -10, 30 20, -10 20, -10 -10)))"
        assert set(tpu.query("pg", q).table.fids) == set(
            oracle.query("pg", q).table.fids
        )

    def test_overlap_pad_sentinel_under_origin_spanning_bbox(self):
        """A feature bbox spanning the int-domain origin corner must not
        match padded query slots (the overlap-pad regression class)."""
        spec = "name:String,*geom:LineString;geomesa.xz.precision='12'"
        sft = parse_spec("sp", spec)
        # a line crossing lon/lat 0 — bbox spans the normalized midpoint
        table = FeatureTable.from_records(
            sft,
            [{"name": "span", "geom": LineString([[-1, -1], [1, 1]])},
             {"name": "far", "geom": LineString([[100, 50], [101, 51]])}],
            ["span", "far"],
        )
        tpu = DataStore(backend="tpu")
        tpu.create_schema(sft)
        tpu.write("sp", table)
        r = tpu.query("sp", "BBOX(geom, 99, 49, 102, 52)")
        assert set(r.table.fids) == {"far"}

    def test_null_geometry_rejected_at_write(self):
        """The store's write-time validation rejects null geometries before
        they can reach device load (all-indices-validate-before-write)."""
        spec = "name:String,*geom:LineString;geomesa.xz.precision='12'"
        sft = parse_spec("ng", spec)
        table = FeatureTable.from_records(
            sft,
            [{"name": "ok", "geom": LineString([[10, 10], [11, 11]])},
             {"name": "null", "geom": None}],
            ["ok", "null"],
        )
        tpu = DataStore(backend="tpu")
        tpu.create_schema(sft)
        with pytest.raises(ValueError, match="null geometry"):
            tpu.write("ng", table)

    def test_nonfinite_bounds_never_match_on_device(self):
        """Defense in depth: a non-finite bbox row (should validation ever
        let one through) is stamped unsatisfiable at load, not crashed on."""
        import numpy as np

        from geomesa_tpu.planning.planner import build_indices

        spec = "name:String,*geom:LineString;geomesa.xz.precision='12'"
        sft = parse_spec("nf", spec)
        table = FeatureTable.from_records(
            sft,
            [{"name": "ok", "geom": LineString([[10, 10], [11, 11]])},
             {"name": "weird", "geom": LineString([[50, 50], [51, 51]])}],
            ["ok", "weird"],
        )
        # corrupt one row's bounds to NaN post-validation (simulating an
        # upstream producer bug) and load the backend directly
        table.geom_column().bounds[1] = np.nan
        indices = build_indices(sft)
        with np.errstate(invalid="ignore"):  # NaN bounds by construction
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for ix in indices.values():
                    ix.build(table)
        backend = TpuBackend()
        state = backend.load(sft, table, indices)  # must not raise
        kinds = {k: getattr(v, "kind", None) for k, v in state.items()}
        assert "bboxes" in kinds.values()


class TestCountManyBboxStore:
    def test_loose_counts_match_exact_for_bbox_queries(self):
        tpu, oracle = _stores(n=2000, seed=11)
        queries = [
            "BBOX(geom, -20, -15, 10, 15)",
            "BBOX(geom, 100, 20, 140, 60)",
            "BBOX(geom, -180, -90, 180, 90)",
            ("BBOX(geom, -60, -40, 60, 40) AND dtg DURING "
             "2020-09-14T00:00:00Z/2020-09-16T00:00:00Z"),
        ]
        got = tpu.count_many("trk", queries, loose=True)
        want = [oracle.query("trk", q).count for q in queries]
        # BBOX on extended geometries IS the bbox-overlap predicate, so the
        # loose device counts equal the exact oracle counts here
        assert got == want
        assert tpu.metrics.counter("store.query.device_failovers").count == 0

    def test_disjoint_and_fallback_mix(self):
        tpu, oracle = _stores(n=500, seed=12)
        queries = [
            "BBOX(geom, 200, 90, 210, 95)",           # disjoint -> 0
            "BBOX(geom, -20, -15, 10, 15)",           # batched
            "name = 't1'",                            # non-spatial -> exact
        ]
        got = tpu.count_many("trk", queries, loose=True)
        assert got[0] == 0
        assert got[1] == oracle.query("trk", queries[1]).count
        assert got[2] == 1


class TestPersistenceRoundTrip:
    def test_track_store_save_load_serves_from_mesh(self, tmp_path):
        from geomesa_tpu.store import persistence

        tpu, oracle = _stores(n=800, seed=13)
        persistence.save(tpu, str(tmp_path / "cat"))
        ds2 = persistence.load(str(tmp_path / "cat"))
        st = ds2._state("trk")
        kinds = {k: getattr(v, "kind", None)
                 for k, v in (st.backend_state or {}).items()}
        assert "bboxes" in kinds.values()
        q = QUERIES[0]
        assert set(ds2.query("trk", q).table.fids) == set(
            oracle.query("trk", q).table.fids
        )


class TestSelectDispatchRoutes:
    """The row-select path has two device routes: one-pass (gather at the
    planner's candidate bound — one dispatch) and two-pass (count first to
    tighten capacity — wide scans). Both must yield identical row sets."""

    def test_one_pass_and_two_pass_agree(self, monkeypatch):
        import geomesa_tpu.store.backends as B
        from geomesa_tpu.geometry.types import Point

        rng = np.random.default_rng(19)
        n = 60_000
        lon = rng.uniform(-60, 60, n)
        lat = rng.uniform(-45, 45, n)
        t0 = 1_600_000_000_000
        ds = DataStore(backend="tpu")
        ds.create_schema("ev", "dtg:Date,*geom:Point")
        ds.write("ev", [
            {"dtg": t0 + int(i), "geom": Point(float(lon[i]), float(lat[i]))}
            for i in range(n)
        ], fids=[str(i) for i in range(n)])
        ds.compact("ev")
        q = "BBOX(geom, -20, -15, 30, 25)"
        want = set(np.nonzero(
            (lon >= -20) & (lon <= 30) & (lat >= -15) & (lat <= 25)
        )[0].astype(str).tolist())

        monkeypatch.setattr(B, "_ONE_PASS_MAX_SLOTS", 1 << 62)  # force 1-pass
        one = set(ds.query("ev", q).table.fids.tolist())
        monkeypatch.setattr(B, "_ONE_PASS_MAX_SLOTS", 0)  # force 2-pass
        two = set(ds.query("ev", q).table.fids.tolist())
        assert one == want and two == want
        assert ds.metrics.counter("store.query.device_failovers").count == 0
