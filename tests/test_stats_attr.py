"""Attribute index, stats sketches, cost-based strategy, aggregation hints
(reference suites: AttributeIndexTest, stats/*Test, DensityScan/BinAggregating
tests — SURVEY.md §4)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.stats.sketches import (
    Cardinality,
    DescriptiveStats,
    Frequency,
    Histogram,
    MinMax,
    TopK,
    Z3Histogram,
)
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.utils import bin_format

T0 = 1_498_867_200_000
SPEC = (
    "name:String:index=true,age:Integer:index=true,dtg:Date,*geom:Point"
    ";geomesa.z3.interval='week'"
)


def records(n=3000, seed=9):
    rng = np.random.default_rng(seed)
    lon = rng.uniform(-180, 180, n)
    lat = rng.uniform(-90, 90, n)
    t = T0 + rng.integers(0, 30 * 86_400_000, n)
    return [
        {
            "name": f"name{i % 40}",
            "age": int(rng.integers(0, 100)),
            "dtg": int(t[i]),
            "geom": Point(float(lon[i]), float(lat[i])),
        }
        for i in range(n)
    ]


@pytest.fixture(scope="module")
def stores():
    recs = records()
    oracle = DataStore(backend="oracle")
    tpu = DataStore(backend="tpu")
    for ds in (oracle, tpu):
        ds.create_schema("t", SPEC)
        ds.write("t", recs, fids=[f"t.{i}" for i in range(len(recs))])
    return oracle, tpu


ATTR_QUERIES = [
    "name = 'name7'",
    "name IN ('name1', 'name2', 'name39')",
    "age BETWEEN 10 AND 20",
    "age >= 95",
    "name = 'name3' AND age < 50",
    "name LIKE 'name1%'",
    "name = 'name5' AND BBOX(geom, -90, -45, 90, 45)",
    "name = 'name5' AND dtg DURING 2017-07-03T00:00:00Z/2017-07-20T00:00:00Z",
    "name > 'name35'",
]


class TestAttributeIndex:
    @pytest.mark.parametrize("cql", ATTR_QUERIES)
    def test_parity(self, stores, cql):
        oracle, tpu = stores
        a = set(oracle.query("t", cql).table.fids.tolist())
        b = set(tpu.query("t", cql).table.fids.tolist())
        assert a == b, f"parity failure for {cql!r}"
        assert len(a) > 0  # non-vacuous

    def test_attr_index_selected_for_equality(self, stores):
        _, tpu = stores
        s = tpu.explain("t", "name = 'name7'")
        assert "attr:name" in s, s

    def test_z3_selected_for_spatiotemporal(self, stores):
        _, tpu = stores
        s = tpu.explain(
            "t", "BBOX(geom, -10, -10, 10, 10) AND dtg DURING 2017-07-03T00:00:00Z/2017-07-10T00:00:00Z"
        )
        assert "Index: z3" in s, s

    def test_cost_based_prefers_selective_attr(self, stores):
        # equality on one of 40 names (~2.5%) should beat a whole-world bbox
        _, tpu = stores
        s = tpu.explain("t", "name = 'name7' AND BBOX(geom, -170, -85, 170, 85)")
        assert "attr:name" in s, s

    def test_forced_index_beats_cost(self, stores):
        _, tpu = stores
        r = tpu.query("t", Query(filter="name = 'name7'", hints={"index": "z2"}))
        assert r.plan_info.index_name == "z2"


class TestSketches:
    def test_minmax_merge(self):
        a, b = MinMax(), MinMax()
        a.observe(np.array([3, 5, 9]))
        b.observe(np.array([1, 22]))
        m = a + b
        assert (m.min, m.max) == (1, 22)

    def test_histogram_estimate(self):
        h = Histogram(0.0, 100.0, 100)
        h.observe(np.random.default_rng(0).uniform(0, 100, 10000))
        est = h.estimate_range(25.0, 75.0)
        assert abs(est - 5000) < 300
        assert h.merge(h).total == 20000

    def test_frequency(self):
        f = Frequency()
        f.observe(np.array(["a"] * 50 + ["b"] * 10, dtype=object))
        assert f.count("a") >= 50  # CMS overestimates only
        assert f.count("b") >= 10
        m = f + f
        assert m.count("a") >= 100

    def test_cardinality(self):
        c = Cardinality()
        vals = np.array([f"v{i}" for i in range(5000)], dtype=object)
        c.observe(vals)
        c.observe(vals)  # duplicates don't add
        assert abs(c.estimate() - 5000) / 5000 < 0.1

    def test_topk(self):
        t = TopK(3)
        t.observe(np.array(["x"] * 30 + ["y"] * 20 + ["z"] * 10 + ["w"], dtype=object))
        top = t.top(3)
        assert [k for k, _ in top] == ["x", "y", "z"]

    def test_descriptive_merge(self):
        rng = np.random.default_rng(1)
        v = rng.normal(10, 2, 1000)
        a, b = DescriptiveStats(), DescriptiveStats()
        a.observe(v[:500])
        b.observe(v[500:])
        m = a + b
        assert abs(m.mean - v.mean()) < 1e-9
        assert abs(m.variance - v.var(ddof=1)) < 1e-6

    def test_z3_histogram(self):
        zh = Z3Histogram(bits=8)
        bins = np.array([5, 5, 5, 6], dtype=np.int32)
        zs = np.array([0, 1, 1 << 55, 42], dtype=np.uint64)
        zh.observe_binned(bins, zs)
        assert zh.total == 4
        full = zh.estimate_zranges(5, np.array([[0, (1 << 63) - 1]], dtype=np.uint64))
        assert abs(full - 3) < 1e-6


class TestStatsAPI:
    def test_count_and_bounds(self, stores):
        _, tpu = stores
        assert tpu.stats_count("t") == 3000
        lo, hi = tpu.stats_bounds("t", "age")
        assert lo == 0 and hi == 99

    def test_estimated_count(self, stores):
        _, tpu = stores
        est = tpu.stats_count("t", "name = 'name7'")
        exact = tpu.stats_count("t", "name = 'name7'", exact=True)
        assert exact > 0
        assert est >= exact  # CMS overestimates only
        assert est < exact * 3

    def test_spatiotemporal_estimate(self, stores):
        _, tpu = stores
        cql = "BBOX(geom, -90, -45, 90, 45) AND dtg DURING 2017-07-03T00:00:00Z/2017-07-17T00:00:00Z"
        est = tpu.stats_count("t", cql)
        exact = tpu.stats_count("t", cql, exact=True)
        assert exact > 0
        assert 0.3 < est / exact < 3.0, (est, exact)

    def test_topk_and_cardinality(self, stores):
        _, tpu = stores
        top = tpu.stats_top_k("t", "name", 5)
        assert len(top) == 5
        card = tpu.stats_cardinality("t", "name")
        assert abs(card - 40) / 40 < 0.2


class TestAggregationHints:
    def test_density(self, stores):
        oracle, tpu = stores
        q = Query(
            filter="BBOX(geom, -90, -45, 90, 45)",
            hints={"density": {"bbox": (-90, -45, 90, 45), "width": 64, "height": 32}},
        )
        r = tpu.query("t", q)
        assert r.density.shape == (32, 64)
        assert r.density.sum() == r.count

    def test_stats_hint(self, stores):
        _, tpu = stores
        r = tpu.query("t", Query(filter="age < 50", hints={"stats": "MinMax(age);Count()"}))
        mm = r.stats["MinMax(age)"]
        assert mm.max <= 49
        assert r.stats["Count()"].count == r.count

    def test_bin_hint(self, stores):
        _, tpu = stores
        r = tpu.query(
            "t",
            Query(filter="BBOX(geom, 0, 0, 90, 45)", hints={"bin": {"track": "name", "sort": True}}),
        )
        dec = bin_format.decode(r.bin_data)
        assert len(dec["lat"]) == r.count
        assert np.all(np.diff(dec["dtg_secs"]) >= 0)  # time sorted
        # coordinates survive the f32 roundtrip
        assert dec["lon"].min() >= -0.01 and dec["lon"].max() <= 90.01

    def test_sampling(self, stores):
        _, tpu = stores
        full = tpu.query("t", "INCLUDE").count
        r = tpu.query("t", Query(filter="INCLUDE", hints={"sample": 0.1}))
        assert 0.05 * full < r.count < 0.15 * full

    def test_sampling_by_group(self, stores):
        _, tpu = stores
        r = tpu.query(
            "t", Query(filter="INCLUDE", hints={"sample": 0.5, "sample_by": "name"})
        )
        assert 0.3 * 3000 < r.count < 0.7 * 3000


class TestBinFormat:
    def test_roundtrip(self):
        lon = np.array([10.5, -20.25])
        lat = np.array([45.0, -30.5])
        dtg = np.array([1_500_000_000_000, 1_500_000_060_000], dtype=np.int64)
        data = bin_format.encode(lon, lat, dtg, track_values=["a", "b"])
        assert len(data) == 32
        dec = bin_format.decode(data)
        np.testing.assert_allclose(dec["lon"], lon.astype(np.float32))
        np.testing.assert_allclose(dec["lat"], lat.astype(np.float32))
        assert dec["dtg_secs"].tolist() == [1_500_000_000, 1_500_000_060]

    def test_labeled(self):
        data = bin_format.encode(
            np.array([1.0]), np.array([2.0]), np.array([1_500_000_000_000]),
            track_values=["t"], label_values=["label"],
        )
        assert len(data) == 24
        dec = bin_format.decode(data, labeled=True)
        assert "label" in dec

    def test_merge_sorted(self):
        a = bin_format.encode(
            np.array([1.0]), np.array([1.0]), np.array([2_000_000], dtype=np.int64) * 1000
        )
        b = bin_format.encode(
            np.array([2.0]), np.array([2.0]), np.array([1_000_000], dtype=np.int64) * 1000
        )
        m = bin_format.decode(bin_format.merge_sorted([a, b]))
        assert m["dtg_secs"].tolist() == [1_000_000, 2_000_000]


class TestReviewRegressions:
    """Regressions for review findings on the attr/stats milestone."""

    def test_like_supplementary_plane(self):
        ds = DataStore(backend="tpu")
        ds.create_schema("lk", "name:String:index=true,dtg:Date,*geom:Point")
        ds.write("lk", [
            {"name": "ab\U0001F600", "dtg": T0, "geom": Point(1, 1)},
            {"name": "abc", "dtg": T0, "geom": Point(2, 2)},
            {"name": "zz", "dtg": T0, "geom": Point(3, 3)},
        ])
        r = ds.query("lk", "name LIKE 'ab%'")
        assert r.count == 2  # emoji suffix must not fall outside the range

    def test_indexed_date_attribute_query(self):
        ds = DataStore(backend="tpu")
        ds.create_schema("dt", "d:Date:index=true,dtg:Date,*geom:Point")
        ds.write("dt", [
            {"d": T0 + i * 1000, "dtg": T0, "geom": Point(i, i)} for i in range(10)
        ])
        # quoted date literal against an indexed DATE attribute
        r = ds.query("dt", "d < '2017-07-01T00:00:05Z'")
        assert r.count == 5

    def test_attr_only_index_config_full_scan(self):
        ds = DataStore(backend="tpu")
        ds.create_schema(
            "ao", "name:String:index=true,dtg:Date,*geom:Point;geomesa.indices='attr:name'"
        )
        ds.write("ao", [{"name": None if i == 0 else f"n{i}", "dtg": T0, "geom": Point(i, i)}
                         for i in range(5)])
        # INCLUDE via the only (attribute) index must still see the null-name row
        assert ds.query("ao", "INCLUDE").count == 5
        assert ds.query("ao", "BBOX(geom, 0.5, 0.5, 10, 10)").count == 4

    def test_sample_large_fraction(self, stores):
        _, tpu = stores
        full = tpu.query("t", "INCLUDE").count
        r = tpu.query("t", Query(filter="INCLUDE", hints={"sample": 0.9}))
        assert r.count == full  # ~1 rounds to keep-everything, not half

    def test_stats_before_write(self):
        ds = DataStore(backend="tpu")
        ds.create_schema("nb", "a:Integer,dtg:Date,*geom:Point")
        import pytest as _pt

        with _pt.raises(ValueError, match="no statistics"):
            ds.stats_bounds("nb", "a")
