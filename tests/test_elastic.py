"""Elastic federation tests (ISSUE 19): WAL-backed live shard
migration (ship → dual-apply → cutover, crash recovery via the elastic
journal), zero-downtime membership change, the HBM → RAM → disk tiering
ladder, the autoscaler control plane, and the draining-member signal.
See docs/serving.md § Shard-map lifecycle and docs/operations.md."""

import email
import json
import os
import threading
import urllib.error

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.obs import devmon
from geomesa_tpu.obs import flight as obs_flight
from geomesa_tpu.resilience import faults
from geomesa_tpu.resilience.policy import MemberDrainingError, RetryPolicy
from geomesa_tpu.serving import elastic
from geomesa_tpu.serving.elastic import (
    FederationAutoscaler,
    MigrationError,
    ShardMigrator,
    TieringPolicy,
)
from geomesa_tpu.serving.shards import (
    MIG_DUAL,
    ShardedDataStoreView,
    ShardMigration,
    ShardRouter,
)
from geomesa_tpu.store import persistence
from geomesa_tpu.store.bufferpool import BufferPool, register_residency
from geomesa_tpu.store.datastore import DataStore

T0 = 1_500_000_000_000
SPEC = "name:String,dtg:Date,*geom:Point"


# -- federation helpers -------------------------------------------------------

def _open_fed(root, members=3, n_shards=8, **mig_kw):
    stores = [
        DataStore.open(str(root / f"m{i}"), recover=True,
                       checkpointer=False)
        for i in range(members)
    ]
    view = ShardedDataStoreView(stores, n_shards=n_shards)
    if "pts" not in stores[0].list_schemas():
        view.create_schema("pts", SPEC)
    mig_kw.setdefault("dual_window_s", 0.05)
    mig_kw.setdefault("drain_timeout_s", 10.0)
    migrator = ShardMigrator(
        view, str(root / "journal.json"), str(root / "bundles"), **mig_kw)
    return view, stores, migrator


def _close(stores):
    for s in stores:
        s.close()


def _write_rows(view, n, prefix="f", seed=7):
    rng = np.random.default_rng(seed)
    recs = [
        {"name": f"n{i % 3}", "dtg": T0 + i * 1000,
         "geom": Point(float(rng.uniform(-170, 170)),
                       float(rng.uniform(-60, 60)))}
        for i in range(n)
    ]
    fids = [f"{prefix}{i}" for i in range(n)]
    view.write("pts", recs, fids=fids)
    return recs, fids


def _recs_for_shard(view, router, shard, n, prefix, seed=None):
    """Records that the write path's own keying places on ``shard``
    (geometry rows key by coordinates, so the fid choice is free)."""
    sft = view.get_schema("pts")
    rng = np.random.default_rng(shard * 31 + 1 if seed is None else seed)
    recs: list = []
    while len(recs) < n:
        cand = [
            {"name": "t", "dtg": T0,
             "geom": Point(float(rng.uniform(-170, 170)),
                           float(rng.uniform(-60, 60)))}
            for _ in range(128)
        ]
        shards = view._record_shards(
            sft, cand, [str(i) for i in range(len(cand))], router)
        recs.extend(c for c, s in zip(cand, shards) if int(s) == shard)
    recs = recs[:n]
    return recs, [f"{prefix}{i}" for i in range(n)]


def _census(stores):
    """fid -> [member indices holding it] across the federation."""
    out: dict = {}
    for m, s in enumerate(stores):
        if "pts" not in s.list_schemas():
            continue
        for f in s.query("pts", None).table.fids:
            out.setdefault(str(f), []).append(m)
    return out


# -- the migrator -------------------------------------------------------------

class TestShardMigrator:
    def test_migrate_zero_loss_under_concurrent_writes(self, tmp_path):
        view, stores, mig = _open_fed(tmp_path)
        try:
            _, base = _write_rows(view, 90)
            router = view._generation.router
            shard = 0
            src = router.member_for_shard(shard)
            dst = next(m for m in router.members if m != src)
            errs: list = []
            stop = threading.Event()
            written: list = []

            def writer():
                i = 0
                while not stop.is_set():
                    fid = f"w{i}"
                    try:
                        view.write("pts", [{
                            "name": "w", "dtg": T0 + i,
                            "geom": Point(float(i % 170), 10.0)}],
                            fids=[fid])
                        written.append(fid)
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)
                        return
                    i += 1

            t = threading.Thread(target=writer)
            t.start()
            try:
                out = mig.migrate(shard, dst)
            finally:
                stop.set()
                t.join(10)
            assert not errs
            assert out["shard"] == shard and out["dst"] == dst
            gen = view._generation
            assert gen.router.member_for_shard(shard) == dst
            assert gen.router.coverage_violations() == []
            assert not gen.migrations
            # every acked row exactly once, across base + concurrent
            census = _census(stores)
            for f in base + written:
                assert census.get(f) is not None, f"lost acked row {f}"
                assert len(census[f]) == 1, f"duplicated row {f}"
            # the source holds nothing of the migrated shard any more
            sft = view.get_schema("pts")
            table = stores[src].query("pts", None).table
            if len(table):
                shards = mig._shards_of_table(sft, table, gen.router)
                assert not (shards == shard).any()
            assert mig.history and mig.history[-1] is out
        finally:
            _close(stores)

    def test_tail_replay_applies_post_floor_writes_and_deletes(
            self, tmp_path, monkeypatch):
        view, stores, mig = _open_fed(tmp_path)
        try:
            _write_rows(view, 40)
            router = view._generation.router
            shard = 1
            src = router.member_for_shard(shard)
            dst = next(m for m in router.members if m != src)
            pre_recs, pre_fids = _recs_for_shard(
                view, router, shard, 5, "pre")
            view.write("pts", pre_recs, fids=pre_fids)
            tail_recs, tail_fids = _recs_for_shard(
                view, router, shard, 4, "tail", seed=99)
            victim = pre_fids[0]
            real = persistence.save_shard

            def patched(ds, type_name, path, selector, **kw):
                man = real(ds, type_name, path, selector, **kw)
                # past the floor, before the stop capture: these land in
                # the WAL tail the catch-up replay must apply
                ds.write(type_name, tail_recs, fids=tail_fids)
                ds.delete_features(type_name, [victim])
                return man

            monkeypatch.setattr(persistence, "save_shard", patched)
            out = mig.migrate(shard, dst)
            assert out["rows_replayed"] >= len(tail_fids)
            census = _census(stores)
            for f in tail_fids:
                assert census.get(f) == [dst], f"tail row {f}: {census.get(f)}"
            # the replayed delete removed the shipped copy
            assert victim not in census
            for f in pre_fids[1:]:
                assert census.get(f) == [dst]
        finally:
            _close(stores)

    def test_catchup_timeout_rolls_back_with_anomaly(
            self, tmp_path, monkeypatch):
        rec = obs_flight.FlightRecorder()
        prev = obs_flight.install(rec)
        view, stores, mig = _open_fed(tmp_path, catchup_timeout_s=-1.0)
        try:
            _write_rows(view, 30)
            router = view._generation.router
            shard = 2
            src = router.member_for_shard(shard)
            dst = next(m for m in router.members if m != src)
            pre_recs, pre_fids = _recs_for_shard(
                view, router, shard, 3, "pre")
            view.write("pts", pre_recs, fids=pre_fids)
            tail_recs, tail_fids = _recs_for_shard(
                view, router, shard, 1, "tail", seed=5)
            real = persistence.save_shard

            def patched(ds, type_name, path, selector, **kw):
                man = real(ds, type_name, path, selector, **kw)
                ds.write(type_name, tail_recs, fids=tail_fids)
                return man

            monkeypatch.setattr(persistence, "save_shard", patched)
            before = elastic.migration_metrics()
            with pytest.raises(MigrationError, match="rolled back"):
                mig.migrate(shard, dst)
            after = elastic.migration_metrics()
            assert after.get("rolled_back", 0) == \
                before.get("rolled_back", 0) + 1
            assert after.get("failed", 0) == before.get("failed", 0) + 1
            gen = view._generation
            assert gen.router.member_for_shard(shard) == src
            assert not gen.migrations
            assert gen.router.coverage_violations() == []
            # destination cleaned: no shipped or tail copies survive
            census = _census(stores)
            for f in pre_fids + tail_fids:
                assert dst not in census.get(f, [])
            assert json.loads(
                (tmp_path / "journal.json").read_text())["phase"] == "stable"
            stalls = [r for r in rec.records()
                      if obs_flight.A_MIGRATION in r.anomalies]
            assert stalls and stalls[0].source == "elastic"
        finally:
            _close(stores)
            obs_flight.install(prev)

    def test_recover_rolls_back_after_mid_ship_crash(
            self, tmp_path, monkeypatch):
        view, stores, mig = _open_fed(tmp_path)
        _, base = _write_rows(view, 40)
        router = view._generation.router
        shard = 0
        src = router.member_for_shard(shard)
        dst = next(m for m in router.members if m != src)
        monkeypatch.setattr(
            persistence, "load_shard",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("died")))
        with pytest.raises(RuntimeError, match="died"):
            mig.migrate(shard, dst)
        _close(stores)  # the "crash": journal is stuck at shipping
        monkeypatch.undo()
        view2, stores2, mig2 = _open_fed(tmp_path)
        try:
            out = mig2.recover()
            assert out["phase"] == "shipping"
            assert out["action"] == "rolled_back"
            gen = view2._generation
            assert gen.router.member_for_shard(shard) == src
            assert gen.router.coverage_violations() == []
            census = _census(stores2)
            for f in base:
                assert len(census.get(f, [])) == 1
            # a second recover finds the stable journal: a no-op
            assert mig2.recover()["action"] == "none"
        finally:
            _close(stores2)

    def test_recover_rolls_forward_after_cutover_crash(
            self, tmp_path, monkeypatch):
        view, stores, mig = _open_fed(tmp_path)
        _, base = _write_rows(view, 40)
        router = view._generation.router
        shard = 3
        src = router.member_for_shard(shard)
        dst = next(m for m in router.members if m != src)
        real = faults.crash_point

        def patched(name):
            if name == "elastic.pre_cutover":
                raise RuntimeError("killed at cutover")
            real(name)

        monkeypatch.setattr(faults, "crash_point", patched)
        with pytest.raises(RuntimeError, match="killed at cutover"):
            mig.migrate(shard, dst)
        _close(stores)
        monkeypatch.undo()
        view2, stores2, mig2 = _open_fed(tmp_path)
        try:
            out = mig2.recover()
            assert out["phase"] == "cutover"
            assert out["action"] == "rolled_forward"
            gen = view2._generation
            assert gen.router.member_for_shard(shard) == dst
            assert gen.router.coverage_violations() == []
            census = _census(stores2)
            for f in base:
                assert len(census.get(f, [])) == 1, f"row {f}: {census.get(f)}"
            # the source kept nothing of the rolled-forward shard
            sft = view2.get_schema("pts")
            table = stores2[src].query("pts", None).table
            if len(table):
                shards = mig2._shards_of_table(sft, table, gen.router)
                assert not (shards == shard).any()
        finally:
            _close(stores2)

    def test_validation_errors(self, tmp_path):
        view, stores, mig = _open_fed(tmp_path)
        try:
            router = view._generation.router
            shard = 0
            src = router.member_for_shard(shard)
            with pytest.raises(MigrationError, match="already owned"):
                mig.migrate(shard, src)
            with pytest.raises(MigrationError, match="not a member"):
                mig.migrate(shard, 99)
        finally:
            _close(stores)
        # WAL-less members cannot host a live migration source
        plain = [DataStore(backend="tpu") for _ in range(2)]
        v = ShardedDataStoreView(plain, n_shards=4)
        v.create_schema("pts", SPEC)
        m = ShardMigrator(v, str(tmp_path / "j2.json"), str(tmp_path / "b2"))
        shard = 0
        src = v._generation.router.member_for_shard(shard)
        dst = 1 - src
        with pytest.raises(MigrationError, match="WAL"):
            m.migrate(shard, dst)

    def test_live_membership_change_departure(self, tmp_path):
        view, stores, mig = _open_fed(tmp_path, n_shards=4)
        try:
            _, base = _write_rows(view, 60)
            plan = mig.plan_membership([0, 1])
            assert all(p["action"] in ("migrate", "remove") for p in plan)
            assert {p["member"] for p in plan
                    if p["action"] == "remove"} == {2}
            done = mig.apply_membership([0, 1])
            assert done == plan
            gen = view._generation
            assert gen.router.coverage_violations() == []
            assert gen.router.shards_of_member(2) == []
            assert set(gen.router.shard_member) <= {0, 1}
            census = _census(stores)
            for f in base:
                assert len(census.get(f, [])) == 1
            assert view.stats_count("pts") == 60
        finally:
            _close(stores)

    def test_membership_join_requires_add_member_first(self, tmp_path):
        view, stores, mig = _open_fed(tmp_path, n_shards=4)
        try:
            plan = mig.plan_membership([0, 1, 2, 3])
            assert plan[0] == {"action": "add", "member": 3}
            with pytest.raises(MigrationError, match="add_member"):
                mig.apply_membership([0, 1, 2, 3])
            # after the join the plan is pure migrates onto the newcomer
            m3 = DataStore.open(str(tmp_path / "m3"), recover=True,
                                checkpointer=False)
            try:
                assert view.add_member(m3) == 3
                if "pts" not in m3.list_schemas():
                    m3.create_schema("pts", SPEC)
                plan = mig.plan_membership([0, 1, 2, 3])
                assert plan and all(p["action"] == "migrate" for p in plan)
                assert all(p["dst"] == 3 for p in plan)
            finally:
                m3.close()
        finally:
            _close(stores)


# -- satellite 3: router movement properties ---------------------------------

class TestRouterMovementProperties:
    def test_departure_moves_only_departed_members_shards(self):
        rng = np.random.default_rng(11)
        for _ in range(12):
            n_members = int(rng.integers(2, 6))
            n_shards = int(rng.choice([4, 8, 16, 33]))
            vnodes = int(rng.choice([8, 32, 64]))
            members = [f"m{i}" for i in range(n_members)]
            r = ShardRouter(members, n_shards, virtual_nodes=vnodes)
            gone = members[int(rng.integers(0, n_members))]
            keep = [m for m in members if m != gone]
            if not keep:
                continue
            r2 = r.with_members(keep)
            assert r2.coverage_violations() == []
            for s in range(n_shards):
                if r.shard_member[s] != r2.shard_member[s]:
                    assert r.shard_member[s] == gone

    def test_addition_moves_shards_only_to_the_newcomer(self):
        rng = np.random.default_rng(13)
        for _ in range(12):
            n_members = int(rng.integers(2, 6))
            n_shards = int(rng.choice([4, 8, 16, 33]))
            vnodes = int(rng.choice([8, 32, 64]))
            members = [f"m{i}" for i in range(n_members)]
            r = ShardRouter(members, n_shards, virtual_nodes=vnodes)
            r2 = r.with_members([*members, "new"])
            assert r2.coverage_violations() == []
            for s in range(n_shards):
                if r.shard_member[s] != r2.shard_member[s]:
                    assert r2.shard_member[s] == "new"

    def test_coverage_clean_across_every_step_of_a_plan(self):
        """A multi-step membership plan (join, pinned reassignments one
        shard at a time, departure) keeps total coverage at EVERY
        intermediate router — no shard is ever unowned or double-owned."""
        rng = np.random.default_rng(17)
        for _ in range(6):
            n_shards = int(rng.choice([4, 8, 16]))
            r = ShardRouter([0, 1, 2], n_shards,
                            virtual_nodes=int(rng.choice([8, 32])))
            steps = [r]
            r = r.with_member_added(3)
            steps.append(r)
            target = ShardRouter([0, 1, 3], n_shards, r.virtual_nodes)
            for s in range(n_shards):
                if r.shard_member[s] != target.shard_member[s]:
                    r = r.with_assignment(s, target.shard_member[s])
                    steps.append(r)
            assert r.shards_of_member(2) == []
            r = r.with_member_removed(2)
            steps.append(r)
            for step in steps:
                assert step.coverage_violations() == []
            assert set(r.shard_member) <= {0, 1, 3}


# -- satellite 1: one router snapshot per operation ---------------------------

class TestGenerationSnapshotHammer:
    def test_concurrent_with_members_never_tears_an_operation(self):
        """Red/green for the torn-read fix: every operation keys, places
        and fans off ONE generation snapshot, so a concurrent membership
        flip (same member set — the ring is identical, only the
        generation churns) can never split one write across two maps or
        crash a read mid-fan."""
        stores = [DataStore(backend="tpu") for _ in range(3)]
        view = ShardedDataStoreView(stores, n_shards=12)
        view.create_schema("pts", SPEC)
        _write_rows(view, 60, prefix="base")
        errs: list = []
        stop = threading.Event()

        def flipper():
            for _ in range(200):
                if stop.is_set():
                    return
                try:
                    view.with_members([0, 1, 2])
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    return

        def writer(tag):
            rng = np.random.default_rng(hash(tag) % 2**31)
            for i in range(30):
                try:
                    view.write("pts", [{
                        "name": tag, "dtg": T0 + i,
                        "geom": Point(float(rng.uniform(-170, 170)),
                                      float(rng.uniform(-60, 60)))}],
                        fids=[f"{tag}{i}"])
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    return

        def reader():
            for _ in range(60):
                try:
                    view.query("pts", "BBOX(geom,-180,-90,180,90)")
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    return

        threads = [threading.Thread(target=flipper),
                   threading.Thread(target=writer, args=("wa",)),
                   threading.Thread(target=writer, args=("wb",)),
                   threading.Thread(target=reader)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        stop.set()
        assert not errs, errs[:3]
        assert view.stats_count("pts") == 60 + 30 + 30
        fid_sets = [set(str(f) for f in s.query("pts", None).table.fids)
                    for s in stores]
        for i in range(3):
            for j in range(i + 1, 3):
                assert not (fid_sets[i] & fid_sets[j])


# -- satellite 2: the draining-member signal ----------------------------------

def _http_503(retry_after=None):
    hdrs = email.message_from_string(
        f"Retry-After: {retry_after}\n" if retry_after is not None else "")
    return urllib.error.HTTPError(
        "http://a/api", 503, "unavailable", hdrs, None)


class TestMemberDraining:
    def test_503_with_retry_after_maps_to_typed_error(self):
        from geomesa_tpu.resilience.http import _as_draining

        d = _as_draining(_http_503("1.5"), "http://a/api")
        assert isinstance(d, MemberDrainingError)
        assert d.retry_after_s == 1.5
        # a bare 503 (proxy death, no drain plan) stays a generic 5xx
        assert _as_draining(_http_503(), "u") is None
        assert _as_draining(_http_503("soon"), "u") is None
        e500 = urllib.error.HTTPError(
            "u", 500, "boom", email.message_from_string(""), None)
        assert _as_draining(e500, "u") is None

    def test_drain_is_not_a_breaker_failure(self):
        from geomesa_tpu.resilience.http import _breaker_failure

        assert _breaker_failure(MemberDrainingError("u", 1.0)) is False
        assert _breaker_failure(_http_503("1.0")) is True  # raw 5xx is

    def test_read_retry_honors_retry_after_floor(self):
        sleeps: list = []
        pol = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                          max_delay_s=5.0, seed=1,
                          sleep=sleeps.append)
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] < 3:
                raise MemberDrainingError("http://a", 0.5)
            return "ok"

        assert pol.call(fn, idempotent=True) == "ok"
        assert len(sleeps) == 2
        assert all(s >= 0.5 for s in sleeps)  # the server's floor held

    def test_write_does_not_retry_a_drain(self):
        pol = RetryPolicy(max_attempts=4, base_delay_s=0.001, seed=1,
                          sleep=lambda s: None)
        calls = [0]

        def fn():
            calls[0] += 1
            raise MemberDrainingError("http://a", 0.1)

        with pytest.raises(MemberDrainingError):
            pol.call(fn, idempotent=False)
        assert calls[0] == 1  # immediate: the write re-routes instead

    def test_view_write_reroutes_on_drain_after_map_advance(self):
        class Draining:
            def __init__(self, ds, view_ref):
                self._ds = ds
                self._view_ref = view_ref
                self.drains = 0

            def write(self, *a, **k):
                if self.drains == 0:
                    self.drains += 1
                    # the control plane advanced the map concurrently
                    self._view_ref[0].with_members([0, 1, 2])
                    raise MemberDrainingError("http://m", 0.2)
                return self._ds.write(*a, **k)

            def __getattr__(self, name):
                return getattr(self._ds, name)

        view_ref: list = [None]
        inner = [DataStore(backend="tpu") for _ in range(3)]
        wrapped = [Draining(inner[0], view_ref), inner[1], inner[2]]
        view = ShardedDataStoreView(wrapped, n_shards=8)
        view_ref[0] = view
        view.create_schema("pts", SPEC)
        _write_rows(view, 40)
        assert wrapped[0].drains == 1  # the drain fired and re-routed
        assert view.stats_count("pts") == 40

    def test_view_write_surfaces_drain_when_map_is_stale(self):
        class AlwaysDraining:
            def __init__(self, ds):
                self._ds = ds

            def write(self, *a, **k):
                raise MemberDrainingError("http://m", 0.2)

            def __getattr__(self, name):
                return getattr(self._ds, name)

        inner = [DataStore(backend="tpu") for _ in range(2)]
        view = ShardedDataStoreView(
            [AlwaysDraining(inner[0]), inner[1]], n_shards=8)
        view.create_schema("pts", SPEC)
        # an unchanged generation means the drain signal is ahead of the
        # control plane: surface it, do not spin
        with pytest.raises(MemberDrainingError):
            _write_rows(view, 40)


# -- the tiering ladder -------------------------------------------------------

class _Owner:
    """A pool owner shaped like the backend's residency states: device
    columns in a ``cols`` dict."""

    def __init__(self, n=100):
        self.cols = {"v": np.arange(n, dtype=np.float64)}


class TestTieringPolicy:
    def _demoted(self, type_name, policy, nbytes=800,
                 budget=1000, fingerprint="fp"):
        pool = BufferPool(max_total_bytes=budget)
        pool.attach_tiering(policy)
        owner = _Owner(nbytes // 8)
        register_residency(pool, type_name, "z3", "cols", nbytes, owner,
                           fingerprint=fingerprint)
        assert devmon.ledger().type_bytes(type_name) >= nbytes
        pool.release(type_name, keep_fingerprint=fingerprint)  # → stash
        assert pool.ensure_room(budget - 100)  # stash reclaim demotes
        return pool, owner

    def test_demote_to_ram_then_promote_restores_ledger(self):
        t = "tier_ram_t"
        policy = TieringPolicy(ram_budget=1 << 30, disk_dir=None)
        pool, owner = self._demoted(t, policy)
        assert policy.demotions_ram == 1
        assert policy.tier_bytes()["ram"].get(t) == 800
        # the ledger followed the bytes off the device
        assert devmon.ledger().type_bytes(t) == 0
        assert isinstance(owner.cols["v"], np.ndarray)
        assert policy.coherence_violations() == []
        got = pool.take_donated(t, "z3", "fp")
        assert got is owner
        assert policy.promotions == 1
        assert devmon.ledger().type_bytes(t) == 800  # re-registered
        assert np.array_equal(np.asarray(owner.cols["v"]),
                              np.arange(100, dtype=np.float64))
        assert policy.tier_bytes()["ram"] == {}
        assert (t, "z3") in pool._entries  # re-admitted live
        devmon.ledger().unregister_matching(t, "z3")

    def test_ram_overflow_spills_to_disk_and_promotes_back(self, tmp_path):
        t = "tier_disk_t"
        policy = TieringPolicy(ram_budget=100,
                               disk_dir=str(tmp_path / "cold"))
        pool, owner = self._demoted(t, policy)
        assert policy.demotions_disk == 1
        assert owner.cols == {}  # the RAM actually freed
        tiers = policy.tier_bytes()
        assert tiers["ram"] == {} and tiers["disk"].get(t) == 800
        files = list((tmp_path / "cold").glob("tier-*.npz"))
        assert len(files) == 1
        assert policy.coherence_violations() == []
        got = pool.take_donated(t, "z3", "fp")
        assert got is owner
        assert np.array_equal(np.asarray(owner.cols["v"]),
                              np.arange(100, dtype=np.float64))
        assert not files[0].exists()  # promoted copy left the cold tier
        assert devmon.ledger().type_bytes(t) == 800
        devmon.ledger().unregister_matching(t, "z3")

    def test_no_disk_dir_degrades_overflow_to_a_drop(self):
        t = "tier_drop_t"
        policy = TieringPolicy(ram_budget=100, disk_dir=None)
        pool, owner = self._demoted(t, policy)
        assert policy.drops == 1
        assert policy.tier_bytes() == {"ram": {}, "disk": {}}
        assert pool.take_donated(t, "z3", "fp") is None  # gone for real

    def test_invalidate_drops_all_fingerprints_when_unpinned(self):
        t = "tier_inv_t"
        policy = TieringPolicy(ram_budget=1 << 30, disk_dir=None)
        pool, _ = self._demoted(t, policy)
        pool.purge(t)  # reaches every tier
        assert policy.tier_bytes()["ram"] == {}
        assert pool.take_donated(t, "z3", "fp") is None

    def test_coherence_violations_catch_breakage(self, tmp_path):
        t = "tier_coh_t"
        policy = TieringPolicy(ram_budget=100,
                               disk_dir=str(tmp_path / "cold"))
        self._demoted(t, policy)
        (f,) = (tmp_path / "cold").glob("tier-*.npz")
        os.unlink(f)
        bad = policy.coherence_violations()
        assert any("missing its on-disk file" in v for v in bad)
        # a stale device-ledger row for a demoted entry also flags
        holder = _Owner(8)
        devmon.ledger().register(t, "z3", "cols", 64, owner=holder)
        bad = policy.coherence_violations()
        assert any("still ledgered" in v for v in bad)
        devmon.ledger().unregister_matching(t, "z3")

    def test_sweeper_runs_the_tier_coherence_check(self):
        from geomesa_tpu.obs.audit import InvariantSweeper

        t = "tier_sweep_t"
        policy = TieringPolicy(ram_budget=1 << 30, disk_dir=None)
        pool, _ = self._demoted(t, policy)
        sw = InvariantSweeper()
        sw.attach_pool(pool)
        out = [r for r in sw.sweep_once() if r["check"] == "tiering"]
        assert len(out) == 1
        assert out[0]["checked"] == 1 and out[0]["violations"] == []
        # a pool with no tiering attached abstains instead of failing
        bare = BufferPool(max_total_bytes=10)
        sw2 = InvariantSweeper()
        sw2.attach_pool(bare)
        out2 = [r for r in sw2.sweep_once() if r["check"] == "tiering"]
        assert out2[0]["checked"] == 0

    def test_env_knobs_configure_the_policy(self, monkeypatch, tmp_path):
        monkeypatch.setenv(elastic.TIER_RAM_ENV, "12345")
        monkeypatch.setenv(elastic.TIER_DIR_ENV, str(tmp_path))
        p = TieringPolicy()
        assert p.ram_budget == 12345 and p.disk_dir == str(tmp_path)
        monkeypatch.setenv(elastic.TIER_RAM_ENV, "lots")
        with pytest.raises(ValueError, match="integer byte count"):
            TieringPolicy()


# -- the autoscaler control plane ---------------------------------------------

class TestFederationAutoscaler:
    def _view(self, members=3, n_shards=9):
        stores = [DataStore(backend="tpu") for _ in range(members)]
        view = ShardedDataStoreView(stores, n_shards=n_shards)
        view.create_schema("pts", SPEC)
        return view

    def test_slo_burn_proposes_rebalance_to_healthy_member(self, monkeypatch):
        view = self._view()
        monkeypatch.setattr(view, "member_health", lambda: [
            {"member": 0, "budget_remaining": 0.1},
            {"member": 1, "budget_remaining": 0.9},
            {"member": 2, "budget_remaining": 0.9},
        ])
        sc = FederationAutoscaler(view)
        props = sc.evaluate()
        moves = [p for p in props if p["action"] == "rebalance"]
        assert moves and moves[0]["src"] == 0
        assert moves[0]["dst"] in (1, 2)
        assert moves[0]["shard"] in \
            view._generation.router.shards_of_member(0)
        snap = sc.snapshot()
        assert snap["evals"] == 1 and snap["proposals_total"] >= 1

    def test_admission_shed_pressure_proposes_capacity(self, monkeypatch):
        class Shedding:
            admitted_count = 10
            shed_count = 30

        view = self._view()
        monkeypatch.setattr(view, "member_health", lambda: [])
        sc = FederationAutoscaler(view, admission=Shedding())
        props = sc.evaluate()
        adds = [p for p in props if p["action"] == "add"]
        assert adds and "shedding" in adds[0]["reason"]

    def test_hbm_pressure_proposes_capacity(self, monkeypatch):
        t = "scaler_hbm_t"
        pool = BufferPool(max_total_bytes=1000)
        owner = _Owner(120)
        register_residency(pool, t, "z3", "cols", 960, owner)
        view = self._view()
        monkeypatch.setattr(view, "member_health", lambda: [])
        sc = FederationAutoscaler(view, pool=pool, hbm_headroom_frac=0.1)
        try:
            props = sc.evaluate()
            assert any(p["action"] == "add"
                       and "HBM headroom" in p["reason"] for p in props)
        finally:
            devmon.ledger().unregister_matching(t, "z3")

    def test_idle_member_attracts_a_shard(self, monkeypatch):
        view = self._view()
        monkeypatch.setattr(view, "member_health", lambda: [])
        view.add_member(DataStore(backend="tpu"))  # owns nothing yet
        sc = FederationAutoscaler(view)
        props = sc.evaluate()
        moves = [p for p in props if p["action"] == "rebalance"]
        assert moves and moves[0]["dst"] == 3

    def test_no_proposals_while_a_migration_is_in_flight(self, monkeypatch):
        view = self._view()
        monkeypatch.setattr(view, "member_health", lambda: [
            {"member": 0, "budget_remaining": 0.0}])
        gen = view._generation
        view.swap_generation(gen.advance(
            migrations=(ShardMigration(0, 0, 1, MIG_DUAL),)))
        sc = FederationAutoscaler(view)
        assert sc.evaluate() == []  # let the in-flight move settle

    def test_step_executes_bounded_moves_through_the_migrator(
            self, tmp_path, monkeypatch):
        view, stores, mig = _open_fed(tmp_path, n_shards=4)
        try:
            _write_rows(view, 30)
            router = view._generation.router
            src = router.member_for_shard(0)
            monkeypatch.setattr(view, "member_health", lambda: [
                {"member": src, "budget_remaining": 0.0}])
            sc = FederationAutoscaler(view, migrator=mig,
                                      auto_execute=True,
                                      max_moves_per_eval=1)
            props = sc.step()
            assert any(p["action"] == "rebalance" for p in props)
            assert sc.snapshot()["executed_total"] == 1
            gen = view._generation
            assert gen.router.coverage_violations() == []
            assert view.stats_count("pts") == 30
        finally:
            _close(stores)


# -- observability surfaces ---------------------------------------------------

class TestElasticObservability:
    def _call(self, app, method, path, query=""):
        import io

        environ = {
            "REQUEST_METHOD": method, "PATH_INFO": path,
            "QUERY_STRING": query, "CONTENT_LENGTH": "0",
            "wsgi.input": io.BytesIO(b""),
        }
        out = {}

        def start_response(status, headers):
            out["status"] = int(status.split()[0])
            out["headers"] = dict(headers)

        body = b"".join(app(environ, start_response))
        return out["status"], body

    def test_migration_counters_and_prometheus_lines(self):
        before = elastic.migration_metrics()
        elastic._count_migration("started")
        after = elastic.migration_metrics()
        assert after["started"] == before.get("started", 0) + 1
        text = elastic.prometheus_text()
        assert 'geomesa_shard_migrations_total{state="started"}' in text
        assert "geomesa_autoscaler_evals_total" in text

    def test_tier_bytes_exposition(self):
        t = "tier_prom_t"
        policy = TieringPolicy(ram_budget=1 << 30, disk_dir=None)
        pool = BufferPool(max_total_bytes=1000)
        pool.attach_tiering(policy)
        owner = _Owner(100)
        register_residency(pool, t, "z3", "cols", 800, owner,
                           fingerprint="fp")
        pool.release(t, keep_fingerprint="fp")
        pool.ensure_room(900)
        text = elastic.prometheus_text()
        assert (f'geomesa_tier_bytes{{tier="ram",type="{t}"}} 800'
                in text)

    def test_obs_shards_route_on_a_sharded_view(self):
        from geomesa_tpu.web import GeoMesaApp

        stores = [DataStore(backend="tpu") for _ in range(2)]
        view = ShardedDataStoreView(stores, n_shards=4)
        view.create_schema("pts", SPEC)
        app = GeoMesaApp(view, coalesce_ms=0)
        status, body = self._call(app, "GET", "/api/obs/shards")
        assert status == 200
        doc = json.loads(body)
        assert "migration_counters" in doc
        assert doc["coverage_violations"] == []
        assert doc["n_stores"] == 2
        assert doc["migrations"] == []

    def test_obs_shards_route_on_a_plain_store(self):
        from geomesa_tpu.web import GeoMesaApp

        ds = DataStore(backend="tpu")
        ds.create_schema("pts", SPEC)
        app = GeoMesaApp(ds, coalesce_ms=0)
        status, body = self._call(app, "GET", "/api/obs/shards")
        assert status == 200
        doc = json.loads(body)
        assert doc["sharded"] is False
        assert "migration_counters" in doc

    def test_metrics_exposition_includes_elastic_families(self):
        from geomesa_tpu.web import GeoMesaApp

        ds = DataStore(backend="tpu")
        ds.create_schema("pts", SPEC)
        app = GeoMesaApp(ds, coalesce_ms=0)
        status, body = self._call(app, "GET", "/api/metrics",
                                  "format=prometheus")
        assert status == 200
        assert b"geomesa_shard_migrations_total" in body
