"""Avro interop (schema evolution), shapefile read/write, XML converter
(reference: geomesa-feature-avro serde tests, convert-shp/-xml suites)."""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import LineString, Point, Polygon
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec

T0 = 1_498_867_200_000


def _table(n=25):
    sft = parse_spec("av", "name:String,age:Integer,score:Double,flag:Boolean,dtg:Date,*geom:Point")
    recs = [
        {"name": f"n{i}" if i % 5 else None, "age": i, "score": i * 0.5,
         "flag": bool(i % 2), "dtg": T0 + i * 1000,
         "geom": Point(float(i % 90), float(-i % 45))}
        for i in range(n)
    ]
    return FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(n)])


class TestAvro:
    def test_round_trip(self, tmp_path):
        from geomesa_tpu.io.avro import read_avro, write_avro

        t = _table()
        p = tmp_path / "f.avro"
        write_avro(t, str(p))
        t2 = read_avro(str(p), reader_sft=t.sft)
        assert list(t2.fids) == list(t.fids)
        for i in (0, 5, 24):
            assert t2.record(i) == t.record(i)

    def test_schema_evolution_add_and_drop(self, tmp_path):
        from geomesa_tpu.io.avro import read_avro, write_avro

        t = _table(10)
        p = tmp_path / "f.avro"
        write_avro(t, str(p))
        # evolved reader: 'score' dropped, 'city' added (defaults to null)
        evolved = parse_spec("av", "name:String,age:Integer,city:String,dtg:Date,*geom:Point")
        t2 = read_avro(str(p), reader_sft=evolved)
        assert len(t2) == 10
        r = t2.record(3)
        assert r["name"] == "n3" and r["age"] == 3
        assert r["city"] is None
        assert "score" not in r
        assert r["geom"] == Point(3.0, 42.0)

    def test_raw_read_exposes_writer_schema(self, tmp_path):
        from geomesa_tpu.io.avro import read_avro, write_avro

        t = _table(4)
        p = tmp_path / "f.avro"
        write_avro(t, str(p))
        records, fids, writer = read_avro(str(p))
        assert writer["name"] == "av"
        assert len(records) == 4 and fids[0] == "f0"

    def test_multi_block(self, tmp_path):
        from geomesa_tpu.io.avro import read_avro, write_avro

        t = _table(25)
        p = tmp_path / "f.avro"
        write_avro(t, str(p), block_rows=7)  # forces 4 blocks
        t2 = read_avro(str(p), reader_sft=t.sft)
        assert list(t2.fids) == list(t.fids)
        assert t2.record(24) == t.record(24)


class TestShapefile:
    def test_point_write_read_round_trip(self, tmp_path):
        from geomesa_tpu.convert.shapefile import read_shapefile, write_shapefile

        t = _table(12)
        shp = tmp_path / "pts.shp"
        write_shapefile(t, str(shp))
        assert shp.exists() and shp.with_suffix(".dbf").exists() and shp.with_suffix(".shx").exists()
        t2 = read_shapefile(str(shp))
        assert len(t2) == 12
        g1 = t.geom_column()
        g2 = t2.geom_column()
        np.testing.assert_allclose(g2.x, g1.x)
        np.testing.assert_allclose(g2.y, g1.y)
        r = t2.record(3)
        assert r["name"] == "n3"
        assert int(r["age"]) == 3
        assert abs(float(r["score"]) - 1.5) < 1e-6

    def test_read_into_datastore(self, tmp_path):
        from geomesa_tpu.convert.shapefile import read_shapefile, write_shapefile
        from geomesa_tpu.store.datastore import DataStore

        t = _table(30)
        shp = tmp_path / "pts.shp"
        write_shapefile(t, str(shp))
        loaded = read_shapefile(str(shp))
        ds = DataStore(backend="tpu")
        ds.create_schema(loaded.sft)
        ds.write(loaded.sft.name, loaded)
        assert ds.query(loaded.sft.name, "BBOX(geom, -1, -1, 10, 45)").count > 0

    def test_polygon_read(self, tmp_path):
        # hand-build a one-polygon .shp + .dbf and read it back
        import struct

        ring = np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], dtype=np.float64)
        body = struct.pack("<i", 5) + struct.pack("<4d", 0, 0, 4, 4)
        body += struct.pack("<ii", 1, len(ring)) + struct.pack("<i", 0)
        body += ring.astype("<f8").tobytes()
        rec = struct.pack(">ii", 1, len(body) // 2) + body
        header = (
            struct.pack(">i20x i", 9994, (100 + len(rec)) // 2)
            + struct.pack("<ii", 1000, 5)
            + struct.pack("<4d", 0, 0, 4, 4)
            + struct.pack("<4d", 0, 0, 0, 0)
        )
        (tmp_path / "poly.shp").write_bytes(header + rec)
        # minimal dbf: one C field, one record
        dbf = struct.pack("<B3BIHH20x", 3, 24, 1, 1, 1, 32 + 32 + 1, 1 + 8)
        dbf += b"name".ljust(11, b"\x00") + b"C" + b"\x00" * 4 + bytes([8, 0]) + b"\x00" * 14
        dbf += b"\x0d" + b" " + b"zone-a  " + b"\x1a"
        (tmp_path / "poly.dbf").write_bytes(dbf)

        from geomesa_tpu.convert.shapefile import read_shapefile

        t = read_shapefile(str(tmp_path / "poly.shp"))
        assert len(t) == 1
        g = t.record(0)["geom"]
        assert g.geom_type == "Polygon"
        assert t.record(0)["name"] == "zone-a"


class TestXmlConverter:
    XML = """<data>
      <row id="a1"><who>alice</who><lon>10.5</lon><lat>-3.25</lat>
        <when>2017-07-01T00:00:10Z</when><n units="m">7</n></row>
      <row id="a2"><who>bob</who><lon>-120.0</lon><lat>45.0</lat>
        <when>2017-07-02T00:00:00Z</when><n units="ft">9</n></row>
      <row id="bad"><who>eve</who><lon>999</lon><lat>0</lat>
        <when>2017-07-03T00:00:00Z</when><n units="m">1</n></row>
    </data>"""

    def _conv(self, **kw):
        from geomesa_tpu.convert.xml_converter import XmlConverter

        sft = parse_spec("x", "who:String,n:Integer,units:String,dtg:Date,*geom:Point")
        fields = {"who": "who", "n": "n", "units": "n/@units",
                  "dtg": "when", "geom": "point(lon, lat)"}
        return XmlConverter(sft, fields, feature_path=".//row", id_field="@id", **kw)

    def test_extracts_elements_attrs_and_points(self):
        t = self._conv().convert_str(self.XML)
        assert len(t) == 2  # lon=999 row skipped
        assert list(t.fids) == ["a1", "a2"]
        r = t.record(0)
        assert r["who"] == "alice" and r["n"] == 7 and r["units"] == "m"
        assert r["geom"] == Point(10.5, -3.25)
        assert r["dtg"] == T0 + 10_000

    def test_raise_mode(self):
        with pytest.raises(ValueError, match="bad record"):
            self._conv(error_mode="raise").convert_str(self.XML)

    def test_wkt_expression(self):
        from geomesa_tpu.convert.xml_converter import XmlConverter

        sft = parse_spec("w", "name:String,*geom:Geometry")
        xml = "<r><f><name>t</name><g>LINESTRING (0 0, 1 1)</g></f></r>"
        conv = XmlConverter(sft, {"name": "name", "geom": "wkt(g)"},
                            feature_path=".//f")
        t = conv.convert_str(xml)
        assert t.record(0)["geom"] == LineString(np.array([[0.0, 0.0], [1.0, 1.0]]))
