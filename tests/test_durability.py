"""Durability plane (ISSUE 14): WAL journaling of acked writes,
crash-consistent incremental checkpoints, exactly-once recovery replay,
durable journal head-trims, the kill-at-every-named-crash-point matrix,
and the double-open lock contract. docs/operations.md § Durability &
recovery."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.wal import (
    SCHEMA_TOPIC,
    WalLockedError,
    WalTailError,
    WriteAheadLog,
    topic_for,
    wal_metrics,
)
from geomesa_tpu.stream.journal import JournalBus, TrimmedError

SPEC = "name:String,age:Integer,dtg:Date,*geom:Point:srid=4326"
T0 = 1_498_867_200_000
BBOX_ALL = "BBOX(geom, -180, -90, 180, 90)"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def recs(n, base=0):
    return [
        {"name": f"n{i % 5}", "age": i % 90, "dtg": T0 + i * 1000,
         "geom": Point(float(i % 90 - 45), float(i % 60 - 30))}
        for i in range(base, base + n)
    ]


def fids(n, tag):
    return [f"{tag}.{i}" for i in range(n)]


def open_store(cat, **kw):
    kw.setdefault("recover", True)
    kw.setdefault("checkpointer", False)
    return DataStore.open(str(cat), **kw)


def count(ds, t="evt"):
    return ds.query(t, BBOX_ALL).count


# -- WAL core -----------------------------------------------------------------
class TestWalRecovery:
    def test_acked_writes_survive_simulated_kill(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(40), fids=fids(40, "a"))
        ds.delete_features("evt", ["a.1", "a.2"])
        ds._wal.abandon()  # the in-process SIGKILL stand-in
        ds2 = open_store(cat)
        assert count(ds2) == 38
        live = {str(f) for f in ds2.query("evt", BBOX_ALL).table.fids}
        assert "a.1" not in live and "a.3" in live
        ds2.close()

    def test_checkpoint_stamps_replay_floor_exactly_once(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(10), fids=fids(10, "a"))
        ds.save(str(cat))
        man = json.loads((cat / "manifest.json").read_text())
        assert man["wal"]["topics"][topic_for("evt")] > 0
        assert SCHEMA_TOPIC in man["wal"]["topics"]
        # records below the stamp must NOT re-apply over the checkpoint
        ds._wal.abandon()
        ds2 = open_store(cat)
        assert count(ds2) == 10  # not 20
        ds2.close()

    def test_tail_past_checkpoint_replays(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(10), fids=fids(10, "a"))
        ds.save(str(cat))
        ds.write("evt", recs(7, 100), fids=fids(7, "b"))
        ds.clear("evt")
        ds.write("evt", recs(3, 200), fids=fids(3, "c"))
        ds.age_off("evt")  # no TTL → no-op, must not journal garbage
        ds._wal.abandon()
        ds2 = open_store(cat)
        assert count(ds2) == 3
        assert {str(f) for f in ds2.query("evt", BBOX_ALL).table.fids} == {
            "c.0", "c.1", "c.2"}
        ds2.close()

    def test_recover_false_refuses_tail(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(3))
        ds._wal.abandon()
        with pytest.raises(WalTailError):
            open_store(cat, recover=False)
        ds2 = open_store(cat)  # and recover=True still works after
        assert count(ds2) == 3
        ds2.close()

    def test_schema_ops_interleave_in_order(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(4), fids=fids(4, "a"))
        ds.update_schema("evt", add="sev:Integer")
        ds.write("evt", [{"name": "x", "age": 1, "dtg": T0, "sev": 7,
                          "geom": Point(0, 0)}], fids=["s.0"])
        ds.update_schema("evt", rename_to="evt2")
        ds.write("evt2", recs(2, 50), fids=fids(2, "p"))
        ds.delete_schema("evt2")
        ds.create_schema("evt2", SPEC)
        ds.write("evt2", recs(1, 90), fids=fids(1, "q"))
        ds._wal.abandon()
        ds2 = open_store(cat)
        # the delete+recreate means only the post-recreate row survives
        assert ds2.list_schemas() == ["evt2"]
        assert count(ds2, "evt2") == 1
        attrs = {a.name for a in ds2.get_schema("evt2").attributes}
        assert "sev" not in attrs  # the recreated schema, not the evolved one
        ds2.close()

    def test_update_features_replays(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(5), fids=fids(5, "a"))
        ds.update_features(
            "evt", [{"name": "upd", "age": 99, "dtg": T0,
                     "geom": Point(1, 1)}], ["a.2"])
        ds._wal.abandon()
        ds2 = open_store(cat)
        assert count(ds2) == 5
        res = ds2.query("evt", BBOX_ALL)
        row = [res.table.record(i) for i, f in enumerate(res.table.fids)
               if str(f) == "a.2"]
        assert row and row[0]["name"] == "upd"
        ds2.close()

    def test_double_open_fails_fast_then_succeeds(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        with pytest.raises(WalLockedError):
            open_store(cat)
        ds.close()
        ds2 = open_store(cat)
        assert ds2.list_schemas() == ["evt"]
        ds2.close()

    def test_double_open_from_second_process(self, tmp_path):
        """The satellite pin: a SECOND PROCESS opening a WAL catalog fails
        fast with the typed error, then succeeds after release."""
        cat = tmp_path / "cat"
        ds = open_store(cat)
        code = (
            "import os; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
            "from geomesa_tpu.store.datastore import DataStore\n"
            "from geomesa_tpu.store.wal import WalLockedError\n"
            "try:\n"
            f"    DataStore.open({str(cat)!r}, recover=True, "
            "checkpointer=False)\n"
            "    print('OPENED')\n"
            "except WalLockedError:\n"
            "    print('LOCKED')\n"
        )
        out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                             capture_output=True, text=True, timeout=120)
        assert out.stdout.strip() == "LOCKED", (out.stdout, out.stderr[-800:])
        ds.close()
        out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                             capture_output=True, text=True, timeout=120)
        assert out.stdout.strip() == "OPENED", (out.stdout, out.stderr[-800:])

    def test_wal_trimmed_after_checkpoint(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        for b in range(5):
            ds.write("evt", recs(20, b * 100), fids=fids(20, f"b{b}"))
        topic = topic_for("evt")
        before = ds._wal.bus.committed_offset(topic)
        assert ds._wal.bus.head_offset(topic) == 0
        ds.save(str(cat))
        # committed segments below the manifest stamp left the disk
        assert ds._wal.bus.head_offset(topic) == before
        assert ds._wal.bytes_since_checkpoint == 0
        # and the catalog still recovers losslessly afterwards
        ds.write("evt", recs(5, 900), fids=fids(5, "z"))
        ds._wal.abandon()
        ds2 = open_store(cat)
        assert count(ds2) == 105
        ds2.close()

    def test_incremental_checkpoint_reuses_unchanged_types(self, tmp_path):
        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("hot", SPEC)
        ds.create_schema("cold", SPEC)
        ds.write("hot", recs(10), fids=fids(10, "h"))
        ds.write("cold", recs(10), fids=fids(10, "c"))
        ds.save(str(cat))
        man1 = json.loads((cat / "manifest.json").read_text())
        ds.write("hot", recs(5, 50), fids=fids(5, "h2"))
        skipped0 = wal_metrics()["checkpoint_skipped_types"]
        ds.save(str(cat))
        man2 = json.loads((cat / "manifest.json").read_text())
        # cold reused (same shard files), hot restaged (new generation)
        assert ([f["file"] for f in man2["types"]["cold"]["files"]] ==
                [f["file"] for f in man1["types"]["cold"]["files"]])
        assert ([f["file"] for f in man2["types"]["hot"]["files"]] !=
                [f["file"] for f in man1["types"]["hot"]["files"]])
        assert wal_metrics()["checkpoint_skipped_types"] == skipped0 + 1
        # a delete+recreate of the same name must NOT reuse (ident guard)
        ds.delete_schema("cold")
        ds.create_schema("cold", SPEC)
        ds.save(str(cat))
        man3 = json.loads((cat / "manifest.json").read_text())
        assert man3["types"]["cold"]["files"] == []
        ds.close()
        ds2 = open_store(cat)
        assert count(ds2, "cold") == 0 and count(ds2, "hot") == 15
        ds2.close()

    def test_background_checkpointer_triggers_and_stops(self, tmp_path):
        cat = tmp_path / "cat"
        ds = DataStore.open(str(cat), recover=True, checkpointer=True,
                            ckpt_bytes=2000)
        ds.create_schema("evt", SPEC)
        deadline = time.monotonic() + 30
        b = 0
        while not (cat / "manifest.json").exists():
            ds.write("evt", recs(20, b * 100), fids=fids(20, f"b{b}"))
            b += 1
            if time.monotonic() > deadline:
                pytest.fail("background checkpointer never triggered")
            time.sleep(0.05)
        ds.close()  # deterministic: joins the checkpointer thread
        assert not ds._wal_ckpt
        man = json.loads((cat / "manifest.json").read_text())
        assert "wal" in man

    def test_wal_off_write_path_overhead_under_2pct(self, tmp_path):
        """The non-durable write path pays ONE gate branch per write
        (docs/operations.md pins it < 2% of the cheapest write)."""
        ds = DataStore(backend="tpu")
        ds.create_schema("evt", SPEC)
        data = recs(256)
        walls = []
        for b in range(30):
            t = time.perf_counter()
            ds.write("evt", data, fids=fids(256, f"b{b}"))
            walls.append(time.perf_counter() - t)
        write_s = float(np.percentile(walls, 50))
        t = time.perf_counter()
        for _ in range(20000):
            ds._wal_active()
        gate_s = (time.perf_counter() - t) / 20000
        assert gate_s / write_s < 0.02, (gate_s, write_s)

    def test_group_commit_p99_within_3x_wal_off(self, tmp_path):
        """Acceptance pin: group-commit batching (fsync off — the
        kill-and-recover durability mode the crash harness proves) keeps
        acked-write p99 within 3x the WAL-off baseline. The two paths are
        measured INTERLEAVED (off then wal per iteration) so an ambient
        load spike lands in both distributions — the pin bounds the
        product, not CI scheduler noise — and re-measures up to three
        times."""
        def _timed_write(ds, st, data, tag):
            e0 = st.epoch
            t = time.perf_counter()
            ds.write("evt", data, fids=fids(512, tag))
            wall = time.perf_counter() - t
            # synchronous compactions (identical on both paths) excluded
            return None if st.epoch != e0 else wall

        for attempt in range(3):
            ds_off = DataStore(backend="tpu")
            ds_off.create_schema("evt", SPEC)
            os.environ["GEOMESA_TPU_WAL_FSYNC"] = "off"
            try:
                wdir = tmp_path / f"wal{attempt}"
                ds_wal = DataStore(backend="tpu", wal_dir=str(wdir))
            finally:
                del os.environ["GEOMESA_TPU_WAL_FSYNC"]
            ds_wal.create_schema("evt", SPEC)
            st_off, st_wal = ds_off._state("evt"), ds_wal._state("evt")
            data = recs(512)
            for w in range(3):  # warmup: compiles, first journal I/O
                ds_off.write("evt", data, fids=fids(512, f"w{w}"))
                ds_wal.write("evt", data, fids=fids(512, f"w{w}"))
            off, wal = [], []
            for b in range(80):
                o = _timed_write(ds_off, st_off, data, f"b{b}")
                w = _timed_write(ds_wal, st_wal, data, f"b{b}")
                if o is not None:
                    off.append(o)
                if w is not None:
                    wal.append(w)
            ds_wal._wal.close()
            p99_off = float(np.percentile(off, 99))
            p99_wal = float(np.percentile(wal, 99))
            if p99_wal <= 3.0 * p99_off:
                return
        pytest.fail(f"group-commit p99 {p99_wal * 1e3:.3f}ms > 3x WAL-off "
                    f"{p99_off * 1e3:.3f}ms")

    def test_group_commit_batches_concurrent_writers(self, tmp_path):
        import threading

        os.environ["GEOMESA_TPU_WAL_FLUSH_MS"] = "4"
        try:
            ds = DataStore(backend="tpu", wal_dir=str(tmp_path / "wal"))
        finally:
            del os.environ["GEOMESA_TPU_WAL_FLUSH_MS"]
        ds.create_schema("evt", SPEC)
        m0 = wal_metrics()
        n_threads, per = 6, 8

        def w(t):
            for b in range(per):
                ds.write("evt", recs(4, t * 1000 + b * 10),
                         fids=fids(4, f"t{t}.{b}"))

        threads = [__import__("threading").Thread(target=w, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        m1 = wal_metrics()
        records = m1["records"] - m0["records"]
        flushes = m1["flushes"] - m0["flushes"]
        assert records == n_threads * per
        assert flushes < records  # batching happened
        assert m1["group_max"] >= 2
        assert count(ds) == n_threads * per * 4
        ds._wal.close()

    def test_transient_flush_failure_never_loses_records(self, tmp_path):
        """A failed group-commit flush (ENOSPC-style) raises to the caller
        (no ack) but must NOT lose the journaled-but-unflushed record:
        un-committed records re-enqueue and ride the next flush — so a
        schema create whose flush failed still recovers, and every later
        acked write to that type survives (review finding: the create
        record vanishing made recovery silently skip the type's writes)."""
        cat = tmp_path / "cat"
        ds = open_store(cat)
        real = ds._wal.bus.publish_many
        boom = {"n": 1}

        def flaky(*a, **kw):
            if boom["n"]:
                boom["n"] -= 1
                raise OSError(28, "No space left on device")
            return real(*a, **kw)

        ds._wal.bus.publish_many = flaky
        with pytest.raises(OSError):
            ds.create_schema("evt", SPEC)
        assert "evt" in ds.list_schemas()  # applied; ack failed
        ds._wal.bus.publish_many = real
        ds.write("evt", recs(5), fids=fids(5, "a"))  # flush carries both
        ds._wal.abandon()
        ds2 = open_store(cat)
        assert count(ds2) == 5
        ds2.close()

    def test_unrecovered_attach_cannot_shadow_or_trim_tail(self, tmp_path):
        """Attaching a plain store (the ambient-GEOMESA_TPU_WAL shape) to
        a journal that still holds acked records must refuse to mutate or
        checkpoint: a save would trim — destroy — history that was never
        replayed (review finding). DataStore.open remains the recovery
        door."""
        from geomesa_tpu.store import persistence

        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(4), fids=fids(4, "a"))
        wal_dir = ds._wal.path
        ds._wal.abandon()  # crash with an unreplayed tail
        plain = DataStore(backend="tpu", wal_dir=wal_dir)
        with pytest.raises(WalTailError):
            plain.create_schema("evt2", SPEC)
        with pytest.raises(WalTailError):
            persistence.save(plain, str(cat))
        plain._wal.close()
        # recovery still works and loses nothing
        ds2 = open_store(cat)
        assert count(ds2) == 4
        ds2.close()

    def test_save_type_refuses_wal_store(self, tmp_path):
        """save_type would rewrite shards without moving the WAL replay
        floors — the next recovery would duplicate rows — so WAL-mode
        stores must use the stamped whole-store save."""
        from geomesa_tpu.store import persistence

        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(3))
        with pytest.raises(ValueError, match="WAL"):
            persistence.save_type(ds, str(cat), "evt")
        ds.close()

    def test_sweeper_wal_invariant(self, tmp_path):
        from geomesa_tpu.obs.audit import InvariantSweeper

        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(10), fids=fids(10, "a"))
        ds.save(str(cat))
        sweeper = InvariantSweeper()
        sweeper.attach_store(ds)
        wal_checks = [r for r in sweeper.sweep_once() if r["check"] == "wal"]
        assert wal_checks and wal_checks[0]["checked"] > 0
        assert wal_checks[0]["violations"] == []
        # red: an applied seq the journal never issued must be flagged
        st = ds._state("evt")
        with st.lock:
            st.wal_seq = ds._wal.seq_highwater() + 50
        bad = [r for r in sweeper.sweep_once() if r["check"] == "wal"]
        assert bad[0]["violations"]
        ds.close()

    def test_wal_prometheus_exposition(self, tmp_path):
        from geomesa_tpu.store import wal as walmod

        ds = open_store(tmp_path / "cat")
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(3))
        text = walmod.prometheus_text()
        for series in ("geomesa_wal_records_total",
                       "geomesa_wal_flushes_total",
                       "geomesa_recovery_replayed_records_total"):
            assert f"# TYPE {series}" in text
        ds.close()

    def test_cli_wal_inspection(self, tmp_path, capsys):
        from geomesa_tpu.cli.__main__ import main as cli_main

        cat = tmp_path / "cat"
        ds = open_store(cat)
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(6), fids=fids(6, "a"))
        ds.save(str(cat))
        ds.write("evt", recs(2, 50), fids=fids(2, "b"))
        ds._wal.flush()
        wal_dir = ds._wal.path
        ds.close()
        cli_main(["wal", "--dir", wal_dir, "--catalog", str(cat), "--json"])
        report = json.loads(capsys.readouterr().out)
        assert report["unreplayed_tail"] == 1  # the post-checkpoint write
        evt = [t for t in report["topics"] if t["type"] == "evt"][0]
        assert evt["ops"].get("write") == 1  # below-stamp records trimmed


# -- persistence fsync satellite ----------------------------------------------
class TestDurableCheckpointFsync:
    def _save_with_volatile_fs(self, tmp_path, monkeypatch, durable):
        """Emulate the machine-crash page-cache loss the satellite-1 bug
        exposes: shard files whose CONTENTS were never fsynced before the
        rename read back empty under the committed name."""
        from geomesa_tpu.store import persistence

        synced: set = set()
        real = persistence._fsync_file
        monkeypatch.setattr(persistence, "_fsync_file",
                            lambda p: (synced.add(str(p)), real(p)))
        ds = DataStore(backend="tpu")
        ds.create_schema("evt", SPEC)
        ds.write("evt", recs(20), fids=fids(20, "a"))
        cat = tmp_path / "cat"
        persistence.save(ds, str(cat), durable=durable)
        lost = 0
        for tdir in cat.iterdir():
            if not tdir.is_dir():
                continue
            for shard in tdir.glob("part-*"):
                if str(shard) + ".tmp" not in synced:
                    shard.write_bytes(b"")  # the page cache never landed
                    lost += 1
        return cat, lost

    def test_red_without_durable_a_crash_tears_the_shard(
            self, tmp_path, monkeypatch):
        cat, lost = self._save_with_volatile_fs(tmp_path, monkeypatch,
                                                durable=False)
        assert lost > 0
        with pytest.raises(Exception):
            ds = DataStore.load(str(cat))
            assert count(ds) == 20  # unreachable unless silently wrong

    def test_green_durable_fsyncs_contents_before_rename(
            self, tmp_path, monkeypatch):
        cat, lost = self._save_with_volatile_fs(tmp_path, monkeypatch,
                                                durable=True)
        assert lost == 0
        ds = DataStore.load(str(cat))
        assert count(ds) == 20


# -- journal head-trim satellite ----------------------------------------------
class TestJournalTrim:
    def test_trim_keeps_logical_offsets_and_types_errors(self, tmp_path):
        bus = JournalBus(str(tmp_path), partitions=2)
        bus.publish_many("t", [(f"k{i}", b"m%03d" % i) for i in range(12)])
        rec = list(bus.iter_records("t"))
        below = rec[5][0]
        assert bus.trim("t", below) > 0  # the 2-arg durable form
        assert bus.head_offset("t") == below
        # logical cursors survive: resuming from a pre-trim cursor ABOVE
        # the head still frames correctly
        out, cur = bus.total_poll_bytes("t", rec[7][0])
        assert out[0] == b"m007"
        # cursor 0 = start of retained; below-head cursors are typed errors
        out, _ = bus.total_poll_bytes("t", 0)
        assert out[0] == b"m005"
        with pytest.raises(TrimmedError):
            bus.total_poll_bytes("t", max(below - 1, 1))
        # a second trim below the head is a no-op
        assert bus.trim("t", below) == 0
        # appends continue beyond the trim
        bus.publish_many("t", [("x", b"after")])
        assert list(bus.iter_records("t"))[-1][2] == b"after"
        bus.close()

    def test_trim_memory_form_still_works(self, tmp_path):
        bus = JournalBus(str(tmp_path), partitions=2)
        for i in range(6):
            bus.publish("t", f"k{i}", b"x%d" % i)
        end0 = bus.end_offset("t", 0)
        assert bus.trim("t", 0, end0) >= 0  # 3-arg in-memory release
        assert bus.end_offset("t", 0) == end0  # offsets unaffected
        bus.close()

    def test_fresh_reader_attaches_at_head(self, tmp_path):
        bus = JournalBus(str(tmp_path), partitions=1)
        bus.publish_many("t", [(f"k{i}", b"r%d" % i) for i in range(8)])
        below = list(bus.iter_records("t"))[4][0]
        bus.trim("t", below)
        bus.close()
        bus2 = JournalBus(str(tmp_path), partitions=1)
        assert bus2.end_offset("t", 0) == 4  # only retained records
        got = []
        bus2.subscribe("t", got.append)
        assert got == [b"r4", b"r5", b"r6", b"r7"]
        bus2.close()

    def test_established_reader_below_trim_gets_typed_error(self, tmp_path):
        bus = JournalBus(str(tmp_path), partitions=1)
        bus.publish_many("t", [(f"k{i}", b"r%d" % i) for i in range(4)])
        assert bus.end_offset("t", 0) == 4  # reader state established
        other = JournalBus(str(tmp_path), partitions=1)
        other.publish_many("t", [("k", b"r4")])
        below = list(other.iter_records("t"))[4][1]  # END of record 4
        other.trim("t", below)  # trims ABOVE the first bus's scan position
        other.close()
        with pytest.raises(TrimmedError):
            bus.end_offset("t", 0)
        bus.close()

    def test_checkpointed_consumer_durable_trim(self, tmp_path):
        from geomesa_tpu.stream.consumer import ThreadedConsumer

        bus = JournalBus(str(tmp_path), partitions=2)
        seen = []
        consumer = ThreadedConsumer(bus, "t", lambda d, p: seen.append(d),
                                    threads=2, durable_trim=True)
        for i in range(30):
            bus.publish("t", f"k{i}", b"c%02d" % i)
        assert consumer.drain(10.0)
        assert len(seen) == 30
        # the fully-applied prefix leaves the disk (throttled: poke once)
        bus.trim_applied("t", list(consumer._offsets))
        assert bus.head_offset("t") > 0
        committed = bus.committed_offset("t")
        assert bus.head_offset("t") <= committed
        consumer.close()
        bus.close()
        # a fresh process sees only the retained tail — bounded disk
        bus2 = JournalBus(str(tmp_path), partitions=2)
        retained = len(list(bus2.iter_records("t")))
        assert retained < 30
        bus2.close()

    def test_tail_repair_with_header(self, tmp_path):
        """Torn bytes past the commit offset are truncated on the next
        append even after the log gained a trim header."""
        bus = JournalBus(str(tmp_path), partitions=1)
        bus.publish_many("t", [(f"k{i}", b"ok%d" % i) for i in range(5)])
        bus.trim("t", list(bus.iter_records("t"))[2][0])
        with open(bus._log_path("t"), "ab") as f:
            f.write(b"\xde\xad\xbe\xef-torn-tail")
        bus.publish_many("t", [("k", b"after-repair")])
        payloads = [p for _s, _e, p in bus.iter_records("t")]
        assert payloads == [b"ok2", b"ok3", b"ok4", b"after-repair"]
        bus.close()


# -- the kill matrix (real SIGKILL subprocesses) ------------------------------
class TestCrashMatrix:
    """One kill/recover cycle per NAMED crash point via the harness
    driver (real SIGKILL subprocesses); every restart must recover to
    referee parity with zero acked loss — scripts/crash_smoke.py
    verifies all four durability contracts per cycle."""

    @pytest.mark.parametrize("points", [
        ["wal.post_append_pre_commit", "wal.mid_group_commit"],
        ["ckpt.mid_shard_renames", "ckpt.pre_manifest_replace",
         "recover.mid_replay"],
    ])
    def test_kill_matrix(self, tmp_path, points):
        cmd = [sys.executable, os.path.join(REPO, "scripts", "crash_smoke.py"),
               "--dir", str(tmp_path / "work"),
               "--cycles", str(len(points)), "--rows", "20"]
        for p in points:
            cmd += ["--point", p]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   GEOMESA_CRASH_TIMEOUT_S="45")
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=400, env=env, cwd=REPO)
        assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-800:])
        assert "zero acked-write loss" in out.stdout

    def test_red_leg_detects_injected_loss(self, tmp_path):
        """GEOMESA_TPU_WAL_UNSAFE acks before durability; the harness must
        DETECT the loss (exit 0 = detector fired), never stay silent."""
        cmd = [sys.executable, os.path.join(REPO, "scripts", "crash_smoke.py"),
               "--dir", str(tmp_path / "work"), "--red", "--cycles", "3",
               "--rows", "20"]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   GEOMESA_CRASH_TIMEOUT_S="45")
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=400, env=env, cwd=REPO)
        assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-800:])
        assert "DETECTED" in out.stdout
