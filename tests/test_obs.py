"""Observability subsystem tests: span propagation, EXPLAIN ANALYZE stage
timelines, exporters, jit telemetry, metrics quantiles, gauge atomicity,
and the per-span overhead bound (the tracing-overhead smoke gate wired
into scripts/lint.sh)."""

import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import obs
from geomesa_tpu.geometry import Point
from geomesa_tpu.obs import trace as obs_trace
from geomesa_tpu.obs.export import (
    PROMETHEUS_CONTENT_TYPE,
    chrome_trace_events,
    prometheus_text,
    write_chrome_trace,
)
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore, ExplainAnalyze
from geomesa_tpu.utils.audit import InMemoryAuditWriter
from geomesa_tpu.utils.metrics import Gauge, Histogram, MetricsRegistry

CQL = (
    "BBOX(geom,-50,-50,0,50) AND dtg DURING "
    "2017-07-01T00:00:00Z/2017-07-01T00:05:00Z"
)


@pytest.fixture(autouse=True)
def _tracing_off():
    """Every test starts and ends with global tracing off + empty buffer."""
    obs.disable()
    obs.drain()
    yield
    obs.disable()
    obs.drain()


def _store(backend="tpu", n=400):
    rng = np.random.default_rng(7)
    ds = DataStore(backend=backend, audit_writer=InMemoryAuditWriter())
    ds.create_schema(parse_spec("pts", "name:String,dtg:Date,*geom:Point"))
    recs = [
        {
            "name": f"n{i % 3}",
            "dtg": 1_498_867_200_000 + i * 700,
            "geom": Point(
                float(rng.uniform(-50, 50)), float(rng.uniform(-50, 50))
            ),
        }
        for i in range(n)
    ]
    ds.write("pts", recs)
    ds.compact("pts")
    return ds


class TestSpanCore:
    def test_disabled_is_noop_singleton(self):
        s1 = obs.span("a", x=1)
        s2 = obs.span("b")
        assert s1 is s2 is obs_trace.NOOP
        with s1 as s:
            assert obs.current() is None
            assert s.set(y=2) is s
        assert obs.drain() == []

    def test_nesting_and_ids(self):
        obs.enable(jax_telemetry=False)
        with obs.span("root", kind="r") as root:
            assert obs.current() is root
            with obs.span("child") as c1:
                assert c1.trace_id == root.trace_id
                assert c1.parent_id == root.span_id
                with obs.span("grand") as g:
                    assert g.parent_id == c1.span_id
            with obs.span("child2") as c2:
                pass
        assert obs.current() is None
        assert [c.name for c in root.children] == ["child", "child2"]
        assert root.children[0].children[0].name == "grand"
        assert root.parent_id == ""
        assert root.duration_ms > 0
        # completed root landed in the buffer
        roots = obs.drain()
        assert root in roots
        # ids unique across the tree
        ids = [s.span_id for s in root.walk()]
        assert len(ids) == len(set(ids))

    def test_collect_forces_tracing_without_global_enable(self):
        assert not obs.enabled()
        with obs.collect("outer") as root:
            with obs.span("inner"):
                pass
        assert [c.name for c in root.children] == ["inner"]
        # forced scope ended: spans are no-ops again
        assert obs.span("after") is obs_trace.NOOP

    def test_exception_annotated(self):
        obs.enable(jax_telemetry=False)
        with pytest.raises(ValueError):
            with obs.span("boom") as s:
                raise ValueError("x")
        assert s.attrs["error"] == "ValueError"

    def test_thread_isolation(self):
        """Spans on different threads never attach to each other: each
        thread's ContextVar starts empty → disjoint trees."""
        obs.enable(jax_telemetry=False)
        errs = []

        def work(i):
            try:
                with obs.span(f"t{i}") as s:
                    time.sleep(0.002)
                    with obs.span("inner"):
                        pass
                assert s.parent_id == ""
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=work, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        roots = obs.drain()
        assert len(roots) == 8
        assert len({r.trace_id for r in roots}) == 8
        for r in roots:
            assert [c.name for c in r.children] == ["inner"]
            assert r.children[0].parent_id == r.span_id


class TestQueryTracing:
    def test_explain_analyze_timeline_sums_to_wall(self):
        ds = _store()
        r = ds.query("pts", CQL)  # warm the jit caches first
        ea = ds.explain("pts", CQL, analyze=True)
        assert isinstance(ea, ExplainAnalyze)
        assert ea.hits == r.count
        names = [n for n, _ in ea.stages]
        # the pipeline stages the issue names (serialize lives in web/)
        assert "plan" in names and "reduce" in names
        assert "dispatch" in names or "refine" in names
        # durations PARTITION wall time (the 'other' residual closes gaps)
        assert ea.wall_ms > 0
        assert abs(sum(ms for _, ms in ea.stages) - ea.wall_ms) < 1e-6
        # static explain is unchanged, analyze renders both parts
        assert "Index:" in ea.plan and "Stage timeline" in str(ea)
        assert ds.explain("pts", CQL).startswith("Planning")

    def test_audit_joins_trace(self):
        ds = _store()
        ds.query("pts", CQL)
        assert ds.audit_writer.events[-1].trace_id == ""  # tracing off
        ea = ds.explain("pts", CQL, analyze=True)
        ev = ds.audit_writer.events[-1]
        assert ev.trace_id == ea.timeline.root.trace_id
        assert ev.span_id == ea.timeline.root.span_id
        rec = json.loads(ev.to_json())
        assert rec["trace_id"] and rec["span_id"]

    def test_select_many_batch_span_with_per_query_children(self):
        ds = _store()
        ds.select_many("pts", [CQL, "INCLUDE"])  # warm compile untraced
        obs.enable(jax_telemetry=False)
        try:
            results = ds.select_many("pts", [CQL, "INCLUDE", None])
        finally:
            obs.disable()
        assert len(results) == 3
        batches = [r for r in obs.drain() if r.name == "select_many"]
        assert len(batches) == 1
        batch = batches[0]
        assert batch.attrs["n_queries"] == 3
        qspans = [c for c in batch.children if c.name == "query"]
        # one per-query child span per query, all inside ONE batch trace
        assert len(qspans) == 3
        assert {s.trace_id for s in batch.walk()} == {batch.trace_id}
        for s in qspans:
            assert s.parent_id == batch.span_id

    def test_concurrent_web_queries_disjoint_span_trees(self):
        """The threaded web server: simultaneous requests must build
        disjoint per-request traces with correct parent links."""
        from tests.test_web import jcall
        from geomesa_tpu.web import GeoMesaApp

        ds = _store()
        app = GeoMesaApp(ds)
        jcall(app, "GET", "/api/schemas/pts/query",
              "cql=BBOX(geom,-50,-50,0,50)")  # warm
        obs.enable(jax_telemetry=False)
        errs, n_threads = [], 6

        def request(i):
            try:
                status, out = jcall(
                    app, "GET", "/api/schemas/pts/query",
                    "cql=BBOX(geom,-50,-50,0,50)&limit=5",
                )
                assert status == 200
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [
            threading.Thread(target=request, args=(i,))
            for i in range(n_threads)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        obs.disable()
        assert not errs
        roots = [r for r in obs.drain() if r.name == "http"]
        assert len(roots) == n_threads
        assert len({r.trace_id for r in roots}) == n_threads
        for r in roots:
            # every span in a request's tree carries ITS trace id and a
            # parent chain that resolves within the tree
            members = {s.span_id for s in r.walk()}
            for s in r.walk():
                assert s.trace_id == r.trace_id
                if s is not r:
                    assert s.parent_id in members
            names = {s.name for s in r.walk()}
            assert "query" in names and "serialize" in names

    def test_timeout_worker_inherits_context(self):
        ds = _store()
        from geomesa_tpu.planning.planner import Query

        with obs.collect("root") as root:
            ds.query("pts", Query(filter=CQL, hints={"timeout": 30.0}))
        # the scan ran on the watchdog worker thread; its spans must
        # attach under THIS trace, not float as orphan roots
        assert root.find("query")
        assert {s.trace_id for s in root.walk()} == {root.trace_id}
        assert [r for r in obs.drain() if r.name != "root"] == []


class TestOverhead:
    N_CALLS = 20_000

    def _per_span_ns(self):
        t0 = time.perf_counter_ns()
        for _ in range(self.N_CALLS):
            with obs.span("x", a=1):
                pass
        return (time.perf_counter_ns() - t0) / self.N_CALLS

    def test_disabled_span_cost_bounded(self):
        assert not obs.enabled()
        per_span = min(self._per_span_ns() for _ in range(3))
        # generous CI bound; typical is well under 1 µs
        assert per_span < 20_000, f"disabled span cost {per_span:.0f} ns"

    def test_query_path_overhead_under_2pct(self):
        """The acceptance bound: with tracing disabled, instrumentation on
        the cached-jit select path must cost < 2% — measured as (spans per
        query) x (no-op span cost) against the query's own p50."""
        ds = _store()
        ds.query("pts", CQL)  # compile + plan-cache warm
        lat = []
        for _ in range(15):
            t0 = time.perf_counter_ns()
            ds.query("pts", CQL)
            lat.append(time.perf_counter_ns() - t0)
        p50_ns = float(np.percentile(lat, 50))
        with obs.collect("probe") as root:
            ds.query("pts", CQL)
        n_spans = sum(1 for _ in root.walk()) - 1  # minus the probe root
        assert n_spans >= 3  # the path IS instrumented
        per_span = min(self._per_span_ns() for _ in range(3))
        overhead = n_spans * per_span
        assert overhead < 0.02 * p50_ns, (
            f"{n_spans} spans x {per_span:.0f} ns = {overhead:.0f} ns "
            f">= 2% of p50 {p50_ns:.0f} ns"
        )


class TestExporters:
    def test_chrome_trace_roundtrip(self, tmp_path):
        obs.enable(jax_telemetry=False)
        with obs.span("outer", label="o"):
            with obs.span("inner"):
                pass
        obs.disable()
        path = str(tmp_path / "trace.json")
        n = write_chrome_trace(path, drain=True)
        assert n >= 3  # outer + inner + thread metadata
        doc = json.load(open(path))
        events = doc["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"outer", "inner"}
        for e in xs:
            assert e["ts"] >= 0 and e["dur"] >= 0 and "tid" in e
            assert e["args"]["trace_id"]
        # drained: a second export is empty of X events
        assert all(
            e["ph"] != "X" for e in chrome_trace_events()
        )

    def test_chrome_trace_explicit_root(self):
        with obs.collect("r") as root:
            with obs.span("s"):
                pass
        events = chrome_trace_events(root)
        assert {e["name"] for e in events if e["ph"] == "X"} == {"r", "s"}

    def test_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("store.queries").inc(3)
        reg.gauge("circuit.open").set(1.0)
        for v in range(100):
            reg.histogram("query.hits").update(float(v))
        with reg.timer("req").time():
            pass
        txt = prometheus_text(reg)
        assert "# TYPE geomesa_store_queries_total counter" in txt
        assert "geomesa_store_queries_total 3" in txt
        assert "geomesa_circuit_open 1" in txt
        assert 'geomesa_query_hits{quantile="0.5"} 49.5' in txt
        assert 'geomesa_query_hits{quantile="0.99"}' in txt
        assert "geomesa_query_hits_count 100" in txt
        assert "geomesa_req_seconds_count 1" in txt
        # duplicate family across registries: emitted once
        reg2 = MetricsRegistry()
        reg2.counter("store.queries").inc(9)
        txt2 = prometheus_text(reg, reg2)
        vals = [
            ln for ln in txt2.splitlines()
            if ln.startswith("geomesa_store_queries_total ")
        ]
        assert vals == ["geomesa_store_queries_total 3"]

    def test_registry_report_prometheus(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        assert "geomesa_c_total 1" in reg.report_prometheus()
        assert PROMETHEUS_CONTENT_TYPE.startswith("text/plain")


class TestJaxTelemetry:
    def test_jit_census_and_transfer_bytes(self):
        from geomesa_tpu.obs import jaxmon

        ds = _store()
        ds.query("pts", CQL)
        ds.query("pts", CQL)
        rep = jaxmon.jit_report()
        steps = rep["steps"]
        assert steps, "no observed jit steps on the select path"
        name, stats = next(iter(steps.items()))
        assert stats["calls"] >= stats.get("compiles", 0) >= 1
        # residency staging accounted host→device bytes
        assert rep.get("h2d_bytes", 0) > 0

    def test_recompile_counter_keyed_by_signature(self):
        from geomesa_tpu.obs.jaxmon import observed, registry

        calls = []

        def fake_step(x):
            calls.append(x.shape)
            return x

        w = observed("fake_step", fake_step)
        base = registry().snapshot().get(
            "jax.jit.fake_step.recompiles", {}
        ).get("count", 0)
        w(np.zeros(4))
        w(np.zeros(4))  # same abstract signature: no recompile
        snap = registry().snapshot()
        assert snap["jax.jit.fake_step.calls"]["count"] == 2
        assert snap["jax.jit.fake_step.compiles"]["count"] == 1
        w(np.zeros(8))  # NEW signature on a warm step: the live J003
        snap = registry().snapshot()
        assert snap["jax.jit.fake_step.compiles"]["count"] == 2
        assert snap["jax.jit.fake_step.recompiles"]["count"] == base + 1

    def test_failed_dispatch_does_not_consume_signature(self):
        """A step that dies (device error → circuit-breaker failover) must
        not burn its abstract signature: the successful retry IS the
        compile and must be classified as one."""
        from geomesa_tpu.obs.jaxmon import observed, registry

        state = {"fail": True}

        def step(x):
            if state["fail"]:
                raise RuntimeError("device unavailable")
            return x

        w = observed("flaky_step", step)
        with pytest.raises(RuntimeError):
            w(np.zeros(4))
        snap = registry().snapshot()
        assert snap["jax.jit.flaky_step.compiles"]["count"] == 0
        assert snap["jax.jit.flaky_step.calls"]["count"] == 0
        state["fail"] = False
        w(np.zeros(4))
        snap = registry().snapshot()
        assert snap["jax.jit.flaky_step.compiles"]["count"] == 1
        assert snap["jax.jit.flaky_step.calls"]["count"] == 1
        assert snap["jax.jit.flaky_step.recompiles"]["count"] == 0

    def test_compile_listener_installed(self):
        import jax
        import jax.numpy as jnp

        from geomesa_tpu.obs import jaxmon

        assert jaxmon.install()  # idempotent
        before = jaxmon.registry().snapshot().get(
            "jax.compile.events", {}
        ).get("count", 0)

        def _probe(x):
            return x * 2 + 1

        jax.jit(_probe)(jnp.zeros(3)).block_until_ready()
        snap = jaxmon.registry().snapshot()
        assert snap["jax.compile.events"]["count"] > before
        assert any(k.startswith("jax.compile.") for k in snap)


class TestHistogramQuantiles:
    def test_exact_under_reservoir_size(self):
        h = Histogram()
        for v in range(101):
            h.update(float(v))
        p50, p95, p99 = h.quantiles()
        assert p50 == 50.0 and p95 == 95.0 and p99 == 99.0

    def test_sampled_beyond_reservoir(self):
        h = Histogram()
        for v in range(20_000):
            h.update(float(v))
        p50, p95, p99 = h.quantiles()
        # reservoir is a uniform sample: quantiles land near truth
        assert abs(p50 - 10_000) < 2_000
        assert abs(p95 - 19_000) < 1_000
        assert abs(p99 - 19_800) < 1_000
        assert p50 < p95 < p99

    def test_empty(self):
        assert Histogram().quantiles() == [0.0, 0.0, 0.0]

    def test_snapshot_and_sinks_carry_quantiles(self):
        reg = MetricsRegistry()
        for v in range(100):
            reg.histogram("h").update(float(v))
        with reg.timer("t").time():
            pass
        snap = reg.snapshot()
        assert snap["h"]["p50"] == 49.5 and snap["h"]["p99"] > snap["h"]["p95"]
        assert {"p50_ms", "p95_ms", "p99_ms"} <= set(snap["t"])
        # Graphite/StatsD render every snapshot key → quantiles included
        assert any(
            ln.startswith("gm.h.p95 ")
            for ln in reg.report_graphite("gm").splitlines()
        )
        from geomesa_tpu.utils.metrics import emf_snapshot

        rec = emf_snapshot(reg, namespace="ns")
        names = {
            m["Name"] for m in rec["_aws"]["CloudWatchMetrics"][0]["Metrics"]
        }
        assert {"h.p50", "h.p95", "h.p99", "t.p99"} <= names
        assert rec["h.p50"] == 49.5


class TestGaugeAtomicity:
    def test_concurrent_set_and_sample(self):
        """C001-style assertion: racing set()/value reads never tear and
        never raise; the final value is the last write of some thread."""
        g = Gauge()
        valid = {float(i) for i in range(8)}
        stop = threading.Event()
        errs = []

        def writer(i):
            try:
                while not stop.is_set():
                    g.set(float(i))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        def reader():
            try:
                while not stop.is_set():
                    assert g.value in valid
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
        ts += [threading.Thread(target=reader) for _ in range(4)]
        for t in ts:
            t.start()
        time.sleep(0.3)
        stop.set()
        for t in ts:
            t.join()
        assert not errs
        assert g.value in valid

    def test_add_is_atomic(self):
        g = Gauge()
        n, per = 8, 2_000

        def bump():
            for _ in range(per):
                g.add(1.0)

        ts = [threading.Thread(target=bump) for _ in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert g.value == float(n * per)

    def test_fn_backed_sampling(self):
        g = Gauge()
        g.fn = lambda: 7
        assert g.value == 7.0
