"""Device-failure detection, host failover, circuit breaker, recovery.

Reference stance (SURVEY.md §5): failure recovery is delegated to the backing
store's replicas; here the host columnar table is the replica, so a dead
accelerator degrades queries to exact host scans instead of failing them.
"""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.backends import TpuBackend
from geomesa_tpu.store.datastore import DataStore

SPEC = "name:String,dtg:Date,*geom:Point;geomesa.z3.interval='week'"
T0 = 1_600_000_000_000


def _make_store(n=500, seed=3):
    rng = np.random.default_rng(seed)
    sft = parse_spec("evt", SPEC)
    ds = DataStore(backend="tpu")
    ds.create_schema(sft)
    recs = [
        {
            "name": f"f{i}",
            "dtg": T0 + int(rng.integers(0, 6 * 86_400_000)),
            "geom": Point(float(rng.uniform(-170, 170)), float(rng.uniform(-80, 80))),
        }
        for i in range(n)
    ]
    ds.write("evt", FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(n)]))
    ds.compact("evt")
    return ds


BBOX = "BBOX(geom, -60, -40, 60, 40)"


class TestQueryFailover:
    def test_device_error_fails_over_and_trips_circuit(self, monkeypatch):
        ds = _make_store()
        expected = set(ds.query("evt", BBOX).table.fids)
        assert expected  # non-trivial result set

        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            raise RuntimeError("UNAVAILABLE: relay tunnel wedged")

        monkeypatch.setattr(ds.backend, "_mesh_select_positions", boom)
        r = ds.query("evt", BBOX)
        assert set(r.table.fids) == expected  # exact host failover
        assert calls["n"] == 1
        assert ds.metrics.counter("store.query.device_failovers").count == 1

        # circuit open: the next query never touches the device path
        r2 = ds.query("evt", BBOX)
        assert set(r2.table.fids) == expected
        assert calls["n"] == 1

    def test_non_device_error_propagates(self, monkeypatch):
        ds = _make_store()

        def bad(*a, **k):
            raise ValueError("planner bug")

        monkeypatch.setattr(ds.backend, "_mesh_select_positions", bad)
        with pytest.raises(ValueError):
            ds.query("evt", BBOX)
        # a logic error must NOT open the device circuit
        assert ds._device_available()

    def test_recover_closes_circuit(self, monkeypatch):
        ds = _make_store()
        orig = type(ds.backend)._mesh_select_positions
        calls = {"n": 0}

        def flaky(self, *a, **k):
            calls["n"] += 1
            raise RuntimeError("DEADLINE_EXCEEDED")

        monkeypatch.setattr(type(ds.backend), "_mesh_select_positions", flaky)
        ds.query("evt", BBOX)
        assert not ds._device_available()
        monkeypatch.setattr(type(ds.backend), "_mesh_select_positions", orig)
        assert ds.recover("evt")
        assert ds._device_available()
        expected = set(ds.query("evt", BBOX).table.fids)
        # device path used again (flaky stub no longer installed: calls==1)
        assert calls["n"] == 1
        assert len(expected) > 0

    def test_count_many_failover(self, monkeypatch):
        ds = _make_store()
        qs = [Query(filter=BBOX), Query(filter="BBOX(geom, 0, 0, 50, 30)")]
        truth = [ds.query("evt", q.filter).count for q in qs]

        from geomesa_tpu.parallel import query as pq

        def boom(mesh):
            def step(*a, **k):
                raise RuntimeError("UNAVAILABLE")

            return step

        monkeypatch.setattr(pq, "cached_batched_count_step", boom)
        # loose kernel counts can exceed exact (superset); failover path is
        # exact, so with the device dead the counts must equal the truth
        got = ds.count_many("evt", qs, loose=True)
        assert got == truth
        assert ds.metrics.counter("store.query.device_failovers").count >= 1

    def test_knn_many_failover(self, monkeypatch):
        from geomesa_tpu.process import knn as knn_mod

        ds = _make_store()
        pts = [Point(10.0, 10.0), Point(-50.0, 20.0)]
        want = [t.fids.tolist() for t, _ in knn_mod.knn_many(ds, "evt", pts, k=3)]

        def boom(mesh, k, with_ttl=False, impl=None):
            def step(*a, **k2):
                raise RuntimeError("UNAVAILABLE")

            return step

        monkeypatch.setattr(
            "geomesa_tpu.parallel.query.cached_batched_knn_step", boom
        )
        got = knn_mod.knn_many(ds, "evt", pts, k=3)
        assert [t.fids.tolist() for t, _ in got] == want


class TestLoadFailover:
    def test_write_survives_device_load_failure(self, monkeypatch):
        ds = _make_store(n=100)

        def boom(self, sft, table, indices, fingerprint=None):
            raise RuntimeError("backend 'axon' unavailable")

        monkeypatch.setattr(TpuBackend, "load", boom)
        sft = ds.get_schema("evt")
        extra = FeatureTable.from_records(
            sft,
            [{"name": "x", "dtg": T0, "geom": Point(1.0, 2.0)}],
            ["extra-1"],
        )
        ds.write("evt", extra)
        ds.compact("evt")  # rebuild hits the dead loader → host state
        assert ds.metrics.counter("store.device.load_failures").count >= 1
        r = ds.query("evt", "BBOX(geom, 0.5, 1.5, 1.5, 2.5)")
        assert "extra-1" in set(r.table.fids)

    def test_recover_reloads_device_state(self, monkeypatch):
        ds = _make_store(n=100)
        orig = TpuBackend.load

        def boom(self, sft, table, indices, fingerprint=None):
            raise RuntimeError("backend 'axon' unavailable")

        monkeypatch.setattr(TpuBackend, "load", boom)
        sft = ds.get_schema("evt")
        ds.write(
            "evt",
            FeatureTable.from_records(
                sft, [{"name": "y", "dtg": T0, "geom": Point(3.0, 4.0)}], ["y-1"]
            ),
        )
        ds.compact("evt")
        st = ds._state("evt")
        assert st.backend_state is None
        monkeypatch.setattr(TpuBackend, "load", orig)
        assert ds.recover()
        assert st.backend_state is not None
        # device select serves again, parity vs oracle
        r = ds.query("evt", BBOX)
        oracle = DataStore(backend="oracle")
        oracle.create_schema(parse_spec("evt", SPEC))
        oracle.write("evt", st.table)
        assert set(r.table.fids) == set(oracle.query("evt", BBOX).table.fids)
