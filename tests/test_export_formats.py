"""CLI export format breadth: avro/parquet/orc/gml/leaflet/shp round-trips."""

import numpy as np
import pytest

from geomesa_tpu.cli.__main__ import main
from geomesa_tpu.geometry.types import LineString, Point, Polygon
from geomesa_tpu.io.gml import to_gml
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store import persistence
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    sft = parse_spec(
        "evt", "name:String,dtg:Date,*geom:Point;geomesa.z3.interval='week'"
    )
    ds = DataStore()
    ds.create_schema(sft)
    recs = [
        {"name": f"n{i}", "dtg": T0 + i * 1000, "geom": Point(float(i), 10.0)}
        for i in range(20)
    ]
    ds.write("evt", FeatureTable.from_records(sft, recs, [f"n{i}" for i in range(20)]))
    cat = tmp_path_factory.mktemp("exp") / "cat"
    persistence.save(ds, str(cat))
    return cat


def _export(catalog, fmt, dst):
    main(["export", "-c", str(catalog), "-n", "evt",
          "-q", "BBOX(geom, 4.5, 9, 12.5, 11)", "--format", fmt, "-o", str(dst)])


class TestExportFormats:
    def test_avro(self, catalog, tmp_path):
        from geomesa_tpu.io.avro import read_avro

        dst = tmp_path / "e.avro"
        _export(catalog, "avro", dst)
        records, fids, writer = read_avro(str(dst))
        assert len(records) == 8
        assert set(fids) == {f"n{i}" for i in range(5, 13)}

    def test_parquet_and_orc(self, catalog, tmp_path):
        import pyarrow.orc as po
        import pyarrow.parquet as pq

        dst = tmp_path / "e.parquet"
        _export(catalog, "parquet", dst)
        at = pq.read_table(str(dst))
        assert at.num_rows == 8

        dst2 = tmp_path / "e.orc"
        _export(catalog, "orc", dst2)
        at2 = po.read_table(str(dst2))
        assert at2.num_rows == 8

    def test_gml(self, catalog, tmp_path):
        import xml.etree.ElementTree as ET

        dst = tmp_path / "e.gml"
        _export(catalog, "gml", dst)
        root = ET.fromstring(dst.read_text())
        members = [el for el in root.iter() if el.tag.endswith("featureMember")]
        assert len(members) == 8
        poses = [el.text for el in root.iter() if el.tag.endswith("pos")]
        assert "5 10" in poses

    def test_leaflet(self, catalog, tmp_path):
        dst = tmp_path / "map.html"
        _export(catalog, "leaflet", dst)
        html = dst.read_text()
        assert "L.map(" in html and '"n5"' in html or "n5" in html

    def test_shp(self, catalog, tmp_path):
        from geomesa_tpu.convert.shapefile import read_shapefile

        dst = tmp_path / "e.shp"
        _export(catalog, "shp", dst)
        t = read_shapefile(str(dst))
        assert len(t) == 8


class TestProjectionInteraction:
    def test_gml_with_projection(self, catalog, tmp_path):
        dst = tmp_path / "p.gml"
        main(["export", "-c", str(catalog), "-n", "evt",
              "-q", "BBOX(geom, 4.5, 9, 12.5, 11)", "--format", "gml",
              "-a", "name,geom", "-o", str(dst)])
        doc = dst.read_text()
        assert "<geomesa:name>" in doc and "<geomesa:dtg>" not in doc

    def test_avro_projection_narrows_schema(self, catalog, tmp_path):
        from geomesa_tpu.io.avro import read_avro

        dst = tmp_path / "p.avro"
        main(["export", "-c", str(catalog), "-n", "evt",
              "-q", "BBOX(geom, 4.5, 9, 12.5, 11)", "--format", "avro",
              "-a", "name", "-o", str(dst)])
        records, fids, writer = read_avro(str(dst))
        names = {f["name"] for f in writer["fields"]}
        # projected-out attributes are absent from the schema, not null
        assert "dtg" not in names and "name" in names
        assert all(r["name"] is not None for r in records)

    def test_shp_projection_without_geom_clean_error(self, catalog, tmp_path):
        with pytest.raises(SystemExit, match="geometry"):
            main(["export", "-c", str(catalog), "-n", "evt",
                  "--format", "shp", "-a", "name",
                  "-o", str(tmp_path / "p.shp")])

    def test_shp_requires_shp_suffix_and_keeps_existing(self, catalog, tmp_path):
        dst = tmp_path / "out.dat"
        dst.write_bytes(b"precious")
        with pytest.raises(SystemExit, match="OUTPUT.shp"):
            main(["export", "-c", str(catalog), "-n", "evt",
                  "--format", "shp", "-o", str(dst)])
        assert dst.read_bytes() == b"precious"  # not truncated


class TestGmlGeometryKinds:
    def test_line_polygon_multi(self):
        sft = parse_spec("g", "name:String,*geom:Geometry")
        recs = [
            {"name": "ln", "geom": LineString([[0, 0], [1, 1], [2, 0]])},
            {"name": "pg", "geom": Polygon([[0, 0], [4, 0], [4, 4], [0, 4]])},
        ]
        t = FeatureTable.from_records(sft, recs, ["ln", "pg"])
        doc = to_gml(t).decode()
        assert "<gml:LineString>" in doc
        assert "<gml:Polygon>" in doc and "exterior" in doc
        assert "&" not in doc.replace("&amp;", "").replace("&lt;", "").replace("&gt;", "").replace("&quot;", "").replace("&apos;", "")

    def test_escaping(self):
        sft = parse_spec("g", "name:String,*geom:Point")
        t = FeatureTable.from_records(
            sft, [{"name": "a<b>&c", "geom": Point(1.0, 2.0)}], ["f<&>1"]
        )
        doc = to_gml(t).decode()
        assert "a&lt;b&gt;&amp;c" in doc
        assert 'gml:id="f&lt;&amp;&gt;1"' in doc
