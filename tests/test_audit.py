"""Continuous correctness auditor (obs/audit.py + ops/referee.py).

The ISSUE 13 gate: sampled shadow re-execution against the independent
referee catches an injected single-row device corruption (bundle written,
``replay --bundle`` reproducing it), epoch races abstain instead of
alarming under concurrent writes, the delta-debug minimizer shrinks a
4-conjunct predicate to the one faulty clause, the invariant sweeps go
red on seeded structural drift, audit traffic stays out of every
feedback plane, and the 0%-sampling off path holds the <2% bound on the
cached-jit select path.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import obs
from geomesa_tpu.filter import ast
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.obs import audit, devmon, flight, usage, workload
from geomesa_tpu.obs import replay as obs_replay
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.resilience import faults
from geomesa_tpu.store.datastore import DataStore

CQL = "BBOX(geom, -101, 9, -80, 30)"
CQL4 = ("BBOX(geom, -101, 9, -80, 30) AND age >= 0 AND age <= 100 "
        "AND dtg DURING 2020-09-13T00:00:00Z/2020-09-14T00:00:00Z")


def _store(n=200, compact=True):
    ds = DataStore(backend="tpu")
    ds.create_schema(
        "evt", "name:String,age:Integer,dtg:Date,*geom:Point:srid=4326")
    recs = [
        {"name": f"n{i}", "age": i % 7,
         "dtg": 1_600_000_000_000 + i * 1000,
         "geom": Point(-100 + i * 0.1, 10 + i * 0.05)}
        for i in range(n)
    ]
    ds.write("evt", recs)
    if compact:
        ds.compact("evt")
    return ds


@pytest.fixture()
def auditor(tmp_path):
    """A synchronous (drain-driven) rate-1.0 auditor with a bundle dir,
    installed for the test and restored after."""
    aud = audit.ContinuousAuditor(
        rate=1.0, autostart=False, bundle_dir=str(tmp_path / "bundles"))
    prev = audit.install(aud)
    yield aud
    audit.install(prev)
    audit.set_rate(0.0)


@pytest.fixture()
def fresh_flight():
    prev = flight.install(flight.FlightRecorder(dump_dir=None))
    yield flight.get()
    flight.install(prev)


def _corrupt(ds, row=3):
    """Flip one device-column value through the deterministic
    FaultInjector rule (resilience/faults.py kind=flip) + reload."""
    inj = faults.FaultInjector().rule("flip", match="evt", truncate_at=row)
    faults.install(inj)
    try:
        ds.recover("evt")
    finally:
        faults.uninstall()
    return inj


class TestReferee:
    def test_select_matches_live_on_clean_store(self):
        ds = _store()
        from geomesa_tpu.ops import referee

        q = Query(filter=CQL)
        live = sorted(str(f) for f in ds.query("evt", q).table.fids)
        st = ds._types["evt"]
        main, _i, _b, _s, delta = st.snapshot()
        assert referee.referee_select(st.sft, main, delta, q) == live

    def test_delta_tier_rows_included(self):
        ds = _store(100, compact=True)
        ds.write("evt", [{"name": "x", "age": 1,
                          "dtg": 1_600_000_000_000,
                          "geom": Point(-90.0, 12.0)}])
        from geomesa_tpu.ops import referee

        q = Query(filter=CQL)
        st = ds._types["evt"]
        main, _i, _b, _s, delta = st.snapshot()
        ref = referee.referee_select(st.sft, main, delta, q)
        assert len(ref) == ds.query("evt", q).count

    def test_agg_equal_tolerates_summation_noise(self):
        from geomesa_tpu.ops import referee

        a = {("k",): {"count": 3, "cols": {"v": [3, 1.0, 0.1, 0.7]}}}
        b = {("k",): {"count": 3,
                      "cols": {"v": [3, 1.0 + 1e-12, 0.1, 0.7]}}}
        assert referee.agg_equal(a, b)[0]
        b[("k",)]["count"] = 4
        ok, detail = referee.agg_equal(a, b)
        assert not ok and "count" in detail


class TestShadowAudit:
    def test_clean_store_audits_pass(self, auditor):
        ds = _store()
        ds.query("evt", CQL)
        out = ds.aggregate_many("evt", [CQL], group_by=["age"],
                                value_cols=["age"])
        assert out[0] is not None
        assert auditor.drain() == 2
        snap = auditor.snapshot()
        assert snap["checks"]["select"]["passed"] == 1
        assert snap["checks"]["agg"]["passed"] == 1
        assert snap["checks"]["select"]["diverged"] == 0
        assert snap["checks"]["agg"]["diverged"] == 0

    def test_count_many_exact_audits(self, auditor):
        ds = _store()
        counts = ds.count_many("evt", [CQL], loose=False)
        assert counts == [200]
        auditor.drain()
        assert auditor.snapshot()["checks"]["count"]["passed"] == 1

    def test_loose_counts_never_audited(self, auditor):
        ds = _store()
        ds.count_many("evt", [CQL], loose=True)
        auditor.drain()
        assert auditor.snapshot()["checks"]["count"]["checked"] == 0

    def test_hint_audits_at_zero_rate(self, auditor):
        audit.set_rate(0.0)
        ds = _store()
        ds.query("evt", Query(filter=CQL, hints={"audit": True}))
        ds.query("evt", CQL)  # untagged: not audited
        assert auditor.drain() == 1
        assert auditor.snapshot()["checks"]["select"]["passed"] == 1

    def test_ineligible_shapes_skip(self, auditor):
        ds = _store()
        ds.query("evt", Query(filter=CQL, limit=5))
        ds.query("evt", Query(filter=CQL, hints={"density": {}}))
        auditor.drain()
        assert auditor.snapshot()["checks"]["select"]["checked"] == 0


class TestDivergence:
    def test_corruption_caught_bundle_replays(self, auditor, fresh_flight):
        """The end-to-end acceptance pin: an injected one-row device
        corruption is caught by shadow re-execution within K sampled
        queries, emits A_DIVERGE + non-zero diverged counters, writes a
        repro bundle, and the bundle replays to the same divergence."""
        ds = _store()
        _corrupt(ds, row=3)
        caught = None
        for k in range(8):  # detected within K sampled queries
            ds.query("evt", CQL)
            auditor.drain()
            if auditor.snapshot()["checks"]["select"]["diverged"]:
                caught = k
                break
        assert caught is not None
        snap = auditor.snapshot()
        div = snap["divergences"][-1]
        assert div["kind"] == "select"
        assert "missing from live" in div["detail"]
        # prometheus counter non-zero
        text = auditor.prometheus_text()
        assert 'geomesa_audit_diverged_total{kind="select"} 1' in text
        # A_DIVERGE flight anomaly
        anom = [r for r in fresh_flight.records()
                if flight.A_DIVERGE in (r.anomalies or ())]
        assert anom and anom[-1].source == "audit"
        # the bundle replays to the same divergence on the live store
        assert div["bundle_path"]
        doc = obs_replay.replay_bundle(ds, div["bundle_path"])
        assert doc["reproduced"]
        assert doc["original"]["diverged"]
        # a healthy store does NOT reproduce it (exit-3 contract)
        clean = _store()
        doc2 = obs_replay.replay_bundle(clean, div["bundle_path"])
        assert not doc2["reproduced"]

    def test_minimizer_shrinks_to_faulty_clause(self, auditor):
        """A 4-conjunct predicate minimizes to the one faulty clause:
        the non-spatial conjuncts drop (the divergence persists without
        them) and the surviving BBOX halves toward the corrupted row."""
        ds = _store()
        _corrupt(ds, row=3)
        ds.query("evt", CQL4)
        auditor.drain()
        snap = auditor.snapshot()
        assert snap["checks"]["select"]["diverged"] == 1
        minimized = snap["divergences"][-1]["minimized"]
        assert "AND" not in minimized  # one clause survives
        assert minimized.startswith("BBOX")  # the faulty (spatial) one
        # and it shrank: the minimized box is narrower than the original
        from geomesa_tpu.filter.cql import parse

        m = parse(minimized)
        assert (m.xmax - m.xmin) < ((-80) - (-101)) / 2

    def test_minimize_predicate_unit(self):
        """ddmin semantics on a synthetic oracle: divergence persists
        while the candidate still matches the faulty point."""
        from geomesa_tpu.filter.cql import parse

        f = parse(CQL4)
        faulty = (-99.7, 10.15)  # row 3's point

        def diverges(cand):
            # evaluate the candidate against a one-row table
            from geomesa_tpu.schema.columnar import FeatureTable
            from geomesa_tpu.schema.sft import parse_spec

            sft = parse_spec(
                "t", "age:Integer,dtg:Date,*geom:Point:srid=4326")
            t = FeatureTable.from_records(sft, [{
                "age": 3, "dtg": 1_600_000_003_000,
                "geom": Point(*faulty)}], ["f0"])
            return bool(cand.mask(t)[0])

        m = audit.minimize_predicate(f, diverges, max_checks=64)
        # 1-minimal: one clause survives (the symmetric oracle lets
        # ddmin keep whichever divergence-preserving leaf it reaches
        # first), narrowed down to a sliver around the faulty point
        assert not isinstance(m, (ast.And, ast.Or))
        assert diverges(m)  # still covers the faulty point
        if isinstance(m, ast.BBox):
            assert (m.xmax - m.xmin) < 1e-3
        else:
            assert isinstance(m, ast.During)
            assert (m.hi_millis - m.lo_millis) <= 4

    def test_epoch_race_abstains_never_alarms(self, auditor):
        """A write landing between capture and re-check moves the epoch:
        the check abstains. Under a concurrent writer hammering the
        store, rate-1.0 auditing must produce ZERO divergences."""
        ds = _store()
        ds.query("evt", CQL)
        # mutate before the drain: the queued check's epoch is stale
        ds.write("evt", [{"name": "z", "age": 1,
                          "dtg": 1_600_000_000_000,
                          "geom": Point(-90.0, 12.0)}])
        auditor.drain()
        snap = auditor.snapshot()
        assert snap["checks"]["select"]["abstained"] == 1
        assert snap["checks"]["select"]["diverged"] == 0

        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                ds.write("evt", [{"name": f"w{i}", "age": 1,
                                  "dtg": 1_600_000_000_000 + i,
                                  "geom": Point(-90.0, 12.0)}])
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(10):
                ds.query("evt", CQL)
                auditor.drain()
        finally:
            stop.set()
            t.join()
        auditor.drain()
        snap = auditor.snapshot()
        assert snap["checks"]["select"]["diverged"] == 0  # abstain, never alarm


class TestFeedbackHygiene:
    def test_audit_executions_invisible_to_feedback_planes(
            self, auditor, tmp_path):
        """Satellite bugfix red/green: the auditor's own executions (the
        minimizer re-runs the live path repeatedly) must not land in the
        cost table, usage meter, SLO burn, or workload capture."""
        ds = _store()
        prev_meter = usage.install(usage.UsageMeter())
        prev_journal = workload.install(
            workload.WorkloadJournal(str(tmp_path / "cap"), flush_every=1))
        try:
            # GREEN CONTROL: a normal query moves all four planes
            ds.query("evt", CQL)
            auditor.drain()  # the clean audit itself must not move them
            meter = usage.get()
            base_obs = meter.observe_count
            base_events = workload.get().event_count
            base_cost = devmon.costs().snapshot()["entry_count"]
            base_slo = ds.slo.tracker("store.query", "evt").burn_rate(300.0)
            assert base_obs >= 1 and base_events >= 1

            # RED TRIGGER: a divergence runs the minimizer (many shadow
            # live re-executions) — none of them may meter/train/burn
            _corrupt(ds, row=3)
            ds.query("evt", CQL)
            live_obs_after_query = meter.observe_count
            auditor.drain()
            assert auditor.snapshot()["checks"]["select"]["diverged"] == 1
            assert meter.observe_count == live_obs_after_query
            assert workload.get().event_count == base_events + 1  # the live one
            # the cost table saw only the LIVE queries' signatures, and
            # per-signature counts did not grow during the drain
            snap_before = devmon.costs().snapshot()
            auditor.drain()
            assert devmon.costs().snapshot() == snap_before
        finally:
            usage.install(prev_meter)
            workload.install(prev_journal)

    def test_shadow_context_flag(self):
        assert not audit.in_shadow()
        with audit.shadow():
            assert audit.in_shadow()
        assert not audit.in_shadow()


class TestSweeper:
    def test_pyramid_reconciles_then_catches_corruption(self):
        ds = _store(300)
        st = ds._types["evt"]
        pyr = ds._pyramid(st, "evt", st.table, ["age"], ["age"], st.epoch)
        assert pyr is not None
        aud = audit.ContinuousAuditor(rate=0.0, autostart=False)
        sw = audit.InvariantSweeper(auditor=aud)
        sw.attach_store(ds)
        res = {r["check"]: r for r in sw.sweep_once()}
        assert res["pyramid"]["checked"] > 0
        assert res["pyramid"]["violations"] == []
        assert res["ledger"]["violations"] == []
        assert res["query_cache"]["violations"] == []
        # seed drift: bump one non-empty partial
        nz = np.argwhere(pyr.levels[-1].cnt > 0)[0]
        pyr.levels[-1].cnt[tuple(nz)] += 1
        res = {r["check"]: r for r in sw.sweep_once()}
        assert res["pyramid"]["violations"]
        counters = aud.snapshot()["checks"]
        assert counters["sweep:pyramid"]["diverged"] == 1

    def test_query_cache_epoch_invariants(self):
        ds = _store()
        ds.aggregate_many("evt", [CQL], group_by=["age"],
                          value_cols=["age"])
        aud = audit.ContinuousAuditor(rate=0.0, autostart=False)
        sw = audit.InvariantSweeper(auditor=aud)
        sw.attach_store(ds)
        res = {r["check"]: r for r in sw.sweep_once()}
        assert res["query_cache"]["checked"] >= 1
        assert res["query_cache"]["violations"] == []
        # seed a future-stamped entry: served-after-epoch-catches-up bug
        ds.agg_cache.put("evt", ("fake",), (10**6, 10**6),
                         {"groups": [], "count": np.zeros(0, np.int64),
                          "cols": {}})
        res = {r["check"]: r for r in sw.sweep_once()}
        assert any("ahead of live" in v
                   for v in res["query_cache"]["violations"])
        # and an entry outliving its schema
        ds.agg_cache.invalidate()
        ds.agg_cache.put("ghost", ("k",), (0, 0),
                         {"groups": [], "count": np.zeros(0, np.int64),
                          "cols": {}})
        res = {r["check"]: r for r in sw.sweep_once()}
        assert any("deleted/renamed" in v
                   for v in res["query_cache"]["violations"])

    def test_matrix_sentinels_red_green(self):
        from geomesa_tpu.stream.matrix import SubscriptionMatrix

        m = SubscriptionMatrix()
        sid = m.subscribe_packed(np.array([[0, 100, 0, 100]]),
                                 np.array([[0, 0, 1, 0]]), lambda b: None)
        m.unsubscribe(sid)
        assert m.validate_sentinels() == []
        # corrupt a masked slot: make its box satisfiable
        slot = m._slots.index(None)
        m._boxes[slot, 0] = [0, 100, 0, 100]
        out = m.validate_sentinels()
        assert out and "slot" in out[0]

    def test_shard_coverage_red_green(self):
        from geomesa_tpu.serving.shards import ShardRouter

        r = ShardRouter([0, 1, 2], n_shards=8)
        assert r.coverage_violations() == []
        r.shard_member[3] = 99  # departed member owns a shard
        assert any("departed" in v for v in r.coverage_violations())

    def test_standing_counts_cross_check(self):
        from geomesa_tpu.stream.datastore import StreamingDataStore

        sds = StreamingDataStore()
        sds.create_schema("t", "name:String,dtg:Date,*geom:Point:srid=4326")
        hits = []
        sid = sds.subscribe_query("t", "BBOX(geom, -101, 9, -80, 30)",
                                  hits.append)
        for i in range(40):
            sds.put("t", f"f{i}", {
                "name": f"n{i}", "dtg": 1_600_000_000_000 + i * 1000,
                "geom": Point(-100 + i * 0.1, 10 + i * 0.05)})
        assert sds.drain("t")
        aud = audit.ContinuousAuditor(rate=0.0, autostart=False)
        sw = audit.InvariantSweeper(auditor=aud)
        sw.attach_stream(sds)
        res = {r["check"]: r for r in sw.sweep_once()}
        assert res["standing_counts"]["checked"] == 1
        assert res["standing_counts"]["violations"] == []
        # seed a missed delivery: cumulative total below the exact count
        hub = sds.query_hub("t")
        hub.scanner._totals[sid] -= 2
        res = {r["check"]: r for r in sw.sweep_once()}
        assert any("missed deliveries" in v
                   for v in res["standing_counts"]["violations"])
        sds.close()

    def test_sweep_queries_stay_out_of_feedback_planes(self):
        """The standing-count sweep issues real store.query calls: they
        run in shadow, so a sweep never meters usage, trains the cost
        table, or gets sampled into a fresh audit check."""
        from geomesa_tpu.stream.datastore import StreamingDataStore

        sds = StreamingDataStore()
        sds.create_schema("t", "name:String,dtg:Date,*geom:Point:srid=4326")
        sds.subscribe_query("t", "BBOX(geom, -101, 9, -80, 30)",
                            lambda b: None)
        for i in range(10):
            sds.put("t", f"f{i}", {
                "name": f"n{i}", "dtg": 1_600_000_000_000 + i * 1000,
                "geom": Point(-100 + i * 0.1, 10 + i * 0.05)})
        assert sds.drain("t")
        aud = audit.ContinuousAuditor(rate=1.0, autostart=False)
        prev = audit.install(aud)
        prev_meter = usage.install(usage.UsageMeter())
        try:
            sw = audit.InvariantSweeper(auditor=aud)
            sw.attach_stream(sds)
            res = {r["check"]: r for r in sw.sweep_once()}
            assert res["standing_counts"]["checked"] == 1
            assert usage.get().observe_count == 0
            assert aud.queue_depth() == 0  # sweep query not re-sampled
        finally:
            usage.install(prev_meter)
            audit.install(prev)
            audit.set_rate(0.0)
        sds.close()

    def test_sweeper_thread_lifecycle(self):
        aud = audit.ContinuousAuditor(rate=0.0, autostart=False)
        sw = audit.InvariantSweeper(auditor=aud, interval_s=0.01)
        sw.attach_store(_store(50))
        sw.start()
        deadline = time.monotonic() + 5.0
        while sw.sweep_count == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        sw.close()
        sw.close()  # idempotent
        assert sw.sweep_count >= 1


class TestStreamFreshness:
    def test_watermark_and_freshness_gauges(self):
        from geomesa_tpu.stream import telemetry
        from geomesa_tpu.stream.datastore import StreamingDataStore

        telemetry.reset()
        sds = StreamingDataStore()
        sds.create_schema("t", "name:String,dtg:Date,*geom:Point:srid=4326")
        sds.subscribe_query("t", "BBOX(geom, -101, 9, -80, 30)",
                            lambda b: None)
        last_ms = 1_600_000_000_000 + 39 * 1000
        for i in range(40):
            sds.put("t", f"f{i}", {
                "name": f"n{i}", "dtg": 1_600_000_000_000 + i * 1000,
                "geom": Point(-100 + i * 0.1, 10 + i * 0.05)})
        assert sds.drain("t")
        rep = telemetry.report()["geomesa-t"]
        (sub, wm), = rep["watermarks"].items()
        # week-binned offsets are second-granular: the watermark is the
        # newest event time rounded down to its offset unit
        assert abs(wm["watermark_ms"] - last_ms) < 1000
        assert wm["freshness_ms"] > 0
        lines = telemetry.prometheus_lines()
        assert any("geomesa_stream_watermark_ms{" in ln for ln in lines)
        assert any("geomesa_stream_freshness_ms{" in ln for ln in lines)
        sds.close()
        telemetry.reset()


class TestMemberCosts:
    def test_per_member_aggregates_and_filter(self):
        from geomesa_tpu.store.merged import MergedDataStoreView

        m0, m1 = _store(60), _store(80)
        view = MergedDataStoreView([m0, m1])
        for _ in range(3):
            view.query("evt", CQL)
        view.stats_count("evt", CQL)
        rows = view.member_costs_snapshot()
        assert {r["member"] for r in rows} == {0, 1}
        ops = {r["op"] for r in rows}
        assert "query" in ops and "stats_count" in ops
        q_rows = [r for r in rows if r["op"] == "query"]
        assert all(r["count"] == 3 for r in q_rows)
        assert all(r["wall_ms_p50"] > 0 for r in q_rows)
        only0 = view.member_costs_snapshot(member=0)
        assert {r["member"] for r in only0} == {0}
        text = view.explain("evt", CQL)
        assert "Member cost asymmetry" in text

    def test_costs_endpoint_member_section(self):
        from geomesa_tpu.store.merged import MergedDataStoreView
        from geomesa_tpu.web.app import GeoMesaApp

        view = MergedDataStoreView([_store(50), _store(50)])
        view.query("evt", CQL)
        app = GeoMesaApp(view, coalesce_ms=0)
        status, doc = _jcall(app, "GET", "/api/obs/costs")
        assert status == 200
        assert {m["member"] for m in doc["members"]} == {0, 1}
        status, doc = _jcall(app, "GET", "/api/obs/costs", "member=1")
        assert {m["member"] for m in doc["members"]} == {1}


def _jcall(app, method, path, query="", body=None):
    raw = json.dumps(body).encode() if body is not None else b""
    environ = {
        "REQUEST_METHOD": method, "PATH_INFO": path,
        "QUERY_STRING": query, "CONTENT_LENGTH": str(len(raw)),
        "wsgi.input": io.BytesIO(raw),
    }
    out = {}

    def start_response(status, headers):
        out["status"] = int(status.split()[0])

    chunks = app(environ, start_response)
    data = b"".join(chunks)
    return out["status"], json.loads(data) if data else None


class TestSurfaces:
    def test_obs_audit_endpoint_and_metrics(self, auditor):
        from geomesa_tpu.web.app import GeoMesaApp

        ds = _store()
        ds.query("evt", CQL)
        auditor.drain()
        app = GeoMesaApp(ds, coalesce_ms=0)
        status, doc = _jcall(app, "GET", "/api/obs/audit")
        assert status == 200
        assert doc["checks"]["select"]["passed"] == 1
        # prometheus exposition carries the audit series
        raw = json.dumps(None)
        environ = {
            "REQUEST_METHOD": "GET", "PATH_INFO": "/api/metrics",
            "QUERY_STRING": "format=prometheus", "CONTENT_LENGTH": "0",
            "wsgi.input": io.BytesIO(b""),
        }
        out = {}

        def start_response(status_line, headers):
            out["status"] = status_line

        text = b"".join(app(environ, start_response)).decode()
        assert "geomesa_audit_checked_total" in text
        assert 'geomesa_audit_passed_total{kind="select"} 1' in text

    def test_explain_analyze_audit_line(self, auditor):
        ds = _store()
        ea = ds.explain("evt", CQL, analyze=True)
        assert ea.audit is not None
        assert ea.audit["verdict"] == "pass"
        assert "Audit: pass (select)" in str(ea)

    def test_queue_bound_drops_counted(self):
        aud = audit.ContinuousAuditor(rate=1.0, autostart=False,
                                      max_queue=2)
        prev = audit.install(aud)
        try:
            ds = _store(50)
            for _ in range(5):
                ds.query("evt", CQL)
            assert aud.queue_depth() == 2
            assert aud.dropped == 3
            aud.drain()
        finally:
            audit.install(prev)
            audit.set_rate(0.0)

    def test_install_swap_back_revives_auditor_and_rate(self):
        """install(old) after old was swapped out must revive its
        worker (a closed auditor would silently drop every enqueue) and
        restore ITS sampling rate."""
        a = audit.ContinuousAuditor(rate=1.0, autostart=True)
        prev = audit.install(a)
        b = audit.ContinuousAuditor(rate=0.0, autostart=True)
        audit.install(b)  # closes a, rate now 0
        assert not audit.ENABLED
        audit.install(a)  # swap back: revived, rate 1.0 again
        try:
            assert audit.ENABLED
            ds = _store(50)
            ds.query("evt", CQL)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if a.snapshot()["checks"]["select"]["passed"]:
                    break
                time.sleep(0.01)
            assert a.snapshot()["checks"]["select"]["passed"] == 1
            assert a.dropped == 0
        finally:
            audit.install(prev)
            audit.set_rate(0.0)

    def test_ineligible_queries_do_not_burn_sampling_ticks(self):
        """Eligibility is checked BEFORE the sampling tick: a workload
        dominated by density queries must not erode the configured rate
        over auditable selects."""
        aud = audit.ContinuousAuditor(rate=1.0, autostart=False)
        prev = audit.install(aud)
        try:
            ds = _store(50)
            for _ in range(5):
                ds.query("evt", Query(filter=CQL, hints={"density": {}}))
            ds.query("evt", CQL)  # the eligible one still samples
            assert aud.queue_depth() == 1
            aud.drain()
        finally:
            audit.install(prev)
            audit.set_rate(0.0)

    def test_worker_thread_runs_checks(self):
        aud = audit.ContinuousAuditor(rate=1.0, autostart=True)
        prev = audit.install(aud)
        try:
            ds = _store(50)
            ds.query("evt", CQL)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if aud.snapshot()["checks"]["select"]["checked"]:
                    break
                time.sleep(0.01)
            assert aud.snapshot()["checks"]["select"]["passed"] == 1
        finally:
            audit.install(prev)  # closes the worker
            audit.set_rate(0.0)


class TestOverhead:
    def test_off_path_overhead_under_2pct(self):
        """Acceptance bound: the always-on auditor at 0% sampling adds
        one module-global bool + one ContextVar read + a hints lookup
        per query — measured against the cached-jit select path's p50
        (the devmon/flight bound's methodology)."""
        assert audit.ENABLED is False
        ds = _store(1500)
        ds.query("evt", CQL)  # compile + plan-cache warm
        lat = []
        for _ in range(15):
            t0 = time.perf_counter_ns()
            ds.query("evt", CQL)
            lat.append(time.perf_counter_ns() - t0)
        p50_ns = float(np.percentile(lat, 50))
        q = Query(filter=CQL)
        N = 200_000
        t0 = time.perf_counter_ns()
        for _ in range(N):
            # the REAL added per-query work at 0% sampling: the enabled
            # flag, the shadow check _audit pays, and the hint lookup
            if (audit.ENABLED and not audit.in_shadow()
                    and audit.sampled()) or q.hints.get("audit"):
                pass
            audit.in_shadow()
        per_query = (time.perf_counter_ns() - t0) / N
        assert per_query < 0.02 * p50_ns, (
            f"audit off-path {per_query:.0f} ns >= 2% of p50 "
            f"{p50_ns:.0f} ns")


class TestBundleFormat:
    def test_bundle_is_issue11_event_shaped(self, auditor):
        ds = _store()
        _corrupt(ds)
        ds.query("evt", Query(filter=CQL, hints={"audit": True},
                              auths=None))
        auditor.drain()
        path = auditor.snapshot()["divergences"][-1]["bundle_path"]
        doc = audit.load_bundle(path)
        ev = doc["event"]
        # the ISSUE 11 wide-event keys replay/load_events understand
        for key in ("ts_arrival", "op", "type", "filter", "hints",
                    "tenant", "auths", "plan_signature", "latency_ms"):
            assert key in ev
        assert doc["epoch"] and doc["minimized"]
        assert doc["live"] is not None
