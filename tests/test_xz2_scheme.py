"""XZ2 partition scheme + extended-geometry pruning correctness
(reference: ``geomesa-fs-storage-common/.../partitions/XZ2Scheme``; the
enlarged-cell semantics come from ``XZ2SFC.scala:24`` — SURVEY.md §2.12)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import LineString, Point
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store import persistence
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.partitions import XZ2Scheme, Z2Scheme, scheme_from_spec

T0 = 1_498_867_200_000
LINE_SPEC = "name:String,dtg:Date,*geom:LineString;geomesa.fs.scheme='%s'"


def line_store(scheme: str):
    sft = parse_spec("lines", LINE_SPEC % scheme)
    ds = DataStore(backend="oracle")
    ds.create_schema(sft)
    recs = [
        # centroid far west, but reaches into the eastern query box
        {"name": "long", "dtg": T0,
         "geom": LineString([(-120.0, 10.0), (100.0, 10.0)])},
        # small, fully in the east
        {"name": "east", "dtg": T0,
         "geom": LineString([(95.0, 9.0), (97.0, 11.0)])},
        # small, far west — prunable for an eastern query
        {"name": "west", "dtg": T0,
         "geom": LineString([(-150.0, -40.0), (-149.0, -39.0)])},
    ]
    ds.write("lines", recs, fids=["a", "b", "c"])
    return ds


EAST_BOX = "BBOX(geom, 90, 5, 110, 15)"


class TestPrunedLoadCorrectness:
    @pytest.mark.parametrize("scheme", ["z2-2", "xz2-4"])
    def test_extended_geoms_survive_pruning(self, tmp_path, scheme):
        ds = line_store(scheme)
        persistence.save(ds, str(tmp_path / "cat"))
        ds2 = persistence.load(str(tmp_path / "cat"), backend="oracle",
                               filter=EAST_BOX)
        hits = sorted(ds2.query("lines", EAST_BOX).table.fids.tolist())
        assert hits == ["a", "b"]  # the long line must not be pruned away

    def test_xz2_actually_prunes(self, tmp_path):
        ds = line_store("xz2-4")
        persistence.save(ds, str(tmp_path / "cat"))
        ds2 = persistence.load(str(tmp_path / "cat"), backend="oracle",
                               filter=EAST_BOX)
        # the far-west small feature's fine cell is disjoint from the box
        assert "c" not in set(ds2.query("lines").table.fids.tolist())
        assert ds2.metrics.counter("catalog.partitions_pruned.lines").count > 0


class TestXZ2Elements:
    def test_small_feature_fine_level(self):
        s = XZ2Scheme(g=8)
        bb = np.array([[10.0, 10.0, 10.1, 10.1]])
        lvl, ix, iy = s._elements(bb)
        assert lvl[0] == 8  # tiny bbox keys at the finest level
        # the doubled extent must contain the bbox
        cw, ch = 360.0 / 2 ** lvl[0], 180.0 / 2 ** lvl[0]
        x1, y1 = -180 + ix[0] * cw, -90 + iy[0] * ch
        assert x1 <= 10.0 and 10.1 <= x1 + 2 * cw
        assert y1 <= 10.0 and 10.1 <= y1 + 2 * ch

    def test_huge_feature_level_zero(self):
        s = XZ2Scheme(g=8)
        bb = np.array([[-170.0, -80.0, 170.0, 80.0]])
        lvl, _, _ = s._elements(bb)
        assert lvl[0] == 0

    def test_doubled_extent_invariant_random(self):
        rng = np.random.default_rng(5)
        n = 2000
        x1 = rng.uniform(-180, 179, n)
        y1 = rng.uniform(-90, 89, n)
        w = rng.uniform(0, 40, n) * (rng.random(n) < 0.5)  # half are points
        h = rng.uniform(0, 20, n) * (rng.random(n) < 0.5)
        bb = np.stack(
            [x1, y1, np.minimum(x1 + w, 180.0), np.minimum(y1 + h, 90.0)],
            axis=1,
        )
        s = XZ2Scheme(g=6)
        lvl, ix, iy = s._elements(bb)
        cw = 360.0 / 2.0**lvl
        ch = 180.0 / 2.0**lvl
        cx1 = -180.0 + ix * cw
        cy1 = -90.0 + iy * ch
        assert (cx1 <= bb[:, 0] + 1e-9).all()
        assert (bb[:, 2] <= cx1 + 2 * cw + 1e-9).all()
        assert (cy1 <= bb[:, 1] + 1e-9).all()
        assert (bb[:, 3] <= cy1 + 2 * ch + 1e-9).all()

    def test_prune_never_drops_overlapping(self):
        """Pruned partition ⇒ provably no feature in it can hit the box."""
        from geomesa_tpu.filter.bounds import Extraction

        rng = np.random.default_rng(6)
        s = XZ2Scheme(g=5)
        sft = parse_spec("t", LINE_SPEC % "xz2-5")
        n = 1000
        x1 = rng.uniform(-180, 175, n)
        y1 = rng.uniform(-90, 85, n)
        bb = np.stack(
            [x1, y1,
             np.minimum(x1 + rng.uniform(0, 30, n), 180.0),
             np.minimum(y1 + rng.uniform(0, 15, n), 90.0)],
            axis=1,
        )
        recs = [
            {"name": f"l{i}", "dtg": T0,
             "geom": LineString([(bb[i, 0], bb[i, 1]), (bb[i, 2], bb[i, 3])])}
            for i in range(n)
        ]
        t = FeatureTable.from_records(sft, recs, [str(i) for i in range(n)])
        keys = s.keys(sft, t)
        qbox = (0.0, 0.0, 40.0, 20.0)
        e = Extraction(boxes=[qbox], intervals=None)
        pruned_keys = {k for k in set(keys) if not s.prune(sft, e, k)}
        overlaps = (
            (bb[:, 2] >= qbox[0]) & (bb[:, 0] <= qbox[2])
            & (bb[:, 3] >= qbox[1]) & (bb[:, 1] <= qbox[3])
        )
        for i in np.nonzero(overlaps)[0]:
            assert keys[i] not in pruned_keys


class TestZ2SpillFallback:
    def test_oversized_features_key_to_spill(self):
        s = Z2Scheme(bits=2)
        sft = parse_spec("t", LINE_SPEC % "z2-2")
        recs = [
            {"name": "long", "dtg": T0,
             "geom": LineString([(-120.0, 10.0), (100.0, 10.0)])},
            {"name": "small", "dtg": T0,
             "geom": LineString([(95.0, 9.0), (96.0, 10.0)])},
        ]
        t = FeatureTable.from_records(sft, recs, ["a", "b"])
        keys = s.keys(sft, t)
        assert keys[0] == "all"  # spans cells: unprunable spill partition
        assert keys[1].startswith("z2_2_")

    def test_spec_roundtrip(self):
        s = scheme_from_spec("xz2-7")
        assert isinstance(s, XZ2Scheme) and s.g == 7
        assert isinstance(scheme_from_spec("xz2"), XZ2Scheme)
        c = scheme_from_spec("datetime,xz2-4")
        assert c.name == "composite"
