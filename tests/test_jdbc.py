"""JDBC-role converter: sqlite → FeatureTable → queryable store."""

import sqlite3

import numpy as np
import pytest

from geomesa_tpu.convert.delimited import EvaluationContext
from geomesa_tpu.convert.jdbc import JdbcConverter, ingest_jdbc
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore


@pytest.fixture()
def db(tmp_path):
    path = tmp_path / "events.db"
    conn = sqlite3.connect(str(path))
    conn.execute(
        "CREATE TABLE ev (id TEXT, label TEXT, lon REAL, lat REAL, ts TEXT)"
    )
    rows = [
        (f"e{i}", f"L{i % 3}", -50.0 + i, 10.0 + (i % 5),
         f"2021-03-0{1 + i % 9}T00:00:00Z")
        for i in range(30)
    ]
    conn.executemany("INSERT INTO ev VALUES (?,?,?,?,?)", rows)
    # a bad row: NULL coordinates
    conn.execute("INSERT INTO ev VALUES ('bad', 'L9', NULL, NULL, NULL)")
    conn.commit()
    yield conn, str(path)
    conn.close()


SFT = "label:String,dtg:Date,*geom:Point;geomesa.z3.interval='month'"


class TestJdbcConverter:
    def test_convert_by_column_name(self, db):
        conn, _ = db
        sft = parse_spec("ev", SFT)
        conv = JdbcConverter(
            sft,
            "SELECT id, label, lon, lat, ts FROM ev",
            fields={"label": "label", "dtg": "isodate(ts)",
                    "geom": "point(lon, lat)"},
            id_field="id",
        )
        ctx = EvaluationContext()
        t = conv.convert_connection(conn, ctx=ctx)
        assert len(t) == 30  # NULL-coord row skipped
        assert ctx.failure == 1
        assert list(t.fids[:2]) == ["e0", "e1"]
        assert t.columns["label"].values[0] == "L0"
        g = t.geom_column()
        np.testing.assert_allclose(g.x[:3], [-50, -49, -48])

    def test_positional_refs_and_params(self, db):
        conn, _ = db
        sft = parse_spec("ev", SFT)
        conv = JdbcConverter(
            sft,
            "SELECT id, label, lon, lat, ts FROM ev WHERE label = ?",
            fields={"label": "$2", "dtg": "isodate($5)",
                    "geom": "point($3, $4)"},
            id_field="$1",
        )
        t = conv.convert_connection(conn, params=("L1",))
        assert len(t) == 10
        assert set(t.columns["label"].values) == {"L1"}

    def test_convert_sqlite_path(self, db):
        _, path = db
        sft = parse_spec("ev", SFT)
        conv = JdbcConverter(
            sft, "SELECT id, label, lon, lat, ts FROM ev",
            fields={"label": "label", "dtg": "isodate(ts)",
                    "geom": "point(lon, lat)"},
        )
        t = conv.convert_sqlite(path)
        assert len(t) == 30

    def test_ingest_and_query(self, db):
        conn, _ = db
        ds = DataStore()
        ds.create_schema(parse_spec("ev", SFT))
        n = ingest_jdbc(
            ds, "ev", conn, "SELECT id, label, lon, lat, ts FROM ev",
            fields={"label": "label", "dtg": "isodate(ts)",
                    "geom": "point(lon, lat)"},
            id_field="id",
        )
        assert n == 30
        r = ds.query("ev", "BBOX(geom, -50.5, 9, -45.5, 16) AND label = 'L0'")
        got = {str(f) for f in r.table.fids}
        assert got == {"e0", "e3"}

    def test_empty_result(self, db):
        conn, _ = db
        sft = parse_spec("ev", SFT)
        conv = JdbcConverter(
            sft, "SELECT id, label, lon, lat, ts FROM ev WHERE label = 'zz'",
            fields={"label": "label", "dtg": "isodate(ts)",
                    "geom": "point(lon, lat)"},
        )
        t = conv.convert_connection(conn)
        assert len(t) == 0
