"""Ring-collective KNN merge (ppermute over the data axis): identical
results to the all_gather heap merge, O(k) per-hop payload — the ring
sequence-parallel pattern over the z-curve axis (SURVEY.md §5)."""

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.parallel.mesh import make_mesh, shard_columns
from geomesa_tpu.parallel.query import (
    cached_batched_knn_step,
    cached_ring_knn_step,
)


def _store(n=4096, seed=5):
    rng = np.random.default_rng(seed)
    lon = rng.uniform(-180, 180, n)
    lat = rng.uniform(-90, 90, n)
    order = np.lexsort((lat, lon))
    xi = ((lon[order] + 180.0) / 360.0 * 2**31).astype(np.int32)
    yi = ((lat[order] + 90.0) / 180.0 * 2**31).astype(np.int32)
    return xi, yi


class TestRingKnn:
    def test_matches_allgather_merge(self):
        xi, yi = _store()
        mesh = make_mesh(8, query_parallel=2)
        cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
        qx = jnp.asarray(np.linspace(-150, 150, 4, dtype=np.float32))
        qy = jnp.asarray(np.linspace(-60, 60, 4, dtype=np.float32))
        k = 7
        d_ag, r_ag = cached_batched_knn_step(mesh, k)(
            cols["x"], cols["y"], jnp.int32(len(xi)), qx, qy
        )
        d_ring, r_ring = cached_ring_knn_step(mesh, k)(
            cols["x"], cols["y"], jnp.int32(len(xi)), qx, qy
        )
        assert np.allclose(np.asarray(d_ag), np.asarray(d_ring))
        # same rows modulo equal-distance ties: compare distance multisets
        # exactly and row sets where distances are strictly increasing
        d = np.asarray(d_ag)
        strict = np.diff(d, axis=1) > 0
        ra, rr = np.asarray(r_ag), np.asarray(r_ring)
        for q in range(d.shape[0]):
            if strict[q].all():
                assert set(ra[q]) == set(rr[q])

    def test_knn_many_ring_topology(self):
        from geomesa_tpu.geometry import Point
        from geomesa_tpu.process.knn import knn_many
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(2)
        recs = [
            {"name": f"n{i}",
             "geom": Point(float(rng.uniform(-180, 180)),
                           float(rng.uniform(-90, 90)))}
            for i in range(3000)
        ]
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("pts", "name:String,*geom:Point"))
        ds.write("pts", recs, fids=[f"f{i}" for i in range(3000)])
        pts = [Point(10.0, 5.0), Point(-45.0, 30.0)]
        a = knn_many(ds, "pts", pts, k=6, topology="gather")
        b = knn_many(ds, "pts", pts, k=6, topology="ring")
        for (ta, da), (tb, db) in zip(a, b):
            assert np.allclose(da, db)
            assert sorted(ta.fids.tolist()) == sorted(tb.fids.tolist())
        import pytest

        with pytest.raises(ValueError, match="topology"):
            knn_many(ds, "pts", pts, k=2, topology="mesh")

    def test_ring_correct_vs_bruteforce(self):
        xi, yi = _store(2048, seed=9)
        mesh = make_mesh(8)
        cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
        qx = np.array([10.0, -45.0], dtype=np.float32)
        qy = np.array([5.0, 30.0], dtype=np.float32)
        k = 5
        d_ring, rows = cached_ring_knn_step(mesh, k)(
            cols["x"], cols["y"], jnp.int32(len(xi)), jnp.asarray(qx), jnp.asarray(qy)
        )
        d_ring = np.asarray(d_ring)
        sx, sy = np.float32(360.0 / 2**31), np.float32(180.0 / 2**31)
        xf = xi.astype(np.float32) * sx - np.float32(180.0)
        yf = yi.astype(np.float32) * sy - np.float32(90.0)
        for q in range(2):
            d2 = (xf - qx[q]) ** 2 + (yf - qy[q]) ** 2
            want = np.sort(np.sqrt(d2.astype(np.float64)))[:k]
            assert np.allclose(np.sort(d_ring[q]), want, rtol=1e-5)
