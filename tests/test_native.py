"""Native C++ zranges: exact agreement with the pure-Python BFS + speedup."""

import time

import numpy as np
import pytest

import importlib

from geomesa_tpu import native

# the curve package re-exports the zranges *function*, which shadows the
# submodule on `from ... import`; load the module explicitly
zr_mod = importlib.import_module("geomesa_tpu.curve.zranges")


def python_zranges(lows, highs, precision, max_ranges=2000):
    """Call the pure-Python path directly (bypassing the native fast path)."""
    native_fn = native.zranges_native
    native.zranges_native = lambda *a, **k: None
    try:
        return zr_mod.zranges(lows, highs, precision, max_ranges)
    finally:
        native.zranges_native = native_fn


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
class TestNativeZRanges:
    def test_exact_agreement_2d(self, rng):
        for _ in range(25):
            lo = rng.integers(0, 1 << 20, size=2)
            ext = rng.integers(1, 1 << 16, size=2)
            lows = tuple(int(v) for v in lo)
            highs = tuple(int(a + b) for a, b in zip(lo, ext))
            for budget in (16, 200, 2000):
                a = native.zranges_native(lows, highs, 31, budget)
                b = python_zranges(lows, highs, 31, budget)
                np.testing.assert_array_equal(a, b, err_msg=f"{lows} {highs} {budget}")

    def test_exact_agreement_3d(self, rng):
        for _ in range(15):
            lo = rng.integers(0, 1 << 12, size=3)
            ext = rng.integers(1, 1 << 9, size=3)
            lows = tuple(int(v) for v in lo)
            highs = tuple(int(a + b) for a, b in zip(lo, ext))
            a = native.zranges_native(lows, highs, 21, 500)
            b = python_zranges(lows, highs, 21, 500)
            np.testing.assert_array_equal(a, b)

    def test_full_domain(self):
        m = (1 << 31) - 1
        r = native.zranges_native((0, 0), (m, m), 31)
        assert r.shape == (1, 2) and int(r[0, 1]) == (1 << 62) - 1

    def test_inverted_box(self):
        assert len(native.zranges_native((10, 10), (5, 5), 31)) == 0

    def test_speedup(self):
        lows, highs = (100_000, 200_000), (900_000, 700_000)
        native.zranges_native(lows, highs, 31, 2000)  # warm
        t0 = time.perf_counter()
        for _ in range(5):
            native.zranges_native(lows, highs, 31, 2000)
        t_native = (time.perf_counter() - t0) / 5
        t0 = time.perf_counter()
        t_py = None
        python_zranges(lows, highs, 31, 2000)
        t_py = time.perf_counter() - t0
        assert t_native < t_py  # typically 20-50x
