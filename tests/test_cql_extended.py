"""Extended CQL predicate grammar: CROSSES/TOUCHES/OVERLAPS/EQUALS/BEYOND/
RELATE/ILIKE (reference: full ECQL surface via GeoTools + FastFilterFactory
— SURVEY.md §2.2; DE-9IM backed by the from-scratch relate in geometry/ops)."""

import numpy as np
import pytest

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.bounds import extract
from geomesa_tpu.filter.cql import CQLError, parse as parse_cql
from geomesa_tpu.geometry import LineString, Point, Polygon
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec

LINE_SPEC = "name:String,*geom:LineString"
POINT_SPEC = "name:String,*geom:Point"


def line_table():
    sft = parse_spec("t", LINE_SPEC)
    recs = [
        # crosses the unit-square boundary through its interior
        {"name": "crossing", "geom": LineString([(-1.0, 0.5), (2.0, 0.5)])},
        # touches the square only at its corner
        {"name": "touching", "geom": LineString([(1.0, 1.0), (2.0, 2.0)])},
        # entirely inside
        {"name": "inside", "geom": LineString([(0.2, 0.2), (0.8, 0.8)])},
        # far away
        {"name": "far", "geom": LineString([(5.0, 5.0), (6.0, 6.0)])},
    ]
    return FeatureTable.from_records(sft, recs, ["a", "b", "c", "d"])


SQUARE = "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"


class TestNewSpatialPredicates:
    def test_crosses(self):
        t = line_table()
        m = parse_cql(f"CROSSES(geom, {SQUARE})").mask(t)
        assert m.tolist() == [True, False, False, False]

    def test_touches(self):
        t = line_table()
        m = parse_cql(f"TOUCHES(geom, {SQUARE})").mask(t)
        assert m.tolist() == [False, True, False, False]

    def test_overlaps_lines(self):
        sft = parse_spec("t", LINE_SPEC)
        recs = [
            {"name": "overlap", "geom": LineString([(0.0, 0.0), (2.0, 0.0)])},
            {"name": "disjoint", "geom": LineString([(0.0, 5.0), (1.0, 5.0)])},
        ]
        t = FeatureTable.from_records(sft, recs, ["a", "b"])
        m = parse_cql("OVERLAPS(geom, LINESTRING (1 0, 3 0))").mask(t)
        assert m.tolist() == [True, False]

    def test_equals_points(self):
        sft = parse_spec("t", POINT_SPEC)
        recs = [
            {"name": "same", "geom": Point(3.5, -2.25)},
            {"name": "other", "geom": Point(3.5, -2.26)},
        ]
        t = FeatureTable.from_records(sft, recs, ["a", "b"])
        m = parse_cql("EQUALS(geom, POINT (3.5 -2.25))").mask(t)
        assert m.tolist() == [True, False]

    def test_beyond(self):
        sft = parse_spec("t", POINT_SPEC)
        recs = [
            {"name": "near", "geom": Point(0.1, 0.0)},
            {"name": "far", "geom": Point(10.0, 0.0)},
        ]
        t = FeatureTable.from_records(sft, recs, ["a", "b"])
        m = parse_cql("BEYOND(geom, POINT (0 0), 111.320, kilometers)").mask(t)
        assert m.tolist() == [False, True]
        # complement of DWITHIN over valid rows
        dw = parse_cql("DWITHIN(geom, POINT (0 0), 111.320, kilometers)").mask(t)
        assert np.array_equal(m, ~dw)

    def test_relate_pattern(self):
        t = line_table()
        # interior/interior intersection (first cell T) — inside + crossing
        m = parse_cql(f"RELATE(geom, {SQUARE}, 'T********')").mask(t)
        assert m.tolist() == [True, False, True, False]

    def test_relate_bad_pattern(self):
        with pytest.raises(CQLError, match="9 chars"):
            parse_cql("RELATE(geom, POINT (0 0), 'T*')")

    def test_ilike(self):
        sft = parse_spec("t", POINT_SPEC)
        recs = [
            {"name": "Alpha", "geom": Point(0, 0)},
            {"name": "beta", "geom": Point(0, 0)},
        ]
        t = FeatureTable.from_records(sft, recs, ["a", "b"])
        assert parse_cql("name ILIKE 'al%'").mask(t).tolist() == [True, False]
        assert parse_cql("name LIKE 'al%'").mask(t).tolist() == [False, False]


class TestBoundsExtraction:
    def test_constraining_ops_extract_bbox(self):
        for cql in (f"CROSSES(geom, {SQUARE})", f"TOUCHES(geom, {SQUARE})",
                    f"OVERLAPS(geom, {SQUARE})", f"EQUALS(geom, {SQUARE})"):
            e = extract(parse_cql(cql), "geom", None, ())
            assert e.boxes is not None
            assert e.boxes[0] == pytest.approx((0.0, 0.0, 1.0, 1.0))

    def test_unconstrained_ops(self):
        for cql in ("BEYOND(geom, POINT (0 0), 1, kilometers)",
                    f"RELATE(geom, {SQUARE}, 'FF*FF****')"):
            e = extract(parse_cql(cql), "geom", None, ())
            assert e.boxes is None

    def test_beyond_correct_under_planning(self):
        # BEYOND must not be planned as a bbox scan: rows OUTSIDE the literal
        # must still be found through the index-planned path
        from geomesa_tpu.planning.planner import Query
        from geomesa_tpu.store.datastore import DataStore

        for backend in ("oracle", "tpu"):
            ds = DataStore(backend=backend)
            ds.create_schema(parse_spec("pts", POINT_SPEC))
            recs = [{"name": f"n{i}", "geom": Point(float(i * 20 - 80), 0.0)}
                    for i in range(9)]
            ds.write("pts", recs, fids=[f"f{i}" for i in range(9)])
            r = ds.query("pts", "BEYOND(geom, POINT (0 0), 3000, kilometers)")
            near = {f"f{i}" for i in range(9)
                    if abs(i * 20 - 80) <= 3000 / 111.32}
            assert set(r.table.fids.tolist()) == {f"f{i}" for i in range(9)} - near


class TestRoundTrip:
    @pytest.mark.parametrize("cql", [
        f"CROSSES(geom, {SQUARE})",
        "BEYOND(geom, POINT (0 0), 5.0, kilometers)",
        "DWITHIN(geom, POINT (1 2), 10.0, kilometers)",
        f"RELATE(geom, {SQUARE}, 'T*T******')",
        "name ILIKE 'a%'",
    ])
    def test_to_cql_round_trips(self, cql):
        f1 = parse_cql(cql)
        f2 = parse_cql(ast.to_cql(f1))
        assert type(f1) is type(f2)
        if isinstance(f1, ast.SpatialOp):
            assert f1.op == f2.op and f1.pattern == f2.pattern
            assert f1.distance == pytest.approx(f2.distance)
        if isinstance(f1, ast.Like):
            assert (f1.pattern, f1.nocase) == (f2.pattern, f2.nocase)
