"""Arrow IPC, catalog persistence, converters, and CLI end-to-end tests
(reference suites: arrow io tests, fs-storage metadata tests, convert tests,
tools Ingest/Export command tests — SURVEY.md §4)."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from geomesa_tpu.convert.delimited import DelimitedConverter, EvaluationContext
from geomesa_tpu.convert.gdelt import gdelt_converter, gdelt_sft
from geomesa_tpu.geometry import LineString, Point
from geomesa_tpu.io.arrow import from_arrow, from_ipc_bytes, to_arrow, to_ipc_bytes
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000
SPEC = "name:String,age:Integer,score:Double,flag:Boolean,dtg:Date,*geom:Point"


def table(n=50):
    rng = np.random.default_rng(2)
    sft = parse_spec("t", SPEC)
    recs = [
        {
            "name": f"n{i}" if i % 7 else None,
            "age": int(i),
            "score": float(i) * 1.5,
            "flag": bool(i % 2),
            "dtg": T0 + i * 1000,
            "geom": Point(float(rng.uniform(-180, 180)), float(rng.uniform(-90, 90))),
        }
        for i in range(n)
    ]
    return FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(n)])


class TestArrow:
    def test_roundtrip(self):
        t = table()
        at = to_arrow(t)
        assert at.num_rows == 50
        t2 = from_arrow(t.sft, at)
        for i in (0, 7, 49):
            assert t.record(i) == t2.record(i)
        assert t2.fids.tolist() == t.fids.tolist()

    def test_ipc_roundtrip(self):
        t = table()
        data = to_ipc_bytes(t)
        t2 = from_ipc_bytes(t.sft, data)
        assert len(t2) == len(t)
        assert t2.record(3) == t.record(3)

    def test_merge_ipc_streams_sorted(self):
        from geomesa_tpu.io.arrow import merge_ipc_streams

        t = table()
        # three out-of-order shard chunks
        chunks = [
            to_ipc_bytes(t.take(np.arange(30, 50))),
            to_ipc_bytes(t.take(np.arange(0, 15))),
            to_ipc_bytes(t.take(np.arange(15, 30))),
        ]
        data = merge_ipc_streams(t.sft, chunks, sort_by="dtg")
        merged = from_ipc_bytes(t.sft, data)
        assert len(merged) == 50
        assert np.all(np.diff(merged.dtg_millis()) >= 0)
        # dictionaries re-encode over the merged domain: values survive
        assert sorted(str(f) for f in merged.fids) == sorted(str(f) for f in t.fids)
        rec = merged.record(0)
        assert rec["dtg"] == T0

    def test_merge_ipc_empty(self):
        from geomesa_tpu.io.arrow import merge_ipc_streams

        t = table()
        data = merge_ipc_streams(t.sft, [])
        assert len(from_ipc_bytes(t.sft, data)) == 0

    def test_point_fixed_size_list(self):
        t = table()
        at = to_arrow(t)
        import pyarrow as pa

        assert pa.types.is_fixed_size_list(at.schema.field("geom").type)

    def test_linestring_wkt(self):
        sft = parse_spec("l", "dtg:Date,*geom:LineString")
        t = FeatureTable.from_records(
            sft,
            [{"dtg": T0, "geom": LineString(np.array([[0, 0], [1, 1], [2, 0]], float))}],
        )
        t2 = from_arrow(sft, to_arrow(t))
        assert t2.record(0)["geom"] == t.record(0)["geom"]


class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        ds = DataStore(backend="tpu")
        sft = parse_spec("pts", SPEC + ";geomesa.z3.interval='day'")
        ds.create_schema(sft)
        t = table()
        ds.write("pts", t)
        manifest = ds.save(str(tmp_path / "cat"))
        assert manifest["types"]["pts"]["count"] == 50
        assert len(manifest["types"]["pts"]["files"]) >= 1  # time-partitioned

        ds2 = DataStore.load(str(tmp_path / "cat"))
        assert ds2.list_schemas() == ["pts"]
        assert ds2.get_schema("pts").z3_interval.value == "day"
        r1 = ds.query("pts", "BBOX(geom, -90, -45, 90, 45)")
        r2 = ds2.query("pts", "BBOX(geom, -90, -45, 90, 45)")
        assert set(r1.table.fids.tolist()) == set(r2.table.fids.tolist())

    def test_resave_uses_fresh_generation(self, tmp_path):
        """A second save must never rename over shards the live manifest
        references (hybrid-checkpoint crash safety): filenames are
        generation-unique and stale generations are GC'd after the flip."""
        ds = DataStore(backend="tpu")
        sft = parse_spec("pts", SPEC + ";geomesa.z3.interval='day'")
        ds.create_schema(sft)
        ds.write("pts", table())
        m1 = ds.save(str(tmp_path / "cat"))
        files1 = {f["file"] for f in m1["types"]["pts"]["files"]}
        t2 = table()
        t2.fids[:] = [f"x.{i}" for i in range(50)]
        ds.write("pts", t2)
        m2 = ds.save(str(tmp_path / "cat"))
        files2 = {f["file"] for f in m2["types"]["pts"]["files"]}
        assert m2["generation"] == m1["generation"] + 1
        assert files1.isdisjoint(files2)
        on_disk = {p.name for p in (tmp_path / "cat" / "pts").glob("*.parquet")}
        assert on_disk == files2  # old generation GC'd
        ds2 = DataStore.load(str(tmp_path / "cat"))
        assert ds2.query("pts", "INCLUDE").count == 100

    def test_empty_store(self, tmp_path):
        ds = DataStore()
        ds.create_schema("e", "dtg:Date,*geom:Point")
        ds.save(str(tmp_path / "cat"))
        ds2 = DataStore.load(str(tmp_path / "cat"))
        assert ds2.list_schemas() == ["e"]
        assert ds2.query("e", "INCLUDE").count == 0


GDELT_ROW = (
    "123456\t20170714\t201707\t2017\t2017.5342\tUSA\tUNITED STATES\tUSA\t\t\t\t\t\t\t\t"
    "RUS\tRUSSIA\tRUS\t\t\t\t\t\t\t\t1\t042\t042\t04\t1\t1.5\t10\t2\t5\t-2.3\t"
    "3\tWashington DC\tUS\tUSDC\t38.9072\t-77.0369\t531871\t"
    "3\tMoscow\tRU\tRUMOW\t55.7558\t37.6173\t524901\t"
    "3\tParis\tFR\tFR00\t48.85\t2.35\t2988507\t"
    "20170714\thttp://example.com"
)


class TestConverters:
    def test_gdelt_converter(self, tmp_path):
        f = tmp_path / "gdelt.tsv"
        rows = []
        for i in range(10):
            parts = GDELT_ROW.split("\t")
            parts[0] = str(100 + i)
            rows.append("\t".join(parts))
        f.write_text("\n".join(rows))
        conv = gdelt_converter()
        ctx = EvaluationContext()
        t = conv.convert_path(str(f), ctx)
        assert len(t) == 10 and ctx.success == 10
        rec = t.record(0)
        assert rec["actor1Name"] == "UNITED STATES"
        assert rec["goldsteinScale"] == 1.5
        assert rec["geom"] == Point(-77.0369, 38.9072)
        # dtg parsed from yyyyMMdd
        assert rec["dtg"] == int(np.datetime64("2017-07-14", "ms").astype(np.int64))
        assert t.fids[0] == "100"

    def test_bad_records_skipped(self, tmp_path):
        sft = parse_spec("x", "a:Integer,dtg:Date,*geom:Point")
        conv = DelimitedConverter(
            sft,
            fields={"a": "int($1)", "dtg": "millisToDate($2)", "geom": "point($3, $4)"},
        )
        f = tmp_path / "x.csv"
        f.write_text("1,1500000000000,10,20\nbad,1500000000000,10,20\n2,1500000000000,200,20\n")
        ctx = EvaluationContext()
        t = conv.convert_path(str(f), ctx)
        assert len(t) == 1  # row 2: bad int; row 3: lon 200 out of bounds
        assert ctx.failure == 2

    def test_raise_mode(self, tmp_path):
        sft = parse_spec("x", "a:Integer,dtg:Date,*geom:Point")
        conv = DelimitedConverter(
            sft,
            fields={"a": "int($1)", "dtg": "millisToDate($2)", "geom": "point($3, $4)"},
            error_mode="raise",
        )
        f = tmp_path / "x.csv"
        f.write_text("bad,1500000000000,10,20\n")
        with pytest.raises(ValueError, match="bad record"):
            conv.convert_path(str(f))

    def test_concat_and_literals(self, tmp_path):
        sft = parse_spec("x", "k:String,dtg:Date,*geom:Point")
        conv = DelimitedConverter(
            sft,
            fields={
                "k": "concat($1, '-', $2)",
                "dtg": "millisToDate($3)",
                "geom": "point($4, $5)",
            },
        )
        f = tmp_path / "x.csv"
        f.write_text("a,b,1500000000000,1,2\n")
        t = conv.convert_path(str(f))
        assert t.record(0)["k"] == "a-b"


def run_cli(*argv):
    from geomesa_tpu.cli.__main__ import main

    main(list(argv))


class TestCLI:
    def test_end_to_end(self, tmp_path, capsys):
        cat = str(tmp_path / "cat")
        # build a gdelt file
        f = tmp_path / "g.tsv"
        rows = []
        for i in range(20):
            parts = GDELT_ROW.split("\t")
            parts[0] = str(i)
            parts[39] = str(30 + i)  # lat spread
            rows.append("\t".join(parts))
        f.write_text("\n".join(rows))

        run_cli("ingest", "-c", cat, "-n", "gdelt", "--converter", "gdelt", str(f))
        out = capsys.readouterr().out
        assert "ingested 20" in out

        run_cli("get-type-names", "-c", cat)
        assert "gdelt" in capsys.readouterr().out

        run_cli("describe-schema", "-c", cat, "-n", "gdelt")
        out = capsys.readouterr().out
        assert "*geom" in out and "features: 20" in out

        run_cli("explain", "-c", cat, "-n", "gdelt", "-q", "BBOX(geom, -80, 30, -70, 45)")
        assert "Index:" in capsys.readouterr().out

        run_cli(
            "export", "-c", cat, "-n", "gdelt",
            "-q", "BBOX(geom, -80, 30, -70, 45)", "--format", "json",
            "-o", str(tmp_path / "out.json"),
        )
        lines = (tmp_path / "out.json").read_text().strip().splitlines()
        assert len(lines) >= 1
        assert json.loads(lines[0])["actor1Name"] == "UNITED STATES"

        run_cli("stats-count", "-c", cat, "-n", "gdelt")
        assert capsys.readouterr().out.strip() == "20"

        run_cli("sql", "-c", cat, "-q", "SELECT COUNT(*) AS n FROM gdelt")
        out = capsys.readouterr().out.strip().splitlines()
        assert out[0] == "n" and out[1] == "20"

        run_cli("sql", "-c", cat, "--format", "json",
                "-q", "SELECT actor1Name, COUNT(*) AS n FROM gdelt "
                      "GROUP BY actor1Name LIMIT 2")
        jlines = [json.loads(x) for x in
                  capsys.readouterr().out.strip().splitlines()]
        assert jlines and all("actor1Name" in r and "n" in r for r in jlines)

        run_cli("stats-top-k", "-c", cat, "-n", "gdelt", "-a", "actor1Name", "-k", "3")
        assert "UNITED STATES" in capsys.readouterr().out

        run_cli("stats-analyze", "-c", cat, "-n", "gdelt")
        assert "count: 20" in capsys.readouterr().out

        run_cli("version")
        assert "geomesa-tpu" in capsys.readouterr().out

    def test_export_arrow_and_bin(self, tmp_path, capsys):
        cat = str(tmp_path / "cat")
        f = tmp_path / "g.tsv"
        f.write_text(GDELT_ROW)
        run_cli("ingest", "-c", cat, "-n", "g", "--converter", "gdelt", str(f))
        capsys.readouterr()

        run_cli("export", "-c", cat, "-n", "g", "--format", "arrow",
                "-o", str(tmp_path / "o.arrow"))
        capsys.readouterr()
        data = (tmp_path / "o.arrow").read_bytes()
        t = from_ipc_bytes(gdelt_sft("g"), data)
        assert len(t) == 1

        run_cli("export", "-c", cat, "-n", "g", "--format", "bin",
                "--bin-track", "actor1Name", "-o", str(tmp_path / "o.bin"))
        capsys.readouterr()
        assert len((tmp_path / "o.bin").read_bytes()) == 16


class TestReviewRegressions:
    def test_arrow_null_point_roundtrip(self):
        sft = parse_spec("np2", "p2:Point,dtg:Date,*geom:Point")
        t = FeatureTable.from_records(
            sft,
            [
                {"p2": Point(1, 2), "dtg": T0, "geom": Point(5, 5)},
                {"p2": None, "dtg": T0, "geom": Point(6, 6)},
            ],
        )
        t2 = from_arrow(sft, to_arrow(t))
        assert t2.record(0)["p2"] == Point(1, 2)
        assert t2.record(1)["p2"] is None  # not Point(nan, nan)

    def test_converter_empty_optional_numeric(self, tmp_path):
        sft = parse_spec("x", "a:Integer,s:Double,dtg:Date,*geom:Point")
        conv = DelimitedConverter(
            sft,
            fields={"a": "int($1)", "s": "double($2)", "dtg": "millisToDate($3)",
                    "geom": "point($4, $5)"},
        )
        f = tmp_path / "x.csv"
        # row 1: empty optional double -> kept with null; row 2: garbage -> dropped
        f.write_text("1,,1500000000000,10,20\n2,zzz,1500000000000,10,20\n")
        ctx = EvaluationContext()
        t = conv.convert_path(str(f), ctx)
        assert len(t) == 1 and ctx.failure == 1
        assert t.record(0)["s"] is None
        assert t.record(0)["a"] == 1

    def test_persistence_stale_cleanup(self, tmp_path):
        cat = str(tmp_path / "cat")
        ds = DataStore()
        ds.create_schema("a", "dtg:Date,*geom:Point")
        ds.create_schema("b", "dtg:Date,*geom:Point")
        ds.write("a", [{"dtg": T0, "geom": Point(1, 1)}])
        ds.write("b", [{"dtg": T0, "geom": Point(1, 1)}])
        ds.save(cat)
        ds.delete_schema("b")
        ds.save(cat)
        assert not (Path(cat) / "b").exists()
        ds2 = DataStore.load(cat)
        assert ds2.list_schemas() == ["a"]

    def test_tube_on_linestring_schema(self):
        from geomesa_tpu.geometry import LineString as LS
        from geomesa_tpu.process.processes import tube_select

        ds = DataStore()
        ds.create_schema("ls", "dtg:Date,*geom:LineString")
        ds.write("ls", [
            {"dtg": T0 + 86_400_000, "geom": LS(np.array([[0.0, 0.0], [0.5, 0.5]]))},
            {"dtg": T0 + 86_400_000, "geom": LS(np.array([[50.0, 50.0], [51.0, 51.0]]))},
        ])
        track = [(-1.0, -1.0, T0), (1.0, 1.0, T0 + 2 * 86_400_000)]
        t = tube_select(ds, "ls", track, buffer_deg=1.0, time_buffer_ms=86_400_000)
        assert len(t) == 1  # centroid of the first line is near the track

    def test_converter_boolean(self, tmp_path):
        sft = parse_spec("b", "flag:Boolean,dtg:Date,*geom:Point")
        conv = DelimitedConverter(
            sft, fields={"flag": "$1", "dtg": "millisToDate($2)", "geom": "point($3, $4)"}
        )
        f = tmp_path / "b.csv"
        f.write_text("true,1500000000000,1,1\nfalse,1500000000000,2,2\n,1500000000000,3,3\nxx,1500000000000,4,4\n")
        ctx = EvaluationContext()
        t = conv.convert_path(str(f), ctx)
        assert len(t) == 3 and ctx.failure == 1  # 'xx' dropped, empty -> null
        assert t.record(0)["flag"] is True
        assert t.record(1)["flag"] is False
        assert t.record(2)["flag"] is None
        to_arrow(t)  # must not raise

    def test_atomic_save_leaves_loadable_catalog(self, tmp_path):
        cat = str(tmp_path / "cat")
        ds = DataStore()
        ds.create_schema("a", "dtg:Date,*geom:Point")
        ds.write("a", [{"dtg": T0, "geom": Point(1, 1)}])
        ds.save(cat)
        # no temp droppings after a clean save
        assert not list(Path(cat).rglob("*.tmp"))
        assert DataStore.load(cat).query("a", "INCLUDE").count == 1

    def test_stats_estimate_sees_delta(self):
        ds = DataStore()
        ds.create_schema("sd", "dtg:Date,*geom:Point")
        bulk = [{"dtg": T0, "geom": Point(i * 0.01, 0.0)} for i in range(2000)]
        ds.write("sd", bulk)  # compacts
        ds.write("sd", [{"dtg": T0, "geom": Point(150.0, 80.0)}])  # hot
        est = ds.stats_count("sd", "BBOX(geom, 149, 79, 151, 81)")
        assert est >= 1  # the delta-only row is visible to estimates


class TestPartitionSchemes:
    """PartitionScheme SPI + query-time pruning (PartitionScheme.scala role)."""

    def _spread_table(self, n=60):
        # points spread over two distinct regions so z2 cells separate them
        rng = np.random.default_rng(8)
        sft = parse_spec("pz", "name:String,dtg:Date,*geom:Point;geomesa.fs.scheme='z2-3'")
        recs = []
        for i in range(n):
            if i % 2:
                x, y = rng.uniform(100, 140), rng.uniform(20, 50)   # east
            else:
                x, y = rng.uniform(-140, -100), rng.uniform(-50, -20)  # west
            recs.append({"name": f"g{i % 3}", "dtg": T0 + i * 1000,
                         "geom": Point(float(x), float(y))})
        return sft, FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(n)])

    def test_z2_scheme_prunes_partitions(self, tmp_path):
        sft, t = self._spread_table()
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        ds.write("pz", t)
        m = ds.save(str(tmp_path / "cat"))
        assert len(m["types"]["pz"]["files"]) >= 2  # east/west split
        assert m["types"]["pz"]["scheme"] == "z2-3"

        cql = "BBOX(geom, 100, 20, 140, 50)"  # east only
        pruned_ds = DataStore.load(str(tmp_path / "cat"), filter=cql)
        full_ds = DataStore.load(str(tmp_path / "cat"))
        assert pruned_ds.metrics.counter("catalog.partitions_pruned.pz").count > 0
        # the pruned store answers the pruning query identically
        a = set(full_ds.query("pz", cql).table.fids.tolist())
        b = set(pruned_ds.query("pz", cql).table.fids.tolist())
        assert a == b and len(a) == 30

    def test_attribute_scheme_prunes(self, tmp_path):
        sft = parse_spec("pa", "name:String,dtg:Date,*geom:Point;geomesa.fs.scheme='attribute:name'")
        recs = [{"name": f"v{i % 4}", "dtg": T0 + i, "geom": Point(i * 0.1, 0.0)}
                for i in range(40)]
        t = FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(40)])
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        ds.write("pa", t)
        m = ds.save(str(tmp_path / "cat"))
        assert len(m["types"]["pa"]["files"]) == 4

        pruned = DataStore.load(str(tmp_path / "cat"), filter="name = 'v2'")
        assert pruned.metrics.counter("catalog.partitions_pruned.pa").count == 3
        assert pruned.query("pa", "name = 'v2'").count == 10

    def test_composite_scheme_keys(self, tmp_path):
        sft = parse_spec(
            "pc", "name:String,dtg:Date,*geom:Point;geomesa.fs.scheme='datetime,z2-2'"
        )
        recs = [{"name": "a", "dtg": T0 + i * 86_400_000 * 9,
                 "geom": Point(-100.0 if i % 2 else 100.0, 0.0)} for i in range(8)]
        t = FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(8)])
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        ds.write("pc", t)
        m = ds.save(str(tmp_path / "cat"))
        keys = {f["partition"] for f in m["types"]["pc"]["files"]}
        assert all("/" in k for k in keys)  # composite key segments
        ds2 = DataStore.load(str(tmp_path / "cat"))
        assert ds2.query("pc", "INCLUDE").count == 8

    def test_orc_round_trip(self, tmp_path):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t", SPEC))
        t = table()
        ds.write("t", t)
        m = ds.save(str(tmp_path / "cat_orc"), file_format="orc")
        assert all(f["file"].endswith(".orc") for f in m["types"]["t"]["files"])
        ds2 = DataStore.load(str(tmp_path / "cat_orc"))
        a = ds.query("t", "age >= 10 AND age < 30")
        b = ds2.query("t", "age >= 10 AND age < 30")
        assert set(a.table.fids.tolist()) == set(b.table.fids.tolist())
        # null validity survives the ORC round trip
        r = ds2.query("t", "INCLUDE").table
        names = {f: rec for f, rec in zip(r.fids, (r.record(i) for i in range(len(r))))}
        assert names["f7"]["name"] is None


class TestDeleteFeaturesCommand:
    def test_delete_by_fids_and_cql(self, tmp_path, capsys):
        cat = str(tmp_path / "cat")
        run_cli("create-schema", "-c", cat, "-n", "t",
                "--spec", "name:String,dtg:Date,*geom:Point")
        f = tmp_path / "d.csv"
        f.write_text("\n".join(
            f"n{i},2017-07-01T00:00:00Z,{i},0" for i in range(10)) + "\n")
        run_cli("ingest", "-c", cat, "-n", "t", "--backend", "oracle",
                "--field", "name=$1", "--field", "dtg=isodate($2)",
                "--field", "geom=point($3, $4)", "--id-field", "$1", str(f))
        capsys.readouterr()
        run_cli("delete-features", "-c", cat, "-n", "t",
                "--backend", "oracle", "--fids", "n0,n1")
        assert "deleted 2" in capsys.readouterr().out
        run_cli("delete-features", "-c", cat, "-n", "t",
                "--backend", "oracle", "-q", "BBOX(geom, 4.5, -1, 7.5, 1)")
        assert "deleted 3" in capsys.readouterr().out
        run_cli("stats-count", "-c", cat, "-n", "t", "--backend", "oracle")
        assert capsys.readouterr().out.strip() == "5"


class TestExportSrs:
    def test_export_srs_reprojects(self, tmp_path, capsys):
        cat = str(tmp_path / "cat")
        f = tmp_path / "g.tsv"
        f.write_text(GDELT_ROW)
        run_cli("ingest", "-c", cat, "-n", "g", "--converter", "gdelt", str(f))
        capsys.readouterr()
        run_cli("export", "-c", cat, "-n", "g", "--format", "json",
                "--srs", "EPSG:3857", "-o", str(tmp_path / "o.json"))
        capsys.readouterr()
        import json as _json
        import re as _re

        rec = _json.loads(
            (tmp_path / "o.json").read_text().strip().splitlines()[0]
        )
        geom_field = next(k for k, v in rec.items() if "POINT" in str(v).upper()
                          or "Point" in str(v))
        nums = [float(x) for x in _re.findall(r"-?\d+\.?\d*", rec[geom_field])]
        # meters, not degrees: web-mercator magnitudes
        assert any(abs(v) > 10_000 for v in nums), rec[geom_field]

    def test_export_bad_srs_fails_fast(self, tmp_path, capsys):
        import pytest as _pytest

        cat = str(tmp_path / "cat")
        f = tmp_path / "g.tsv"
        f.write_text(GDELT_ROW)
        run_cli("ingest", "-c", cat, "-n", "g", "--converter", "gdelt", str(f))
        capsys.readouterr()
        with _pytest.raises(SystemExit, match="unsupported CRS"):
            run_cli("export", "-c", cat, "-n", "g", "--format", "json",
                    "--srs", "EPSG:9999", "-o", str(tmp_path / "o.json"))
