"""Visibility security, query audit, and metrics.

Mirrors the reference's ``VisibilityEvaluatorTest`` semantics (`&` binds
tighter than `|`), auth-filtered reads, and audit/metrics plumbing
(SURVEY.md §2.19, §5).
"""

import json

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.security.visibility import (
    VisibilityParseError,
    evaluate_column,
    parse_visibility,
)
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.utils.audit import InMemoryAuditWriter, JsonlAuditWriter
from geomesa_tpu.utils.metrics import MetricsRegistry


class TestVisibilityParser:
    def test_single_auth(self):
        assert parse_visibility("admin").evaluate(frozenset({"admin"}))
        assert not parse_visibility("admin").evaluate(frozenset({"user"}))

    def test_empty_visible_to_all(self):
        assert parse_visibility("").evaluate(frozenset())
        assert parse_visibility(None).evaluate(frozenset())

    def test_and_or(self):
        e = parse_visibility("alpha&beta")
        assert e.evaluate(frozenset({"alpha", "beta"}))
        assert not e.evaluate(frozenset({"alpha"}))
        e = parse_visibility("alpha|beta")
        assert e.evaluate(frozenset({"beta"}))
        assert not e.evaluate(frozenset({"gamma"}))

    def test_precedence_and_binds_tighter(self):
        # user|admin&test == user|(admin&test)  (VisibilityEvaluator.scala:43)
        e = parse_visibility("user|admin&test")
        assert e.evaluate(frozenset({"user"}))
        assert e.evaluate(frozenset({"admin", "test"}))
        assert not e.evaluate(frozenset({"admin"}))
        # user&admin|test == (user&admin)|test
        e = parse_visibility("user&admin|test")
        assert e.evaluate(frozenset({"test"}))
        assert not e.evaluate(frozenset({"user"}))

    def test_parens(self):
        e = parse_visibility("alpha&(beta|gamma)")
        assert e.evaluate(frozenset({"alpha", "gamma"}))
        assert not e.evaluate(frozenset({"beta", "gamma"}))

    def test_quoted_auth(self):
        e = parse_visibility('"a b"&c')
        assert e.evaluate(frozenset({"a b", "c"}))

    def test_round_trip(self):
        for s in ["admin", "a&b", "a|b&c", "a&(b|c)", "a&b&c|d"]:
            e = parse_visibility(s)
            assert parse_visibility(e.expression()).evaluate(
                frozenset({"a", "b", "c", "d", "admin"})
            ) == e.evaluate(frozenset({"a", "b", "c", "d", "admin"}))

    @pytest.mark.parametrize("bad", ["a&", "|a", "a b", "(a", 'a&""', "a&&b"])
    def test_parse_errors(self, bad):
        with pytest.raises(VisibilityParseError):
            parse_visibility(bad)

    def test_evaluate_column(self):
        vis = np.array(["admin", "", "user|admin", "secret&admin", None], dtype=object)
        mask = evaluate_column(vis, ["admin"])
        assert list(mask) == [True, True, True, False, True]


def _vis_store(backend="oracle"):
    sft = parse_spec(
        "tracks",
        "dtg:Date,*geom:Point:srid=4326,vis:String;geomesa.vis.field='vis'",
    )
    ds = DataStore(backend=backend, audit_writer=InMemoryAuditWriter())
    ds.create_schema(sft)
    recs = [
        {"dtg": 1_500_000_000_000 + i, "geom": Point(i, i), "vis": v}
        for i, v in enumerate(["admin", "", "user|admin", "secret", "admin&ops"])
    ]
    ds.write("tracks", FeatureTable.from_records(sft, recs, [f"f{i}" for i in range(5)]))
    return ds


class TestVisibilityQueries:
    def test_unrestricted_sees_all(self):
        ds = _vis_store()
        assert ds.query("tracks").count == 5

    def test_auth_filtering(self):
        ds = _vis_store()
        res = ds.query("tracks", Query(auths=["admin"]))
        assert res.count == 3  # admin, unlabeled, user|admin
        res = ds.query("tracks", Query(auths=[]))
        assert res.count == 1  # only unlabeled
        res = ds.query("tracks", Query(auths=["admin", "ops"]))
        assert res.count == 4

    def test_malformed_visibility_rejected_at_write(self):
        sft = parse_spec(
            "t2", "dtg:Date,*geom:Point:srid=4326,vis:String;geomesa.vis.field='vis'"
        )
        ds = DataStore(backend="oracle")
        ds.create_schema(sft)
        with pytest.raises(VisibilityParseError):
            ds.write(
                "t2",
                [{"dtg": 1, "geom": Point(0, 0), "vis": "a&&b"}],
            )
        # the failed write left nothing behind; valid writes still work
        assert ds.query("t2").count == 0
        ds.write("t2", [{"dtg": 1, "geom": Point(0, 0), "vis": "a&b"}])
        assert ds.query("t2", Query(auths=["a", "b"])).count == 1

    def test_visibility_applies_before_aggregation(self):
        ds = _vis_store()
        res = ds.query(
            "tracks",
            Query(auths=[], hints={"stats": "Count()"}),
        )
        assert res.stats["Count()"].count == 1


class TestAudit:
    def test_events_recorded(self):
        ds = _vis_store()
        ds.query("tracks", "BBOX(geom, -1, -1, 2.5, 2.5)")
        events = ds.audit_writer.query_events("tracks")
        assert len(events) == 1
        e = events[0]
        assert e.hits == 3 and "BBOX" in e.filter and e.user == "unknown"
        assert e.scan_time_ms >= 0.0

    def test_hints_values_recorded(self):
        ds = _vis_store()
        ds.query("tracks", Query(hints={"stats": "Count()", "sample": 0.5}))
        e = ds.audit_writer.events[-1]
        assert "stats='Count()'" in e.hints and "sample=0.5" in e.hints

    def test_bad_vis_field_rejected_at_create(self):
        with pytest.raises(ValueError, match="viz"):
            DataStore(backend="oracle").create_schema(
                parse_spec(
                    "bad", "dtg:Date,*geom:Point,vis:String;geomesa.vis.field='viz'"
                )
            )

    def test_jsonl_writer(self, tmp_path):
        path = str(tmp_path / "audit.jsonl")
        ds = _vis_store()
        ds.audit_writer = JsonlAuditWriter(path)
        ds.query("tracks")
        ds.audit_writer.close()
        lines = open(path).read().strip().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["type_name"] == "tracks" and rec["hits"] == 5


class TestMetrics:
    def test_counters_and_histograms(self):
        ds = _vis_store()
        ds.query("tracks")
        ds.query("tracks")
        snap = ds.metrics.snapshot()
        assert snap["store.queries"]["count"] == 2
        assert snap["store.writes"]["count"] == 5
        assert snap["store.query.hits"]["count"] == 2
        assert snap["store.query.hits"]["mean"] == 5.0

    def test_timer(self):
        reg = MetricsRegistry()
        with reg.timer("t").time():
            pass
        assert reg.snapshot()["t"]["count"] == 1

    def test_reporters(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc(3)
        txt = reg.report_graphite("gm")
        assert "gm.c.count 3 " in txt
        path = str(tmp_path / "metrics.csv")
        reg.report_delimited(path)
        assert "counter,c,count,3" in open(path).read()


class TestAttributeVisibility:
    """Attribute-level visibility (KryoVisibilityRowEncoder role): comma
    lists redact per attribute; record-level strings still drop whole rows."""

    SPEC = ("name:String,age:Integer,vis:String,dtg:Date,*geom:Point"
            ";geomesa.vis.field='vis'")

    def _store(self):
        from geomesa_tpu.geometry.types import Point

        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("s", self.SPEC))
        # attribute order: name, age, vis, dtg, geom
        recs = [
            # name needs admin; everything else public
            {"name": "classified", "age": 1, "vis": "admin,,,,",
             "dtg": 1_500_000_000_000, "geom": Point(1, 1)},
            # fully public
            {"name": "open", "age": 2, "vis": "",
             "dtg": 1_500_000_000_000, "geom": Point(2, 2)},
            # whole record needs secret (record-level, no commas)
            {"name": "hidden", "age": 3, "vis": "secret",
             "dtg": 1_500_000_000_000, "geom": Point(3, 3)},
            # every attribute needs secret (attribute-level all-redacted)
            {"name": "gone", "age": 4, "vis": "secret,secret,secret,secret,secret",
             "dtg": 1_500_000_000_000, "geom": Point(4, 4)},
        ]
        ds.write("s", recs, fids=["a", "b", "c", "d"])
        return ds

    def test_redaction_and_row_drop(self):
        from geomesa_tpu.planning.planner import Query

        ds = self._store()
        r = ds.query("s", Query(filter="INCLUDE", auths=()))
        # c (record-level secret) and d (no visible attribute) are dropped
        assert sorted(r.table.fids.tolist()) == ["a", "b"]
        recs = {f: r.table.record(i) for i, f in enumerate(r.table.fids)}
        assert recs["a"]["name"] is None      # redacted attribute
        assert recs["a"]["age"] == 1          # visible attribute survives
        assert recs["b"]["name"] == "open"

    def test_admin_sees_everything(self):
        from geomesa_tpu.planning.planner import Query

        ds = self._store()
        r = ds.query("s", Query(filter="INCLUDE", auths=("admin", "secret")))
        assert sorted(r.table.fids.tolist()) == ["a", "b", "c", "d"]
        recs = {f: r.table.record(i) for i, f in enumerate(r.table.fids)}
        assert recs["a"]["name"] == "classified"


class TestDictionaryPushdown:
    """String predicates resolve against the column dictionary once
    (ArrowFilterOptimizer role) and must agree with the per-row path."""

    def _table(self, n=5000):
        from geomesa_tpu.schema.columnar import FeatureTable

        rng = np.random.default_rng(12)
        sft = parse_spec("d", "name:String,k:Integer")
        names = np.array([f"cat{i}" for i in rng.integers(0, 40, n)], dtype=object)
        names[::97] = None  # nulls
        recs = [{"name": names[i], "k": int(i)} for i in range(n)]
        return FeatureTable.from_records(sft, recs, [str(i) for i in range(n)])

    def test_eq_in_like_match_row_path(self):
        from geomesa_tpu.filter import ast

        t = self._table()
        col = t.columns["name"]
        assert col.dictionary() is not None
        for f in (
            ast.Compare("=", "name", "cat7"),
            ast.Compare("<>", "name", "cat7"),
            ast.In("name", ("cat1", "cat2", "nope")),
            ast.Like("name", "cat1%"),
        ):
            fast = f.mask(t)
            # force the per-row path by shrinking below the threshold
            small_rows = np.arange(len(t))
            ref = np.concatenate([
                type(f).mask(f, t.take(small_rows[i : i + 500]))
                for i in range(0, len(t), 500)
            ])
            np.testing.assert_array_equal(fast, ref), type(f).__name__

    def test_eq_miss_literal(self):
        from geomesa_tpu.filter import ast

        t = self._table()
        assert ast.Compare("=", "name", "zzz-not-there").mask(t).sum() == 0
