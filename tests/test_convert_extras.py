"""Converter framework extensions: JSON, fixed-width, type inference,
validators (reference: geomesa-convert suites — SURVEY.md §2.16)."""

import json

import numpy as np
import pandas as pd
import pytest

from geomesa_tpu.convert.delimited import EvaluationContext
from geomesa_tpu.convert.fixed_width import FixedWidthConverter
from geomesa_tpu.convert.infer import infer_schema
from geomesa_tpu.convert.json_converter import JsonConverter, geojson_geometry
from geomesa_tpu.convert.validate import apply_validators, validation_mask
from geomesa_tpu.geometry.types import Point, Polygon
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec


class TestJsonConverter:
    SFT = parse_spec("j", "name:String,age:Integer,dtg:Date,*geom:Point")

    def conv(self, **kw):
        return JsonConverter(
            self.SFT,
            fields={
                "name": "$.props.name",
                "age": "$.props.age",
                "dtg": "isodate($.when)",
                "geom": "point($.lon, $.lat)",
            },
            feature_path="$.features[*]",
            id_field="$.id",
            **kw,
        )

    def doc(self):
        return json.dumps(
            {
                "features": [
                    {"id": "a", "props": {"name": "n1", "age": 31},
                     "when": "2017-07-01T00:00:00Z", "lon": 10.0, "lat": 20.0},
                    {"id": "b", "props": {"name": "n2", "age": 7},
                     "when": "2017-07-02T12:00:00Z", "lon": -5.5, "lat": 4.25},
                ]
            }
        )

    def test_feature_array(self):
        t = self.conv().convert_str(self.doc())
        assert len(t) == 2
        assert list(t.fids) == ["a", "b"]
        r = t.record(0)
        assert r["name"] == "n1" and r["age"] == 31
        assert r["dtg"] == 1_498_867_200_000
        assert r["geom"].x == 10.0 and r["geom"].y == 20.0

    def test_json_lines(self):
        conv = JsonConverter(
            self.SFT,
            fields={
                "name": "$.name",
                "age": "$.age",
                "dtg": "millisToDate($.t)",
                "geom": "geojson($.geometry)",
            },
        )
        lines = "\n".join(
            json.dumps(
                {"name": f"x{i}", "age": i, "t": 1000 * i,
                 "geometry": {"type": "Point", "coordinates": [i, -i]}}
            )
            for i in range(5)
        )
        t = conv.convert_str(lines)
        assert len(t) == 5
        assert t.record(3)["geom"].x == 3.0
        np.testing.assert_array_equal(
            t.dtg_millis(), np.arange(5) * 1000
        )

    def test_error_modes(self):
        bad = json.dumps(
            {
                "features": [
                    {"id": "a", "props": {"name": "n1", "age": 1},
                     "when": "2017-07-01T00:00:00Z", "lon": 10.0, "lat": 20.0},
                    {"id": "bad", "props": {"name": "n2", "age": 2},
                     "when": "2017-07-01T00:00:00Z", "lon": 999.0, "lat": 20.0},
                ]
            }
        )
        ctx = EvaluationContext()
        t = self.conv().convert_str(bad, ctx)
        assert len(t) == 1 and ctx.failure == 1 and ctx.success == 1
        with pytest.raises(ValueError, match="bad record"):
            self.conv(error_mode="raise").convert_str(bad)

    def test_geojson_geometry_kinds(self):
        p = geojson_geometry({"type": "Point", "coordinates": [1, 2]})
        assert isinstance(p, Point)
        poly = geojson_geometry(
            {"type": "Polygon", "coordinates": [[[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]]]}
        )
        assert isinstance(poly, Polygon)
        assert geojson_geometry(None) is None
        assert geojson_geometry({"type": "Point", "coordinates": []}) is None


class TestFixedWidth:
    def test_slices_and_transforms(self):
        sft = parse_spec("f", "code:String,val:Integer,*geom:Point")
        #        0-3: code, 3-9: lon, 9-15: lat, 15-18: val
        lines = [
            "abc  10.5  20.5  7 ",
            "xyz -11.25 41.0 42 ",
        ]
        conv = FixedWidthConverter(
            sft,
            slices=[(0, 3), (3, 7), (10, 6), (16, 3)],
            fields={"code": "$1", "val": "int($4)", "geom": "point($2, $3)"},
        )
        t = conv.convert_lines(lines)
        assert len(t) == 2
        assert t.record(0)["code"] == "abc"
        assert t.record(1)["val"] == 42
        assert t.record(1)["geom"].x == pytest.approx(-11.25)


class TestInference:
    def test_infer_types_and_geometry(self):
        df = pd.DataFrame(
            {
                "name": ["a", "b", "c"],
                "count": ["1", "2", "3"],
                "big": [str(2**40), "5", "6"],
                "ratio": ["0.5", "1.5", "2.0"],
                "flag": ["true", "false", "true"],
                "when": ["2017-07-01T00:00:00Z"] * 3,
                "lon": ["10.0", "20.0", "30.0"],
                "lat": ["-5.0", "5.0", "15.0"],
            }
        )
        sft, fields = infer_schema(df, "t")
        types = {a.name: a.type.name for a in sft.attributes}
        assert types["name"] == "STRING"
        assert types["count"] == "INT"
        assert types["big"] == "LONG"
        assert types["ratio"] == "DOUBLE"
        assert types["flag"] == "BOOLEAN"
        assert types["when"] == "DATE"
        assert sft.geom_field == "geom"
        assert fields["geom"] == "point(lon, lat)"

    def test_infer_from_csv(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("id,x,y\n1,5.0,6.0\n2,7.0,8.0\n")
        sft, fields = infer_schema(str(p), "c")
        assert sft.geom_field == "geom"
        assert {a.name for a in sft.attributes} >= {"id", "x", "y", "geom"}


class TestValidators:
    SFT = parse_spec("v", "name:String,dtg:Date,*geom:Point")

    def table(self):
        return FeatureTable.from_records(
            self.SFT,
            [
                {"name": "ok", "dtg": 1000, "geom": Point(1, 1)},
                {"name": "nogeo", "dtg": 1000, "geom": None},
                {"name": "nodtg", "dtg": None, "geom": Point(2, 2)},
            ],
        )

    def test_masks(self):
        t = self.table()
        np.testing.assert_array_equal(
            validation_mask(t, ("index",)), [True, False, False]
        )
        np.testing.assert_array_equal(
            validation_mask(t, ("has-geo",)), [True, False, True]
        )
        np.testing.assert_array_equal(
            validation_mask(t, ("has-dtg",)), [True, True, False]
        )
        np.testing.assert_array_equal(validation_mask(t, ("none",)), [True] * 3)

    def test_apply(self):
        ctx = EvaluationContext(success=3)
        out = apply_validators(self.table(), ("index",), ctx)
        assert len(out) == 1 and out.record(0)["name"] == "ok"
        assert ctx.failure == 2 and ctx.success == 1
        with pytest.raises(ValueError, match="failed validation"):
            apply_validators(self.table(), ("index",), error_mode="raise")
        with pytest.raises(ValueError, match="unknown validator"):
            validation_mask(self.table(), ("bogus",))
