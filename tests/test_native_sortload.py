"""Native sort/merge kernels + delimited loader: exact agreement with the
numpy/pandas paths (reference native checklist — SURVEY.md §2.9)."""

import numpy as np
import pytest

from geomesa_tpu import native


def _have(name):
    return native._load_lib(name) is not None


@pytest.mark.skipif(not _have("sortmerge"), reason="no C++ toolchain")
class TestSortMerge:
    def test_lexsort_bin_z_agrees(self, rng):
        n = 50_000
        bins = rng.integers(0, 40, n).astype(np.int32)
        zs = rng.integers(0, 1 << 62, n).astype(np.uint64)
        np.testing.assert_array_equal(
            native.lexsort_bin_z(bins, zs), np.lexsort((zs, bins))
        )

    def test_sort_u64_agrees(self, rng):
        keys = rng.integers(0, 1 << 62, 50_000).astype(np.uint64)
        np.testing.assert_array_equal(
            native.sort_u64(keys), np.argsort(keys, kind="stable")
        )

    def test_stability_on_duplicates(self):
        bins = np.zeros(6, dtype=np.int32)
        zs = np.array([5, 5, 1, 5, 1, 1], dtype=np.uint64)
        perm = native.lexsort_bin_z(bins, zs)
        # equal keys keep input order
        np.testing.assert_array_equal(perm, [2, 4, 5, 0, 1, 3])

    def test_merge_bin_z(self, rng):
        na, nb = 10_000, 3_000
        bins_a = np.sort(rng.integers(0, 20, na)).astype(np.int32)
        zs_a = np.empty(na, dtype=np.uint64)
        for b in np.unique(bins_a):
            m = bins_a == b
            zs_a[m] = np.sort(rng.integers(0, 1 << 60, int(m.sum())).astype(np.uint64))
        bins_b = np.sort(rng.integers(0, 20, nb)).astype(np.int32)
        zs_b = np.empty(nb, dtype=np.uint64)
        for b in np.unique(bins_b):
            m = bins_b == b
            zs_b[m] = np.sort(rng.integers(0, 1 << 60, int(m.sum())).astype(np.uint64))
        perm = native.merge_bin_z(bins_a, zs_a, bins_b, zs_b)
        all_bins = np.concatenate([bins_a, bins_b])[perm]
        all_zs = np.concatenate([zs_a, zs_b])[perm]
        assert np.all(np.diff(all_bins) >= 0)
        same = np.diff(all_bins) == 0
        assert np.all(np.diff(all_zs.astype(object))[same] >= 0)

    def test_index_build_uses_native(self):
        # Z3 build through the native path matches brute-force expectations
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(2)
        n = 5000
        recs = [
            {"dtg": 1_498_867_200_000 + int(rng.integers(0, 10 * 86_400_000)),
             "geom": Point(float(rng.uniform(-180, 180)), float(rng.uniform(-90, 90)))}
            for _ in range(n)
        ]
        ds = DataStore(backend="tpu")
        ds.create_schema("s", "dtg:Date,*geom:Point")
        ds.write("s", recs)
        ds.compact("s")
        st = ds._state("s")
        z3 = st.indices["z3"]
        assert np.all(np.diff(z3.bins) >= 0)


@pytest.mark.skipif(not _have("delimited"), reason="no C++ toolchain")
class TestDelimitedLoader:
    def test_typed_extraction(self):
        data = (
            b"a\t20170701\t1.5\t-3\n"
            b"b\t20170815\t2.25\t7\n"
            b"c\t\t\t\n"            # empty cells -> invalid
            b"d\tgarbage\tx\t1e2\n"  # unparseable -> invalid (1e2 not int)
        )
        out = native.parse_delimited(
            data, "\t",
            [(1, native.DATE_YYYYMMDD), (2, native.F64), (3, native.I64)],
        )
        assert out is not None
        (dates, floats, ints), valid = out
        assert len(dates) == 4
        # 2017-07-01 epoch millis
        assert dates[0] == 1_498_867_200_000
        assert dates[1] == 1_502_755_200_000  # 2017-08-15
        assert floats[0] == 1.5 and floats[1] == 2.25
        assert ints[0] == -3 and ints[1] == 7
        np.testing.assert_array_equal(valid[0], [True, True, False, False])
        np.testing.assert_array_equal(valid[1], [True, True, False, False])
        np.testing.assert_array_equal(valid[2], [True, True, False, False])

    def test_agrees_with_pandas_on_gdelt_shape(self, rng):
        import pandas as pd

        n = 2000
        lines = []
        for i in range(n):
            fields = [""] * 57
            fields[0] = str(i)
            fields[1] = f"2017{rng.integers(1, 13):02d}{rng.integers(1, 29):02d}"
            fields[30] = f"{rng.uniform(-10, 10):.6f}"
            fields[39] = f"{rng.uniform(-90, 90):.6f}"
            fields[40] = f"{rng.uniform(-180, 180):.6f}"
            lines.append("\t".join(fields))
        data = ("\n".join(lines) + "\n").encode()
        (gold, lat, lon), valid = native.parse_delimited(
            data, "\t", [(30, native.F64), (39, native.F64), (40, native.F64)]
        )
        df = pd.read_csv(
            __import__("io").BytesIO(data), sep="\t", header=None, dtype=str,
            keep_default_na=False, na_values=[],
        )
        np.testing.assert_allclose(gold, df[30].astype(float).to_numpy())
        np.testing.assert_allclose(lat, df[39].astype(float).to_numpy())
        np.testing.assert_allclose(lon, df[40].astype(float).to_numpy())
        assert valid.all()

    def test_no_trailing_newline(self):
        out = native.parse_delimited(b"x,1.5\ny,2.5", ",", [(1, native.F64)])
        (vals,), valid = out
        np.testing.assert_allclose(vals, [1.5, 2.5])

    def test_missing_trailing_columns(self):
        out = native.parse_delimited(b"1,2\n3\n", ",", [(0, native.I64), (1, native.I64)])
        (a, b), valid = out
        assert a.tolist() == [1, 3]
        np.testing.assert_array_equal(valid[1], [True, False])
