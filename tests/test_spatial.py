"""ST_* function library, geometry ops, DE-9IM relate, geohash, WKB.

Mirrors the reference's spark-jts test strategy (SURVEY.md §2.14): known-value
assertions per UDF plus relation truth tables.
"""

import numpy as np
import pytest

from geomesa_tpu.geometry import ops
from geomesa_tpu.geometry.types import (
    LineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    box,
)
from geomesa_tpu.geometry.wkb import from_wkb, to_wkb
from geomesa_tpu.geometry.wkt import from_wkt, to_wkt
from geomesa_tpu.spatial import ST, geohash_bbox, geohash_encode, geohash_neighbors
from geomesa_tpu.spatial.st_functions import st


def P(x, y):
    return Point(x, y)


SQ = box(0, 0, 2, 2)  # unit-ish square


class TestWkb:
    @pytest.mark.parametrize(
        "wkt",
        [
            "POINT (1 2)",
            "LINESTRING (0 0, 1 1, 2 0)",
            "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))",
            "MULTIPOINT (0 0, 1 1)",
            "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))",
            "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((2 2, 3 2, 3 3, 2 3, 2 2)))",
        ],
    )
    def test_round_trip(self, wkt):
        g = from_wkt(wkt)
        assert to_wkt(from_wkb(to_wkb(g))) == to_wkt(g)

    def test_point_layout(self):
        # little-endian, type 1, doubles
        b = to_wkb(Point(1.0, 2.0))
        assert b[0] == 1 and int.from_bytes(b[1:5], "little") == 1

    def test_ewkb_srid_and_z(self):
        import struct

        # PostGIS EWKB: SRID flag carries a 4-byte SRID payload to skip
        ewkb = struct.pack("<BII", 1, 0x20000001, 4326) + struct.pack("<dd", 1.5, 2.5)
        g = from_wkb(ewkb)
        assert (g.x, g.y) == (1.5, 2.5)
        # EWKB Z flag: 3 ordinates per point, Z dropped
        zwkb = struct.pack("<BI", 1, 0x80000001) + struct.pack("<ddd", 1.0, 2.0, 9.9)
        g = from_wkb(zwkb)
        assert (g.x, g.y) == (1.0, 2.0)
        # ISO WKB Z: type 1002 = LineString Z
        iso = struct.pack("<BII", 1, 1002, 2) + struct.pack("<dddddd", 0, 0, 5, 1, 1, 6)
        assert to_wkt(from_wkb(iso)) == "LINESTRING (0 0, 1 1)"


class TestMeasures:
    def test_area(self):
        assert ops.area(SQ) == pytest.approx(4.0)
        holed = Polygon(SQ.shell, (box(0.5, 0.5, 1.0, 1.0).shell,))
        assert ops.area(holed) == pytest.approx(4.0 - 0.25)
        assert ops.area(LineString([[0, 0], [1, 1]])) == 0.0

    def test_length(self):
        assert ops.length(LineString([[0, 0], [3, 4]])) == pytest.approx(5.0)
        assert ops.length(SQ) == pytest.approx(8.0)

    def test_centroid(self):
        c = ops.centroid(SQ)
        assert (c.x, c.y) == pytest.approx((1.0, 1.0))
        c = ops.centroid(LineString([[0, 0], [2, 0]]))
        assert (c.x, c.y) == pytest.approx((1.0, 0.0))

    def test_distance_sphere_known(self):
        # London -> Paris great-circle ≈ 344 km
        d = ops.distance_sphere(P(-0.1278, 51.5074), P(2.3522, 48.8566))
        assert d == pytest.approx(343_500, rel=0.01)

    def test_length_sphere(self):
        # one degree of longitude at the equator ≈ 111.19 km
        d = ops.length_sphere(LineString([[0, 0], [1, 0]]))
        assert d == pytest.approx(111_195, rel=0.001)


class TestConstructiveOps:
    def test_convex_hull(self):
        g = MultiPoint(tuple(P(x, y) for x, y in [(0, 0), (2, 0), (1, 1), (2, 2), (0, 2), (1, 0.5)]))
        h = ops.convex_hull(g)
        assert isinstance(h, Polygon)
        assert ops.area(h) == pytest.approx(4.0)

    def test_envelope_boundary(self):
        assert ops.area(ops.envelope(LineString([[0, 0], [2, 1]]))) == pytest.approx(2.0)
        b = ops.boundary(SQ)
        assert isinstance(b, LineString) and ops.length(b) == pytest.approx(8.0)
        bl = ops.boundary(LineString([[0, 0], [1, 0]]))
        assert isinstance(bl, MultiPoint) and len(bl.parts) == 2

    def test_closest_point(self):
        cp = ops.closest_point(LineString([[0, 0], [10, 0]]), P(3, 5))
        assert (cp.x, cp.y) == pytest.approx((3.0, 0.0))

    def test_closest_point_contained(self):
        # point inside the polygon: distance 0, the point itself is closest
        cp = ops.closest_point(box(0, 0, 10, 10), P(5, 5))
        assert (cp.x, cp.y) == (5.0, 5.0)
        assert ops.distance_sphere(box(0, 0, 10, 10), P(5, 5)) == 0.0

    def test_translate(self):
        t = ops.translate(P(1, 1), 2, -1)
        assert (t.x, t.y) == (3.0, 0.0)

    def test_buffer_point(self):
        buf = ops.buffer_point(P(0, 0), 111_195)  # ~1 degree at equator
        xmin, ymin, xmax, ymax = buf.bbox
        assert xmax == pytest.approx(1.0, rel=0.01)
        assert ymax == pytest.approx(1.0, rel=0.01)

    def test_antimeridian_split(self):
        g = Polygon(
            np.array([[170.0, 0], [-170.0, 0], [-170.0, 10], [170.0, 10], [170.0, 0]])
        )
        safe = ops.antimeridian_safe(g)
        assert isinstance(safe, MultiPolygon)
        assert ops.area(safe) == pytest.approx(200.0)

    def test_antimeridian_split_with_hole(self):
        g = Polygon(
            np.array([[170.0, 0], [-170.0, 0], [-170.0, 10], [170.0, 10], [170.0, 0]]),
            (np.array([[175.0, 2], [-178.0, 2], [-178.0, 8], [175.0, 8], [175.0, 2]]),),
        )
        safe = ops.antimeridian_safe(g)
        assert ops.area(safe) == pytest.approx(200.0 - 42.0)

    def test_validity(self):
        assert ops.is_valid(SQ)
        bowtie = Polygon(np.array([[0.0, 0], [2, 2], [2, 0], [0, 2], [0, 0]]))
        assert not ops.is_valid(bowtie)
        assert ops.is_simple(LineString([[0, 0], [1, 1]]))
        assert not ops.is_simple(LineString([[0, 0], [2, 2], [2, 0], [0, 2]]))
        assert ops.is_ring(LineString(SQ.shell))


class TestRelate:
    def test_overlapping_squares(self):
        assert ops.relate(box(0, 0, 2, 2), box(1, 1, 3, 3)) == "212101212"

    def test_edge_touching_squares(self):
        assert ops.relate(box(0, 0, 1, 1), box(1, 0, 2, 1)) == "FF2F11212"

    def test_disjoint_squares(self):
        assert ops.relate(box(0, 0, 1, 1), box(5, 5, 6, 6)) == "FF2FF1212"

    def test_contains_squares(self):
        assert ops.relate(box(0, 0, 4, 4), box(1, 1, 2, 2)) == "212FF1FF2"

    def test_equal_squares(self):
        assert ops.relate(SQ, box(0, 0, 2, 2)) == "2FFF1FFF2"
        assert ops.equals(SQ, box(0, 0, 2, 2))

    def test_point_in_polygon(self):
        assert ops.relate(P(1, 1), SQ) == "0FFFFF212"
        assert ops.relate(SQ, P(1, 1)) == "0F2FF1FF2"
        assert ops.relate(P(5, 5), SQ) == "FF0FFF212"

    def test_line_crosses_polygon(self):
        line = LineString([[-1, 1], [3, 1]])
        m = ops.relate(line, SQ)
        assert m[0] == "1" and m[2] == "1"  # interior crosses, exits
        assert ops.crosses(line, SQ)

    def test_crossing_lines(self):
        a = LineString([[0, 0], [2, 2]])
        b = LineString([[0, 2], [2, 0]])
        assert ops.relate(a, b) == "0F1FF0102"
        assert ops.crosses(a, b)
        assert not ops.overlaps(a, b)

    def test_overlapping_lines(self):
        a = LineString([[0, 0], [2, 0]])
        b = LineString([[1, 0], [3, 0]])
        m = ops.relate(a, b)
        assert m[0] == "1"
        assert ops.overlaps(a, b)

    def test_touching_lines(self):
        a = LineString([[0, 0], [1, 1]])
        b = LineString([[1, 1], [2, 0]])
        assert ops.touches(a, b)
        assert not ops.crosses(a, b)

    def test_touch_corner_squares(self):
        assert ops.touches(box(0, 0, 1, 1), box(1, 1, 2, 2))

    def test_overlaps_squares(self):
        assert ops.overlaps(box(0, 0, 2, 2), box(1, 1, 3, 3))
        assert not ops.overlaps(box(0, 0, 4, 4), box(1, 1, 2, 2))  # containment

    def test_covers(self):
        assert ops.covers(box(0, 0, 4, 4), box(1, 1, 2, 2))
        assert ops.covers(box(0, 0, 4, 4), box(0, 0, 2, 4))  # shared boundary
        assert not ops.covers(box(0, 0, 2, 2), box(1, 1, 3, 3))
        assert ops.covered_by(box(1, 1, 2, 2), box(0, 0, 4, 4))

    def test_polygon_in_hole(self):
        outer = Polygon(box(0, 0, 10, 10).shell, (box(2, 2, 8, 8).shell,))
        inner = box(4, 4, 6, 6)
        m = ops.relate(outer, inner)
        assert m[0] == "F"  # interiors disjoint (inner sits in the hole)

    def test_nested_via_representative_point(self):
        # concave C-shape vs a square in its notch: centroid would misclassify
        c_shape = Polygon(
            np.array([[0.0, 0], [5, 0], [5, 1], [1, 1], [1, 4], [5, 4], [5, 5], [0, 5], [0, 0]])
        )
        notch_sq = box(2, 2, 3, 3)
        m = ops.relate(c_shape, notch_sq)
        assert m[0] == "F"


class TestGeohash:
    def test_known_value(self):
        # classic example: Ezequiel's town — geohash "ezs42"
        assert str(geohash_encode(-5.603, 42.605, 5)) == "ezs42"

    def test_vectorized(self):
        out = geohash_encode([-5.603, 0.0], [42.605, 0.0], 5)
        assert list(out) == ["ezs42", "s0000"]

    def test_bbox_round_trip(self):
        xmin, ymin, xmax, ymax = geohash_bbox("ezs42")
        assert xmin <= -5.603 <= xmax and ymin <= 42.605 <= ymax
        assert (xmax - xmin) == pytest.approx(360.0 / 2**13)

    def test_neighbors(self):
        n = geohash_neighbors("ezs42")
        assert len(n) == 8 and "ezs42" not in n

    def test_precision_limit(self):
        with pytest.raises(ValueError):
            geohash_encode(10.0, 10.0, 13)
        # max precision round-trips
        gh = str(geohash_encode(10.0, 10.0, 12))
        xmin, ymin, xmax, ymax = geohash_bbox(gh)
        assert xmin <= 10.0 <= xmax and ymin <= 10.0 <= ymax

    def test_encode_decode_random(self):
        rng = np.random.default_rng(0)
        lons = rng.uniform(-180, 180, 50)
        lats = rng.uniform(-90, 90, 50)
        for gh, lon, lat in zip(geohash_encode(lons, lats, 9), lons, lats):
            xmin, ymin, xmax, ymax = geohash_bbox(gh)
            assert xmin <= lon <= xmax and ymin <= lat <= ymax


class TestSTRegistry:
    def test_all_reference_udfs_present(self):
        # every UDF name registered by the reference's spark-jts module
        reference_names = [
            "st_aggregateDistanceSphere", "st_antimeridianSafeGeom", "st_area",
            "st_asBinary", "st_asGeoJSON", "st_asLatLonText", "st_asText",
            "st_boundary", "st_box2DFromGeoHash", "st_bufferPoint",
            "st_byteArray", "st_castToGeometry", "st_castToLineString",
            "st_castToPoint", "st_castToPolygon", "st_centroid",
            "st_closestPoint", "st_contains", "st_convexhull", "st_coordDim",
            "st_covers", "st_crosses", "st_dimension", "st_disjoint",
            "st_distance", "st_distanceSphere", "st_envelope", "st_equals",
            "st_exteriorRing", "st_geoHash", "st_geomFromGeoHash",
            "st_geomFromText", "st_geomFromWKB", "st_geomFromWKT",
            "st_geometryFromText", "st_geometryN", "st_idlSafeGeom",
            "st_interiorRingN", "st_intersects", "st_isClosed",
            "st_isCollection", "st_isEmpty", "st_isRing", "st_isSimple",
            "st_isValid", "st_length", "st_lengthSphere", "st_lineFromText",
            "st_mLineFromText", "st_mPointFromText", "st_mPolyFromText",
            "st_makeBBOX", "st_makeBox2D", "st_makeLine", "st_makePoint",
            "st_makePointM", "st_numGeometries", "st_numPoints", "st_overlaps",
            "st_point", "st_pointFromGeoHash", "st_pointFromText",
            "st_pointFromWKB", "st_pointN", "st_polygon", "st_polygonFromText",
            "st_relate", "st_relateBool", "st_touches", "st_translate",
            "st_within", "st_x", "st_y",
        ]
        for name in reference_names:
            assert name.lower() in ST, name

    def test_scalar_calls(self):
        g = st("st_geomFromText", "POINT (1 2)")
        assert (st("st_x", g), st("st_y", g)) == (1.0, 2.0)
        assert st("st_asText", st("st_makeBBOX", 0, 0, 2, 2)) == to_wkt(SQ)
        assert st("st_contains", SQ, P(1, 1))
        assert st("st_geoHash", P(-5.603, 42.605), 25) == "ezs42"
        assert st("st_dimension", SQ) == 2
        assert "Polygon" in st("st_asGeoJSON", SQ)

    def test_column_calls(self):
        pts = np.empty(3, dtype=object)
        pts[:] = [P(1, 1), P(5, 5), P(0, 0)]
        mask = st("st_contains", SQ, pts)
        # (0,0) is a corner: boundary contact only, so contains is False (JTS)
        assert mask.dtype == bool and list(mask) == [True, False, False]
        cov = st("st_covers", SQ, pts)
        assert list(cov) == [True, False, True]
        areas = st("st_area", np.array([SQ, box(0, 0, 1, 1)], dtype=object))
        assert list(areas) == [4.0, 1.0]
        # integer accessors keep integer dtype over columns
        dims = st("st_dimension", np.array([SQ, P(0, 0)], dtype=object))
        assert dims.dtype == np.int64 and list(dims) == [2, 0]

    def test_wkb_round_trip_udf(self):
        b = st("st_asBinary", SQ)
        assert st("st_asText", st("st_geomFromWKB", b)) == to_wkt(SQ)

    def test_make_line_and_polygon(self):
        line = st("st_makeLine", [P(0, 0), P(1, 0), P(1, 1)])
        assert st("st_numPoints", line) == 3
        ring = st("st_makeLine", [P(0, 0), P(1, 0), P(1, 1), P(0, 0)])
        poly = st("st_polygon", ring)
        assert st("st_area", poly) == pytest.approx(0.5)
        poly2 = st("st_makePolygon", ring)  # spark-jts alias of st_polygon
        assert st("st_area", poly2) == pytest.approx(0.5)
        assert st("st_geometryType", poly2) == "Polygon"
        assert st("st_geometryType", P(1, 2)) == "Point"

    def test_lat_lon_text(self):
        txt = st("st_asLatLonText", P(-75.5, 35.25))
        assert "35°15'" in txt and "N" in txt and "W" in txt


class TestGeohashBboxCover:
    def test_cover_tight_and_complete(self):
        from geomesa_tpu.spatial.geohash import (
            geohash_bbox,
            geohash_encode,
            geohashes_in_bbox,
        )

        box = (-0.6, 51.2, 0.4, 51.7)
        ghs = geohashes_in_bbox(box, 5)
        assert len(ghs) == len(set(ghs))
        for g in ghs:
            x1, y1, x2, y2 = geohash_bbox(g)
            assert x2 >= box[0] and x1 <= box[2]
            assert y2 >= box[1] and y1 <= box[3]
        for cx, cy in [(box[0], box[1]), (box[2], box[3])]:
            assert str(geohash_encode([cx], [cy], 5)[0]) in set(ghs)

    def test_limits(self):
        import pytest

        from geomesa_tpu.spatial.geohash import geohashes_in_bbox

        with pytest.raises(ValueError, match="max_hashes"):
            geohashes_in_bbox((-180, -90, 180, 90), 6)
        with pytest.raises(ValueError, match="precision"):
            geohashes_in_bbox((0, 0, 1, 1), 0)
        assert len(geohashes_in_bbox((-180, -90, 180, 90), 1)) == 32
