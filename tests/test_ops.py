"""Operational hardening: split points, catalog locks, query watchdog,
column groups (reference: DefaultSplitter, DistributedLocking,
ThreadManagement, ColumnGroups — SURVEY.md §2.3)."""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu.filter.cql import parse
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.column_groups import ColumnGroups, filter_attributes
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.splitter import balanced_splits, default_splits, shard_of
from geomesa_tpu.utils.locks import LockTimeout, catalog_lock
from geomesa_tpu.utils.timeouts import QueryTimeout, Watchdog, run_with_timeout


class TestSplitter:
    def test_default_splits_z(self):
        s = default_splits("z3", 4)
        assert len(s) == 3
        assert np.all(np.diff(s) > 0)
        # evenly spaced across the 62-bit domain
        assert s[0] == (1 << 62) // 4

    def test_default_splits_attr(self):
        s = default_splits("attr", 8)
        assert len(s) == 7 and s[0] == 32

    def test_balanced_splits_equal_counts(self):
        rng = np.random.default_rng(0)
        keys = np.sort(rng.integers(0, 1 << 40, 10_000))
        splits = balanced_splits(keys, 8)
        sid = shard_of(keys, splits)
        counts = np.bincount(sid, minlength=8)
        # skewed data still lands in near-equal shards
        assert counts.max() - counts.min() <= 2

    def test_balanced_splits_skewed(self):
        keys = np.sort(np.concatenate([np.zeros(5000, np.int64),
                                       np.arange(5000, dtype=np.int64) + 10]))
        splits = balanced_splits(keys, 4)
        sid = shard_of(keys, splits)
        counts = np.bincount(sid, minlength=4)
        assert counts.sum() == 10_000
        # identical keys can't be split apart; everything else balances
        assert counts[-1] >= 2000

    def test_degenerate(self):
        assert len(balanced_splits(np.array([], np.int64), 4)) == 0
        assert len(default_splits("z2", 1)) == 0
        assert shard_of(np.arange(5), np.empty(0, np.int64)).tolist() == [0] * 5


class TestCatalogLock:
    def test_exclusive(self, tmp_path):
        p = str(tmp_path / "cat")
        order = []
        with catalog_lock(p):
            t = threading.Thread(
                target=lambda: (
                    [order.append("wait")],
                    catalog_lock(p, timeout_s=5).__enter__(),
                    order.append("acquired"),
                )
            )
            t.start()
            time.sleep(0.2)
            order.append("release")
        t.join(5)
        assert order == ["wait", "release", "acquired"]

    def test_timeout(self, tmp_path):
        p = str(tmp_path / "cat")
        with catalog_lock(p):
            # flock is per-fd, so a second acquisition in another *process*
            # would block; emulate with a thread + tiny timeout
            err = []

            def try_lock():
                try:
                    with catalog_lock(p, timeout_s=0.2):
                        pass
                except LockTimeout as e:
                    err.append(e)

            t = threading.Thread(target=try_lock)
            t.start()
            t.join(5)
            assert len(err) == 1

    def test_save_concurrent_is_serialized(self, tmp_path):
        # concurrent saves from SEPARATE PROCESSES (the lock's actual
        # scenario — flock is cross-process) must serialize, leaving a
        # consistent loadable catalog
        import subprocess
        import sys

        path = str(tmp_path / "cat")
        script = (
            "import sys\n"
            "from geomesa_tpu.geometry.types import Point\n"
            "from geomesa_tpu.store.datastore import DataStore\n"
            "ds = DataStore(backend='oracle')\n"
            "ds.create_schema('t', 'a:Integer,*geom:Point')\n"
            "ds.write('t', [{'a': i, 'geom': Point(i, i)} for i in range(10)])\n"
            f"ds.save({path!r})\n"
        )
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script],
                cwd="/root/repo",
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for _ in range(4)
        ]
        for p in procs:
            _, err = p.communicate(timeout=120)
            assert p.returncode == 0, err.decode()
        from geomesa_tpu.store import persistence

        out = persistence.load(path, backend="oracle")
        assert out.query("t").count == 10


class TestWatchdog:
    def test_run_inline_without_timeout(self):
        assert run_with_timeout(lambda: 42, None) == 42

    def test_timeout_raises(self):
        with pytest.raises(QueryTimeout):
            run_with_timeout(time.sleep, 0.05, 0.5)

    def test_result_within_deadline(self):
        assert run_with_timeout(lambda: "ok", 2.0) == "ok"

    def test_query_timeout_hint(self, monkeypatch):
        ds = DataStore(backend="oracle")
        ds.create_schema("t", "a:Integer,dtg:Date,*geom:Point")
        ds.write("t", [{"a": i, "dtg": i, "geom": Point(0, 0)} for i in range(100)])
        ds.compact("t")  # move rows to the main tier so the scan runs select()
        # normal query under generous timeout works
        assert ds.query("t", Query(hints={"timeout": 30.0})).count == 100
        assert ds.watchdog.abandoned == 0
        # a deterministically slow scan trips the watchdog
        orig = type(ds.backend).select

        def slow_select(self, *args, **kwargs):
            time.sleep(0.5)
            return orig(self, *args, **kwargs)

        monkeypatch.setattr(type(ds.backend), "select", slow_select)
        with pytest.raises(QueryTimeout):
            ds.query("t", Query(hints={"timeout": 0.05}))
        assert ds.watchdog.abandoned == 1
        assert ds.metrics.snapshot()["store.query.timeouts"]["count"] == 1
        assert ds.watchdog.active() == []

    def test_registry(self):
        w = Watchdog()
        t1 = w.register("q1")
        w.register("q2")
        assert len(w.active()) == 2
        w.complete(t1)
        assert w.active() == ["q2"]


class TestColumnGroups:
    SPEC = ("name:String,heading:Double,dtg:Date,*geom:Point;"
            "geomesa.column.groups='track:name;full:name,heading'")

    def test_parse_and_select(self):
        sft = parse_spec("cg", self.SPEC)
        cg = ColumnGroups(sft)
        # geom + dtg implicitly in every group
        assert cg.groups["track"] == {"name", "geom", "dtg"}
        name, attrs = cg.group_for(["name"], parse("BBOX(geom,0,0,1,1)"))
        assert name == "track"
        name, _ = cg.group_for(["name", "heading"], None)
        assert name == "full"
        name, attrs = cg.group_for(None, None)  # no projection → everything
        assert name == "default" and attrs == {"name", "heading", "dtg", "geom"}

    def test_filter_attributes(self):
        f = parse("BBOX(geom,0,0,1,1) AND name = 'x' AND heading > 5")
        assert filter_attributes(f) == {"geom", "name", "heading"}

    def test_unknown_attr_rejected(self):
        sft = parse_spec("cg", "a:Integer,*geom:Point;geomesa.column.groups='g:nope'")
        with pytest.raises(ValueError, match="unknown attributes"):
            ColumnGroups(sft)

    def test_reduced_sft_and_partial_load(self, tmp_path):
        sft = parse_spec("cg", self.SPEC)
        ds = DataStore(backend="oracle")
        ds.create_schema(sft)
        ds.write(
            "cg",
            [
                {"name": f"n{i}", "heading": float(i), "dtg": 1000 * i, "geom": Point(i, i)}
                for i in range(20)
            ],
        )
        path = str(tmp_path / "cat")
        ds.save(path)
        from geomesa_tpu.store import persistence

        out = persistence.load(path, backend="oracle", column_group="track")
        sft2 = out.get_schema("cg")
        assert [a.name for a in sft2.attributes] == ["name", "dtg", "geom"]
        r = out.query("cg", "BBOX(geom, -1, -1, 5, 5)")
        assert r.count == 6
        assert set(r.table.columns) == {"name", "dtg", "geom"}
