"""Distributed SQL aggregation (VERDICT r3 item 2): GROUP BY / SUM / MIN /
MAX / AVG / COUNT / HAVING execute on the mesh via a fused segment-reduce
(``DataStore.aggregate_many`` → ``parallel.query.make_grouped_agg_step``)
with NO row materialization, exact edge correction, and host-side delta
fold. Every test checks parity against an oracle-backed host fold.
"""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.sql.engine import sql
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000


def _mk(backend: str, n: int = 4000, seed: int = 21, compact: bool = True):
    rng = np.random.default_rng(seed)
    ds = DataStore(backend=backend)
    ds.create_schema("ev", "name:String,val:Double,cnt:Integer,dtg:Date,*geom:Point")
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-45, 45, n)
    # plant rows exactly ON the query bbox boundary so the exact edge
    # correction path is exercised (int-domain superset diverges there)
    lon[:25] = 10.0
    lat[25:50] = -20.0
    t = T0 + rng.integers(0, 3 * 86_400_000, n)
    recs = []
    for i in range(n):
        recs.append({
            "name": f"g{i % 7}",
            "val": None if i % 11 == 0 else float((i * 37) % 1000) / 10.0,
            "cnt": int(i % 13),
            "dtg": int(t[i]),
            "geom": Point(float(lon[i]), float(lat[i])),
        })
    ds.write("ev", recs, fids=[f"e{i}" for i in range(n)])
    if compact:
        ds.compact("ev")
    return ds


QUERIES = [
    "SELECT name, COUNT(*) AS n, SUM(val) AS s, MIN(val) AS lo, "
    "MAX(val) AS hi, AVG(val) AS m FROM ev GROUP BY name",
    "SELECT name, COUNT(val) AS nv, SUM(cnt) AS sc FROM ev "
    "WHERE BBOX(geom, -50, -40, 10, -20) GROUP BY name",
    "SELECT COUNT(*) AS n, SUM(val) AS s, MIN(cnt) AS lo, MAX(cnt) AS hi, "
    "AVG(val) AS m FROM ev WHERE BBOX(geom, -20, -30, 40, 35)",
    "SELECT name, cnt, COUNT(*) AS n FROM ev "
    "WHERE BBOX(geom, -30, -30, 30, 30) GROUP BY name, cnt",
    "SELECT name, COUNT(*) AS n FROM ev GROUP BY name HAVING COUNT(*) > 500",
    "SELECT name FROM ev GROUP BY name HAVING AVG(val) >= 49",
    "SELECT name, SUM(val) AS s FROM ev GROUP BY name ORDER BY s DESC LIMIT 3",
]


def _sorted_rows(res):
    return sorted(
        tuple(None if v is None else round(float(v), 6) if isinstance(v, (int, float)) else v
              for v in row)
        for row in res.rows()
    )


class TestMeshAggParity:
    @pytest.mark.parametrize("q", QUERIES)
    def test_parity_vs_host_fold(self, q):
        tpu = _mk("tpu")
        host = _mk("oracle")
        got = sql(tpu, q)
        want = sql(host, q)
        if "ORDER BY" in q:
            # distributed f64 sums reduce in a different order than the host
            # fold — compare with tolerance, but keep row ORDER significant
            def _r(rows):
                return [
                    tuple(
                        round(float(v), 6) if isinstance(v, float) else v
                        for v in row
                    )
                    for row in rows
                ]

            assert _r(got.rows()) == _r(want.rows())
        else:
            assert _sorted_rows(got) == _sorted_rows(want)

    def test_group_by_takes_mesh_path(self, monkeypatch):
        """The mesh fold must serve grouped aggregates with ZERO row
        materialization (no ds.query call)."""
        ds = _mk("tpu")
        calls = {"q": 0}
        real = ds.query
        monkeypatch.setattr(
            ds, "query",
            lambda *a, **k: (calls.__setitem__("q", calls["q"] + 1),
                            real(*a, **k))[1],
        )
        r = sql(ds, "SELECT name, COUNT(*) AS n, SUM(val) AS s FROM ev "
                    "WHERE BBOX(geom, -50, -40, 10, -20) GROUP BY name")
        assert calls["q"] == 0, "grouped aggregate materialized rows"
        assert len(r) > 0

    def test_live_store_delta_fold_with_new_group(self, monkeypatch):
        """Pending hot-tier rows (including a group key absent from the main
        tier) fold into the mesh result without compaction or query()."""
        ds = _mk("tpu")
        ds.write("ev", [
            {"name": "fresh", "val": 5.0, "cnt": 1, "dtg": T0,
             "geom": Point(0.5, 0.5)},
            {"name": "g0", "val": 7.0, "cnt": 2, "dtg": T0,
             "geom": Point(0.6, 0.6)},
        ], fids=["d1", "d2"])
        host = _mk("oracle")
        host.write("ev", [
            {"name": "fresh", "val": 5.0, "cnt": 1, "dtg": T0,
             "geom": Point(0.5, 0.5)},
            {"name": "g0", "val": 7.0, "cnt": 2, "dtg": T0,
             "geom": Point(0.6, 0.6)},
        ], fids=["d1", "d2"])
        calls = {"q": 0}
        real = ds.query
        monkeypatch.setattr(
            ds, "query",
            lambda *a, **k: (calls.__setitem__("q", calls["q"] + 1),
                            real(*a, **k))[1],
        )
        q = ("SELECT name, COUNT(*) AS n, SUM(val) AS s FROM ev "
             "GROUP BY name")
        got = sql(ds, q)
        assert calls["q"] == 0
        assert _sorted_rows(got) == _sorted_rows(sql(host, q))
        assert "fresh" in got.columns["name"].tolist()

    def test_time_filtered_group_by(self):
        tpu = _mk("tpu")
        host = _mk("oracle")
        q = ("SELECT name, COUNT(*) AS n, SUM(cnt) AS s FROM ev WHERE "
             "dtg DURING 2020-09-13T12:00:00Z/2020-09-14T18:30:00Z "
             "GROUP BY name")
        assert _sorted_rows(sql(tpu, q)) == _sorted_rows(sql(host, q))

    def test_attribute_filter_falls_back_with_parity(self):
        tpu = _mk("tpu")
        host = _mk("oracle")
        q = ("SELECT name, COUNT(*) AS n FROM ev WHERE cnt >= 7 "
             "GROUP BY name")
        assert _sorted_rows(sql(tpu, q)) == _sorted_rows(sql(host, q))

    def test_string_min_falls_back_with_parity(self):
        tpu = _mk("tpu")
        host = _mk("oracle")
        q = "SELECT MIN(name) AS lo FROM ev"
        assert sql(tpu, q).rows() == sql(host, q).rows()

    def test_disjoint_filter(self):
        tpu = _mk("tpu")
        host = _mk("oracle")
        for q in (
            "SELECT name, COUNT(*) AS n FROM ev "
            "WHERE BBOX(geom, 170, 80, 179, 89) GROUP BY name",
            "SELECT COUNT(*) AS n, SUM(val) AS s FROM ev "
            "WHERE BBOX(geom, 170, 80, 179, 89)",
        ):
            assert _sorted_rows(sql(tpu, q)) == _sorted_rows(sql(host, q))

    def test_ttl_store_serves_on_mesh_with_parity(self, monkeypatch):
        """TTL stores stay on the mesh path (rows below the quantized
        cutoff unit drop on device; the ambiguous unit re-adds host-side
        at exact ms) with full host-fold parity."""
        import time as _time

        from geomesa_tpu.schema.sft import parse_spec

        now = int(_time.time() * 1000)
        results = {}
        for backend in ("tpu", "oracle"):
            sft = parse_spec("tt", "name:String,val:Double,dtg:Date,*geom:Point")
            sft.user_data["geomesa.age.off"] = 3_600_000  # 1h
            ds = DataStore(backend=backend)
            ds.create_schema(sft)
            recs = []
            for i in range(400):
                fresh = i % 2 == 0
                recs.append({
                    "name": f"g{i % 3}", "val": float(i),
                    # expired rows are 2h old; fresh ones a few minutes
                    "dtg": now - (7_200_000 if not fresh else 120_000 + i),
                    "geom": Point(float(i % 50), 0.0),
                })
            ds.write("tt", recs, fids=[str(i) for i in range(400)])
            ds.compact("tt")
            if backend == "tpu":
                calls = {"q": 0}
                real = ds.query
                monkeypatch.setattr(
                    ds, "query",
                    lambda *a, **k: (calls.__setitem__("q", calls["q"] + 1),
                                    real(*a, **k))[1],
                )
            r = sql(ds, "SELECT name, COUNT(*) AS n, SUM(val) AS s FROM tt "
                        "GROUP BY name")
            if backend == "tpu":
                assert calls["q"] == 0, "TTL store fell back to the host fold"
                monkeypatch.undo()
            results[backend] = _sorted_rows(r)
        assert results["tpu"] == results["oracle"]
        assert len(results["tpu"]) == 3
        # only fresh rows counted
        assert sum(n for _, n, _ in results["tpu"]) == 200

    def test_ttl_ambiguous_unit_exact_ms(self):
        """Rows whose timestamp is below the cutoff but inside the SAME
        quantized (bin, offset) unit must not aggregate (exact-ms parity
        with the host fold — the device mask alone cannot decide them)."""
        from geomesa_tpu.schema.sft import parse_spec

        t0 = 1_500_000_000_000  # whole second = quantization boundary
        ttl = 3_600_000
        now_ms = t0 + ttl + 500  # cutoff lands mid-second at t0 + 500
        sft = parse_spec("ta", "name:String,val:Double,dtg:Date,*geom:Point")
        sft.user_data["geomesa.age.off"] = ttl
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        recs = []
        for i in range(200):
            if i % 2 == 0:  # fresh: 100ms after the cutoff, same second
                recs.append({"name": "g", "val": 1.0, "dtg": t0 + 600,
                             "geom": Point(1.0, 1.0)})
            else:  # expired by 400ms, SAME second as the cutoff
                recs.append({"name": "g", "val": 1000.0, "dtg": t0 + 100,
                             "geom": Point(1.0, 1.0)})
        ds.write("ta", recs, fids=[str(i) for i in range(200)])
        ds.compact("ta")
        out = ds.aggregate_many(
            "ta", [None], group_by=["name"], value_cols=["val"],
            now_ms=now_ms,
        )[0]
        assert out is not None
        assert int(out["count"].sum()) == 100  # expired half excluded
        assert float(out["cols"]["val"]["sum"][0]) == 100.0
        assert float(out["cols"]["val"]["max"][0]) == 1.0


class TestDistinctAndMultiOrder:
    def test_distinct_rides_mesh_with_parity(self, monkeypatch):
        """SELECT DISTINCT over plain columns is a GROUP BY with no
        aggregates: zero row materialization, host parity incl. first-
        occurrence order and LIMIT."""
        tpu = _mk("tpu")
        host = _mk("oracle")
        calls = {"q": 0}
        real = tpu.query
        monkeypatch.setattr(
            tpu, "query",
            lambda *a, **k: (calls.__setitem__("q", calls["q"] + 1),
                            real(*a, **k))[1],
        )
        for q in (
            "SELECT DISTINCT name FROM ev WHERE BBOX(geom, -50, -40, 10, -20)",
            "SELECT DISTINCT name, cnt FROM ev "
            "WHERE BBOX(geom, -30, -30, 30, 30)",
            "SELECT DISTINCT name FROM ev ORDER BY name DESC LIMIT 3",
        ):
            got = sql(tpu, q)
            assert calls["q"] == 0, f"DISTINCT materialized rows: {q}"
            want = sql(host, q)
            assert [tuple(r) for r in got.rows()] \
                == [tuple(r) for r in want.rows()], q

    def test_distinct_limit_first_occurrence_order(self):
        """LIMIT on un-ORDERed DISTINCT returns the FIRST-seen keys on both
        engines."""
        for backend in ("tpu", "oracle"):
            ds = DataStore(backend=backend)
            ds.create_schema("d", "name:String,*geom:Point")
            ds.write("d", [
                {"name": n, "geom": Point(float(i), 0.0)}
                for i, n in enumerate(["c", "a", "c", "b", "a", "d"])
            ], fids=[str(i) for i in range(6)])
            ds.compact("d")
            r = sql(ds, "SELECT DISTINCT name FROM d LIMIT 2")
            assert r.columns["name"].tolist() == ["c", "a"], backend

    def test_distinct_desc_tie_order_parity(self):
        """Descending sorts keep tied rows in first-occurrence order on
        BOTH engines (a naive argsort()[::-1] reverses ties and splits the
        engines under LIMIT)."""
        for backend in ("tpu", "oracle"):
            ds = DataStore(backend=backend)
            ds.create_schema("t2", "name:String,cnt:Integer,*geom:Point")
            ds.write("t2", [
                {"name": "a", "cnt": 1, "geom": Point(1.0, 0.0)},
                {"name": "a", "cnt": 2, "geom": Point(2.0, 0.0)},
                {"name": "b", "cnt": 5, "geom": Point(3.0, 0.0)},
            ], fids=["0", "1", "2"])
            ds.compact("t2")
            r = sql(ds, "SELECT DISTINCT name, cnt FROM t2 "
                        "ORDER BY name DESC LIMIT 2")
            assert [tuple(x) for x in r.rows()] == [("b", 5), ("a", 1)], backend

    def test_order_by_alias(self):
        for backend in ("tpu", "oracle"):
            ds = DataStore(backend=backend)
            ds.create_schema("al", "name:String,*geom:Point")
            ds.write("al", [
                {"name": n, "geom": Point(float(i), 0.0)}
                for i, n in enumerate("cab")
            ], fids=["0", "1", "2"])
            ds.compact("al")
            r = sql(ds, "SELECT name AS n FROM al ORDER BY n")
            assert r.columns["n"].tolist() == ["a", "b", "c"], backend
            r2 = sql(ds, "SELECT DISTINCT name AS n FROM al ORDER BY n DESC")
            assert r2.columns["n"].tolist() == ["c", "b", "a"], backend

    def test_empty_order_by_rejected(self):
        from geomesa_tpu.sql.engine import SqlError

        ds = _mk("tpu", n=50)
        with pytest.raises(SqlError, match="ORDER BY"):
            sql(ds, "SELECT name FROM ev ORDER BY , LIMIT 2")

    def test_multi_key_order_by(self):
        tpu = _mk("tpu")
        host = _mk("oracle")
        q = ("SELECT name, cnt, COUNT(*) AS n FROM ev "
             "WHERE BBOX(geom, -40, -30, 40, 30) "
             "GROUP BY name, cnt ORDER BY name ASC, cnt DESC LIMIT 12")
        got = [tuple(r) for r in sql(tpu, q).rows()]
        want = [tuple(r) for r in sql(host, q).rows()]
        assert got == want
        names = [r[0] for r in got]
        assert names == sorted(names)
        for nm in set(names):  # cnt strictly descending within each name
            cs = [r[1] for r in got if r[0] == nm]
            assert cs == sorted(cs, reverse=True)

    def test_multi_key_order_plain_select(self):
        tpu = _mk("tpu")
        host = _mk("oracle")
        q = ("SELECT name, cnt FROM ev WHERE BBOX(geom, -20, -20, 20, 20) "
             "ORDER BY name DESC, cnt ASC LIMIT 20")
        got = [tuple(r) for r in sql(tpu, q).rows()]
        assert got == [tuple(r) for r in sql(host, q).rows()]
        assert len(got) == 20

    def test_multi_key_order_on_unselected_column(self):
        """Multi-key sort keys may be schema columns outside the select
        list — they feed the sort, never the output."""
        tpu = _mk("tpu")
        host = _mk("oracle")
        q = ("SELECT name FROM ev WHERE BBOX(geom, -20, -20, 20, 20) "
             "ORDER BY cnt DESC, val ASC LIMIT 15")
        got = sql(tpu, q)
        assert list(got.columns) == ["name"]  # sort keys not in output
        assert [tuple(r) for r in got.rows()] \
            == [tuple(r) for r in sql(host, q).rows()]


class TestExtendedGeometryAggregation:
    def _mk(self, backend):
        from geomesa_tpu.geometry.types import LineString

        rng = np.random.default_rng(71)
        ds = DataStore(backend=backend)
        ds.create_schema("trk", "name:String,val:Double,dtg:Date,*geom:LineString")
        recs = []
        for i in range(1200):
            cx, cy = rng.uniform(-60, 60), rng.uniform(-45, 45)
            pts = np.stack([
                cx + np.cumsum(rng.normal(0, 0.05, 5)),
                cy + np.cumsum(rng.normal(0, 0.05, 5)),
            ], axis=1)
            recs.append({
                "name": f"g{i % 5}", "val": float(i % 90),
                "dtg": T0 + i * 1000, "geom": LineString(pts),
            })
        ds.write("trk", recs, fids=[str(i) for i in range(1200)])
        ds.compact("trk")
        return ds

    def test_xz_store_group_by_on_mesh(self, monkeypatch):
        """Extended-geometry (XZ bbox-layout) stores aggregate on the mesh
        via the int-bbox overlap fold, with host parity and zero row
        materialization."""
        tpu = self._mk("tpu")
        host = self._mk("oracle")
        calls = {"q": 0}
        real = tpu.query
        monkeypatch.setattr(
            tpu, "query",
            lambda *a, **k: (calls.__setitem__("q", calls["q"] + 1),
                            real(*a, **k))[1],
        )
        for q in (
            "SELECT name, COUNT(*) AS n, SUM(val) AS s FROM trk "
            "WHERE BBOX(geom, -40, -30, 20, 25) GROUP BY name",
            "SELECT name, MIN(val) AS lo, MAX(val) AS hi FROM trk "
            "GROUP BY name",
        ):
            got = _sorted_rows(sql(tpu, q))
            assert calls["q"] == 0, "extended-geometry agg materialized rows"
            assert got == _sorted_rows(sql(host, q)), q


class TestHostOrderParity:
    def test_group_order_is_first_matching_row(self):
        """Host fold orders groups by first occurrence among FILTERED rows;
        the mesh path must match exactly (observable through LIMIT)."""
        for backend in ("tpu", "oracle"):
            ds = DataStore(backend=backend)
            ds.create_schema("o", "name:String,dtg:Date,*geom:Point")
            # row0: group B OUTSIDE the bbox; row1: group A inside;
            # row2: group B inside → filtered first-occurrence order: A, B
            ds.write("o", [
                {"name": "B", "dtg": T0, "geom": Point(100.0, 40.0)},
                {"name": "A", "dtg": T0, "geom": Point(1.0, 1.0)},
                {"name": "B", "dtg": T0, "geom": Point(2.0, 2.0)},
                {"name": "C", "dtg": T0, "geom": Point(3.0, 3.0)},
            ], fids=["r0", "r1", "r2", "r3"])
            ds.compact("o")
            r = sql(ds, "SELECT name, COUNT(*) AS n FROM o "
                        "WHERE BBOX(geom, 0, 0, 50, 50) GROUP BY name")
            rows = [tuple(x) for x in r.rows()]
            assert rows == [("A", 1), ("B", 1), ("C", 1)], (backend, rows)
            r1 = sql(ds, "SELECT name, COUNT(*) AS n FROM o "
                         "WHERE BBOX(geom, 0, 0, 50, 50) GROUP BY name "
                         "LIMIT 1")
            assert [tuple(x) for x in r1.rows()] == [("A", 1)], backend

    def test_delta_only_group_orders_after_main(self):
        ds = DataStore(backend="tpu")
        ds.create_schema("o2", "name:String,dtg:Date,*geom:Point")
        ds.write("o2", [
            {"name": "M", "dtg": T0, "geom": Point(1.0, 1.0)},
        ], fids=["m0"])
        ds.compact("o2")
        ds.write("o2", [
            {"name": "D", "dtg": T0, "geom": Point(2.0, 2.0)},
        ], fids=["d0"])
        r = sql(ds, "SELECT name, COUNT(*) AS n FROM o2 GROUP BY name")
        assert [tuple(x) for x in r.rows()] == [("M", 1), ("D", 1)]

    def test_nan_group_keys_fall_back_with_host_semantics(self):
        """NaN GROUP BY keys: nan != nan, so the host fold gives each NaN
        row its own group — the mesh path must decline rather than collapse
        them."""
        for backend in ("tpu", "oracle"):
            ds = DataStore(backend=backend)
            ds.create_schema("nn", "v:Double,dtg:Date,*geom:Point")
            ds.write("nn", [
                {"v": float("nan"), "dtg": T0, "geom": Point(1.0, 1.0)},
                {"v": float("nan"), "dtg": T0, "geom": Point(2.0, 2.0)},
                {"v": 3.0, "dtg": T0, "geom": Point(3.0, 3.0)},
            ], fids=["a", "b", "c"])
            ds.compact("nn")
            r = sql(ds, "SELECT v, COUNT(*) AS n FROM nn GROUP BY v")
            assert len(r) == 3, backend  # two NaN groups + one value group
            assert sorted(r.columns["n"].tolist()) == [1, 1, 1]


class TestRemoteAggregation:
    def test_http_aggregate_parity_and_sql_over_remote(self):
        """The /aggregate endpoint ships per-group partials; a RemoteDataStore
        serves sql() GROUP BY with the owner's mesh doing the fold."""
        import threading
        from wsgiref.simple_server import make_server

        from geomesa_tpu.store.remote import RemoteDataStore
        from geomesa_tpu.web.app import GeoMesaApp

        local = _mk("tpu", n=2500)
        httpd = make_server("127.0.0.1", 0, GeoMesaApp(local))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            remote = RemoteDataStore(f"http://127.0.0.1:{port}")
            q = "BBOX(geom, -50, -40, 10, -20)"
            want = local.aggregate_many(
                "ev", [q], group_by=["name"], value_cols=["val"]
            )[0]
            got = remote.aggregate_many(
                "ev", [q], group_by=["name"], value_cols=["val"]
            )[0]
            assert got["groups"] == want["groups"]
            np.testing.assert_array_equal(got["count"], want["count"])
            np.testing.assert_allclose(
                got["cols"]["val"]["sum"], want["cols"]["val"]["sum"]
            )
            sql_q = ("SELECT name, COUNT(*) AS n, SUM(val) AS s FROM ev "
                     f"WHERE {q} GROUP BY name")
            assert _sorted_rows(sql(remote, sql_q)) \
                == _sorted_rows(sql(local, sql_q))
            # a declining query comes back as None over the wire too
            out = remote.aggregate_many(
                "ev", ["cnt >= 7"], group_by=["name"], value_cols=["val"]
            )
            assert out == [None]
            # a Query carrying auths/limit must decline LOCALLY — shipping
            # just its filter would aggregate over rows the caller may not
            # see (visibility) or drop limit semantics
            out = remote.aggregate_many(
                "ev",
                [Query(filter=q, auths=["secret"]), Query(filter=q, limit=3)],
                group_by=["name"], value_cols=["val"],
            )
            assert out == [None, None]
        finally:
            httpd.shutdown()


class TestMergedViewAggregation:
    def _pair(self, backend):
        a = _mk(backend, n=1500, seed=61)
        b = _mk(backend, n=1500, seed=62)
        from geomesa_tpu.store.merged import MergedDataStoreView

        return MergedDataStoreView([a, b])

    def test_federated_group_by_parity(self):
        """sql() GROUP BY over a merged view pushes per-member mesh folds
        and merges partials — parity with the all-host merged fold."""
        tpu = self._pair("tpu")
        host = self._pair("oracle")
        for q in (
            "SELECT name, COUNT(*) AS n, SUM(val) AS s, MIN(val) AS lo, "
            "MAX(val) AS hi FROM ev GROUP BY name",
            "SELECT name, COUNT(*) AS n FROM ev "
            "WHERE BBOX(geom, -50, -40, 10, -20) GROUP BY name",
            "SELECT COUNT(*) AS n, AVG(val) AS m FROM ev "
            "WHERE BBOX(geom, -20, -30, 40, 35)",
        ):
            assert _sorted_rows(sql(tpu, q)) == _sorted_rows(sql(host, q)), q

    def test_member_decline_declines_view(self):
        tpu = self._pair("tpu")
        out = tpu.aggregate_many(
            "ev", ["cnt >= 7"], group_by=["name"], value_cols=["val"]
        )
        assert out == [None]

    def test_scope_filters_apply(self):
        from geomesa_tpu.store.merged import MergedDataStoreView

        a = _mk("tpu", n=1500, seed=61)
        b = _mk("tpu", n=1500, seed=62)
        view = MergedDataStoreView([
            (a, "BBOX(geom, -60, -45, 0, 0)"),
            (b, "BBOX(geom, 0, 0, 60, 45)"),
        ])
        out = view.aggregate_many("ev", [None], group_by=["name"])[0]
        assert out is not None
        want = view.stats_count("ev", None, exact=True)
        assert int(out["count"].sum()) == int(want)


class TestMeshAggFuzz:
    def test_random_queries_parity(self):
        """Property fuzz: random bbox/time filters x random group/value
        column combinations agree with the host fold exactly."""
        rng = np.random.default_rng(99)
        tpu = _mk("tpu", n=3000, seed=31)
        host = _mk("oracle", n=3000, seed=31)
        aggs = ["COUNT(*) AS c", "SUM(val) AS s", "MIN(cnt) AS lo",
                "MAX(val) AS hi", "AVG(cnt) AS m", "COUNT(val) AS nv"]
        for trial in range(12):
            x1 = rng.uniform(-60, 40)
            y1 = rng.uniform(-45, 30)
            w = rng.uniform(5, 70)
            h = rng.uniform(5, 50)
            where = f"BBOX(geom, {x1}, {y1}, {x1 + w}, {y1 + h})"
            if trial % 3 == 0:
                t_lo = T0 + int(rng.integers(0, 2 * 86_400_000))
                import datetime as _dt

                iso = _dt.datetime.fromtimestamp(
                    t_lo / 1000, _dt.timezone.utc
                ).strftime("%Y-%m-%dT%H:%M:%SZ")
                iso2 = _dt.datetime.fromtimestamp(
                    (t_lo + 86_400_000) / 1000, _dt.timezone.utc
                ).strftime("%Y-%m-%dT%H:%M:%SZ")
                where += f" AND dtg DURING {iso}/{iso2}"
            picks = rng.choice(len(aggs), size=2, replace=False)
            group = ["name", "cnt"][: int(rng.integers(1, 3))]
            sel = ", ".join([*group, *(aggs[i] for i in picks)])
            q = (f"SELECT {sel} FROM ev WHERE {where} "
                 f"GROUP BY {', '.join(group)}")
            assert _sorted_rows(sql(tpu, q)) == _sorted_rows(sql(host, q)), q


class TestMeshAggConcurrency:
    def test_aggregate_during_writes_and_compactions(self):
        """aggregate_many stays coherent while a background thread writes
        and compacts: counts never regress below the initial row count and
        never exceed the final one."""
        import threading

        ds = _mk("tpu", n=2000, seed=41)
        stop = threading.Event()
        errs: list = []

        def churn():
            i = 0
            try:
                while not stop.is_set():
                    ds.write("ev", [{
                        "name": f"g{i % 7}", "val": 1.0, "cnt": 1,
                        "dtg": T0, "geom": Point(0.0, 0.0),
                    }], fids=[f"x{i}"])
                    if i % 10 == 0:
                        ds.compact("ev")
                    i += 1
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=churn)
        t.start()
        try:
            lo = 2000
            for _ in range(30):
                out = ds.aggregate_many(
                    "ev", [None], group_by=["name"], value_cols=["val"]
                )[0]
                if out is None:
                    continue
                total = int(out["count"].sum())
                assert total >= lo
        finally:
            stop.set()
            t.join(timeout=30)
        assert not errs, errs
        hi = ds.stats_count("ev")
        out = ds.aggregate_many("ev", [None], group_by=["name"])[0]
        assert out is not None and int(out["count"].sum()) == hi


class TestAggregateManyApi:
    def test_direct_api_shapes(self):
        ds = _mk("tpu")
        out = ds.aggregate_many(
            "ev", [None, "BBOX(geom, -50, -40, 10, -20)"],
            group_by=["name"], value_cols=["val", "cnt"],
        )
        assert len(out) == 2
        for r in out:
            assert r is not None
            G = len(r["groups"])
            assert r["count"].shape == (G,)
            for c in ("val", "cnt"):
                for k in ("count", "sum", "min", "max"):
                    assert r["cols"][c][k].shape == (G,)
            assert (r["count"] > 0).all()

    def test_date_aggregation_int_result(self):
        ds = _mk("tpu")
        host = _mk("oracle")
        q = "SELECT MIN(dtg) AS lo, MAX(dtg) AS hi FROM ev"
        got = sql(ds, q).rows()
        want = sql(host, q).rows()
        assert got == want
        assert isinstance(got[0][0], int)

    def test_nonbatchable_queries_return_none(self):
        ds = _mk("tpu")
        out = ds.aggregate_many(
            "ev", ["cnt >= 7"], group_by=["name"], value_cols=["val"],
        )
        assert out == [None]
