"""R002 known-good twin: the same call-graph shape, but every path agrees
on one global order (``_ingest`` before ``_flush``)."""

import threading


class Pipeline:
    def __init__(self):
        self._ingest = threading.Lock()
        self._flush = threading.Lock()

    def ingest(self, batch):
        with self._ingest:
            self._drain(batch)

    def _drain(self, batch):
        with self._flush:
            return list(batch)

    def flush(self):
        with self._ingest:
            with self._flush:
                return None
