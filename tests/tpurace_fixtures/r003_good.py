"""R003 known-good twin: the sleep and the write happen OUTSIDE the
critical section; the lock guards only the counter update."""

import threading
import time


class Courier:
    def __init__(self):
        self._lock = threading.Lock()
        self._sent = 0

    def send(self, path, payload):
        time.sleep(0.01)
        with open(path, "wb") as f:
            f.write(payload)
        with self._lock:
            self._sent += 1

    def count(self):
        with self._lock:
            return self._sent
