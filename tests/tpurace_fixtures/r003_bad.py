"""R003 known-bad: ``send`` sleeps and does file I/O inside the lock's
critical section — every other thread queues behind a disk write."""

import threading
import time


class Courier:
    def __init__(self):
        self._lock = threading.Lock()
        self._sent = 0

    def send(self, path, payload):
        with self._lock:
            time.sleep(0.01)
            with open(path, "wb") as f:
                f.write(payload)
            self._sent += 1

    def count(self):
        with self._lock:
            return self._sent
