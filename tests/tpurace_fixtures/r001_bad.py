"""R001 known-bad: ``Registry._items``/``_epoch`` are lock-guarded in the
majority of writes, but written bare in ``evict``/``bump`` and through a
TYPED cross-class reference in ``Admin.wipe`` (the inter-procedural case
a single-file rule cannot see)."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._epoch = 0

    def put(self, k, v):
        with self._lock:
            self._items[k] = v
            self._epoch += 1

    def replace(self, items):
        with self._lock:
            self._items = dict(items)
            self._epoch += 1

    def _rebuild_locked(self):
        self._items.clear()

    def evict(self, k):
        self._items.pop(k, None)

    def bump(self):
        self._epoch += 1


class Admin:
    def __init__(self, reg: Registry):
        self.reg = reg

    def wipe(self):
        self.reg._items = {}
