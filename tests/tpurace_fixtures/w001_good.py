"""W001 known-good twin: the waiver suppresses a REAL R001 (intentional
lock-free publication), so it is live, not stale."""

import threading


class Loud:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def a(self):
        with self._lock:
            self._n += 1

    def b(self):
        with self._lock:
            self._n += 1

    def c(self):
        # monotonic hint only; torn reads are acceptable by design
        self._n += 1  # tpurace: disable=R001
