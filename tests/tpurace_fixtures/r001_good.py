"""R001 known-good twin: every tracked write holds the inferred guard —
including the ``*_locked`` helper (caller-holds-lock convention) and the
typed cross-class write, which takes the lock properly."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._epoch = 0

    def put(self, k, v):
        with self._lock:
            self._items[k] = v
            self._epoch += 1

    def replace(self, items):
        with self._lock:
            self._items = dict(items)
            self._epoch += 1

    def _rebuild_locked(self):
        self._items.clear()

    def evict(self, k):
        with self._lock:
            self._items.pop(k, None)

    def bump(self):
        with self._lock:
            self._epoch += 1


class Admin:
    def __init__(self, reg: Registry):
        self.reg = reg

    def wipe(self):
        with self.reg._lock:
            self.reg._items = {}
