"""W001 known-bad: both waivers suppress nothing — the R001 waiver sits
on a properly-locked write, the R003 waiver on a line with no blocking
call. Dead waivers are themselves violations."""

import threading


class Quiet:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1  # tpurace: disable=R001

    def also(self):
        with self._lock:
            # tpurace: disable-next-line=R003
            self._n += 1
