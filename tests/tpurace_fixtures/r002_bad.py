"""R002 known-bad: ``ingest`` nests ``_ingest`` → ``_flush`` ONLY through
the call graph (``_drain``), while ``flush`` nests ``_flush`` →
``_ingest`` directly — a cycle no single ``with`` block shows."""

import threading


class Pipeline:
    def __init__(self):
        self._ingest = threading.Lock()
        self._flush = threading.Lock()

    def ingest(self, batch):
        with self._ingest:
            self._drain(batch)

    def _drain(self, batch):
        with self._flush:
            return list(batch)

    def flush(self):
        with self._flush:
            with self._ingest:
                return None
