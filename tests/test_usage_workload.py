"""The usage & workload plane (ISSUE 11, docs/observability.md § Usage
metering & workload replay): tenant-attributed metering accuracy, the
SpaceSaving heavy-hitter error bound and prometheus label-cardinality
cap, workload capture → deterministic replay round-trips with row-count
parity, tenant propagation across a 2-member federated view, cost-model
persistence, and the <2% always-on overhead bound with capture AND
metering enabled on the cached-jit select path.

Doubles as the CI usage/workload gate in scripts/lint.sh; also rides the
lock-order sanitizer subset (the usage meter and workload journal locks
are documented leaves — docs/concurrency.md).
"""

import json
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import obs
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.obs import flight as obs_flight
from geomesa_tpu.obs import replay as obs_replay
from geomesa_tpu.obs import usage as obs_usage
from geomesa_tpu.obs import workload as obs_workload
from geomesa_tpu.obs.flight import FlightRecorder
from geomesa_tpu.obs.slo import SloEngine
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.resilience.policy import RetryPolicy
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.merged import MergedDataStoreView
from geomesa_tpu.store.remote import RemoteDataStore
from geomesa_tpu.web.app import GeoMesaApp

T0 = 1_500_000_000_000
CQL = "BBOX(geom,-50,-40,50,40)"


@pytest.fixture(autouse=True)
def _iso():
    """Fresh meter, disabled journal, fresh flight recorder per test —
    the usage/workload singletons are process-global accumulators."""
    prev_meter = obs_usage.install(obs_usage.UsageMeter(k=8))
    prev_journal = obs_workload.install(None)
    prev_rec = obs_flight.install(
        FlightRecorder(dump_dir=None, min_dump_interval_s=0.0))
    yield
    obs_usage.install(prev_meter)
    obs_workload.install(prev_journal)
    obs_flight.install(prev_rec)


def _filled_store(seed=1, n=200, name="pts"):
    rng = np.random.default_rng(seed)
    ds = DataStore(backend="tpu")
    ds.create_schema(name, "name:String,dtg:Date,*geom:Point")
    ds.write(name, [
        {"name": f"n{i % 5}", "dtg": T0 + i * 1000,
         "geom": Point(float(rng.uniform(-170, 170)),
                       float(rng.uniform(-40, 40)))}
        for i in range(n)
    ], fids=[f"{seed}-{i}" for i in range(n)])
    ds.compact(name)
    return ds


# ---------------------------------------------------------------------------
# SpaceSaving sketch: error bound + monitoring guarantee
# ---------------------------------------------------------------------------

class TestSpaceSaving:
    def test_heavy_hitters_monitored_and_error_bounded(self):
        """The classic SpaceSaving guarantees on a skewed stream: every
        key with true weight > W/K is monitored, and each reported count
        lies in [true, true + error] with error <= W/K."""
        rng = np.random.default_rng(3)
        k = 8
        s = obs_usage.SpaceSaving(k)
        true: dict = {}
        # 4 heavy keys + a long tail of 200 singletons
        stream = (["h0"] * 400 + ["h1"] * 300 + ["h2"] * 200 + ["h3"] * 150
                  + [f"t{i}" for i in range(200)])
        rng.shuffle(stream)
        for key in stream:
            s.offer(key, 1.0)
            true[key] = true.get(key, 0) + 1
        W = s.total
        assert W == len(stream)
        top = {key: (c, err) for key, c, err in s.top()}
        for hk in ("h0", "h1", "h2", "h3"):
            assert hk in top, f"heavy hitter {hk} not monitored"
            c, err = top[hk]
            assert err <= W / k + 1e-9
            assert true[hk] <= c <= true[hk] + err + 1e-9
        # capacity is exact
        assert len(s.top()) == k

    def test_weighted_offers(self):
        s = obs_usage.SpaceSaving(4)
        s.offer(("acme", "pts", "z3:rows"), 120.0)
        s.offer(("globex", "pts", "z3:rows"), 5.0)
        s.offer(("acme", "pts", "z3:rows"), 80.0)
        top = s.top(1)
        assert top[0][0] == ("acme", "pts", "z3:rows")
        assert top[0][1] == pytest.approx(200.0)


# ---------------------------------------------------------------------------
# metering accuracy + bounded label cardinality
# ---------------------------------------------------------------------------

class TestMeterAccuracy:
    def test_hand_counted_totals(self):
        """Lifetime and window counters match exactly what was fed."""
        clk = [1000.0]
        m = obs_usage.UsageMeter(k=4, clock=lambda: clk[0])
        expect: dict = {}
        rng = np.random.default_rng(5)
        for i in range(60):
            t = f"t{i % 3}"
            rows = int(rng.integers(0, 50))
            wall = float(rng.uniform(0.5, 9.0))
            m.observe(t, "pts", "z3:rows", rows=rows, wall_ms=wall,
                      device_ms=wall / 2)
            e = expect.setdefault(t, [0, 0, 0, 0.0, 0.0])
            e[0] += 1
            e[1] += rows
            e[3] += wall
            e[4] += wall / 2
            clk[0] += 1.0
        m.note_bytes_out("t0", 12345)
        expect["t0"][2] += 12345
        snap = m.snapshot()
        assert snap["tenant_count"] == 3
        by_tenant = {t["tenant"]: t for t in snap["tenants"]}
        for t, e in expect.items():
            life = by_tenant[t]["lifetime"]
            assert life["queries"] == e[0]
            assert life["rows"] == e[1]
            assert life["bytes_out"] == e[2]
            assert life["wall_ms"] == pytest.approx(e[3])
            assert life["device_ms"] == pytest.approx(e[4])
            # everything happened within the 1h window
            w1h = by_tenant[t]["windows"]["1h"]
            assert w1h["queries"] == e[0]
            assert w1h["wall_ms"] == pytest.approx(e[3])

    def test_default_tenant_for_anonymous(self):
        m = obs_usage.UsageMeter(k=4)
        m.observe(None, "pts", "z3:rows", rows=1, wall_ms=1.0)
        snap = m.snapshot()
        assert snap["tenants"][0]["tenant"] == obs_usage.DEFAULT_TENANT

    def test_prometheus_cardinality_capped_and_reconciles(self):
        """More tenants than K: the scrape holds exactly K+1 label values
        per tenant metric, and the sum across all labels (top-K + other)
        equals the true total — nothing is lost in the rollup."""
        m = obs_usage.UsageMeter(k=4)
        for i in range(20):
            m.observe(f"t{i:02d}", "pts", "z3:rows", rows=2,
                      wall_ms=float(i + 1))
        lines = m.prometheus_lines()
        qlines = [ln for ln in lines
                  if ln.startswith("geomesa_tenant_queries_total{")]
        assert len(qlines) == m.k + 1
        assert sum(1 for ln in qlines if 'tenant="other"' in ln) == 1
        total = sum(float(ln.rsplit(" ", 1)[1]) for ln in qlines)
        assert total == 20
        # rows reconcile too
        rlines = [ln for ln in lines
                  if ln.startswith("geomesa_tenant_rows_total{")]
        assert sum(float(ln.rsplit(" ", 1)[1]) for ln in rlines) == 40

    def test_tenant_table_bounded_eviction_folds_into_other(self):
        m = obs_usage.UsageMeter(k=2, max_tenants=4)
        for i in range(10):
            m.observe(f"t{i}", "pts", "sig", rows=1, wall_ms=1.0)
        snap = m.snapshot()
        assert snap["tenant_count"] <= 4
        # nothing lost: tracked lifetimes + other rollup = 10 queries
        tracked = sum(t["lifetime"]["queries"] for t in snap["tenants"])
        assert tracked + snap["other_lifetime"]["queries"] == 10
        # the SLO engine is bounded by the same cap: evicted tenants
        # drop their trackers, so an unbounded tenant-id stream cannot
        # grow the engine or its exposition
        assert len(m.slo.trackers()) <= 4

    def test_slo_lines_use_distinct_metric_names(self):
        """The meter's per-tenant SLO gauges ride the scrape under their
        OWN names (geomesa_tenant_slo_*) — a second # TYPE header for
        geomesa_slo_burn_rate (the store engine's name) would make
        strict text-format consumers reject the whole payload."""
        m = obs_usage.UsageMeter(k=4)
        m.observe("acme", "pts", "sig", rows=1, wall_ms=1.0)
        lines = m.prometheus_lines()
        assert any(
            ln.startswith("geomesa_tenant_slo_burn_rate") for ln in lines)
        assert not any(
            ln.startswith(("geomesa_slo_burn_rate",
                           "# TYPE geomesa_slo_burn_rate"))
            for ln in lines)

    def test_client_controlled_tenant_escaped_in_exposition(self):
        """A tenant id with quotes/backslashes/newlines (the header is
        client-controlled) must not malform the scrape — every emitted
        line still parses as name{labels} value."""
        evil = 'evil"} 1\nback\\slash'
        m = obs_usage.UsageMeter(k=4)
        m.observe(evil, "pts", "sig", rows=1, wall_ms=1.0)

        def label_value(ln):
            """Escape-aware scan of the first label value (the
            exposition-spec parse a real consumer does)."""
            i = ln.index('="') + 2
            out = []
            while True:
                c = ln[i]
                if c == "\\":
                    out.append({"\\": "\\", '"': '"', "n": "\n"}[ln[i + 1]])
                    i += 2
                    continue
                if c == '"':
                    return "".join(out), ln[i + 1:]
                out.append(c)
                i += 1

        lines = [ln for ln in m.prometheus_lines()
                 if not ln.startswith("#")]
        assert lines
        for ln in lines:
            assert "\n" not in ln  # a raw newline would split the line
            value, rest = label_value(ln)
            # round-trip: the consumer recovers the exact tenant id, and
            # the remainder is a well-formed close + sample value
            assert value in (evil, obs_usage.UsageMeter.OTHER)
            assert rest.startswith("}") or rest.startswith(',window="')

    def test_tenant_slo_series_bounded_by_k(self):
        """The K+1 cardinality bound covers the geomesa_tenant_slo_*
        gauges too, not just the counters."""
        m = obs_usage.UsageMeter(k=4)
        for i in range(30):
            m.observe(f"t{i:02d}", "pts", "sig", rows=1,
                      wall_ms=float(i + 1))
        lines = m.prometheus_lines()
        tenants = set()
        for ln in lines:
            if ln.startswith("#"):
                continue
            start = ln.index('tenant="') + len('tenant="')
            tenants.add(ln[start:ln.index('"', start)])
        assert len(tenants) <= m.k + 1
        slo_lines = [ln for ln in lines
                     if ln.startswith("geomesa_tenant_slo_burn_rate{")]
        assert 0 < len(slo_lines) <= m.k * 2  # K tenants x 2 windows

    def test_timed_out_queries_meter_against_tenant(self):
        """A deadline-shed query never reaches _audit but must still
        burn the tenant's accounting — the heaviest tenants are exactly
        the ones that time out."""
        from geomesa_tpu.utils.timeouts import Deadline, QueryTimeout

        ds = _filled_store()
        with pytest.raises(QueryTimeout):
            ds.query("pts", Query(
                filter=CQL,
                hints={"tenant": "hog", "deadline": Deadline.after_ms(-1)}))
        snap = obs_usage.get().snapshot()
        by_tenant = {t["tenant"]: t for t in snap["tenants"]}
        assert by_tenant["hog"]["lifetime"]["queries"] == 1
        tk = obs_usage.get().slo.tracker("tenant.query", "hog")
        assert tk.burn_rate(300.0) > 0  # ok=False burned the budget

    def test_store_query_meters_tenant_rows(self):
        """End to end through DataStore._audit: per-tenant query and row
        totals match the hand-counted query results."""
        ds = _filled_store()
        counts = {}
        for i, tenant in enumerate(["acme", "globex", "acme"]):
            q = Query(filter=CQL, hints={"tenant": tenant})
            r = ds.query("pts", q)
            counts[tenant] = counts.get(tenant, 0) + r.count
        snap = obs_usage.get().snapshot()
        by_tenant = {t["tenant"]: t for t in snap["tenants"]}
        assert by_tenant["acme"]["lifetime"]["queries"] == 2
        assert by_tenant["globex"]["lifetime"]["queries"] == 1
        assert by_tenant["acme"]["lifetime"]["rows"] == counts["acme"]
        assert by_tenant["globex"]["lifetime"]["rows"] == counts["globex"]
        # the flight record carries the same tenant + a plan signature
        recs = obs_flight.get().records()
        assert recs[-1].tenant in ("acme", "globex")
        assert recs[-1].plan_signature
        # heavy hitters key by (tenant, type, signature)
        hh = snap["heavy_hitters"]
        assert any(h["tenant"] == "acme" and h["type"] == "pts"
                   for h in hh)

    def test_tenant_context_fallback(self):
        """No hint: the request-scoped context attributes the query (the
        web layer's binding); outside any context the default applies."""
        ds = _filled_store()
        with obs_usage.tenant_context("ctx-tenant"):
            ds.query("pts", CQL)
        ds.query("pts", CQL)
        snap = obs_usage.get().snapshot()
        by_tenant = {t["tenant"]: t for t in snap["tenants"]}
        assert by_tenant["ctx-tenant"]["lifetime"]["queries"] == 1
        assert by_tenant[obs_usage.DEFAULT_TENANT]["lifetime"]["queries"] == 1


# ---------------------------------------------------------------------------
# workload capture → deterministic replay
# ---------------------------------------------------------------------------

class TestCaptureReplay:
    def test_capture_replay_round_trip_row_parity(self, tmp_path):
        """The acceptance pin: a captured workload replayed closed-loop
        reproduces byte-identical row counts per query and emits a
        recorded-vs-replayed p50/p95 report per plan signature."""
        obs_workload.install(obs_workload.WorkloadJournal(str(tmp_path)))
        ds = _filled_store()
        filters = [CQL, "BBOX(geom,-170,-40,0,40)", "name = 'n1'", None]
        recorded_rows = []
        for i in range(12):
            q = Query(filter=filters[i % 4],
                      hints={"tenant": f"t{i % 2}"})
            recorded_rows.append(ds.query("pts", q).count)
        obs_workload.flush()

        events = obs_replay.load_events(str(tmp_path))
        assert len(events) == 12
        # deterministic order: seq strictly increasing, arrival sorted
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == 12
        arrivals = [e["ts_arrival"] for e in events]
        assert arrivals == sorted(arrivals)
        assert [e["rows"] for e in events] == recorded_rows
        assert all(e["plan_signature"] for e in events)
        assert all(e["tenant"] in ("t0", "t1") for e in events)

        doc = obs_replay.run(ds, str(tmp_path))
        assert doc["parity_ok"], doc["row_mismatches"]
        assert doc["events"] == 12
        assert doc["errors"] == 0
        for sig, s in doc["signatures"].items():
            assert s["parity"]
            assert s["recorded_ms"]["p50"] >= 0
            assert s["replayed_ms"]["p50"] > 0
            assert s["recorded_ms"]["p95"] >= s["recorded_ms"]["p50"]
        # the report loads as a bench --regress baseline shape
        assert all(
            "value" in c and c["unit"] == "ms/query"
            for c in doc["configs"].values()
        )

    def test_replay_tenant_filter_and_attribution(self, tmp_path):
        """--tenant replays one tenant's slice, and replayed queries
        re-attribute to the recorded tenant (metering + flight)."""
        obs_workload.install(obs_workload.WorkloadJournal(str(tmp_path)))
        ds = _filled_store()
        for i in range(8):
            ds.query("pts", Query(filter=CQL,
                                  hints={"tenant": f"t{i % 2}"}))
        obs_workload.flush()
        obs_usage.install(obs_usage.UsageMeter(k=8))  # reset the meter
        events = obs_replay.load_events(str(tmp_path), tenant="t1")
        assert len(events) == 4
        outcomes = obs_replay.replay(ds, events)
        assert all(o["parity"] for o in outcomes)
        snap = obs_usage.get().snapshot()
        by_tenant = {t["tenant"]: t for t in snap["tenants"]}
        assert by_tenant["t1"]["lifetime"]["queries"] == 4
        assert "t0" not in by_tenant

    def test_open_loop_pacing_honors_speed(self, tmp_path):
        """Open-loop replay sleeps recorded inter-arrival / speed."""
        events = [
            {"seq": 1, "ts_arrival": 100.0, "op": "query", "type": "pts",
             "filter": None, "latency_ms": 1.0, "rows": 0},
            {"seq": 2, "ts_arrival": 101.0, "op": "query", "type": "pts",
             "filter": None, "latency_ms": 1.0, "rows": 0},
            {"seq": 3, "ts_arrival": 103.0, "op": "query", "type": "pts",
             "filter": None, "latency_ms": 1.0, "rows": 0},
        ]

        class _Store:
            def query(self, name, q):
                class R:
                    count = 0
                return R()

        sleeps = []
        clock = [0.0]

        def fake_sleep(s):
            sleeps.append(s)
            clock[0] += s

        obs_replay.replay(_Store(), events, speed=2.0,
                          _sleep=fake_sleep, _clock=lambda: clock[0])
        # inter-arrivals 1s and 2s at speed 2 → due at 0.5s and 1.5s
        assert sleeps == [pytest.approx(0.5), pytest.approx(1.0)]

    def test_rotation_bounded_and_readable(self, tmp_path):
        j = obs_workload.WorkloadJournal(str(tmp_path), max_bytes=4096,
                                         max_files=3, flush_every=8)
        for i in range(600):
            j.append({"ts_arrival": float(i), "op": "query",
                      "type": "pts", "pad": "x" * 64})
        j.flush()
        files = j.files()
        assert 1 <= len(files) <= 3
        import os

        for f in files:
            assert os.path.getsize(f) <= 4096 + 100 * 8  # cap + one batch
        events = obs_workload.read_events(str(tmp_path))
        assert events, "rotation lost everything"
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        # the newest events survive rotation
        assert seqs[-1] == 600

    def test_replay_does_not_recapture_into_the_journal(self, tmp_path):
        """Replaying while capture is enabled (the documented runbook
        state) must not append the replayed queries back onto the
        recording being read."""
        obs_workload.install(obs_workload.WorkloadJournal(str(tmp_path)))
        ds = _filled_store()
        for _ in range(3):
            ds.query("pts", Query(filter=CQL, hints={"tenant": "t0"}))
        obs_workload.flush()
        assert len(obs_workload.read_events(str(tmp_path))) == 3
        doc = obs_replay.run(ds, str(tmp_path))
        assert doc["parity_ok"]
        obs_workload.flush()
        assert len(obs_workload.read_events(str(tmp_path))) == 3
        # capture resumes after the replay (the journal is restored)
        ds.query("pts", Query(filter=CQL, hints={"tenant": "t0"}))
        obs_workload.flush()
        assert len(obs_workload.read_events(str(tmp_path))) == 4

    def test_aggregation_hinted_events_abstain_from_parity(self):
        """A density audit records grid mass, not row count — replaying
        it compares latency but must not manufacture a parity failure."""

        class _Store:
            def query(self, name, q):
                class R:
                    count = 0  # density results carry no row table
                return R()

        events = [{"seq": 1, "op": "query", "type": "pts", "filter": None,
                   "hints": {"density": {"width": 8, "height": 8}},
                   "latency_ms": 1.0, "rows": 57}]
        outcomes = obs_replay.replay(_Store(), events)
        assert outcomes[0]["parity"] is None
        doc = obs_replay.report(events, outcomes)
        assert doc["parity_ok"] is True
        assert not doc["row_mismatches"]

    def test_read_events_ignores_reader_side_rotation_config(self, tmp_path):
        """Reading globs EVERY rotated file on disk — a capture written
        with a larger max_files than the reader's env must not silently
        lose its oldest rotations."""
        j = obs_workload.WorkloadJournal(str(tmp_path), max_bytes=4096,
                                         max_files=8, flush_every=4)
        for i in range(400):
            j.append({"ts_arrival": float(i), "op": "query",
                      "type": "pts", "pad": "x" * 64})
        j.flush()
        assert len(j.files()) > 4  # writer really rotated past 4 files
        # reader with DEFAULT (smaller) config still sees everything
        events = obs_workload.read_events(str(tmp_path))
        assert {e["seq"] for e in events} == {
            e["seq"]
            for p in j.files()
            for e in obs_workload.read_events(p)
        }
        assert len(events) > 100

    def test_empty_replay_never_reads_as_pass(self):
        doc = obs_replay.report([], [], mode="closed-loop")
        assert doc["events"] == 0
        assert doc["parity_ok"] is False

    def test_remote_replay_skips_unforwardable_events(self):
        """--url mode: events carrying hints (beyond tenant) or auths
        can't round-trip over the RemoteDataStore surface — they skip
        with a reason instead of manufacturing parity failures."""

        class _Store:
            def query(self, name, q):
                class R:
                    count = 3
                return R()

        events = [
            {"seq": 1, "op": "query", "type": "pts", "filter": None,
             "hints": {"density": {"width": 8}}, "latency_ms": 1.0,
             "rows": 5},
            {"seq": 2, "op": "query", "type": "pts", "filter": None,
             "hints": None, "auths": ["s"], "latency_ms": 1.0, "rows": 5},
            {"seq": 3, "op": "query", "type": "pts", "filter": None,
             "hints": {"tenant": "acme"}, "latency_ms": 1.0, "rows": 3},
        ]
        outcomes = obs_replay.replay(_Store(), events, remote=True)
        assert "skipped" in outcomes[0] and "density" in outcomes[0]["skipped"]
        assert "skipped" in outcomes[1] and "auths" in outcomes[1]["skipped"]
        assert outcomes[2].get("parity") is True
        doc = obs_replay.report(events, outcomes)
        assert doc["events"] == 1 and doc["skipped"] == 2
        assert doc["parity_ok"] is True

    def test_unreplayable_hints_dropped(self, tmp_path):
        from geomesa_tpu.utils.timeouts import Deadline

        obs_workload.install(obs_workload.WorkloadJournal(str(tmp_path)))
        ds = _filled_store()
        q = Query(filter=CQL, hints={"tenant": "t0", "loose_bbox": True,
                                     "deadline": Deadline.after_ms(60000)})
        ds.query("pts", q)
        obs_workload.flush()
        (e,) = obs_replay.load_events(str(tmp_path))
        assert "deadline" not in (e["hints"] or {})
        assert e["hints"]["loose_bbox"] is True


# ---------------------------------------------------------------------------
# tenant propagation across a federated view (2 live HTTP members)
# ---------------------------------------------------------------------------

def _serve(app):
    from wsgiref.simple_server import WSGIRequestHandler, make_server

    class _Quiet(WSGIRequestHandler):
        def log_message(self, *a):
            pass

    httpd = make_server("127.0.0.1", 0, app, handler_class=_Quiet)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


class TestFederatedTenantPropagation:
    def test_tenant_propagates_to_member_flight_records(self):
        """A federated query under a tenant context: the outbound RPCs
        carry X-Geomesa-Tenant (resilience/http.py choke point), the
        member web layer re-binds it, and the member-side store audit
        records attribute to the ORIGINAL tenant."""
        servers = []
        try:
            members = []
            for seed in (1, 2):
                store = _filled_store(seed=seed)
                httpd, url = _serve(GeoMesaApp(store))
                servers.append(httpd)
                members.append(RemoteDataStore(
                    url, retry=RetryPolicy(max_attempts=1)))
            view = MergedDataStoreView(members)
            with obs_usage.tenant_context("fed-tenant"):
                res = view.query("pts", CQL)
            assert res.count > 0
            recs = obs_flight.get().records()
            store_recs = [r for r in recs if r.source == "store"]
            fed_recs = [r for r in recs if r.source == "federation"]
            # both member stores audited with the propagated tenant
            assert len(store_recs) >= 2
            assert all(r.tenant == "fed-tenant" for r in store_recs)
            assert len(fed_recs) == 1 and fed_recs[0].tenant == "fed-tenant"
            # metering: member legs + the view-level record all attribute
            snap = obs_usage.get().snapshot()
            by_tenant = {t["tenant"]: t for t in snap["tenants"]}
            assert by_tenant["fed-tenant"]["lifetime"]["queries"] >= 3
        finally:
            for s in servers:
                s.shutdown()

    def test_web_endpoints_tenant_header_and_obs_tenants(self):
        """X-Geomesa-Tenant on a plain query attributes server-side; the
        /api/obs/tenants and filtered /api/obs/flight surfaces serve it."""
        store = _filled_store()
        httpd, url = _serve(GeoMesaApp(store))
        try:
            req = urllib.request.Request(
                url + "/api/schemas/pts/query?cql="
                + urllib.parse.quote(CQL),
                headers={"X-Geomesa-Tenant": "hdr-tenant"})
            with urllib.request.urlopen(req, timeout=10) as r:
                json.load(r)
            # headerless traffic accrues bytes under the default tenant
            with urllib.request.urlopen(
                    url + "/api/schemas/pts/query?cql="
                    + urllib.parse.quote(CQL), timeout=10) as r:
                json.load(r)
            anon = obs_usage.get().snapshot()
            anon_row = {t["tenant"]: t for t in anon["tenants"]}[
                obs_usage.DEFAULT_TENANT]
            assert anon_row["lifetime"]["bytes_out"] > 0
            with urllib.request.urlopen(
                    url + "/api/obs/tenants", timeout=10) as r:
                doc = json.load(r)
            names = [t["tenant"] for t in doc["tenants"]]
            assert "hdr-tenant" in names
            by = {t["tenant"]: t for t in doc["tenants"]}
            assert by["hdr-tenant"]["lifetime"]["queries"] == 1
            assert by["hdr-tenant"]["lifetime"]["bytes_out"] > 0
            # flight filter: only this tenant's records come back
            with urllib.request.urlopen(
                    url + "/api/obs/flight?tenant=hdr-tenant",
                    timeout=10) as r:
                fl = json.load(r)
            assert fl["records"]
            assert all(rec["tenant"] == "hdr-tenant"
                       for rec in fl["records"])
            # prometheus: geomesa_tenant_* series present, K+1 bound holds
            with urllib.request.urlopen(
                    url + "/api/metrics?format=prometheus",
                    timeout=10) as r:
                text = r.read().decode()
            qlines = [ln for ln in text.splitlines()
                      if ln.startswith("geomesa_tenant_queries_total{")]
            assert any('tenant="hdr-tenant"' in ln for ln in qlines)
            assert len(qlines) <= obs_usage.get().k + 1
            # one # TYPE header per metric name across the WHOLE payload
            # (strict text-format consumers reject duplicates wholesale)
            type_lines = [ln for ln in text.splitlines()
                          if ln.startswith("# TYPE ")]
            names = [ln.split()[2] for ln in type_lines]
            assert len(names) == len(set(names)), sorted(
                n for n in names if names.count(n) > 1)
        finally:
            httpd.shutdown()


# ---------------------------------------------------------------------------
# cost-model persistence (sidecar under GEOMESA_TPU_WORKLOAD_DIR)
# ---------------------------------------------------------------------------

class TestCostPersistence:
    def test_snapshot_load_round_trip(self, tmp_path):
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.planning import costmodel

        path = str(tmp_path / "costs.json")
        ct = devmon.costs()
        for i in range(20):
            ct.observe("pts", "z3:iv4:rows", wall_ms=2.0 + (i % 3),
                       rows=10)
        ct.tick("pts", "select_route")
        costmodel.model().record_calibration("pts", "z3:iv4:rows", 2.5, 3.0)
        before = ct.predict("pts", "z3:iv4:rows")
        assert devmon.save_cost_snapshot(path) == path

        # "restart": fresh table + model
        devmon.install(new_costs=devmon.CostTable())
        costmodel.install(costmodel.CostModel())
        assert devmon.costs().predict("pts", "z3:iv4:rows") is None
        assert devmon.load_cost_snapshot(path)
        after = devmon.costs().predict("pts", "z3:iv4:rows")
        assert after is not None
        assert after["wall_ms_p50"] == pytest.approx(
            before["wall_ms_p50"])
        assert after["observations"] == before["observations"]
        # probe cadence survives: the tick counter continues, not restarts
        assert devmon.costs().tick("pts", "select_route") == 2
        cal = costmodel.model().calibration_report()
        assert cal["entry_count"] == 1
        assert cal["entries"][0]["last_actual_ms"] == pytest.approx(3.0)

    def test_load_never_regresses_a_richer_live_entry(self, tmp_path):
        """Merge by richness: a live table that learned PAST the
        snapshot keeps its entries on load (a second store open must not
        roll the planner back to stale p50s)."""
        from geomesa_tpu.obs import devmon

        path = str(tmp_path / "costs.json")
        ct = devmon.costs()
        for _ in range(5):
            ct.observe("pts", "sig", wall_ms=100.0)
        devmon.save_cost_snapshot(path)
        # the live table learns on, past the snapshot, at a new level
        for _ in range(20):
            ct.observe("pts", "sig", wall_ms=1.0)
        before = ct.predict("pts", "sig")
        assert devmon.load_cost_snapshot(path)
        after = ct.predict("pts", "sig")
        assert after["observations"] == before["observations"] == 25
        assert after["wall_ms_p50"] == before["wall_ms_p50"]

    def test_schema_delete_purges_persisted_entries(self, tmp_path,
                                                    monkeypatch):
        from geomesa_tpu.obs import devmon

        monkeypatch.setenv("GEOMESA_TPU_WORKLOAD_DIR", str(tmp_path))
        ds = _filled_store(name="doomed")
        ds.query("doomed", CQL)
        devmon.save_cost_snapshot()
        path = devmon.cost_sidecar_path()
        with open(path) as fh:
            doc = json.load(fh)
        assert any(e["type"] == "doomed" for e in doc["costs"]["entries"])
        ds.delete_schema("doomed")
        with open(path) as fh:
            doc = json.load(fh)
        assert not any(
            e["type"] == "doomed" for e in doc["costs"]["entries"])
        assert not any(t[0] == "doomed" for t in doc["costs"]["ticks"])

    def test_catalog_save_load_round_trips_costs(self, tmp_path,
                                                 monkeypatch):
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.store import persistence

        monkeypatch.setenv("GEOMESA_TPU_WORKLOAD_DIR",
                           str(tmp_path / "wl"))
        ds = _filled_store()
        ds.query("pts", CQL)
        sig_rows = devmon.costs().snapshot()["entries"]
        assert sig_rows
        persistence.save(ds, str(tmp_path / "cat"))
        devmon.install(new_costs=devmon.CostTable())
        assert not devmon.costs().snapshot()["entries"]
        ds2 = persistence.load(str(tmp_path / "cat"))
        loaded = devmon.costs().snapshot()["entries"]
        assert {(r["type"], r["signature"]) for r in loaded} >= {
            (r["type"], r["signature"]) for r in sig_rows}
        assert ds2.stats_count("pts") == ds.stats_count("pts")


# ---------------------------------------------------------------------------
# overhead: the <2% bound with capture + metering ON
# ---------------------------------------------------------------------------

class TestOverhead:
    def test_capture_and_metering_under_2pct(self, tmp_path):
        """The lint.sh gate: flight record + SLO observation + usage
        metering + workload capture per query (everything _audit adds,
        untraced) must cost < 2% of the cached-jit select path's p50."""
        obs_workload.install(obs_workload.WorkloadJournal(str(tmp_path)))
        ds = _filled_store(n=400)
        sel = ("BBOX(geom,-50,-40,50,40) AND dtg DURING "
               "2017-07-14T02:40:00Z/2017-07-14T02:41:00Z")
        ds.query("pts", sel)  # compile + plan-cache warm
        lat = []
        for _ in range(15):
            t0 = time.perf_counter_ns()
            ds.query("pts", sel)
            lat.append(time.perf_counter_ns() - t0)
        p50_ns = float(np.percentile(lat, 50))

        eng = SloEngine()
        N = 5_000

        def per_call_ns():
            t0 = time.perf_counter_ns()
            for i in range(N):
                obs_flight.record(op="query", type_name="pts", plan=CQL,
                                  latency_ms=1.0, rows=10,
                                  breakdown={"plan": 0.1, "scan": 0.9},
                                  tenant="acme",
                                  plan_signature="z3:iv4:rows")
                eng.observe("store.query", ok=True, key="pts",
                            latency_ms=1.0)
                obs_usage.observe("acme", "pts", "z3:iv4:rows", rows=10,
                                  wall_ms=1.0)
                obs_workload.record(
                    ts=1.0, op="query", type_name="pts", source="store",
                    filter_text=CQL, hints=None, tenant="acme",
                    auths=None, plan_signature="z3:iv4:rows",
                    predicted_ms=None, latency_ms=1.0, rows=10)
            return (time.perf_counter_ns() - t0) / N

        cost = min(per_call_ns() for _ in range(3))
        assert cost < 0.02 * p50_ns, (
            f"capture+metering cost {cost:.0f} ns "
            f">= 2% of query p50 {p50_ns:.0f} ns")

    def test_steady_select_zero_recompiles_with_capture_on(self, tmp_path):
        """The acceptance pin's second half: capture + metering add no
        jit traffic — the steady cached-select path stays at zero new
        compile signatures and zero recompiles (jaxmon census)."""
        from geomesa_tpu.obs import jaxmon

        obs_workload.install(obs_workload.WorkloadJournal(str(tmp_path)))
        ds = _filled_store(n=400)
        sel = ("BBOX(geom,-50,-40,50,40) AND dtg DURING "
               "2017-07-14T02:40:00Z/2017-07-14T02:41:00Z")
        for _ in range(3):
            ds.query("pts", Query(filter=sel, hints={"tenant": "acme"}))
        before = jaxmon.jit_report()
        for _ in range(10):
            ds.query("pts", Query(filter=sel, hints={"tenant": "acme"}))
        after = jaxmon.jit_report()
        assert (after.get("recompiles", 0)
                - before.get("recompiles", 0)) == 0
        assert set(after["steps"]) == set(before["steps"])


# ---------------------------------------------------------------------------
# device-ms reconciliation (tenant series vs devmon attribution)
# ---------------------------------------------------------------------------

class TestDeviceMsReconciliation:
    def test_tenant_device_ms_matches_devprof_attribution(self):
        """Every query profiled (devprof hint): the meter's per-tenant
        device-ms total equals the sum of the flight records' device
        attributions — the two surfaces reconcile exactly when sampling
        is 100% (within sampling error otherwise)."""
        ds = _filled_store()
        for _ in range(4):
            ds.query("pts", Query(filter=CQL,
                                  hints={"tenant": "dev-t",
                                         "devprof": True}))
        recs = [r for r in obs_flight.get().records()
                if r.tenant == "dev-t"]
        dev_total = sum(
            r.device.get("device_compute", 0.0)
            + r.device.get("dispatch", 0.0)
            + r.device.get("compile", 0.0)
            for r in recs
        )
        snap = obs_usage.get().snapshot()
        by_tenant = {t["tenant"]: t for t in snap["tenants"]}
        metered = by_tenant["dev-t"]["lifetime"]["device_ms"]
        assert metered == pytest.approx(dev_total, rel=1e-6)
        assert metered > 0
