"""Thread-safety: queries race background compactions (lambda persister
shape); writes landing mid-compaction are never lost."""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore

SPEC = "name:String,dtg:Date,*geom:Point;geomesa.z3.interval='week'"
T0 = 1_600_000_000_000


def _table(sft, lo, hi):
    rng = np.random.default_rng(lo)
    recs = [
        {"name": f"n{i}", "dtg": T0 + i,
         "geom": Point(float(rng.uniform(-170, 170)), float(rng.uniform(-80, 80)))}
        for i in range(lo, hi)
    ]
    return FeatureTable.from_records(sft, recs, [f"n{i}" for i in range(lo, hi)])


class TestQueryVsCompaction:
    def test_queries_consistent_under_background_compaction(self):
        """Readers must always see a coherent (table, indices) pair: every
        query result equals the brute-force answer for SOME prefix of the
        write history (monotonic row counts, no phantom/corrupt rows)."""
        sft = parse_spec("evt", SPEC)
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        ds.write("evt", _table(sft, 0, 3000))
        ds.compact("evt")

        stop = threading.Event()
        errors: list = []
        per_thread: list[list[int]] = [[] for _ in range(3)]

        def churn():
            # repeated write+compact cycles (the background persister role)
            lo = 3000
            try:
                while not stop.is_set():
                    ds.write("evt", _table(sft, lo, lo + 500))
                    ds.compact("evt")
                    lo += 500
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader(slot):
            try:
                while not stop.is_set():
                    r = ds.query("evt", "BBOX(geom, -180, -90, 180, 90)")
                    per_thread[slot].append(r.count)
                    # fids must be unique (a torn snapshot duplicates rows)
                    assert len(set(r.table.fids)) == r.count
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=churn)] + [
            threading.Thread(target=reader, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(3.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:2]
        # counts observed by EACH reader only ever grow (appends, no deletes);
        # monotonicity holds per thread, not across interleaved threads
        assert any(per_thread), "readers never completed a query"
        for counts in per_thread:
            assert all(b >= a for a, b in zip(counts, counts[1:])), (
                "non-monotonic result sizes within one reader: torn snapshot"
            )

    def test_write_during_compaction_not_lost(self, monkeypatch):
        """A write landing while compact() rebuilds must survive in the hot
        tier (drop_consumed semantics)."""
        sft = parse_spec("evt", SPEC)
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        ds.write("evt", _table(sft, 0, 1000))

        st = ds._state("evt")
        orig_rebuild = ds._rebuild
        injected = {"done": False}

        def slow_rebuild(st_, table, **kw):
            # simulate a concurrent write arriving mid-rebuild
            if not injected["done"]:
                injected["done"] = True
                ds.write("evt", [{"name": "late", "dtg": T0,
                                  "geom": Point(1.0, 2.0)}], fids=["late-1"])
            return orig_rebuild(st_, table, **kw)

        monkeypatch.setattr(ds, "_rebuild", slow_rebuild)
        ds.compact("evt")
        monkeypatch.undo()
        # the late write is still queryable (hot tier) and survives the next
        # compaction too
        assert "late-1" in set(ds.query("evt", None).table.fids)
        assert st.delta.rows == 1
        ds.compact("evt")
        assert "late-1" in set(ds.query("evt", None).table.fids)
        assert ds.query("evt", None).count == 1001

    def test_concurrent_mutators_serialize(self):
        """compact vs delete_features racing: deletes never resurrect and
        writes never vanish (mutator serialization via mutate_lock)."""
        sft = parse_spec("evt", SPEC)
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        ds.write("evt", _table(sft, 0, 2000))
        ds.compact("evt")

        errors: list = []

        def deleter():
            try:
                for i in range(0, 1000, 50):
                    ds.delete_features("evt", [f"n{j}" for j in range(i, i + 50)])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def compactor():
            try:
                lo = 2000
                for _ in range(10):
                    ds.write("evt", _table(sft, lo, lo + 100))
                    ds.compact("evt")
                    lo += 100
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=deleter), threading.Thread(target=compactor)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errors, errors[:2]
        ds.compact("evt")
        fids = set(ds.query("evt", None).table.fids)
        # every delete stuck (no resurrections), every write survived
        assert not any(f"n{i}" in fids for i in range(1000))
        assert all(f"n{i}" in fids for i in range(1000, 2000))
        assert all(f"n{i}" in fids for i in range(2000, 3000))
        assert len(fids) == 2000

    def test_concurrent_writes_unique_fids(self):
        """Auto-generated sequential fids never collide across threads."""
        sft = parse_spec("evt", SPEC)
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        errors: list = []

        def writer():
            try:
                for _ in range(20):
                    ds.write("evt", [{
                        "name": "w", "dtg": T0, "geom": Point(1.0, 2.0)}])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=writer) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errors, errors[:2]
        r = ds.query("evt", None)
        assert r.count == 80
        assert len(set(r.table.fids)) == 80  # no duplicate ids
