"""Federation resilience layer (docs/resilience.md): retry policies,
circuit breakers, fault injection, deadline propagation, and
partial-result degradation across the remote/federation stack.

Doubles as the CI chaos smoke gate: scripts/lint.sh re-runs this file
with GEOMESA_TPU_FAULTS set — every test here pins its own injector
(the autouse fixture installs an EMPTY one, overriding the ambient env
spec), except the chaos tests, which adopt the ambient spec when one is
present and must still pass under it."""

import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import obs
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.resilience import faults as rfaults
from geomesa_tpu.resilience import http as rhttp
from geomesa_tpu.resilience.faults import FaultInjector, from_spec
from geomesa_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpenError,
    CorruptPayloadError,
    RetryPolicy,
    retryable,
)
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.merged import MergedDataStoreView
from geomesa_tpu.store.remote import RemoteDataStore
from geomesa_tpu.utils.timeouts import Deadline, QueryTimeout

T0 = 1_500_000_000_000


@pytest.fixture(autouse=True)
def _fault_isolation():
    """Every test starts with a pinned EMPTY injector (deterministic
    transport even when the chaos gate exports GEOMESA_TPU_FAULTS) and
    leaves the process-wide install state untouched."""
    rfaults.install(FaultInjector())
    yield
    rfaults.uninstall()


def _http_error(code=503):
    return urllib.error.HTTPError(
        "http://x", code, "boom", None, io.BytesIO(b"{}"))


def _refused():
    return urllib.error.URLError(ConnectionRefusedError(111, "refused"))


def _filled_store(lo=-170.0, hi=170.0, seed=1, n=400, name="f"):
    rng = np.random.default_rng(seed)
    ds = DataStore(backend="tpu")
    ds.create_schema(name, "name:String,dtg:Date,*geom:Point")
    ds.write(name, [
        {"name": f"n{i % 9}", "dtg": T0 + i * 1000,
         "geom": Point(float(rng.uniform(lo, hi)),
                       float(rng.uniform(-40, 40)))}
        for i in range(n)
    ], fids=[f"{seed}-{i}" for i in range(n)])
    return ds


@pytest.fixture(scope="module")
def remote_server(tmp_path_factory):
    """One real HTTP server over a real store (module-scoped; tests pick
    their fault rules per-test, so sharing the server is safe)."""
    from wsgiref.simple_server import WSGIRequestHandler, make_server

    from geomesa_tpu.stream.journal import JournalBus
    from geomesa_tpu.web.app import GeoMesaApp

    class _Quiet(WSGIRequestHandler):
        def log_message(self, *a):  # keep fault-heavy runs readable
            pass

    store = _filled_store(seed=1)
    bus = JournalBus(str(tmp_path_factory.mktemp("journal")), partitions=2)
    httpd = make_server("127.0.0.1", 0, GeoMesaApp(store, journal=bus),
                        handler_class=_Quiet)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield store, f"http://127.0.0.1:{port}", port
    httpd.shutdown()
    bus.close()


def _fast_retry(**kw):
    kw.setdefault("base_delay_s", 0.001)
    kw.setdefault("max_delay_s", 0.01)
    kw.setdefault("seed", 1)
    return RetryPolicy(**kw)


class TestRetryPolicy:
    def test_backoff_bounded_and_deterministic(self):
        a = RetryPolicy(base_delay_s=0.05, max_delay_s=1.0, seed=9)
        b = RetryPolicy(base_delay_s=0.05, max_delay_s=1.0, seed=9)
        d = prev = None
        seq_a, seq_b = [], []
        for _ in range(8):
            d = a.next_delay(d)
            prev = b.next_delay(prev)
            seq_a.append(d)
            seq_b.append(prev)
            assert 0.05 <= d <= 1.0
        assert seq_a == seq_b  # same seed, same schedule

    def test_idempotent_retries_5xx_then_succeeds(self):
        sleeps = []
        p = RetryPolicy(max_attempts=4, seed=2, sleep=sleeps.append)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise _http_error(503)
            return "ok"

        assert p.call(flaky, idempotent=True) == "ok"
        assert calls[0] == 3 and len(sleeps) == 2

    def test_mutation_does_not_retry_5xx(self):
        p = RetryPolicy(max_attempts=4, sleep=lambda s: None)
        calls = [0]

        def failing():
            calls[0] += 1
            raise _http_error(500)

        with pytest.raises(urllib.error.HTTPError):
            p.call(failing, idempotent=False)
        assert calls[0] == 1  # the server may have applied the write

    def test_mutation_retries_connect_before_send(self):
        p = RetryPolicy(max_attempts=3, seed=3, sleep=lambda s: None)
        calls = [0]

        def refused_once():
            calls[0] += 1
            if calls[0] == 1:
                raise _refused()
            return "ok"

        assert p.call(refused_once, idempotent=False) == "ok"
        assert calls[0] == 2

    def test_504_is_not_retryable(self):
        assert not retryable(_http_error(504), idempotent=True)
        assert retryable(_http_error(503), idempotent=True)

    def test_circuit_open_is_not_retryable(self):
        assert not retryable(CircuitOpenError("e", 1.0), idempotent=True)

    def test_query_timeout_is_not_retryable(self):
        # QueryTimeout ⊂ TimeoutError ⊂ OSError: the subclass must be
        # carved out or spent deadlines would retry with backoff sleeps
        assert not retryable(QueryTimeout("spent"), idempotent=True)
        assert not retryable(QueryTimeout("spent"), idempotent=False)

    def test_retry_budget_sheds_retries_when_dry(self):
        now = [0.0]  # frozen clock: no refill
        p = RetryPolicy(max_attempts=3, budget=2, budget_window_s=100.0,
                        clock=lambda: now[0], sleep=lambda s: None, seed=4)
        calls = [0]

        def failing():
            calls[0] += 1
            raise _http_error(503)

        with pytest.raises(urllib.error.HTTPError):
            p.call(failing)  # burns both tokens (2 retries + give-up)
        assert calls[0] == 3
        calls[0] = 0
        with pytest.raises(urllib.error.HTTPError):
            p.call(failing)  # budget dry: first error surfaces
        assert calls[0] == 1
        now[0] = 100.0  # window elapsed: bucket refills
        calls[0] = 0
        with pytest.raises(urllib.error.HTTPError):
            p.call(failing)
        assert calls[0] == 3


class TestCircuitBreaker:
    def _breaker(self, t, **kw):
        kw.setdefault("window", 10)
        kw.setdefault("min_volume", 4)
        kw.setdefault("failure_rate", 0.5)
        kw.setdefault("cooldown_s", 5.0)
        return CircuitBreaker("ep", clock=lambda: t[0], **kw)

    def test_closed_to_open_at_failure_rate(self):
        t = [0.0]
        b = self._breaker(t)
        for _ in range(3):
            b.record_failure()
        assert b.state == "closed"  # below min_volume
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(CircuitOpenError):
            b.before_call()

    def test_half_open_probe_closes_on_success(self):
        t = [0.0]
        b = self._breaker(t)
        for _ in range(4):
            b.record_failure()
        t[0] = 6.0  # cooldown passed
        assert b.state == "half_open"
        b.before_call()  # the probe slot
        with pytest.raises(CircuitOpenError):
            b.before_call()  # only `probes` trial calls go through
        b.record_success()
        assert b.state == "closed"
        b.before_call()  # healthy again

    def test_half_open_probe_failure_reopens(self):
        t = [0.0]
        b = self._breaker(t)
        for _ in range(4):
            b.record_failure()
        t[0] = 6.0
        b.before_call()
        b.record_failure()
        assert b.state == "open" and b.open_count == 2
        t[0] = 7.0  # cooldown restarted at t=6: still open
        with pytest.raises(CircuitOpenError):
            b.before_call()

    def test_stale_completion_is_not_a_probe_outcome(self):
        # a slow call issued BEFORE the trip, completing during half-open,
        # must neither close the breaker nor restart the cooldown
        t = [0.0]
        b = self._breaker(t)
        for _ in range(4):
            b.record_failure()
        t[0] = 6.0
        assert b.state == "half_open"
        b.record_success()  # stale: no probe in flight
        assert b.state == "half_open"
        b.record_failure()  # stale failure: cooldown must NOT restart
        assert b.state == "half_open"
        b.before_call()  # the real probe
        b.record_success()
        assert b.state == "closed"

    def test_mixed_traffic_below_rate_stays_closed(self):
        t = [0.0]
        b = self._breaker(t, failure_rate=0.75)
        for i in range(30):
            b.record(i % 2 == 0)  # ≤60% failures in any window < 75%
        assert b.state == "closed"


class TestFaultSpec:
    def test_spec_round_trip(self):
        inj = from_spec(
            "kind=http,status=502,rate=0.25,seed=3,match=:9,times=5;"
            "kind=latency,ms=7;kind=truncate,at=16,match=/query")
        kinds = [r.kind for r in inj.rules]
        assert kinds == ["http", "latency", "truncate"]
        r = inj.rules[0]
        assert (r.status, r.rate, r.times, r.match) == (502, 0.25, 5, ":9")
        assert inj.rules[2].truncate_at == 16

    def test_spec_errors(self):
        with pytest.raises(ValueError):
            from_spec("kind=nope")
        with pytest.raises(ValueError):
            from_spec("rate=0.5")  # missing kind
        with pytest.raises(ValueError):
            from_spec("kind=http,bogus=1")

    def test_seeded_schedule_is_deterministic(self):
        def pattern():
            inj = FaultInjector().rule("http", rate=0.3, seed=11)
            out = []
            for _ in range(50):
                try:
                    inj.before_send("GET", "http://h/x")
                    out.append(0)
                except urllib.error.HTTPError:
                    out.append(1)
            return out

        p1, p2 = pattern(), pattern()
        assert p1 == p2 and 5 < sum(p1) < 25

    def test_after_and_times_bound_the_schedule(self):
        inj = FaultInjector().rule("refuse", after=2, times=3)
        outcomes = []
        for _ in range(8):
            try:
                inj.before_send("GET", "http://h/x")
                outcomes.append(0)
            except urllib.error.URLError:
                outcomes.append(1)
        assert outcomes == [0, 0, 1, 1, 1, 0, 0, 0]

    def test_install_overrides_env(self, monkeypatch):
        monkeypatch.setenv("GEOMESA_TPU_FAULTS", "kind=refuse")
        assert rfaults.active() is not None
        assert rfaults.active().rules == []  # autouse EMPTY override wins
        rfaults.uninstall()
        amb = rfaults.active()
        assert amb is not None and amb.rules[0].kind == "refuse"


class TestErrorMapping:
    """Satellite: _get must map HTTPError exactly like _send — reads
    against a missing type raise KeyError, not raw HTTPError."""

    def test_reads_raise_local_exception_types(self, remote_server):
        _, url, _ = remote_server
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        with pytest.raises(KeyError):
            remote.get_schema("no-such-type")
        with pytest.raises(KeyError):
            remote.query("no-such-type", "INCLUDE")
        with pytest.raises(KeyError):
            remote.stats_count("no-such-type")

    def test_bad_cql_maps_to_value_error(self, remote_server):
        _, url, _ = remote_server
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        with pytest.raises(ValueError):
            remote.query("f", "THIS IS NOT CQL ???")


class TestRetryIntegration:
    def test_read_survives_transient_refusals(self, remote_server):
        _, url, port = remote_server
        inj = FaultInjector().rule("refuse", times=2, match=f":{port}")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=4))
        with inj.activate():
            r = remote.query("f", "name = 'n3'")
        assert r.count > 0
        assert inj.counts()[0][2] == 2  # both injected faults were eaten

    def test_read_survives_transient_5xx(self, remote_server):
        _, url, port = remote_server
        inj = FaultInjector().rule("http", status=503, times=2,
                                   match=f":{port}")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=4))
        with inj.activate():
            assert remote.stats_count("f", exact=True) == 400

    def test_mutation_fails_fast_on_5xx(self, remote_server):
        _, url, port = remote_server
        # scoped to the WRITE path: the schema prefetch is a read and may
        # legitimately retry
        inj = FaultInjector().rule("http", status=500,
                                   match=f":{port}/api/schemas/f/features")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=4))
        remote.get_schema("f")
        with inj.activate():
            with pytest.raises(urllib.error.HTTPError):
                remote.write("f", [{"name": "x", "dtg": T0,
                                    "geom": Point(0.0, 0.0)}])
        assert inj.counts()[0][1] == 1  # exactly one attempt: no replay

    def test_mutation_retries_refused_connection(self, remote_server):
        local, url, port = remote_server
        before = local.stats_count("f", exact=True)
        inj = FaultInjector().rule("refuse", times=1, match=f":{port}")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=3))
        with inj.activate():
            n = remote.write("f", [{"name": "x", "dtg": T0,
                                    "geom": Point(0.0, 0.0)}],
                             fids=["retry-w-0"])
        assert n == 1
        assert local.stats_count("f", exact=True) == before + 1


class TestCorruptPayload:
    """Satellite: truncated/corrupt Arrow from a member is a TYPED error,
    and partial mode degrades on it instead of failing the federation."""

    def test_truncated_arrow_raises_typed_error(self, remote_server):
        _, url, port = remote_server
        inj = FaultInjector().rule("truncate", truncate_at=20,
                                   match=f":{port}/api/schemas/f/query")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        with inj.activate():
            with pytest.raises(CorruptPayloadError) as ei:
                remote.query("f", "INCLUDE")
        assert "Arrow" in str(ei.value) and url in str(ei.value)

    def test_corrupt_arrow_raises_typed_error(self, remote_server):
        _, url, port = remote_server
        inj = FaultInjector().rule("corrupt",
                                   match=f":{port}/api/schemas/f/query")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        with inj.activate():
            with pytest.raises(CorruptPayloadError):
                remote.query("f", "INCLUDE")

    def test_truncated_json_raises_typed_error(self, remote_server):
        # JSON endpoints get the same typed treatment as Arrow ones
        _, url, port = remote_server
        inj = FaultInjector().rule("truncate", truncate_at=5,
                                   match=f":{port}/api/schemas/f/stats")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        with inj.activate():
            with pytest.raises(CorruptPayloadError) as ei:
                remote.stats_count("f")
        assert "JSON" in str(ei.value)

    def test_partial_mode_degrades_on_corrupt_member(self, remote_server):
        _, url, port = remote_server
        east = _filled_store(seed=2, n=150)
        inj = FaultInjector().rule("truncate", truncate_at=20,
                                   match=f":{port}/api/schemas/f/query")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        view = MergedDataStoreView([remote, east], on_member_error="partial")
        with inj.activate():
            with obs.collect("probe") as root:
                r = view.query("f", "INCLUDE")
        assert r.degraded
        assert r.count == 150  # the surviving member's rows
        assert r.member_errors == [
            (0, "CorruptPayloadError", r.member_errors[0][2])
        ]
        assert view.metrics.counter("federation.member_errors").count == 1
        # degradations mark the view's own federation.query span (one per
        # query since the distributed-tracing work), inside this trace
        fed = root.find("federation.query")
        assert len(fed) == 1
        assert [e[0] for e in fed[0].events] == ["member_error", "degraded"]

    def test_fail_mode_raises_on_corrupt_member(self, remote_server):
        _, url, port = remote_server
        east = _filled_store(seed=2, n=150)
        inj = FaultInjector().rule("truncate", truncate_at=20,
                                   match=f":{port}/api/schemas/f/query")
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=1))
        view = MergedDataStoreView([remote, east])  # default: fail
        with inj.activate():
            with pytest.raises(CorruptPayloadError):
                view.query("f", "INCLUDE")


@pytest.fixture(scope="module")
def slow_server():
    """A server whose store sleeps mid-query — the deadline-expiry hop."""
    from wsgiref.simple_server import WSGIRequestHandler, make_server

    from geomesa_tpu.web.app import GeoMesaApp

    class _Quiet(WSGIRequestHandler):
        def log_message(self, *a):
            pass

    store = _filled_store(seed=5, n=120)

    def slow(sft, query):
        time.sleep(0.4)
        return query

    store.register_interceptor("f", slow)
    httpd = make_server("127.0.0.1", 0, GeoMesaApp(store),
                        handler_class=_Quiet)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield store, f"http://127.0.0.1:{port}"
    httpd.shutdown()


class TestDeadline:
    def test_deadline_basics(self):
        d = Deadline.after_ms(50)
        assert 0 < d.remaining_ms() <= 50
        assert not d.expired()
        d2 = Deadline.after(-1)
        assert d2.expired() and d2.remaining_s() < 0

    def test_expired_deadline_sheds_before_sending(self):
        # dead port: a connect attempt would raise URLError, but the
        # pre-send shed must win — QueryTimeout without a round trip
        remote = RemoteDataStore("http://127.0.0.1:9",
                                 retry=_fast_retry(max_attempts=1))
        q = Query(filter=None, hints={"deadline": Deadline.after(-1)})
        with pytest.raises(QueryTimeout):
            remote.query("f", q)

    def test_local_store_sheds_expired_deadline(self):
        ds = _filled_store(seed=7, n=60)
        with pytest.raises(QueryTimeout):
            ds.query("f", Query(hints={"deadline": Deadline.after(-1)}))
        assert ds.metrics.counter("store.query.deadline_shed").count == 1

    def test_server_sheds_spent_budget_with_504(self, remote_server):
        _, url, _ = remote_server
        req = urllib.request.Request(
            url + "/api/schemas/f/query?format=arrow",
            headers={"X-Geomesa-Deadline-Ms": "0"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 504
        assert "deadline" in json.loads(ei.value.read().decode())["error"]

    def test_deadline_request_error_releases_watchdog(self, remote_server):
        # a 404 on a deadline-carrying request must release the watchdog
        # registration (not leak it in the active set forever)
        store, url, _ = remote_server
        req = urllib.request.Request(
            url + "/api/schemas/no-such-type/query",
            headers={"X-Geomesa-Deadline-Ms": "5000"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 404
        assert not [a for a in store.watchdog.active()
                    if a.startswith("http ")]

    def test_bad_deadline_header_is_400(self, remote_server):
        _, url, _ = remote_server
        req = urllib.request.Request(
            url + "/api/schemas/f/query",
            headers={"X-Geomesa-Deadline-Ms": "soon"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400

    def test_deadline_shed_does_not_consume_half_open_probe(self):
        # a shed records no breaker outcome, so it must not eat the
        # half-open probe slot (that would wedge the breaker half-open)
        t = [0.0]
        b = CircuitBreaker("ep", min_volume=2, cooldown_s=5.0,
                           clock=lambda: t[0])
        b.record_failure()
        b.record_failure()
        t[0] = 6.0
        assert b.state == "half_open"
        with pytest.raises(QueryTimeout):
            rhttp.request("GET", "http://127.0.0.1:9/x", breaker=b,
                          deadline=Deadline.after(-1))
        b.before_call()  # the probe slot is still there
        b.record_success()
        assert b.state == "closed"

    def test_two_hop_deadline_expires_at_remote(self, slow_server):
        """Satellite: federated query with a 2-hop budget expires AT the
        remote (504), the client maps it to QueryTimeout, and the
        abandoned-worker gauge drains back to zero."""
        from geomesa_tpu.utils import timeouts as uto

        store, url = slow_server
        remote = RemoteDataStore(url, retry=_fast_retry(max_attempts=2))
        east = _filled_store(seed=6, n=60)
        view = MergedDataStoreView([remote, east])  # fail mode: surfaces
        abandoned_before = store.watchdog.abandoned
        q = Query(filter=None, hints={"deadline": Deadline.after_ms(150)})
        t0 = time.perf_counter()
        with pytest.raises(QueryTimeout):
            view.query("f", q)
        # enforced at ~150ms, far before the 400ms sleep completes
        assert time.perf_counter() - t0 < 0.35
        # exactly ONE abandoned entity per blown request — the nested
        # web-request/store-scan wrappers must not double-count
        assert store.watchdog.abandoned == abandoned_before + 1
        assert store.metrics.counter("web.deadline.expired").count >= 1
        deadline = time.monotonic() + 5.0
        while uto.abandoned_running() and time.monotonic() < deadline:
            time.sleep(0.02)  # the abandoned worker finishes its sleep
        assert uto.abandoned_running() == 0

    def test_partial_mode_degrades_on_slow_member_timeout(self, slow_server):
        # the slow member blows its own SOCKET timeout (no shared
        # deadline: the healthy member must keep its full budget) and the
        # federation serves the survivor
        _, url = slow_server
        remote = RemoteDataStore(url, timeout_s=0.1,
                                 retry=_fast_retry(max_attempts=1))
        east = _filled_store(seed=6, n=60)
        view = MergedDataStoreView([remote, east],
                                   on_member_error="partial")
        r = view.query("f", "INCLUDE")
        assert r.degraded and r.count == 60
        assert r.member_errors[0][0] == 0


class TestPartialFederation:
    """The acceptance scenario: 30% 5xx on one of three members."""

    def _view(self, url, port, mode, times=None, rate=0.3):
        inj = FaultInjector().rule(
            "http", status=503, rate=rate, seed=13, times=times,
            match=f":{port}")
        flaky = RemoteDataStore(
            url,
            # no client-side retries: every injected 5xx must reach the
            # federation layer (and the breaker) undampened
            retry=_fast_retry(max_attempts=1),
            breaker=CircuitBreaker(endpoint=f":{port}", window=10,
                                   min_volume=4, failure_rate=0.25,
                                   cooldown_s=0.15),
        )
        view = MergedDataStoreView(
            [flaky, _filled_store(seed=3, n=200), _filled_store(seed=4, n=200)],
            on_member_error=mode,
        )
        return view, flaky, inj

    def test_partial_answers_every_query_and_breaker_cycles(
            self, remote_server):
        _, url, port = remote_server
        view, flaky, inj = self._view(url, port, "partial", times=30)
        degraded = 0
        with inj.activate():
            for _ in range(40):
                r = view.query("f", "name = 'n1'")
                assert r.count >= 0  # every query answers
                degraded += int(r.degraded)
        assert degraded >= 1  # failures surfaced as partials, not errors
        assert flaky.breaker.open_count >= 1  # opened after threshold
        # the member recovers (no more faults): after the cooldown the
        # half-open probe succeeds, the breaker closes, answers are
        # complete again
        time.sleep(0.2)
        r = view.query("f", "name = 'n1'")
        assert flaky.breaker.state == "closed"
        assert not r.degraded
        assert view.metrics.counter("federation.degraded_queries").count >= 1

    def test_open_breaker_skips_member_fast(self, remote_server):
        _, url, port = remote_server
        view, flaky, inj = self._view(url, port, "partial", rate=1.0)
        with inj.activate():
            for _ in range(6):
                view.query("f", "name = 'n1'")
        assert flaky.breaker.state == "open"
        # breaker open: the member is skipped WITHOUT a round trip
        seen_before = sum(s for _, s, _ in inj.counts())
        with inj.activate():
            r = view.query("f", "name = 'n1'")
        assert r.degraded
        assert r.member_errors[0][1] == "CircuitOpenError"
        assert sum(s for _, s, _ in inj.counts()) == seen_before

    def test_fail_mode_raises(self, remote_server):
        _, url, port = remote_server
        view, _, inj = self._view(url, port, "fail", rate=1.0)
        with inj.activate():
            with pytest.raises(urllib.error.HTTPError):
                view.query("f", "name = 'n1'")

    def test_all_members_failing_raises_even_in_partial(self, remote_server):
        _, url, port = remote_server
        inj = FaultInjector().rule("refuse", match=f":{port}")
        view = MergedDataStoreView(
            [RemoteDataStore(url, retry=_fast_retry(max_attempts=1))],
            on_member_error="partial")
        with inj.activate():
            with pytest.raises(urllib.error.URLError):
                view.query("f", "INCLUDE")

    def test_stats_count_partial(self, remote_server):
        _, url, port = remote_server
        inj = FaultInjector().rule("refuse", match=f":{port}")
        east = _filled_store(seed=3, n=200)
        view = MergedDataStoreView(
            [RemoteDataStore(url, retry=_fast_retry(max_attempts=1)), east],
            on_member_error="partial")
        with inj.activate():
            assert view.stats_count("f", exact=True) == 200
        assert view.metrics.counter("federation.member_errors").count == 1

    def test_aggregate_many_partial_marks_degraded(self):
        # stub members: one hard-down, one answering with fixed partials —
        # the view must merge the survivor and mark the result degraded
        base = _filled_store(seed=3, n=10)

        class Down:
            def get_schema(self, name):
                return base.get_schema(name)

            def aggregate_many(self, *a, **kw):
                raise ConnectionError("member down")

        class Up:
            def get_schema(self, name):
                return base.get_schema(name)

            def aggregate_many(self, type_name, queries, group_by=None,
                               value_cols=(), now_ms=None):
                return [{
                    "groups": [("a",), ("b",)],
                    "count": np.asarray([3, 4], dtype=np.int64),
                    "cols": {},
                } for _ in queries]

        view = MergedDataStoreView([Down(), Up()], on_member_error="partial")
        out = view.aggregate_many("f", ["INCLUDE"], group_by=["name"])
        assert out[0]["degraded"] is True
        assert out[0]["member_errors"][0][1] == "ConnectionError"
        assert int(out[0]["count"].sum()) == 7
        assert view.metrics.counter("federation.member_errors").count == 1

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError):
            MergedDataStoreView([_filled_store(n=10)], on_member_error="eh")


class TestRoutedFallback:
    def _stores(self):
        a = _filled_store(seed=8, n=80)
        b = _filled_store(seed=9, n=80)

        class Flaky:
            """Member-failure facade over a real store."""

            def __init__(self, ds):
                self.ds = ds
                self.calls = 0

            def get_schema(self, name):
                return self.ds.get_schema(name)

            def list_schemas(self):
                return self.ds.list_schemas()

            def query(self, *a, **kw):
                self.calls += 1
                raise ConnectionError("member down")

            def stats_count(self, *a, **kw):
                self.calls += 1
                raise ConnectionError("member down")

        return Flaky(a), b

    def test_fallback_to_include_store(self):
        from geomesa_tpu.store.routed import RoutedDataStoreView

        flaky, include = self._stores()
        view = RoutedDataStoreView(
            [(flaky, [["name"]]), (include, [[]])],
            on_member_error="fallback")
        r = view.query("f", "name = 'n1'")
        assert flaky.calls == 1 and r.count > 0
        assert view.metrics.counter("federation.route_fallbacks").count == 1

    def test_fail_mode_propagates(self):
        from geomesa_tpu.store.routed import RoutedDataStoreView

        flaky, include = self._stores()
        view = RoutedDataStoreView([(flaky, [["name"]]), (include, [[]])])
        with pytest.raises(ConnectionError):
            view.query("f", "name = 'n1'")


class TestJournalResilience:
    """Satellite: the remote journal tailer backs off with the policy
    (no fixed sleep) and surfaces health through utils/metrics."""

    def test_tailer_backs_off_and_recovers(self, remote_server):
        from geomesa_tpu.stream.remote_journal import RemoteJournal

        _, url, port = remote_server
        inj = FaultInjector().rule("refuse", times=4,
                                   match=f":{port}/api/journal")
        got: list[bytes] = []
        rj = RemoteJournal(
            url, poll_interval_s=0.02,
            retry=_fast_retry(max_attempts=1),  # every refusal hits the loop
            breaker=CircuitBreaker(endpoint=f":{port}", min_volume=10_000),
        )
        with inj.activate():
            rj.subscribe("t-resil", got.append)
            deadline = time.monotonic() + 5.0
            while (rj.metrics.counter(
                    "remote_journal.transient_errors").count < 4
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            rj.publish("t-resil", "k", b"after-the-storm")
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
        try:
            assert got == [b"after-the-storm"]
            m = rj.metrics
            assert m.counter("remote_journal.transient_errors").count >= 4
            assert m.gauge("remote_journal.consecutive_failures").value == 0.0
            assert m.gauge("remote_journal.healthy").value == 1.0
            assert rj.healthy()
        finally:
            rj.close()


class TestChaosSmoke:
    """Runs MEANINGFULLY under the lint.sh chaos gate: when
    GEOMESA_TPU_FAULTS is exported these tests adopt the ambient spec
    (plus a port-scoped default otherwise) and must still answer."""

    def _ambient_or(self, default: FaultInjector) -> FaultInjector:
        rfaults.uninstall()  # drop the autouse empty override
        return rfaults.from_env() or default

    def test_partial_federation_answers_under_ambient_chaos(
            self, remote_server):
        _, url, port = remote_server
        inj = self._ambient_or(
            FaultInjector()
            .rule("http", status=503, rate=0.3, seed=21, match=f":{port}")
            .rule("latency", latency_ms=2.0, rate=0.2, seed=22,
                  match=f":{port}"))
        view = MergedDataStoreView(
            [RemoteDataStore(url, retry=_fast_retry(max_attempts=4)),
             _filled_store(seed=3, n=200)],
            on_member_error="partial")
        with inj.activate():
            for i in range(25):
                r = view.query("f", f"name = 'n{i % 9}'")
                assert r.count >= 0  # answered, degraded or not
        assert True  # surviving the storm IS the assertion

    def test_retries_absorb_ambient_chaos_on_single_client(
            self, remote_server):
        local, url, port = remote_server
        inj = self._ambient_or(
            FaultInjector().rule("http", status=503, rate=0.3, seed=23,
                                 match=f":{port}"))
        # generous attempts: ambient chaos gates may inject aggressively
        remote = RemoteDataStore(url, retry=_fast_retry(
            max_attempts=6, budget=10_000))
        view = MergedDataStoreView([remote, _filled_store(seed=3, n=200)],
                                   on_member_error="partial")
        with inj.activate():
            counts = [view.query("f", "name = 'n2'").count
                      for _ in range(10)]
        assert max(counts) == view.stores[1][0].query(
            "f", "name = 'n2'").count + local.query("f", "name = 'n2'").count


class TestSpanEvents:
    def test_span_events_export_as_instant_events(self):
        from geomesa_tpu.obs.export import chrome_trace_events

        with obs.collect("probe") as root:
            obs.event("member_error", member=2, error="URLError")
        assert [e[0] for e in root.events] == ["member_error"]
        evts = chrome_trace_events(root)
        inst = [e for e in evts if e["ph"] == "i"]
        assert len(inst) == 1 and inst[0]["name"] == "member_error"
        assert inst[0]["args"] == {"member": 2, "error": "URLError"}

    def test_event_is_noop_without_live_span(self):
        obs.event("orphan", x=1)  # must not raise, must not record
        assert obs.current() is None


class TestOverhead:
    def test_resilience_envelope_under_2pct_of_cached_select(
            self, remote_server):
        """Acceptance bound, measured the way the obs overhead gate is:
        (envelope invocations per query = 1) x (no-fault envelope cost)
        must be < 2% of the path the envelope actually rides — the
        REMOTE cached select's own p50 (local selects never enter the
        resilience layer)."""
        _, url, _ = remote_server
        remote = RemoteDataStore(url)
        cql = "BBOX(geom, -50, -40, 50, 40)"
        remote.query("f", cql)  # schema cache + server jit/plan warm
        lat = []
        for _ in range(15):
            t0 = time.perf_counter_ns()
            remote.query("f", cql)
            lat.append(time.perf_counter_ns() - t0)
        p50_ns = float(np.percentile(lat, 50))

        policy = RetryPolicy()
        breaker = CircuitBreaker("ep")

        def envelope():
            # exactly what rhttp.request adds per no-fault exchange
            breaker.before_call()
            rfaults.active()
            breaker.record_success()
            return None

        def one_pass():
            t0 = time.perf_counter_ns()
            for _ in range(1000):
                policy.call(envelope)
            return (time.perf_counter_ns() - t0) / 1000.0

        per_call = min(one_pass() for _ in range(3))
        assert per_call < 0.02 * p50_ns, (
            f"resilience envelope {per_call:.0f} ns >= 2% of remote "
            f"cached select p50 {p50_ns:.0f} ns")
