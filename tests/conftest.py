"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's two-tier test scheme (SURVEY.md §4): pure-math units
run on numpy; planner/kernel/sharding suites run the real code paths on a
virtual 8-device CPU mesh (the analog of Accumulo's MockInstance in-JVM
backend), so multi-chip behavior is exercised without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: tests always run the CPU mesh
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon site hook (sitecustomize) force-registers the TPU relay backend and
# sets jax_platforms="axon,cpu" at interpreter start, overriding the env var —
# override it back before any backend initializes.
jax.config.update("jax_platforms", "cpu")

# tpurace dynamic prong: GEOMESA_TPU_SANITIZE=1 wraps every lock the repo
# creates in an Eraser-style lock-order recorder (see
# geomesa_tpu/analysis/race/sanitizer.py). Install BEFORE any geomesa_tpu
# submodule import so module-level and instance locks all land in the
# graph (geomesa_tpu/__init__ itself is lazy and creates none).
_sanitizer = None
if os.environ.get("GEOMESA_TPU_SANITIZE", "") not in ("", "0"):
    from geomesa_tpu.analysis.race import sanitizer as _sanitizer

    _sanitizer.install()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _fresh_cost_state():
    """Per-test isolation of the adaptive-planner feedback state: the
    process-global observed-cost table and cost model accumulate across
    queries (probe ticks, learned route verdicts, calibration), so a
    suite run would otherwise leak one test's training into the next
    test's strategy choices — order-fragile by construction (the same
    lesson test_geoblocks learned for the agg route). Tests that manage
    their own installs simply stack on top; both restore on teardown."""
    from geomesa_tpu.obs import devmon
    from geomesa_tpu.planning import costmodel

    prev = devmon.install(new_costs=devmon.CostTable())
    prev_model = costmodel.install()
    yield
    devmon.install(new_costs=prev[1])
    costmodel.install(prev_model)


@pytest.fixture(scope="session", autouse=True)
def _lock_order_gate():
    """Under GEOMESA_TPU_SANITIZE=1, fail the run if real execution ever
    acquired repo locks in cycle-forming orders (the schedule that
    actually deadlocks never needs to happen — opposite orders on any
    two threads are enough to flag)."""
    yield
    if _sanitizer is not None:
        _sanitizer.check()
