"""Cross-host coordination with NO shared filesystem (VERDICT r4 item 7):

- ``/api/lease`` + ``http_lease_lock``: the ZK ``DistributedLocking.scala:14``
  role served by a coordinator process over HTTP — 4-process mutual-exclusion
  soak, expiry recovery, lease-coordinated one-winner schema creation.
- ``catalog_lock`` takes the HTTP lease (not the filesystem lease) when
  ``GEOMESA_COORDINATOR_URL`` is set.
- ``/api/journal`` + ``RemoteJournal``: the Kafka-broker role — a
  StreamingDataStore consumes another process's live stream across the HTTP
  boundary (``KafkaDataStore.scala:52`` role with no shared mount).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.utils.locks import (
    LeaseService,
    LockTimeout,
    catalog_lock,
    http_lease_lock,
)


@pytest.fixture()
def coordinator():
    from wsgiref.simple_server import make_server

    from geomesa_tpu.web.app import GeoMesaApp

    store = DataStore(backend="tpu")
    app = GeoMesaApp(store)
    httpd = make_server("127.0.0.1", 0, app)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield store, app, f"http://127.0.0.1:{port}"
    httpd.shutdown()


_WORKER = r"""
import sys, time
from geomesa_tpu.utils.locks import http_lease_lock

url, name, counter, iters = sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4])
for _ in range(iters):
    with http_lease_lock(url, name=name, ttl_s=30.0, timeout_s=60.0,
                         poll_s=0.005):
        with open(counter) as f:
            v = int(f.read())
        time.sleep(0.002)  # widen the race window
        with open(counter, "w") as f:
            f.write(str(v + 1))
print("worker done")
"""


class TestHttpLease:
    def test_four_process_mutual_exclusion_soak(self, coordinator, tmp_path):
        _, _, url = coordinator
        counter = tmp_path / "counter"
        counter.write_text("0")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        iters, nproc = 12, 4
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WORKER, url, "soak", str(counter),
                 str(iters)],
                env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            )
            for _ in range(nproc)
        ]
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, err.decode()[-2000:]
        # unguarded read-modify-write would lose increments under the race
        assert int(counter.read_text()) == iters * nproc

    def test_contention_then_release(self, coordinator):
        _, app, url = coordinator
        order = []

        def hold_then(label, hold_s):
            with http_lease_lock(url, name="c1", timeout_s=10.0,
                                 poll_s=0.01):
                order.append(("in", label))
                time.sleep(hold_s)
                order.append(("out", label))

        t1 = threading.Thread(target=hold_then, args=("a", 0.15))
        t1.start()
        time.sleep(0.05)
        t2 = threading.Thread(target=hold_then, args=("b", 0.0))
        t2.start()
        t1.join()
        t2.join()
        # b could only enter after a exited
        assert order == [("in", "a"), ("out", "a"), ("in", "b"), ("out", "b")]
        assert app.leases._leases == {}  # both released

    def test_timeout_when_held(self, coordinator):
        _, _, url = coordinator
        with http_lease_lock(url, name="held", ttl_s=30.0):
            with pytest.raises(LockTimeout, match="held"):
                with http_lease_lock(url, name="held", timeout_s=0.15,
                                     poll_s=0.02):
                    pass

    def test_expiry_breaks_dead_holder(self, coordinator):
        _, app, url = coordinator
        # a holder that died without releasing: acquire directly, never
        # release — the lease must expire and admit the next contender
        out = app.leases.acquire("dead", "crashed-host", ttl_s=0.2)
        assert out["ok"]
        t0 = time.monotonic()
        with http_lease_lock(url, name="dead", timeout_s=5.0, poll_s=0.02):
            waited = time.monotonic() - t0
        assert 0.1 <= waited < 2.0  # waited for expiry, not the timeout

    def test_renew_extends_and_rejects_stale_token(self, coordinator):
        import json as _json
        import urllib.request

        _, app, url = coordinator
        out = app.leases.acquire("r", "h", ttl_s=0.5)

        def _post(op, body):
            req = urllib.request.Request(
                f"{url}/api/lease/{op}", data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as r:
                return _json.loads(r.read())

        # renew over HTTP extends the expiry
        assert _post("renew", {"name": "r", "token": out["token"],
                               "ttl_s": 30.0})["ok"]
        assert app.leases._leases["r"][2] > time.time() + 10
        # stale/garbage token cannot renew
        assert not _post("renew", {"name": "r", "token": "nope",
                                   "ttl_s": 30.0})["ok"]
        app.leases.release("r", out["token"])

    def test_stale_release_does_not_evict_new_holder(self, coordinator):
        _, app, _ = coordinator
        old = app.leases.acquire("n", "h1", ttl_s=0.01)
        time.sleep(0.05)
        new = app.leases.acquire("n", "h2", ttl_s=30.0)
        assert new["ok"]
        app.leases.release("n", old["token"])  # stale token: no-op
        assert app.leases._leases["n"][0] == new["token"]

    def test_catalog_lock_routes_to_coordinator(self, coordinator, tmp_path,
                                                monkeypatch):
        # with GEOMESA_COORDINATOR_URL set, the cross-host layer must be
        # the HTTP lease — sabotage the filesystem lease to prove it's
        # not consulted
        import geomesa_tpu.utils.locks as locks_mod

        _, app, url = coordinator
        monkeypatch.setenv("GEOMESA_COORDINATOR_URL", url)

        def _boom(*a, **k):
            raise AssertionError("filesystem lease used despite coordinator")

        monkeypatch.setattr(locks_mod, "lease_lock", _boom)
        with catalog_lock(str(tmp_path / "cat")):
            assert len(app.leases._leases) == 1
        assert app.leases._leases == {}


_CREATE_WORKER = r"""
import sys
from geomesa_tpu.utils.locks import http_lease_lock
from geomesa_tpu.store.remote import RemoteDataStore

url = sys.argv[1]
remote = RemoteDataStore(url)
# lease-coordinated check-then-create: the no-shared-mount analog of the
# reference's ZK-locked ensureSchema
with http_lease_lock(url, name="schema:race", timeout_s=60.0, poll_s=0.005):
    if "race" in remote.list_schemas():
        print("lost")
    else:
        remote.create_schema("race", "name:String,*geom:Point")
        print("won")
"""


def test_lease_coordinated_create_schema_one_winner(coordinator):
    store, _, url = coordinator
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CREATE_WORKER, url],
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for _ in range(3)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err.decode()[-2000:]
        outs.append(out.decode().strip())
    assert sorted(outs) == ["lost", "lost", "won"]
    assert "race" in store.list_schemas()


class TestHttpSchemaRegistry:
    """Live schema-registry service interop (Confluent REST protocol):
    producers/consumers on different hosts share writer-schema ids through
    the service and resolve evolution across the wire."""

    @pytest.fixture()
    def registry_server(self):
        from wsgiref.simple_server import make_server

        from geomesa_tpu.stream.confluent import SchemaRegistry
        from geomesa_tpu.web.app import GeoMesaApp

        store = DataStore(backend="tpu")
        reg = SchemaRegistry()
        httpd = make_server(
            "127.0.0.1", 0, GeoMesaApp(store, schema_registry=reg))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield reg, f"http://127.0.0.1:{port}"
        httpd.shutdown()

    def test_protocol_roundtrip(self, registry_server):
        from geomesa_tpu.io.avro import avro_schema
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.stream.confluent import HttpSchemaRegistry

        _, url = registry_server
        c1 = HttpSchemaRegistry(url)
        c2 = HttpSchemaRegistry(url)
        s1 = avro_schema(parse_spec("e", "name:String,*geom:Point"))
        s2 = avro_schema(parse_spec("e", "name:String,v:Integer,*geom:Point"))
        # ids are service-assigned, idempotent, shared across clients
        assert c1.register("e", s1) == c2.register("e", s1) == 1
        assert c1.register("e", s2) == 2
        assert c2.versions("e") == [1, 2]
        # a client that never registered s2 resolves it by id over HTTP
        assert c2.schema_by_id(2) == s2
        with pytest.raises(KeyError):
            c1.schema_by_id(99)
        # same schema under a SECOND subject must reach the server (the
        # id cache is per (subject, schema), not per schema)
        assert c1.register("e2", s1) == 1
        assert c1.versions("e2") == [1]

    def test_cross_client_schema_evolution(self, registry_server):
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.stream.confluent import (
            AvroGeoMessageSerializer,
            HttpSchemaRegistry,
        )
        from geomesa_tpu.stream.messages import Put

        _, url = registry_server
        # producer (v1) and consumer (v2, adds a field) each bind their
        # serializer to their OWN client of the shared live registry
        old = AvroGeoMessageSerializer(
            parse_spec("e", "name:String,dtg:Date,*geom:Point"),
            HttpSchemaRegistry(url))
        new = AvroGeoMessageSerializer(
            parse_spec("e", "name:String,sev:Integer,dtg:Date,*geom:Point"),
            HttpSchemaRegistry(url))
        wire = old.serialize(
            Put("f1", {"name": "x", "dtg": 9, "geom": Point(1.0, 2.0)}, 5))
        out = new.deserialize(wire)  # writer schema fetched by id over HTTP
        assert out.record["name"] == "x"
        assert out.record["sev"] is None
        assert out.record["geom"].y == 2.0


@pytest.fixture()
def journal_server(tmp_path):
    from wsgiref.simple_server import make_server

    from geomesa_tpu.stream.journal import JournalBus
    from geomesa_tpu.web.app import GeoMesaApp

    store = DataStore(backend="tpu")
    bus = JournalBus(str(tmp_path / "journal"), poll_interval_s=0.01)
    httpd = make_server("127.0.0.1", 0, GeoMesaApp(store, journal=bus))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield bus, f"http://127.0.0.1:{port}"
    httpd.shutdown()
    bus.close()


class TestRemoteJournal:
    def test_publish_poll_roundtrip(self, journal_server):
        from geomesa_tpu.stream.remote_journal import RemoteJournal

        bus, url = journal_server
        rj = RemoteJournal(url)
        assert rj.partitions == bus.partitions
        for i in range(20):
            rj.publish("t1", f"k{i % 3}", f"m{i}".encode())
        # remote per-partition logs mirror the local ones exactly
        for p in range(bus.partitions):
            assert rj.poll("t1", p, 0, 64) == bus.poll("t1", p, 0, 64)
            assert rj.end_offset("t1", p) == bus.end_offset("t1", p)
        # total order preserved across the boundary
        assert rj.total_poll("t1", 0, 64) == [
            f"m{i}".encode() for i in range(20)
        ]
        assert rj.topic_size("t1") == 20
        rj.close()

    def test_cursor_tail_matches_total_order(self, journal_server):
        from geomesa_tpu.stream.remote_journal import RemoteJournal

        bus, url = journal_server
        rj = RemoteJournal(url)
        for i in range(30):
            rj.publish("tc", f"k{i}", f"p{i}".encode())
        # walk the byte cursor in steps; concatenation must equal the
        # total-order log exactly
        got, cursor = [], 0
        while True:
            batch, nxt = rj.total_poll_cursor("tc", cursor)
            if not batch:
                break
            got.extend(batch)
            assert nxt > cursor
            cursor = nxt
        assert got == [f"p{i}".encode() for i in range(30)]
        # cursor is stable at the tip, then advances with new data
        assert rj.total_poll_cursor("tc", cursor) == ([], cursor)
        rj.publish("tc", "k", b"tip")
        batch, _ = rj.total_poll_cursor("tc", cursor)
        assert batch == [b"tip"]
        rj.close()

    def test_subscribe_to_journal_less_server_fails_fast(self, coordinator):
        from geomesa_tpu.stream.remote_journal import RemoteJournal

        _, _, url = coordinator  # server has NO journal attached
        rj = RemoteJournal(url, poll_interval_s=0.01)
        seen = []
        rj.subscribe("t", seen.append)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and rj.healthy():
            time.sleep(0.02)
        # the 404 misconfiguration surfaces instead of an idle-looking tail
        assert not rj.healthy()
        assert rj.last_error is not None and rj.last_error.code == 404
        assert seen == []
        rj.close()

    def test_no_journal_404(self, coordinator):
        import urllib.error
        import urllib.request

        _, _, url = coordinator
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/api/journal/t/end")
        assert e.value.code == 404

    def test_streaming_store_consumes_across_http(self, journal_server):
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.stream.datastore import StreamingDataStore
        from geomesa_tpu.stream.remote_journal import RemoteJournal

        bus, url = journal_server
        spec = "name:String,*geom:Point"
        feeder = StreamingDataStore(bus=bus)
        feeder.create_schema("live", spec)

        consumer = StreamingDataStore(
            bus=RemoteJournal(url, poll_interval_s=0.02))
        consumer.create_schema("live", spec)

        for i in range(50):
            feeder.put("live", f"f{i}",
                       {"name": f"n{i}", "geom": Point(float(i % 20), 0.0)})
        feeder.delete("live", "f7")

        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if consumer.cache("live").size() == 49:
                break
            time.sleep(0.05)
        assert consumer.cache("live").size() == 49
        got = consumer.query("live", "BBOX(geom, -0.5, -0.5, 5.5, 0.5)")
        exp = sum(1 for i in range(50) if i % 20 <= 5 and i != 7)
        assert got.count == exp
        # writes from the REMOTE side flow back through the same broker
        consumer.put("live", "fx", {"name": "x", "geom": Point(0.0, 0.0)})
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if feeder.cache("live").get("fx") is not None:
                break
            time.sleep(0.05)
        assert feeder.cache("live").get("fx") is not None
        consumer.close()
