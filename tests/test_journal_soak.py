"""Cross-process streaming soak: the durable journal bus survives a
SIGKILLed writer with no lost and no duplicated features.

VERDICT r2 item 7 / missing #1 (the Kafka-broker durability role): a WRITER
process streams Puts through a :class:`JournalBus` on disk, is hard-killed
mid-stream, restarts, and resumes from the journal itself (no side-channel
progress file); the READER (this process) materializes the topic through the
standard :class:`StreamingDataStore` consumer machinery and must end with
exactly the full feature set.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import geomesa_tpu  # noqa: F401
from geomesa_tpu.stream.datastore import StreamingDataStore
from geomesa_tpu.stream.journal import JournalBus

TOTAL = 3000

# The writer resumes by reading ITS OWN journal — the broker is the source
# of truth, like a Kafka producer reconciling from the topic tail.
WRITER = """
import sys, zlib
import geomesa_tpu
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.stream.journal import JournalBus
from geomesa_tpu.stream.messages import GeoMessageSerializer, Put

root, total = sys.argv[1], int(sys.argv[2])
sft = parse_spec("evt", "name:String,dtg:Date,*geom:Point")
ser = GeoMessageSerializer(sft)
bus = JournalBus(root, partitions=4)
topic = "geomesa-evt"

# resume point: highest fid already durable in the journal
done = set()
for p in range(bus.partitions):
    for data in bus.poll(topic, p, 0, max_n=10**9):
        msg = ser.deserialize(data)
        done.add(int(msg.fid))
start = (max(done) + 1) if done else 0
sys.stderr.write(f"writer: resuming at {start} ({len(done)} durable)\\n")

from geomesa_tpu.geometry.types import Point
for i in range(start, total):
    rec = {"name": f"n{i}", "dtg": 1_600_000_000_000 + i,
           "geom": Point(float(i % 360 - 180) * 0.5, float(i % 180 - 90) * 0.5)}
    bus.publish(topic, str(i), ser.serialize(Put(str(i), rec, 1_600_000_000_000 + i)))
print("writer: done", total - start)
"""


def _spawn_writer(root: str):
    return subprocess.Popen(
        [sys.executable, "-c", WRITER, root, str(TOTAL)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


class TestJournalSoak:
    def test_writer_killed_and_restarted_no_loss_no_dup(self, tmp_path):
        root = str(tmp_path / "journal")
        probe = JournalBus(root, partitions=4)

        # 1) writer starts streaming; hard-kill it mid-stream
        w1 = _spawn_writer(root)
        deadline = time.monotonic() + 60
        while probe.topic_size("geomesa-evt") < TOTAL // 4:
            if w1.poll() is not None:
                out, err = w1.communicate()
                pytest.fail(f"writer died early: {err.decode()[-500:]}")
            if time.monotonic() > deadline:
                pytest.fail("writer produced nothing in 60s")
            time.sleep(0.01)
        w1.send_signal(signal.SIGKILL)
        w1.wait(timeout=10)
        n_after_kill = probe.topic_size("geomesa-evt")
        assert TOTAL // 4 <= n_after_kill < TOTAL, n_after_kill

        # 2) restarted writer resumes FROM THE JOURNAL and completes
        w2 = _spawn_writer(root)
        out, err = w2.communicate(timeout=120)
        assert w2.returncode == 0, err.decode()[-500:]
        assert b"writer: done" in out

        # 3) reader materializes through the standard consumer machinery
        reader_bus = JournalBus(root, partitions=4)
        ds = StreamingDataStore(bus=reader_bus, async_consumers=2)
        ds.create_schema("evt", "name:String,dtg:Date,*geom:Point")
        assert ds.drain("evt", timeout_s=60)
        cache = ds.cache("evt")
        assert cache.size() == TOTAL
        fids = {s.fid for s in cache.states()}
        assert fids == {str(i) for i in range(TOTAL)}

        # 4) no duplication at the JOURNAL level: every fid appended once
        #    (the cache would silently dedupe, so check the log itself with
        #    a FRESH bus — the consumer trimmed the reader bus's window)
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.stream.messages import GeoMessageSerializer

        ser = GeoMessageSerializer(parse_spec(
            "evt", "name:String,dtg:Date,*geom:Point"
        ))
        audit_bus = JournalBus(root, partitions=4)
        seen: dict[str, int] = {}
        for p in range(audit_bus.partitions):
            for data in audit_bus.poll("geomesa-evt", p, 0, max_n=10**9):
                fid = ser.deserialize(data).fid
                seen[fid] = seen.get(fid, 0) + 1
        dups = {f: c for f, c in seen.items() if c != 1}
        assert not dups, f"duplicated fids in journal: {list(dups)[:5]}"
        assert len(seen) == TOTAL

        # 5) queries serve from the materialized cache
        r = ds.query("evt", "BBOX(geom, -10, -10, 10, 10)")
        want = sum(
            1 for i in range(TOTAL)
            if -10 <= (i % 360 - 180) * 0.5 <= 10
            and -10 <= (i % 180 - 90) * 0.5 <= 10
        )
        assert r.count == want
        ds.close()

    def test_two_live_processes_reader_tails_writer(self, tmp_path):
        """Reader attached BEFORE the writer finishes sees the stream arrive
        live across the process boundary."""
        root = str(tmp_path / "journal2")
        reader_bus = JournalBus(root, partitions=4, poll_interval_s=0.005)
        ds = StreamingDataStore(bus=reader_bus, async_consumers=2)
        ds.create_schema("evt", "name:String,dtg:Date,*geom:Point")
        w = _spawn_writer(root)
        try:
            deadline = time.monotonic() + 90
            while ds.cache("evt").size() < TOTAL:
                if time.monotonic() > deadline:
                    pytest.fail(
                        f"reader saw {ds.cache('evt').size()}/{TOTAL} in 90s"
                    )
                time.sleep(0.02)
        finally:
            w.wait(timeout=60)
            ds.close()
        assert {s.fid for s in ds.cache("evt").states()} == {str(i) for i in range(TOTAL)}

    def test_lost_commit_sidecar_recovers_not_truncates(self, tmp_path):
        """A missing .commit sidecar must NEVER be read as 'commit 0' — a
        publish after sidecar loss recovers the framed prefix from the log
        instead of truncating committed history away."""
        root = str(tmp_path / "j4")
        bus = JournalBus(root, partitions=2)
        for i in range(10):
            bus.publish("t", str(i), f"msg{i}".encode())
        os.remove(bus._commit_path("t"))
        # readers still see everything (framed-prefix fallback)
        fresh = JournalBus(root, partitions=2)
        assert fresh.topic_size("t") == 10
        # a writer restart publishes without destroying history
        bus2 = JournalBus(root, partitions=2)
        bus2.publish("t", "new", b"msg-new")
        assert JournalBus(root, partitions=2).topic_size("t") == 11

    def test_topic_names_never_collide(self, tmp_path):
        root = str(tmp_path / "j5")
        bus = JournalBus(root, partitions=1)
        bus.publish("evt:1", "a", b"colon")
        bus.publish("evt_1", "a", b"underscore")
        assert bus._log_path("evt:1") != bus._log_path("evt_1")
        assert bus.poll("evt:1", 0, 0, 10) == [b"colon"]
        assert bus.poll("evt_1", 0, 0, 10) == [b"underscore"]

    def test_bus_reusable_after_close_and_trim_bounds_memory(self, tmp_path):
        root = str(tmp_path / "j6")
        bus = JournalBus(root, partitions=1, poll_interval_s=0.005)
        got: list[bytes] = []
        bus.publish("t", "a", b"one")
        bus.subscribe("t", got.append)
        bus.close()
        # a NEW subscriber after close restarts the tailer and still gets
        # the full backlog plus new records
        got2: list[bytes] = []
        bus.subscribe("t", got2.append)
        bus.publish("t", "b", b"two")
        deadline = time.monotonic() + 10
        while len(got2) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got2 == [b"one", b"two"], got2
        bus.close()
        # trim releases the polled window; the journal file keeps everything
        bus3 = JournalBus(root, partitions=1)
        assert len(bus3.poll("t", 0, 0, 10)) == 2
        bus3.trim("t", 0, 2)
        assert bus3.poll("t", 0, 0, 10) == []  # this reader released it
        assert len(JournalBus(root, partitions=1).poll("t", 0, 0, 10)) == 2

    def test_journal_bus_torn_tail_repaired(self, tmp_path):
        """Torn bytes past the commit offset (writer death mid-append) are
        invisible to readers and a restarted writer REPAIRS them — the next
        record must frame correctly, never splice into the torn remainder."""
        import struct

        root = str(tmp_path / "j3")
        bus = JournalBus(root, partitions=2)
        bus.publish("t", "a", b"hello")
        # simulate a torn append: header promising 100 bytes, 10 present,
        # never committed
        with open(bus._log_path("t"), "ab") as f:
            f.write(struct.pack("<IBq", 100, 0, 0) + b"0123456789")
        total = sum(
            len(bus.poll("t", p, 0, 100)) for p in range(bus.partitions)
        )
        assert total == 1  # torn record invisible

        # a restarted writer publishes: the torn tail is truncated under
        # the lock and the new record lands at the commit boundary
        bus2 = JournalBus(root, partitions=2)
        bus2.publish("t", "b", b"world")
        for b in (bus, bus2):
            msgs = [
                bytes(m)
                for p in range(b.partitions)
                for m in b.poll("t", p, 0, 100)
            ]
            assert sorted(msgs) == [b"hello", b"world"], msgs
        # and the log itself holds exactly two well-formed records
        assert bus2.topic_size("t") == 2
