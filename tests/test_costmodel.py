"""Adaptive cost-model planner (ISSUE 9, ROADMAP item 3).

Covers the decision engine (seeded ranking, learned override, bounded
probe cadence, SLO tie-breaking), the planner golden grid (strategy
choice across a selectivity × index-availability grid, the cost-model
override path, the cheap-select fast path), the residual-mask refine
parity, the select dispatch-route fast path (singleton select through
the batched planned steps — red/green pinned against the oracle), the
join route choice, and calibration reporting."""

import numpy as np
import pytest

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.cql import parse as parse_cql
from geomesa_tpu.geometry import Point
from geomesa_tpu.obs import devmon
from geomesa_tpu.obs.devmon import CostTable, ResidencyLedger
from geomesa_tpu.planning import costmodel
from geomesa_tpu.planning.costmodel import Candidate, CostModel
from geomesa_tpu.planning.planner import (
    CHEAP_MAX_RANGES,
    CHEAP_SELECT_ROWS,
    Query,
    QueryPlanner,
    StrategyDecider,
    build_indices,
)
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.stats.store_stats import StoreStats
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000
SPEC = "name:String,age:Integer:index=true,dtg:Date,*geom:Point"


@pytest.fixture()
def fresh():
    """Isolated cost table + cost model for the test; restored after."""
    prev = devmon.install(ResidencyLedger(), CostTable())
    prev_model = costmodel.install()
    yield
    devmon.install(*prev)
    costmodel.install(prev_model)


def _fill(ds, n=3000, seed=7, type_name="evt"):
    rng = np.random.default_rng(seed)
    recs = [
        {
            "name": f"n{i % 40}",
            "age": int(rng.integers(0, 100)),
            "dtg": T0 + int(rng.integers(0, 10 * 86_400_000)),
            "geom": Point(float(rng.uniform(-180, 180)),
                          float(rng.uniform(-90, 90))),
        }
        for i in range(n)
    ]
    ds.write(type_name, recs, fids=[f"f{i}" for i in range(n)])
    ds.compact(type_name)


def _store(n=3000, backend="tpu"):
    ds = DataStore(backend=backend)
    ds.create_schema(parse_spec("evt", SPEC))
    _fill(ds, n)
    return ds


def _planner_fixture(n=3000, indices=None):
    """(sft, indices, stats) over a synthetic table — planner-only tests
    need no store."""
    ds = _store(n)
    st = ds._state("evt")
    idx = dict(st.indices)
    if indices is not None:
        idx = {k: v for k, v in idx.items() if k in indices}
    return st.sft, idx, st.stats


# ---------------------------------------------------------------------------
# decision engine
# ---------------------------------------------------------------------------

class TestCostModelChoose:
    def test_seeded_ranking_before_training(self, fresh):
        m = CostModel(table=CostTable())
        win, ranked, source = m.choose("t", "d", [
            Candidate("a", "sig:a", seed_ms=2.0),
            Candidate("b", "sig:b", seed_ms=1.0),
        ])
        assert (win.name, source) == ("b", "stats")
        assert [c.name for c in ranked] == ["b", "a"]

    def test_learned_override_beats_seeds(self, fresh):
        ct = CostTable()
        m = CostModel(table=ct)
        # seeds say "b"; measurements say "a" is 10x faster
        for _ in range(10):
            ct.observe("t", "sig:a", wall_ms=1.0)
            ct.observe("t", "sig:b", wall_ms=10.0)
        win, _, source = m.choose("t", "d", [
            Candidate("a", "sig:a", seed_ms=2.0),
            Candidate("b", "sig:b", seed_ms=1.0),
        ])
        assert (win.name, source) == ("a", "cost-model")

    def test_partial_training_stays_on_seeds(self, fresh):
        ct = CostTable()
        m = CostModel(table=ct)
        for _ in range(10):
            ct.observe("t", "sig:a", wall_ms=1.0)  # only one side trained
        win, _, source = m.choose("t", "d", [
            Candidate("a", "sig:a", seed_ms=2.0),
            Candidate("b", "sig:b", seed_ms=1.0),
        ], probe=False)
        assert (win.name, source) == ("b", "stats")

    def test_probe_cadence_remeasures_loser(self, fresh):
        ct = CostTable()
        m = CostModel(table=ct)
        for _ in range(10):
            ct.observe("t", "sig:a", wall_ms=1.0)
            ct.observe("t", "sig:b", wall_ms=10.0)
        picks = [
            m.choose("t", "d", [
                Candidate("a", "sig:a", seed_ms=1.0),
                Candidate("b", "sig:b", seed_ms=2.0),
            ])[0].name
            for _ in range(2 * costmodel.PROBE_EVERY)
        ]
        assert picks.count("b") == 2  # exactly the two scheduled probes
        # the probe consults carry source "probe"
        srcs = [
            m.choose("t", "d2", [
                Candidate("a", "sig:a", seed_ms=1.0),
                Candidate("b", "sig:b", seed_ms=2.0),
            ])[2]
            for _ in range(costmodel.PROBE_EVERY)
        ]
        assert srcs.count("probe") == 1

    def test_probe_bounded_by_seed_ratio(self, fresh):
        """A candidate seeded catastrophically worse than the winner is
        never probed — bounded exploration."""
        m = CostModel(table=CostTable())
        picks = [
            m.choose("t", "d", [
                Candidate("cheap", "sig:a", seed_ms=1.0),
                Candidate("scan", "sig:b",
                          seed_ms=costmodel.PROBE_MAX_RATIO * 100.0),
            ])[0].name
            for _ in range(2 * costmodel.PROBE_EVERY)
        ]
        assert picks.count("scan") == 0

    def test_slo_tie_break_prefers_low_variance(self, fresh):
        ct = CostTable()
        m = CostModel(table=ct)
        # a: faster p50, fat tail; b: near-tied p50, tight tail
        for i in range(20):
            ct.observe("t", "sig:a", wall_ms=40.0 if i == 0 else 10.0)
            ct.observe("t", "sig:b", wall_ms=11.0)
        norm, _, _ = m.choose("t", "d1", [
            Candidate("a", "sig:a", seed_ms=1.0),
            Candidate("b", "sig:b", seed_ms=2.0),
        ], probe=False)
        burn, _, src = m.choose("t", "d2", [
            Candidate("a", "sig:a", seed_ms=1.0),
            Candidate("b", "sig:b", seed_ms=2.0),
        ], under_burn=True, probe=False)
        assert norm.name == "a"  # p50 wins un-burned
        assert (burn.name, src) == ("b", "cost-model/slo")

    def test_select_route_flips_with_observations(self, fresh):
        ct = CostTable()
        m = CostModel(table=ct)
        assert m.choose_select_route("t") == "twopass"  # seeded default
        for _ in range(10):
            ct.observe("t", "sel:twopass", wall_ms=20.0)
            ct.observe("t", "sel:planned", wall_ms=2.0)
        assert m.choose_select_route("t") == "planned"

    def test_join_route_seeds_by_density(self, fresh):
        m = CostModel(table=CostTable())
        assert m.choose_join_path("t", 0.01) == "block"
        assert m.choose_join_path("t2", 0.9) == "dense"


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

class TestCalibration:
    def test_record_and_report(self, fresh):
        m = costmodel.model()
        m.record_calibration("t", "z3:iv8:rows", 10.0, 8.0)
        m.record_calibration("t", "z3:iv8:rows", 10.0, 10.0)
        rep = m.calibration_report()
        assert rep["entry_count"] == 1 and rep["samples"] == 2
        e = rep["entries"][0]
        assert e["count"] == 2
        assert e["mean_abs_rel_err"] == pytest.approx(0.125, abs=1e-3)
        assert e["mean_signed_rel_err"] == pytest.approx(0.125, abs=1e-3)
        assert rep["overall_mean_abs_rel_err"] == pytest.approx(
            0.125, abs=1e-3)

    def test_forget_drops_type(self, fresh):
        m = costmodel.model()
        m.record_calibration("gone", "z3:rows", 1.0, 1.0)
        m.record_calibration("kept", "z3:rows", 1.0, 1.0)
        m.forget("gone")
        types = {e["type"] for e in m.calibration_report()["entries"]}
        assert types == {"kept"}

    def test_queries_feed_calibration(self, fresh):
        """The audit path records predicted-vs-actual once the plan shape
        has a usable prior."""
        ds = _store(2000)
        cql = "BBOX(geom, -60, -30, 60, 30)"
        for _ in range(8):
            ds.query("evt", cql)
        rep = costmodel.model().calibration_report()
        assert rep["samples"] >= 1
        assert any(e["type"] == "evt" for e in rep["entries"])

    def test_explain_analyze_renders_calibration_and_alternatives(
            self, fresh):
        ds = _store(2000)
        cql = ("BBOX(geom, -60, -30, 60, 30) AND "
               "dtg AFTER 2017-07-02T00:00:00Z")
        for _ in range(5):
            ds.query("evt", cql)
        ea = ds.explain("evt", cql, analyze=True)
        assert ea.cost["calibration_error"] is not None
        assert ea.cost["strategy_source"]
        # the z3/z2 decision has at least one rejected alternative
        assert ea.cost["alternatives"]
        text = str(ea)
        assert "calibration error" in text
        assert "Rejected:" in text

    def test_schema_delete_purges_calibration(self, fresh):
        ds = _store(2000)
        for _ in range(6):
            ds.query("evt", "BBOX(geom, -60, -30, 60, 30)")
        ds.delete_schema("evt")
        assert not any(
            e["type"] == "evt"
            for e in costmodel.model().calibration_report()["entries"]
        )


# ---------------------------------------------------------------------------
# planner golden grid
# ---------------------------------------------------------------------------

BOX_TIME = ("BBOX(geom, -60, -30, 60, 30) AND "
            "dtg DURING 2017-07-02T00:00:00Z/2017-07-05T00:00:00Z")
BOX_ONLY = "BBOX(geom, -60, -30, 60, 30)"
ATTR_EQ = "age = 17"


class TestPlannerGolden:
    """Strategy choice pinned across selectivity × index availability."""

    def _choose(self, cql, indices=None, stats=True, hints=None,
                cost_model=None, type_name="evt", under_burn=False):
        sft, idx, st_stats = _planner_fixture(indices=indices)
        f = parse_cql(cql)
        from geomesa_tpu.filter.bounds import coerce_attr_bounds, extract

        e = extract(f, sft.geom_field, sft.dtg_field,
                    attrs=tuple(n.split(":", 1)[1] for n in idx
                                if n.startswith("attr:")))
        e = coerce_attr_bounds(sft, e)
        dec = {}
        name, _ = StrategyDecider.choose(
            idx, e, f, hints or {}, st_stats if stats else None,
            type_name=type_name, cost_model=cost_model,
            under_burn=under_burn, decision=dec,
        )
        return name, dec

    def test_grid_spatiotemporal_prefers_z3(self, fresh):
        name, dec = self._choose(BOX_TIME)
        assert name == "z3" and dec["source"] == "stats"

    def test_grid_spatial_only_prefers_z2(self, fresh):
        name, _ = self._choose(BOX_ONLY)
        assert name == "z2"

    def test_grid_spatial_only_without_z2_takes_z3(self, fresh):
        name, _ = self._choose(BOX_ONLY, indices=["z3", "id"])
        assert name == "z3"

    def test_grid_selective_attr_equality_wins(self, fresh):
        # ~1% selectivity on the attr index vs a loose half-world box:
        # the penalized attr estimate still undercuts the spatial cover
        name, dec = self._choose(f"{ATTR_EQ} AND BBOX(geom,-179,-89,179,89)")
        assert name == "attr:age"
        assert dec["est_rows"] <= 3000 * 0.05

    def test_grid_no_stats_heuristic(self, fresh):
        name, dec = self._choose(BOX_TIME, stats=False)
        assert name == "z3" and dec["source"] == "heuristic"

    def test_grid_forced_hint_wins(self, fresh):
        name, dec = self._choose(BOX_TIME, hints={"index": "z2"})
        assert name == "z2" and dec["source"] == "forced"

    def test_cost_model_override_flips_choice(self, fresh):
        """Stats prefer z3 for bbox+time; inject measurements proving z2
        serves this type faster — the trained model overrides."""
        ct = CostTable()
        model = CostModel(table=ct)
        for _ in range(10):
            ct.observe("evt", "z3:iv64:rows", wall_ms=50.0)
            ct.observe("evt", "z2:iv64:rows", wall_ms=1.0)
            ct.observe("evt", "attr:age:rows", wall_ms=2.0)
        name, dec = self._choose(BOX_TIME, cost_model=model)
        assert name == "z2"
        assert dec["source"] == "cost-model"
        assert dec["predicted_ms"] is not None
        # rejected alternatives carry their estimates + observations
        alt_names = {a["name"] for a in dec["alternatives"]}
        assert "z3" in alt_names
        z3_alt = next(a for a in dec["alternatives"] if a["name"] == "z3")
        assert z3_alt["observed_ms_p50"] == pytest.approx(50.0, rel=0.2)

    def test_strategy_probe_cadence(self, fresh):
        """Every PROBE_EVERY-th strategy consult re-measures the losing
        index (bounded: seeds within PROBE_MAX_RATIO)."""
        ct = CostTable()
        model = CostModel(table=ct)
        for _ in range(10):
            ct.observe("evt", "z3:iv64:rows", wall_ms=1.0)
            ct.observe("evt", "z2:iv64:rows", wall_ms=5.0)
        picks = [
            self._choose(BOX_TIME, cost_model=model)[0]
            for _ in range(2 * costmodel.PROBE_EVERY)
        ]
        assert picks.count("z3") > picks.count("z2") > 0

    def test_cheap_fast_path_reduces_range_budget(self, fresh):
        """High-selectivity selects skip the union search and decompose
        with the reduced budget; loose ones keep the full machinery."""
        sft, idx, stats = _planner_fixture()
        planner = QueryPlanner(sft, idx, stats, cost_model=False)
        # a tiny box: estimate ≪ CHEAP_SELECT_ROWS
        _, _, info = planner.plan(Query(filter=(
            "BBOX(geom, 10, 10, 10.5, 10.5) AND "
            "dtg DURING 2017-07-02T00:00:00Z/2017-07-02T06:00:00Z")))
        assert info.cheap
        assert info.n_intervals <= CHEAP_MAX_RANGES
        assert any("cheap fast path" in n for n in info.notes)
        # a loose half-world box: not cheap once the threshold sits
        # below its estimate (test stores are far smaller than the
        # production absolute threshold)
        import geomesa_tpu.planning.planner as planner_mod

        saved = planner_mod.CHEAP_SELECT_ROWS
        planner_mod.CHEAP_SELECT_ROWS = 100
        try:
            _, _, info2 = planner.plan(Query(filter=BOX_ONLY))
        finally:
            planner_mod.CHEAP_SELECT_ROWS = saved
        assert not info2.cheap
        assert info2.est_rows > 100

    def test_cheap_path_results_identical(self, fresh):
        """Red/green: the reduced range budget only widens the int-domain
        superset — result rows are identical to the oracle referee."""
        ds = _store(3000)
        ref = DataStore(backend="oracle")
        ref.create_schema(parse_spec("evt", SPEC))
        _fill(ref)
        cql = ("BBOX(geom, 10, 10, 14, 14) AND "
               "dtg DURING 2017-07-02T00:00:00Z/2017-07-03T00:00:00Z")
        r = ds.query("evt", cql)
        assert r.plan_info.cheap
        assert sorted(r.table.fids.tolist()) == sorted(
            ref.query("evt", cql).table.fids.tolist())

    def test_static_explain_renders_strategy_block(self, fresh):
        ds = _store(2000)
        text = ds.explain("evt", BOX_TIME)
        assert "Strategy:" in text
        assert "Rejected:" in text


# ---------------------------------------------------------------------------
# residual mask (the refine fast path)
# ---------------------------------------------------------------------------

class TestResidualMask:
    def _table(self, n=500):
        ds = _store(n)
        return ds._state("evt").table

    @pytest.mark.parametrize("cql", [
        "BBOX(geom, -60, -30, 60, 30)",
        "BBOX(geom, -60, -30, 60, 30) AND dtg AFTER 2017-07-04T00:00:00Z",
        "age BETWEEN 10 AND 40",
        "name = 'n3' OR age > 90",
        "NOT (age < 50)",
        "INTERSECTS(geom, POLYGON((-10 -10, 10 -10, 10 10, -10 10, -10 -10)))",
        "name LIKE 'n1%'",
        "age IS NULL",
        "IN ('f1', 'f7', 'f99')",
        "INCLUDE",
    ])
    def test_parity_with_full_take(self, cql):
        table = self._table()
        f = parse_cql(cql)
        rng = np.random.default_rng(3)
        rows = np.sort(rng.choice(len(table), size=200, replace=False))
        got = ast.residual_mask(f, table, rows)
        want = np.asarray(f.mask(table.take(rows)), dtype=bool)
        assert got.dtype == np.bool_
        assert (got == want).all()

    def test_opaque_node_falls_back(self):
        class Weird(ast.Filter):
            def mask(self, table):
                return np.arange(len(table)) % 2 == 0

        table = self._table(100)
        rows = np.arange(0, 100, 3)
        got = ast.residual_mask(Weird(), table, rows)
        want = Weird().mask(table.take(rows))
        assert (got == want).all()

    def test_column_refs(self):
        f = parse_cql("BBOX(geom,0,0,1,1) AND (age > 3 OR name = 'x')")
        props, fids, opaque = ast.column_refs(f)
        assert props == {"geom", "age", "name"}
        assert not fids and not opaque
        props, fids, _ = ast.column_refs(parse_cql("IN ('a','b')"))
        assert fids and not props


# ---------------------------------------------------------------------------
# select dispatch route (the bench-6 fast path)
# ---------------------------------------------------------------------------

class TestSelectRoute:
    def test_singleton_planned_route_red_green(self, fresh):
        """Red/green pin: force the planned route (the batched block-pair
        steps run with a singleton batch) and require byte-identical row
        sets vs the oracle referee — the fast path must never change
        results, only cost."""
        ds = _store(4000)
        ref = DataStore(backend="oracle")
        ref.create_schema(parse_spec("evt", SPEC))
        _fill(ref, 4000)
        cql = ("BBOX(geom, -90, -45, 90, 45) AND "
               "dtg DURING 2017-07-02T00:00:00Z/2017-07-06T00:00:00Z")
        ct = devmon.costs()
        # train the table so the planned route wins outright
        for _ in range(10):
            ct.observe("evt", "sel:planned", wall_ms=1.0)
            ct.observe("evt", "sel:twopass", wall_ms=50.0)
        assert costmodel.model().choose_select_route("evt") == "planned"
        got = ds.query("evt", cql)
        want = ref.query("evt", cql)
        assert sorted(got.table.fids.tolist()) == sorted(
            want.table.fids.tolist())
        # the dispatch observed its route (planned gains an observation
        # beyond the 10 injected)
        assert ct.predict("evt", "sel:planned")["observations"] >= 11

    def test_route_observations_accumulate(self, fresh):
        ds = _store(2000)
        for _ in range(4):
            ds.query("evt", "BBOX(geom, -60, -30, 60, 30)")
        p = devmon.costs().predict("evt", "sel:twopass")
        assert p is not None and p["observations"] >= 4

    def test_exec_cache_reused_on_cached_plans(self, fresh):
        """The plan-cache-hit path memoizes the dispatch payload: the
        second identical query reuses the staged split instead of
        re-deriving it (and results stay identical)."""
        ds = _store(2000)
        cql = "BBOX(geom, -60, -30, 60, 30)"
        r1 = ds.query("evt", cql)
        st = ds._state("evt")
        key = ds._plan_cache_key(Query(filter=cql))
        plan, _, _ = st.plan_cache[key]
        assert plan.exec_cache  # populated by the first dispatch
        memo_before = dict(plan.exec_cache)
        r2 = ds.query("evt", cql)
        assert plan.exec_cache == memo_before  # reused, not rebuilt
        assert sorted(r1.table.fids.tolist()) == sorted(
            r2.table.fids.tolist())


# ---------------------------------------------------------------------------
# stats selectivity API
# ---------------------------------------------------------------------------

class TestSelectivity:
    def test_fraction_and_rows_compose(self, fresh):
        ds = _store(3000)
        stats: StoreStats = ds._state("evt").stats
        assert stats.selectivity(parse_cql("INCLUDE")) == pytest.approx(
            1.0, abs=0.05)
        # a superset cover estimate: may overshoot the true 0.5 fraction
        half = stats.selectivity(parse_cql("BBOX(geom,-180,-90,0,90)"))
        assert 0.3 < half < 0.85
        tiny = stats.selectivity(parse_cql("BBOX(geom,10,10,10.2,10.2)"))
        assert tiny < 0.01
        # attribute bounds compose via min
        both = stats.selectivity(
            parse_cql("age = 17 AND BBOX(geom,-180,-90,0,90)"))
        assert both <= min(
            half, stats.selectivity(parse_cql("age = 17"))) + 1e-9

    def test_disjoint_is_zero(self, fresh):
        ds = _store(1000)
        stats = ds._state("evt").stats
        assert stats.estimate_filter_rows(parse_cql("age = 5 AND age = 9")) \
            == 0.0

    def test_stats_count_uses_shared_estimator(self, fresh):
        ds = _store(3000)
        est = ds.stats_count("evt", "BBOX(geom,-180,-90,0,90)")
        stats = ds._state("evt").stats
        assert est == pytest.approx(
            stats.estimate_filter_rows(parse_cql("BBOX(geom,-180,-90,0,90)")))


# ---------------------------------------------------------------------------
# join route
# ---------------------------------------------------------------------------

class TestJoinRoute:
    def _poly_store(self, n=3000):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("evt", SPEC))
        _fill(ds, n)
        from geomesa_tpu.geometry.types import Polygon

        ring = np.array([[-20.0, -20.0], [20.0, -20.0], [20.0, 20.0],
                         [-20.0, 20.0], [-20.0, -20.0]])
        return ds, [Polygon(ring)]

    def test_join_counts_auto_routes_and_records(self, fresh):
        from geomesa_tpu.process.join import (
            join_counts_auto,
            measured_pair_density,
        )

        ds, polys = self._poly_store()
        density = measured_pair_density(ds, "evt", polys)
        assert density is not None and 0.0 < density <= 1.0
        counts, route = join_counts_auto(ds, "evt", polys)
        assert route in ("block", "dense")
        # parity vs the exact host predicate
        from geomesa_tpu.geometry import predicates as P

        col = ds._state("evt").table.geom_column()
        want = int(P.points_within_geom(col.x, col.y, polys[0]).sum())
        assert int(counts[0]) == want
        # the run recorded its route signature
        assert devmon.costs().predict("evt", f"join:{route}") is not None

    def test_join_route_flips_with_observations(self, fresh):
        from geomesa_tpu.process.join import join_counts_auto

        ds, polys = self._poly_store()
        ct = devmon.costs()
        for _ in range(10):
            ct.observe("evt", "join:block", wall_ms=50.0)
            ct.observe("evt", "join:dense", wall_ms=1.0)
        counts, route = join_counts_auto(ds, "evt", polys)
        assert route == "dense"


# ---------------------------------------------------------------------------
# zero-recompile census (the steady select path)
# ---------------------------------------------------------------------------

class TestZeroRecompiles:
    def test_steady_select_path_zero_recompiles(self, fresh):
        """THE J003 contract for the adaptive select route: once BOTH
        dispatch routes are warm (one full probe cycle covers the planned
        singleton route too), further queries — including the scheduled
        probes — add no new compile signatures and never recompile
        (jaxmon census pin). The fast path must share the batched steps'
        jit cache, not grow its own."""
        from geomesa_tpu.obs import jaxmon

        ds = _store(2000)
        cql = ("BBOX(geom, -60, -30, 60, 30) AND "
               "dtg DURING 2017-07-02T00:00:00Z/2017-07-06T00:00:00Z")
        # warm: one full probe cycle exercises twopass AND the planned
        # probe consult, compiling every shape the steady path can touch
        for _ in range(costmodel.PROBE_EVERY + 2):
            ds.query("evt", cql)
        before = jaxmon.jit_report()
        for _ in range(costmodel.PROBE_EVERY + 2):
            ds.query("evt", cql)
        after = jaxmon.jit_report()
        assert (after.get("recompiles", 0) - before.get("recompiles", 0)) == 0
        assert set(after["steps"]) == set(before["steps"])


# ---------------------------------------------------------------------------
# review-pass regression pins
# ---------------------------------------------------------------------------

class TestReviewPins:
    def test_probe_plans_never_cached(self, fresh):
        """A probe-tick plan deliberately took the LOSING strategy; caching
        it would replay the loser for every later identical query. The
        plan store must skip it (and the next identical query caches a
        normal plan)."""
        ds = _store(1000)
        st = ds._state("evt")
        planner = QueryPlanner(st.sft, st.indices, st.stats,
                               cost_model=False)
        q = Query(filter="BBOX(geom, -10, -10, 10, 10)")
        plan, f, info = planner.plan(q)
        info.strategy_source = "probe"
        key = ds._plan_cache_key(q)
        ds._plan_store(st, st.indices, key, (plan, f, info))
        assert key not in st.plan_cache
        info.strategy_source = "cost-model"
        ds._plan_store(st, st.indices, key, (plan, f, info))
        assert key in st.plan_cache

    def test_zero_seed_floor_skips_probe(self, fresh):
        """A 0-row best estimate gives the PROBE_MAX_RATIO bound nothing
        to anchor on: the probe is skipped, never unbounded."""
        m = CostModel(table=CostTable())
        picks = [
            m.choose("t", "d", [
                Candidate("tiny", "sig:a", est_rows=0.0),
                Candidate("scan", "sig:b", est_rows=1e7),
            ])[0].name
            for _ in range(2 * costmodel.PROBE_EVERY)
        ]
        assert picks.count("scan") == 0

    def test_wide_plan_payload_not_memoized(self, fresh):
        """Dispatch payloads above the slot cap re-derive per query
        instead of pinning unaccounted device arrays in the plan cache."""
        from geomesa_tpu.store import backends as B

        ds = _store(2000)
        saved = B._EXEC_MEMO_MAX_SLOTS
        B._EXEC_MEMO_MAX_SLOTS = 1  # force every payload over the cap
        try:
            cql = "BBOX(geom, -60, -30, 60, 30)"
            r1 = ds.query("evt", cql)
            st = ds._state("evt")
            plan, _, _ = st.plan_cache[ds._plan_cache_key(Query(filter=cql))]
            assert not plan.exec_cache  # over the cap: nothing pinned
            r2 = ds.query("evt", cql)  # still correct, just re-derived
            assert sorted(r1.table.fids.tolist()) == sorted(
                r2.table.fids.tolist())
        finally:
            B._EXEC_MEMO_MAX_SLOTS = saved
