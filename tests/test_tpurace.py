"""tpurace: fixture tests pin exact (rule, line) findings per rule, the
gate test runs the whole-program analysis over the package against the
committed baseline, and the sanitizer unit tests drive REAL threads
through deliberate lock orders.

Like tpulint, the static prong is pure AST (fixtures under
``tpurace_fixtures/`` are never imported) and runs with JAX gated off.
The sanitizer tests snapshot/restore the global lock-order graph so a
deliberately-created cycle can never leak into (or mask findings of)
the session-end gate that ``GEOMESA_TPU_SANITIZE=1`` arms.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from geomesa_tpu.analysis import (
    LintConfig,
    apply_baseline,
    load_baseline,
)
from geomesa_tpu.analysis.race import (
    RACE_RULE_IDS,
    analyze_race_paths,
    guard_map,
)
from geomesa_tpu.analysis.race import sanitizer
from geomesa_tpu.analysis.core import iter_py_files, parse_module

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "geomesa_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tpurace_fixtures")
BASELINE = os.path.join(REPO, ".tpulint-baseline.json")
# fixtures live outside the package tree: open the path-scoped knobs up
RACE_CFG = LintConfig(race_paths=("",), r003_paths=("",))


def _race(name):
    vs = analyze_race_paths([os.path.join(FIXTURES, name)], RACE_CFG)
    return [(v.rule, v.line) for v in vs if not v.suppressed]


def _modules(paths):
    out = []
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as f:
            mod = parse_module(f.read(), fp)
        if hasattr(mod, "tree"):  # skip E000 Violations
            out.append(mod)
    return out


class TestRuleFixtures:
    """Each rule flags its known-bad fixture at exact lines and stays
    silent on the known-good twin."""

    @pytest.mark.parametrize("name,expected", [
        # bare dict.pop + bare counter bump + typed cross-class assignment
        ("r001_bad.py", [("R001", 29), ("R001", 32), ("R001", 40)]),
        # cycle closed through the call graph; anchored at the BA nesting
        ("r002_bad.py", [("R002", 23)]),
        # sleep + open inside the critical section
        ("r003_bad.py", [("R003", 15), ("R003", 16)]),
        # two stale waivers (same-line and next-line forms)
        ("w001_bad.py", [("W001", 15), ("W001", 19)]),
    ])
    def test_bad_fixture_flagged(self, name, expected):
        assert _race(name) == expected

    @pytest.mark.parametrize("name", [
        "r001_good.py", "r002_good.py", "r003_good.py", "w001_good.py",
    ])
    def test_good_fixture_clean(self, name):
        assert _race(name) == []

    def test_live_waiver_suppresses_r001(self):
        vs = analyze_race_paths(
            [os.path.join(FIXTURES, "w001_good.py")], RACE_CFG)
        assert [(v.rule, v.waived) for v in vs] == [("R001", True)]


class TestGuardMap:
    def test_fixture_inference(self):
        gm = guard_map(_modules([os.path.join(FIXTURES, "r001_bad.py")]),
                       RACE_CFG)
        items = gm["Registry._items"]
        # put/replace/_rebuild_locked guarded; evict + Admin.wipe bare
        assert items["guard"] == "Registry._lock"
        assert (items["guarded_writes"], items["total_writes"]) == (3, 5)
        assert gm["Registry._epoch"]["guard"] == "Registry._lock"

    def test_duplicate_class_names_stay_analyzed(self):
        """The repo has namesake classes (utils/metrics.Histogram vs
        stats/sketches.Histogram). Bare-name TYPING is unresolvable for
        them, but the classes themselves must stay in the pass under
        module-qualified ids — dropping one would silently exempt its
        locks and writes from R001-R003."""
        gm = guard_map(_modules([PKG]), LintConfig())
        qualified = [k for k in gm if k.startswith("utils.metrics.Histogram.")]
        assert qualified, sorted(gm)
        assert (gm["utils.metrics.Histogram._reservoir"]["guard"]
                == "utils.metrics.Histogram._lock")

    def test_package_guard_map_pins_known_guards(self):
        """The inferred guard map on the REAL tree must keep resolving the
        repo idioms: the journal's reader-index state behind the bus lock,
        and _TypeState's snapshot-swap fields behind st.lock even though
        the writes happen in DataStore methods via a typed local."""
        gm = guard_map(_modules([PKG]), LintConfig())
        assert gm["JournalBus._tailer"]["guard"] == "JournalBus._lock"
        assert gm["_TypeState.table"]["guard"] == "_TypeState.lock"
        assert gm["_TypeState.indices"]["guard"] == "_TypeState.lock"
        assert gm["MessageBus._plogs"]["guard"] == "MessageBus._lock"
        for info in gm.values():
            assert 2 * info["guarded_writes"] > info["total_writes"]


class TestPackageRaceGate:
    """THE gate: zero unwaived R001/R002/R003 on the committed tree."""

    def test_package_clean_against_baseline(self):
        vs = analyze_race_paths([PKG], LintConfig())
        apply_baseline(vs, load_baseline(BASELINE))
        new = [v for v in vs if not v.suppressed]
        assert new == [], "\n".join(
            f"{v.path}:{v.line}: {v.rule} {v.message}" for v in new)

    def test_known_waivers_are_live(self):
        """The two committed R003 waivers (journal read under the bus
        lock, jaxmon one-time listener registration) must keep
        suppressing real findings — if they go stale, W001 fires here."""
        vs = analyze_race_paths([PKG], LintConfig())
        waived = {(os.path.basename(v.path), v.rule)
                  for v in vs if v.waived}
        assert ("journal.py", "R003") in waived
        assert ("jaxmon.py", "R003") in waived


class TestCliRace:
    def _run(self, *args):
        env = dict(os.environ, GEOMESA_TPU_NO_JAX="1")
        return subprocess.run(
            [sys.executable, "-m", "geomesa_tpu.analysis", *args],
            capture_output=True, text=True, cwd=REPO, env=env,
        )

    def test_race_gate_exits_zero(self):
        out = self._run("--race", PKG, "--baseline", BASELINE)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_race_violations_exit_nonzero(self):
        out = self._run("--race", os.path.join(FIXTURES, "r002_bad.py"))
        # fixture lives outside the default r003/race scopes, but R002
        # is path-unscoped: the CLI must still fail on it
        assert out.returncode == 1
        assert "R002" in out.stdout

    def test_guards_json(self):
        out = self._run("--race", "--guards", PKG)
        assert out.returncode == 0, out.stdout + out.stderr
        gm = json.loads(out.stdout)
        assert gm["JournalBus._tailer"]["guard"] == "JournalBus._lock"

    def test_list_rules_includes_race(self):
        out = self._run("--list-rules")
        for rid in (*RACE_RULE_IDS, "W001"):
            assert rid in out.stdout

    def test_rules_filter_applies_in_race_mode(self):
        # r001_bad has R001 findings only: masking them with --rules R003
        # must exit clean, selecting R001 must still fail
        bad = os.path.join(FIXTURES, "r001_bad.py")
        assert self._run("--race", bad, "--rules", "R003").returncode == 0
        out = self._run("--race", bad, "--rules", "R001")
        assert out.returncode == 1 and "R001" in out.stdout

    def test_unknown_rule_id_is_a_usage_error(self):
        out = self._run("--race", PKG, "--rules", "R999")
        assert out.returncode == 2

    def test_mode_mismatched_rules_are_a_usage_error(self):
        """--rules that selects nothing in the chosen mode must not exit
        0 (a misconfigured CI gate would read as clean forever)."""
        bad = os.path.join(FIXTURES, "r003_bad.py")
        out = self._run(bad, "--rules", "R003")  # race rule, no --race
        assert out.returncode == 2, out.stdout + out.stderr
        out = self._run("--race", bad, "--rules", "J001")  # lint-only set
        assert out.returncode == 2, out.stdout + out.stderr
        out = self._run(bad, "--rules", "W001")  # judges nothing alone
        assert out.returncode == 2, out.stdout + out.stderr


class _SanitizerHarness:
    """Install (if the env gate didn't already), isolate global state."""

    def __enter__(self):
        self._was_installed = sanitizer.installed()
        self._snap = sanitizer.snapshot()
        if not self._was_installed:
            sanitizer.install()
        sanitizer.reset()
        return sanitizer

    def __exit__(self, *exc):
        sanitizer.restore(self._snap)
        if not self._was_installed:
            sanitizer.uninstall()
        return False


class TestSanitizer:
    def test_consistent_order_is_clean(self):
        with _SanitizerHarness() as san:
            a = threading.Lock()
            b = threading.Lock()

            def work():
                for _ in range(50):
                    with a:
                        with b:
                            pass

            threads = [threading.Thread(target=work) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert san.cycle_report() == []
            assert san.edges()  # the A->B edge was recorded

    def test_opposite_orders_cycle_without_deadlocking(self):
        """The Eraser property: the two orders run at DIFFERENT times, no
        deadlock ever happens on this schedule — the sanitizer still
        convicts the order inversion."""
        with _SanitizerHarness() as san:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass

            def reversed_order():
                with b:
                    with a:
                        pass

            t = threading.Thread(target=reversed_order)
            t.start()
            t.join()
            report = san.cycle_report()
            assert len(report) == 1
            assert len(report[0]["cycle"]) == 3  # A -> B -> A
            with pytest.raises(sanitizer.LockOrderError):
                san.check()

    def test_rlock_reentry_is_not_an_edge(self):
        with _SanitizerHarness() as san:
            r = threading.RLock()

            def work():
                with r:
                    with r:  # re-entry: no self-edge, no cycle
                        pass

            work()
            assert san.cycle_report() == []

    def test_wrapping_scope_is_repo_only(self):
        with _SanitizerHarness():
            here = threading.Lock()  # created from tests/: wrapped
            assert type(here).__name__ == "_SanitizedLock"
            # an Event's internal Condition lock is created inside
            # threading.py: must stay a native primitive
            ev = threading.Event()
            assert "Sanitized" not in type(ev._cond._lock).__name__

    def test_condition_wait_rerecords_held_lock(self):
        """Condition(our RLock) interop: _release_save drops the lock
        across wait() and _acquire_restore RE-RECORDS it — an ordering
        edge taken after the wait must not become invisible."""
        with _SanitizerHarness() as san:
            r = threading.RLock()
            other = threading.Lock()
            cond = threading.Condition(r)
            parked = threading.Event()

            def waiter():
                with cond:
                    parked.set()
                    cond.wait(timeout=5.0)
                    with other:  # edge r -> other, taken AFTER the wait
                        pass

            t = threading.Thread(target=waiter)
            t.start()
            assert parked.wait(timeout=5.0)
            time.sleep(0.05)  # let the waiter actually park in wait()
            with cond:
                cond.notify()
            t.join(timeout=5.0)
            assert not t.is_alive()
            r_site, other_site = r._site, other._site
            assert other_site in san.edges().get(r_site, []), san.edges()

    def test_lock_semantics_preserved(self):
        with _SanitizerHarness():
            lk = threading.Lock()
            assert lk.acquire(False)
            assert lk.locked()
            assert not lk.acquire(False)
            lk.release()
            assert not lk.locked()
