"""OSM converter (nodes/ways) + converter scripting-function registry."""

import numpy as np
import pytest

from geomesa_tpu.convert.delimited import (
    DelimitedConverter,
    register_function,
    unregister_function,
)
from geomesa_tpu.convert.osm import (
    OsmConverter,
    parse_osm_nodes,
    parse_osm_ways,
)
from geomesa_tpu.schema.sft import parse_spec

OSM_XML = """<?xml version="1.0"?>
<osm version="0.6">
  <node id="101" lat="48.137" lon="11.575" user="alice"
        timestamp="2020-05-01T10:00:00Z">
    <tag k="amenity" v="cafe"/>
    <tag k="name" v="Cafe Eins"/>
  </node>
  <node id="102" lat="48.140" lon="11.580" user="bob"
        timestamp="2020-05-02T11:30:00Z"/>
  <node id="103" lat="48.150" lon="11.590" user="bob"
        timestamp="2020-05-02T11:31:00Z"/>
  <node id="999" lat="95.0" lon="200.0" user="bad"/>
  <way id="7" user="carol" timestamp="2020-06-01T00:00:00Z">
    <nd ref="101"/> <nd ref="102"/> <nd ref="103"/>
    <tag k="highway" v="primary"/>
    <tag k="name" v="Main St"/>
  </way>
  <way id="8" user="carol">
    <nd ref="102"/> <nd ref="77777"/>
  </way>
  <way id="9" user="carol">
    <nd ref="101"/>
  </way>
</osm>
"""


class TestOsmNodes:
    def test_all_nodes(self):
        t = parse_osm_nodes(OSM_XML)
        # node 999 has out-of-range coords and is dropped
        assert len(t) == 3
        assert list(t.fids) == ["n101", "n102", "n103"]
        ids = t.columns["osmId"].values
        assert list(ids) == [101, 102, 103]
        g = t.geom_column()
        assert g.x[0] == pytest.approx(11.575)
        assert g.y[0] == pytest.approx(48.137)
        assert "amenity=cafe" in t.columns["tags"].values[0]
        # timestamps parsed to epoch millis
        assert t.dtg_millis()[0] == 1588327200000

    def test_tagged_only_and_promoted_tags(self):
        t = parse_osm_nodes(OSM_XML, tag_fields=("amenity",), tagged_only=True)
        assert len(t) == 1
        assert t.columns["amenity"].values[0] == "cafe"
        # promoted key is excluded from the residual tags text
        assert "amenity" not in t.columns["tags"].values[0]
        assert "name=Cafe Eins" in t.columns["tags"].values[0]

    def test_converter_facade_queryable(self):
        from geomesa_tpu.store.datastore import DataStore

        conv = OsmConverter(mode="nodes", type_name="osm_n")
        table = conv.convert_str(OSM_XML)
        ds = DataStore()
        ds.create_schema(conv.sft)
        ds.write("osm_n", table)
        res = ds.query("osm_n", "BBOX(geom, 11.5, 48.0, 11.6, 48.2)")
        assert len(res.table) == 3


class TestOsmWays:
    def test_ways_resolved(self):
        t = parse_osm_ways(OSM_XML)
        # way 8 has an unresolvable ref, way 9 has <2 nodes: both skipped
        assert len(t) == 1
        assert list(t.fids) == ["w7"]
        assert t.columns["nNodes"].values[0] == 3
        geom = t.geom_column().values[0]
        assert geom.coords.shape == (3, 2)
        np.testing.assert_allclose(geom.coords[0], [11.575, 48.137])
        np.testing.assert_allclose(geom.coords[2], [11.590, 48.150])
        assert "highway=primary" in t.columns["tags"].values[0]

    def test_ways_xz2_query(self):
        from geomesa_tpu.store.datastore import DataStore

        conv = OsmConverter(mode="ways", type_name="osm_w")
        ds = DataStore()
        ds.create_schema(conv.sft)
        ds.write("osm_w", conv.convert_str(OSM_XML))
        hit = ds.query("osm_w", "BBOX(geom, 11.57, 48.13, 11.60, 48.16)")
        assert len(hit.table) == 1
        miss = ds.query("osm_w", "BBOX(geom, -10, -10, -5, -5)")
        assert len(miss.table) == 0


class TestScriptingFunctions:
    def test_string_builtins(self):
        sft = parse_spec("s", "a:String,b:String,*geom:Point")
        conv = DelimitedConverter(
            sft,
            fields={
                "a": "upper($1)",
                "b": "replace(trim($2), 'x', 'y')",
                "geom": "point($3, $4)",
            },
            header=False,
        )
        t = conv.convert_str("ab, xo x ,1,2\ncd,xx,3,4\n")
        assert list(t.columns["a"].values) == ["AB", "CD"]
        assert list(t.columns["b"].values) == ["yo y", "yy"]

    def test_registered_vectorized(self):
        register_function("geohash4", lambda c: np.asarray(
            [s[:4] for s in c], dtype=object))
        try:
            sft = parse_spec("s", "g:String,*geom:Point")
            conv = DelimitedConverter(
                sft, fields={"g": "geohash4($1)", "geom": "point($2, $3)"},
                header=False,
            )
            t = conv.convert_str("u4pruydq,10,50\n")
            assert t.columns["g"].values[0] == "u4pr"
        finally:
            unregister_function("geohash4")

    def test_registered_scalar(self):
        register_function(
            "pad5", lambda v: str(v).zfill(5), vectorized=False)
        try:
            sft = parse_spec("s", "g:String,*geom:Point")
            conv = DelimitedConverter(
                sft, fields={"g": "pad5($1)", "geom": "point($2, $3)"},
                header=False,
            )
            t = conv.convert_str("42,10,50\n7,11,51\n")
            assert list(t.columns["g"].values) == ["00042", "00007"]
        finally:
            unregister_function("pad5")

    def test_shadow_builtin_rejected(self):
        with pytest.raises(ValueError):
            register_function("point", lambda c: c)

    def test_cli_osm_ingest(self, tmp_path):
        from geomesa_tpu.cli.__main__ import main

        src = tmp_path / "extract.osm"
        src.write_text(OSM_XML)
        cat = tmp_path / "cat"
        main(["ingest", "-c", str(cat), "-n", "osm_cli",
              "--converter", "osm-nodes", str(src)])
        dst = tmp_path / "out.csv"
        main(["export", "-c", str(cat), "-n", "osm_cli",
              "-q", "BBOX(geom, 11.5, 48.0, 11.6, 48.2)",
              "--format", "csv", "-o", str(dst)])
        body = dst.read_text()
        assert "101" in body and "alice" in body
