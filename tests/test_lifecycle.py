"""Capstone lifecycle test: the full 'switching from GeoMesa' user journey
in one pass — schema DDL → config-driven ingest → CQL breadth → analytics →
SQL → paging/export → persistence round-trip → streaming tier → HBM tier
controls → schema evolution → modify/delete — with oracle parity where the
device path runs. One test crossing every subsystem boundary guards the
seams the per-module suites can't."""

import json

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.store import persistence
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000


@pytest.fixture(scope="module")
def journey(tmp_path_factory):
    """Build one store through the whole write-side journey, once."""
    root = tmp_path_factory.mktemp("journey")
    ds = DataStore(backend="tpu")

    # 1. DDL with index config, TTL off, visibility off (plain analytics)
    ds.create_schema(
        "trips",
        "route:String:index=true,fare:Double,dtg:Date,*geom:Point;"
        "geomesa.z3.interval='day',geomesa.fs.scheme='datetime'",
    )

    # 2. config-driven ingest (the HOCON-converter role) from a CSV
    csv = root / "trips.csv"
    rng = np.random.default_rng(8)
    rows = []
    for i in range(3000):
        lon = float(rng.uniform(-74.3, -73.7))
        lat = float(rng.uniform(40.5, 40.95))
        day = int(rng.integers(1, 27))
        rows.append(
            f"T{i},R{i % 7},{float(rng.uniform(3, 80)):.2f},"
            f"2017-07-{day:02d}T{int(rng.integers(0, 24)):02d}:00:00Z,"
            f"{lon:.6f},{lat:.6f}"
        )
    csv.write_text("\n".join(rows) + "\n")
    cfg = root / "conv.json"
    cfg.write_text(json.dumps({
        "type": "delimited-text",
        "id-field": "$1",
        "fields": {
            "route": "$2", "fare": "double($3)", "dtg": "isodate($4)",
            "geom": "point($5, $6)",
        },
    }))
    from geomesa_tpu.convert.config import load_converter

    conv = load_converter(str(cfg), sft=ds.get_schema("trips"))
    t = conv.convert_path(str(csv))
    assert len(t) == 3000
    ds.write("trips", t)
    ds.compact("trips")
    return ds, root


def _oracle_of(ds):
    o = DataStore(backend="oracle")
    o.create_schema(ds.get_schema("trips"))
    full = ds.query("trips")
    o.write("trips", full.table, fids=full.table.fids.tolist())
    return o


class TestJourney:
    def test_cql_breadth_with_parity(self, journey):
        ds, _ = journey
        oracle = _oracle_of(ds)
        queries = [
            "BBOX(geom, -74.05, 40.7, -73.9, 40.85)",
            "BBOX(geom, -74.2, 40.6, -73.8, 40.9) AND dtg DURING "
            "2017-07-05T00:00:00Z/2017-07-12T00:00:00Z",
            "route = 'R3' AND fare > 40",
            "route IN ('R1', 'R2') AND strLength(route) = 2",
            "fare BETWEEN 10 AND 20 OR route LIKE 'R6%'",
            "DWITHIN(geom, POINT (-73.98 40.75), 3, kilometers)",
        ]
        for q in queries:
            a = set(ds.query("trips", q).table.fids.tolist())
            b = set(oracle.query("trips", q).table.fids.tolist())
            assert a == b, q

    def test_analytics(self, journey):
        ds, _ = journey
        # density grid conserves mass
        r = ds.query("trips", Query(hints={"density": {
            "bbox": (-74.3, 40.5, -73.7, 40.95), "width": 64, "height": 64}}))
        assert float(r.density.sum()) == 3000.0
        # grouped stats
        r = ds.query("trips", Query(hints={"stats": "GroupBy(route, Stats(fare))"}))
        g = r.stats["GroupBy(route, Stats(fare))"]
        assert len(g.groups) == 7
        assert sum(s.count for s in g.groups.values()) == 3000
        # batched KNN, both merge topologies
        from geomesa_tpu.process.knn import knn_many

        pts = [Point(-73.98, 40.75), Point(-74.1, 40.6)]
        for topo in ("gather", "ring"):
            out = knn_many(ds, "trips", pts, k=5, topology=topo)
            assert all(len(tbl) == 5 for tbl, _ in out)

    def test_sql(self, journey):
        ds, _ = journey
        from geomesa_tpu.sql import sql

        r = sql(ds, "SELECT route, COUNT(*) AS n, AVG(fare) AS avg_fare "
                    "FROM trips GROUP BY route HAVING COUNT(*) > 10 "
                    "ORDER BY n DESC")
        assert sum(r.columns["n"]) == 3000
        assert all(float(v) > 0 for v in r.columns["avg_fare"])
        d = sql(ds, "SELECT DISTINCT route FROM trips")
        assert len(d) == 7

    def test_paging_and_arrow_export(self, journey):
        ds, _ = journey
        from geomesa_tpu.io.arrow import from_ipc_bytes, to_ipc_bytes

        q = "BBOX(geom, -74.1, 40.6, -73.8, 40.9)"
        full = ds.query("trips", Query(filter=q, sort_by=("id", False)))
        paged = []
        for off in range(0, full.count, 500):
            p = ds.query("trips", Query(filter=q, sort_by=("id", False),
                                        start_index=off, limit=500))
            paged.extend(p.table.fids.tolist())
        assert paged == full.table.fids.tolist()
        ipc = to_ipc_bytes(full.table)
        back = from_ipc_bytes(ds.get_schema("trips"), ipc)
        assert back.fids.tolist() == full.table.fids.tolist()

    def test_persistence_roundtrip_with_pruning(self, journey):
        ds, root = journey
        cat = str(root / "cat")
        persistence.save(ds, cat)
        flt = ("BBOX(geom, -75, 40, -73, 41) AND dtg DURING "
               "2017-07-03T00:00:00Z/2017-07-06T00:00:00Z")
        ds2 = persistence.load(cat, backend="oracle", filter=flt)
        assert ds2.metrics.counter("catalog.partitions_pruned.trips").count > 0
        want = set(ds.query("trips", flt).table.fids.tolist())
        assert set(ds2.query("trips", flt).table.fids.tolist()) == want

    def test_hbm_tier_controls(self, journey):
        ds, _ = journey
        res = ds.device_residency("trips")
        assert res["resident"]
        q = "route = 'R1'"
        want = set(ds.query("trips", q).table.fids.tolist())
        ds.evict_device("trips")
        assert set(ds.query("trips", q).table.fids.tolist()) == want
        assert ds.recover("trips")
        assert ds.device_residency("trips")["resident"]

    def test_modify_delete_evolve(self, journey):
        ds, _ = journey
        n0 = ds.query("trips").count
        ds.update_features(
            "trips",
            [{"route": "R0", "fare": 1.0, "dtg": T0, "geom": Point(-74.0, 40.7)}],
            ["T17"],
        )
        assert ds.query("trips").count == n0
        assert ds.query("trips", "IN ('T17')").records()[0]["fare"] == 1.0
        ds.delete_features("trips", ["T18", "T19"])
        assert ds.query("trips").count == n0 - 2
        # schema evolution: append an attribute, old rows null
        ds.update_schema("trips", add="tip:Double")
        assert ds.query("trips", "tip IS NULL").count == n0 - 2

    def test_streaming_tier(self, journey):
        ds, _ = journey
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lds = LambdaDataStore(persist_age_ms=1000, persist_interval_s=None,
                              consumers=2, cold=ds)
        now = T0 + 30 * 86_400_000
        for i in range(50):
            lds.write("trips", f"live{i}",
                      {"route": "LIVE", "fare": 9.9, "dtg": now,
                       "geom": Point(-73.9, 40.8)}, ts=now)
        assert lds.stream.drain("trips")
        r = lds.query("trips", "route = 'LIVE'")
        assert r.count == 50
        lds.close()
