"""WMS 1.3.0 GetCapabilities + GetMap (VERDICT r3 item 4): heatmap tiles
ride the fused device density path; point tiles render bounded feature
sets; 4326 (lat/lon axis order) and 3857 both serve; grid mass matches the
oracle count for the tile bbox.
"""

import io
import xml.etree.ElementTree as ET

import numpy as np
import pytest
from PIL import Image

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.web.wms import WmsError, handle_wms

T0 = 1_600_000_000_000


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(77)
    store = DataStore(backend="tpu")
    store.create_schema("pts", "name:String,dtg:Date,*geom:Point")
    n = 3000
    # all points in the NE quadrant of the world: a correct tile must light
    # up ONLY the top-right image quadrant (catches axis-order/flip bugs)
    lon = rng.uniform(20, 150, n)
    lat = rng.uniform(15, 75, n)
    store.write("pts", [
        {"name": f"p{i}", "dtg": T0 + (i % 1000) * 1000,
         "geom": Point(float(lon[i]), float(lat[i]))}
        for i in range(n)
    ], fids=[str(i) for i in range(n)])
    store.compact("pts")
    store._lonlat = (lon, lat)
    return store


def _png(body) -> np.ndarray:
    return np.asarray(Image.open(io.BytesIO(body)).convert("RGBA"))


class TestCapabilities:
    def test_capabilities_lists_layers(self, ds):
        status, body, ctype = handle_wms(
            ds, {"service": "WMS", "request": "GetCapabilities"}
        )
        assert status == 200 and ctype == "text/xml"
        root = ET.fromstring(body)
        names = [
            e.text for e in root.iter("{http://www.opengis.net/wms}Name")
        ]
        assert "pts" in names


class TestGetMap:
    def test_heat_tile_4326_axis_order_and_mass(self, ds):
        # WMS 1.3.0 EPSG:4326 BBOX is lat,lon order: whole world
        status, body, ctype = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "EPSG:4326", "bbox": "-90,-180,90,180",
            "width": "128", "height": "128", "format": "image/png",
        })
        assert status == 200 and ctype == "image/png"
        img = _png(body)
        assert img.shape == (128, 128, 4)
        alpha = img[..., 3]
        assert (alpha > 0).any()
        # data lives at lon>20, lat>15 → image top-right quadrant only
        # (PNG row 0 = north)
        assert (alpha[:64, 64:] > 0).sum() > 0
        assert (alpha[64:, :64] > 0).sum() == 0  # SW quadrant empty

    def test_heat_mass_matches_oracle_count(self, ds):
        """The density grid the tile renders carries EXACTLY the rows the
        oracle counts in the tile bbox (DensityScan parity)."""
        bbox = (30.0, 20.0, 100.0, 60.0)
        grids = ds.density_many("pts", [None], bbox, width=64, height=64,
                                loose=False)
        mass = float(np.asarray(grids[0]).sum())
        lon, lat = ds._lonlat
        want = int(((lon >= bbox[0]) & (lon <= bbox[2])
                    & (lat >= bbox[1]) & (lat <= bbox[3])).sum())
        assert mass == want
        # and the served PNG lights exactly the grid's nonzero cells
        status, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "CRS:84", "bbox": "30,20,100,60",
            "width": "64", "height": "64",
        })
        img = _png(body)
        hot = np.asarray(grids[0])[::-1] > 0  # tile is north-up
        assert ((img[..., 3] > 0) == hot).all()

    def test_3857_tile(self, ds):
        from geomesa_tpu.utils.crs import transform_coords

        (x1, x2), (y1, y2) = transform_coords(
            np.array([-180.0, 180.0]), np.array([-80.0, 80.0]),
            "EPSG:4326", "EPSG:3857",
        )
        status, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "EPSG:3857", "bbox": f"{x1},{y1},{x2},{y2}",
            "width": "96", "height": "96",
        })
        img = _png(body)
        assert img.shape == (96, 96, 4)
        alpha = img[..., 3]
        assert (alpha[:, 48:] > 0).any()  # east half hot
        assert (alpha[:, :32] > 0).sum() == 0  # far west empty

    def test_points_style(self, ds):
        status, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "styles": "points", "crs": "CRS:84", "bbox": "-180,-90,180,90",
            "width": "128", "height": "128",
        })
        img = _png(body)
        alpha = img[..., 3]
        assert (alpha[:70, 70:] > 0).any()
        assert (alpha[80:, :40] > 0).sum() == 0

    def test_time_param_filters(self, ds):
        # TIME covering only the first 100 seconds → far fewer rows
        full = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "CRS:84", "bbox": "-180,-90,180,90",
            "width": "32", "height": "32",
        })[1]
        some = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "CRS:84", "bbox": "-180,-90,180,90",
            "width": "32", "height": "32",
            "time": "2020-09-13T12:26:40Z/2020-09-13T12:28:20Z",
        })[1]
        assert (_png(full)[..., 3] > 0).sum() >= (_png(some)[..., 3] > 0).sum()

    def test_time_single_instant_matches(self, ds):
        """A single-instant TIME must hit features AT that timestamp
        (DURING t/t has exclusive endpoints and would match nothing)."""
        body = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "CRS:84", "bbox": "-180,-90,180,90",
            "width": "32", "height": "32",
            "time": "2020-09-13T12:26:40Z",  # == T0: rows with i%1000==0
        })[1]
        assert (_png(body)[..., 3] > 0).any()

    def test_srs_key_uses_lonlat_order(self, ds):
        """The 1.1.x SRS key means lon,lat BBOX order — the NE-quadrant
        data must land top-right, same as the 1.3.0 lat,lon request."""
        body = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "version": "1.1.1", "srs": "EPSG:4326",
            "bbox": "-180,-90,180,90",  # lon,lat order
            "width": "64", "height": "64",
        })[1]
        alpha = _png(body)[..., 3]
        assert (alpha[:32, 32:] > 0).any()
        assert (alpha[32:, :32] > 0).sum() == 0

    def test_point_dilation_does_not_wrap(self, ds):
        """A point on the west edge of the tile must not paint the east
        edge (np.roll-style wraparound)."""
        store = DataStore(backend="tpu")
        store.create_schema("edge", "name:String,*geom:Point")
        store.write("edge", [{"name": "w", "geom": Point(-179.99, 0.0)}],
                    fids=["w"])
        body = handle_wms(store, {
            "service": "WMS", "request": "GetMap", "layers": "edge",
            "styles": "points", "crs": "CRS:84", "bbox": "-180,-90,180,90",
            "width": "64", "height": "64",
        })[1]
        alpha = _png(body)[..., 3]
        assert (alpha[:, :2] > 0).any()      # west edge painted
        assert (alpha[:, -4:] > 0).sum() == 0  # east edge clean

    def test_bad_cql_returns_wms_error(self, ds):
        with pytest.raises(WmsError) as ei:
            handle_wms(ds, {
                "service": "WMS", "request": "GetMap", "layers": "pts",
                "crs": "CRS:84", "bbox": "-180,-90,180,90",
                "width": "16", "height": "16", "cql_filter": "name ==",
            })
        assert ei.value.code == "InvalidParameterValue"

    def test_transparent_false_background(self, ds):
        body = handle_wms(ds, {
            "service": "WMS", "request": "GetMap", "layers": "pts",
            "crs": "CRS:84", "bbox": "-179,-89,-170,-80",  # empty corner
            "width": "16", "height": "16", "transparent": "FALSE",
        })[1]
        img = _png(body)
        assert (img == 255).all()  # opaque white, no data

    def test_errors(self, ds):
        with pytest.raises(WmsError, match="no such layer") as ei:
            handle_wms(ds, {"service": "WMS", "request": "GetMap",
                            "layers": "nope", "bbox": "0,0,1,1"})
        assert ei.value.code == "LayerNotDefined"
        with pytest.raises(WmsError, match="BBOX"):
            handle_wms(ds, {"service": "WMS", "request": "GetMap",
                            "layers": "pts"})
        with pytest.raises(WmsError, match="CRS"):
            handle_wms(ds, {"service": "WMS", "request": "GetMap",
                            "layers": "pts", "crs": "EPSG:9999",
                            "bbox": "0,0,1,1"})
        with pytest.raises(WmsError):
            handle_wms(ds, {"service": "WMS", "request": "GetMap",
                            "layers": "pts", "crs": "CRS:84",
                            "bbox": "5,5,1,1"})


class TestOverHttp:
    def test_wms_route_and_exception_report(self, ds):
        import threading
        from urllib.error import HTTPError
        from urllib.request import urlopen
        from wsgiref.simple_server import make_server

        from geomesa_tpu.web.app import GeoMesaApp

        httpd = make_server("127.0.0.1", 0, GeoMesaApp(ds))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            url = (f"http://127.0.0.1:{port}/wms?service=WMS&request=GetMap"
                   "&layers=pts&crs=CRS:84&bbox=-180,-90,180,90"
                   "&width=32&height=32&format=image/png")
            with urlopen(url) as r:
                assert r.headers["Content-Type"] == "image/png"
                img = _png(r.read())
            assert img.shape == (32, 32, 4)
            bad = (f"http://127.0.0.1:{port}/wms?service=WMS&request=GetMap"
                   "&layers=missing&crs=CRS:84&bbox=0,0,1,1")
            try:
                urlopen(bad)
                raise AssertionError("expected 400")
            except HTTPError as e:
                assert e.code == 400
                root = ET.fromstring(e.read())
                assert "ServiceException" in root[0].tag
        finally:
            httpd.shutdown()


class TestGetFeatureInfo:
    """GetFeatureInfo: the identify surface — features under a clicked
    pixel, honoring the exact pixel->geography transform GetMap renders
    with (4326 lat/lon order, 3857 mercator rows) plus BUFFER,
    FEATURE_COUNT, CQL_FILTER, and both INFO_FORMATs."""

    def _pixel_of(self, lon, lat, bbox, w, h):
        xmin, ymin, xmax, ymax = bbox
        i = int((lon - xmin) / (xmax - xmin) * w)
        j = int((ymax - lat) / (ymax - ymin) * h)
        return i, j

    def test_json_identify_hits_known_point(self, ds):
        import json

        lon, lat = ds._lonlat
        # target the first feature; world tile in CRS:84 (lon/lat order)
        bbox = (-180.0, -90.0, 180.0, 90.0)
        w = h = 512
        i, j = self._pixel_of(lon[0], lat[0], bbox, w, h)
        status, body, ctype = handle_wms(ds, {
            "service": "WMS", "request": "GetFeatureInfo",
            "query_layers": "pts", "crs": "CRS:84",
            "bbox": "-180,-90,180,90", "width": str(w), "height": str(h),
            "i": str(i), "j": str(j), "buffer": "2", "feature_count": "50",
            "info_format": "application/json",
        })
        assert status == 200 and "json" in ctype
        fc = json.loads(body)
        assert fc["type"] == "FeatureCollection"
        fids = {f["id"] for f in fc["features"]}
        assert "0" in fids
        # every returned feature really is within the +-(buffer+1) pixel
        # window of the click
        dx = (2 + 1) / w * 360.0
        dy = (2 + 1) / h * 180.0
        for f in fc["features"]:
            fx, fy = f["geometry"]["coordinates"]
            assert abs(fx - lon[0]) <= dx * 1.5 + 360.0 / w
            assert abs(fy - lat[0]) <= dy * 1.5 + 180.0 / h

    def test_latlon_axis_order_130(self, ds):
        import json

        lon, lat = ds._lonlat
        bbox = (-180.0, -90.0, 180.0, 90.0)
        w = h = 512
        i, j = self._pixel_of(lon[0], lat[0], bbox, w, h)
        # WMS 1.3.0 EPSG:4326: BBOX in lat,lon order — same click, same hit
        status, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetFeatureInfo",
            "query_layers": "pts", "crs": "EPSG:4326",
            "bbox": "-90,-180,90,180", "width": str(w), "height": str(h),
            "i": str(i), "j": str(j), "buffer": "2", "feature_count": "50",
            "info_format": "application/json",
        })
        fids = {f["id"] for f in json.loads(body)["features"]}
        assert "0" in fids

    def test_3857_identify(self, ds):
        import json

        import numpy as np

        lon, lat = ds._lonlat
        # mercator world tile: pixel row from the mercator transform
        w = h = 512
        R = 6378137.0
        mx = lambda d: np.radians(d) * R  # noqa: E731
        my = lambda d: R * np.log(np.tan(np.pi / 4 + np.radians(d) / 2))  # noqa: E731
        xmin, xmax = mx(-180), mx(180)
        ymin, ymax = my(-85.0), my(85.0)
        i = int((mx(lon[0]) - xmin) / (xmax - xmin) * w)
        j = int((ymax - my(lat[0])) / (ymax - ymin) * h)
        status, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetFeatureInfo",
            "query_layers": "pts", "crs": "EPSG:3857",
            "bbox": f"{xmin},{ymin},{xmax},{ymax}",
            "width": str(w), "height": str(h),
            "i": str(i), "j": str(j), "buffer": "2", "feature_count": "50",
            "info_format": "application/json",
        })
        fids = {f["id"] for f in json.loads(body)["features"]}
        assert "0" in fids

    def test_text_plain_default_and_feature_count(self, ds):
        lon, lat = ds._lonlat
        bbox = (-180.0, -90.0, 180.0, 90.0)
        i, j = self._pixel_of(lon[0], lat[0], bbox, 512, 512)
        status, body, ctype = handle_wms(ds, {
            "service": "WMS", "request": "GetFeatureInfo",
            "query_layers": "pts", "crs": "CRS:84",
            "bbox": "-180,-90,180,90", "width": "512", "height": "512",
            "i": str(i), "j": str(j), "buffer": "4",
        })
        assert ctype == "text/plain"
        assert "fid = " in body and "name = " in body
        # FEATURE_COUNT defaults to 1: at most one feature listed
        assert body.count("fid = ") == 1

    def test_empty_window(self, ds):
        import json

        # south-west quadrant holds no points (fixture is NE-only)
        status, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetFeatureInfo",
            "query_layers": "pts", "crs": "CRS:84",
            "bbox": "-180,-90,180,90", "width": "256", "height": "256",
            "i": "10", "j": "250", "info_format": "application/json",
        })
        assert json.loads(body)["features"] == []

    def test_cql_filter_applies(self, ds):
        import json

        lon, lat = ds._lonlat
        i, j = self._pixel_of(lon[0], lat[0],
                              (-180.0, -90.0, 180.0, 90.0), 512, 512)
        base = {
            "service": "WMS", "request": "GetFeatureInfo",
            "query_layers": "pts", "crs": "CRS:84",
            "bbox": "-180,-90,180,90", "width": "512", "height": "512",
            "i": str(i), "j": str(j), "buffer": "3", "feature_count": "50",
            "info_format": "application/json",
        }
        _, body, _ = handle_wms(ds, {**base, "cql_filter": "name = 'p0'"})
        fids = {f["id"] for f in json.loads(body)["features"]}
        assert fids == {"0"}
        _, body, _ = handle_wms(
            ds, {**base, "cql_filter": "name = 'no-such'"})
        assert json.loads(body)["features"] == []

    def test_errors(self, ds):
        base = {"service": "WMS", "request": "GetFeatureInfo",
                "query_layers": "pts", "crs": "CRS:84",
                "bbox": "-180,-90,180,90", "width": "64", "height": "64"}
        with pytest.raises(WmsError, match="I/J") as ei:
            handle_wms(ds, dict(base))
        assert ei.value.code == "MissingParameterValue"
        with pytest.raises(WmsError, match="outside") as ei:
            handle_wms(ds, {**base, "i": "64", "j": "0"})
        assert ei.value.code == "InvalidPoint"
        with pytest.raises(WmsError, match="INFO_FORMAT"):
            handle_wms(ds, {**base, "i": "1", "j": "1",
                            "info_format": "text/html"})
        with pytest.raises(WmsError, match="QUERY_LAYERS"):
            handle_wms(ds, {**{k: v for k, v in base.items()
                               if k != "query_layers"},
                            "i": "1", "j": "1"})


class TestGetLegendGraphic:
    def test_heat_legend_gradient(self, ds):
        status, body, ctype = handle_wms(ds, {
            "service": "WMS", "request": "GetLegendGraphic",
            "layer": "pts", "style": "heat",
            "width": "20", "height": "64",
        })
        assert status == 200 and ctype == "image/png"
        img = _png(body)
        assert img.shape == (64, 20, 4)
        # a vertical gradient: the top row is the ramp's hot end (red-ish),
        # rows vary down the column, all columns identical
        assert (img[:, 0] == img[:, -1]).all()
        top, mid = img[0, 0], img[32, 0]
        assert top[3] == 255 and (top[:3] != mid[:3]).any()
        assert int(top[0]) > int(top[2]), "hot end should lean red"

    def test_points_legend_swatch(self, ds):
        _, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetLegendGraphic",
            "layer": "pts", "style": "points",
        })
        img = _png(body)
        assert (img[..., :3] == (0x1f, 0x78, 0xb4)).all()

    def test_capabilities_advertises(self, ds):
        _, body, _ = handle_wms(
            ds, {"service": "WMS", "request": "GetCapabilities"})
        assert "GetLegendGraphic" in body

    def test_unknown_style(self, ds):
        with pytest.raises(WmsError, match="unknown STYLE"):
            handle_wms(ds, {"service": "WMS", "layer": "pts",
                            "request": "GetLegendGraphic", "style": "nope"})

    def test_unknown_layer_rejected(self, ds):
        with pytest.raises(WmsError, match="no such layer") as ei:
            handle_wms(ds, {"service": "WMS", "layer": "ghost",
                            "request": "GetLegendGraphic"})
        assert ei.value.code == "LayerNotDefined"

    def test_one_pixel_legend_is_visible(self, ds):
        _, body, _ = handle_wms(ds, {
            "service": "WMS", "request": "GetLegendGraphic",
            "layer": "pts", "style": "heat", "width": "1", "height": "1",
        })
        img = _png(body)
        assert img.shape == (1, 1, 4) and img[0, 0, 3] == 255
