"""tpusync: fixture tests pin exact (rule, line) findings per S-rule
family, the cross-module fixture proves budget costs ride the
whole-program fixpoint, the package gate holds the live tree to its
declared dispatch budgets, and the reconcile surface is proven against
a REAL staged-select ledger export — static bound vs measured rate,
red and green.

Pure AST like the other prongs: fixtures under ``tpusync_fixtures/``
are never imported, and the static analysis runs with JAX gated off.
Only the live-export tests touch a device path."""

import json
import os
import shutil
import subprocess
import sys

import pytest

from geomesa_tpu.analysis import LintConfig
from geomesa_tpu.analysis.core import AnalysisCrash, lint_paths
from geomesa_tpu.analysis.flow import analyze_flow_paths
from geomesa_tpu.analysis.race import analyze_race_paths
from geomesa_tpu.analysis.race.lockset import _Project, load_modules
from geomesa_tpu.analysis.sync import (
    LEDGER_EXPORT_KIND,
    SYNC_RULE_IDS,
    analyze_sync_paths,
    load_ledger_export,
)
from geomesa_tpu.analysis.sync.contracts_scan import scan_sync_contracts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "geomesa_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tpusync_fixtures")


def _sync(name, config=None, reconcile=None):
    vs = analyze_sync_paths([os.path.join(FIXTURES, name)],
                            config or LintConfig(), reconcile=reconcile)
    return [(os.path.basename(v.path), v.line, v.rule)
            for v in vs if not v.suppressed]


def _run_cli(*argv, env_extra=None, cwd=None):
    env = dict(os.environ, GEOMESA_TPU_NO_JAX="1")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "geomesa_tpu.analysis", *argv],
        capture_output=True, text=True, env=env, cwd=cwd or REPO)


class TestRuleFixtures:
    """Each S-rule family flags its known-bad fixture at exact lines and
    stays silent on the known-good twin."""

    @pytest.mark.parametrize("name,expected", [
        # two-dispatch seq over budget 1, constant 4-loop over budget 2,
        # and a malformed (non-literal) budget declaration
        ("s001_bad.py", [
            ("s001_bad.py", 13, "S001"),
            ("s001_bad.py", 21, "S001"),
            ("s001_bad.py", 30, "S001"),
        ]),
        # block_until_ready + np.asarray in the region, materialize two
        # calls down the graph, implicit bool() in a certain-band branch
        ("s002_bad.py", [
            ("s002_bad.py", 21, "S002"),
            ("s002_bad.py", 22, "S002"),
            ("s002_bad.py", 31, "S002"),
            ("s002_bad.py", 38, "S002"),
        ]),
        # direct step-in-for, dispatch behind a helper call in a while,
        # and the comprehension form
        ("s003_bad.py", [
            ("s003_bad.py", 14, "S003"),
            ("s003_bad.py", 26, "S003"),
            ("s003_bad.py", 32, "S003"),
        ]),
        # raw jax.jit / jax.pmap calls outside the factory discipline
        ("s004_bad.py", [
            ("s004_bad.py", 9, "S004"),
            ("s004_bad.py", 13, "S004"),
        ]),
        # stale tpusync waivers, same-line and next-line forms
        ("w001_sync_bad.py", [
            ("w001_sync_bad.py", 10, "W001"),
            ("w001_sync_bad.py", 13, "W001"),
        ]),
    ])
    def test_bad_fixture_flagged(self, name, expected):
        assert _sync(name) == expected

    @pytest.mark.parametrize("name", [
        "s001_good.py", "s002_good.py", "s003_good.py", "s004_good.py",
        "w001_sync_good.py",
    ])
    def test_good_fixture_clean(self, name):
        assert _sync(name) == []

    def test_s001_message_carries_witness_chain(self):
        vs = analyze_sync_paths(
            [os.path.join(FIXTURES, "s001_bad.py")], LintConfig())
        two_pass = next(v for v in vs if v.line == 13)
        assert "worst case is 2 dispatch(es)" in two_pass.message
        assert "line 16" in two_pass.message  # first step() of the pair
        looped = next(v for v in vs if v.line == 21)
        assert "4 dispatch(es)" in looped.message
        assert "×4 (loop)" in looped.message

    def test_s002_message_names_root_and_retire_escape(self):
        vs = analyze_sync_paths(
            [os.path.join(FIXTURES, "s002_bad.py")], LintConfig())
        deep = next(v for v in vs if v.line == 31)
        assert "@host_sync_free" in deep.message
        assert "materialize" in deep.message
        assert "# tpusync: retire" in deep.message
        certain = next(v for v in vs if v.line == 38)
        assert "@device_band(certain=True)" in certain.message

    def test_live_waiver_suppresses_s_rule(self):
        """The shared waiver tokenizer honors the tpusync namespace: the
        good W001 fixture DOES contain a real S003, waived in source."""
        vs = analyze_sync_paths(
            [os.path.join(FIXTURES, "w001_sync_good.py")], LintConfig())
        waived = [v for v in vs if v.waived]
        assert [(v.rule, v.line) for v in waived] == [("S003", 16)]

    def test_retired_sync_is_not_a_finding(self):
        """s002_good retires BOTH its pipeline-end awaits (same-line and
        next-line): no S002, and no stale-waiver W001 either — retire is
        a sync-site blessing, not a waiver."""
        vs = analyze_sync_paths(
            [os.path.join(FIXTURES, "s002_good.py")], LintConfig())
        assert vs == []


class TestCrossModule:
    """The findings that REQUIRE the whole-program cost fixpoint."""

    def test_budget_violation_across_modules(self):
        """s001_x: the budget holder's own body has zero dispatch sites
        — both dispatches live in ``work.py`` one call away, so the
        finding exists only if costs propagate over the call graph."""
        assert _sync("s001_x") == [("api.py", 10, "S001")]

    def test_cross_module_witness_expands_the_callee(self):
        vs = analyze_sync_paths(
            [os.path.join(FIXTURES, "s001_x")], LintConfig())
        (v,) = [x for x in vs if not x.suppressed]
        assert "count_and_gather" in v.message
        assert "inside" in v.message  # the expanded callee chain


class TestPackageSyncGate:
    """The live tree holds its own budgets: zero unwaived S findings,
    and the fused-path surfaces the ISSUE names all declare budgets."""

    def test_package_clean(self):
        targets = [PKG, os.path.join(REPO, "scripts"),
                   os.path.join(REPO, "bench.py")]
        vs = analyze_sync_paths(targets, LintConfig())
        new = [v for v in vs if not v.suppressed]
        assert new == [], "\n".join(
            f"{v.path}:{v.line}: {v.rule} {v.message}" for v in new)

    def test_declared_budget_coverage(self):
        """select / select_many / aggregate_many / matrix-scan /
        corridor: every fused-path surface carries a budget, the
        corridor kernel is sync-free, and the DataStore facade is the
        choreography boundary."""
        modules, errors = load_modules([PKG])
        assert errors == []
        c = scan_sync_contracts(_Project(modules), modules)
        assert c.errors == []
        budgets = {b.label: b.n for b in c.budgets}
        assert budgets["TpuBackend.select"] == 2
        assert budgets["TpuBackend.select_many_positions"] == 2
        assert budgets["DataStore.select_many"] == 2
        assert budgets["DataStore.aggregate_many"] == 1
        assert budgets["SubscriptionMatrix.scan_chunk"] == 1
        assert budgets["trajectory.corridor:tube_select_many"] == 2
        assert budgets["trajectory.corridor:_corridor_kernel"] == 1
        sigs = {b.label: b.signatures for b in c.budgets}
        assert sigs["TpuBackend.select"] == ("*:rows",)
        assert sigs["DataStore.aggregate_many"] == ("*:stats",)
        assert "trajectory.corridor:_corridor_kernel" in {
            d.label for d in c.sync_free}
        assert "DataStore" in {d.label for d in c.choreo}

    def test_in_tree_sync_waivers_are_live(self):
        """Every `# tpusync: disable` in the tree suppresses a real
        finding (the chunked/streaming loops reviewed in this PR) — a
        stale one would surface as W001 in the gate above; pin the
        count so silent drift is visible."""
        out = subprocess.run(
            ["grep", "-rlE", r"# tpusync: disable(-next-line)?=S[0-9]",
             PKG, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True)
        files = set(out.stdout.split())
        assert {os.path.join(PKG, "process", "join.py"),
                os.path.join(PKG, "stream", "pipeline.py"),
                os.path.join(REPO, "bench.py")} == files


class TestWaiverParity:
    """One tokenizer, four namespaces: each prong judges exactly its
    own waivers stale and leaves the other prongs' namespaces alone."""

    SRC = (
        "import threading\n"
        "x = 1  # tpulint: disable=C001\n"
        "y = 2  # tpurace: disable=R001\n"
        "z = 3  # tpuflow: disable=F001\n"
        "w = 4  # tpusync: disable=S001\n"
    )

    @pytest.fixture()
    def tree(self, tmp_path):
        p = tmp_path / "waivers.py"
        p.write_text(self.SRC)
        return str(p)

    def test_lint_judges_only_its_namespace(self, tree):
        vs = lint_paths([tree], LintConfig())
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 2)]

    def test_race_judges_only_its_namespace(self, tree):
        cfg = LintConfig(race_paths=("",), r003_paths=("",))
        vs = analyze_race_paths([tree], cfg)
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 3)]

    def test_flow_judges_only_its_namespace(self, tree):
        vs = analyze_flow_paths([tree], LintConfig())
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 4)]

    def test_sync_judges_only_its_namespace(self, tree):
        vs = analyze_sync_paths([tree], LintConfig())
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 5)]


class TestCli:
    """Exit codes, rule-filter validation, and the reconcile guards."""

    def test_sync_gate_exits_zero_on_package(self):
        out = _run_cli("--sync", PKG)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_sync_bad_fixture_exits_one(self):
        out = _run_cli("--sync", os.path.join(FIXTURES, "s003_bad.py"))
        assert out.returncode == 1
        assert "S003" in out.stdout

    def test_sync_rules_filter_validation(self):
        out = _run_cli("--sync", "--rules", "J001", PKG)
        assert out.returncode == 2
        out = _run_cli("--rules", "S001", PKG)
        assert out.returncode == 2
        assert "--sync" in out.stderr
        out = _run_cli("--rules", "S001,F001", PKG)
        assert out.returncode == 2
        assert "--all-prongs" in out.stderr

    def test_sync_rule_subset_runs(self):
        out = _run_cli("--sync", "--rules", "S004",
                       os.path.join(FIXTURES, "s003_bad.py"))
        assert out.returncode == 0, out.stdout + out.stderr
        out = _run_cli("--sync", "--rules", "S004",
                       os.path.join(FIXTURES, "s004_bad.py"))
        assert out.returncode == 1
        assert "S003" not in out.stdout

    def test_list_rules_includes_sync(self):
        out = _run_cli("--list-rules")
        assert out.returncode == 0
        for rid in SYNC_RULE_IDS:
            assert rid in out.stdout

    def test_reconcile_requires_sync(self, tmp_path):
        p = tmp_path / "ledger.json"
        p.write_text(json.dumps({
            "kind": LEDGER_EXPORT_KIND, "schema_version": 1,
            "entries": []}))
        out = _run_cli("--reconcile", str(p), PKG)
        assert out.returncode == 2
        assert "--sync" in out.stderr

    def test_reconcile_missing_file_is_usage_error(self):
        out = _run_cli("--sync", "--reconcile", "/nonexistent/ledger.json",
                       PKG)
        assert out.returncode == 2

    def test_reconcile_wrong_kind_is_usage_error(self, tmp_path):
        p = tmp_path / "ledger.json"
        p.write_text(json.dumps({"kind": "something-else",
                                 "schema_version": 1, "entries": []}))
        out = _run_cli("--sync", "--reconcile", str(p),
                       os.path.join(FIXTURES, "s001_good.py"))
        assert out.returncode == 2
        assert "roundtrip-ledger" in out.stderr

    def test_reconcile_wrong_schema_version_is_usage_error(self, tmp_path):
        p = tmp_path / "ledger.json"
        p.write_text(json.dumps({"kind": LEDGER_EXPORT_KIND,
                                 "schema_version": 99, "entries": []}))
        out = _run_cli("--sync", "--reconcile", str(p),
                       os.path.join(FIXTURES, "s001_good.py"))
        assert out.returncode == 2
        assert "schema_version" in out.stderr


class TestExitCodeAudit:
    """A crashed or partial sync analysis must never read as clean."""

    def test_crashed_sync_prong_exits_three(self, monkeypatch, capsys):
        from geomesa_tpu.analysis import __main__ as cli
        from geomesa_tpu.analysis import sync

        target = os.path.join(FIXTURES, "s001_good.py")

        def boom(paths, config=None, reconcile=None):
            raise AnalysisCrash(target, "rule S001",
                                RuntimeError("synthetic"))

        monkeypatch.setattr(sync, "analyze_sync_paths", boom)
        rc = cli.main(["--sync", target])
        assert rc == 3
        err = capsys.readouterr().err
        assert "s001_good.py" in err and "rule S001" in err

    def test_internal_error_exits_three(self, monkeypatch, capsys):
        from geomesa_tpu.analysis import __main__ as cli
        from geomesa_tpu.analysis import sync

        def boom(paths, config=None, reconcile=None):
            raise RuntimeError("unexpected")

        monkeypatch.setattr(sync, "analyze_sync_paths", boom)
        rc = cli.main(["--sync", os.path.join(FIXTURES, "s001_good.py")])
        assert rc == 3
        assert "internal error" in capsys.readouterr().err


class TestIncremental:
    """--changed-only warm path for the sync prong, and the reconcile
    cache bypass (ledger contents are outside the tree fingerprint)."""

    def _cli(self, tmp_path, *argv):
        return _run_cli(*argv, env_extra={
            "TPULINT_CACHE_DIR": str(tmp_path / "cache")})

    def test_edit_invalidates_cache(self, tmp_path):
        tree = tmp_path / "tree"
        tree.mkdir()
        shutil.copy(os.path.join(FIXTURES, "s001_good.py"),
                    tree / "mod.py")
        out = self._cli(tmp_path, "--sync", "--changed-only", str(tree))
        assert out.returncode == 0, out.stdout + out.stderr
        out = self._cli(tmp_path, "--sync", "--changed-only", str(tree))
        assert out.returncode == 0
        src = (tree / "mod.py").read_text()
        src += (
            "\n\n@dispatch_budget(0)\n"
            "def late(mesh, xs):\n"
            "    return cached_probe_step(mesh)(xs)\n"
        )
        (tree / "mod.py").write_text(src)
        out = self._cli(tmp_path, "--sync", "--changed-only", str(tree))
        assert out.returncode == 1
        assert "S001" in out.stdout

    def test_reconcile_bypasses_warm_cache(self, tmp_path):
        """A warm clean cache must not mask a fresh ledger divergence:
        --reconcile always analyzes live."""
        tree = tmp_path / "tree"
        tree.mkdir()
        shutil.copy(os.path.join(FIXTURES, "s001_good.py"),
                    tree / "mod.py")
        out = self._cli(tmp_path, "--sync", "--changed-only", str(tree))
        assert out.returncode == 0, out.stdout + out.stderr
        budget_mod = tree / "sel.py"
        budget_mod.write_text(
            "from geomesa_tpu.analysis.contracts import dispatch_budget\n"
            "\n\n"
            "def cached_sel_step(mesh):\n"
            "    return lambda x: x\n"
            "\n\n"
            "@dispatch_budget(2, signatures=('z2:*',))\n"
            "def select(mesh, xs):\n"
            "    step = cached_sel_step(mesh)\n"
            "    return step(step(xs))\n")
        out = self._cli(tmp_path, "--sync", "--changed-only", str(tree))
        assert out.returncode == 0, out.stdout + out.stderr
        ledger = tmp_path / "ledger.json"
        ledger.write_text(json.dumps({
            "kind": LEDGER_EXPORT_KIND, "schema_version": 1,
            "entries": [{"type": "pts", "signature": "z2:iv16:rows",
                         "queries": 2, "dispatches": 8}]}))
        out = self._cli(tmp_path, "--sync", "--changed-only",
                        "--reconcile", str(ledger), str(tree))
        assert out.returncode == 1
        assert "ledger reconcile" in out.stdout


class TestReconcile:
    """Static bound vs measured dispatch rate, red and green."""

    BUDGET_SRC = (
        "from geomesa_tpu.analysis.contracts import dispatch_budget\n"
        "\n\n"
        "def cached_sel_step(mesh):\n"
        "    return lambda x: x\n"
        "\n\n"
        "@dispatch_budget(2, signatures=('z2:*',))\n"
        "def select(mesh, xs):\n"
        "    step = cached_sel_step(mesh)\n"
        "    return step(step(xs))\n"
    )

    @pytest.fixture()
    def tree(self, tmp_path):
        p = tmp_path / "sel.py"
        p.write_text(self.BUDGET_SRC)
        return str(p)

    def _reconcile(self, tree, entries):
        vs = analyze_sync_paths([tree], LintConfig(), reconcile=entries)
        return [(v.line, v.rule) for v in vs if not v.suppressed]

    def test_measured_within_bound_is_clean(self, tree):
        assert self._reconcile(tree, [
            {"type": "pts", "signature": "z2:iv16:rows",
             "queries": 3, "dispatches": 6},
        ]) == []

    def test_measured_above_bound_flags_declaration(self, tree):
        found = self._reconcile(tree, [
            {"type": "pts", "signature": "z2:iv16:rows",
             "queries": 2, "dispatches": 8},
        ])
        assert found == [(8, "S001")]  # the @dispatch_budget line

    def test_unclaimed_signature_is_ignored(self, tree):
        assert self._reconcile(tree, [
            {"type": "pts", "signature": "scan:rows",
             "queries": 2, "dispatches": 50},
        ]) == []

    def test_entries_must_be_objects(self, tmp_path):
        p = tmp_path / "ledger.json"
        p.write_text(json.dumps({"kind": LEDGER_EXPORT_KIND,
                                 "schema_version": 1, "entries": [1, 2]}))
        with pytest.raises(ValueError, match="entries"):
            load_ledger_export(str(p))


class TestLedgerExportSurfaces:
    """The measured side: LedgerTable.export(), the web route, and the
    CLI puller all speak the one schema the analyzer validates."""

    def _charged_table(self):
        from geomesa_tpu.obs.ledger import LedgerTable, QueryLedger

        t = LedgerTable()
        ql = QueryLedger()
        ql.note_dispatch(0.0, 0.001)
        ql.note_dispatch(0.002, 0.003)
        ql.note_sync(0.003, 0.004)
        t.charge("pts", "z2:iv16:rows", ql, 5.0)
        return t

    def test_export_round_trips_through_loader(self, tmp_path):
        doc = self._charged_table().export()
        assert doc["kind"] == LEDGER_EXPORT_KIND
        assert doc["schema_version"] == 1
        p = tmp_path / "ledger.json"
        p.write_text(json.dumps(doc))
        (e,) = load_ledger_export(str(p))
        assert e["type"] == "pts"
        assert e["signature"] == "z2:iv16:rows"
        assert e["queries"] == 1
        assert e["dispatches"] == 2
        assert e["syncs"] == 1

    def test_web_route_serves_the_export_schema(self):
        import io

        from geomesa_tpu.obs import ledger as ledger_mod
        from geomesa_tpu.store.datastore import DataStore
        from geomesa_tpu.web.app import GeoMesaApp

        app = GeoMesaApp(DataStore(backend="tpu"))
        prev = ledger_mod.install(self._charged_table())
        try:
            def call(query):
                environ = {
                    "REQUEST_METHOD": "GET",
                    "PATH_INFO": "/api/obs/ledger",
                    "QUERY_STRING": query,
                    "CONTENT_LENGTH": "0",
                    "wsgi.input": io.BytesIO(b""),
                }
                out = {}

                def start_response(status, headers):
                    out["status"] = int(status.split()[0])

                body = b"".join(app(environ, start_response))
                return out["status"], json.loads(body)

            status, doc = call("format=json")
            assert status == 200
            assert doc["kind"] == LEDGER_EXPORT_KIND
            assert doc["schema_version"] == 1
            assert doc["entries"][0]["dispatches"] == 2
            status, doc = call("")  # format optional, json is the default
            assert status == 200
            status, doc = call("format=csv")
            assert status == 400
        finally:
            ledger_mod.install(prev)

    def test_cli_export_writes_loader_valid_file(self, tmp_path):
        import argparse
        import threading
        from wsgiref.simple_server import WSGIRequestHandler, make_server

        from geomesa_tpu.cli.__main__ import cmd_obs_ledger_export
        from geomesa_tpu.obs import ledger as ledger_mod
        from geomesa_tpu.store.datastore import DataStore
        from geomesa_tpu.web.app import GeoMesaApp

        class _Quiet(WSGIRequestHandler):
            def log_message(self, *a):
                pass

        app = GeoMesaApp(DataStore(backend="tpu"))
        httpd = make_server("127.0.0.1", 0, app, handler_class=_Quiet)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        prev = ledger_mod.install(self._charged_table())
        out_path = tmp_path / "ledger.json"
        try:
            cmd_obs_ledger_export(argparse.Namespace(
                url=f"http://127.0.0.1:{httpd.server_address[1]}",
                timeout=10.0, output=str(out_path), limit=32, json=False))
        finally:
            ledger_mod.install(prev)
            httpd.shutdown()
        (e,) = load_ledger_export(str(out_path))
        assert e["signature"] == "z2:iv16:rows"
        assert e["dispatches"] == 2


class TestReconcileLiveExport:
    """The acceptance pin: a --sync --reconcile pass over a ledger
    exported from a REAL staged-select run reports zero divergence for
    the staged signature — and a tampered export flags the declaration."""

    @pytest.fixture(scope="class")
    def export_entries(self, tmp_path_factory):
        import numpy as np

        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.obs import ledger as ledger_mod
        from geomesa_tpu.obs.ledger import LedgerTable
        from geomesa_tpu.store import backends
        from geomesa_tpu.store.datastore import DataStore

        ds = DataStore(backend="tpu")
        ds.create_schema("pts", "name:String,dtg:Date,*geom:Point")
        rng = np.random.default_rng(5)
        t0 = 1_500_000_000_000
        ds.write("pts", [
            {"name": f"n{i % 3}", "dtg": t0 + i * 1000,
             "geom": Point(float(rng.uniform(-170, 170)),
                           float(rng.uniform(-60, 60)))}
            for i in range(300)
        ], fids=[f"f{i}" for i in range(300)])
        ds.compact("pts")
        # force the staged two-phase select (count -> host sizing ->
        # gather): the multi-dispatch signature the budgets must cover
        prev_slots = backends._ONE_PASS_MAX_SLOTS
        backends._ONE_PASS_MAX_SLOTS = 0
        cql = "BBOX(geom,-50,-40,50,40)"
        try:
            ds.query("pts", cql)  # compile the staged steps
            prev = ledger_mod.install(LedgerTable())
            try:
                for _ in range(3):
                    ds.query("pts", cql)
                doc = ledger_mod.table().export()
            finally:
                ledger_mod.install(prev)
        finally:
            backends._ONE_PASS_MAX_SLOTS = prev_slots
        path = tmp_path_factory.mktemp("ledger") / "ledger.json"
        path.write_text(json.dumps(doc))
        return load_ledger_export(str(path))

    def _analyze(self, entries):
        targets = [os.path.join(PKG, "store", "backends.py"),
                   os.path.join(PKG, "store", "datastore.py")]
        vs = analyze_sync_paths(targets, LintConfig(rules=("S001",)),
                                reconcile=entries)
        return [v for v in vs if not v.suppressed]

    def test_staged_select_is_multi_dispatch(self, export_entries):
        rows = [e for e in export_entries
                if e["signature"].endswith(":rows") and e["queries"]]
        assert rows, export_entries
        assert any(e["dispatches"] / e["queries"] >= 2.0 for e in rows)

    def test_live_export_reconciles_clean(self, export_entries):
        assert self._analyze(export_entries) == [], (
            "staged select diverged from its declared budget")

    def test_tampered_export_flags_declaration(self, export_entries):
        tampered = [dict(e, dispatches=e["dispatches"] * 5)
                    for e in export_entries]
        found = self._analyze(tampered)
        assert found, "5x the measured rate must exceed the budget"
        assert all(v.rule == "S001" for v in found)
        assert any("ledger reconcile" in v.message for v in found)


class TestSarifMultiProng:
    """--all-prongs --format sarif: one run per prong including
    tpusync, S-rule suppressions survive, and the full multi-prong
    document shape is pinned as a golden file."""

    def test_four_driver_runs(self):
        out = _run_cli("--all-prongs", "--format", "sarif",
                       os.path.join(FIXTURES, "w001_sync_good.py"))
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        names = [r["tool"]["driver"]["name"] for r in doc["runs"]]
        assert names == ["tpulint", "tpurace", "tpuflow", "tpusync"]
        sync_rules = {r["id"] for r in
                      doc["runs"][3]["tool"]["driver"]["rules"]}
        assert sync_rules == {"S001", "S002", "S003", "S004", "W001"}
        lint_rules = {r["id"] for r in
                      doc["runs"][0]["tool"]["driver"]["rules"]}
        assert not lint_rules & sync_rules - {"W001"}

    def test_s_rule_suppression_round_trip(self):
        out = _run_cli("--all-prongs", "--format", "sarif",
                       os.path.join(FIXTURES, "w001_sync_good.py"))
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        sync_run = doc["runs"][3]
        s003 = [r for r in sync_run["results"] if r["ruleId"] == "S003"]
        assert len(s003) == 1
        assert s003[0]["suppressions"][0]["kind"] == "inSource"

    def test_multi_prong_golden_file(self, monkeypatch):
        """Golden-file pin of the --all-prongs SARIF document shape
        (regenerate with tests/tpulint_fixtures/make_sarif_golden.py
        when the registry or layout changes ON PURPOSE)."""
        from geomesa_tpu.analysis import lint_source
        from geomesa_tpu.analysis.report import render_json_multi

        monkeypatch.chdir(REPO)  # the golden pins repo-relative URIs

        lint_fix = os.path.join(REPO, "tests", "tpulint_fixtures")
        rel = "tests/tpulint_fixtures/j003_bad.py"
        cfg = LintConfig(j002_paths=("",), j004_paths=("",),
                         c001_paths=("",))
        with open(os.path.join(lint_fix, "j003_bad.py"),
                  encoding="utf-8") as f:
            src = f.read()
        doc = json.loads(render_json_multi([
            ("tpulint", lint_source(src, rel, cfg)),
            ("tpurace", analyze_race_paths([rel], cfg)),
            ("tpuflow", analyze_flow_paths([rel], cfg)),
            ("tpusync", analyze_sync_paths([rel], cfg)),
        ]))
        with open(os.path.join(lint_fix, "sarif_multi_golden.json"),
                  encoding="utf-8") as f:
            golden = json.load(f)
        assert doc == golden
