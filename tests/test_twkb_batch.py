"""Native TWKB batch decode + TWKB-encoded geometry persistence
(reference: ``TwkbSerialization.scala`` as the compact geometry row format —
SURVEY.md §2.4; native decoder in ``native/twkb.cpp``)."""

import numpy as np
import pyarrow as pa
import pytest

from geomesa_tpu.geometry import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geometry.twkb import from_twkb, from_twkb_batch, to_twkb
from geomesa_tpu.geometry.wkt import to_wkt
from geomesa_tpu.io.arrow import from_arrow, to_arrow
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec

SQ = np.array([[0, 0], [1, 0], [1, 1], [0, 1], [0, 0]], dtype=float)


def geoms():
    rng = np.random.default_rng(7)
    return [
        None,
        Point(1.5, -2.25),
        LineString(np.round(np.cumsum(rng.normal(0, 0.01, (30, 2)), axis=0), 6)),
        Polygon(SQ, holes=(SQ * 0.3 + 0.2,)),
        MultiPoint([Point(1, 2), Point(3, 4)]),
        MultiLineString([LineString([(0, 0), (1, 1)]),
                         LineString([(2, 2), (3, 3), (4, 2)])]),
        MultiPolygon([Polygon(SQ), Polygon(SQ + 5, holes=(SQ * 0.2 + 5.3,))]),
    ]


class TestBatchDecode:
    def test_matches_scalar_decode(self):
        gs = geoms()
        blobs = [to_twkb(g) for g in gs]
        batch = from_twkb_batch(blobs)
        for b, g in zip(batch, gs):
            one = from_twkb(to_twkb(g))
            if g is None:
                assert b is None and one is None
                continue
            assert type(b) is type(one)
            assert to_wkt(b) == to_wkt(one)

    def test_native_used_and_fast(self):
        from geomesa_tpu import native

        if native._twkb_lib() is None:
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(1)
        many = [
            to_twkb(LineString(np.cumsum(rng.normal(0, 0.01, (40, 2)), axis=0)))
            for _ in range(2000)
        ]
        out = from_twkb_batch(many)
        ref = [from_twkb(b) for b in many]
        assert all(np.allclose(a.coords, b.coords) for a, b in zip(out, ref))

    def test_none_blob_fallback(self):
        blobs = [to_twkb(Point(1, 2)), None, to_twkb(Point(3, 4))]
        out = from_twkb_batch(blobs)
        assert out[1] is None and out[0] == Point(1, 2)

    def test_malformed_input_safe(self):
        from geomesa_tpu import native

        if native._twkb_lib() is None:
            pytest.skip("no native toolchain")
        # truncated varint must not crash the native decoder
        bad = bytes([2, 0, 0xFF])
        offs = np.array([0, len(bad)], dtype=np.int64)
        assert native.twkb_decode_batch(bad, offs) is None

    def test_huge_varint_counts_rejected(self):
        """Crafted counts near 2^63/2^64 must fail the bounds check, not wrap
        it: `2 * k` overflowed for k >= 2^63 and the scan then returned
        garbage totals that under-sized the decode arrays (heap overrun)."""
        from geomesa_tpu import native

        if native._twkb_lib() is None:
            pytest.skip("no native toolchain")

        def varint(v):
            out = bytearray()
            while True:
                b = v & 0x7F
                v >>= 7
                if v:
                    out.append(b | 0x80)
                else:
                    out.append(b)
                    return bytes(out)

        for count in (2**63, 2**63 + 1, 2**64 - 1, 2**62, 2**32):
            for head in (2, 4, 5):  # linestring, multipoint, multiline
                blob = bytes([head, 0]) + varint(count) + b"\x02" * 2048
                offs = np.array([0, len(blob)], dtype=np.int64)
                assert native.twkb_decode_batch(blob, offs) is None, (
                    head, count
                )
            # polygon: ring count huge
            blob = bytes([3, 0]) + varint(count) + b"\x02" * 2048
            offs = np.array([0, len(blob)], dtype=np.int64)
            assert native.twkb_decode_batch(blob, offs) is None
        # and via the public path (reachable from untrusted ingest): a Python
        # exception is acceptable, a segfault is not
        blob = bytes([2, 0]) + varint(2**63) + b"\x02" * 2048
        try:
            from_twkb_batch([blob])
        except (ValueError, MemoryError, OverflowError):
            pass


class TestBatchEncode:
    def test_byte_identical_to_python(self):
        from geomesa_tpu import native
        from geomesa_tpu.geometry.twkb import to_twkb_batch

        if native._twkb_lib() is None:
            pytest.skip("no native toolchain")
        gs = geoms()
        buf, offs = to_twkb_batch(gs)
        for i, g in enumerate(gs):
            assert bytes(buf[offs[i] : offs[i + 1]]) == to_twkb(g)

    def test_precision_range_enforced(self):
        from geomesa_tpu.geometry.twkb import to_twkb_batch

        with pytest.raises(ValueError, match="precision"):
            to_twkb_batch([Point(1, 2)], precision=9)

    def test_encode_decode_roundtrip(self):
        from geomesa_tpu.geometry.twkb import to_twkb_batch

        gs = [g for g in geoms()]
        packed = to_twkb_batch(gs)
        if packed is None:
            pytest.skip("no native toolchain")
        buf, offs = packed
        blobs = [bytes(buf[offs[i] : offs[i + 1]]) for i in range(len(gs))]
        out = from_twkb_batch(blobs)
        for g, d in zip(gs, out):
            if g is None:
                assert d is None
            else:
                assert to_wkt(d) == to_wkt(from_twkb(to_twkb(g)))


class TestArrowTwkb:
    def test_roundtrip_with_nulls(self):
        sft = parse_spec("t", "name:String,*geom:Geometry")
        gs = geoms()
        recs = [{"name": f"g{i}", "geom": g} for i, g in enumerate(gs)]
        t = FeatureTable.from_records(sft, recs, [str(i) for i in range(len(gs))])
        at = to_arrow(t, geometry_encoding="twkb")
        f = at.schema.field("geom")
        assert f.metadata[b"geom"] == b"twkb"
        base = f.type.value_type if pa.types.is_dictionary(f.type) else f.type
        assert pa.types.is_binary(base)
        t2 = from_arrow(sft, at)
        for i, g in enumerate(gs):
            g2 = t2.record(i)["geom"]
            if g is None:
                assert g2 is None
            else:
                assert to_wkt(g2) == to_wkt(from_twkb(to_twkb(g)))

    def test_default_wkb_roundtrip_bit_exact(self):
        """The canonical mapping is lossless: coordinates that are NOT
        representable at any fixed-point precision must round-trip exactly
        (the TWKB default silently quantized them — ADVICE r2)."""
        sft = parse_spec("t", "name:String,*geom:Geometry")
        gs = geoms()
        recs = [{"name": f"g{i}", "geom": g} for i, g in enumerate(gs)]
        t = FeatureTable.from_records(sft, recs, [str(i) for i in range(len(gs))])
        at = to_arrow(t)
        assert at.schema.field("geom").metadata[b"geom"] == b"wkb"
        t2 = from_arrow(sft, at)
        for i, g in enumerate(gs):
            g2 = t2.record(i)["geom"]
            if g is None:
                assert g2 is None
            else:
                assert to_wkt(g2) == to_wkt(g)  # full f64 repr, no quantize
        # adversarial coordinates: irrational-ish doubles survive bit-exact
        from geomesa_tpu.geometry.types import Point as Pt

        sft2 = parse_spec("p", "*geom:Geometry")
        pts = [Pt(np.pi * 10**k, -np.e * 10**-k) for k in range(-3, 4)]
        t3 = FeatureTable.from_records(
            sft2, [{"geom": p} for p in pts], [str(i) for i in range(len(pts))]
        )
        t4 = from_arrow(sft2, to_arrow(t3))
        for p, r in zip(pts, (t4.record(i)["geom"] for i in range(len(pts)))):
            assert (r.x, r.y) == (p.x, p.y)

    def test_legacy_wkt_catalogs_still_read(self):
        # catalogs written before the TWKB switch hold WKT strings
        sft = parse_spec("t", "name:String,*geom:LineString")
        lines = [LineString([(0, 0), (1, 1)]), LineString([(2, 2), (3, 1)])]
        at = pa.table(
            {
                "__fid__": pa.array(["a", "b"]),
                "name": pa.array(["x", "y"]),
                "geom": pa.array([to_wkt(g) for g in lines], type=pa.string()),
            }
        )
        t = from_arrow(sft, at)
        assert to_wkt(t.record(0)["geom"]) == to_wkt(lines[0])
        assert to_wkt(t.record(1)["geom"]) == to_wkt(lines[1])

    def test_smaller_than_wkt(self):
        rng = np.random.default_rng(3)
        sft = parse_spec("t", "*geom:LineString")
        recs = [
            {"geom": LineString(np.cumsum(rng.normal(0, 0.01, (50, 2)), axis=0))}
            for _ in range(200)
        ]
        t = FeatureTable.from_records(sft, recs, [str(i) for i in range(200)])
        at = to_arrow(t, geometry_encoding="twkb")
        twkb_bytes = at.column("geom").nbytes
        wkt_bytes = sum(
            len(to_wkt(r["geom"])) for r in (t.record(i) for i in range(200))
        )
        assert twkb_bytes < wkt_bytes / 3

    def test_persistence_roundtrip_queries(self, tmp_path):
        from geomesa_tpu.store import persistence
        from geomesa_tpu.store.datastore import DataStore

        sft = parse_spec("lines", "name:String,dtg:Date,*geom:LineString")
        rng = np.random.default_rng(5)
        recs = []
        for i in range(300):
            x0 = float(rng.uniform(-170, 160))
            y0 = float(rng.uniform(-80, 70))
            recs.append(
                {"name": f"l{i}", "dtg": 1_500_000_000_000 + i,
                 "geom": LineString([(x0, y0), (x0 + 2, y0 + 1.5)])}
            )
        ds = DataStore(backend="oracle")
        ds.create_schema(sft)
        ds.write("lines", recs, fids=[str(i) for i in range(300)])
        persistence.save(ds, str(tmp_path / "cat"))
        ds2 = persistence.load(str(tmp_path / "cat"), backend="oracle")
        q = "BBOX(geom, -30, -20, 40, 30)"
        assert set(ds2.query("lines", q).table.fids.tolist()) == set(
            ds.query("lines", q).table.fids.tolist()
        )
