"""Sharded SPMD query tests on the virtual 8-device CPU mesh (SURVEY.md §4:
multi-chip behavior exercised without hardware, like the reference's
mock-cluster suites)."""

import numpy as np
import pytest

import jax

from geomesa_tpu.curve.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
from geomesa_tpu.ops.refine import pack_boxes, pack_times
from geomesa_tpu.parallel.mesh import make_mesh, shard_columns, data_shards
from geomesa_tpu.parallel.query import (
    make_batched_count_step,
    make_batched_density_step,
    make_select_step,
    max_shard_candidates,
    split_intervals_by_shard,
)

N = 4096


@pytest.fixture(scope="module")
def store_arrays():
    rng = np.random.default_rng(11)
    lon = rng.uniform(-180, 180, N)
    lat = rng.uniform(-90, 90, N)
    t = 1_500_000_000_000 + rng.integers(0, 20 * 86_400_000, N)
    binned = BinnedTime(TimePeriod.WEEK)
    bins, offs = binned.to_bin_and_offset(t)
    xi = norm_lon(31).normalize(lon).astype(np.int32)
    yi = norm_lat(31).normalize(lat).astype(np.int32)
    # z-sort (bin, morton) like the real store
    from geomesa_tpu.curve.sfc import z3_sfc

    z = z3_sfc(TimePeriod.WEEK).index(lon, lat, offs)
    perm = np.lexsort((z, bins))
    return (
        xi[perm],
        yi[perm],
        bins[perm].astype(np.int32),
        offs[perm].astype(np.int32),
    )


def brute_counts(xi, yi, bins, offs, boxes, times):
    out = []
    for b, t in zip(boxes, times):
        in_box = np.zeros(len(xi), dtype=bool)
        for xlo, xhi, ylo, yhi in b:
            in_box |= (xi >= xlo) & (xi <= xhi) & (yi >= ylo) & (yi <= yhi)
        in_time = np.zeros(len(xi), dtype=bool)
        for blo, olo, bhi, ohi in t:
            after = (bins > blo) | ((bins == blo) & (offs >= olo))
            before = (bins < bhi) | ((bins == bhi) & (offs <= ohi))
            in_time |= after & before
        out.append(int((in_box & in_time).sum()))
    return np.array(out, dtype=np.int32)


def make_queries(q=4):
    nlon = norm_lon(31)
    nlat = norm_lat(31)
    boxes, times = [], []
    rng = np.random.default_rng(5)
    for i in range(q):
        x1 = float(rng.uniform(-170, 150))
        y1 = float(rng.uniform(-80, 60))
        x2, y2 = x1 + 20, y1 + 20
        b = np.array(
            [[nlon.normalize(x1), nlon.normalize(x2), nlat.normalize(y1), nlat.normalize(y2)]],
            dtype=np.int32,
        )
        t = np.array([[2480, 0, 2482, 604799]], dtype=np.int32)
        boxes.append(pack_boxes(b))
        times.append(pack_times(t))
    return np.stack(boxes), np.stack(times)


class TestShardedQueries:
    def test_device_count(self):
        assert len(jax.devices()) == 8

    @pytest.mark.parametrize("query_parallel", [1, 2])
    def test_batched_count_parity(self, store_arrays, query_parallel):
        xi, yi, bins, offs = store_arrays
        mesh = make_mesh(query_parallel=query_parallel)
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        step = make_batched_count_step(mesh)
        boxes, times = make_queries(4)
        import jax.numpy as jnp

        counts = step(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.int32(len(xi)), jnp.asarray(boxes), jnp.asarray(times),
        )
        expected = brute_counts(xi, yi, bins, offs, boxes, times)
        np.testing.assert_array_equal(np.asarray(counts), expected)
        assert expected.sum() > 0  # non-vacuous

    @pytest.mark.parametrize("query_parallel", [1, 2])
    def test_planned_count_pruned_blocks(self, store_arrays, query_parallel):
        """Index-pruned count (VERDICT r4 item 3): counts over ONLY the
        planner's candidate blocks must equal the full-scan counts when
        the block set covers every matching row — including batches with
        different pair counts and empty-result queries."""
        from geomesa_tpu.parallel.query import (
            intervals_to_block_pairs,
            make_planned_count_step,
            pad_block_pairs,
        )

        xi, yi, bins, offs = store_arrays
        B = 64
        mesh = make_mesh(query_parallel=query_parallel)
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs},
            multiple=B,
        )
        assert rows_per_shard % B == 0
        import jax.numpy as jnp

        R, q = 2, 4
        boxes_r, times_r, pq_r, pb_r, expected = [], [], [], [], []
        pair_budget = 256
        for r in range(R):
            boxes, times = make_queries(q)
            if r == 1:
                # one empty-result query: impossible box
                boxes[2] = pack_boxes(
                    np.array([[5, 4, 5, 4]], np.int32))
            exp = brute_counts(xi, yi, bins, offs, boxes, times)
            # exact minimal cover: row-run intervals of the true matches
            ivs = []
            for i in range(q):
                m = np.zeros(len(xi), dtype=bool)
                for xlo, xhi, ylo, yhi in boxes[i]:
                    m |= ((xi >= xlo) & (xi <= xhi)
                          & (yi >= ylo) & (yi <= yhi))
                tm = np.zeros(len(xi), dtype=bool)
                for blo, olo, bhi, ohi in times[i]:
                    tm |= (((bins > blo) | ((bins == blo) & (offs >= olo)))
                           & ((bins < bhi) | ((bins == bhi)
                                             & (offs <= ohi))))
                rows = np.flatnonzero(m & tm)
                if len(rows) == 0:
                    ivs.append(np.empty((0, 2), np.int64))
                    continue
                cut = np.flatnonzero(np.diff(rows) > 1)
                starts = np.concatenate(([rows[0]], rows[cut + 1]))
                ends = np.concatenate((rows[cut] + 1, [rows[-1] + 1]))
                ivs.append(np.stack([starts, ends], axis=1))
            q_, b_ = intervals_to_block_pairs(ivs, B)
            pq, pb = pad_block_pairs(q_, b_, pair_budget)
            boxes_r.append(boxes)
            times_r.append(times)
            pq_r.append(pq)
            pb_r.append(pb)
            expected.append(exp)

        step = make_planned_count_step(mesh, q, B, pair_budget, chunk=8)
        counts = np.asarray(step(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.int32(len(xi)),
            jnp.asarray(np.stack(pq_r)), jnp.asarray(np.stack(pb_r)),
            jnp.asarray(np.stack(boxes_r)), jnp.asarray(np.stack(times_r)),
        ))
        np.testing.assert_array_equal(counts, np.stack(expected))
        assert np.stack(expected).sum() > 0  # non-vacuous
        assert expected[1][2] == 0  # the empty query really is empty

    def test_pad_block_pairs_overflow_raises(self):
        from geomesa_tpu.parallel.query import pad_block_pairs

        with pytest.raises(ValueError, match="exceed budget"):
            pad_block_pairs(np.zeros(9, np.int32), np.zeros(9, np.int32), 8)

    def test_batched_count_pallas_impl(self, store_arrays):
        """shard_map + interpret-mode Pallas kernel agrees with brute force."""
        xi, yi, bins, offs = store_arrays
        mesh = make_mesh()
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        step = make_batched_count_step(mesh, impl="pallas")
        boxes, times = make_queries(2)
        import jax.numpy as jnp

        counts = step(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.int32(len(xi)), jnp.asarray(boxes), jnp.asarray(times),
        )
        expected = brute_counts(xi, yi, bins, offs, boxes, times)
        np.testing.assert_array_equal(np.asarray(counts), expected)

    def test_select_step_parity(self, store_arrays):
        xi, yi, bins, offs = store_arrays
        mesh = make_mesh()
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        # plan: a couple of global row intervals
        intervals = np.array([[100, 900], [1500, 3200]], dtype=np.int64)
        shards = data_shards(mesh)
        bucket = max(64, max_shard_candidates(intervals, rows_per_shard, shards))
        idx, cnts = split_intervals_by_shard(intervals, rows_per_shard, shards, bucket)
        boxes, times = make_queries(1)
        import jax.numpy as jnp

        step = make_select_step(mesh)
        mask, total = step(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.asarray(idx), jnp.asarray(cnts),
            jnp.asarray(boxes[0]), jnp.asarray(times[0]),
        )
        # brute force over the same intervals
        sel = np.concatenate([np.arange(s, e) for s, e in intervals])
        bsel = brute_counts(
            xi[sel], yi[sel], bins[sel], offs[sel], boxes[:1], times[:1]
        )[0]
        assert int(total) == int(bsel)

    def test_batched_density(self, store_arrays):
        xi, yi, bins, offs = store_arrays
        mesh = make_mesh(query_parallel=2)
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        boxes, times = make_queries(2)
        grid_bounds = np.stack([
            np.array([boxes[q, 0, 0], boxes[q, 0, 1], boxes[q, 0, 2], boxes[q, 0, 3]], dtype=np.int32)
            for q in range(2)
        ])
        import jax.numpy as jnp

        step = make_batched_density_step(mesh, width=64, height=64)
        grids = step(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.int32(len(xi)), jnp.asarray(boxes), jnp.asarray(times),
            jnp.asarray(grid_bounds),
        )
        grids = np.asarray(grids)
        assert grids.shape == (2, 64, 64)
        expected = brute_counts(xi, yi, bins, offs, boxes, times)
        # grid mass == count (all matching rows inside their query's grid bounds)
        np.testing.assert_allclose(grids.sum(axis=(1, 2)), expected.astype(np.float32))


class TestDistributedSelect:
    """Distributed row retrieval (ArrowScan/QueryPlan.scan role): two-pass
    count→gather over the mesh returns the exact matching row positions."""

    def test_gather_step_positions_parity(self, store_arrays):
        from geomesa_tpu.parallel.query import (
            cached_select_count_step,
            cached_select_gather_step,
        )
        import jax.numpy as jnp

        xi, yi, bins, offs = store_arrays
        mesh = make_mesh()
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        intervals = np.array([[0, len(xi)]], dtype=np.int64)  # full scan
        shards = data_shards(mesh)
        bucket = max(64, max_shard_candidates(intervals, rows_per_shard, shards))
        idx, cnts = split_intervals_by_shard(intervals, rows_per_shard, shards, bucket)
        boxes, times = make_queries(1)
        counts = np.asarray(
            cached_select_count_step(mesh)(
                cols["x"], cols["y"], cols["bins"], cols["offs"],
                jnp.asarray(idx), jnp.asarray(cnts),
                jnp.asarray(boxes[0]), jnp.asarray(times[0]),
            )
        )
        capacity = max(128, int(counts.max()))
        pos, hits = cached_select_gather_step(mesh, capacity)(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.asarray(idx), jnp.asarray(cnts),
            jnp.asarray(boxes[0]), jnp.asarray(times[0]),
        )
        pos, hits = np.asarray(pos), np.asarray(hits)
        got = np.sort(np.concatenate([pos[d, : hits[d]] for d in range(shards)]))
        # brute force reference positions
        b, t = boxes[0], times[0]
        in_box = np.zeros(len(xi), dtype=bool)
        for xlo, xhi, ylo, yhi in b:
            in_box |= (xi >= xlo) & (xi <= xhi) & (yi >= ylo) & (yi <= yhi)
        in_time = np.zeros(len(xi), dtype=bool)
        for blo, olo, bhi, ohi in t:
            after = (bins > blo) | ((bins == blo) & (offs >= olo))
            before = (bins < bhi) | ((bins == bhi) & (offs <= ohi))
            in_time |= after & before
        expected = np.nonzero(in_box & in_time)[0]
        assert len(expected) > 0  # non-vacuous
        np.testing.assert_array_equal(got, expected)
        assert (hits == counts).all()

    def test_gather_step_replicated_all_gather(self, store_arrays):
        from geomesa_tpu.parallel.query import (
            cached_select_count_step,
            cached_select_gather_step,
        )
        import jax.numpy as jnp

        xi, yi, bins, offs = store_arrays
        mesh = make_mesh()
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        intervals = np.array([[0, len(xi)]], dtype=np.int64)
        shards = data_shards(mesh)
        bucket = max(64, max_shard_candidates(intervals, rows_per_shard, shards))
        idx, cnts = split_intervals_by_shard(intervals, rows_per_shard, shards, bucket)
        boxes, times = make_queries(1)
        args = (
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.asarray(idx), jnp.asarray(cnts),
            jnp.asarray(boxes[0]), jnp.asarray(times[0]),
        )
        counts = np.asarray(cached_select_count_step(mesh)(*args))
        capacity = max(128, int(counts.max()))
        pos_r, hits_r = cached_select_gather_step(mesh, capacity, True)(*args)
        pos_d, hits_d = cached_select_gather_step(mesh, capacity, False)(*args)
        # replicated output == distributed output, merged on-fabric
        np.testing.assert_array_equal(np.asarray(pos_r), np.asarray(pos_d))
        np.testing.assert_array_equal(np.asarray(hits_r), np.asarray(hits_d))

    def test_datastore_mesh_select_rows_and_arrow_out(self):
        """End-to-end: DataStore.query on the tpu (mesh) backend returns the
        oracle row set and exports Arrow IPC."""
        from geomesa_tpu.io.arrow import from_ipc_bytes, to_ipc_bytes
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(77)
        n = 20_000
        recs = []
        from geomesa_tpu.geometry.types import Point

        for i in range(n):
            recs.append({
                "name": f"f{i % 97}",
                "dtg": 1_500_000_000_000 + int(rng.integers(0, 10 * 86_400_000)),
                "geom": Point(float(rng.uniform(-60, 60)), float(rng.uniform(-40, 40))),
            })
        oracle = DataStore(backend="oracle")
        tpu = DataStore(backend="tpu")
        cql = (
            "BBOX(geom, -20, -20, 25, 30) AND dtg DURING "
            "2017-07-14T00:00:00Z/2017-07-18T00:00:00Z"
        )
        for ds in (oracle, tpu):
            ds.create_schema("pts", "name:String,dtg:Date,*geom:Point")
            ds.write("pts", recs)
        a = oracle.query("pts", cql)
        b = tpu.query("pts", cql)
        assert a.count > 100  # non-vacuous
        assert set(a.table.fids.tolist()) == set(b.table.fids.tolist())
        # Arrow IPC out of the mesh-selected rows, round-tripped
        data = to_ipc_bytes(b.table)
        rt = from_ipc_bytes(b.table.sft, data)
        assert set(rt.fids.tolist()) == set(a.table.fids.tolist())


class TestOnehotBincount:
    def test_matches_numpy_across_chunks(self):
        import jax.numpy as jnp

        from geomesa_tpu.parallel.query import _onehot_bincount

        rng = np.random.default_rng(6)
        for n in (100, 8192, 8193, 30_000):
            ids = rng.integers(0, 17, n).astype(np.int32)
            got = np.asarray(_onehot_bincount(jnp.asarray(ids), 17))
            want = np.bincount(ids, minlength=17)
            # the last class is the DISCARD class: chunk padding lands there
            np.testing.assert_array_equal(got[:-1], want[:-1])
        assert got.dtype == np.int32  # int32 carry: exact at any count

    def test_auto_falls_back_above_group_cap(self):
        from geomesa_tpu.parallel.mesh import make_mesh
        from geomesa_tpu.parallel.query import (
            _MXU_BINCOUNT_MAX_GROUPS,
            make_grouped_agg_step,
        )

        # on the CPU test backend auto is always "segment"; the cap logic is
        # exercised by constructing the step at high cardinality (must not
        # raise and must compile the segment path)
        step = make_grouped_agg_step(
            make_mesh(8, query_parallel=2),
            _MXU_BINCOUNT_MAX_GROUPS * 2, 0, 64,
        )
        assert step is not None


class TestGroupedAggImpls:
    def test_mxu_bincount_equals_segment_impl(self):
        """The one-hot-matmul count path (TPU auto-choice — the density
        kernel's scatter-beating trick) must agree EXACTLY with the
        segment_sum path: bf16 one-hot entries are 0/1 and f32 accumulation
        is exact below 2**24."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from geomesa_tpu.parallel.mesh import make_mesh, shard_columns
        from geomesa_tpu.parallel.query import make_grouped_agg_step

        rng = np.random.default_rng(3)
        mesh = make_mesh(8, query_parallel=2)
        n = 20_000
        G = 64
        x = rng.integers(0, 1 << 20, n).astype(np.int32)
        y = rng.integers(0, 1 << 20, n).astype(np.int32)
        bins = rng.integers(0, 4, n).astype(np.int32)
        offs = rng.integers(0, 1000, n).astype(np.int32)
        gid = rng.integers(0, G, n).astype(np.int32)
        vals = rng.normal(size=(2, n))
        vals[0, ::9] = np.nan
        cols, padded, _ = shard_columns(mesh, {
            "x": x, "y": y, "bins": bins, "offs": offs, "gid": gid,
            "rowid": np.arange(n, dtype=np.int32),
        })
        pv = np.zeros((2, padded))
        pv[:, :n] = vals
        dvals = jax.device_put(pv, NamedSharding(mesh, P(None, "data")))
        boxes = np.broadcast_to(
            np.array([[0, 700_000, 0, 1 << 20]], np.int32), (2, 1, 4)
        ).copy()
        times = np.broadcast_to(
            np.array([[0, -1, 10, 10_000]], np.int32), (2, 1, 4)
        ).copy()
        args = (cols["x"], cols["y"], cols["bins"], cols["offs"],
                cols["gid"], cols["rowid"], dvals, jnp.int32(n),
                jnp.asarray(boxes), jnp.asarray(times))
        seg = make_grouped_agg_step(mesh, G, 2, 256, impl="segment")(*args)
        mxu = make_grouped_agg_step(mesh, G, 2, 256, impl="mxu")(*args)
        for a, b, name in zip(seg[:4], mxu[:4],
                              ("cnt", "first", "vcnt", "vsum")):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name
            )
