"""Schema-registry Avro stream messages: framing, evolution, stream store."""

import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.stream.confluent import AvroGeoMessageSerializer, SchemaRegistry
from geomesa_tpu.stream.messages import Clear, Delete, Put

SPEC_V1 = "name:String,dtg:Date,*geom:Point"
SPEC_V2 = "name:String,severity:Integer,dtg:Date,*geom:Point"  # adds a field


class TestRegistry:
    def test_idempotent_ids(self):
        reg = SchemaRegistry()
        from geomesa_tpu.io.avro import avro_schema

        s1 = avro_schema(parse_spec("e", SPEC_V1))
        s2 = avro_schema(parse_spec("e", SPEC_V2))
        assert reg.register("e", s1) == reg.register("e", s1) == 1
        assert reg.register("e", s2) == 2
        assert reg.versions("e") == [1, 2]
        assert reg.schema_by_id(2) == s2
        with pytest.raises(KeyError):
            reg.schema_by_id(99)


class TestRoundTrip:
    def test_put_delete_clear(self):
        reg = SchemaRegistry()
        ser = AvroGeoMessageSerializer(parse_spec("e", SPEC_V1), reg)
        put = Put("f1", {"name": "a", "dtg": 1000, "geom": Point(3.0, 4.0)}, 77)
        out = ser.deserialize(ser.serialize(put))
        assert out.fid == "f1" and out.ts == 77
        assert out.record["name"] == "a"
        assert out.record["geom"].x == 3.0
        d = ser.deserialize(ser.serialize(Delete("f1", 88)))
        assert isinstance(d, Delete) and d.fid == "f1"
        assert isinstance(ser.deserialize(ser.serialize(Clear(99))), Clear)

    def test_null_attribute(self):
        reg = SchemaRegistry()
        ser = AvroGeoMessageSerializer(parse_spec("e", SPEC_V1), reg)
        put = Put("f2", {"name": None, "dtg": 5, "geom": Point(1.0, 2.0)}, 1)
        out = ser.deserialize(ser.serialize(put))
        assert out.record["name"] is None

    def test_bad_magic(self):
        reg = SchemaRegistry()
        ser = AvroGeoMessageSerializer(parse_spec("e", SPEC_V1), reg)
        with pytest.raises(ValueError):
            ser.deserialize(b"\x01\x00\x00\x00\x01rest")


class TestEvolution:
    def test_old_producer_new_consumer(self):
        # v1 producer writes; v2 consumer (extra 'severity' field) reads:
        # the missing field resolves to null
        reg = SchemaRegistry()
        old = AvroGeoMessageSerializer(parse_spec("e", SPEC_V1), reg)
        new = AvroGeoMessageSerializer(parse_spec("e", SPEC_V2), reg)
        wire = old.serialize(
            Put("f1", {"name": "x", "dtg": 9, "geom": Point(1.0, 1.0)}, 5)
        )
        out = new.deserialize(wire)
        assert out.record["name"] == "x"
        assert out.record["severity"] is None
        assert out.record["geom"].y == 1.0

    def test_new_producer_old_consumer(self):
        # v2 producer writes (with severity); v1 consumer drops the field
        reg = SchemaRegistry()
        old = AvroGeoMessageSerializer(parse_spec("e", SPEC_V1), reg)
        new = AvroGeoMessageSerializer(parse_spec("e", SPEC_V2), reg)
        wire = new.serialize(
            Put("f2", {"name": "y", "severity": 3, "dtg": 9,
                       "geom": Point(2.0, 2.0)}, 5)
        )
        out = old.deserialize(wire)
        assert out.record["name"] == "y"
        assert "severity" not in out.record
        assert out.record["geom"].x == 2.0


class TestStreamStoreIntegration:
    def test_bus_roundtrip_with_avro_codec(self):
        """The stream datastore accepts the drop-in Avro codec."""
        from geomesa_tpu.stream.datastore import MessageBus, StreamingDataStore

        reg = SchemaRegistry()
        bus = MessageBus()
        sds = StreamingDataStore(bus=bus)
        sft = parse_spec("live", SPEC_V1 + ";geomesa.z3.interval='week'")
        sds.create_schema(sft, serializer=AvroGeoMessageSerializer(sft, reg))
        sds.put("live", "a", {"name": "a", "dtg": 1_600_000_000_000,
                              "geom": Point(1.0, 2.0)})
        sds.put("live", "b", {"name": "b", "dtg": 1_600_000_000_000,
                              "geom": Point(50.0, 8.0)})
        r = sds.query("live", "BBOX(geom, 0, 0, 10, 10)")
        assert set(r.table.fids) == {"a"}
        sds.delete("live", "a")
        r = sds.query("live", "BBOX(geom, 0, 0, 10, 10)")
        assert len(r.table) == 0
        sds.close()

    def test_mismatched_serializer_rejected(self):
        from geomesa_tpu.stream.datastore import StreamingDataStore

        reg = SchemaRegistry()
        other = parse_spec("other", "a:Integer,*geom:Point")
        sds = StreamingDataStore()
        sft = parse_spec("live", SPEC_V1)
        with pytest.raises(ValueError, match="bound to schema"):
            sds.create_schema(sft, serializer=AvroGeoMessageSerializer(other, reg))
