"""Query interceptors, z3-prefixed feature ids, GPX converter
(reference: QueryInterceptor.scala:27, uuid/Z3 time-UUIDs, OSM-GPX configs —
SURVEY.md §2.3/§2.16/§2.18)."""

import numpy as np
import pytest

from geomesa_tpu.convert.gpx import gpx_track_sft, parse_gpx
from geomesa_tpu.filter import ast
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.utils.fid import Z3FidGenerator, z3_fids

T0 = 1_498_867_200_000


class TestInterceptors:
    def _ds(self):
        ds = DataStore(backend="oracle")
        ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
        ds.write("t", [
            {"name": f"n{i % 3}", "dtg": T0 + i, "geom": Point(i, i)}
            for i in range(30)
        ])
        return ds

    def test_rewrite_applies(self):
        ds = self._ds()

        def only_n1(sft, q):
            from dataclasses import replace

            return replace(q, filter=ast.And([q.resolved_filter(),
                                              ast.Compare("=", "name", "n1")]))

        ds.register_interceptor("t", only_n1)
        assert ds.query("t").count == 10

    def test_scope(self):
        ds = self._ds()
        ds.create_schema("u", "name:String,dtg:Date,*geom:Point")
        ds.write("u", [{"name": "x", "dtg": T0, "geom": Point(0, 0)}])
        calls = []
        ds.register_interceptor("u", lambda sft, q: calls.append(sft.name) or q)
        ds.query("t")
        assert calls == []
        ds.query("u")
        assert calls == ["u"]

    def test_global_interceptor_and_none_return(self):
        ds = self._ds()
        seen = []
        ds.register_interceptor(None, lambda sft, q: seen.append(1) or None)
        assert ds.query("t").count == 30  # None return leaves query unchanged
        assert seen == [1]


class TestZ3Fids:
    def test_vectorized_prefix_clusters(self):
        lons = np.array([10.0, 10.0001, -120.0])
        lats = np.array([20.0, 20.0001, -45.0])
        ts = np.array([T0, T0 + 1000, T0], dtype=np.int64)
        fids = z3_fids(lons, lats, ts)
        assert len(set(fids)) == 3  # unique (random suffix)
        # nearby points share a long id prefix; distant ones don't
        a, b, c = [f.split("-")[0] for f in fids]
        assert a[:8] == b[:8]
        # same time bin ⇒ same leading (bin) chars; the z part must differ
        assert a[4:10] != c[4:10]

    def test_generator_matches_vectorized_prefix(self):
        gen = Z3FidGenerator()
        f1 = gen.fid(10.0, 20.0, T0)
        f2 = z3_fids([10.0], [20.0], [T0])[0]
        assert f1.split("-")[0] == f2.split("-")[0]

    def test_store_opt_in(self):
        ds = DataStore(backend="oracle")
        ds.create_schema(
            "z", "dtg:Date,*geom:Point;geomesa.fid.uuid='z3'"
        )
        ds.write("z", [{"dtg": T0 + i, "geom": Point(10 + i * 1e-4, 20.0)}
                       for i in range(5)])
        r = ds.query("z")
        fids = list(r.table.fids)
        assert all("-" in f and len(f.split("-")[0]) == 16 for f in fids)
        # co-located features share the coarse-z prefix
        prefixes = {f[:8] for f in fids}
        assert len(prefixes) == 1

    def test_store_default_sequential(self):
        ds = DataStore(backend="oracle")
        ds.create_schema("s", "dtg:Date,*geom:Point")
        ds.write("s", [{"dtg": T0, "geom": Point(0, 0)}])
        assert list(ds.query("s").table.fids) == ["s.0"]


GPX = """<?xml version="1.0"?>
<gpx version="1.1" xmlns="http://www.topografix.com/GPX/1/1">
 <trk><name>morning ride</name><trkseg>
  <trkpt lat="47.60" lon="-122.33"><time>2017-07-01T08:00:00Z</time></trkpt>
  <trkpt lat="47.61" lon="-122.32"><time>2017-07-01T08:05:00Z</time></trkpt>
  <trkpt lat="47.62" lon="-122.31"><time>2017-07-01T08:10:00Z</time></trkpt>
 </trkseg></trk>
 <trk><trkseg>
  <trkpt lat="40.0" lon="-74.0"/>
  <trkpt lat="40.1" lon="-74.1"/>
 </trkseg></trk>
 <trk><trkseg>
  <trkpt lat="1.0" lon="1.0"/>
 </trkseg></trk>
</gpx>"""


class TestGpx:
    def test_tracks(self):
        t = parse_gpx(GPX)
        # 1-point track dropped in LineString mode
        assert len(t) == 2
        r0 = t.record(0)
        assert r0["name"] == "morning ride"
        assert r0["nPoints"] == 3
        assert r0["dtg"] == 1_498_896_000_000  # 2017-07-01T08:00Z
        assert r0["geom"].coords.shape == (3, 2)
        assert t.record(1)["dtg"] is None

    def test_points_mode(self):
        t = parse_gpx(GPX, as_points=True)
        assert len(t) == 6
        assert t.record(0)["geom"].x == pytest.approx(-122.33)

    def test_ingest_into_store(self):
        from geomesa_tpu.convert.validate import apply_validators

        ds = DataStore(backend="oracle")
        ds.create_schema(gpx_track_sft())
        # drop timestampless tracks before write (the SimpleFeatureValidator
        # gate — the store rejects null indexed dates)
        table = apply_validators(parse_gpx(GPX), ("index",))
        ds.write("gpx_tracks", table)
        r = ds.query("gpx_tracks", "BBOX(geom, -123, 47, -122, 48)")
        assert r.count == 1
        assert r.table.record(0)["name"] == "morning ride"
