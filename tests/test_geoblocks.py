"""GeoBlocks pyramid + query cache (ops/geoblocks.py, ISSUE 7): exact
parity of the interior-from-pyramid + boundary-refined-from-base answer
against the brute-force referee, epoch-based invalidation (a write can
never leave a stale cached answer servable — red/green), warm repeats
served from cache byte-identically, pool-attributed warm-up staging, and
the concurrent write+aggregate stress that rides the lock-order sanitizer
in CI (scripts/lint.sh)."""

import threading

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.obs import devmon
from geomesa_tpu.ops.geoblocks import AggPyramid, QueryCache
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000
SPEC = "name:String,val:Double,cnt:Integer,dtg:Date,*geom:Point"


@pytest.fixture(autouse=True)
def _fresh_costs():
    """The cost table (and its routing probe phase) is process-global:
    without isolation, earlier tests' consult ticks decide which test
    lands on the every-16th probe-the-loser route — order-fragile."""
    from geomesa_tpu.obs.devmon import CostTable

    prev = devmon.install(new_costs=CostTable())
    yield
    devmon.install(new_costs=prev[1])


def mk(backend="tpu", n=3000, seed=21, compact=True):
    rng = np.random.default_rng(seed)
    ds = DataStore(backend=backend)
    ds.create_schema("ev", SPEC)
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-45, 45, n)
    # rows exactly ON the query box edges: the boundary refinement path
    # must settle these against the f64 filter, not the int superset
    lon[:25] = 10.0
    lat[25:50] = -20.0
    t = T0 + rng.integers(0, 3 * 86_400_000, n)
    recs = [
        {
            "name": f"g{i % 7}",
            "val": None if i % 11 == 0 else float((i * 37) % 1000) / 10.0,
            "cnt": int(i % 13),
            "dtg": int(t[i]),
            "geom": Point(float(lon[i]), float(lat[i])),
        }
        for i in range(n)
    ]
    ds.write("ev", recs, fids=[f"e{i}" for i in range(n)])
    if compact:
        ds.compact("ev")
    return ds


QUERIES = [
    "BBOX(geom, -50, -40, 10, -20)",
    "BBOX(geom, -50, -40, 10, -20) AND dtg DURING "
    "2020-09-13T12:00:00Z/2020-09-15T00:00:00Z",
    "dtg DURING 2020-09-13T12:00:00Z/2020-09-14T00:00:00Z",
    "INCLUDE",
    "BBOX(geom, -0.5, -0.5, 0.5, 0.5)",  # tiny box: all-boundary cover
]


def _same(a, b, rtol=1e-9):
    if (a is None) != (b is None):
        return False
    if a is None:
        return True
    if a["groups"] != b["groups"]:
        return False
    if not np.array_equal(a["count"], b["count"]):
        return False
    for c in a["cols"]:
        for k in ("count", "min", "max"):
            x, y = a["cols"][c][k], b["cols"][c][k]
            if not np.allclose(x, y, rtol=rtol, equal_nan=True):
                return False
        if not np.allclose(a["cols"][c]["sum"], b["cols"][c]["sum"],
                           rtol=1e-6, equal_nan=True):
            return False
    return True


class TestPyramidParity:
    @pytest.mark.parametrize("q", QUERIES)
    def test_pyramid_equals_fused_scan(self, q, monkeypatch):
        tpu = mk("tpu")
        got = tpu.aggregate_many("ev", [q], group_by=["name"],
                                 value_cols=["val", "cnt"])
        assert got[0] is not None
        assert tpu.metrics.counter("store.agg.pyramid_served").count == 1
        # referee: the SAME query through the fused device scan
        monkeypatch.setenv("GEOMESA_TPU_PYRAMID", "0")
        ref_ds = mk("tpu", seed=21)
        ref = ref_ds.aggregate_many("ev", [q], group_by=["name"],
                                    value_cols=["val", "cnt"])
        assert ref[0] is not None
        assert ref_ds.metrics.counter("store.agg.pyramid_served").count == 0
        assert _same(got[0], ref[0])

    def test_no_group_by_and_delta_fold(self):
        tpu = mk("tpu")
        tpu.write("ev", [
            {"name": "fresh", "val": 5.0, "cnt": 1, "dtg": T0,
             "geom": Point(0.25, 0.25)},
        ], fids=["d1"])
        q = "BBOX(geom, -10, -10, 10, 10)"
        got = tpu.aggregate_many("ev", [q], group_by=["name"],
                                 value_cols=["val"])
        import os

        os.environ["GEOMESA_TPU_PYRAMID"] = "0"
        try:
            ref_ds = mk("tpu")
            ref_ds.write("ev", [
                {"name": "fresh", "val": 5.0, "cnt": 1, "dtg": T0,
                 "geom": Point(0.25, 0.25)},
            ], fids=["d1"])
            ref = ref_ds.aggregate_many("ev", [q], group_by=["name"],
                                        value_cols=["val"])
        finally:
            del os.environ["GEOMESA_TPU_PYRAMID"]
        assert _same(got[0], ref[0])
        assert any(k == ("fresh",) for k in got[0]["groups"])

    def test_global_aggregate_no_groups(self):
        tpu = mk("tpu")
        got = tpu.aggregate_many("ev", ["INCLUDE"], group_by=None,
                                 value_cols=["val"])
        assert got[0] is not None
        assert int(got[0]["count"].sum()) == 3000

    def test_byte_cap_falls_back_to_scan(self, monkeypatch):
        monkeypatch.setenv("GEOMESA_TPU_PYRAMID_BYTES", "64")
        tpu = mk("tpu")
        out = tpu.aggregate_many("ev", [QUERIES[0]], group_by=["name"],
                                 value_cols=["val"])
        assert out[0] is not None  # fused scan served it
        assert tpu.metrics.counter("store.agg.pyramid_served").count == 0


class TestEpochInvalidation:
    def test_write_red_green(self):
        """THE satellite red/green: a cached aggregate must never serve
        the pre-write answer after a write returns."""
        ds = mk("tpu")
        q = "BBOX(geom, -60, -45, 60, 45)"
        before = ds.aggregate_many("ev", [q], group_by=["name"],
                                   value_cols=["val"])
        n_before = int(before[0]["count"].sum())
        # prime the cache (warm hit)
        again = ds.aggregate_many("ev", [q], group_by=["name"],
                                  value_cols=["val"])
        assert ds.metrics.counter("store.agg.cache_hits").count == 1
        assert _same(before[0], again[0])
        ds.write("ev", [{"name": "g0", "val": 1.0, "cnt": 0, "dtg": T0,
                         "geom": Point(0.1, 0.1)}], fids=["w1"])
        after = ds.aggregate_many("ev", [q], group_by=["name"],
                                  value_cols=["val"])
        assert int(after[0]["count"].sum()) == n_before + 1
        # compaction re-sorts: cached first-occurrence order is stale too
        ds.compact("ev")
        post_compact = ds.aggregate_many("ev", [q], group_by=["name"],
                                         value_cols=["val"])
        assert int(post_compact[0]["count"].sum()) == n_before + 1
        # deletes invalidate as well
        ds.delete_features("ev", ["w1"])
        post_del = ds.aggregate_many("ev", [q], group_by=["name"],
                                     value_cols=["val"])
        assert int(post_del[0]["count"].sum()) == n_before

    def test_warm_repeat_is_cache_served_and_identical(self):
        ds = mk("tpu")
        q = "BBOX(geom, -50, -40, 10, -20)"
        cold = ds.aggregate_many("ev", [q], group_by=["name"],
                                 value_cols=["val"])
        served0 = ds.metrics.counter("store.agg.pyramid_served").count
        warm = ds.aggregate_many("ev", [q], group_by=["name"],
                                 value_cols=["val"])
        # the warm run recomputed NOTHING: no pyramid or scan execution
        assert ds.metrics.counter("store.agg.pyramid_served").count == served0
        assert ds.agg_cache.snapshot()["hits"] == 1
        assert _same(cold[0], warm[0], rtol=0.0)

    def test_concurrent_write_aggregate_stress(self):
        """Writers and aggregators race; every answer must be internally
        consistent and the final quiesced answer exact. Runs under the
        GEOMESA_TPU_SANITIZE lock-order sanitizer in scripts/lint.sh."""
        ds = mk("tpu", n=800)
        q = "BBOX(geom, -60, -45, 60, 45)"
        errs = []
        stop = threading.Event()

        def writer(tid):
            try:
                for i in range(20):
                    ds.write("ev", [{
                        "name": f"w{tid}", "val": 1.0, "cnt": 0,
                        "dtg": T0 + i, "geom": Point(0.5, 0.5),
                    }], fids=[f"w{tid}-{i}"])
            except Exception as e:  # noqa: BLE001
                errs.append(e)
            finally:
                stop.set()

        def aggregator():
            try:
                while not stop.is_set():
                    out = ds.aggregate_many(
                        "ev", [q], group_by=["name"], value_cols=["val"])
                    if out[0] is not None:
                        assert int(out[0]["count"].sum()) >= 800
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(2)]
        threads += [threading.Thread(target=aggregator) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs
        final = ds.aggregate_many("ev", [q], group_by=["name"],
                                  value_cols=["val"])
        assert int(final[0]["count"].sum()) == 800 + 2 * 20


class TestSchemaLifecycleInvalidation:
    def test_delete_recreate_never_serves_dead_tables_answer(self):
        """The epoch tuple RECURS across delete_schema + create_schema of
        the same name — the cache must die with the schema, not outlive
        it and serve the dead table's aggregate as the new table's."""
        ds = mk("tpu", n=400)
        q = "BBOX(geom, -60, -45, 60, 45)"
        before = ds.aggregate_many("ev", [q], group_by=["name"],
                                   value_cols=[])[0]
        assert before is not None and len(before["groups"]) == 7
        ds.delete_schema("ev")
        ds.create_schema("ev", SPEC)
        recs = [{"name": "zz", "val": 1.0, "cnt": 0,
                 "dtg": T0 + i, "geom": Point(0.1, 0.1)}
                for i in range(50)]
        ds.write("ev", recs, fids=[f"n{i}" for i in range(50)])
        ds.compact("ev")
        after = ds.aggregate_many("ev", [q], group_by=["name"],
                                  value_cols=[])[0]
        assert after is not None
        assert after["groups"] == [("zz",)]
        assert int(after["count"].sum()) == 50

    def test_rename_drops_old_name_cache(self):
        ds = mk("tpu", n=300)
        q = "BBOX(geom, -60, -45, 60, 45)"
        ds.aggregate_many("ev", [q], group_by=["name"], value_cols=[])
        ds.update_schema("ev", rename_to="ev2")
        assert ds.agg_cache.snapshot()["entries"] == 0
        got = ds.aggregate_many("ev2", [q], group_by=["name"],
                                value_cols=[])[0]
        assert int(got["count"].sum()) == 300


class TestPoolAttribution:
    def test_pool_label_excluded_from_devprof(self):
        """Satellite red/green: pool warm-up staging bytes land on the
        pool's jaxmon counter, never in the triggering query's devprof
        h2d split; unlabeled (query-side) staging IS attributed."""
        from geomesa_tpu.obs import jaxmon

        with devmon.profiled() as prof:
            mine = np.zeros(128, dtype=np.int32)
            pool_bytes = np.zeros(256, dtype=np.int32)
            jaxmon.count_h2d(mine)
            jaxmon.count_h2d(pool_bytes, label="pool")
        assert prof.h2d_bytes == mine.nbytes  # pool bytes excluded
        snap = jaxmon.registry().snapshot()
        assert snap["jax.transfer.h2d_bytes.pool"]["count"] >= (
            pool_bytes.nbytes)

    def test_agg_residency_staging_is_pool_labelled(self, monkeypatch):
        """The fused path's value-column staging (a pool warm-up a query
        happens to trigger) must not inflate that query's h2d split."""
        from geomesa_tpu.obs import jaxmon

        monkeypatch.setenv("GEOMESA_TPU_PYRAMID", "0")  # force fused path
        ds = mk("tpu")
        pool0 = (jaxmon.registry()
                 .counter("jax.transfer.h2d_bytes.pool").count)
        with devmon.profiled() as prof:
            out = ds.aggregate_many("ev", [QUERIES[0]], group_by=["name"],
                                    value_cols=["val"])
        assert out[0] is not None
        pool_staged = (jaxmon.registry()
                       .counter("jax.transfer.h2d_bytes.pool").count
                       - pool0)
        assert pool_staged > 0  # the (V, N) value matrix warm-up
        # the profiled query's h2d excludes the pool warm-up bytes
        assert prof.h2d_bytes < pool_staged + 4096


class TestPyramidUnit:
    def test_boundary_rows_are_a_superset_of_edge_rows(self):
        rng = np.random.default_rng(5)
        n = 2000
        xi = rng.integers(0, 2**31, n)
        yi = rng.integers(0, 2**31, n)
        gid = rng.integers(0, 4, n)
        pyr = AggPyramid(xi, yi, np.zeros(n, np.int64), gid,
                         [(g,) for g in range(4)],
                         np.zeros((0, n)))
        box = (2**29, 2**30, 2**29, 2**30)
        cnt, first, _vc, _vs, _mn, _mx, rows = pyr.answer(box, None)
        inside = ((xi >= box[0]) & (xi <= box[1])
                  & (yi >= box[2]) & (yi <= box[3]))
        strict = ((xi > box[0]) & (xi < box[1])
                  & (yi > box[2]) & (yi < box[3]))
        # interior partials + boundary rows cover every int-domain match
        interior_total = int(cnt.sum())
        row_mask = np.zeros(n, dtype=bool)
        row_mask[rows] = True
        assert interior_total + int((inside & row_mask).sum()) >= int(
            inside.sum())
        # interior never includes a row ON the box edge
        assert interior_total <= int(strict.sum())

    def test_no_constraints_counts_everything(self):
        n = 500
        rng = np.random.default_rng(6)
        pyr = AggPyramid(
            rng.integers(0, 2**31, n), rng.integers(0, 2**31, n),
            rng.integers(0, 5, n), np.zeros(n, np.int64), [()],
            np.zeros((0, n)))
        cnt, first, *_rest, rows = pyr.answer(None, None)
        assert int(cnt.sum()) + 0 == n  # full grid interior, no window
        assert len(rows) == 0
        assert int(first[0]) == 0

    def test_byte_cap_raises(self):
        with pytest.raises(ValueError, match="byte cap"):
            AggPyramid(np.zeros(4, np.int64), np.zeros(4, np.int64),
                       np.zeros(4, np.int64), np.zeros(4, np.int64),
                       [()], np.zeros((0, 4)), byte_cap=16)


class TestQueryCacheUnit:
    def test_epoch_mismatch_misses_and_drops(self):
        qc = QueryCache()
        res = {"groups": [("a",)], "count": np.array([1]),
               "cols": {}}
        qc.put("t", "k", (1, 1), res)
        assert qc.get("t", "k", (1, 1)) is not None
        assert qc.get("t", "k", (1, 2)) is None  # stale: dropped
        assert qc.get("t", "k", (1, 1)) is None  # eager drop happened
        assert qc.snapshot()["misses"] == 2

    def test_deep_copy_isolation(self):
        qc = QueryCache()
        res = {"groups": [("a",)], "count": np.array([5]),
               "cols": {"v": {"sum": np.array([1.0])}}}
        qc.put("t", "k", 1, res)
        got = qc.get("t", "k", 1)
        got["count"][0] = 999
        got["cols"]["v"]["sum"][0] = -1.0
        clean = qc.get("t", "k", 1)
        assert clean["count"][0] == 5
        assert clean["cols"]["v"]["sum"][0] == 1.0

    def test_lru_eviction(self):
        qc = QueryCache(max_entries=2)
        r = {"groups": [], "count": np.zeros(0, np.int64), "cols": {}}
        qc.put("t", "a", 1, r)
        qc.put("t", "b", 1, r)
        qc.put("t", "c", 1, r)
        assert qc.get("t", "a", 1) is None
        assert qc.get("t", "c", 1) is not None
        assert qc.snapshot()["evictions"] == 1

    def test_choose_agg_path_consults_cost_table(self):
        from geomesa_tpu.obs.devmon import CostTable
        from geomesa_tpu.planning.planner import choose_agg_path

        ct = CostTable()
        assert choose_agg_path(ct, "t") == "pyramid"  # no data: default
        for _ in range(10):
            ct.observe("t", "gagg:pyramid", wall_ms=10.0)
            ct.observe("t", "gagg:scan", wall_ms=1.0)
        assert choose_agg_path(ct, "t") == "scan"
        ct2 = CostTable()
        for _ in range(10):
            ct2.observe("t", "gagg:pyramid", wall_ms=1.0)
            ct2.observe("t", "gagg:scan", wall_ms=10.0)
        assert choose_agg_path(ct2, "t") == "pyramid"

    def test_agg_route_probes_the_loser(self):
        from geomesa_tpu.obs.devmon import CostTable
        from geomesa_tpu.planning.planner import (AGG_PROBE_EVERY,
                                                  choose_agg_path)

        # scan wins — but the pyramid must still be probed periodically
        # so its profile stays fresh and the verdict can flip back
        ct = CostTable()
        for _ in range(10):
            ct.observe("t", "gagg:pyramid", wall_ms=10.0)
            ct.observe("t", "gagg:scan", wall_ms=1.0)
        routes = [choose_agg_path(ct, "t")
                  for _ in range(2 * AGG_PROBE_EVERY)]
        assert routes.count("pyramid") == 2
        # symmetric: a pyramid-default workload (scan has NO observations
        # and could otherwise never qualify) still measures the scan
        ct2 = CostTable()
        routes2 = [choose_agg_path(ct2, "t")
                   for _ in range(AGG_PROBE_EVERY)]
        assert routes2.count("scan") == 1
        # the schedule rides the consult counter, not observation counts:
        # consults that never observe still advance toward the next probe
        ct3 = CostTable()
        for _ in range(10):
            ct3.observe("t", "gagg:pyramid", wall_ms=10.0)
            ct3.observe("t", "gagg:scan", wall_ms=1.0)
        seen = set()
        for _ in range(2 * AGG_PROBE_EVERY):
            seen.add(choose_agg_path(ct3, "t"))
        assert seen == {"scan", "pyramid"}


class TestLambdaWarmPath:
    def test_feature_cache_version_bumps(self):
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.stream.cache import FeatureCache

        fc = FeatureCache(parse_spec("ev", SPEC))
        v0 = fc.version
        fc.put("a", {"name": "x"}, ts=1)
        assert fc.version > v0
        v1 = fc.version
        fc.delete("a")
        assert fc.version > v1
        v2 = fc.version
        fc.clear()
        assert fc.version > v2

    def test_lambda_data_epoch_advances_on_both_tiers(self):
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lds = LambdaDataStore(persist_interval_s=None)
        try:
            lds.create_schema("ev", SPEC)
            e0 = lds.data_epoch("ev")
            lds.write("ev", "f1", {
                "name": "a", "val": 1.0, "cnt": 1, "dtg": T0,
                "geom": Point(1.0, 1.0),
            })
            lds.stream.drain("ev")  # hot put applies on a consumer thread
            e1 = lds.data_epoch("ev")
            assert e1 != e0
            lds.cold.write("ev", [{
                "name": "b", "val": 2.0, "cnt": 2, "dtg": T0,
                "geom": Point(2.0, 2.0),
            }], fids=["c1"])
            assert lds.data_epoch("ev") != e1
        finally:
            lds.close()
