"""Curve-math invariants, modeled on the reference's pure-math suites
(``geomesa-z3/src/test/scala/.../curve/{Z2Test,Z3Test,XZ2SFCTest,BinnedTimeTest,
NormalizedDimensionTest}.scala`` — SURVEY.md §4): encode/invert round-trips,
range-cover correctness over random boxes, and known-value tables."""

import numpy as np
import pytest

from geomesa_tpu.curve import TimePeriod, Z2SFC, merge_ranges, z3_sfc, zranges
from geomesa_tpu.curve import xz2_sfc, xz3_sfc, zorder
from geomesa_tpu.curve.binned_time import BinnedTime
from geomesa_tpu.curve.normalize import NormalizedDimension


class TestZOrder:
    def test_known_values_2d(self):
        # interleave convention: x in even (LSB) positions
        assert int(zorder.encode2(np.uint64(1), np.uint64(0))) == 0b01
        assert int(zorder.encode2(np.uint64(0), np.uint64(1))) == 0b10
        assert int(zorder.encode2(np.uint64(3), np.uint64(0))) == 0b0101
        assert int(zorder.encode2(np.uint64(0b11), np.uint64(0b11))) == 0b1111

    def test_known_values_3d(self):
        assert int(zorder.encode3(np.uint64(1), np.uint64(0), np.uint64(0))) == 0b001
        assert int(zorder.encode3(np.uint64(0), np.uint64(1), np.uint64(0))) == 0b010
        assert int(zorder.encode3(np.uint64(0), np.uint64(0), np.uint64(1))) == 0b100
        assert int(zorder.encode3(np.uint64(7), np.uint64(7), np.uint64(7))) == 0b111111111

    def test_roundtrip_2d(self, rng):
        x = rng.integers(0, 1 << 31, size=10_000).astype(np.uint64)
        y = rng.integers(0, 1 << 31, size=10_000).astype(np.uint64)
        z = zorder.encode2(x, y)
        dx, dy = zorder.decode2(z)
        np.testing.assert_array_equal(dx, x)
        np.testing.assert_array_equal(dy, y)

    def test_roundtrip_3d(self, rng):
        x = rng.integers(0, 1 << 21, size=10_000).astype(np.uint64)
        y = rng.integers(0, 1 << 21, size=10_000).astype(np.uint64)
        t = rng.integers(0, 1 << 21, size=10_000).astype(np.uint64)
        z = zorder.encode3(x, y, t)
        dx, dy, dt = zorder.decode3(z)
        np.testing.assert_array_equal(dx, x)
        np.testing.assert_array_equal(dy, y)
        np.testing.assert_array_equal(dt, t)

    def test_monotone_in_each_dim(self):
        # fixing y, z is monotone in x (and vice versa) for same-magnitude prefixes
        x = np.arange(100, dtype=np.uint64)
        z = zorder.encode2(x, np.uint64(0))
        assert np.all(np.diff(z.astype(np.int64)) > 0)


class TestNormalize:
    def test_bounds(self):
        d = NormalizedDimension(-180.0, 180.0, 21)
        assert int(d.normalize(-180.0)) == 0
        assert int(d.normalize(180.0)) == d.max_index
        assert int(d.normalize(200.0)) == d.max_index  # clamp
        assert int(d.normalize(-200.0)) == 0

    def test_roundtrip_within_bin(self, rng):
        d = NormalizedDimension(-90.0, 90.0, 21)
        x = rng.uniform(-90, 90, size=1000)
        i = d.normalize(x)
        mid = d.denormalize(i)
        # midpoint is within half a bin of the original
        assert np.max(np.abs(mid - x)) <= (180.0 / (1 << 21))

    def test_monotone(self, rng):
        d = NormalizedDimension(-180.0, 180.0, 21)
        x = np.sort(rng.uniform(-180, 180, size=1000))
        i = d.normalize(x)
        assert np.all(np.diff(i) >= 0)


class TestBinnedTime:
    MS = np.array(
        [0, 1, 86_399_999, 86_400_000, 1_234_567_890_123, 1_700_000_000_000],
        dtype=np.int64,
    )

    @pytest.mark.parametrize("period", list(TimePeriod))
    def test_roundtrip(self, period):
        bt = BinnedTime(period)
        b, off = bt.to_bin_and_offset(self.MS)
        back = bt.from_bin_and_offset(b, off)
        unit = bt.offset_unit_millis()
        # lossy only below the offset resolution
        assert np.all(np.abs(back - self.MS) < unit)
        assert np.all(off >= 0)
        assert np.all(off < int(bt.max_offset) + 1)

    def test_day_known(self):
        bt = BinnedTime(TimePeriod.DAY)
        b, off = bt.to_bin_and_offset(np.array([86_400_000 + 123], dtype=np.int64))
        assert b[0] == 1 and off[0] == 123

    def test_month_calendar(self):
        bt = BinnedTime(TimePeriod.MONTH)
        # 1970-03-01T00:00:00Z = 59 days
        ms = np.array([59 * 86_400_000], dtype=np.int64)
        b, off = bt.to_bin_and_offset(ms)
        assert b[0] == 2 and off[0] == 0


def brute_force_cover_check(ranges, zs_in_box):
    """Every z of a point inside the box must fall in some returned range."""
    if len(zs_in_box) == 0:
        return True
    lo = ranges[:, 0]
    hi = ranges[:, 1]
    idx = np.searchsorted(lo, zs_in_box, side="right") - 1
    ok = (idx >= 0) & (zs_in_box <= hi[np.clip(idx, 0, len(hi) - 1)])
    return bool(np.all(ok))


class TestZRanges:
    def test_full_domain(self):
        r = zranges((0, 0), ((1 << 31) - 1, (1 << 31) - 1), 31)
        assert r.shape == (1, 2)
        assert int(r[0, 0]) == 0 and int(r[0, 1]) == (1 << 62) - 1

    def test_single_cell(self):
        r = zranges((5, 7), (5, 7), 31)
        z = int(zorder.encode2(np.uint64(5), np.uint64(7)))
        assert brute_force_cover_check(r, np.array([z], dtype=np.uint64))

    def test_cover_correctness_2d(self, rng):
        for _ in range(20):
            lo = rng.integers(0, 1 << 16, size=2)
            ext = rng.integers(1, 1 << 12, size=2)
            lows = (int(lo[0]), int(lo[1]))
            highs = (int(lo[0] + ext[0]), int(lo[1] + ext[1]))
            r = zranges(lows, highs, 31, max_ranges=64)
            assert len(r) <= 2 * 64  # merge may keep it under; budget is soft
            # sample points inside the box
            xs = rng.integers(lows[0], highs[0] + 1, size=200).astype(np.uint64)
            ys = rng.integers(lows[1], highs[1] + 1, size=200).astype(np.uint64)
            zs = zorder.encode2(xs, ys)
            assert brute_force_cover_check(r, np.sort(zs))

    def test_cover_correctness_3d(self, rng):
        for _ in range(10):
            lo = rng.integers(0, 1 << 12, size=3)
            ext = rng.integers(1, 1 << 8, size=3)
            lows = tuple(int(v) for v in lo)
            highs = tuple(int(a + b) for a, b in zip(lo, ext))
            r = zranges(lows, highs, 21, max_ranges=100)
            xs = rng.integers(lows[0], highs[0] + 1, size=100).astype(np.uint64)
            ys = rng.integers(lows[1], highs[1] + 1, size=100).astype(np.uint64)
            ts = rng.integers(lows[2], highs[2] + 1, size=100).astype(np.uint64)
            zs = zorder.encode3(xs, ys, ts)
            assert brute_force_cover_check(r, np.sort(zs))

    def test_ranges_sorted_disjoint(self, rng):
        r = zranges((100, 200), (5000, 9000), 31, max_ranges=500)
        assert np.all(r[:, 0] <= r[:, 1])
        assert np.all(r[1:, 0].astype(np.int64) > r[:-1, 1].astype(np.int64) + 1 - 1)

    def test_budget_respected_loosely(self):
        r = zranges((0, 0), ((1 << 20), (1 << 20) + 12345), 31, max_ranges=16)
        # hitting the budget coarsens ranges rather than dropping coverage
        assert len(r) <= 64


class TestSFC:
    def test_z2_index_invert(self, rng):
        sfc = Z2SFC()
        x = rng.uniform(-180, 180, size=1000)
        y = rng.uniform(-90, 90, size=1000)
        z = sfc.index(x, y)
        ix, iy = sfc.invert(z)
        assert np.max(np.abs(ix - x)) <= 360.0 / (1 << 31) * 1.01
        assert np.max(np.abs(iy - y)) <= 180.0 / (1 << 31) * 1.01

    def test_z2_ranges_cover(self, rng):
        sfc = Z2SFC()
        bbox = (-10.0, -10.0, 10.0, 10.0)
        r = sfc.ranges([bbox], max_ranges=200)
        x = rng.uniform(-10, 10, size=500)
        y = rng.uniform(-10, 10, size=500)
        zs = np.sort(sfc.index(x, y))
        assert brute_force_cover_check(r, zs)

    def test_z3_ranges_cover(self, rng):
        sfc = z3_sfc(TimePeriod.WEEK)
        r = sfc.ranges([(-5.0, -5.0, 5.0, 5.0)], (1000.0, 200000.0), max_ranges=500)
        x = rng.uniform(-5, 5, size=500)
        y = rng.uniform(-5, 5, size=500)
        t = rng.uniform(1000, 200000, size=500)
        zs = np.sort(sfc.index(x, y, t))
        assert brute_force_cover_check(r, zs)


class TestXZ:
    def test_index_range_of_codes(self, rng):
        sfc = xz2_sfc(12)
        n = 500
        xmin = rng.uniform(-179, 178, size=n)
        ymin = rng.uniform(-89, 88, size=n)
        xmax = xmin + rng.uniform(0, 1, size=n)
        ymax = ymin + rng.uniform(0, 1, size=n)
        codes = sfc.index((xmin, ymin), (xmax, ymax))
        assert np.all(codes < sfc.max_code)

    def test_point_boxes_get_max_depth(self):
        sfc = xz2_sfc(12)
        c1 = sfc.index((np.array([10.0]), np.array([10.0])), (np.array([10.0]), np.array([10.0])))
        assert int(c1[0]) > 0

    def test_ranges_cover_intersecting_objects(self, rng):
        sfc = xz2_sfc(12)
        window = ((-20.0, -20.0), (20.0, 20.0))
        r = sfc.ranges([window], max_ranges=500)
        # objects that intersect the window must have covered codes
        n = 300
        xmin = rng.uniform(-30, 15, size=n)
        ymin = rng.uniform(-30, 15, size=n)
        xmax = xmin + rng.uniform(0, 10, size=n)
        ymax = ymin + rng.uniform(0, 10, size=n)
        inter = (xmax >= -20) & (xmin <= 20) & (ymax >= -20) & (ymin <= 20)
        codes = sfc.index((xmin, ymin), (xmax, ymax))
        assert brute_force_cover_check(r, np.sort(codes[inter]))

    def test_xz3_ranges_cover(self, rng):
        sfc = xz3_sfc(TimePeriod.WEEK, 8)
        window = ((-20.0, -20.0, 0.0), (20.0, 20.0, 300000.0))
        r = sfc.ranges([window], max_ranges=500)
        n = 200
        xmin = rng.uniform(-25, 15, size=n)
        ymin = rng.uniform(-25, 15, size=n)
        tmin = rng.uniform(0, 250000, size=n)
        xmax = xmin + rng.uniform(0, 5, size=n)
        ymax = ymin + rng.uniform(0, 5, size=n)
        tmax = tmin + rng.uniform(0, 10000, size=n)
        codes = sfc.index((xmin, ymin, tmin), (xmax, ymax, tmax))
        inter = (xmax >= -20) & (xmin <= 20) & (ymax >= -20) & (ymin <= 20) & (tmax >= 0)
        assert brute_force_cover_check(r, np.sort(codes[inter]))


class TestMergeRanges:
    def test_merge(self):
        r = merge_ranges([(5, 10), (0, 3), (11, 20), (25, 30)])
        np.testing.assert_array_equal(
            r, np.array([[0, 3], [5, 20], [25, 30]], dtype=np.uint64)
        )
