"""Property fuzzing: CQL round-trip, TWKB codec, paging partition invariant
(reference analog: the curve/filter property suites of SURVEY.md §4, applied
to the whole filter/codec surface with generated inputs)."""

import numpy as np
import pytest

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.cql import parse as parse_cql
from geomesa_tpu.geometry import LineString, MultiLineString, Point, Polygon
from geomesa_tpu.geometry.twkb import from_twkb, from_twkb_batch, to_twkb, to_twkb_batch
from geomesa_tpu.geometry.wkt import to_wkt
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000


def _rand_filter(rng) -> str:
    """One random predicate from the supported grammar."""
    kind = rng.integers(0, 9)
    if kind == 0:
        x1, y1 = rng.uniform(-170, 150), rng.uniform(-80, 60)
        return f"BBOX(geom, {x1:.3f}, {y1:.3f}, {x1 + 10:.3f}, {y1 + 10:.3f})"
    if kind == 1:
        lo = int(rng.integers(1, 14))
        hi = lo + int(rng.integers(1, 14))
        return (
            f"dtg DURING 2017-07-{lo:02d}T00:00:00Z/"
            f"2017-07-{hi:02d}T00:00:00Z"
        )
    if kind == 2:
        return f"age {rng.choice(['<', '>', '<=', '>=', '=', '<>'])} {int(rng.integers(0, 100))}"
    if kind == 3:
        return f"name LIKE 'n{int(rng.integers(0, 9))}%'"
    if kind == 4:
        return f"name IN ('n{int(rng.integers(0, 5))}', 'n{int(rng.integers(5, 9))}')"
    if kind == 5:
        return "name IS NULL" if rng.random() < 0.5 else "name IS NOT NULL"
    if kind == 6:
        x, y = rng.uniform(-170, 160), rng.uniform(-80, 70)
        return f"DWITHIN(geom, POINT ({x:.3f} {y:.3f}), {rng.uniform(10, 500):.1f}, kilometers)"
    if kind == 7:
        return f"age BETWEEN {int(rng.integers(0, 40))} AND {int(rng.integers(41, 99))}"
    return f"strLength(name) = {int(rng.integers(1, 4))}"


def _rand_tree(rng, depth=0) -> str:
    if depth >= 2 or rng.random() < 0.4:
        return _rand_filter(rng)
    op = rng.choice([" AND ", " OR "])
    parts = [f"({_rand_tree(rng, depth + 1)})" for _ in range(int(rng.integers(2, 4)))]
    s = op.join(parts)
    return f"NOT ({s})" if rng.random() < 0.2 else s


def _table(n=400, seed=0):
    rng = np.random.default_rng(seed)
    sft = parse_spec("t", "name:String,age:Integer,dtg:Date,*geom:Point")
    recs = [
        {
            "name": None if i % 17 == 0 else f"n{i % 9}",
            "age": int(rng.integers(0, 100)),
            "dtg": int(T0 + rng.integers(0, 28 * 86_400_000)),
            "geom": Point(float(rng.uniform(-180, 180)), float(rng.uniform(-90, 90))),
        }
        for i in range(n)
    ]
    return FeatureTable.from_records(sft, recs, [str(i) for i in range(n)])


class TestCqlFuzz:
    def test_round_trip_preserves_semantics(self):
        """parse(to_cql(parse(s))) must select the same rows as parse(s)."""
        t = _table()
        rng = np.random.default_rng(42)
        for i in range(150):
            s = _rand_tree(rng)
            f1 = parse_cql(s)
            f2 = parse_cql(ast.to_cql(f1))
            m1, m2 = f1.mask(t), f2.mask(t)
            assert np.array_equal(m1, m2), f"iteration {i}: {s!r}"

    def test_planned_equals_bruteforce(self):
        """Index-planned execution == brute-force mask for random filters."""
        t = _table(1200, seed=3)
        tpu = DataStore(backend="tpu")
        tpu.create_schema(t.sft)
        tpu.write("t", t, fids=t.fids.tolist())
        rng = np.random.default_rng(7)
        for i in range(40):
            s = _rand_tree(rng)
            want = set(t.fids[parse_cql(s).mask(t)].tolist())
            got = set(tpu.query("t", s).table.fids.tolist())
            assert got == want, f"iteration {i}: {s!r}"


class TestTwkbFuzz:
    def _rand_geom(self, rng):
        kind = rng.integers(0, 4)
        if kind == 0:
            return Point(
                round(float(rng.uniform(-180, 180)), 6),
                round(float(rng.uniform(-90, 90)), 6),
            )
        if kind == 1:
            n = int(rng.integers(2, 40))
            c = np.round(
                np.cumsum(rng.normal(0, 0.05, (n, 2)), axis=0)
                + [rng.uniform(-90, 90), rng.uniform(-45, 45)], 6,
            )
            return LineString(c)
        if kind == 2:
            cx, cy = rng.uniform(-90, 90), rng.uniform(-45, 45)
            ang = np.linspace(0, 2 * np.pi, int(rng.integers(4, 12)), endpoint=False)
            r = rng.uniform(0.5, 3)
            ring = np.round(
                np.stack([cx + r * np.cos(ang), cy + r * np.sin(ang)], axis=1), 6
            )
            ring = np.vstack([ring, ring[:1]])
            return Polygon(ring)
        return MultiLineString(
            [LineString(np.round(rng.uniform(-50, 50, (3, 2)), 6)) for _ in range(2)]
        )

    def test_codec_round_trip(self):
        rng = np.random.default_rng(5)
        geoms = [self._rand_geom(rng) for _ in range(300)]
        blobs = [to_twkb(g) for g in geoms]
        # scalar decode, batch decode, and batch encode must all agree
        batch_dec = from_twkb_batch(blobs)
        packed = to_twkb_batch(geoms)
        for i, g in enumerate(geoms):
            scalar = from_twkb(blobs[i])
            assert to_wkt(batch_dec[i]) == to_wkt(scalar)
            if packed is not None:
                buf, offs = packed
                assert bytes(buf[offs[i] : offs[i + 1]]) == blobs[i]

    def test_coordinates_within_quantum(self):
        rng = np.random.default_rng(6)
        for _ in range(100):
            g = self._rand_geom(rng)
            d = from_twkb(to_twkb(g))
            assert np.allclose(np.array(g.bbox), np.array(d.bbox), atol=1e-6)


class TestPagingFuzz:
    def test_pages_partition_any_query(self):
        """start_index pages always partition the sorted full result."""
        t = _table(800, seed=9)
        ds = DataStore(backend="tpu")
        ds.create_schema(t.sft)
        ds.write("t", t, fids=t.fids.tolist())
        rng = np.random.default_rng(11)
        for i in range(15):
            s = _rand_tree(rng)
            full = ds.query("t", Query(filter=s, sort_by=("id", False)))
            size = int(rng.integers(1, 50))
            pages = []
            off = 0
            while True:
                p = ds.query(
                    "t",
                    Query(filter=s, sort_by=("id", False),
                          start_index=off, limit=size),
                )
                if p.count == 0:
                    break
                pages.extend(p.table.fids.tolist())
                off += size
            assert pages == full.table.fids.tolist(), f"iteration {i}: {s!r}"
