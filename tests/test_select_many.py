"""Batched row retrieval (``DataStore.select_many``): the whole batch's
device work in two dispatches, results identical to per-query ``query()``
(VERDICT r4 item 2 — the BatchScanner multi-range role)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000


@pytest.fixture(scope="module")
def sel_ds():
    rng = np.random.default_rng(17)
    n = 30_000
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-60, 60, n)
    t = T0 + rng.integers(0, 10 * 86_400_000, n)
    ds = DataStore(backend="tpu")
    ds.create_schema("ev", "name:String,val:Double,dtg:Date,*geom:Point")
    recs = [
        {"name": f"c{i % 7}", "val": float(i % 100), "dtg": int(t[i]),
         "geom": Point(float(lon[i]), float(lat[i]))}
        for i in range(n)
    ]
    ds.write("ev", recs, fids=[f"e{i}" for i in range(n)])
    ds.compact("ev")
    return ds


def _cqls():
    out = []
    rng = np.random.default_rng(3)
    for i in range(6):
        x1 = float(rng.uniform(-55, 30))
        y1 = float(rng.uniform(-55, 30))
        out.append(
            f"BBOX(geom, {x1}, {y1}, {x1 + 20}, {y1 + 15}) "
            f"AND dtg AFTER 2017-07-0{1 + i % 5}T00:00:00Z"
        )
    out.append("BBOX(geom, 170, 80, 179, 89)")  # empty result
    out.append(None)  # full scan (INCLUDE)
    return out


class TestSelectMany:
    def test_matches_per_query_results(self, sel_ds):
        cqls = _cqls()
        batched = sel_ds.select_many("ev", cqls)
        for c, r in zip(cqls, batched):
            want = sel_ds.query("ev", c)
            assert sorted(r.table.fids) == sorted(want.table.fids), c
            assert r.count == want.count

    def test_residual_filter_applies(self, sel_ds):
        # attribute predicate rides as residual on the gathered rows
        c = "BBOX(geom, -40, -40, 40, 40) AND val > 90"
        [r] = sel_ds.select_many("ev", [c])
        want = sel_ds.query("ev", c)
        assert sorted(r.table.fids) == sorted(want.table.fids)
        assert all(v > 90 for v in r.table.columns["val"].values)

    def test_hot_delta_rows_included(self, sel_ds):
        sel_ds.write("ev", [
            {"name": "fresh", "val": 1.0, "dtg": T0,
             "geom": Point(0.5, 0.5)}
        ], fids=["hot1"])
        try:
            c = "BBOX(geom, 0, 0, 1, 1)"
            [r] = sel_ds.select_many("ev", [c])
            want = sel_ds.query("ev", c)
            assert sorted(r.table.fids) == sorted(want.table.fids)
            assert "hot1" in set(r.table.fids)
        finally:
            sel_ds.delete_features("ev", ["hot1"])
            sel_ds.compact("ev")

    def test_query_objects_with_limit_and_projection(self, sel_ds):
        q = Query(filter="BBOX(geom, -40, -40, 40, 40)",
                  properties=["name"], limit=5)
        [r] = sel_ds.select_many("ev", [q])
        want = sel_ds.query("ev", q)
        assert len(r.table) == len(want.table) == 5
        assert list(r.table.columns) == list(want.table.columns)

    def test_oracle_backend_falls_back(self):
        ds = DataStore(backend="oracle")
        ds.create_schema("o", "name:String,*geom:Point")
        ds.write("o", [{"name": "a", "geom": Point(1.0, 1.0)}],
                 fids=["f1"])
        [r] = ds.select_many("o", ["BBOX(geom, 0, 0, 2, 2)"])
        assert list(r.table.fids) == ["f1"]

    def test_extended_geometry_store_batches(self):
        """XZ bbox-layout stores batch too (overlap-mode planned steps):
        linestring tracks, per-query-identical to query()."""
        from geomesa_tpu.geometry.types import LineString

        rng = np.random.default_rng(23)
        ds = DataStore(backend="tpu")
        ds.create_schema("trk", "name:String,*geom:LineString")
        n = 5000
        recs = []
        for i in range(n):
            x0 = float(rng.uniform(-60, 55))
            y0 = float(rng.uniform(-60, 55))
            recs.append({
                "name": f"t{i}",
                "geom": LineString([
                    [x0, y0], [x0 + 2, y0 + 1], [x0 + 4, y0]]),
            })
        ds.write("trk", recs, fids=[f"t{i}" for i in range(n)])
        ds.compact("trk")
        cqls = [
            "BBOX(geom, -30, -30, 0, 0)",
            "BBOX(geom, 10, 10, 40, 40)",
            "BBOX(geom, 100, 70, 120, 80)",  # empty
        ]
        batched = ds.select_many("trk", cqls)
        for c, r in zip(cqls, batched):
            want = ds.query("trk", c)
            assert sorted(r.table.fids) == sorted(want.table.fids), c
        assert batched[0].count > 0

    def test_remote_select_many_over_http(self, sel_ds):
        """Federation surface: the whole batch crosses the wire in ONE
        HTTP round trip, per-query Arrow tables come back identical to
        the local batch path."""
        import threading
        from wsgiref.simple_server import make_server

        from geomesa_tpu.store.remote import RemoteDataStore
        from geomesa_tpu.web.app import GeoMesaApp

        httpd = make_server("127.0.0.1", 0, GeoMesaApp(sel_ds))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            remote = RemoteDataStore(f"http://127.0.0.1:{port}")
            cqls = [c for c in _cqls()][:4]
            got = remote.select_many("ev", cqls)
            want = sel_ds.select_many("ev", cqls)
            for g, w in zip(got, want):
                assert sorted(g.table.fids) == sorted(w.table.fids)
        finally:
            httpd.shutdown()

    def test_two_dispatch_budget(self, sel_ds, monkeypatch):
        """The batched path must not dispatch per query: count the backend
        device calls while a 6-query batch runs."""
        import geomesa_tpu.parallel.query as pq

        calls = {"n": 0}
        orig_count = pq.cached_planned_count_step
        orig_gather = pq.cached_planned_gather_step

        def wrap(orig):
            def f(*a, **k):
                step = orig(*a, **k)

                def counted(*sa, **sk):
                    calls["n"] += 1
                    return step(*sa, **sk)

                return counted
            return f

        monkeypatch.setattr(pq, "cached_planned_count_step",
                            wrap(orig_count))
        monkeypatch.setattr(pq, "cached_planned_gather_step",
                            wrap(orig_gather))
        cqls = [c for c in _cqls() if c][:5]
        sel_ds.select_many("ev", cqls)
        assert calls["n"] == 2, calls
