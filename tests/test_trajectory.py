"""Trajectory plane (ISSUE 15): device corridor engine, track state +
batched per-entity aggregation, XZ interlink joins, XZ curve coverage,
process satellites, SQL/HTTP surfaces, and the audit-plane wiring.

The acceptance pins: tube-select on the device corridor path matches the
demoted host referee across a randomized grid (incl. heading and
time-buffer legs) with ZERO steady-state recompiles (jaxmon census);
interlink returns the EXACT pair set of a nested-loop f64 referee on 2D
and XZ3 time-lifted legs; XZSFC.ranges is a superset cover of index()
codes for random extended boxes.
"""

import json
from io import BytesIO

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point, Polygon
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000


def _track_store(n=400, n_tracks=16, seed=7, heading=True, name="trk"):
    ds = DataStore(backend="tpu")
    spec = "track:String,dtg:Date,*geom:Point:srid=4326"
    if heading:
        spec = "track:String,heading:Double,dtg:Date,*geom:Point:srid=4326"
    ds.create_schema(name, spec)
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        rec = {
            "track": f"t{i % n_tracks}",
            "dtg": T0 + i * 60_000,
            "geom": Point(float(rng.uniform(-12, 12)),
                          float(rng.uniform(-6, 6))),
        }
        if heading:
            rec["heading"] = (None if i % 13 == 0
                              else float(rng.uniform(0, 360)))
        recs.append(rec)
    ds.write(name, recs)
    ds.compact(name)
    return ds


def _fids(table):
    return sorted(str(f) for f in table.fids)


# ---------------------------------------------------------------------------
# Satellite 1: vectorized track_label (output order + tie rule pinned)
# ---------------------------------------------------------------------------

class TestTrackLabel:
    @staticmethod
    def _legacy(table, track_field):
        """The historical dict-loop, kept verbatim as the red/green
        reference: latest time wins, ties keep the EARLIEST row."""
        t = table.dtg_millis()
        groups = table.columns[track_field].values
        best = {}
        for i, g in enumerate(groups.astype(object)):
            j = best.get(g)
            if j is None or t[i] > t[j]:
                best[g] = i
        return np.asarray(sorted(best.values()), dtype=np.int64)

    def test_matches_legacy_loop(self):
        from geomesa_tpu.process.tracks import track_label

        ds = _track_store(300, n_tracks=11, seed=3, heading=False)
        t = ds.query("trk", Query()).table
        got = track_label(t, "track")
        want = t.take(self._legacy(t, "track"))
        assert list(got.fids) == list(want.fids)

    def test_tie_keeps_earliest_row(self):
        """Duplicate (track, time) rows: the legacy loop kept the first
        row it saw — the vectorized reduction must pin the same rule."""
        from geomesa_tpu.process.tracks import track_label
        from geomesa_tpu.schema.columnar import FeatureTable
        from geomesa_tpu.schema.sft import parse_spec

        sft = parse_spec(
            "ties", "track:String,dtg:Date,*geom:Point:srid=4326")
        recs = [
            {"track": "a", "dtg": T0 + 5, "geom": Point(0, 0)},
            {"track": "a", "dtg": T0 + 9, "geom": Point(1, 0)},  # winner
            {"track": "a", "dtg": T0 + 9, "geom": Point(2, 0)},  # later tie
            {"track": "b", "dtg": T0 + 1, "geom": Point(3, 0)},
            {"track": "b", "dtg": T0 + 1, "geom": Point(4, 0)},  # later tie
        ]
        t = FeatureTable.from_records(
            sft, recs, fids=[f"f{i}" for i in range(len(recs))])
        got = track_label(t, "track")
        assert list(got.fids) == ["f1", "f3"]
        assert list(got.fids) == list(t.take(self._legacy(t, "track")).fids)

    def test_empty_table(self):
        from geomesa_tpu.process.tracks import track_label

        ds = _track_store(5, heading=False)
        t = ds.query("trk", Query(filter="track = 'nope'")).table
        assert len(track_label(t, "track")) == 0


# ---------------------------------------------------------------------------
# Satellite 2: route_search NaN-heading mask
# ---------------------------------------------------------------------------

class TestRouteSearchHeadings:
    def test_nan_heading_rows_never_aligned(self):
        """A NaN heading must be explicitly not-aligned: rows spatially
        inside the corridor but with a null/NaN heading are excluded,
        while identical rows with an aligned heading match."""
        from geomesa_tpu.process.tracks import route_search

        ds = DataStore(backend="tpu")
        ds.create_schema(
            "rs", "heading:Double,dtg:Date,*geom:Point:srid=4326")
        # route due-east (bearing 90); all rows on the route line
        recs = [
            {"heading": 90.0, "dtg": T0, "geom": Point(0.5, 0.0)},
            {"heading": None, "dtg": T0, "geom": Point(1.0, 0.0)},
            {"heading": float("nan"), "dtg": T0, "geom": Point(1.5, 0.0)},
            {"heading": 270.0, "dtg": T0, "geom": Point(2.0, 0.0)},
        ]
        ds.write("rs", recs)
        ds.compact("rs")
        r = route_search(ds, "rs", [(0.0, 0.0), (3.0, 0.0)], 0.2,
                         heading_field="heading", heading_tolerance_deg=30)
        assert len(r) == 1
        assert float(r.columns["heading"].values[0]) == 90.0
        # bidirectional admits the reverse heading but still never NaN
        r2 = route_search(ds, "rs", [(0.0, 0.0), (3.0, 0.0)], 0.2,
                          heading_field="heading", heading_tolerance_deg=30,
                          bidirectional=True)
        assert len(r2) == 2


# ---------------------------------------------------------------------------
# Track state + batched per-entity aggregation
# ---------------------------------------------------------------------------

class TestTrackState:
    def test_csr_layout_and_invariants(self):
        from geomesa_tpu.trajectory.state import build_track_state

        ds = _track_store(300, n_tracks=9, seed=11, heading=False)
        st = build_track_state(ds, "trk", "track")
        assert st.n_entities == 9
        assert st.offsets[0] == 0 and st.offsets[-1] == st.n == 300
        assert st.validate() == []
        # per-entity rows are time-sorted and single-track
        for e in range(st.n_entities):
            lo, hi = st.offsets[e], st.offsets[e + 1]
            assert np.all(np.diff(st.t_ms[lo:hi]) >= 0)
            vals = st.table.columns["track"].values[lo:hi]
            assert len(set(vals.astype(object))) == 1

    def test_stats_parity_vs_host_referee(self):
        from geomesa_tpu.trajectory.state import (
            build_track_state, track_stats, track_stats_host)

        ds = _track_store(500, n_tracks=20, seed=5, heading=False)
        st = build_track_state(ds, "trk", "track")
        dev = track_stats(ds, "trk", "track", state=st)
        host = track_stats_host(st)
        for k in ("length_deg", "duration_s", "avg_speed_deg_s",
                  "heading_change_deg", "dwell_s"):
            np.testing.assert_allclose(dev[k], host[k], rtol=5e-3, atol=1e-3)
        for k in ("rows", "first_ms", "last_ms"):
            assert list(dev[k]) == list(host[k])
        # labels are the last row per entity
        assert list(dev["last_fid"]) == [
            str(st.table.fids[st.offsets[e + 1] - 1])
            for e in range(st.n_entities)]

    def test_dwell_counts_stationary_time(self):
        from geomesa_tpu.trajectory.state import (
            build_track_state, track_stats_host)

        ds = DataStore(backend="tpu")
        ds.create_schema("dw", "track:String,dtg:Date,*geom:Point:srid=4326")
        recs = (
            [{"track": "a", "dtg": T0 + i * 1000, "geom": Point(1.0, 1.0)}
             for i in range(5)]  # parked 4 s
            + [{"track": "a", "dtg": T0 + 5000 + i * 1000,
                "geom": Point(1.0 + 0.1 * (i + 1), 1.0)} for i in range(3)]
        )
        ds.write("dw", recs)
        st = build_track_state(ds, "dw", "track")
        host = track_stats_host(st)
        assert host["dwell_s"][0] == pytest.approx(4.0)
        assert host["duration_s"][0] == pytest.approx(7.0)

    def test_epoch_invalidation_on_write(self):
        from geomesa_tpu.trajectory import state as tstate

        ds = _track_store(100, n_tracks=4, seed=2, heading=False)
        st1 = tstate.get_track_state(ds, "trk", "track")
        assert tstate.get_track_state(ds, "trk", "track") is st1  # cached
        ds.write("trk", [{"track": "t0", "dtg": T0 + 10**9,
                          "geom": Point(0, 0)}])  # delta write bumps epoch
        st2 = tstate.get_track_state(ds, "trk", "track")
        assert st2 is not st1
        assert st2.n == st1.n + 1

    def test_device_columns_register_in_ledger(self):
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.trajectory.state import (
            LEDGER_GROUP, build_track_state)

        ds = _track_store(128, n_tracks=4, seed=9, heading=False)
        st = build_track_state(ds, "trk", "track")
        st.device_columns(pool=ds.backend.pool)
        snap = devmon.ledger().snapshot()
        groups = {g for idx in snap["resident"].get("trk", {}).values()
                  for g in idx}
        assert LEDGER_GROUP in groups
        # eviction callback drops the device slot; next use restages
        st._evict()
        assert st.nbytes == 0
        assert st.device_columns(pool=None)[0] is not None

    def test_delete_recreate_never_serves_stale_state(self):
        """Review pin: a recreated same-name type RESTARTS its (rebuild
        epoch, delta version) tuple, so the cached state's epoch can
        collide — delete_schema must purge cached track states."""
        from geomesa_tpu.trajectory import state as tstate

        ds = DataStore(backend="tpu")
        ds.create_schema("tt", "track:String,dtg:Date,*geom:Point:srid=4326")
        ds.write("tt", [{"track": "old", "dtg": T0 + i,
                         "geom": Point(0, 0)} for i in range(10)])
        st1 = tstate.get_track_state(ds, "tt", "track")
        assert list(st1.entities) == ["old"]
        ds.delete_schema("tt")
        ds.create_schema("tt", "track:String,dtg:Date,*geom:Point:srid=4326")
        ds.write("tt", [{"track": "new", "dtg": T0 + i,
                         "geom": Point(1, 1)} for i in range(10)])
        st2 = tstate.get_track_state(ds, "tt", "track")
        assert list(st2.entities) == ["new"]

    def test_label_tie_rule_matches_track_label(self):
        """Review pin: TRACK_STATS' last-position label resolves equal
        (track, max-time) ties to the LOWEST original row — the same
        rule the vectorized track_label pins — so the two label surfaces
        can never disagree on the same table."""
        from geomesa_tpu.process.tracks import track_label
        from geomesa_tpu.trajectory.state import (
            build_track_state, track_stats_host)

        ds = DataStore(backend="tpu")
        ds.create_schema("tie", "track:String,dtg:Date,*geom:Point:srid=4326")
        ds.write("tie", [
            {"track": "a", "dtg": T0 + 9, "geom": Point(1, 0)},  # winner
            {"track": "a", "dtg": T0 + 9, "geom": Point(2, 0)},  # later tie
            {"track": "a", "dtg": T0 + 5, "geom": Point(0, 0)},
        ], fids=["f0", "f1", "f2"])
        st = build_track_state(ds, "tie", "track")
        stats = track_stats_host(st)
        t = ds.query("tie", Query()).table
        assert list(stats["last_fid"]) == list(track_label(t, "track").fids)
        assert list(stats["last_fid"]) == ["f0"]

    def test_pool_keys_distinct_per_filter_and_auths(self):
        """Review pin: concurrently-live states for the same (type,
        field) but different filter/auths register under DISTINCT pool
        keys — a shared key would let the pool replace the older entry
        while its device columns stay alive unbudgeted."""
        from geomesa_tpu.trajectory.state import TrackState

        def key(filter_text="", auths=None):
            st = TrackState.__new__(TrackState)
            st.track_field = "track"
            st.filter_text = filter_text
            st.auths = None if auths is None else tuple(sorted(auths))
            return st._pool_key()

        long_a = "x = '" + "a" * 80 + "1'"
        long_b = "x = '" + "a" * 80 + "2'"
        keys = {key(), key(auths=[]), key(auths=["a"]),
                key(long_a), key(long_b)}
        assert len(keys) == 5

    def test_auths_key_cached_states_apart(self):
        """Review pin: auths are part of the cache key AND thread into
        the scan — a restricted caller must never read an unrestricted
        caller's cached rows."""
        from geomesa_tpu.trajectory import state as tstate

        ds = DataStore(backend="tpu")
        ds.create_schema(
            "vt", "track:String,vis:String,dtg:Date,"
            "*geom:Point:srid=4326;geomesa.vis.field='vis'")
        ds.write("vt", [
            {"track": "a", "vis": "", "dtg": T0, "geom": Point(0, 0)},
            {"track": "b", "vis": "secret", "dtg": T0, "geom": Point(1, 1)},
        ])
        open_st = tstate.get_track_state(ds, "vt", "track", auths=None)
        restricted = tstate.get_track_state(ds, "vt", "track", auths=[])
        assert restricted is not open_st
        assert list(restricted.entities) == ["a"]
        assert set(open_st.entities) == {"a", "b"}

    def test_sweeper_track_state_red_green(self):
        from geomesa_tpu.obs import audit
        from geomesa_tpu.trajectory.state import build_track_state

        ds = _track_store(60, n_tracks=3, seed=4, heading=False)
        st = build_track_state(ds, "trk", "track")
        aud = audit.ContinuousAuditor(rate=0.0, autostart=False)
        sweeper = audit.InvariantSweeper(auditor=aud)
        sweeper.attach_track_state(st)
        results = sweeper.sweep_once()
        track = [r for r in results if r["check"] == "track_state"]
        assert track and track[0]["violations"] == []
        assert aud.passed.get("sweep:track_state", 0) == 1
        # red: corrupt the time order inside an entity
        st.t_ms = st.t_ms.copy()
        lo, hi = int(st.offsets[0]), int(st.offsets[1])
        assert hi - lo >= 2
        st.t_ms[lo], st.t_ms[hi - 1] = st.t_ms[hi - 1], st.t_ms[lo]
        results = sweeper.sweep_once()
        track = [r for r in results if r["check"] == "track_state"]
        assert track[0]["violations"]
        assert aud.diverged.get("sweep:track_state", 0) == 1
        # red: broken CSR
        st2 = build_track_state(ds, "trk", "track")
        st2.offsets = st2.offsets.copy()
        st2.offsets[-1] += 1
        assert any("offsets[-1]" in v for v in st2.validate())


# ---------------------------------------------------------------------------
# Corridor engine: randomized-grid parity vs the demoted host paths
# ---------------------------------------------------------------------------

class TestCorridor:
    def test_tube_select_randomized_grid_parity(self):
        """Device corridor path == host tube_select across a randomized
        grid of tracks × buffers × time buffers."""
        from geomesa_tpu.process.processes import tube_select as host_tube
        from geomesa_tpu.trajectory.corridor import tube_select_device

        ds = _track_store(450, n_tracks=18, seed=21, heading=False)
        rng = np.random.default_rng(77)
        for trial in range(6):
            npts = int(rng.integers(2, 5))
            xs = np.sort(rng.uniform(-11, 11, npts))
            ys = rng.uniform(-5, 5, npts)
            ts = np.sort(rng.integers(0, 450 * 60_000, npts)) + T0
            track = [(float(x), float(y), int(t))
                     for x, y, t in zip(xs, ys, ts)]
            buf = float(rng.uniform(0.3, 3.0))
            tb = int(rng.integers(1, 120)) * 60_000
            dev = tube_select_device(ds, "trk", track, buf, tb)
            host = host_tube(ds, "trk", track, buf, tb)
            assert _fids(dev) == _fids(host), (trial, buf, tb)

    def test_route_search_heading_legs_parity(self):
        from geomesa_tpu.process.tracks import route_search as host_route
        from geomesa_tpu.trajectory.corridor import route_search_device

        ds = _track_store(400, n_tracks=10, seed=31, heading=True)
        rng = np.random.default_rng(13)
        for trial in range(4):
            npts = int(rng.integers(2, 4))
            route = [(float(x), float(y))
                     for x, y in zip(np.sort(rng.uniform(-10, 10, npts)),
                                     rng.uniform(-4, 4, npts))]
            buf = float(rng.uniform(0.5, 2.5))
            tol = float(rng.uniform(20, 90))
            bidir = bool(trial % 2)
            dev = route_search_device(
                ds, "trk", route, buf, heading_field="heading",
                heading_tolerance_deg=tol, bidirectional=bidir)
            host = host_route(
                ds, "trk", route, buf, heading_field="heading",
                heading_tolerance_deg=tol, bidirectional=bidir)
            assert _fids(dev) == _fids(host), (trial, buf, tol, bidir)

    def test_batched_many_matches_singles_and_host_route(self):
        from geomesa_tpu.trajectory.corridor import (
            CorridorSpec, tube_select_many)

        ds = _track_store(300, n_tracks=12, seed=41, heading=False)
        specs = [
            CorridorSpec.tube([(-8, -3, T0), (0, 0, T0 + 10**7),
                               (8, 3, T0 + 2 * 10**7)], 1.2, 3_600_000),
            CorridorSpec.tube([(-4, 4, T0 + 10**6),
                               (6, -4, T0 + 10**7)], 0.8, 1_800_000),
            CorridorSpec.route([(-10, 0), (10, 0)], 1.5),
        ]
        batched = tube_select_many(ds, "trk", specs)
        host = tube_select_many(ds, "trk", specs, route="host")
        dev = tube_select_many(ds, "trk", specs, route="device")
        for b, h, d in zip(batched, host, dev):
            assert _fids(b) == _fids(h) == _fids(d)

    def test_zero_steady_state_recompiles(self):
        """THE J003 pin: repeated corridor scans at steady bucket shapes
        never recompile (jaxmon census), matching the subscription-matrix
        contract."""
        from geomesa_tpu.obs import jaxmon
        from geomesa_tpu.trajectory.corridor import tube_select_device

        ds = _track_store(350, n_tracks=8, seed=51, heading=False)
        track = [(-8.0, -3.0, T0), (8.0, 3.0, T0 + 2 * 10**7)]
        tube_select_device(ds, "trk", track, 1.0, 3_600_000,
                           )  # warm: compiles the bucket's step
        before = jaxmon.jit_report()
        steps = [s for s in before["steps"] if s.startswith("corridor_")]
        assert steps, before["steps"].keys()
        for i in range(4):
            shifted = [(x + 0.1 * i, y, t) for x, y, t in track]
            tube_select_device(ds, "trk", shifted, 1.0 + 0.05 * i,
                               3_600_000)
        after = jaxmon.jit_report()
        assert (after.get("recompiles", 0) - before.get("recompiles", 0)) == 0

    def test_cost_model_routes_and_observes(self):
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.trajectory.corridor import tube_select_device

        ds = _track_store(200, n_tracks=6, seed=61, heading=False)
        track = [(-5.0, -2.0, T0), (5.0, 2.0, T0 + 10**7)]
        tube_select_device(ds, "trk", track, 1.0, 3_600_000)
        snap = devmon.costs().snapshot()
        sigs = {e["signature"] for e in snap.get("entries", [])
                if e["type"] == "trk"}
        assert any(s.startswith("traj:corridor-") for s in sigs), sigs

    def test_empty_candidates(self):
        from geomesa_tpu.trajectory.corridor import tube_select_device

        ds = _track_store(50, n_tracks=2, seed=71, heading=False)
        out = tube_select_device(
            ds, "trk", [(100.0, 80.0, T0), (101.0, 81.0, T0 + 1000)],
            0.1, 1000)
        assert len(out) == 0

    def test_mixed_batch_nan_headings_stay_in_unconstrained_corridors(self):
        """Review pin: in a batch mixing heading-constrained and plain
        corridors, rows with NaN/invalid headings must still match the
        PLAIN corridors on the device route (the unconstrained-tolerance
        sentinel is accepted explicitly — a finite stand-in silently
        dropped them, because NaN compares False)."""
        from geomesa_tpu.trajectory.corridor import (
            CorridorSpec, tube_select_many)

        ds = _track_store(250, n_tracks=8, seed=121, heading=True)
        specs = [
            CorridorSpec.route([(-10, 0), (10, 0)], 2.0,
                               heading_tolerance_deg=40),
            CorridorSpec.route([(-10, 0), (10, 0)], 2.0),  # unconstrained
        ]
        dev = tube_select_many(ds, "trk", specs, heading_field="heading",
                               route="device")
        host = tube_select_many(ds, "trk", specs, heading_field="heading",
                                route="host")
        assert _fids(dev[0]) == _fids(host[0])
        assert _fids(dev[1]) == _fids(host[1])
        # the unconstrained corridor must include NaN-heading rows the
        # constrained one excludes (the store seeds nulls every 13th row)
        t = dev[1]
        h = t.columns["heading"]
        nan_rows = (~h.is_valid()) | ~np.isfinite(
            h.values.astype(np.float64))
        assert nan_rows.any()


# ---------------------------------------------------------------------------
# Interlink: exact pair parity vs the nested-loop f64 referee
# ---------------------------------------------------------------------------

def _link_store(name, n, poly=False, seed=0, span_ms=86_400_000):
    ds = DataStore(backend="tpu")
    spec = "dtg:Date,*geom:" + ("Polygon" if poly else "Point") + ":srid=4326"
    ds.create_schema(name, spec)
    r = np.random.default_rng(seed)
    recs = []
    for _ in range(n):
        x, y = float(r.uniform(-20, 20)), float(r.uniform(-10, 10))
        if poly:
            w, h = float(r.uniform(0.1, 2)), float(r.uniform(0.1, 2))
            g = Polygon(np.array([[x, y], [x + w, y], [x + w, y + h],
                                  [x, y + h], [x, y]]))
        else:
            g = Point(x, y)
        recs.append({"dtg": T0 + int(r.integers(0, span_ms)), "geom": g})
    ds.write(name, recs)
    ds.compact(name)
    return ds


class TestInterlink:
    @pytest.fixture(scope="class")
    def stores(self):
        return (_link_store("L", 100, poly=True, seed=1),
                _link_store("R", 250, poly=False, seed=2))

    def _tables(self, stores):
        lds, rds = stores
        return (lds.query("L", Query()).table, rds.query("R", Query()).table)

    @pytest.mark.parametrize("pred,dist,tb", [
        ("intersects", 0.0, None),
        ("dwithin", 0.6, None),
        ("intersects", 0.0, 3_600_000),  # XZ3 time-lifted
        ("dwithin", 0.4, 7_200_000),  # XZ3 + distance
    ])
    def test_exact_pair_parity(self, stores, pred, dist, tb):
        from geomesa_tpu.trajectory.interlink import (
            interlink, interlink_referee)

        lds, rds = stores
        lt, rt = self._tables(stores)
        live = interlink(lds, "L", rds, "R", pred=pred, distance=dist,
                         time_buffer_ms=tb)
        ref = interlink_referee(lt, rt, pred=pred, distance=dist,
                                time_buffer_ms=tb)
        assert live == ref
        assert (len(live) > 0) or pred == "intersects"  # grids do link

    def test_block_route_parity(self, stores):
        """The blocked-device-join pairing (ops/join block kernels via
        join_rows_device) returns the same exact pair set."""
        from geomesa_tpu.trajectory.interlink import (
            interlink, interlink_referee)

        lds, rds = stores
        lt, rt = self._tables(stores)
        live = interlink(lds, "L", rds, "R", pred="intersects",
                         route="block")
        assert live == interlink_referee(lt, rt, pred="intersects")

    def test_point_point_dwithin(self):
        from geomesa_tpu.trajectory.interlink import (
            interlink, interlink_referee)

        a = _link_store("A", 120, seed=5)
        b = _link_store("B", 120, seed=6)
        at = a.query("A", Query()).table
        bt = b.query("B", Query()).table
        live = interlink(a, "A", b, "B", pred="dwithin", distance=1.0)
        assert live == interlink_referee(at, bt, "dwithin", 1.0)
        assert len(live) > 0

    def test_unsupported_predicate_raises(self, stores):
        from geomesa_tpu.trajectory.interlink import interlink

        lds, rds = stores
        with pytest.raises(ValueError, match="unsupported predicate"):
            interlink(lds, "L", rds, "R", pred="crosses")

    def test_forced_block_route_refuses_unservable_constraints(self, stores):
        """Review pin: a forced block route cannot apply rfilter/auths/
        the time lift — it must refuse rather than silently widen."""
        from geomesa_tpu.trajectory.interlink import interlink

        lds, rds = stores
        for kw in ({"rfilter": "INCLUDE"}, {"auths": []},
                   {"time_buffer_ms": 1000}):
            with pytest.raises(ValueError, match="route='block'"):
                interlink(lds, "L", rds, "R", route="block", **kw)

    def test_link_members_federated(self):
        from geomesa_tpu.store.merged import MergedDataStoreView
        from geomesa_tpu.trajectory.interlink import (
            interlink_referee, link_members)

        a = _link_store("evt", 80, seed=8)
        b = _link_store("evt", 80, seed=9)
        view = MergedDataStoreView([a, b])
        at = a.query("evt", Query()).table
        bt = b.query("evt", Query()).table
        pairs = link_members(view, 0, "evt", 1, pred="dwithin",
                             distance=0.8)
        assert pairs == interlink_referee(at, bt, "dwithin", 0.8)


# ---------------------------------------------------------------------------
# Satellite 3: XZ curve coverage (property + lenient edge cases)
# ---------------------------------------------------------------------------

class TestXZCurves:
    def test_ranges_superset_of_index_2d(self):
        """For random extended boxes and random query windows: every box
        INTERSECTING the window has its sequence code inside some range
        of the window's cover — the XZ soundness contract the interlink
        pruning and the xz index scans both lean on."""
        from geomesa_tpu.curve.xz import xz2_sfc

        sfc = xz2_sfc(12)
        rng = np.random.default_rng(17)
        n = 300
        x1 = rng.uniform(-179, 178, n)
        y1 = rng.uniform(-89, 88, n)
        w = rng.exponential(1.0, n)
        h = rng.exponential(1.0, n)
        x2 = np.minimum(x1 + w, 180.0)
        y2 = np.minimum(y1 + h, 90.0)
        codes = sfc.index((x1, y1), (x2, y2))
        for _ in range(12):
            qx1, qy1 = rng.uniform(-170, 150), rng.uniform(-80, 70)
            qx2 = qx1 + rng.uniform(0.5, 25)
            qy2 = qy1 + rng.uniform(0.5, 15)
            ranges = sfc.ranges([((qx1, qy1), (qx2, qy2))])
            hits = (x2 >= qx1) & (x1 <= qx2) & (y2 >= qy1) & (y1 <= qy2)
            for c in codes[hits]:
                assert np.any((ranges[:, 0] <= c) & (c <= ranges[:, 1])), (
                    f"code {c} of an intersecting box not covered")

    def test_ranges_superset_of_index_3d_time_lifted(self):
        from geomesa_tpu.curve.xz import XZSFC

        sfc = XZSFC(g=10, dims=3, mins=(-180.0, -90.0, 0.0),
                    maxs=(180.0, 90.0, 1000.0))
        rng = np.random.default_rng(23)
        n = 200
        x1 = rng.uniform(-170, 160, n)
        y1 = rng.uniform(-85, 80, n)
        t = rng.uniform(0, 1000, n)
        x2 = np.minimum(x1 + rng.exponential(0.8, n), 180.0)
        y2 = np.minimum(y1 + rng.exponential(0.8, n), 90.0)
        codes = sfc.index((x1, y1, t), (x2, y2, t))
        for _ in range(8):
            qx1, qy1 = rng.uniform(-160, 120), rng.uniform(-75, 55)
            qt1 = rng.uniform(0, 900)
            win = ((qx1, qy1, qt1),
                   (qx1 + rng.uniform(1, 30), qy1 + rng.uniform(1, 20),
                    qt1 + rng.uniform(10, 100)))
            ranges = sfc.ranges([win])
            (wlo, whi) = win
            hits = ((x2 >= wlo[0]) & (x1 <= whi[0])
                    & (y2 >= wlo[1]) & (y1 <= whi[1])
                    & (t >= wlo[2]) & (t <= whi[2]))
            for c in codes[hits]:
                assert np.any((ranges[:, 0] <= c) & (c <= ranges[:, 1]))

    def test_lenient_normalization_clamps(self):
        """Out-of-domain boxes clamp per dim (the lenient contract): a
        box hanging past the antimeridian/domain edge indexes like its
        clamped self, and degenerate (point) boxes get full depth."""
        from geomesa_tpu.curve.xz import xz2_sfc

        sfc = xz2_sfc(12)
        over = sfc.index(([-200.0], [-95.0]), ([200.0], [95.0]))
        clamped = sfc.index(([-180.0], [-90.0]), ([180.0], [90.0]))
        assert over[0] == clamped[0]
        # a point box never exceeds max_code and sits at full depth
        pt = sfc.index(([10.0], [10.0]), ([10.0], [10.0]))
        assert 0 <= int(pt[0]) < sfc.max_code
        edge = sfc.index(([180.0], [90.0]), ([180.0], [90.0]))
        assert 0 <= int(edge[0]) < sfc.max_code
        # lenient windows clamp the same way: full-domain cover contains
        # every index code
        ranges = sfc.ranges([((-999.0, -999.0), (999.0, 999.0))])
        for c in (over[0], pt[0], edge[0]):
            assert np.any((ranges[:, 0] <= int(c)) & (int(c) <= ranges[:, 1]))

    def test_point_and_extended_codes_stay_in_domain(self):
        from geomesa_tpu.curve.xz import xz2_sfc

        sfc = xz2_sfc(12)
        rng = np.random.default_rng(29)
        x1 = rng.uniform(-180, 179, 500)
        y1 = rng.uniform(-90, 89, 500)
        x2 = np.minimum(x1 + rng.exponential(2.0, 500), 180.0)
        y2 = np.minimum(y1 + rng.exponential(2.0, 500), 90.0)
        codes = sfc.index((x1, y1), (x2, y2))
        assert np.all(codes < sfc.max_code)


# ---------------------------------------------------------------------------
# SQL + HTTP surfaces
# ---------------------------------------------------------------------------

class TestSqlSurface:
    @pytest.fixture(scope="class")
    def ds(self):
        ds = _track_store(200, n_tracks=5, seed=81, heading=False)
        z = np.random.default_rng(82)
        ds.create_schema("zones", "dtg:Date,*geom:Point:srid=4326")
        ds.write("zones", [
            {"dtg": T0 + int(z.integers(0, 10**7)),
             "geom": Point(float(z.uniform(-10, 10)),
                           float(z.uniform(-5, 5)))}
            for _ in range(40)])
        ds.compact("zones")
        return ds

    def test_tube_select_fn(self, ds):
        from geomesa_tpu.sql.engine import sql
        from geomesa_tpu.trajectory.corridor import tube_select_device

        stmt = (f"SELECT * FROM TUBE_SELECT('trk', "
                f"'-8 -3 {T0}, 8 3 {T0 + 2 * 10**7}', 1.5, 3600000)")
        r = sql(ds, stmt)
        want = tube_select_device(
            ds, "trk", [(-8, -3, T0), (8, 3, T0 + 2 * 10**7)],
            1.5, 3_600_000)
        assert sorted(r.columns["__fid__"]) == _fids(want)

    def test_track_stats_fn(self, ds):
        from geomesa_tpu.sql.engine import sql

        r = sql(ds, "SELECT * FROM TRACK_STATS('trk', 'track')")
        assert len(r) == 5
        assert "length_deg" in r.columns and "avg_speed_deg_s" in r.columns
        r2 = sql(ds, "SELECT * FROM TRACK_STATS('trk', 'track') LIMIT 2")
        assert len(r2) == 2

    def test_st_link_fn(self, ds):
        from geomesa_tpu.sql.engine import sql
        from geomesa_tpu.trajectory.interlink import interlink

        r = sql(ds, "SELECT * FROM ST_LINK('trk', 'zones', 'dwithin', 0.5)")
        want = interlink(ds, "trk", ds, "zones", pred="dwithin",
                         distance=0.5)
        assert list(zip(r.columns["left_fid"], r.columns["right_fid"])) \
            == want

    def test_bad_args_raise(self, ds):
        from geomesa_tpu.sql.engine import SqlError, sql

        with pytest.raises(SqlError):
            sql(ds, "SELECT * FROM TUBE_SELECT('trk')")
        with pytest.raises(SqlError):
            sql(ds, "SELECT * FROM TUBE_SELECT('trk', 'x y', 1.0, 10)")

    def test_plain_sql_still_parses(self, ds):
        from geomesa_tpu.sql.engine import sql

        r = sql(ds, "SELECT track, COUNT(*) AS n FROM trk GROUP BY track")
        assert len(r) == 5


class TestWebSurface:
    @pytest.fixture(scope="class")
    def app(self):
        from geomesa_tpu.web.app import GeoMesaApp

        ds = _track_store(150, n_tracks=4, seed=91, heading=False)
        z = np.random.default_rng(92)
        ds.create_schema("zones", "dtg:Date,*geom:Point:srid=4326")
        ds.write("zones", [
            {"dtg": T0 + int(z.integers(0, 10**7)),
             "geom": Point(float(z.uniform(-10, 10)),
                           float(z.uniform(-5, 5)))}
            for _ in range(30)])
        ds.compact("zones")
        return GeoMesaApp(ds, coalesce_ms=0)

    def _post(self, app, path, body):
        raw = json.dumps(body).encode()
        env = {"REQUEST_METHOD": "POST", "PATH_INFO": path,
               "QUERY_STRING": "", "CONTENT_LENGTH": str(len(raw)),
               "wsgi.input": BytesIO(raw)}
        out = {}

        def sr(status, headers):
            out["status"] = int(status.split()[0])

        payload = b"".join(app(env, sr))
        return out["status"], payload

    def test_tube_select_endpoint(self, app):
        s, b = self._post(app, "/api/schemas/trk/tube-select", {
            "track": [[-8, -3, T0], [8, 3, T0 + 2 * 10**7]],
            "buffer_deg": 1.5, "time_buffer_ms": 3_600_000})
        assert s == 200
        doc = json.loads(b)
        assert doc["type"] == "FeatureCollection"

    def test_track_stats_endpoint(self, app):
        s, b = self._post(app, "/api/schemas/trk/track-stats",
                          {"track_field": "track"})
        assert s == 200
        doc = json.loads(b)
        assert doc["entities"] == 4
        assert len(doc["columns"]["length_deg"]) == 4

    def test_link_endpoint(self, app):
        s, b = self._post(app, "/api/link", {
            "left": "trk", "right": "zones", "pred": "dwithin",
            "distance": 0.5})
        assert s == 200
        doc = json.loads(b)
        assert doc["count"] == len(doc["pairs"])

    def test_bad_bodies_400(self, app):
        assert self._post(app, "/api/schemas/trk/tube-select", {})[0] == 400
        assert self._post(app, "/api/schemas/trk/track-stats", {})[0] == 400
        assert self._post(app, "/api/link", {"left": "trk"})[0] == 400

    def test_admission_covers_trajectory_routes(self):
        from geomesa_tpu.web.app import _ADMISSION_ROUTES

        assert {"_tube_select", "_track_stats", "_link"} \
            <= _ADMISSION_ROUTES


# ---------------------------------------------------------------------------
# Audit-plane wiring (satellite 6)
# ---------------------------------------------------------------------------

class TestAuditWiring:
    @pytest.fixture()
    def auditor(self):
        from geomesa_tpu.obs import audit

        aud = audit.ContinuousAuditor(rate=1.0, autostart=False)
        prev = audit.install(aud)
        yield aud
        audit.install(prev)
        audit.set_rate(0.0)

    def test_corridor_shadow_check_passes(self, auditor):
        from geomesa_tpu.trajectory.corridor import tube_select_device

        ds = _track_store(150, n_tracks=5, seed=101, heading=False)
        tube_select_device(
            ds, "trk", [(-6.0, -2.0, T0), (6.0, 2.0, T0 + 10**7)],
            1.0, 3_600_000)
        assert auditor.checked.get("corridor", 0) >= 1
        assert auditor.diverged.get("corridor", 0) == 0
        assert auditor.passed.get("corridor", 0) >= 1

    def test_interlink_shadow_check_passes(self, auditor):
        from geomesa_tpu.trajectory.interlink import interlink

        a = _link_store("A", 60, seed=15)
        b = _link_store("B", 60, seed=16)
        interlink(a, "A", b, "B", pred="dwithin", distance=0.8,
                  route="xz")
        assert auditor.checked.get("interlink", 0) >= 1
        assert auditor.diverged.get("interlink", 0) == 0

    def test_note_check_divergence_raises_anomaly(self, auditor):
        from geomesa_tpu.obs import flight

        prev = flight.install(flight.FlightRecorder(dump_dir=None))
        try:
            auditor.note_check("corridor", False, type_name="trk",
                               detail="live=1 referee=2 rows")
            assert auditor.diverged.get("corridor", 0) == 1
            assert len(auditor.divergences) == 1
            recs = flight.get().snapshot(limit=8)["records"]
            assert any(flight.A_DIVERGE in (r.get("anomalies") or ())
                       or "diverge" in str(r.get("anomalies", "")).lower()
                       for r in recs)
        finally:
            flight.install(prev)

    def test_prometheus_exposes_new_kinds(self, auditor):
        auditor.note_check("corridor", True)
        auditor.note_check("interlink", True, abstain=True)
        text = auditor.prometheus_text()
        assert 'geomesa_audit_passed_total{kind="corridor"} 1' in text
        assert 'geomesa_audit_abstained_total{kind="interlink"} 1' in text

    def test_shadow_traffic_trains_nothing(self, auditor):
        """The corridor audit's referee runs inside audit.shadow(): the
        traj:* cost profiles must see exactly ONE live observation, and
        the shadow tube_select query must not add a second."""
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.trajectory.corridor import tube_select_device

        ds = _track_store(100, n_tracks=4, seed=111, heading=False)
        tube_select_device(
            ds, "trk", [(-5.0, -2.0, T0), (5.0, 2.0, T0 + 10**7)],
            1.0, 3_600_000)
        snap = devmon.costs().snapshot()
        traj = [e for e in snap.get("entries", [])
                if e["type"] == "trk"
                and e["signature"].startswith("traj:")]
        assert sum(e.get("count", 0) for e in traj) == 1
