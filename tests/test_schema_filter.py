"""Schema DSL, columnar table, geometry, CQL parse and bounds-extraction tests
(modeled on the reference's filter/feature suites — SURVEY.md §4)."""

import numpy as np
import pytest

from geomesa_tpu.filter import ast, extract, parse
from geomesa_tpu.filter.cql import CQLError, datetime_to_millis
from geomesa_tpu.geometry import LineString, Point, Polygon, box, from_wkt, to_wkt
from geomesa_tpu.geometry import predicates as P
from geomesa_tpu.schema.columnar import FeatureTable, point_column
from geomesa_tpu.schema.sft import AttributeType, parse_spec

SPEC = "name:String:index=true,age:Integer,dtg:Date,*geom:Point:srid=4326"


def make_table(n=100, seed=0):
    rng = np.random.default_rng(seed)
    sft = parse_spec("test", SPEC)
    recs = [
        {
            "name": f"name{i % 10}",
            "age": int(i % 50),
            "dtg": int(1_500_000_000_000 + i * 3_600_000),
            "geom": Point(float(rng.uniform(-180, 180)), float(rng.uniform(-90, 90))),
        }
        for i in range(n)
    ]
    return FeatureTable.from_records(sft, recs, [f"fid{i}" for i in range(n)])


class TestSFT:
    def test_parse_spec(self):
        sft = parse_spec("gdelt", SPEC + ";geomesa.z3.interval='day',geomesa.z.splits='8'")
        assert [a.name for a in sft.attributes] == ["name", "age", "dtg", "geom"]
        assert sft.default_geom == "geom"
        assert sft.dtg_field == "dtg"
        assert sft.attr("name").indexed
        assert sft.z3_interval.value == "day"
        assert sft.shards == 8
        assert sft.geom_is_points

    def test_spec_roundtrip(self):
        sft = parse_spec("t", SPEC)
        sft2 = parse_spec("t", sft.to_spec())
        assert [a.name for a in sft2.attributes] == [a.name for a in sft.attributes]
        assert sft2.default_geom == sft.default_geom

    def test_bad_specs(self):
        with pytest.raises(ValueError):
            parse_spec("t", "name:Bogus")
        with pytest.raises(ValueError):
            parse_spec("t", "*name:String,geom:Point")
        with pytest.raises(ValueError):
            parse_spec("t", "a:String,a:Integer")


class TestColumnar:
    def test_from_records_roundtrip(self):
        t = make_table(10)
        assert len(t) == 10
        rec = t.record(3)
        assert rec["name"] == "name3"
        assert isinstance(rec["geom"], Point)

    def test_nulls(self):
        sft = parse_spec("t", "a:Integer,*geom:Point")
        t = FeatureTable.from_records(
            sft, [{"a": 1, "geom": Point(0, 0)}, {"a": None, "geom": None}]
        )
        assert t.record(1)["a"] is None
        assert t.record(1)["geom"] is None
        assert t.columns["a"].is_valid().tolist() == [True, False]

    def test_take_concat(self):
        t = make_table(20)
        a = t.take(np.arange(5))
        b = t.take(np.arange(5, 20))
        c = FeatureTable.concat([a, b])
        assert len(c) == 20
        assert c.fids[7] == t.fids[7]

    def test_point_column_fast_path(self):
        sft = parse_spec("t", "*geom:Point")
        col = point_column(np.array([1.0, 2.0]), np.array([3.0, 4.0]))
        t = FeatureTable.from_columns(sft, ["a", "b"], {"geom": col})
        assert t.record(1)["geom"] == Point(2.0, 4.0)


class TestGeometry:
    def test_wkt_roundtrip(self):
        for wkt in [
            "POINT (30 10)",
            "LINESTRING (30 10, 10 30, 40 40)",
            "POLYGON ((30 10, 40 40, 20 40, 10 20, 30 10))",
            "POLYGON ((35 10, 45 45, 15 40, 10 20, 35 10), (20 30, 35 35, 30 20, 20 30))",
            "MULTIPOINT ((10 40), (40 30))",
            "MULTILINESTRING ((10 10, 20 20), (40 40, 30 30))",
            "MULTIPOLYGON (((30 20, 45 40, 10 40, 30 20)), ((15 5, 40 10, 10 20, 15 5)))",
        ]:
            g = from_wkt(wkt)
            g2 = from_wkt(to_wkt(g))
            assert to_wkt(g) == to_wkt(g2)

    def test_point_in_polygon(self):
        poly = from_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")
        xs = np.array([5.0, 15.0, 0.0, 10.0, -1.0])
        ys = np.array([5.0, 5.0, 5.0, 10.0, -1.0])
        cls = P.classify_points_polygon(xs, ys, poly)
        assert cls.tolist() == [P.INTERIOR, P.EXTERIOR, P.BOUNDARY, P.BOUNDARY, P.EXTERIOR]

    def test_polygon_with_hole(self):
        poly = from_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (4 4, 6 4, 6 6, 4 6, 4 4))")
        assert P.points_within_geom(np.array([5.0]), np.array([5.0]), poly)[0] == False  # noqa: E712
        assert P.points_within_geom(np.array([2.0]), np.array([2.0]), poly)[0] == True  # noqa: E712

    def test_intersects_line_polygon(self):
        poly = from_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")
        crossing = from_wkt("LINESTRING (-5 5, 15 5)")
        outside = from_wkt("LINESTRING (20 20, 30 30)")
        inside = from_wkt("LINESTRING (2 2, 8 8)")
        assert P.intersects(crossing, poly)
        assert not P.intersects(outside, poly)
        assert P.intersects(inside, poly)  # fully inside still intersects

    def test_distance(self):
        a = Point(0, 0)
        b = Point(3, 4)
        assert P.distance(a, b) == pytest.approx(5.0)
        line = from_wkt("LINESTRING (0 10, 10 10)")
        assert P.distance(Point(5, 0), line) == pytest.approx(10.0)

    def test_dwithin(self):
        assert P.dwithin(Point(0, 0), Point(0, 3), 3.0)
        assert not P.dwithin(Point(0, 0), Point(0, 3.1), 3.0)


class TestCQL:
    def test_bbox_and_during(self):
        f = parse(
            "BBOX(geom, -10, -10, 10, 10) AND dtg DURING 2017-07-14T00:00:00.000Z/2017-07-15T00:00:00.000Z"
        )
        assert isinstance(f, ast.And)
        t = make_table(200)
        m = f.mask(t)
        col = t.geom_column()
        expected = (
            (col.x >= -10) & (col.x <= 10) & (col.y >= -10) & (col.y <= 10)
            & (t.dtg_millis() > datetime_to_millis("2017-07-14T00:00:00"))
            & (t.dtg_millis() < datetime_to_millis("2017-07-15T00:00:00"))
        )
        np.testing.assert_array_equal(m, expected)

    def test_intersects(self):
        f = parse("INTERSECTS(geom, POLYGON ((0 0, 20 0, 20 20, 0 20, 0 0)))")
        t = make_table(100)
        m = f.mask(t)
        col = t.geom_column()
        exp = (col.x >= 0) & (col.x <= 20) & (col.y >= 0) & (col.y <= 20)
        np.testing.assert_array_equal(m, exp)

    def test_attribute_ops(self):
        t = make_table(100)
        assert parse("name = 'name3'").mask(t).sum() == 10
        assert parse("age < 10").mask(t).sum() == 20
        assert parse("age BETWEEN 0 AND 9").mask(t).sum() == 20
        assert parse("name IN ('name1', 'name2')").mask(t).sum() == 20
        assert parse("name LIKE 'name%'").mask(t).sum() == 100
        assert parse("NOT name = 'name3'").mask(t).sum() == 90
        assert parse("INCLUDE").mask(t).all()
        assert not parse("EXCLUDE").mask(t).any()

    def test_fid_filter(self):
        t = make_table(10)
        m = parse("IN ('fid1', 'fid5')").mask(t)
        assert m.sum() == 2 and m[1] and m[5]

    def test_parse_errors(self):
        for bad in ["BBOX(geom, 1, 2)", "name ~ 'x'", "dtg DURING x/y", "(a = 1"]:
            with pytest.raises(CQLError):
                parse(bad)

    def test_dwithin_units(self):
        f = parse("DWITHIN(geom, POINT (0 0), 111320, meters)")
        assert isinstance(f, ast.SpatialOp)
        assert f.distance == pytest.approx(1.0)


class TestExtraction:
    def test_bbox_and_during(self):
        f = parse(
            "BBOX(geom, -10, -10, 10, 10) AND dtg DURING 2017-07-14T00:00:00.000Z/2017-07-15T00:00:00.000Z"
        )
        e = extract(f, "geom", "dtg")
        assert e.boxes == [(-10.0, -10.0, 10.0, 10.0)]
        lo = datetime_to_millis("2017-07-14T00:00:00") + 1
        hi = datetime_to_millis("2017-07-15T00:00:00") - 1
        assert e.intervals == [(lo, hi)]

    def test_or_union(self):
        f = parse("BBOX(geom, 0, 0, 5, 5) OR BBOX(geom, 20, 20, 25, 25)")
        e = extract(f, "geom", "dtg")
        assert len(e.boxes) == 2

    def test_mixed_or_unconstrained(self):
        f = parse("BBOX(geom, 0, 0, 5, 5) OR name = 'x'")
        e = extract(f, "geom", "dtg")
        assert e.boxes is None

    def test_and_intersection(self):
        f = parse("BBOX(geom, 0, 0, 10, 10) AND BBOX(geom, 5, 5, 20, 20)")
        e = extract(f, "geom", "dtg")
        assert e.boxes == [(5.0, 5.0, 10.0, 10.0)]

    def test_not_unconstrained(self):
        e = extract(parse("NOT BBOX(geom, 0, 0, 5, 5)"), "geom", "dtg")
        assert e.boxes is None and e.intervals is None

    def test_disjoint_proof(self):
        f = parse("BBOX(geom, 0, 0, 5, 5) AND BBOX(geom, 10, 10, 20, 20)")
        e = extract(f, "geom", "dtg")
        assert e.disjoint

    def test_temporal_ops(self):
        e = extract(parse("dtg BEFORE 2017-01-01T00:00:00Z"), "geom", "dtg")
        assert e.intervals[0][1] == datetime_to_millis("2017-01-01T00:00:00") - 1
        e = extract(parse("dtg AFTER 2017-01-01T00:00:00Z"), "geom", "dtg")
        assert e.intervals[0][0] == datetime_to_millis("2017-01-01T00:00:00") + 1

    def test_dwithin_expansion(self):
        f = parse("DWITHIN(geom, POINT (0 0), 1, degrees)")
        e = extract(f, "geom", "dtg")
        assert e.boxes == [(-1.0, -1.0, 1.0, 1.0)]


class TestFilterSplitterUnion:
    """Multi-plan alternatives (FilterSplitter.scala:25): cross-attribute ORs
    run as a union of tight index scans, not one full scan."""

    def _store(self, backend="tpu"):
        import numpy as np

        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(31)
        n = 4000
        spec = ("name:String:index=true,code:Integer:index=true,dtg:Date,"
                "*geom:Point")
        ds = DataStore(backend=backend)
        ds.create_schema("u", spec)
        recs = [
            {"name": f"n{i % 50}", "code": int(i % 37),
             "dtg": 1_500_000_000_000 + i * 1000,
             "geom": Point(float(rng.uniform(-170, 170)), float(rng.uniform(-80, 80)))}
            for i in range(n)
        ]
        ds.write("u", recs, fids=[str(i) for i in range(n)])
        return ds

    def test_cross_attribute_or_uses_union(self):
        ds = self._store()
        cql = "name = 'n7' OR code = 11"
        plan = ds.explain("u", cql)
        assert "union(" in plan
        # parity vs oracle
        oracle = self._store(backend="oracle")
        a = set(oracle.query("u", cql).table.fids.tolist())
        b = set(ds.query("u", cql).table.fids.tolist())
        assert a == b and len(a) > 0
        # overlap dedupe: arms overlap on rows with both properties
        both = [f for f in a if int(f) % 50 == 7 and int(f) % 37 == 11]
        assert len(b) == len(a)  # no duplicates from overlapping arms

    def test_or_with_spatial_arm(self):
        ds = self._store()
        cql = "BBOX(geom, -10, -10, 10, 10) OR name = 'n3'"
        plan = ds.explain("u", cql)
        oracle = self._store(backend="oracle")
        a = set(oracle.query("u", cql).table.fids.tolist())
        b = set(ds.query("u", cql).table.fids.tolist())
        assert a == b and len(a) > 0

    def test_unbounded_arm_falls_back(self):
        ds = self._store()
        # second arm is unbounded (no index on score-like predicate) → single plan
        cql = "name = 'n2' OR dtg AFTER 2010-01-01T00:00:00Z"
        plan = ds.explain("u", cql)
        oracle = self._store(backend="oracle")
        a = set(oracle.query("u", cql).table.fids.tolist())
        b = set(ds.query("u", cql).table.fids.tolist())
        assert a == b
