"""Generic ST_Buffer + GeoTIFF blob handler (VERDICT r3 item 9).

Buffer parity referee: dense random probes — every point clearly inside
the true distance field must fall in the buffer, every point clearly
outside must not (the discretized caps allow a small boundary band).
GeoTIFF: tag-level georeferencing extraction (4326 and UTM), blobstore
footprint discovery, raster-store chip loading.
"""

import io

import numpy as np
import pytest

from geomesa_tpu.geometry import ops as O
from geomesa_tpu.geometry import predicates as P
from geomesa_tpu.geometry.types import (
    LineString,
    MultiLineString,
    Point,
    Polygon,
)

BAND = 0.03  # relative boundary band for the discretized arcs


def _parity(geom, r, probes_x, probes_y):
    buf = O.buffer_geometry(geom, r, quad_segs=24)
    inside = P.points_within_geom(probes_x, probes_y, buf)
    d = np.array([
        P.distance(Point(float(x), float(y)), geom)
        for x, y in zip(probes_x, probes_y)
    ])
    must_in = d < r * (1 - BAND)
    must_out = d > r * (1 + BAND)
    assert not (must_in & ~inside).any(), \
        f"{int((must_in & ~inside).sum())} clear-inside probes excluded"
    assert not (must_out & inside).any(), \
        f"{int((must_out & inside).sum())} clear-outside probes included"


class TestBufferGeometry:
    def test_point_buffer_is_disk(self):
        rng = np.random.default_rng(1)
        x, y = rng.uniform(-3, 3, 4000), rng.uniform(-3, 3, 4000)
        _parity(Point(0.5, -0.5), 1.2, x, y)

    def test_linestring_buffer(self):
        rng = np.random.default_rng(2)
        line = LineString([[0, 0], [2, 1], [3, -1], [5, 0]])
        x, y = rng.uniform(-1, 6, 6000), rng.uniform(-3, 3, 6000)
        _parity(line, 0.6, x, y)

    def test_polygon_with_hole_buffer(self):
        rng = np.random.default_rng(3)
        poly = Polygon(
            [[0, 0], [6, 0], [6, 6], [0, 6]],
            ([[2, 2], [4, 2], [4, 4], [2, 4]],),
        )
        x, y = rng.uniform(-2, 8, 8000), rng.uniform(-2, 8, 8000)
        _parity(poly, 0.7, x, y)
        # the hole's center is farther than r from any boundary: NOT buffered
        buf = O.buffer_geometry(poly, 0.7)
        assert not P.points_within_geom(
            np.array([3.0]), np.array([3.0]), buf
        )[0]

    def test_multilinestring_buffer(self):
        rng = np.random.default_rng(4)
        ml = MultiLineString((
            LineString([[0, 0], [1, 2]]), LineString([[4, 0], [5, 2]]),
        ))
        x, y = rng.uniform(-2, 7, 5000), rng.uniform(-2, 4, 5000)
        _parity(ml, 0.5, x, y)

    def test_zero_and_negative(self):
        line = LineString([[0, 0], [1, 1]])
        assert O.buffer_geometry(line, 0.0) is line
        with pytest.raises(ValueError, match="negative"):
            O.buffer_geometry(line, -1.0)

    def test_st_buffer_function_and_dwithin_consistency(self):
        """ST_Buffer through the function registry; containment in the
        buffer agrees with the DWITHIN predicate (the acceleration
        contract)."""
        from geomesa_tpu.spatial.st_functions import ST

        line = LineString([[10, 10], [12, 11]])
        geoms = np.array([line], dtype=object)
        out = ST["st_buffer"](geoms, 0.4)
        buf = out[0]
        rng = np.random.default_rng(5)
        x, y = rng.uniform(9, 13, 3000), rng.uniform(9, 12, 3000)
        inside = P.points_within_geom(x, y, buf)
        d = np.array([
            P.distance(Point(float(a), float(b)), line)
            for a, b in zip(x, y)
        ])
        clear = np.abs(d - 0.4) > 0.4 * BAND
        np.testing.assert_array_equal(inside[clear], (d < 0.4)[clear])


def _make_geotiff(width=8, height=8, scale=(0.5, 0.25), origin=(10.0, 50.0),
                  epsg=4326) -> bytes:
    from PIL import Image
    from PIL.TiffImagePlugin import ImageFileDirectory_v2

    img = Image.fromarray(
        (np.arange(width * height).reshape(height, width) % 255
         ).astype(np.uint8)
    )
    ifd = ImageFileDirectory_v2()
    ifd[33550] = (float(scale[0]), float(scale[1]), 0.0)
    ifd.tagtype[33550] = 12  # DOUBLE
    ifd[33922] = (0.0, 0.0, 0.0, float(origin[0]), float(origin[1]), 0.0)
    ifd.tagtype[33922] = 12
    key = 3072 if epsg and epsg != 4326 else 2048
    ifd[34735] = (1, 1, 0, 1, key, 0, 1, epsg)
    ifd.tagtype[34735] = 3  # SHORT
    buf = io.BytesIO()
    img.save(buf, format="TIFF", tiffinfo=ifd)
    return buf.getvalue()


class TestGeoTiff:
    def test_bounds_4326(self):
        from geomesa_tpu.blob.geotiff import geotiff_bounds

        data = _make_geotiff()
        (xmin, ymin, xmax, ymax), crs = geotiff_bounds(data)
        assert crs == "EPSG:4326"
        assert (xmin, ymax) == (10.0, 50.0)
        assert xmax == pytest.approx(10.0 + 8 * 0.5)
        assert ymin == pytest.approx(50.0 - 8 * 0.25)

    def test_bounds_utm_reprojected(self):
        from geomesa_tpu.blob.geotiff import geotiff_bounds
        from geomesa_tpu.utils.crs import transform_coords

        # a 1 km x 1 km raster near the zone-33 central meridian
        data = _make_geotiff(
            width=10, height=10, scale=(100.0, 100.0),
            origin=(500_000.0, 5_300_000.0), epsg=32633,
        )
        (xmin, ymin, xmax, ymax), crs = geotiff_bounds(data)
        assert crs == "EPSG:32633"
        lon, lat = transform_coords(
            [500_000.0, 501_000.0], [5_299_000.0, 5_300_000.0],
            "EPSG:32633", "EPSG:4326",
        )
        assert xmin == pytest.approx(min(lon), abs=1e-6)
        assert ymax == pytest.approx(max(lat), abs=1e-6)

    def test_put_geotiff_blob_and_raster(self):
        from geomesa_tpu.blob.geotiff import put_geotiff
        from geomesa_tpu.blob.store import BlobStore
        from geomesa_tpu.raster.store import RasterStore

        bs = BlobStore()
        rs = RasterStore()
        blob_id = put_geotiff(
            bs, _make_geotiff(), filename="scene.tif",
            dtg_ms=1_600_000_000_000, raster_store=rs,
        )
        # discoverable through the normal spatial query language
        hits = bs.query_ids("BBOX(geom, 11, 48.5, 12, 49.5)")
        assert blob_id in {i for i, _name in hits}
        payload, meta = bs.get(blob_id)
        assert meta["filename"] == "scene.tif"
        assert rs.count() == 1
        chips = rs.chips_for((10.0, 48.0, 14.0, 50.0))
        assert chips and chips[0][0].shape == (8, 8)

    def test_truncated_tiff_raises_value_error(self):
        from geomesa_tpu.blob.geotiff import geotiff_bounds

        data = _make_geotiff()
        for cut in (6, 9, 40, len(data) // 2):
            with pytest.raises(ValueError):
                geotiff_bounds(data[:cut])

    def test_non_georeferenced_tiff_raises(self):
        from PIL import Image

        from geomesa_tpu.blob.geotiff import geotiff_bounds

        buf = io.BytesIO()
        Image.new("L", (4, 4)).save(buf, format="TIFF")
        with pytest.raises(ValueError, match="georeferencing"):
            geotiff_bounds(buf.getvalue())
