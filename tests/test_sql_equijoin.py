"""Attribute equi-join tests: ``JOIN ... ON a.attr = b.attr`` parity vs a
pandas referee, WHERE routing, GROUP BY/HAVING composition, NULL-key
semantics (reference role: relational joins through Spark Catalyst —
``geomesa-spark-sql/.../GeoMesaRelation.scala:47`` and the join index
``AccumuloJoinIndex.scala:45``)."""

import numpy as np
import pandas as pd
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.schema.columnar import Column, FeatureTable, point_column
from geomesa_tpu.schema.sft import AttributeType, parse_spec
from geomesa_tpu.sql import sql
from geomesa_tpu.sql.engine import SqlError, _split_conjuncts
from geomesa_tpu.store.datastore import DataStore


@pytest.fixture(scope="module")
def eq_ds():
    rng = np.random.default_rng(7)
    store = DataStore(backend="tpu")
    # orders: 400 rows, customer key with some repeats + some NULLs
    store.create_schema(
        "orders", "cust:String,amount:Double,qty:Integer,*geom:Point")
    n = 400
    cust = [f"c{int(i)}" if i >= 0 else None
            for i in rng.integers(-2, 40, n)]
    amount = rng.uniform(1, 100, n).round(2)
    qty = rng.integers(1, 9, n)
    recs = [
        {"cust": cust[i], "amount": float(amount[i]), "qty": int(qty[i]),
         "geom": Point(float(rng.uniform(-50, 50)),
                       float(rng.uniform(-50, 50)))}
        for i in range(n)
    ]
    store.write("orders", recs, fids=[f"o{i}" for i in range(n)])
    # customers: 45 rows, ids c0..c44 (some never referenced), one NULL id
    store.create_schema("cust", "cid:String,tier:Integer,*geom:Point")
    crecs = [
        {"cid": f"c{k}" if k < 45 else None, "tier": int(k % 3),
         "geom": Point(float(k), 0.0)}
        for k in range(46)
    ]
    store.write("cust", crecs, fids=[f"c{k}" for k in range(46)])
    store._truth = pd.DataFrame(
        {"cust": cust, "amount": amount, "qty": qty})
    store._ctruth = pd.DataFrame(
        {"cid": [f"c{k}" if k < 45 else None for k in range(46)],
         "tier": [k % 3 for k in range(46)]})
    return store


def _referee(eq_ds, lwhere=None, rwhere=None):
    # pandas merges None keys against None keys; SQL NULL matches nothing
    l = eq_ds._truth[eq_ds._truth["cust"].notna()]
    r = eq_ds._ctruth[eq_ds._ctruth["cid"].notna()]
    if lwhere is not None:
        l = l[lwhere(l)]
    if rwhere is not None:
        r = r[rwhere(r)]
    return l.merge(r, left_on="cust", right_on="cid", how="inner")


class TestEquiJoin:
    def test_basic_parity(self, eq_ds):
        res = sql(eq_ds,
                  "SELECT a.cust, a.amount, b.tier FROM orders a "
                  "JOIN cust b ON a.cust = b.cid")
        want = _referee(eq_ds)
        assert len(res) == len(want)
        got = sorted(zip(res.columns["a.cust"],
                         [round(float(v), 2) for v in res.columns["a.amount"]],
                         [int(v) for v in res.columns["b.tier"]]))
        exp = sorted(zip(want["cust"], want["amount"].round(2),
                         want["tier"].astype(int)))
        assert got == exp

    def test_null_keys_match_nothing(self, eq_ds):
        res = sql(eq_ds,
                  "SELECT a.cust FROM orders a JOIN cust b ON a.cust = b.cid")
        assert all(v is not None for v in res.columns["a.cust"])

    def test_flipped_on_args(self, eq_ds):
        r1 = sql(eq_ds, "SELECT a.cust, b.tier FROM orders a JOIN cust b "
                        "ON a.cust = b.cid")
        r2 = sql(eq_ds, "SELECT a.cust, b.tier FROM orders a JOIN cust b "
                        "ON b.cid = a.cust")
        assert sorted(map(tuple, zip(*r1.columns.values()))) == \
            sorted(map(tuple, zip(*r2.columns.values())))

    def test_where_routes_to_each_side(self, eq_ds):
        res = sql(eq_ds,
                  "SELECT a.cust, a.amount, b.tier FROM orders a "
                  "JOIN cust b ON a.cust = b.cid "
                  "WHERE a.amount > 50 AND b.tier = 1")
        want = _referee(eq_ds,
                        lwhere=lambda l: l["amount"] > 50,
                        rwhere=lambda r: r["tier"] == 1)
        assert len(res) == len(want)
        assert all(float(v) > 50 for v in res.columns["a.amount"])
        assert all(int(v) == 1 for v in res.columns["b.tier"])

    def test_where_mixed_conjunct_rejected(self, eq_ds):
        with pytest.raises(SqlError, match="exactly one alias"):
            sql(eq_ds, "SELECT a.cust FROM orders a JOIN cust b "
                       "ON a.cust = b.cid WHERE a.amount > b.tier")

    def test_group_by_having_parity(self, eq_ds):
        res = sql(eq_ds,
                  "SELECT b.tier, COUNT(*) AS n, SUM(a.amount) AS s, "
                  "MIN(a.qty) AS lo FROM orders a JOIN cust b "
                  "ON a.cust = b.cid GROUP BY b.tier HAVING COUNT(*) > 10 "
                  "ORDER BY b.tier")
        j = _referee(eq_ds)
        g = j.groupby("tier").agg(
            n=("cust", "size"), s=("amount", "sum"), lo=("qty", "min"))
        g = g[g["n"] > 10].sort_index()
        assert [int(t) for t in res.columns["b.tier"]] == list(g.index)
        assert [int(v) for v in res.columns["n"]] == g["n"].tolist()
        np.testing.assert_allclose(
            [float(v) for v in res.columns["s"]], g["s"].to_numpy())
        assert [int(v) for v in res.columns["lo"]] == g["lo"].tolist()

    def test_select_star_and_limit(self, eq_ds):
        res = sql(eq_ds, "SELECT b.*, a.qty FROM orders a JOIN cust b "
                         "ON a.cust = b.cid LIMIT 5")
        assert len(res) == 5
        assert "b.cid" in res.columns and "a.qty" in res.columns

    def test_order_by_desc(self, eq_ds):
        res = sql(eq_ds, "SELECT a.cust, a.amount FROM orders a JOIN cust b "
                         "ON a.cust = b.cid ORDER BY a.amount DESC LIMIT 10")
        vals = [float(v) for v in res.columns["a.amount"]]
        assert vals == sorted(vals, reverse=True)

    def test_numeric_cross_type_key(self, eq_ds):
        # Integer joined against Double: meet in float64
        store = DataStore(backend="tpu")
        store.create_schema("li", "k:Integer,*geom:Point")
        store.create_schema("rd", "k:Double,v:Integer,*geom:Point")
        store.write("li", [{"k": i, "geom": Point(0.0, 0.0)}
                           for i in range(6)],
                    fids=[f"l{i}" for i in range(6)])
        store.write("rd", [{"k": float(i % 3), "v": i, "geom": Point(1.0, 1.0)}
                           for i in range(6)],
                    fids=[f"r{i}" for i in range(6)])
        res = sql(store, "SELECT a.k, b.v FROM li a JOIN rd b ON a.k = b.k")
        # keys 0,1,2 each match two right rows
        assert len(res) == 6
        assert sorted(int(v) for v in res.columns["a.k"]) == [0, 0, 1, 1, 2, 2]

    def test_uuid_object_keys(self, eq_ds):
        # non-str key values (uuid.UUID objects) must key on str(v), not
        # collapse to "" (which would cross-product every row)
        import uuid

        ids = [uuid.UUID(int=i) for i in range(4)]
        store = DataStore(backend="tpu")
        store.create_schema("lu", "uid:UUID,v:Integer,*geom:Point")
        store.create_schema("ru", "uid:UUID,w:Integer,*geom:Point")
        store.write("lu", [{"uid": ids[i], "v": i, "geom": Point(0.0, 0.0)}
                           for i in range(4)],
                    fids=[f"l{i}" for i in range(4)])
        store.write("ru", [{"uid": ids[3 - i], "w": i, "geom": Point(1.0, 1.0)}
                           for i in range(4)],
                    fids=[f"r{i}" for i in range(4)])
        res = sql(store, "SELECT a.v, b.w FROM lu a JOIN ru b "
                         "ON a.uid = b.uid")
        assert len(res) == 4
        got = sorted(zip((int(v) for v in res.columns["a.v"]),
                         (int(w) for w in res.columns["b.w"])))
        assert got == [(0, 3), (1, 2), (2, 1), (3, 0)]

    def test_geometry_key_rejected(self, eq_ds):
        with pytest.raises(SqlError, match="geometry column"):
            sql(eq_ds, "SELECT a.cust FROM orders a JOIN cust b "
                       "ON a.geom = b.geom")

    def test_incompatible_key_types(self, eq_ds):
        with pytest.raises(SqlError, match="incompatible"):
            sql(eq_ds, "SELECT a.cust FROM orders a JOIN cust b "
                       "ON a.cust = b.tier")


class TestMultiJoin:
    """N-way equi-join chains (left-deep sorted-merge composition)."""

    @pytest.fixture(scope="class")
    def tri_ds(self):
        rng = np.random.default_rng(9)
        store = DataStore(backend="tpu")
        # orders -> customers -> regions
        store.create_schema("ords", "cust:String,amount:Double,*geom:Point")
        store.create_schema("custs", "cid:String,region:Integer,*geom:Point")
        store.create_schema("regs", "rid:Integer,rname:String,*geom:Point")
        n = 300
        cust = [f"c{int(i)}" for i in rng.integers(0, 30, n)]
        amount = rng.uniform(1, 100, n).round(2)
        store.write("ords", [
            {"cust": cust[i], "amount": float(amount[i]),
             "geom": Point(0.0, 0.0)}
            for i in range(n)
        ], fids=[f"o{i}" for i in range(n)])
        store.write("custs", [
            {"cid": f"c{k}", "region": int(k % 5), "geom": Point(0.0, 0.0)}
            for k in range(30)
        ], fids=[f"c{k}" for k in range(30)])
        store.write("regs", [
            {"rid": k, "rname": f"R{k}", "geom": Point(0.0, 0.0)}
            for k in range(4)  # region 4 has no row: inner join drops it
        ], fids=[f"r{k}" for k in range(4)])
        store._truth = pd.DataFrame({"cust": cust, "amount": amount})
        return store

    def _referee(self, tri_ds):
        o = tri_ds._truth
        c = pd.DataFrame({"cid": [f"c{k}" for k in range(30)],
                          "region": [k % 5 for k in range(30)]})
        r = pd.DataFrame({"rid": range(4),
                          "rname": [f"R{k}" for k in range(4)]})
        return (o.merge(c, left_on="cust", right_on="cid")
                 .merge(r, left_on="region", right_on="rid"))

    def test_three_way_parity(self, tri_ds):
        res = sql(tri_ds,
                  "SELECT a.cust, a.amount, c.rname FROM ords a "
                  "JOIN custs b ON a.cust = b.cid "
                  "JOIN regs c ON b.region = c.rid")
        want = self._referee(tri_ds)
        assert len(res) == len(want)
        got = sorted(zip(res.columns["a.cust"],
                         (round(float(v), 2) for v in res.columns["a.amount"]),
                         res.columns["c.rname"]))
        exp = sorted(zip(want["cust"], want["amount"].round(2),
                         want["rname"]))
        assert got == exp

    def test_three_way_group_by(self, tri_ds):
        res = sql(tri_ds,
                  "SELECT c.rname, COUNT(*) AS n, SUM(a.amount) AS s "
                  "FROM ords a JOIN custs b ON a.cust = b.cid "
                  "JOIN regs c ON b.region = c.rid "
                  "GROUP BY c.rname ORDER BY c.rname")
        g = self._referee(tri_ds).groupby("rname").agg(
            n=("cust", "size"), s=("amount", "sum")).sort_index()
        assert list(res.columns["c.rname"]) == list(g.index)
        assert [int(v) for v in res.columns["n"]] == g["n"].tolist()
        np.testing.assert_allclose(
            [float(v) for v in res.columns["s"]], g["s"].to_numpy())

    def test_where_routes_in_chain(self, tri_ds):
        res = sql(tri_ds,
                  "SELECT a.cust FROM ords a "
                  "JOIN custs b ON a.cust = b.cid "
                  "JOIN regs c ON b.region = c.rid "
                  "WHERE a.amount > 50 AND c.rname = 'R2'")
        w = self._referee(tri_ds)
        want = w[(w["amount"] > 50) & (w["rname"] == "R2")]
        assert len(res) == len(want)

    def test_unbound_on_alias_rejected(self, tri_ds):
        with pytest.raises(SqlError, match="already-bound"):
            sql(tri_ds,
                "SELECT a.cust FROM ords a "
                "JOIN custs b ON a.cust = b.cid "
                "JOIN regs c ON d.region = c.rid")

    def test_four_way_chain(self, tri_ds):
        # self-join the chain one more level: regs joined again by rid
        res = sql(tri_ds,
                  "SELECT a.cust, d.rname FROM ords a "
                  "JOIN custs b ON a.cust = b.cid "
                  "JOIN regs c ON b.region = c.rid "
                  "JOIN regs d ON c.rid = d.rid")
        want = self._referee(tri_ds)  # rid self-join is 1:1
        assert len(res) == len(want)


class TestLeftJoin:
    def test_left_join_keeps_unmatched(self, eq_ds):
        res = sql(eq_ds,
                  "SELECT a.cust, b.tier FROM orders a "
                  "LEFT JOIN cust b ON a.cust = b.cid")
        l = eq_ds._truth
        r = eq_ds._ctruth[eq_ds._ctruth["cid"].notna()]
        want = l.merge(r, left_on="cust", right_on="cid", how="left")
        assert len(res) == len(want) == len(l)
        # unmatched (incl. NULL-key) left rows surface tier as None
        n_null = sum(1 for v in res.columns["b.tier"] if v is None)
        assert n_null == int(want["tier"].isna().sum())

    def test_left_outer_spelling(self, eq_ds):
        r1 = sql(eq_ds, "SELECT a.cust FROM orders a "
                        "LEFT JOIN cust b ON a.cust = b.cid")
        r2 = sql(eq_ds, "SELECT a.cust FROM orders a "
                        "LEFT OUTER JOIN cust b ON a.cust = b.cid")
        assert len(r1) == len(r2)

    def test_left_join_group_by_counts_nulls(self, eq_ds):
        res = sql(eq_ds,
                  "SELECT b.tier, COUNT(*) AS n, COUNT(b.tier) AS nn "
                  "FROM orders a LEFT JOIN cust b ON a.cust = b.cid "
                  "GROUP BY b.tier")
        by_tier = {t: (int(n), int(nn)) for t, n, nn in
                   zip(res.columns["b.tier"], res.columns["n"],
                       res.columns["nn"])}
        # the NULL group exists and COUNT(col) excludes its NULLs
        assert None in by_tier
        assert by_tier[None][1] == 0
        total = sum(n for n, _ in by_tier.values())
        assert total == len(eq_ds._truth)

    def test_left_then_inner_null_propagation(self, eq_ds):
        # NULL keys from the left join never match the next inner join
        store = DataStore(backend="tpu")
        store.create_schema("x", "k:Integer,*geom:Point")
        store.create_schema("y", "k:Integer,v:Integer,*geom:Point")
        store.create_schema("z", "v:Integer,w:String,*geom:Point")
        store.write("x", [{"k": i, "geom": Point(0.0, 0.0)}
                          for i in range(4)],
                    fids=[f"x{i}" for i in range(4)])
        store.write("y", [{"k": 0, "v": 10, "geom": Point(0.0, 0.0)},
                          {"k": 1, "v": 11, "geom": Point(0.0, 0.0)}],
                    fids=["y0", "y1"])
        store.write("z", [{"v": 10, "w": "ten", "geom": Point(0.0, 0.0)},
                          {"v": 11, "w": "eleven", "geom": Point(0.0, 0.0)}],
                    fids=["z0", "z1"])
        res = sql(store,
                  "SELECT a.k, c.w FROM x a "
                  "LEFT JOIN y b ON a.k = b.k "
                  "JOIN z c ON b.v = c.v")
        # k=2,3 got NULL v from the left join; the inner join drops them
        assert sorted(int(v) for v in res.columns["a.k"]) == [0, 1]
        # but a left-join chain keeps them with NULL w
        res2 = sql(store,
                   "SELECT a.k, c.w FROM x a "
                   "LEFT JOIN y b ON a.k = b.k "
                   "LEFT JOIN z c ON b.v = c.v")
        assert len(res2) == 4
        ws = {int(k): w for k, w in zip(res2.columns["a.k"],
                                        res2.columns["c.w"])}
        assert ws[0] == "ten" and ws[1] == "eleven"
        assert ws[2] is None and ws[3] is None


def test_column_named_join_still_parses():
    """Dispatch must gate on join STRUCTURE, not token counts: a column
    literally named ``join`` keeps riding the single-table path."""
    store = DataStore(backend="tpu")
    store.create_schema("jt", "join:Integer,*geom:Point")
    store.write("jt", [{"join": i, "geom": Point(0.0, 0.0)}
                       for i in range(5)],
                fids=[f"j{i}" for i in range(5)])
    res = sql(store, "SELECT join FROM jt WHERE join > 2")
    assert sorted(int(v) for v in res.columns["join"]) == [3, 4]


class TestSplitConjuncts:
    def test_basic(self):
        assert _split_conjuncts("a.x > 1 AND b.y = 2") == \
            ["a.x > 1", "b.y = 2"]

    def test_quoted_and_survives(self):
        parts = _split_conjuncts("a.name = 'rock and roll' AND b.t = 1")
        assert parts == ["a.name = 'rock and roll'", "b.t = 1"]

    def test_parenthesized_and_survives(self):
        parts = _split_conjuncts("(a.x > 1 AND a.x < 5) AND b.y = 2")
        assert parts == ["(a.x > 1 AND a.x < 5)", "b.y = 2"]

    def test_word_boundary(self):
        assert _split_conjuncts("a.branding = 'x'") == ["a.branding = 'x'"]


def test_equi_join_parity_1m_x_1m():
    """VERDICT r4 item 8 'done' criterion: parity vs a pandas referee at
    1M x 1M. Keys drawn so the pair count stays ~1M (bounded multiplicity).
    """
    rng = np.random.default_rng(42)
    n = 1_000_000
    lkeys = rng.integers(0, n, n).astype(np.int64)
    rkeys = np.arange(n, dtype=np.int64)
    rng.shuffle(rkeys)
    lval = rng.uniform(0, 1, n)

    store = DataStore(backend="tpu")
    sftl = parse_spec("lt", "k:Long,v:Double,*geom:Point")
    sftr = parse_spec("rt", "k:Long,w:Long,*geom:Point")
    store.create_schema(sftl)
    store.create_schema(sftr)
    zeros = np.zeros(n)
    fids = np.arange(n).astype(str).astype(object)
    store.write("lt", FeatureTable.from_columns(
        sftl, fids,
        {"k": Column(AttributeType.LONG, lkeys),
         "v": Column(AttributeType.DOUBLE, lval),
         "geom": point_column(zeros, zeros)}))
    store.write("rt", FeatureTable.from_columns(
        sftr, fids,
        {"k": Column(AttributeType.LONG, rkeys),
         "w": Column(AttributeType.LONG, np.arange(n, dtype=np.int64)),
         "geom": point_column(zeros, zeros)}))

    res = sql(store, "SELECT a.k, a.v, b.w FROM lt a JOIN rt b ON a.k = b.k")
    want = pd.DataFrame({"k": lkeys, "v": lval}).merge(
        pd.DataFrame({"k": rkeys, "w": np.arange(n, dtype=np.int64)}),
        on="k", how="inner")
    assert len(res) == len(want)
    # right side is a permutation of 0..n-1 on key k with w = original pos,
    # so each pair's w is determined by k: verify the full pairing cheaply
    k_to_w = np.empty(n, dtype=np.int64)
    k_to_w[rkeys] = np.arange(n, dtype=np.int64)
    got_k = res.columns["a.k"].astype(np.int64)
    got_w = res.columns["b.w"].astype(np.int64)
    np.testing.assert_array_equal(got_w, k_to_w[got_k])
    np.testing.assert_array_equal(np.sort(got_k), np.sort(want["k"].to_numpy()))
