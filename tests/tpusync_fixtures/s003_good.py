"""S003 good: bounded or sanctioned iteration — a compile-time-constant
trip count, a loop whose body never dispatches, and the per-chunk
streaming loop of a @choreography_boundary orchestrator."""

from geomesa_tpu.analysis.contracts import choreography_boundary


def cached_probe_step(mesh):
    return lambda x: x


def double_buffered(mesh, xs):
    step = cached_probe_step(mesh)
    out = None
    for _ in range(2):
        out = step(xs)
    return out


def host_only_loop(chunks):
    total = 0
    for c in chunks:
        total += len(c)
    return total


@choreography_boundary
def stream(mesh, chunks):
    step = cached_probe_step(mesh)
    return [step(c) for c in chunks]
