"""S001 cross-module: the two-dispatch worker the api budget cannot
cover. No budget here — the violation lands on the declaration."""


def cached_count_step(mesh):
    return lambda x: x


def cached_gather_step(mesh):
    return lambda x: x


def count_and_gather(mesh, xs):
    counts = cached_count_step(mesh)(xs)
    return cached_gather_step(mesh)(counts)
