"""S001 cross-module bad half: the budget holder only *calls* — every
dispatch it pays for lives one module away, so the finding requires the
whole-program cost fixpoint."""

from geomesa_tpu.analysis.contracts import dispatch_budget

from . import work


@dispatch_budget(1)
def select(mesh, xs):
    return work.count_and_gather(mesh, xs)
