"""W001 under the sync prong: stale tpusync waivers — nothing on these
lines trips an S rule, so the waivers themselves are findings. Same-line
and next-line forms."""


def cached_probe_step(mesh):
    return lambda x: x


x = 1  # tpusync: disable=S003


# tpusync: disable-next-line=S004
def quiet(mesh, xs):
    return cached_probe_step(mesh)(xs)
