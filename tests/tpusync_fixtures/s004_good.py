"""S004 good: every sanctioned jit construction — the decorator form,
the cached_*_step factory layer itself, and a @choreography_boundary
orchestrator that owns its wrappers."""

from functools import lru_cache, partial

import jax

from geomesa_tpu.analysis.contracts import choreography_boundary


@jax.jit
def decorated_step(x):
    return x


@partial(jax.jit, static_argnums=(0,))
def decorated_static(n, x):
    return x


@lru_cache(maxsize=None)
def cached_probe_step(mesh):
    return jax.jit(lambda x: x)


@choreography_boundary
def orchestrate(fn):
    return jax.jit(fn)
