"""S002 bad: host syncs reachable from sync-free regions — a direct
block_until_ready, an np.asarray of a device value in the region
itself, an obs.ledger.materialize readback two calls down the graph,
and an implicit bool() coercion in a branch test inside a
@device_band(certain=True) kernel wrapper."""

import numpy as np

from geomesa_tpu.analysis.contracts import device_band, host_sync_free
from geomesa_tpu.obs import ledger


def cached_probe_step(mesh):
    return lambda x: x


@host_sync_free
def staged(mesh, xs):
    step = cached_probe_step(mesh)
    dev = step(xs)
    dev.block_until_ready()
    host = np.asarray(dev)
    return finishes(host)


def finishes(out):
    return materialized(out)


def materialized(out):
    return ledger.materialize(out)


@device_band(certain=True)
def certain_region(mesh, xs):
    step = cached_probe_step(mesh)
    dev = step(xs)
    if dev:
        return dev
    return xs
