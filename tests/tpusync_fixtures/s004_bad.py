"""S004 bad: a raw jit wrapper built outside the cached_* factory
discipline — invisible to the roundtrip ledger, so nothing can budget
the dispatches it mints."""

import jax


def ad_hoc_wrapper(fn):
    return jax.jit(fn)


def ad_hoc_pmap(fn):
    wrapped = jax.pmap(fn, axis_name="data")
    return wrapped
