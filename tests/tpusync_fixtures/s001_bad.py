"""S001 bad: declared dispatch budgets below the structural worst case
— a two-dispatch sequence under budget 1, a constant-trip loop that
multiplies past the bound, and a malformed declaration (which is itself
an S001: a contract that cannot be checked is a wrong contract)."""

from geomesa_tpu.analysis.contracts import dispatch_budget


def cached_probe_step(mesh):
    return lambda x: x


@dispatch_budget(1)
def two_pass(mesh, xs):
    step = cached_probe_step(mesh)
    counts = step(xs)
    hits = step(counts)
    return hits


@dispatch_budget(2)
def looped(mesh, xs):
    step = cached_probe_step(mesh)
    out = None
    for _ in range(4):
        out = step(xs)
    return out


@dispatch_budget("lots")
def malformed(mesh, xs):
    return cached_probe_step(mesh)(xs)
