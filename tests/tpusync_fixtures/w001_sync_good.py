"""A LIVE tpusync waiver: it suppresses a real S003 finding (the
per-chunk retry loop is chunked by design), so the stale-waiver scan
stays silent and the file gates clean."""


def cached_probe_step(mesh):
    return lambda x: x


def chunked(mesh, chunks):
    step = cached_probe_step(mesh)
    out = []
    for c in chunks:
        # reviewed: chunking bounds device memory, not a fusion miss
        # tpusync: disable-next-line=S003
        out.append(step(c))
    return out
