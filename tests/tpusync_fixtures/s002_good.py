"""S002 good: the intentional await that ENDS the pipeline retires in
source — same-line and next-line forms — and a sync that is not
reachable from any sync-free root stays unflagged."""

import numpy as np

from geomesa_tpu.analysis.contracts import host_sync_free


def cached_probe_step(mesh):
    return lambda x: x


@host_sync_free
def staged(mesh, xs):
    step = cached_probe_step(mesh)
    dev = step(xs)
    out = np.asarray(dev)  # tpusync: retire
    # tpusync: retire-next-line
    tail = np.asarray(step(out))
    return tail


def plain_host_path(mesh, xs):
    # no sync-free root reaches this: an ordinary materialization
    dev = cached_probe_step(mesh)(xs)
    return np.asarray(dev)
