"""S001 good: the same shapes inside their budgets — branches cost the
max arm (not the sum), a constant loop within bound, and a callee behind
a @choreography_boundary facade absorbed to zero from outside it."""

from geomesa_tpu.analysis.contracts import (
    choreography_boundary,
    dispatch_budget,
)


def cached_probe_step(mesh):
    return lambda x: x


@dispatch_budget(1)
def either_arm(mesh, xs, fast):
    step = cached_probe_step(mesh)
    if fast:
        out = step(xs)
    else:
        out = step(step_input(xs))
    return out


def step_input(xs):
    return xs


@dispatch_budget(2)
def bounded_loop(mesh, xs):
    step = cached_probe_step(mesh)
    out = None
    for _ in range(2):
        out = step(xs)
    return out


@choreography_boundary
def orchestrate(mesh, xs):
    step = cached_probe_step(mesh)
    return step(step(step(xs)))


@dispatch_budget(0)
def delegates(mesh, xs):
    # the facade's dispatches are its own accounting problem
    return orchestrate(mesh, xs)
