"""S003 bad: loop-carried dispatch — a step invoked directly in a
``for`` over a runtime iterable, the same roundtrip hidden behind a
helper call inside a ``while``, and the comprehension form."""


def cached_probe_step(mesh):
    return lambda x: x


def per_chunk(mesh, chunks):
    step = cached_probe_step(mesh)
    out = []
    for c in chunks:
        out.append(step(c))
    return out


def one_chunk(mesh, c):
    return cached_probe_step(mesh)(c)


def drain(mesh, queue):
    results = []
    while queue:
        c = queue.pop()
        results.append(one_chunk(mesh, c))
    return results


def mapped(mesh, chunks):
    step = cached_probe_step(mesh)
    return [step(c) for c in chunks]
