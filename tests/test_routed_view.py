"""Routed view: each query goes to exactly ONE delegate store picked by the
filter's attribute set (reference: RoutedDataStoreView.scala:31 +
RouteSelectorByAttribute.scala:20 — id route, attribute routes, include
catch-all, no-route → empty result)."""

import numpy as np
import pytest

from geomesa_tpu.filter.cql import parse
from geomesa_tpu.geometry import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.routed import RoutedDataStoreView, filter_properties

SPEC = "name:String,age:Integer,dtg:Date,*geom:Point"


def _store(tag: str, n: int = 10) -> DataStore:
    ds = DataStore(backend="oracle")
    ds.create_schema("ev", SPEC)
    ds.write("ev", [
        {"name": f"{tag}{i}", "age": i, "dtg": 1_500_000_000_000 + i,
         "geom": Point(i, i)}
        for i in range(n)
    ], fids=[f"{tag}{i}" for i in range(n)])
    return ds


class TestFilterProperties:
    def test_names_and_fid(self):
        names, fid = filter_properties(parse(
            "BBOX(geom, 0, 0, 5, 5) AND age > 3"))
        assert names == {"geom", "age"} and not fid
        names, fid = filter_properties(parse("IN ('a1', 'a2')"))
        assert names == set() and fid
        assert filter_properties(None) == (set(), False)

    def test_nested(self):
        names, fid = filter_properties(parse(
            "NOT (name LIKE 'x%') OR (age < 2 AND IN ('f'))"))
        assert names == {"name", "age"} and fid


class TestRoutedView:
    @pytest.fixture()
    def view(self):
        spatial = _store("s")
        ids = _store("i")
        catchall = _store("c")
        return (
            RoutedDataStoreView([
                (spatial, [["geom", "dtg"], ["geom"]]),
                (ids, ["id"]),
                (catchall, [["name"], []]),
            ]),
            spatial, ids, catchall,
        )

    def test_attribute_routes(self, view):
        v, spatial, ids, catchall = view
        r = v.query("ev", "BBOX(geom, -1, -1, 3, 3) AND dtg AFTER 2017-01-01T00:00:00Z")
        assert all(f.startswith("s") for f in r.table.fids)
        r = v.query("ev", "BBOX(geom, -1, -1, 3, 3)")
        assert all(f.startswith("s") for f in r.table.fids)
        r = v.query("ev", "name = 'c4'")
        assert list(r.table.fids) == ["c4"]

    def test_id_route(self, view):
        v, *_ = view
        r = v.query("ev", "IN ('i1', 'i7')")
        assert sorted(r.table.fids) == ["i1", "i7"]

    def test_include_catchall(self, view):
        v, _, _, catchall = view
        # filter referencing no routed attribute set and no names at all
        r = v.query("ev", None)
        assert all(f.startswith("c") for f in r.table.fids)
        # age-only filter matches no route -> include store serves it
        r = v.query("ev", "age > 7")
        assert all(f.startswith("c") for f in r.table.fids)

    def test_no_route_empty(self):
        spatial = _store("s")
        v = RoutedDataStoreView([(spatial, [["geom"]])])
        r = v.query("ev", "age > 3")  # no attribute match, no include
        assert r.count == 0 and len(r.table) == 0

    def test_stats_count_and_explain(self, view):
        v, *_ = view
        assert v.stats_count("ev", "BBOX(geom, -1, -1, 3, 3)") > 0
        assert v.explain("ev", "BBOX(geom, -1, -1, 3, 3)").startswith(
            "Route: store[0]")
        assert v.explain("ev", Query()).startswith(
            "Route: store[2]")  # no names -> the include store serves it

    def test_specific_route_wins_regardless_of_order(self):
        # a {geom} route declared FIRST must not shadow {geom, dtg}
        a, b = _store("a"), _store("b")
        v = RoutedDataStoreView([(a, [["geom"]]), (b, [["geom", "dtg"]])])
        r = v.query(
            "ev", "BBOX(geom, -1, -1, 3, 3) AND dtg AFTER 2017-01-01T00:00:00Z")
        assert all(f.startswith("b") for f in r.table.fids)
        r = v.query("ev", "BBOX(geom, -1, -1, 3, 3)")
        assert all(f.startswith("a") for f in r.table.fids)

    def test_bare_string_routes_rejected(self):
        a = _store("a")
        with pytest.raises(ValueError, match="list of declarations"):
            RoutedDataStoreView([(a, "id")])

    def test_duplicate_routes_rejected(self):
        a, b = _store("a"), _store("b")
        with pytest.raises(ValueError, match="more than once"):
            RoutedDataStoreView([(a, [["geom"]]), (b, [["geom"]])])
        with pytest.raises(ValueError, match="'id' route"):
            RoutedDataStoreView([(a, ["id"]), (b, ["id"])])
        with pytest.raises(ValueError, match="include route"):
            RoutedDataStoreView([(a, [[]]), (b, [[]])])

    def test_schema_semantics(self):
        a, b = _store("a"), _store("b")
        v = RoutedDataStoreView([(a, [["geom"]]), (b, [[]])])
        assert v.list_schemas() == ["ev"]
        assert [x.name for x in v.get_schema("ev").attributes] == [
            "name", "age", "dtg", "geom"
        ]
