"""HBM residency management: per-index accounting, explicit evict/recover,
and budget-capped loads (SURVEY.md §2.20 P9 at device granularity — the
lambda hot/cold pattern applied to device vs host memory)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import LineString, Point
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.backends import TpuBackend
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000
SPEC = "name:String,dtg:Date,*geom:Point"
Q = "BBOX(geom, -50, -25, 50, 25) AND dtg AFTER 2017-07-02T00:00:00Z"


def fill(ds, n=3000, seed=11):
    rng = np.random.default_rng(seed)
    recs = [
        {
            "name": f"n{i}",
            "dtg": T0 + int(rng.integers(0, 10 * 86_400_000)),
            "geom": Point(float(rng.uniform(-180, 180)), float(rng.uniform(-90, 90))),
        }
        for i in range(n)
    ]
    ds.write("evt", recs, fids=[f"f{i}" for i in range(n)])


class TestResidency:
    def test_report_and_accounting(self):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("evt", SPEC))
        fill(ds)
        r = ds.device_residency("evt")
        assert r["resident"] and r["total_bytes"] > 0
        assert set(r["indices"]) >= {"z3"}
        # nbytes is the sum over the sharded int32 columns
        assert r["total_bytes"] == sum(r["indices"].values())
        assert r["budget_bytes"] is None

    def test_evict_then_recover(self):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("evt", SPEC))
        fill(ds)
        oracle = DataStore(backend="oracle")
        oracle.create_schema(parse_spec("evt", SPEC))
        fill(oracle)
        want = set(oracle.query("evt", Q).table.fids.tolist())

        before = ds.query("evt", Q)
        assert set(before.table.fids.tolist()) == want
        ds.evict_device("evt")
        assert not ds.device_residency("evt")["resident"]
        # host fallback stays exact
        assert set(ds.query("evt", Q).table.fids.tolist()) == want
        assert ds.metrics.counter("store.device.evictions").count == 1
        assert ds.recover("evt")
        assert ds.device_residency("evt")["resident"]
        assert set(ds.query("evt", Q).table.fids.tolist()) == want

    def test_budget_zero_keeps_host_exact(self):
        ds = DataStore(backend=TpuBackend(max_device_bytes=1))
        ds.create_schema(parse_spec("evt", SPEC))
        fill(ds, 500)
        r = ds.device_residency("evt")
        assert not r["resident"]
        assert r["budget_bytes"] == 1
        oracle = DataStore(backend="oracle")
        oracle.create_schema(parse_spec("evt", SPEC))
        fill(oracle, 500)
        assert set(ds.query("evt", Q).table.fids.tolist()) == set(
            oracle.query("evt", Q).table.fids.tolist()
        )

    def test_budget_prioritizes_point_indexes(self):
        # budget for ~one index: z3 (priority) resident, the rest host
        ds0 = DataStore(backend="tpu")
        ds0.create_schema(parse_spec("evt", SPEC))
        fill(ds0, 2000)
        z3_bytes = ds0.device_residency("evt")["indices"]["z3"]

        ds = DataStore(backend=TpuBackend(max_device_bytes=int(z3_bytes * 1.5)))
        ds.create_schema(parse_spec("evt", SPEC))
        fill(ds, 2000)
        r = ds.device_residency("evt")
        assert list(r["indices"]) == ["z3"]
        assert r["total_bytes"] <= int(z3_bytes * 1.5)

    def test_env_budget(self, monkeypatch):
        monkeypatch.setenv("GEOMESA_DEVICE_BUDGET_BYTES", "123456")
        assert TpuBackend().max_device_bytes == 123456
        monkeypatch.setenv("GEOMESA_DEVICE_BUDGET_BYTES", "8G")
        with pytest.raises(ValueError, match="GEOMESA_DEVICE_BUDGET_BYTES"):
            TpuBackend()
        monkeypatch.delenv("GEOMESA_DEVICE_BUDGET_BYTES")
        assert TpuBackend().max_device_bytes is None

    def test_evict_not_lost_to_concurrent_recover(self):
        # eviction holds the mutate lock, so it serializes against recover()
        import threading

        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("evt", SPEC))
        fill(ds, 800)
        stop = threading.Event()
        errs = []

        def churn():
            try:
                while not stop.is_set():
                    ds.recover("evt")
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            for _ in range(10):
                ds.evict_device("evt")
                # either state: evicted, or a subsequent recover re-installed
                # it — but never a torn/partial state; queries stay exact
                assert ds.query("evt", Q).count >= 0
        finally:
            stop.set()
            t.join(timeout=10)
        assert not errs
