"""DCN federation (remote store over HTTP) + distributed multiprocess ingest
(reference: MergedDataStoreView/MergedQueryRunner, ConverterInputFormat)."""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.store.datastore import DataStore

T0 = 1_500_000_000_000


def _filled_store(lo, hi, seed):
    rng = np.random.default_rng(seed)
    ds = DataStore(backend="tpu")
    ds.create_schema("f", "name:String,dtg:Date,*geom:Point")
    recs = [
        {"name": f"n{i % 9}", "dtg": T0 + i * 1000,
         "geom": Point(float(rng.uniform(lo, hi)), float(rng.uniform(-40, 40)))}
        for i in range(800)
    ]
    ds.write("f", recs, fids=[f"{seed}-{i}" for i in range(800)])
    return ds


@pytest.fixture(scope="module")
def remote_server():
    """A real HTTP server over a real store, on a random port."""
    from wsgiref.simple_server import make_server

    from geomesa_tpu.web.app import GeoMesaApp

    store = _filled_store(-170, -5, seed=1)  # "west slice"
    httpd = make_server("127.0.0.1", 0, GeoMesaApp(store))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield store, f"http://127.0.0.1:{port}"
    httpd.shutdown()


class TestRemoteFederation:
    def test_remote_store_query_matches_local(self, remote_server):
        from geomesa_tpu.store.remote import RemoteDataStore

        local, url = remote_server
        remote = RemoteDataStore(url)
        assert remote.list_schemas() == ["f"]
        cql = "BBOX(geom, -60, -40, -20, 40) AND name = 'n3'"
        a = set(local.query("f", cql).table.fids.tolist())
        b = set(remote.query("f", cql).table.fids.tolist())
        assert a == b and len(a) > 0

    def test_merged_view_over_network_boundary(self, remote_server):
        from geomesa_tpu.store.merged import MergedDataStoreView
        from geomesa_tpu.store.remote import RemoteDataStore

        _, url = remote_server
        east = _filled_store(5, 170, seed=2)  # in-process "east slice"
        view = MergedDataStoreView([RemoteDataStore(url), east])
        assert view.list_schemas() == ["f"]
        cql = "name = 'n4'"
        r = view.query("f", cql)
        west_expect = remote_server[0].query("f", cql).count
        east_expect = east.query("f", cql).count
        assert r.count == west_expect + east_expect > 0
        # ast-filter queries serialize over the wire too
        from geomesa_tpu.filter.cql import parse
        from geomesa_tpu.planning.planner import Query

        r2 = view.query("f", Query(filter=parse("BBOX(geom, -180, -45, 180, 45)")))
        assert r2.count > 0

    def test_remote_stats_count(self, remote_server):
        from geomesa_tpu.store.remote import RemoteDataStore

        local, url = remote_server
        remote = RemoteDataStore(url)
        assert remote.stats_count("f", exact=True) == 800


class TestParallelIngest:
    def _csv(self, tmp_path, n=3000, name="big.csv"):
        rng = np.random.default_rng(7)
        lines = [
            f"{i},{T0 + i * 1000},{rng.uniform(-170, 170):.6f},{rng.uniform(-80, 80):.6f}"
            for i in range(n)
        ]
        p = tmp_path / name
        p.write_text("\n".join(lines) + "\n")
        return p, n

    SPEC = {
        "kind": "delimited",
        "sft_name": "ing",
        "sft_spec": "a:Integer,dtg:Date,*geom:Point",
        "fields": {"a": "int($1)", "dtg": "millisToDate($2)",
                   "geom": "point($3, $4)"},
    }

    def test_split_file_covers_every_line(self, tmp_path):
        from geomesa_tpu.convert.parallel_ingest import split_file

        p, n = self._csv(tmp_path)
        chunks = split_file(str(p), 4)
        assert len(chunks) >= 2
        # chunks tile the file exactly
        assert chunks[0][0] == 0
        for (o1, l1), (o2, _) in zip(chunks, chunks[1:]):
            assert o1 + l1 == o2
        import os

        assert sum(l for _, l in chunks) == os.path.getsize(p)
        # every chunk starts at a line boundary
        data = p.read_bytes()
        for o, _ in chunks[1:]:
            assert data[o - 1 : o] == b"\n"

    def test_parallel_chunked_ingest(self, tmp_path):
        from geomesa_tpu.convert.parallel_ingest import parallel_ingest

        p, n = self._csv(tmp_path)
        ds = DataStore(backend="tpu")
        ds.create_schema("ing", self.SPEC["sft_spec"])
        total = parallel_ingest(
            ds, "ing", self.SPEC, chunks_of=str(p), processes=3
        )
        assert total == n
        r = ds.query("ing", "INCLUDE")
        assert r.count == n
        # the attribute column survived the multiprocess round trip intact
        vals = sorted(int(v) for v in r.table.columns["a"].values)
        assert vals == list(range(n))

    def test_parallel_multi_file_ingest(self, tmp_path):
        from geomesa_tpu.convert.parallel_ingest import parallel_ingest

        p1, n1 = self._csv(tmp_path, n=500, name="a.csv")
        p2, n2 = self._csv(tmp_path, n=700, name="b.csv")
        ds = DataStore(backend="tpu")
        ds.create_schema("ing", self.SPEC["sft_spec"])
        total = parallel_ingest(
            ds, "ing", self.SPEC, paths=[str(p1), str(p2)], processes=2
        )
        assert total == n1 + n2
        assert ds.query("ing", "INCLUDE").count == n1 + n2
