"""The three GEOMESA_KNN_IMPL variants (map / scan / blocked) are exact and
interchangeable: same distance multisets as a numpy brute-force referee, same
rows wherever distances are strictly increasing. The blocked impl is the
hierarchical per-block top-k (accelerator shape); ``scan`` streams chunks;
``map`` is the full-column baseline (see parallel/query.py
``_local_knn_heaps``)."""

import jax.numpy as jnp
import numpy as np
import pytest

from geomesa_tpu.parallel.mesh import make_mesh, shard_columns
from geomesa_tpu.parallel.query import make_batched_knn_step

IMPLS = ("map", "scan", "blocked")


def _store(n, seed=11):
    rng = np.random.default_rng(seed)
    lon = rng.uniform(-180, 180, n)
    lat = rng.uniform(-90, 90, n)
    order = np.lexsort((lat, lon))
    lon, lat = lon[order], lat[order]
    xi = ((lon + 180.0) / 360.0 * 2**31).astype(np.int32)
    yi = ((lat + 90.0) / 180.0 * 2**31).astype(np.int32)
    return lon, lat, xi, yi


def _decode_f32(xi, yi):
    sx = np.float32(360.0 / 2**31)
    sy = np.float32(180.0 / 2**31)
    x = xi.astype(np.float32) * sx - np.float32(180.0)
    y = yi.astype(np.float32) * sy - np.float32(90.0)
    return x, y


def _run(impl, mesh, cols, n, qx, qy, k):
    # the explicit impl parameter; the env-knob path has its own sentinel
    # test (test_env_knob_selects_impl)
    step = make_batched_knn_step(mesh, k, impl=impl)
    d, r = step(cols["x"], cols["y"], jnp.int32(n), qx, qy)
    return np.asarray(d), np.asarray(r)


class TestKnnImplEquivalence:
    @pytest.mark.parametrize("k", [1, 7])
    def test_impls_match_bruteforce(self, monkeypatch, k):
        n = 20_001  # odd: blocked/scan padding paths exercised
        lon, lat, xi, yi = _store(n)
        mesh = make_mesh(8, query_parallel=2)
        cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
        q = 6
        qx = jnp.asarray(np.linspace(-150, 150, q, dtype=np.float32))
        qy = jnp.asarray(np.linspace(-60, 60, q, dtype=np.float32))

        # referee in the SAME f32 decode the device uses
        xf, yf = _decode_f32(xi, yi)
        results = {
            impl: _run(impl, mesh, cols, n, qx, qy, k)
            for impl in IMPLS
        }
        for qi in range(q):
            d2 = (
                (xf - np.float32(qx[qi])) ** 2 + (yf - np.float32(qy[qi])) ** 2
            ).astype(np.float32)
            expect = np.sqrt(np.sort(d2)[:k].astype(np.float32))
            for impl, (d, r) in results.items():
                np.testing.assert_allclose(
                    d[qi], expect, rtol=3e-5, atol=1e-4, err_msg=impl
                )
        # rows agree across impls wherever distances strictly increase
        # (ties may legitimately resolve to different equal-distance rows)
        d_ref, r_ref = results["map"]
        for impl in ("scan", "blocked"):
            d, r = results[impl]
            for qi in range(q):
                if (np.diff(d_ref[qi]) > 1e-3).all() and d_ref[qi, 0] > 0:
                    assert set(r[qi]) == set(r_ref[qi]), impl

    def test_short_shard_padding(self, monkeypatch):
        # fewer live rows than shards*k: padded/invalid lanes must surface
        # as inf tails, never as another shard's rows
        n = 13
        lon, lat, xi, yi = _store(n, seed=3)
        mesh = make_mesh(8, query_parallel=2)
        cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
        k = 3  # <= padded shard rows (16/4): a shard top_k cannot exceed
        qx = jnp.asarray(np.zeros(2, np.float32))
        qy = jnp.asarray(np.zeros(2, np.float32))
        xf, yf = _decode_f32(xi, yi)
        d2 = ((xf - 0.0) ** 2 + (yf - 0.0) ** 2).astype(np.float32)
        expect = np.sqrt(np.sort(d2)[:k].astype(np.float32))
        for impl in IMPLS:
            d, r = _run(impl, mesh, cols, n, qx, qy, k)
            for qi in range(2):
                finite = np.isfinite(d[qi])
                np.testing.assert_allclose(
                    d[qi][finite], expect[: finite.sum()], rtol=1e-6,
                    atol=1e-7, err_msg=impl,
                )
                assert (r[qi] >= 0).all() and (r[qi] < max(n, 8)).all(), impl

    def test_fuzz_random_shapes(self, monkeypatch):
        # seeded fuzz over n (odd / pow2 / sub-block) × k × Q: the three
        # impls must return the same ascending distance vectors (fusion
        # noise band) for every trial — the repo's property-fuzz pattern
        # (tests/test_fuzz.py) applied to the KNN sweep surface
        rng = np.random.default_rng(123)
        mesh = make_mesh(8, query_parallel=2)
        for trial in range(5):
            n = int(rng.choice([257, 4096, 10_000, 65_537, 1_000]))
            k = int(rng.choice([1, 3, 16]))
            q = int(rng.choice([2, 4, 8]))
            lon, lat, xi, yi = _store(n, seed=trial)
            cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
            qx = jnp.asarray(rng.uniform(-150, 150, q).astype(np.float32))
            qy = jnp.asarray(rng.uniform(-60, 60, q).astype(np.float32))
            outs = {
                impl: _run(impl, mesh, cols, n, qx, qy, k)
                for impl in IMPLS
            }
            d_ref = outs["map"][0]
            for impl in ("scan", "blocked"):
                np.testing.assert_allclose(
                    outs[impl][0], d_ref, rtol=3e-5, atol=1e-4,
                    err_msg=f"trial={trial} impl={impl} n={n} k={k} q={q}",
                )

    def test_env_knob_selects_impl(self, monkeypatch):
        # the env knob must actually route to the chosen impl (outputs are
        # identical across impls BY DESIGN, so equality tests cannot catch a
        # knob regression — a call-counting sentinel can)
        from geomesa_tpu.parallel import query as Q

        calls = []
        real = Q._local_knn_heaps_blocked

        def sentinel(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(Q, "_local_knn_heaps_blocked", sentinel)
        n = 2_048
        lon, lat, xi, yi = _store(n, seed=2)
        mesh = make_mesh(8, query_parallel=2)
        cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
        qx = jnp.asarray(np.zeros(2, np.float32))
        qy = jnp.asarray(np.zeros(2, np.float32))
        monkeypatch.setenv("GEOMESA_KNN_IMPL", "blocked")
        make_batched_knn_step(mesh, 4)(
            cols["x"], cols["y"], jnp.int32(n), qx, qy
        )
        assert calls, "GEOMESA_KNN_IMPL=blocked did not route to the impl"
        # an explicit impl= overrides the env knob
        calls.clear()
        monkeypatch.setenv("GEOMESA_KNN_IMPL", "map")
        make_batched_knn_step(mesh, 4, impl="blocked")(
            cols["x"], cols["y"], jnp.int32(n), qx, qy
        )
        assert calls, "explicit impl='blocked' did not override the env knob"

    def test_blocked_through_ring_topology(self, monkeypatch):
        # the ppermute-ring merge consumes the same per-shard heaps — the
        # blocked impl must compose with it exactly as map does
        from geomesa_tpu.parallel.query import make_ring_knn_step

        n = 8_192
        lon, lat, xi, yi = _store(n, seed=21)
        mesh = make_mesh(8, query_parallel=2)
        cols, _, _ = shard_columns(mesh, {"x": xi, "y": yi})
        k, q = 6, 4
        qx = jnp.asarray(np.linspace(-120, 120, q, dtype=np.float32))
        qy = jnp.asarray(np.linspace(-50, 50, q, dtype=np.float32))
        monkeypatch.setenv("GEOMESA_KNN_IMPL", "map")
        d_map, _ = make_ring_knn_step(mesh, k)(
            cols["x"], cols["y"], jnp.int32(n), qx, qy
        )
        monkeypatch.setenv("GEOMESA_KNN_IMPL", "blocked")
        d_blk, _ = make_ring_knn_step(mesh, k)(
            cols["x"], cols["y"], jnp.int32(n), qx, qy
        )
        np.testing.assert_allclose(
            np.asarray(d_blk), np.asarray(d_map), rtol=3e-5, atol=1e-4
        )

    def test_knn_many_impl_passthrough(self, monkeypatch):
        # the process-layer surface threads impl down to the heap sweep
        from geomesa_tpu.geometry import Point
        from geomesa_tpu.parallel import query as Q
        from geomesa_tpu.process.knn import knn_many
        from geomesa_tpu.store.datastore import DataStore

        calls = []
        real = Q._local_knn_heaps_blocked

        def sentinel(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(Q, "_local_knn_heaps_blocked", sentinel)
        # the sentinel fires at TRACE time: memoized steps from earlier
        # tests would skip tracing, so start from a cold step cache
        Q.cached_batched_knn_step.cache_clear()
        Q.cached_ring_knn_step.cache_clear()
        ds = DataStore(backend="tpu")
        ds.create_schema("kp", "dtg:Date,*geom:Point")
        rng = np.random.default_rng(6)
        ds.write("kp", [
            {"dtg": 1_500_000_000_000, "geom": Point(
                float(rng.uniform(-10, 10)), float(rng.uniform(-10, 10)))}
            for _ in range(500)
        ])
        ds.compact("kp")  # fold the hot tier: the device path needs main
        out = knn_many(ds, "kp", [Point(0, 0), Point(5, 5)], k=4,
                       impl="blocked")
        assert calls and len(out) == 2 and all(len(t) == 4 for t, _ in out)

    def test_blocked_ttl_masking(self, monkeypatch):
        # blocked impl under the TTL signature: expired rows never surface
        n = 4_096
        lon, lat, xi, yi = _store(n, seed=9)
        rng = np.random.default_rng(4)
        bins = np.sort(rng.integers(0, 4, n)).astype(np.int32)
        offs = rng.integers(0, 1000, n).astype(np.int32)
        mesh = make_mesh(8, query_parallel=2)
        cols, _, _ = shard_columns(
            mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs}
        )
        k = 9
        cut = jnp.asarray(np.array([2, 0], np.int32))
        qx = jnp.asarray(np.zeros(2, np.float32))
        qy = jnp.asarray(np.zeros(2, np.float32))
        monkeypatch.setenv("GEOMESA_KNN_IMPL", "blocked")
        step = make_batched_knn_step(mesh, k, with_ttl=True)
        d, r = step(
            cols["x"], cols["y"], cols["bins"], cols["offs"],
            jnp.int32(n), qx, qy, cut,
        )
        d, r = np.asarray(d), np.asarray(r)
        live = bins >= 2
        xf, yf = _decode_f32(xi, yi)
        d2 = ((xf) ** 2 + (yf) ** 2).astype(np.float32)[live]
        expect = np.sqrt(np.sort(d2)[:k].astype(np.float32))
        np.testing.assert_allclose(d[0], expect, rtol=3e-5, atol=1e-4)
        assert live[r[0]].all()  # every returned row is a live row
