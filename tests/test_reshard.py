"""all_to_all resharding: device redistribution equals global sort-split
(reference: range repartitioning / spatial shuffle — SURVEY.md §2.20 P1/P2,
§5)."""

import numpy as np
import pytest

import jax

from geomesa_tpu.parallel.mesh import data_shards, make_mesh, shard_columns
from geomesa_tpu.parallel.reshard import reshard
from geomesa_tpu.store.splitter import balanced_splits


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()  # all 8 virtual CPU devices, data axis only


def _setup(mesh, n, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 60, n).astype(np.uint64)
    a = rng.integers(-1000, 1000, n).astype(np.int32)
    b = np.arange(n, dtype=np.int32)
    cols, padded, rows_per_shard = shard_columns(
        mesh, {"key": keys, "a": a, "b": b}
    )
    return keys, a, b, cols


class TestReshard:
    def test_matches_global_sort_split(self, mesh):
        n = 40_000
        keys, a, b, cols = _setup(mesh, n)
        shards = data_shards(mesh)
        splits = balanced_splits(np.sort(keys), shards)
        key_out, cols_out, counts, overflow = reshard(
            mesh, cols["key"], n, splits, {"a": cols["a"], "b": cols["b"]}
        )
        assert overflow == 0
        assert counts.sum() == n

        key_np = np.asarray(key_out)
        a_np = np.asarray(cols_out["a"])
        b_np = np.asarray(cols_out["b"])
        per = key_np.shape[0] // shards

        # referee: global sort + contiguous balanced split
        order = np.argsort(keys, kind="stable")
        gk = keys[order]
        # owner uses "number of splits <= key" (shard_of semantics), so the
        # shard boundary in the sorted referee is the first key >= split
        bounds = np.concatenate([[0], np.searchsorted(gk, splits, side="left"), [n]])
        got_all = []
        for s in range(shards):
            c = counts[s]
            sk = key_np[s * per : s * per + c]
            sa = a_np[s * per : s * per + c]
            sb = b_np[s * per : s * per + c]
            # shard owns exactly its split range, locally sorted
            np.testing.assert_array_equal(sk, gk[bounds[s] : bounds[s + 1]])
            assert np.all(np.diff(sk.astype(object)) >= 0)
            # payload rows stayed attached to their keys
            np.testing.assert_array_equal(sa, a[sb])
            got_all.append(sb)
        # every original row landed somewhere exactly once
        assert sorted(np.concatenate(got_all).tolist()) == list(range(n))

    def test_skewed_keys_overflow_reported(self, mesh):
        # all keys identical → every row routes to one shard; tiny capacity
        # must report overflow instead of silently dropping
        n = 8_000
        keys = np.full(n, 42, dtype=np.uint64)
        cols, _, _ = shard_columns(mesh, {"key": keys, "a": np.zeros(n, np.int32)})
        shards = data_shards(mesh)
        splits = (np.arange(1, shards) * 1000).astype(np.uint64)
        from geomesa_tpu.parallel.reshard import make_reshard_step

        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        step = make_reshard_step(mesh, 1, capacity=16)
        rep = NamedSharding(mesh, P())
        out = step(
            cols["key"],
            jax.device_put(jnp.int32(n), rep),
            jax.device_put(jnp.asarray(splits, dtype=np.uint64), rep),
            cols["a"],
        )
        overflow = int(out[-1])
        counts = np.asarray(out[-2])
        assert overflow > 0
        assert counts.sum() + overflow == n

    def test_empty_and_padding(self, mesh):
        # n not divisible by shards: padding rows must not be routed
        n = 1003
        keys, a, b, cols = _setup(mesh, n, seed=5)
        shards = data_shards(mesh)
        splits = balanced_splits(np.sort(keys), shards)
        _, _, counts, overflow = reshard(
            mesh, cols["key"], n, splits, {"a": cols["a"], "b": cols["b"]}
        )
        assert overflow == 0
        assert counts.sum() == n


class TestDeviceIngestLifecycle:
    """balanced_splits + reshard wired as the store-lifecycle rebalance
    (DefaultSplitter stats-driven cuts; VERDICT r1 item 6): skewed geodata
    lands balanced across the mesh, sorted per shard."""

    def _keys(self, n, hemisphere=True, seed=3):
        import geomesa_tpu  # noqa: F401
        from geomesa_tpu.curve.binned_time import BinnedTime, TimePeriod
        from geomesa_tpu.curve.sfc import z3_sfc

        rng = np.random.default_rng(seed)
        # fully skewed: every point in the western hemisphere, clustered
        lon = rng.uniform(-179, -1, n) if hemisphere else rng.uniform(-180, 180, n)
        lat = rng.normal(40, 5, n).clip(-90, 90)
        t = 1_500_000_000_000 + rng.integers(0, 6 * 86_400_000, n)
        _, offs = BinnedTime(TimePeriod.WEEK).to_bin_and_offset(t)
        return z3_sfc(TimePeriod.WEEK).index(lon, lat, offs).astype(np.uint64)

    def test_skewed_hemisphere_balanced(self):
        from geomesa_tpu.parallel.mesh import data_shards, make_mesh
        from geomesa_tpu.store.device_ingest import device_bulk_build

        n = 16_384
        keys = self._keys(n)
        rows = np.arange(n, dtype=np.int32)
        mesh = make_mesh()
        shards = data_shards(mesh)
        key_out, cols_out, counts, splits = device_bulk_build(
            mesh, keys, {"row": rows}
        )
        assert counts.sum() == n
        # balance: every shard within 10% of the ideal share
        ideal = n / shards
        assert (np.abs(counts - ideal) <= 0.10 * ideal).all(), counts
        # correctness: per-shard sorted, ranges respect splits, multiset equal
        key_np = np.asarray(key_out).reshape(shards, -1)
        row_np = np.asarray(cols_out["row"]).reshape(shards, -1)
        got_keys, got_rows = [], []
        bounds = np.concatenate([[0], np.asarray(splits, np.uint64), [2**64 - 1]])
        for d in range(shards):
            k = key_np[d, : counts[d]]
            assert (np.diff(k.astype(np.uint64)) >= 0).all()
            assert (k >= bounds[d]).all() and (k <= bounds[d + 1]).all()
            got_keys.append(k)
            got_rows.append(row_np[d, : counts[d]])
        got = np.concatenate(got_keys)
        np.testing.assert_array_equal(np.sort(got), np.sort(keys))
        # payload rode along consistently: key[row i] == original keys[i]
        allrows = np.concatenate(got_rows)
        np.testing.assert_array_equal(got, keys[allrows])

    def test_sorted_arrival_overflow_retry(self):
        # adversarial arrival order (already z-sorted): every source shard
        # sends its whole slice to one destination — exercises the
        # capacity-doubling retry loop
        from geomesa_tpu.parallel.mesh import data_shards, make_mesh
        from geomesa_tpu.store.device_ingest import device_bulk_build

        n = 4096
        keys = np.sort(self._keys(n, seed=9))
        mesh = make_mesh()
        shards = data_shards(mesh)
        key_out, cols_out, counts, splits = device_bulk_build(
            mesh, keys, {"row": np.arange(n, dtype=np.int32)}
        )
        assert counts.sum() == n
        ideal = n / shards
        assert (np.abs(counts - ideal) <= 0.10 * ideal).all(), counts
