"""all_to_all resharding: device redistribution equals global sort-split
(reference: range repartitioning / spatial shuffle — SURVEY.md §2.20 P1/P2,
§5)."""

import numpy as np
import pytest

import jax

from geomesa_tpu.parallel.mesh import data_shards, make_mesh, shard_columns
from geomesa_tpu.parallel.reshard import reshard
from geomesa_tpu.store.splitter import balanced_splits


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()  # all 8 virtual CPU devices, data axis only


def _setup(mesh, n, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 60, n).astype(np.uint64)
    a = rng.integers(-1000, 1000, n).astype(np.int32)
    b = np.arange(n, dtype=np.int32)
    cols, padded, rows_per_shard = shard_columns(
        mesh, {"key": keys, "a": a, "b": b}
    )
    return keys, a, b, cols


class TestReshard:
    def test_matches_global_sort_split(self, mesh):
        n = 40_000
        keys, a, b, cols = _setup(mesh, n)
        shards = data_shards(mesh)
        splits = balanced_splits(np.sort(keys), shards)
        key_out, cols_out, counts, overflow = reshard(
            mesh, cols["key"], n, splits, {"a": cols["a"], "b": cols["b"]}
        )
        assert overflow == 0
        assert counts.sum() == n

        key_np = np.asarray(key_out)
        a_np = np.asarray(cols_out["a"])
        b_np = np.asarray(cols_out["b"])
        per = key_np.shape[0] // shards

        # referee: global sort + contiguous balanced split
        order = np.argsort(keys, kind="stable")
        gk = keys[order]
        # owner uses "number of splits <= key" (shard_of semantics), so the
        # shard boundary in the sorted referee is the first key >= split
        bounds = np.concatenate([[0], np.searchsorted(gk, splits, side="left"), [n]])
        got_all = []
        for s in range(shards):
            c = counts[s]
            sk = key_np[s * per : s * per + c]
            sa = a_np[s * per : s * per + c]
            sb = b_np[s * per : s * per + c]
            # shard owns exactly its split range, locally sorted
            np.testing.assert_array_equal(sk, gk[bounds[s] : bounds[s + 1]])
            assert np.all(np.diff(sk.astype(object)) >= 0)
            # payload rows stayed attached to their keys
            np.testing.assert_array_equal(sa, a[sb])
            got_all.append(sb)
        # every original row landed somewhere exactly once
        assert sorted(np.concatenate(got_all).tolist()) == list(range(n))

    def test_skewed_keys_overflow_reported(self, mesh):
        # all keys identical → every row routes to one shard; tiny capacity
        # must report overflow instead of silently dropping
        n = 8_000
        keys = np.full(n, 42, dtype=np.uint64)
        cols, _, _ = shard_columns(mesh, {"key": keys, "a": np.zeros(n, np.int32)})
        shards = data_shards(mesh)
        splits = (np.arange(1, shards) * 1000).astype(np.uint64)
        from geomesa_tpu.parallel.reshard import make_reshard_step

        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        step = make_reshard_step(mesh, 1, capacity=16)
        rep = NamedSharding(mesh, P())
        out = step(
            cols["key"],
            jax.device_put(jnp.int32(n), rep),
            jax.device_put(jnp.asarray(splits, dtype=np.uint64), rep),
            cols["a"],
        )
        overflow = int(out[-1])
        counts = np.asarray(out[-2])
        assert overflow > 0
        assert counts.sum() + overflow == n

    def test_empty_and_padding(self, mesh):
        # n not divisible by shards: padding rows must not be routed
        n = 1003
        keys, a, b, cols = _setup(mesh, n, seed=5)
        shards = data_shards(mesh)
        splits = balanced_splits(np.sort(keys), shards)
        _, _, counts, overflow = reshard(
            mesh, cols["key"], n, splits, {"a": cols["a"], "b": cols["b"]}
        )
        assert overflow == 0
        assert counts.sum() == n


class TestDeviceIngestLifecycle:
    """balanced_splits + reshard wired as the store-lifecycle rebalance
    (DefaultSplitter stats-driven cuts; VERDICT r1 item 6): skewed geodata
    lands balanced across the mesh, sorted per shard."""

    def _keys(self, n, hemisphere=True, seed=3):
        import geomesa_tpu  # noqa: F401
        from geomesa_tpu.curve.binned_time import BinnedTime, TimePeriod
        from geomesa_tpu.curve.sfc import z3_sfc

        rng = np.random.default_rng(seed)
        # fully skewed: every point in the western hemisphere, clustered
        lon = rng.uniform(-179, -1, n) if hemisphere else rng.uniform(-180, 180, n)
        lat = rng.normal(40, 5, n).clip(-90, 90)
        t = 1_500_000_000_000 + rng.integers(0, 6 * 86_400_000, n)
        _, offs = BinnedTime(TimePeriod.WEEK).to_bin_and_offset(t)
        return z3_sfc(TimePeriod.WEEK).index(lon, lat, offs).astype(np.uint64)

    def test_skewed_hemisphere_balanced(self):
        from geomesa_tpu.parallel.mesh import data_shards, make_mesh
        from geomesa_tpu.store.device_ingest import device_bulk_build

        n = 16_384
        keys = self._keys(n)
        rows = np.arange(n, dtype=np.int32)
        mesh = make_mesh()
        shards = data_shards(mesh)
        key_out, cols_out, counts, splits = device_bulk_build(
            mesh, keys, {"row": rows}
        )
        assert counts.sum() == n
        # balance: every shard within 10% of the ideal share
        ideal = n / shards
        assert (np.abs(counts - ideal) <= 0.10 * ideal).all(), counts
        # correctness: per-shard sorted, ranges respect splits, multiset equal
        key_np = np.asarray(key_out).reshape(shards, -1)
        row_np = np.asarray(cols_out["row"]).reshape(shards, -1)
        got_keys, got_rows = [], []
        bounds = np.concatenate([[0], np.asarray(splits, np.uint64), [2**64 - 1]])
        for d in range(shards):
            k = key_np[d, : counts[d]]
            assert (np.diff(k.astype(np.uint64)) >= 0).all()
            assert (k >= bounds[d]).all() and (k <= bounds[d + 1]).all()
            got_keys.append(k)
            got_rows.append(row_np[d, : counts[d]])
        got = np.concatenate(got_keys)
        np.testing.assert_array_equal(np.sort(got), np.sort(keys))
        # payload rode along consistently: key[row i] == original keys[i]
        allrows = np.concatenate(got_rows)
        np.testing.assert_array_equal(got, keys[allrows])

    def test_sorted_arrival_overflow_retry(self):
        # adversarial arrival order (already z-sorted): every source shard
        # sends its whole slice to one destination — exercises the
        # capacity-doubling retry loop
        from geomesa_tpu.parallel.mesh import data_shards, make_mesh
        from geomesa_tpu.store.device_ingest import device_bulk_build

        n = 4096
        keys = np.sort(self._keys(n, seed=9))
        mesh = make_mesh()
        shards = data_shards(mesh)
        key_out, cols_out, counts, splits = device_bulk_build(
            mesh, keys, {"row": np.arange(n, dtype=np.int32)}
        )
        assert counts.sum() == n
        ideal = n / shards
        assert (np.abs(counts - ideal) <= 0.10 * ideal).all(), counts


class TestDeviceSortPerm:
    """device_sort_perm: the index-build host-lexsort replacement."""

    def test_u64_exact(self):
        from geomesa_tpu.parallel.mesh import make_mesh
        from geomesa_tpu.store.device_ingest import device_sort_perm

        rng = np.random.default_rng(11)
        keys = rng.integers(0, 2**62, 10_000, dtype=np.uint64)
        perm = device_sort_perm(make_mesh(), keys)
        np.testing.assert_array_equal(keys[perm], np.sort(keys))
        assert sorted(perm.tolist()) == list(range(len(keys)))

    def test_sentinel_key_rejected_and_host_fallback(self):
        """A route key equal to the reshard padding sentinel (all-ones u64)
        must be REJECTED, not silently dropped; the index build must fall
        back to the host sort and keep the row."""
        import pytest

        from geomesa_tpu.parallel.mesh import make_mesh
        from geomesa_tpu.store.device_ingest import device_sort_perm

        keys = np.array([5, 2**64 - 1, 9], dtype=np.uint64)
        with pytest.raises(ValueError, match="sentinel"):
            device_sort_perm(make_mesh(), keys)

        # index-side guard: bin 0xFFFF + max z routes to all-ones — the
        # build must take the host path and retain every row
        from geomesa_tpu.index.z3 import _lexsort_bin_key

        bins = np.array([65535, 3], dtype=np.int32)
        z = np.array([2**63 - 1, 17], dtype=np.uint64)

        def never(route, tie):  # device path must not be taken
            raise AssertionError("sentinel route reached the device sort")

        perm = _lexsort_bin_key(bins, z, never)
        assert sorted(perm.tolist()) == [0, 1]

    def test_wide_composite_exact(self):
        """(bin, 63-bit z) via coarse route + 15-bit tiebreak must equal the
        host lexsort's sorted products exactly (adversarial: many values
        share route keys so the tiebreak column does real work)."""
        from geomesa_tpu.parallel.mesh import make_mesh
        from geomesa_tpu.store.device_ingest import device_sort_perm

        rng = np.random.default_rng(12)
        n = 8_192
        bins = rng.integers(0, 5, n).astype(np.int32)
        base = rng.integers(0, 2**48, n // 16, dtype=np.uint64)
        z = (np.repeat(base, 16) << np.uint64(15)) | rng.integers(
            0, 2**15, n, dtype=np.uint64
        )
        route = (bins.astype(np.uint64) << np.uint64(48)) | (z >> np.uint64(15))
        tie = (z & np.uint64(0x7FFF)).astype(np.int32)
        perm = device_sort_perm(make_mesh(), route, tie)
        want = np.lexsort((z, bins))
        np.testing.assert_array_equal(bins[perm], bins[want])
        np.testing.assert_array_equal(z[perm], z[want])


class TestDeviceSortThroughDataStore:
    """VERDICT r2 item 4: the PUBLIC ingest/compact path reaches reshard with
    stats-driven splits when the backend is TPU."""

    def _ingest(self, monkeypatch, n=6_000, skew=True):
        import geomesa_tpu  # noqa: F401
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.store.datastore import DataStore

        monkeypatch.setenv("GEOMESA_DEVICE_SORT_MIN_ROWS", "1")
        rng = np.random.default_rng(13)
        lon = rng.uniform(-179, -1, n) if skew else rng.uniform(-180, 180, n)
        lat = rng.normal(40, 5, n).clip(-90, 90)
        t = 1_500_000_000_000 + rng.integers(0, 6 * 86_400_000, n)
        recs = [
            {"dtg": int(t[i]), "geom": Point(lon[i], lat[i])} for i in range(n)
        ]
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("evt", "dtg:Date,*geom:Point"))
        ds.write("evt", recs, fids=[str(i) for i in range(n)])
        return ds, lon, lat, t

    def test_compact_uses_device_sort(self, monkeypatch):
        import geomesa_tpu.store.device_ingest as di

        calls = []
        real = di.device_sort_perm

        def spy(mesh, route, tie=None):
            calls.append(len(route))
            return real(mesh, route, tie)

        monkeypatch.setattr(di, "device_sort_perm", spy)
        ds, lon, lat, t = self._ingest(monkeypatch)
        ds.compact("evt")
        assert calls, "public compact() never reached the device sample sort"

        # parity: device-sorted store answers exactly like the oracle
        from geomesa_tpu.store.datastore import DataStore

        q = (
            "BBOX(geom, -120, 30, -60, 50) AND dtg DURING "
            "2017-07-14T12:00:00.000Z/2017-07-17T06:30:00.500Z"
        )
        got = set(ds.query("evt", q).table.fids.tolist())
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.schema.sft import parse_spec

        n = len(lon)
        oracle = DataStore(backend="oracle")
        oracle.create_schema(parse_spec("evt", "dtg:Date,*geom:Point"))
        oracle.write(
            "evt",
            [{"dtg": int(t[i]), "geom": Point(lon[i], lat[i])}
             for i in range(n)],
            fids=[str(i) for i in range(n)],
        )
        oracle.compact("evt")
        assert got == set(oracle.query("evt", q).table.fids.tolist())

    def test_device_sorted_products_match_host(self, monkeypatch):
        """The z3 index built through the device sorter has IDENTICAL sorted
        key products to the host build (perm may permute exact ties)."""
        ds, lon, lat, t = self._ingest(monkeypatch, n=4_000)
        ds.compact("evt")
        dev_idx = ds._state("evt").indices["z3"]

        from geomesa_tpu.index.z3 import Z3Index

        host_idx = Z3Index(ds.get_schema("evt"))
        host_idx.build(ds._state("evt").table)
        np.testing.assert_array_equal(dev_idx.bins, host_idx.bins)
        np.testing.assert_array_equal(dev_idx.zs, host_idx.zs)
        np.testing.assert_array_equal(dev_idx.offsets, host_idx.offsets)

    def test_sort_failure_degrades_to_host(self, monkeypatch):
        import geomesa_tpu.store.device_ingest as di

        def boom(mesh, route, tie=None):
            raise RuntimeError("device transfer failed")

        monkeypatch.setattr(di, "device_sort_perm", boom)
        ds, lon, lat, t = self._ingest(monkeypatch, n=2_000)
        ds.compact("evt")  # must not raise: host sort serves
        assert ds.query("evt", "BBOX(geom, -179, -90, 0, 90)").count == 2_000
        # circuit tripped: the next rebuild skips the device sorter
        assert not ds._device_available()
