"""Serving-plane tests (ISSUE 12): per-tenant admission control,
request coalescing into batched device dispatch, and consistent-hash
sharded federation. See docs/serving.md."""

import io
import json
import threading
import urllib.error

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.obs import usage
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.serving.admission import AdmissionController
from geomesa_tpu.serving.coalesce import Coalescer
from geomesa_tpu.serving.shards import ShardedDataStoreView, ShardRouter
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.utils.metrics import MetricsRegistry
from geomesa_tpu.web import GeoMesaApp

T0 = 1_500_000_000_000
SPEC = "name:String,dtg:Date,*geom:Point"


def call(app, method, path, query="", body=None, headers=None):
    raw = json.dumps(body).encode() if body is not None else b""
    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": query,
        "CONTENT_LENGTH": str(len(raw)),
        "wsgi.input": io.BytesIO(raw),
        **(headers or {}),
    }
    out = {}

    def start_response(status, headers_):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(headers_)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


def make_store(n=200, seed=5, compacted=True):
    ds = DataStore(backend="tpu")
    ds.create_schema("pts", SPEC)
    rng = np.random.default_rng(seed)
    ds.write("pts", [
        {"name": f"n{i % 3}", "dtg": T0 + i * 1000,
         "geom": Point(float(rng.uniform(-170, 170)),
                       float(rng.uniform(-60, 60)))}
        for i in range(n)
    ], fids=[f"f{i}" for i in range(n)])
    if compacted:
        ds.compact("pts")
    return ds


@pytest.fixture()
def meter():
    """A fresh process usage meter (restored afterwards) so admission /
    metering assertions see only this test's traffic."""
    m = usage.UsageMeter(k=8)
    prev = usage.install(m)
    yield m
    usage.install(prev)


# -- admission control --------------------------------------------------------

class TestAdmission:
    def _controller(self, meter, **kw):
        kw.setdefault("rate_qps", 10.0)
        kw.setdefault("burst", 4.0)
        kw.setdefault("min_rate_qps", 0.5)
        kw.setdefault("meter", meter)
        kw.setdefault("metrics", MetricsRegistry())
        return AdmissionController(**kw)

    def test_token_bucket_refill_deterministic(self, meter):
        """Clock-free deterministic time injection: refill is exactly
        rate * dt, Retry-After is the time to re-cross the reserve."""
        t = [0.0]
        ac = self._controller(meter, clock=lambda: t[0])
        # burst 4, high reserve 0: 4 admits drain to zero, the 5th sheds
        admits = [ac.admit("a", "high").admitted for _ in range(5)]
        assert admits == [True, True, True, True, False]
        d = ac.admit("a", "high")
        assert not d.admitted and d.reason == "rate"
        # need 1 token at 10/s => 0.1 s
        assert d.retry_after_s == pytest.approx(0.1, rel=1e-6)
        t[0] += 0.05  # half a token back: still shed
        assert not ac.admit("a", "high").admitted
        t[0] += 0.1  # now > 1 token
        assert ac.admit("a", "high").admitted

    def test_priority_shed_order_no_inversion(self, meter):
        """Low sheds first; a high-priority request is NEVER shed while
        low-priority traffic is still being admitted."""
        t = [0.0]
        ac = self._controller(meter, rate_qps=10.0, burst=10.0,
                              clock=lambda: t[0])
        order = []
        # alternate low/high until both classes shed
        for i in range(40):
            pri = "low" if i % 2 == 0 else "high"
            d = ac.admit("a", pri)
            order.append((pri, d.admitted))
        first_high_shed = next(
            (i for i, (p, a) in enumerate(order)
             if p == "high" and not a), None)
        last_low_admit = max(
            (i for i, (p, a) in enumerate(order) if p == "low" and a),
            default=-1)
        assert first_high_shed is not None  # bucket fully drained
        assert last_low_admit < first_high_shed
        # and low started shedding strictly before high did
        first_low_shed = next(
            i for i, (p, a) in enumerate(order) if p == "low" and not a)
        assert first_low_shed < first_high_shed

    def test_slo_budget_scales_refill(self, meter):
        """Refill rate is tied to the tenant's live tenant.query error
        budget: a burned tenant refills at the floor, others at full
        rate — the ISSUE 11 substrate consumed as designed."""
        for _ in range(50):
            meter.observe("hog", "pts", "sig", wall_ms=5.0, ok=False)
        for _ in range(50):
            meter.observe("polite", "pts", "sig", wall_ms=5.0, ok=True)
        ac = self._controller(meter)
        assert ac.budget_remaining("hog") == 0.0
        assert ac.budget_remaining("polite") == 1.0
        assert ac.effective_rate("hog") == pytest.approx(0.5)  # the floor
        assert ac.effective_rate("polite") == pytest.approx(10.0)

    def test_shed_lands_in_counters_flight_and_usage(self, meter):
        from geomesa_tpu.obs import flight as _flight

        t = [0.0]
        ac = self._controller(meter, burst=2.0, clock=lambda: t[0])
        before = _flight.get().record_count
        budget_before = meter.slo.tracker(
            "tenant.query", "a").budget_remaining(300.0)
        for _ in range(5):
            ac.admit("a", "normal")
        assert ac.shed_count > 0
        m = ac.metrics
        assert m.counters["serving.admission.shed"].count == ac.shed_count
        assert m.counters["serving.admission.admitted"].count == \
            ac.admitted_count
        # flight records with the shed anomaly, attributed to the tenant
        recs = [r for r in _flight.get().records()
                if r.op == "admission" and r.tenant == "a"]
        assert recs and _flight.A_SHED in recs[-1].anomalies
        assert _flight.get().record_count > before
        # usage counters carry the shed under its own signature...
        snap = meter.snapshot()
        assert any(h["signature"] == "admission.shed"
                   for h in snap["heavy_hitters"])
        # ...WITHOUT burning the tenant's SLO (no lock-out feedback loop)
        assert meter.slo.tracker("tenant.query", "a").budget_remaining(
            300.0) == budget_before
        # prometheus series present with bounded labels
        text = ac.prometheus_text()
        assert "geomesa_admission_shed_total" in text
        assert 'geomesa_admission_shed_tenant_total{tenant="a"}' in text

    def test_web_429_with_retry_after(self, meter):
        ds = make_store(n=20)
        t = [0.0]
        ac = self._controller(meter, rate_qps=2.0, burst=2.0,
                              metrics=ds.metrics, clock=lambda: t[0])
        app = GeoMesaApp(ds, admission=ac, coalesce_ms=0)
        # drain, then shed
        statuses = []
        for _ in range(5):
            s, h, _b = call(app, "GET", "/api/schemas/pts/query",
                            headers={"HTTP_X_GEOMESA_TENANT": "a"})
            statuses.append((s, h))
        assert statuses[0][0] == 200
        shed = [(s, h) for s, h in statuses if s == 429]
        assert shed
        ra = shed[0][1].get("Retry-After")
        assert ra is not None and int(ra) >= 1
        # ops surfaces are exempt: the operator can still see the shed
        s, _h, b = call(app, "GET", "/api/metrics",
                        headers={"HTTP_X_GEOMESA_TENANT": "a"})
        assert s == 200
        assert json.loads(b)["admission"]["shed"] >= 1

    def test_remote_429_typed_and_never_retried(self, meter):
        """Satellite: 429 surfaces as RateLimitedError carrying the
        server's Retry-After, classified NON-retryable — a shedding
        member costs exactly ONE round trip (no retry storm)."""
        import wsgiref.simple_server
        from wsgiref.simple_server import make_server

        from geomesa_tpu.resilience.policy import (
            RateLimitedError,
            RetryPolicy,
            retryable,
        )
        from geomesa_tpu.store.remote import RemoteDataStore

        class Quiet(wsgiref.simple_server.WSGIRequestHandler):
            def log_message(self, *a):
                pass

        ds = make_store(n=10)
        t = [0.0]
        ac = self._controller(meter, rate_qps=1.0, burst=1.0,
                              clock=lambda: t[0])
        app = GeoMesaApp(ds, admission=ac, coalesce_ms=0)
        hits = [0]

        def counting(environ, sr):
            if "/query" in environ.get("PATH_INFO", ""):
                hits[0] += 1
            return app(environ, sr)

        httpd = make_server("127.0.0.1", 0, counting,
                            handler_class=Quiet)
        port = httpd.server_address[1]
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        try:
            rs = RemoteDataStore(
                f"http://127.0.0.1:{port}",
                retry=RetryPolicy(max_attempts=4, base_delay_s=0.001))
            ac.admit("x")  # drain the 1-token anonymous... (own bucket)
            # anonymous bucket: burst 1, normal reserve 0.1 — sheds
            with pytest.raises(RateLimitedError) as ei:
                rs.query("pts", "BBOX(geom,0,0,1,1)")
            assert ei.value.retry_after_s >= 1.0
            assert hits[0] == 1  # ONE attempt: classified non-retryable
        finally:
            httpd.shutdown()
        # the classification contract, pinned directly
        err = urllib.error.HTTPError("http://x", 429, "shed", None, None)
        assert not retryable(err, idempotent=True)
        assert not retryable(err, idempotent=False)
        assert not retryable(
            RateLimitedError("http://x", 2.0), idempotent=True)

    def test_priority_header_unknown_is_normal(self, meter):
        ac = self._controller(meter)
        d = ac.admit("a", "super-extra-vip")
        assert d.priority == "normal"


# -- request coalescing -------------------------------------------------------

def _concurrent(app, reqs, window_warm_s=0.0):
    """Fire reqs = [(path, query, headers)] concurrently after a
    barrier; returns results in request order."""
    results = [None] * len(reqs)
    barrier = threading.Barrier(len(reqs))

    def go(i, path, query, headers):
        barrier.wait()
        results[i] = call(app, "GET", path, query=query, headers=headers)

    threads = [
        threading.Thread(target=go, args=(i, p, q, h))
        for i, (p, q, h) in enumerate(reqs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


CQLS = ["BBOX(geom,-50,-50,50,50)", "name='n1'", "BBOX(geom,0,0,90,60)",
        None]


def _qs(cql):
    return "" if cql is None else "cql=" + cql.replace(" ", "%20")


class TestCoalesce:
    def test_concurrent_requests_share_one_dispatch_byte_identical(self):
        ds = make_store()
        serial_app = GeoMesaApp(ds, coalesce_ms=0)
        serial = {}
        for cql in CQLS:
            s, _h, b = call(serial_app, "GET", "/api/schemas/pts/query",
                            query=_qs(cql))
            assert s == 200
            serial[cql] = b
        app = GeoMesaApp(ds, coalesce_ms=250.0)
        reqs = [("/api/schemas/pts/query", _qs(CQLS[i % len(CQLS)]), None)
                for i in range(8)]
        results = _concurrent(app, reqs)
        for i, (s, _h, b) in enumerate(results):
            assert s == 200
            assert b == serial[CQLS[i % len(CQLS)]]  # byte-identical
        c = app.coalescer
        assert c.query_count == 8
        assert c.dispatch_count < c.query_count  # FEWER dispatches
        assert c.max_width > 1  # coalescing observed

    def test_two_tenant_coalesce_meters_each_tenant(self, meter):
        """Satellite: a coalesced dispatch meters rows/wall per member
        query against ITS tenant — not the batch leader's."""
        import time as _time

        ds = make_store()
        # expected per-tenant row counts from uncoalesced execution
        expected = {
            "acme": ds.query("pts", CQLS[0]).count,
            "globex": ds.query("pts", CQLS[1]).count,
        }

        class SlowFirst:
            """First dispatch stalls so the two tenant requests gather
            into ONE batch behind it (backpressure batching made
            deterministic)."""

            def __init__(self, inner):
                self._inner = inner
                self.n = 0

            def query(self, *a, **k):
                self.n += 1
                if self.n == 1:
                    _time.sleep(0.25)
                return self._inner.query(*a, **k)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        slow = SlowFirst(ds)
        app = GeoMesaApp(slow, coalesce_ms=500.0)
        # occupy the key with an in-flight (slow) dispatch...
        opener = threading.Thread(
            target=call, args=(app, "GET", "/api/schemas/pts/query"),
            kwargs={"query": _qs(CQLS[2])})
        opener.start()
        _time.sleep(0.05)
        # ...so both tenants land in the gathering batch behind it
        reqs = [
            ("/api/schemas/pts/query", _qs(CQLS[0]),
             {"HTTP_X_GEOMESA_TENANT": "acme"}),
            ("/api/schemas/pts/query", _qs(CQLS[1]),
             {"HTTP_X_GEOMESA_TENANT": "globex"}),
        ]
        results = _concurrent(app, reqs)
        opener.join()
        assert all(s == 200 for s, _h, _b in results)
        assert app.coalescer.max_width == 2  # ONE dispatch served both
        snap = meter.snapshot()
        rows = {t["tenant"]: t["lifetime"]["rows"]
                for t in snap["tenants"]}
        assert rows.get("acme") == expected["acme"]
        assert rows.get("globex") == expected["globex"]
        queries = {t["tenant"]: t["lifetime"]["queries"]
                   for t in snap["tenants"]}
        assert queries.get("acme") == 1 and queries.get("globex") == 1

    def test_deadline_too_tight_bypasses_window(self):
        from geomesa_tpu.utils.timeouts import Deadline

        ds = make_store(n=30)
        co = Coalescer(ds, window_s=0.2)
        q = Query(filter=None, hints={"deadline": Deadline.after_ms(50)})
        r = co.submit("pts", "select", q)
        assert r.count == 30
        assert co.dispatch_count == 0  # never entered a batch
        assert co.metrics.counters[
            "serving.coalesce.bypass_deadline"].count == 1

    def test_width_one_keeps_individual_plan_audit(self):
        """A width-1 'batch' must run the ordinary query path so the
        adaptive planner's cost table keeps training on web traffic."""
        from geomesa_tpu.obs import devmon

        prev = devmon.install(devmon.ResidencyLedger(),
                              devmon.CostTable())
        try:
            ds = make_store()
            app = GeoMesaApp(ds, coalesce_ms=20.0)
            s, _h, _b = call(app, "GET", "/api/schemas/pts/query",
                             query=_qs(CQLS[0]))
            assert s == 200
            assert devmon.costs().snapshot()["entry_count"] >= 1
        finally:
            devmon.install(*prev)

    def test_count_and_aggregate_ops_parity(self):
        ds = make_store()
        co = Coalescer(ds, window_s=0.0)  # window off: direct singles
        assert co.window_s == 0.0
        co = Coalescer(ds, window_s=0.001)
        got = co.submit("pts", "count", Query(filter=CQLS[0]), loose=False)
        assert got == ds.count_many("pts", [CQLS[0]], loose=False)[0]
        got = co.submit("pts", "aggregate", Query(filter=None),
                        group_by=["name"])
        ref = ds.aggregate_many("pts", [None], group_by=["name"])[0]
        assert got is not None and ref is not None
        assert sorted(got["groups"]) == sorted(ref["groups"])
        assert got["count"].sum() == ref["count"].sum()

    def test_leader_error_propagates_to_every_waiter(self):
        ds = make_store(n=10)
        app = GeoMesaApp(ds, coalesce_ms=200.0)
        reqs = [("/api/schemas/nope/query", "", None) for _ in range(3)]
        results = _concurrent(app, reqs)
        assert all(s == 404 for s, _h, _b in results)

    def test_store_without_batched_surface_executes_singly(self):
        from geomesa_tpu.store.merged import MergedDataStoreView

        ds = make_store(n=40)
        view = MergedDataStoreView([ds])
        app = GeoMesaApp(view, coalesce_ms=20.0)
        s, _h, b = call(app, "GET", "/api/schemas/pts/query",
                        query=_qs(CQLS[0]))
        assert s == 200
        assert app.coalescer.dispatch_count == 0


# -- shard router + sharded federation ---------------------------------------

def _sft():
    from geomesa_tpu.schema.sft import parse_spec

    return parse_spec("pts", SPEC)


class TestShardRouter:
    def test_partition_total_and_deterministic(self):
        r1 = ShardRouter([0, 1, 2], n_shards=12)
        r2 = ShardRouter([0, 1, 2], n_shards=12)
        assert r1.shard_member == r2.shard_member  # no hash randomization
        rng = np.random.default_rng(3)
        keys = r1.keys_for(rng.uniform(-180, 180, 500),
                           rng.uniform(-90, 90, 500))
        shards = r1.shards_of_keys(keys)
        assert shards.min() >= 0 and shards.max() < 12
        # every shard owned by exactly one member
        assert len(r1.shard_member) == 12
        assert set(r1.shard_member) <= {0, 1, 2}

    def test_members_dedupe_fixes_double_count(self):
        """Red/green (satellite 1): several Z-prefix shard ranges map to
        the SAME member — the fan-out must hit that member ONCE. A
        per-shard fan-out would double-count every row it holds."""
        from geomesa_tpu.filter.cql import parse

        r = ShardRouter([0, 1], n_shards=16)
        sft = _sft()
        # a box wide enough to intersect many shards on both members
        members = r.members_for_filter(
            parse("BBOX(geom,-170,-80,170,80)"), sft)
        shards = r.shards_for_boxes([(-170.0, -80.0, 170.0, 80.0)])
        assert len(shards) > 2  # several shards intersected...
        assert members is not None
        assert len(members) == len(set(members)) <= 2  # ...members deduped
        # and end-to-end: a whole-domain count equals the true row count
        stores = [make_store(n=0, compacted=False) for _ in range(2)]
        view = ShardedDataStoreView(stores, n_shards=16)
        rng = np.random.default_rng(9)
        view.write("pts", [
            {"name": "n", "dtg": T0,
             "geom": Point(float(rng.uniform(-170, 170)),
                           float(rng.uniform(-60, 60)))}
            for i in range(120)
        ], fids=[f"d{i}" for i in range(120)])
        assert view.query(
            "pts", "BBOX(geom,-170,-80,170,80)").count == 120
        assert view.stats_count("pts") == 120

    def test_consistent_hash_minimal_movement(self):
        r = ShardRouter(["a", "b", "c"], n_shards=64)
        r2 = r.with_members(["a", "b"])  # c departs
        moved = [
            s for s in range(64)
            if r.shard_member[s] != r2.shard_member[s]
        ]
        # only c's shards move; a/b keep everything they owned
        assert all(r.shard_member[s] == "c" for s in moved)
        assert {r.shard_member[s] for s in range(64)} == {"a", "b", "c"}

    def test_fid_and_attr_filters_fan_everywhere_disjoint_nowhere(self):
        from geomesa_tpu.filter import ast
        from geomesa_tpu.filter.cql import parse

        r = ShardRouter([0, 1, 2], n_shards=12)
        sft = _sft()
        assert r.members_for_filter(
            ast.FidIn(("f1",)), sft) is None  # fid: all members
        assert r.members_for_filter(parse("name='x'"), sft) is None
        assert r.members_for_filter(None, sft) is None
        disjoint = parse(
            "BBOX(geom,10,10,20,20) AND BBOX(geom,30,30,40,40)")
        assert r.members_for_filter(disjoint, sft) == []

    def test_routed_view_deterministic_under_shard_router(self):
        """Satellite 1 (route fallback audit): with a shard router
        configured, fid filters still route to the id store and
        attribute-only filters to their attribute route — repeatably —
        while single-owner spatial filters route to the owner member."""
        from geomesa_tpu.filter import ast
        from geomesa_tpu.filter.cql import parse
        from geomesa_tpu.store.routed import RoutedDataStoreView

        id_store = make_store(n=5, seed=1, compacted=False)
        attr_store = make_store(n=5, seed=2, compacted=False)
        geo_store = make_store(n=5, seed=3, compacted=False)
        router = ShardRouter([0, 1, 2], n_shards=12)
        view = RoutedDataStoreView(
            [(id_store, ["id"]), (attr_store, [["name"]]),
             (geo_store, [[]])],
            shard_router=router,
        )
        for _ in range(3):  # deterministic: identical every time
            assert view.route(
                ast.FidIn(("f1",)), "pts") is id_store
            assert view.route(parse("name='n1'"), "pts") is attr_store
            # unconstrained: include store
            assert view.route(None, "pts") is geo_store
        f = parse("BBOX(geom,10,10,11,11)")
        owner = router.members_for_filter(f, _sft())
        assert owner is not None and len(owner) == 1
        for _ in range(3):
            assert view.route(f, "pts") is view.stores[owner[0]]


class _CountingStore:
    """Delegating wrapper counting query fan-outs."""

    def __init__(self, ds):
        self._ds = ds
        self.queries = 0

    def query(self, *a, **k):
        self.queries += 1
        return self._ds.query(*a, **k)

    def __getattr__(self, name):
        return getattr(self._ds, name)


class TestShardedView:
    def _mk(self, n=300, members=3, n_shards=12, **kw):
        stores = [DataStore(backend="tpu") for _ in range(members)]
        view = ShardedDataStoreView(stores, n_shards=n_shards, **kw)
        view.create_schema("pts", SPEC)
        rng = np.random.default_rng(5)
        recs = [
            {"name": f"n{i % 3}", "dtg": T0 + i * 1000,
             "geom": Point(float(rng.uniform(-170, 170)),
                           float(rng.uniform(-60, 60)))}
            for i in range(n)
        ]
        view.write("pts", recs, fids=[f"f{i}" for i in range(n)])
        view.compact("pts")
        return view, stores, recs

    def test_write_partitions_each_row_exactly_once(self):
        view, stores, recs = self._mk()
        per = [int(s.stats_count("pts")) for s in stores]
        assert sum(per) == 300
        assert all(p > 0 for p in per)  # every member carries load
        fid_sets = [set(s.query("pts").table.fids.tolist())
                    for s in stores]
        for i in range(len(fid_sets)):
            for j in range(i + 1, len(fid_sets)):
                assert not (fid_sets[i] & fid_sets[j])  # disjoint

    def test_read_parity_with_unsharded_reference(self):
        view, stores, recs = self._mk()
        ref = DataStore(backend="tpu")
        ref.create_schema("pts", SPEC)
        ref.write("pts", recs, fids=[f"f{i}" for i in range(300)])
        ref.compact("pts")
        for cql in CQLS:
            got = view.query("pts", cql)
            want = ref.query("pts", cql)
            assert got.count == want.count
            assert (sorted(got.table.fids.tolist())
                    == sorted(want.table.fids.tolist()))
        # batched surfaces
        got_many = view.select_many("pts", CQLS)
        want_many = [ref.query("pts", c) for c in CQLS]
        for g, w in zip(got_many, want_many):
            assert sorted(g.table.fids.tolist()) == sorted(
                w.table.fids.tolist())
        assert view.count_many("pts", CQLS, loose=False) == [
            w.count for w in want_many]
        ga = view.aggregate_many("pts", [None], group_by=["name"])[0]
        wa = ref.aggregate_many("pts", [None], group_by=["name"])[0]
        assert ga is not None and wa is not None
        assert dict(zip([g[0] for g in ga["groups"]],
                        ga["count"].tolist())) == \
            dict(zip([g[0] for g in wa["groups"]],
                     wa["count"].tolist()))
        # sort/limit re-applied at the view level
        page = view.query("pts", Query(filter=None, sort_by=("dtg", False),
                                       limit=10, start_index=5))
        rpage = ref.query("pts", Query(filter=None, sort_by=("dtg", False),
                                       limit=10, start_index=5))
        assert page.table.fids.tolist() == rpage.table.fids.tolist()

    def test_fanout_prunes_to_intersecting_members(self):
        stores = [_CountingStore(DataStore(backend="tpu"))
                  for _ in range(3)]
        view = ShardedDataStoreView(stores, n_shards=12)
        view.create_schema("pts", SPEC)
        rng = np.random.default_rng(5)
        view.write("pts", [
            {"name": "n", "dtg": T0,
             "geom": Point(float(rng.uniform(-170, 170)),
                           float(rng.uniform(-60, 60)))}
            for i in range(100)
        ], fids=[f"f{i}" for i in range(100)])
        for s in stores:
            s.queries = 0
        # a tiny box: strictly fewer members than the full set
        sub = view._member_subset(
            "pts", Query(filter="BBOX(geom,10,10,10.5,10.5)")
            .resolved_filter())
        assert sub is not None and 1 <= len(sub) < 3
        view.query("pts", "BBOX(geom,10,10,10.5,10.5)")
        assert sum(s.queries for s in stores) == len(sub)
        # attribute-only: all members (rows could be anywhere)
        for s in stores:
            s.queries = 0
        view.query("pts", "name='n'")
        assert sum(s.queries for s in stores) == 3
        # provably disjoint: NO fan-out at all
        for s in stores:
            s.queries = 0
        r = view.query(
            "pts", "BBOX(geom,10,10,20,20) AND BBOX(geom,30,30,40,40)")
        assert r.count == 0
        assert sum(s.queries for s in stores) == 0

    def test_wkt_geometries_place_by_coordinates(self):
        """Red/green: WKT strings are accepted anywhere a geometry is
        (the columnar tier's convention) — the shard writer must place
        them by their coordinates, not the fid hash, or pruned spatial
        reads can never reach the row."""
        stores = [DataStore(backend="tpu") for _ in range(3)]
        view = ShardedDataStoreView(stores, n_shards=12)
        view.create_schema("pts", SPEC)
        view.write("pts", [
            {"name": "w", "dtg": T0, "geom": "POINT (10 10)"},
            {"name": "w", "dtg": T0, "geom": "POINT (-120 40)"},
        ], fids=["wa", "wb"])
        # the narrow box prunes fan-out to the coordinate's shard owner
        # — the row must be there
        assert view.query("pts", "BBOX(geom,9,9,11,11)").count == 1
        assert view.query("pts", "BBOX(geom,-121,39,-119,41)").count == 1

    def test_extended_geometries_fan_everywhere(self):
        """Red/green: rows partition by envelope-CENTER key, so a query
        box can intersect a polygon whose center shard lies far outside
        the box's Z-ranges — non-point types must fan out to ALL
        members or matching rows silently vanish."""
        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.schema.sft import parse_spec

        spec = "name:String,*geom:Polygon;geomesa.xz.precision='10'"
        stores = [DataStore(backend="tpu") for _ in range(3)]
        view = ShardedDataStoreView(stores, n_shards=12)
        view.create_schema("poly", spec)
        # a wide polygon: center x=50, but it reaches x=0
        view.write("poly", [{
            "name": "wide",
            "geom": Polygon(np.array(
                [[0.0, -10.0], [100.0, -10.0], [100.0, 10.0],
                 [0.0, 10.0]])),
        }], fids=["w1"])
        sft = parse_spec("poly", spec)
        router = view.router
        f = Query(filter="BBOX(geom,0,-10,5,10)").resolved_filter()
        # the fix: non-point schemas never prune the fan-out...
        assert router.members_for_filter(f, sft) is None
        # ...so a query box far from the center still finds the row
        assert view.query("poly", "BBOX(geom,0,-10,5,10)").count == 1
        # disjoint filters still fan nowhere
        assert router.members_for_filter(
            Query(filter="BBOX(geom,10,10,20,20) AND "
                         "BBOX(geom,30,30,40,40)").resolved_filter(),
            sft) == []

    def test_disjoint_density_keeps_grid_shape(self):
        """A provably-disjoint filter must still answer a density query
        with a ZERO GRID (the channel's shape), not a table-shaped
        empty result with density=None."""
        view, stores, recs = self._mk(n=50)
        r = view.query("pts", Query(
            filter="BBOX(geom,10,10,20,20) AND BBOX(geom,30,30,40,40)",
            hints={"density": {"width": 8, "height": 8}}))
        assert r.density is not None
        assert np.asarray(r.density).shape == (8, 8)
        assert float(np.asarray(r.density).sum()) == 0.0

    def test_partial_mode_degrades_on_member_failure(self):
        view, stores, recs = self._mk(members=3,
                                      on_member_error="partial")

        class Boom:
            def __getattr__(self, name):
                if name in ("query", "select_many", "count_many",
                            "stats_count"):
                    def _fail(*a, **k):
                        raise ConnectionError("member down")
                    return _fail
                return getattr(stores[0], name)

        total = view.query("pts").count
        dead_rows = stores[0].stats_count("pts")
        view.stores[0] = (Boom(), None)
        r = view.query("pts")
        assert r.degraded and r.member_errors
        assert r.count == total - dead_rows
        # batched surfaces degrade the same way
        out = view.select_many("pts", [None])[0]
        assert out.degraded and out.count == total - dead_rows
        assert view.count_many("pts", [None], loose=False)[0] == \
            total - dead_rows
        # fail mode: the same failure raises
        view.on_member_error = "fail"
        with pytest.raises(ConnectionError):
            view.query("pts")


# -- the end-to-end serving pin ----------------------------------------------

class TestEndToEndServing:
    def test_coalesce_shed_and_usage_reconcile(self, meter):
        """The acceptance pin: concurrent HTTP queries from 3 tenants
        coalesce into fewer device dispatches than queries, results are
        byte-identical to uncoalesced serial execution, per-tenant usage
        totals reconcile, and with one tenant driven past its SLO budget
        ONLY that tenant's requests shed (429)."""
        ds = make_store()
        serial_app = GeoMesaApp(ds, coalesce_ms=0)
        serial = {}
        for cql in CQLS[:3]:
            s, _h, b = call(serial_app, "GET", "/api/schemas/pts/query",
                            query=_qs(cql))
            serial[cql] = b
        expected_rows = {cql: ds.query("pts", cql).count
                         for cql in CQLS[:3]}

        t = [0.0]
        ac = AdmissionController(
            rate_qps=100.0, burst=100.0, min_rate_qps=0.25,
            meter=meter, metrics=ds.metrics, clock=lambda: t[0])
        app = GeoMesaApp(ds, admission=ac, coalesce_ms=250.0)
        tenants = ["t-a", "t-b", "t-c"]
        base_queries = meter.snapshot()["observe_count"]
        reqs = [
            ("/api/schemas/pts/query", _qs(CQLS[i % 3]),
             {"HTTP_X_GEOMESA_TENANT": tenants[i % 3]})
            for i in range(9)
        ]
        results = _concurrent(app, reqs)
        # every query answered, byte-identical to serial execution
        for i, (s, _h, b) in enumerate(results):
            assert s == 200
            assert b == serial[CQLS[i % 3]]
        c = app.coalescer
        assert c.query_count == 9 and c.dispatch_count < 9
        assert c.max_width > 1
        # per-tenant usage totals reconcile exactly
        snap = meter.snapshot()
        per = {x["tenant"]: x["lifetime"] for x in snap["tenants"]}
        for i, tn in enumerate(tenants):
            want = sum(expected_rows[CQLS[j % 3]]
                       for j in range(9) if j % 3 == i)
            assert per[tn]["rows"] == want
            assert per[tn]["queries"] == 3
            assert per[tn]["bytes_out"] > 0  # web egress attribution
        # drive t-c past its SLO budget: its refill collapses to the
        # floor and its burst is gone after the next few requests
        for _ in range(100):
            meter.observe("t-c", "pts", "sig", wall_ms=5.0, ok=False)
        with ac._lock:
            ac._buckets["t-c"].tokens = 0.0  # burst already spent
        codes = {}
        for tn in tenants:
            s, _h, _b = call(app, "GET", "/api/schemas/pts/query",
                             query=_qs(CQLS[0]),
                             headers={"HTTP_X_GEOMESA_TENANT": tn})
            codes[tn] = s
        assert codes["t-c"] == 429  # only the over-budget tenant sheds
        assert codes["t-a"] == 200 and codes["t-b"] == 200
        assert meter.snapshot()["observe_count"] > base_queries
