"""SQL layer tests: pushdown, projection, scalar UDFs, aggregates, GROUP BY
(reference: geomesa-spark-sql — SURVEY.md §2.14/§3.5)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.sql import SqlResult, sql
from geomesa_tpu.sql.engine import SqlError, _rewrite_where
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(11)
    n = 2000
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-60, 60, n)
    t = T0 + rng.integers(0, 5 * 86_400_000, n)
    recs = [
        {
            "name": f"c{i % 5}",
            "val": float(i % 100),
            "dtg": int(t[i]),
            "geom": Point(float(lon[i]), float(lat[i])),
        }
        for i in range(n)
    ]
    store = DataStore(backend="tpu")
    store.create_schema("ev", "name:String,val:Double,dtg:Date,*geom:Point")
    store.write("ev", recs, fids=[f"e{i}" for i in range(n)])
    store._lonlat = (lon, lat)
    return store


class TestRewrite:
    def test_contains_rewrite(self):
        out = _rewrite_where(
            "ST_Contains(geom, ST_GeomFromText('POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))')) AND name = 'x'"
        )
        assert out == "CONTAINS(geom, POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))) AND name = 'x'"

    def test_dwithin_rewrite(self):
        out = _rewrite_where("st_dwithin(geom, 'POINT (5 5)', 2.5)")
        assert out == "DWITHIN(geom, POINT (5 5), 2.5, degrees)"

    def test_quoted_wkt(self):
        out = _rewrite_where("ST_Intersects(geom, 'POINT (1 2)')")
        assert out == "INTERSECTS(geom, POINT (1 2))"


class TestSelect:
    def test_select_star_with_spatial_pushdown(self, ds):
        res = sql(
            ds,
            "SELECT * FROM ev WHERE ST_Within(geom, "
            "ST_GeomFromText('POLYGON ((-10 -10, 10 -10, 10 10, -10 10, -10 -10))'))",
        )
        lon, lat = ds._lonlat
        exp = int(((lon >= -10) & (lon <= 10) & (lat >= -10) & (lat <= 10)).sum())
        # boundary-inclusive vs strict within may differ by measure-zero rows
        assert abs(len(res) - exp) <= 1
        assert set(res.columns) == {"name", "val", "dtg", "geom"}

    def test_projection_and_order_limit(self, ds):
        res = sql(ds, "SELECT name, val FROM ev WHERE val >= 95 ORDER BY val DESC LIMIT 7")
        assert list(res.columns) == ["name", "val"]
        assert len(res) == 7
        vals = [r[1] for r in res.rows()]
        assert vals == sorted(vals, reverse=True)
        assert vals[0] == 99.0

    def test_scalar_st_functions(self, ds):
        res = sql(ds, "SELECT st_x(geom) AS x, st_y(geom) AS y FROM ev LIMIT 5")
        assert list(res.columns) == ["x", "y"]
        assert len(res) == 5
        assert np.isfinite(res.columns["x"]).all()

    def test_st_astext(self, ds):
        res = sql(ds, "SELECT ST_AsText(geom) AS wkt FROM ev LIMIT 2")
        assert res.columns["wkt"][0].startswith("POINT")

    def test_generic_registry_udfs(self, ds):
        # any single-arg ST registry UDF rides the select list; geometry
        # results surface as WKT (the spark-jts SQL-UDF surface role)
        res = sql(ds, "SELECT ST_GeometryType(geom) AS t, "
                      "ST_Centroid(geom) AS c, ST_Area(geom) AS a, "
                      "ST_IsValid(geom) AS v FROM ev LIMIT 3")
        assert list(res.columns) == ["t", "c", "a", "v"]
        assert all(t == "Point" for t in res.columns["t"])
        assert all(c.startswith("POINT") for c in res.columns["c"])
        assert all(a == 0.0 for a in res.columns["a"])
        assert all(v is True for v in res.columns["v"])

    def test_unknown_st_function_rejected(self, ds):
        import pytest

        from geomesa_tpu.sql.engine import SqlError

        with pytest.raises(SqlError, match="unsupported function"):
            sql(ds, "SELECT ST_Bogus(geom) FROM ev LIMIT 1")


class TestAggregates:
    def test_count_star(self, ds):
        res = sql(ds, "SELECT COUNT(*) FROM ev")
        assert res.rows() == [(2000,)]

    def test_filtered_agg(self, ds):
        res = sql(ds, "SELECT COUNT(*) AS n, MIN(val) AS lo, MAX(val) AS hi "
                      "FROM ev WHERE name = 'c2'")
        (n, lo, hi), = res.rows()
        assert n == 400 and lo == 2.0 and hi == 97.0

    def test_group_by(self, ds):
        res = sql(ds, "SELECT name, COUNT(*) AS n, AVG(val) AS m FROM ev "
                      "GROUP BY name ORDER BY name")
        rows = res.rows()
        assert len(rows) == 5
        assert [r[0] for r in rows] == [f"c{i}" for i in range(5)]
        assert all(r[1] == 400 for r in rows)

    def test_group_by_with_spatial_filter(self, ds):
        res = sql(ds, "SELECT name, COUNT(*) AS n FROM ev "
                      "WHERE ST_Intersects(geom, ST_GeomFromText("
                      "'POLYGON ((-60 -60, 60 -60, 60 0, -60 0, -60 -60))')) "
                      "GROUP BY name")
        lon, lat = ds._lonlat
        exp_total = int((lat <= 0).sum())
        assert sum(r[1] for r in res.rows()) == exp_total


class TestCountFastPath:
    def test_count_star_uses_batched_exact_device_count(self, monkeypatch):
        """SELECT COUNT(*) with a pure bbox filter rides the fused device
        count (exact mode) with ZERO row materialization."""
        rng = np.random.default_rng(44)
        n = 10_000
        ds = DataStore(backend="tpu")
        ds.create_schema("c", "name:String,dtg:Date,*geom:Point")
        ds.write(
            "c",
            [{"name": f"n{i % 3}", "dtg": 1_600_000_000_000 + i,
              "geom": Point(float(rng.uniform(-90, 90)),
                            float(rng.uniform(-45, 45)))}
             for i in range(n)],
            fids=[str(i) for i in range(n)],
        )
        ds.compact("c")
        want = ds.query("c", "BBOX(geom, -30, -20, 30, 20)").count
        calls = {"q": 0}
        real = ds.query
        monkeypatch.setattr(
            ds, "query",
            lambda *a, **k: (calls.__setitem__("q", calls["q"] + 1),
                            real(*a, **k))[1],
        )
        r = sql(ds, "SELECT COUNT(*) AS n FROM c "
                    "WHERE BBOX(geom, -30, -20, 30, 20)")
        assert int(r.columns["n"][0]) == want
        assert calls["q"] == 0, "COUNT(*) materialized rows via query()"
        # non-batchable filter still exact through the fallback
        r2 = sql(ds, "SELECT COUNT(*) AS n FROM c WHERE name = 'n1'")
        assert int(r2.columns["n"][0]) == real("c", "name = 'n1'").count


class TestErrors:
    def test_bad_statement(self, ds):
        with pytest.raises(SqlError):
            sql(ds, "DELETE FROM ev")

    def test_non_grouped_column(self, ds):
        with pytest.raises(SqlError, match="GROUP BY"):
            sql(ds, "SELECT name, COUNT(*) FROM ev")

    def test_unknown_function(self, ds):
        with pytest.raises(SqlError, match="unsupported function"):
            sql(ds, "SELECT frob(name) FROM ev")


class TestSpatialJoin:
    @pytest.fixture(scope="class")
    def join_ds(self):
        from geomesa_tpu.geometry.types import Polygon

        rng = np.random.default_rng(3)
        n = 1500
        store = DataStore(backend="tpu")
        store.create_schema("pts", "name:String,val:Double,*geom:Point")
        lon = rng.uniform(-50, 50, n)
        lat = rng.uniform(-50, 50, n)
        recs = [
            {"name": f"p{i}", "val": float(i % 10), "geom": Point(float(lon[i]), float(lat[i]))}
            for i in range(n)
        ]
        store.write("pts", recs, fids=[f"p{i}" for i in range(n)])
        store.create_schema("zones", "zone:String,*geom:Polygon")
        zones = []
        for k, (cx, cy) in enumerate([(-20, -20), (0, 0), (25, 25)]):
            ring = [[cx - 8, cy - 8], [cx + 8, cy - 8], [cx + 8, cy + 8], [cx - 8, cy + 8]]
            zones.append({"zone": f"z{k}", "geom": Polygon(ring)})
        store.write("zones", zones, fids=[f"z{k}" for k in range(3)])
        store._pts = (lon, lat)
        return store

    def _truth(self, join_ds, zone_boxes):
        lon, lat = join_ds._pts
        out = {}
        for z, (x1, y1, x2, y2) in zone_boxes.items():
            out[z] = set(
                np.nonzero((lon > x1) & (lon < x2) & (lat > y1) & (lat < y2))[0]
            )
        return out

    ZONES = {"z0": (-28, -28, -12, -12), "z1": (-8, -8, 8, 8), "z2": (17, 17, 33, 33)}

    def test_join_within(self, join_ds):
        r = sql(
            join_ds,
            "SELECT a.name, b.zone FROM pts a JOIN zones b "
            "ON ST_Within(a.geom, b.geom)",
        )
        truth = self._truth(join_ds, self.ZONES)
        want = sum(len(v) for v in truth.values())
        assert len(r) == want
        # spot-check pairing: every returned (name, zone) is a true pair
        names = r.columns["a.name"]
        zones = r.columns["b.zone"]
        for nm, z in zip(names, zones):
            i = int(nm[1:])
            assert i in truth[z], (nm, z)

    def test_join_flipped_args(self, join_ds):
        r1 = sql(join_ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                          "ON ST_Within(a.geom, b.geom)")
        r2 = sql(join_ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                          "ON ST_Contains(b.geom, a.geom)")
        assert sorted(zip(r1.columns["a.name"], r1.columns["b.zone"])) == \
               sorted(zip(r2.columns["a.name"], r2.columns["b.zone"]))

    def test_join_where_pushdown_and_limit(self, join_ds):
        r = sql(
            join_ds,
            "SELECT a.name, a.val, b.zone FROM pts a JOIN zones b "
            "ON ST_Within(a.geom, b.geom) WHERE a.val > 5 LIMIT 7",
        )
        assert len(r) <= 7
        assert all(float(v) > 5 for v in r.columns["a.val"])

    def test_join_star(self, join_ds):
        r = sql(join_ds, "SELECT b.*, a.name FROM pts a JOIN zones b "
                         "ON ST_Intersects(a.geom, b.geom) LIMIT 3")
        assert set(r.columns) == {"b.zone", "b.geom", "a.name"}

    def test_join_duplicate_items_collapse(self, join_ds):
        r = sql(join_ds, "SELECT a.name, a.name, b.zone FROM pts a "
                         "JOIN zones b ON ST_Within(a.geom, b.geom) LIMIT 5")
        lens = {k: len(v) for k, v in r.columns.items()}
        assert len(set(lens.values())) == 1  # all columns aligned
        r.rows()  # must not raise

    def test_join_on_non_geometry_right_col(self, join_ds):
        with pytest.raises(SqlError, match="geometry column"):
            sql(join_ds, "SELECT a.name FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.zone)")

    def test_join_where_literal_with_alias_text(self, join_ds):
        # a literal containing "b." must not be mistaken for a right-alias
        # reference, and the left-alias strip must not rewrite literals
        r = sql(join_ds, "SELECT a.name FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom) WHERE a.name = 'b.x'")
        assert len(r) == 0  # no point is named 'b.x' — but it parses

    def test_join_group_by(self, join_ds):
        # "points per zone" — the Spark-SQL composition of spatial JOIN
        # with relational aggregation (GeoMesaRelation + Catalyst role)
        r = sql(
            join_ds,
            "SELECT b.zone, COUNT(*) AS n, AVG(a.val) AS m FROM pts a "
            "JOIN zones b ON ST_Within(a.geom, b.geom) GROUP BY b.zone",
        )
        truth = self._truth(join_ds, self.ZONES)
        got = {z: (n, m) for z, n, m in r.rows()}
        for z, idx in truth.items():
            if not idx:
                assert z not in got
                continue
            vals = [float(i % 10) for i in sorted(idx)]
            assert got[z][0] == len(idx)
            assert got[z][1] == pytest.approx(sum(vals) / len(vals))

    def test_join_group_by_having_order(self, join_ds):
        truth = self._truth(join_ds, self.ZONES)
        counts = {z: len(v) for z, v in truth.items() if v}
        floor = sorted(counts.values())[0]
        r = sql(
            join_ds,
            "SELECT b.zone, COUNT(*) AS n FROM pts a "
            "JOIN zones b ON ST_Within(a.geom, b.geom) GROUP BY b.zone "
            f"HAVING COUNT(*) > {floor} ORDER BY n DESC",
        )
        rows = r.rows()
        want = sorted(
            ((z, n) for z, n in counts.items() if n > floor),
            key=lambda t: -t[1],
        )
        assert [n for _, n in rows] == [n for _, n in want]
        assert {z for z, _ in rows} == {z for z, _ in want}
        # HAVING over a left-alias aggregate not in the select list
        r2 = sql(
            join_ds,
            "SELECT b.zone FROM pts a JOIN zones b "
            "ON ST_Within(a.geom, b.geom) GROUP BY b.zone "
            "HAVING AVG(a.val) >= 0",
        )
        assert set(r2.columns["b.zone"]) == set(counts)

    def test_join_group_by_count_only_fast_path(self, join_ds):
        # no left columns + no WHERE → the device join yields match counts
        # without materializing rows; results must equal the full fold
        r = sql(
            join_ds,
            "SELECT b.zone, COUNT(*) AS n FROM pts a "
            "JOIN zones b ON ST_Within(a.geom, b.geom) GROUP BY b.zone",
        )
        truth = self._truth(join_ds, self.ZONES)
        got = dict(r.rows())
        for z, idx in truth.items():
            if idx:
                assert got[z] == len(idx)
            else:
                assert z not in got

    def test_join_group_by_null_handling(self):
        # NULL values must not pollute aggregates (sentinel-zero bug class)
        # nor conflate with real zeros — same mask semantics as the
        # single-table _agg_value fold
        from geomesa_tpu.geometry.types import Polygon

        ds = DataStore(backend="oracle")
        ds.create_schema("npts", "val:Double,*geom:Point")
        ds.write("npts", [
            {"val": 4.0, "geom": Point(1, 1)},
            {"val": None, "geom": Point(2, 2)},
            {"val": 8.0, "geom": Point(3, 3)},
        ])
        ds.create_schema("nz", "zone:String,*geom:Polygon")
        ds.write("nz", [{"zone": "all", "geom": Polygon(
            [[0, 0], [10, 0], [10, 10], [0, 10]])}])
        r = sql(ds, "SELECT b.zone, COUNT(*) AS n, COUNT(a.val) AS nv, "
                    "SUM(a.val) AS s, AVG(a.val) AS m, "
                    "COUNT(DISTINCT a.val) AS d FROM npts a JOIN nz b "
                    "ON ST_Within(a.geom, b.geom) GROUP BY b.zone")
        (zone, n, nv, s, m, d), = r.rows()
        assert (zone, n, nv, s, m, d) == ("all", 3, 2, 12.0, 6.0, 2)

    def test_join_group_by_fuzz_vs_bruteforce(self):
        # seeded fuzz: random point clouds × random convex-ish zones,
        # random aggregate sets — the grouped join fold must match a
        # numpy referee exactly (counts) / approximately (float aggs)
        from geomesa_tpu.geometry.predicates import points_within_geom
        from geomesa_tpu.geometry.types import Polygon

        rng = np.random.default_rng(77)
        for trial in range(4):
            n = int(rng.integers(50, 400))
            ds = DataStore(backend="oracle")
            ds.create_schema("fp", "val:Double,*geom:Point")
            lon = rng.uniform(0, 20, n)
            lat = rng.uniform(0, 20, n)
            vals = np.round(rng.normal(10, 5, n), 3)
            ds.write("fp", [
                {"val": float(vals[i]), "geom": Point(lon[i], lat[i])}
                for i in range(n)
            ])
            polys = []
            for _z in range(int(rng.integers(1, 4))):
                cx, cy = rng.uniform(3, 17, 2)
                ang = np.sort(rng.uniform(0, 2 * np.pi, 8))
                rad = rng.uniform(2, 5, 8)
                polys.append(Polygon(np.stack(
                    [cx + rad * np.cos(ang), cy + rad * np.sin(ang)], 1)))
            ds.create_schema("fz", "zone:String,*geom:Polygon")
            ds.write("fz", [
                {"zone": f"z{j}", "geom": p} for j, p in enumerate(polys)
            ])
            r = sql(ds, "SELECT b.zone, COUNT(*) AS n, SUM(a.val) AS s, "
                        "MIN(a.val) AS lo FROM fp a JOIN fz b "
                        "ON ST_Within(a.geom, b.geom) GROUP BY b.zone")
            got = {z: (cnt, s, lo) for z, cnt, s, lo in r.rows()}
            for j, p in enumerate(polys):
                m = points_within_geom(lon, lat, p)
                if not m.any():
                    assert f"z{j}" not in got
                    continue
                cnt, s, lo = got[f"z{j}"]
                assert cnt == int(m.sum()), f"trial={trial} z{j}"
                assert s == pytest.approx(float(vals[m].sum()))
                assert lo == pytest.approx(float(vals[m].min()))

    def test_sql_auths_scope_select_agg_and_join(self):
        # the auths parameter threads into every path: plain select,
        # aggregation fold, and the join (device gather declines; the
        # host scan applies visibility per planned query)
        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.schema.columnar import FeatureTable
        from geomesa_tpu.schema.sft import parse_spec

        sft = parse_spec(
            "vev", "dtg:Date,*geom:Point,vis:String;geomesa.vis.field='vis'"
        )
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        recs = [
            {"dtg": 1_500_000_000_000, "geom": Point(i, 1), "vis": v}
            for i, v in enumerate(["admin", "", "admin", "", "secret"])
        ]
        ds.write("vev", FeatureTable.from_records(
            sft, recs, [f"v{i}" for i in range(5)]))
        ds.create_schema("vz", "zone:String,*geom:Polygon")
        ds.write("vz", [{"zone": "all", "geom": Polygon(
            [[-1, 0], [6, 0], [6, 2], [-1, 2]])}])

        assert sql(ds, "SELECT COUNT(*) FROM vev").rows() == [(5,)]
        assert sql(ds, "SELECT COUNT(*) FROM vev", auths=[]).rows() == [(2,)]
        assert sql(ds, "SELECT COUNT(*) FROM vev",
                   auths=["admin"]).rows() == [(4,)]
        r = sql(ds, "SELECT b.zone, COUNT(*) AS n FROM vev a JOIN vz b "
                    "ON ST_Within(a.geom, b.geom) GROUP BY b.zone",
                auths=["admin"])
        assert r.rows() == [("all", 4)]
        r2 = sql(ds, "SELECT a.vis FROM vev a JOIN vz b "
                     "ON ST_Within(a.geom, b.geom)", auths=[])
        assert len(r2) == 2 and all(v == "" for v in r2.columns["a.vis"])

    def test_join_group_by_over_merged_view(self):
        # federated "points per zone": events split across two members,
        # zones data on one (schema on all — the reference's intersection
        # semantics, index/view/package.scala getTypeNames)
        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.store.merged import MergedDataStoreView

        a = DataStore(backend="oracle")
        b = DataStore(backend="oracle")
        for ds_, lo in ((a, 0), (b, 5)):
            ds_.create_schema("fev", "name:String,*geom:Point")
            ds_.write("fev", [
                {"name": f"m{lo + i}", "geom": Point(lo + i + 0.5, 1)}
                for i in range(5)
            ])
            ds_.create_schema("fz", "zone:String,*geom:Polygon")
        a.write("fz", [
            {"zone": "west", "geom": Polygon([[0, 0], [5, 0], [5, 2], [0, 2]])},
            {"zone": "east", "geom": Polygon([[5, 0], [10, 0], [10, 2], [5, 2]])},
        ], fids=["w", "e"])
        view = MergedDataStoreView([a, b])
        r = sql(view, "SELECT b.zone, COUNT(*) AS n FROM fev a JOIN fz b "
                      "ON ST_Within(a.geom, b.geom) GROUP BY b.zone")
        assert dict(r.rows()) == {"west": 5, "east": 5}

    def test_join_flat_order_by(self, join_ds):
        r = sql(
            join_ds,
            "SELECT a.name, b.zone FROM pts a JOIN zones b "
            "ON ST_Within(a.geom, b.geom) ORDER BY a.name LIMIT 5",
        )
        names = list(r.columns["a.name"])
        assert len(names) == 5 and names == sorted(names)
        # a full unsorted run must contain the same first-5 when sorted
        full = sql(
            join_ds,
            "SELECT a.name FROM pts a JOIN zones b "
            "ON ST_Within(a.geom, b.geom)",
        )
        assert names == sorted(full.columns["a.name"])[:5]

    def test_join_having_without_group_rejected(self, join_ds):
        with pytest.raises(SqlError, match="HAVING requires GROUP BY"):
            sql(join_ds, "SELECT a.name FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom) HAVING COUNT(*) > 1")

    def test_join_group_by_errors(self, join_ds):
        with pytest.raises(SqlError, match="GROUP BY key"):
            sql(join_ds, "SELECT a.name, COUNT(*) FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom) GROUP BY b.zone")
        with pytest.raises(SqlError, match="aggregate geometry"):
            sql(join_ds, "SELECT b.zone, MIN(b.geom) FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom) GROUP BY b.zone")

    def test_join_errors(self, join_ds):
        with pytest.raises(SqlError, match="left alias"):
            sql(join_ds, "SELECT a.name FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom) WHERE b.zone = 'z0'")
        with pytest.raises(SqlError, match="alias.col"):
            sql(join_ds, "SELECT name FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom)")
        with pytest.raises(SqlError, match="geometry column"):
            sql(join_ds, "SELECT a.name FROM pts a JOIN zones b "
                         "ON ST_Within(a.name, b.geom)")

    def test_join_takes_mesh_path_on_tpu_store(self, join_ds, monkeypatch):
        """VERDICT r2 item 6: the SQL spatial JOIN executes on the device
        mesh (block-sparse candidate gather), not the per-geometry host
        scan, when the left store is TPU-backed."""
        import geomesa_tpu.process.join as pj

        calls = {"device": 0, "host": 0}
        real_dev = pj.join_rows_device
        real_host = pj.join_scan
        monkeypatch.setattr(
            pj, "join_rows_device",
            lambda *a, **k: (calls.__setitem__("device", calls["device"] + 1),
                             real_dev(*a, **k))[1],
        )
        monkeypatch.setattr(
            pj, "join_scan",
            lambda *a, **k: (calls.__setitem__("host", calls["host"] + 1),
                             real_host(*a, **k))[1],
        )
        r = sql(join_ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                         "ON ST_Within(a.geom, b.geom)")
        assert calls["device"] == 1 and calls["host"] == 0
        truth = self._truth(join_ds, self.ZONES)
        assert len(r) == sum(len(v) for v in truth.values())

    def test_join_device_failure_falls_back(self, join_ds, monkeypatch):
        import geomesa_tpu.process.join as pj

        want = sql(join_ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                            "ON ST_Within(a.geom, b.geom)")

        def boom(*a, **k):
            raise RuntimeError("UNAVAILABLE: device wedged")

        monkeypatch.setattr(pj, "join_rows_device", boom)
        got = sql(join_ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                           "ON ST_Within(a.geom, b.geom)")
        assert sorted(zip(got.columns["a.name"], got.columns["b.zone"])) == \
               sorted(zip(want.columns["a.name"], want.columns["b.zone"]))
        join_ds._device_down_until = 0.0  # reset circuit for other tests

    def test_join_mesh_live_store_and_ttl(self, monkeypatch):
        """The mesh join serves LIVE stores without compacting them (a read
        must not trigger a store-wide rebuild): pending delta rows splice
        in host-side, and TTL-expired rows are excluded — matching the
        host path's semantics."""
        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.schema.sft import parse_spec

        t0 = 1_700_000_000_000
        sft = parse_spec("pts", "name:String,dtg:Date,*geom:Point")
        sft.user_data["geomesa.age.off"] = 10**15  # effectively no expiry
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        rng = np.random.default_rng(9)
        lon = rng.uniform(-40, 40, 800)
        lat = rng.uniform(-40, 40, 800)
        ds.write(
            "pts",
            [{"name": f"p{i}", "dtg": t0,
              "geom": Point(float(lon[i]), float(lat[i]))}
             for i in range(800)],
            fids=[f"p{i}" for i in range(800)],
        )
        ds.compact("pts")
        ds.create_schema("zones", "zone:String,*geom:Polygon")
        ring = [[-10, -10], [10, -10], [10, 10], [-10, 10]]
        ds.write("zones", [{"zone": "z0", "geom": Polygon(ring)}], fids=["z0"])
        # pending write inside the zone; must appear without a compaction
        ds.write("pts", [{"name": "hot", "dtg": t0,
                          "geom": Point(0.5, 0.5)}], fids=["hot"])
        assert ds._state("pts").delta.rows > 0
        n_compacts = {"n": 0}
        real_compact = ds.compact
        monkeypatch.setattr(
            ds, "compact",
            lambda *a, **k: (n_compacts.__setitem__("n", n_compacts["n"] + 1),
                             real_compact(*a, **k))[1],
        )
        import geomesa_tpu.process.join as pj

        spy = {"device": 0}
        real_dev = pj.join_rows_device
        monkeypatch.setattr(
            pj, "join_rows_device",
            lambda *a, **k: (spy.__setitem__("device", spy["device"] + 1),
                             real_dev(*a, **k))[1],
        )
        r = sql(ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                    "ON ST_Within(a.geom, b.geom)")
        assert spy["device"] == 1, "live TTL store left the mesh path"
        assert n_compacts["n"] == 0, "read path triggered a compaction"
        names = set(r.columns["a.name"])
        assert "hot" in names
        want = {
            f"p{i}" for i in np.nonzero(
                (lon > -10) & (lon < 10) & (lat > -10) & (lat < 10)
            )[0]
        } | {"hot"}
        assert names == want

    def test_join_mesh_parity_vs_oracle_irregular_polygons(self):
        """Mesh join == oracle join over irregular (non-box) polygons: the
        int-domain device prefilter is a superset and the host residual is
        exact f64, so row sets must match the oracle exactly."""
        from geomesa_tpu.geometry.types import Polygon

        rng = np.random.default_rng(77)
        n = 4000
        lon = rng.uniform(-60, 60, n)
        lat = rng.uniform(-60, 60, n)
        recs = [{"name": f"p{i}", "val": 0.0,
                 "geom": Point(float(lon[i]), float(lat[i]))}
                for i in range(n)]
        polys = []
        for k in range(12):
            cx, cy = rng.uniform(-45, 45, 2)
            ang = np.sort(rng.uniform(0, 2 * np.pi, 9))
            rad = rng.uniform(3, 10, 9)
            ring = np.stack(
                [cx + rad * np.cos(ang), cy + rad * np.sin(ang)], 1
            )
            polys.append({"zone": f"z{k}", "geom": Polygon(ring)})
        results = {}
        for backend in ("tpu", "oracle"):
            ds = DataStore(backend=backend)
            ds.create_schema("pts", "name:String,val:Double,*geom:Point")
            ds.write("pts", recs, fids=[f"p{i}" for i in range(n)])
            ds.create_schema("zones", "zone:String,*geom:Polygon")
            ds.write("zones", polys, fids=[f"z{k}" for k in range(12)])
            r = sql(ds, "SELECT a.name, b.zone FROM pts a JOIN zones b "
                        "ON ST_Within(a.geom, b.geom)")
            results[backend] = sorted(
                zip(r.columns["a.name"], r.columns["b.zone"])
            )
        assert results["tpu"] == results["oracle"]


class TestDistinctHaving:
    def test_distinct(self, ds):
        r = sql(ds, "SELECT DISTINCT name FROM ev")
        assert sorted(r.columns["name"].tolist()) == [f"c{i}" for i in range(5)]

    def test_distinct_multi_column_with_limit(self, ds):
        r = sql(ds, "SELECT DISTINCT name, val FROM ev LIMIT 7")
        assert len(r) == 7
        rows = set(r.rows())
        assert len(rows) == 7  # all distinct

    def test_having_filters_groups(self, ds):
        full = sql(ds, "SELECT name, COUNT(*) AS n FROM ev GROUP BY name")
        counts = dict(zip(full.columns["name"], full.columns["n"]))
        cutoff = int(np.median(list(counts.values())))
        r = sql(
            ds,
            f"SELECT name, COUNT(*) AS n FROM ev GROUP BY name "
            f"HAVING COUNT(*) > {cutoff}",
        )
        want = {k for k, v in counts.items() if v > cutoff}
        assert set(r.columns["name"].tolist()) == want

    def test_having_on_unselected_aggregate(self, ds):
        r = sql(
            ds,
            "SELECT name FROM ev GROUP BY name HAVING AVG(val) >= 0",
        )
        assert len(r) == 5  # every group passes; avg not in select list

    def test_having_requires_group_by(self, ds):
        with pytest.raises(SqlError, match="HAVING requires GROUP BY"):
            sql(ds, "SELECT COUNT(*) FROM ev HAVING COUNT(*) > 1")

    def test_bad_having_expr(self, ds):
        with pytest.raises(SqlError, match="unsupported HAVING"):
            sql(ds, "SELECT name, COUNT(*) FROM ev GROUP BY name HAVING name = 'x'")

    def test_having_keyword_inside_where_literal(self, ds):
        # WHERE string literals containing clause keywords must not hijack
        # clause splitting (quote-masked parsing)
        r = sql(ds, "SELECT name FROM ev WHERE name = 'a having b' LIMIT 5")
        assert len(r) == 0

    def test_distinct_with_aggregates_rejected(self, ds):
        with pytest.raises(SqlError, match="DISTINCT"):
            sql(ds, "SELECT DISTINCT COUNT(*) FROM ev GROUP BY name")

    def test_having_unknown_column(self, ds):
        with pytest.raises(SqlError, match="unknown HAVING column"):
            sql(ds, "SELECT name FROM ev GROUP BY name HAVING SUM(bogus) > 0")

    def test_having_star_only_for_count(self, ds):
        with pytest.raises(SqlError, match=r"AVG\(\*\)"):
            sql(ds, "SELECT name FROM ev GROUP BY name HAVING AVG(*) > 0")

    def test_having_non_numeric_aggregate(self, ds):
        with pytest.raises(SqlError, match="not numeric"):
            sql(ds, "SELECT name FROM ev GROUP BY name HAVING MIN(name) > 0")


class TestOffsetCountDistinct:
    """LIMIT ... OFFSET paging and COUNT(DISTINCT col) — the Spark-SQL
    surface tail (SURVEY.md §2.14)."""

    def test_offset_pages_through_ordered_rows(self, ds):
        full = sql(ds, "SELECT name, val FROM ev ORDER BY val DESC, name "
                       "LIMIT 10")
        page1 = sql(ds, "SELECT name, val FROM ev ORDER BY val DESC, name "
                        "LIMIT 5")
        page2 = sql(ds, "SELECT name, val FROM ev ORDER BY val DESC, name "
                        "LIMIT 5 OFFSET 5")
        assert page1.rows() + page2.rows() == full.rows()

    def test_offset_without_order(self, ds):
        full = sql(ds, "SELECT name FROM ev LIMIT 8")
        tail = sql(ds, "SELECT name FROM ev LIMIT 5 OFFSET 3")
        assert tail.rows() == full.rows()[3:8]

    def test_offset_no_limit(self, ds):
        full = sql(ds, "SELECT name FROM ev")
        rest = sql(ds, "SELECT name FROM ev OFFSET 1990")
        assert rest.rows() == full.rows()[1990:]
        assert len(rest) == 10

    def test_offset_past_end_is_empty(self, ds):
        r = sql(ds, "SELECT name FROM ev LIMIT 5 OFFSET 100000")
        assert len(r) == 0

    def test_offset_on_group_by(self, ds):
        full = sql(ds, "SELECT name, COUNT(*) AS n FROM ev GROUP BY name "
                       "ORDER BY name")
        page = sql(ds, "SELECT name, COUNT(*) AS n FROM ev GROUP BY name "
                       "ORDER BY name LIMIT 2 OFFSET 2")
        assert page.rows() == full.rows()[2:4]

    def test_count_distinct(self, ds):
        r = sql(ds, "SELECT COUNT(DISTINCT name) AS u FROM ev")
        assert r.rows() == [(5,)]
        r = sql(ds, "SELECT COUNT(DISTINCT val) AS u FROM ev")
        assert r.rows() == [(100,)]

    def test_count_distinct_grouped(self, ds):
        r = sql(ds, "SELECT name, COUNT(DISTINCT val) AS u FROM ev "
                    "GROUP BY name ORDER BY name")
        # vals are i % 100 and names are c{i % 5}: each name sees exactly
        # the 20 residues val % 100 with matching i % 5
        assert [row[1] for row in r.rows()] == [20] * 5

    def test_count_distinct_with_where(self, ds):
        lon, lat = ds._lonlat
        m = (lon >= 0) & (lon <= 60) & (lat >= -60) & (lat <= 60)
        names = np.array([f"c{i % 5}" for i in range(len(lon))])
        want = len(set(names[m]))
        r = sql(ds, "SELECT COUNT(DISTINCT name) AS u FROM ev "
                    "WHERE ST_Within(geom, 'POLYGON ((0 -60, 60 -60, "
                    "60 60, 0 60, 0 -60))')")
        assert r.rows() == [(want,)]

    def test_distinct_inside_other_aggs_rejected(self, ds):
        with pytest.raises(SqlError, match="DISTINCT inside SUM"):
            sql(ds, "SELECT SUM(DISTINCT val) FROM ev")

    def test_count_star_offset(self, ds):
        # OFFSET past the single COUNT(*) row yields the empty set (SQL
        # semantics: OFFSET applies to the RESULT rows)
        assert len(sql(ds, "SELECT COUNT(*) FROM ev OFFSET 1")) == 0
        assert len(sql(ds, "SELECT COUNT(*) FROM ev OFFSET 0")) == 1

    def test_count_distinct_geometry(self, ds):
        lon, _ = ds._lonlat
        r = sql(ds, "SELECT COUNT(DISTINCT geom) AS u FROM ev")
        assert r.rows() == [(len(lon),)]

    def test_count_distinct_bad_forms(self, ds):
        with pytest.raises(SqlError, match="exactly one column"):
            sql(ds, "SELECT COUNT(DISTINCT *) FROM ev")
        with pytest.raises(SqlError, match="exactly one column"):
            sql(ds, "SELECT COUNT(DISTINCT name, val) FROM ev")
