"""SQL layer tests: pushdown, projection, scalar UDFs, aggregates, GROUP BY
(reference: geomesa-spark-sql — SURVEY.md §2.14/§3.5)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.sql import SqlResult, sql
from geomesa_tpu.sql.engine import SqlError, _rewrite_where
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(11)
    n = 2000
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-60, 60, n)
    t = T0 + rng.integers(0, 5 * 86_400_000, n)
    recs = [
        {
            "name": f"c{i % 5}",
            "val": float(i % 100),
            "dtg": int(t[i]),
            "geom": Point(float(lon[i]), float(lat[i])),
        }
        for i in range(n)
    ]
    store = DataStore(backend="tpu")
    store.create_schema("ev", "name:String,val:Double,dtg:Date,*geom:Point")
    store.write("ev", recs, fids=[f"e{i}" for i in range(n)])
    store._lonlat = (lon, lat)
    return store


class TestRewrite:
    def test_contains_rewrite(self):
        out = _rewrite_where(
            "ST_Contains(geom, ST_GeomFromText('POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))')) AND name = 'x'"
        )
        assert out == "CONTAINS(geom, POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))) AND name = 'x'"

    def test_dwithin_rewrite(self):
        out = _rewrite_where("st_dwithin(geom, 'POINT (5 5)', 2.5)")
        assert out == "DWITHIN(geom, POINT (5 5), 2.5, degrees)"

    def test_quoted_wkt(self):
        out = _rewrite_where("ST_Intersects(geom, 'POINT (1 2)')")
        assert out == "INTERSECTS(geom, POINT (1 2))"


class TestSelect:
    def test_select_star_with_spatial_pushdown(self, ds):
        res = sql(
            ds,
            "SELECT * FROM ev WHERE ST_Within(geom, "
            "ST_GeomFromText('POLYGON ((-10 -10, 10 -10, 10 10, -10 10, -10 -10))'))",
        )
        lon, lat = ds._lonlat
        exp = int(((lon >= -10) & (lon <= 10) & (lat >= -10) & (lat <= 10)).sum())
        # boundary-inclusive vs strict within may differ by measure-zero rows
        assert abs(len(res) - exp) <= 1
        assert set(res.columns) == {"name", "val", "dtg", "geom"}

    def test_projection_and_order_limit(self, ds):
        res = sql(ds, "SELECT name, val FROM ev WHERE val >= 95 ORDER BY val DESC LIMIT 7")
        assert list(res.columns) == ["name", "val"]
        assert len(res) == 7
        vals = [r[1] for r in res.rows()]
        assert vals == sorted(vals, reverse=True)
        assert vals[0] == 99.0

    def test_scalar_st_functions(self, ds):
        res = sql(ds, "SELECT st_x(geom) AS x, st_y(geom) AS y FROM ev LIMIT 5")
        assert list(res.columns) == ["x", "y"]
        assert len(res) == 5
        assert np.isfinite(res.columns["x"]).all()

    def test_st_astext(self, ds):
        res = sql(ds, "SELECT ST_AsText(geom) AS wkt FROM ev LIMIT 2")
        assert res.columns["wkt"][0].startswith("POINT")


class TestAggregates:
    def test_count_star(self, ds):
        res = sql(ds, "SELECT COUNT(*) FROM ev")
        assert res.rows() == [(2000,)]

    def test_filtered_agg(self, ds):
        res = sql(ds, "SELECT COUNT(*) AS n, MIN(val) AS lo, MAX(val) AS hi "
                      "FROM ev WHERE name = 'c2'")
        (n, lo, hi), = res.rows()
        assert n == 400 and lo == 2.0 and hi == 97.0

    def test_group_by(self, ds):
        res = sql(ds, "SELECT name, COUNT(*) AS n, AVG(val) AS m FROM ev "
                      "GROUP BY name ORDER BY name")
        rows = res.rows()
        assert len(rows) == 5
        assert [r[0] for r in rows] == [f"c{i}" for i in range(5)]
        assert all(r[1] == 400 for r in rows)

    def test_group_by_with_spatial_filter(self, ds):
        res = sql(ds, "SELECT name, COUNT(*) AS n FROM ev "
                      "WHERE ST_Intersects(geom, ST_GeomFromText("
                      "'POLYGON ((-60 -60, 60 -60, 60 0, -60 0, -60 -60))')) "
                      "GROUP BY name")
        lon, lat = ds._lonlat
        exp_total = int((lat <= 0).sum())
        assert sum(r[1] for r in res.rows()) == exp_total


class TestErrors:
    def test_bad_statement(self, ds):
        with pytest.raises(SqlError):
            sql(ds, "DELETE FROM ev")

    def test_non_grouped_column(self, ds):
        with pytest.raises(SqlError, match="GROUP BY"):
            sql(ds, "SELECT name, COUNT(*) FROM ev")

    def test_unknown_function(self, ds):
        with pytest.raises(SqlError, match="unsupported function"):
            sql(ds, "SELECT frob(name) FROM ev")
