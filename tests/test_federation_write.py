"""Write-side federation + cross-host locking (VERDICT r3 item 3).

- RemoteDataStore forwards mutations (create/write/update/delete) to the
  owning process over HTTP; conflicts surface as local exception types.
- lease_lock: cross-host expiring lease (O_EXCL create + stale-break).
- register_schema / save_type: coordinated multi-writer shared catalog —
  two OS processes racing create_schema produce exactly one winner and a
  never-torn manifest.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.store.datastore import DataStore

T0 = 1_500_000_000_000


@pytest.fixture()
def server():
    from wsgiref.simple_server import make_server

    from geomesa_tpu.web.app import GeoMesaApp

    store = DataStore(backend="tpu")
    httpd = make_server("127.0.0.1", 0, GeoMesaApp(store))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield store, f"http://127.0.0.1:{port}"
    httpd.shutdown()


class TestWriteForwarding:
    def test_auths_scoped_query_fails_closed_by_default(self, server):
        # this client cannot apply row visibility to the remote's rows: an
        # auths-scoped query must raise unless the operator declares the
        # remote's trusted auths header (never silently over-serve)
        from geomesa_tpu.planning.planner import Query
        from geomesa_tpu.store.remote import RemoteDataStore

        local, url = server
        local.create_schema("fv", "name:String,*geom:Point")
        local.write("fv", [{"name": "x", "geom": Point(1, 1)}])
        remote = RemoteDataStore(url)
        assert remote.query("fv", None).count == 1  # unscoped: fine
        with pytest.raises(PermissionError, match="visibility"):
            remote.query("fv", Query(auths=["admin"]))
        # opt-in forwarding reaches the remote (the test server has no
        # auth provider, so the header is ignored — transport-level check)
        fwd = RemoteDataStore(url, forward_auths_header="X-Geomesa-Auths")
        assert fwd.query("fv", Query(auths=["admin"])).count == 1

    def test_full_mutation_lifecycle(self, server):
        from geomesa_tpu.store.remote import RemoteDataStore

        local, url = server
        remote = RemoteDataStore(url)
        remote.create_schema("w", "name:String,val:Double,dtg:Date,*geom:Point")
        assert local.get_schema("w").name == "w"

        n = remote.write("w", [
            {"name": f"p{i}", "val": float(i), "dtg": T0 + i,
             "geom": Point(float(i), float(i % 50))}
            for i in range(40)
        ], fids=[f"f{i}" for i in range(40)])
        assert n == 40
        assert local.stats_count("w") == 40
        # read back over the same wire
        got = remote.query("w", "BBOX(geom, -1, -1, 10.5, 50)")
        assert len(got.table) == 11

        n = remote.update_features("w", [
            {"name": "p1x", "val": 99.0, "dtg": T0,
             "geom": Point(1.0, 1.0)},
        ], fids=["f1"])
        assert n == 1
        rec = local.query("w", "IN ('f1')").table.record(0)
        assert rec["name"] == "p1x" and rec["val"] == 99.0

        assert remote.delete_features("w", ["f2", "f3"]) == 2
        assert local.stats_count("w") == 38

        remote.update_schema("w", add="extra:String")
        assert any(a.name == "extra" for a in local.get_schema("w").attributes)

        remote.delete_schema("w")
        assert "w" not in local.list_schemas()

    def test_feature_table_payload(self, server):
        from geomesa_tpu.schema.columnar import FeatureTable
        from geomesa_tpu.store.remote import RemoteDataStore

        local, url = server
        remote = RemoteDataStore(url)
        remote.create_schema("t", "name:String,*geom:Point")
        sft = local.get_schema("t")
        tbl = FeatureTable.from_records(
            sft,
            [{"name": "a", "geom": Point(1.0, 2.0)},
             {"name": "b", "geom": Point(3.0, 4.0)}],
            ["x1", "x2"],
        )
        assert remote.write("t", tbl) == 2
        assert set(local.query("t").table.fids.tolist()) == {"x1", "x2"}

    def test_conflicts_surface_as_local_exceptions(self, server):
        from geomesa_tpu.store.remote import RemoteDataStore

        _, url = server
        remote = RemoteDataStore(url)
        remote.create_schema("c", "name:String,*geom:Point")
        with pytest.raises(ValueError):
            remote.create_schema("c", "name:String,*geom:Point")
        with pytest.raises((KeyError, ValueError)):
            remote.update_features(
                "c", [{"name": "x", "geom": Point(0.0, 0.0)}], fids=["nope"]
            )

    def test_concurrent_remote_create_one_winner(self, server):
        from geomesa_tpu.store.remote import RemoteDataStore

        _, url = server
        results = []

        def attempt():
            r = RemoteDataStore(url)
            try:
                r.create_schema("race", "name:String,*geom:Point")
                results.append("win")
            except ValueError:
                results.append("lose")

        ts = [threading.Thread(target=attempt) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(results) == ["lose", "lose", "lose", "win"]


class TestLeaseLock:
    def test_mutual_exclusion_threads(self, tmp_path):
        from geomesa_tpu.utils.locks import lease_lock

        holders = []

        def job(i):
            with lease_lock(str(tmp_path), ttl_s=10, timeout_s=10):
                holders.append(i)
                time.sleep(0.02)
                assert holders[-1] == i  # nobody entered while held

        ts = [threading.Thread(target=job, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(holders) == 4

    def test_stale_claim_is_reaped(self, tmp_path):
        """A crashed holder's expired claim sorts first but is reaped, so a
        new contender acquires without waiting out the timeout."""
        from geomesa_tpu.utils.locks import lease_lock

        claims = tmp_path / ".geomesa.catalog.claims"
        claims.mkdir()
        dead = claims / f"c-{0:020d}-deadbeef"
        dead.write_text(json.dumps(
            {"holder": "dead:1", "expires_unix": time.time() - 5}
        ))
        t0 = time.monotonic()
        with lease_lock(str(tmp_path), ttl_s=5, timeout_s=5):
            assert not dead.exists()  # reaped during arbitration
        assert time.monotonic() - t0 < 2.0

    def test_live_earlier_claim_blocks_until_timeout(self, tmp_path):
        from geomesa_tpu.utils.locks import LockTimeout, lease_lock

        claims = tmp_path / ".geomesa.catalog.claims"
        claims.mkdir()
        alive = claims / f"c-{1:020d}-aaaa"
        alive.write_text(json.dumps(
            {"holder": "alive:1", "expires_unix": time.time() + 60}
        ))
        with pytest.raises(LockTimeout):
            with lease_lock(str(tmp_path), ttl_s=60, timeout_s=0.4):
                pass
        assert alive.exists()  # a live claim is NEVER broken

    def test_release_removes_only_own_claim(self, tmp_path):
        from geomesa_tpu.utils.locks import lease_lock

        claims = tmp_path / ".geomesa.catalog.claims"
        with lease_lock(str(tmp_path), ttl_s=60, timeout_s=5):
            # a later contender queues behind us while we hold
            waiter = claims / f"c-{10**18:020d}-zzzz"
            waiter.write_text(json.dumps(
                {"holder": "waiter:2", "expires_unix": time.time() + 60}
            ))
        assert waiter.exists()  # release touched only our claim
        assert not [p for p in claims.glob("c-*") if p != waiter]


_RACE_SCRIPT = r"""
import sys, time
import jax; jax.config.update("jax_platforms", "cpu")
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.persistence import SchemaExistsError, register_schema

path, start_at = sys.argv[1], float(sys.argv[2])
sft = parse_spec("race", "name:String,*geom:Point")
time.sleep(max(0.0, start_at - time.time()))  # synchronized start
wins = 0
try:
    register_schema(path, sft)
    wins = 1
except SchemaExistsError:
    pass
# hammer a few more coordinated mutations to stress the lock/manifest
for i in range(5):
    try:
        register_schema(path, parse_spec(f"t{i}", "name:String,*geom:Point"))
    except SchemaExistsError:
        pass
print("WIN" if wins else "LOSE")
"""


class TestTwoProcessSchemaRace:
    def test_exactly_one_winner_no_torn_catalog(self, tmp_path):
        """Two OS processes race create_schema on a shared catalog: exactly
        one wins; the manifest stays valid and loadable throughout."""
        path = str(tmp_path / "cat")
        start_at = time.time() + 1.0
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _RACE_SCRIPT, path, str(start_at)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env, cwd="/root/repo",
            )
            for _ in range(2)
        ]
        outs = [p.communicate(timeout=180) for p in procs]
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, (out, err)
        verdicts = [out.strip().splitlines()[-1] for out, _ in outs]
        assert sorted(verdicts) == ["LOSE", "WIN"], (verdicts, outs)
        manifest = json.loads(
            (tmp_path / "cat" / "manifest.json").read_text()
        )
        assert "race" in manifest["types"]
        # every contended t{i} registered exactly once; catalog loads clean
        assert all(f"t{i}" in manifest["types"] for i in range(5))
        from geomesa_tpu.store.persistence import load

        ds = load(path)
        assert set(ds.list_schemas()) == {"race"} | {f"t{i}" for i in range(5)}


class TestSaveType:
    def test_multi_writer_shared_catalog(self, tmp_path):
        from geomesa_tpu.store.persistence import load, save_type

        path = str(tmp_path / "cat")
        a = DataStore(backend="tpu")
        a.create_schema("alpha", "name:String,dtg:Date,*geom:Point")
        a.write("alpha", [
            {"name": "a", "dtg": T0, "geom": Point(1.0, 1.0)}
        ], fids=["a0"])
        b = DataStore(backend="tpu")
        b.create_schema("beta", "name:String,dtg:Date,*geom:Point")
        b.write("beta", [
            {"name": "b", "dtg": T0, "geom": Point(2.0, 2.0)},
            {"name": "b2", "dtg": T0, "geom": Point(3.0, 3.0)},
        ], fids=["b0", "b1"])

        save_type(a, path, "alpha")
        save_type(b, path, "beta")  # must NOT clobber alpha
        ds = load(path)
        assert set(ds.list_schemas()) == {"alpha", "beta"}
        assert ds.stats_count("alpha") == 1 and ds.stats_count("beta") == 2

        # second-generation save of one type leaves the other untouched
        a.write("alpha", [
            {"name": "a2", "dtg": T0, "geom": Point(4.0, 4.0)}
        ], fids=["a1"])
        save_type(a, path, "alpha")
        ds2 = load(path)
        assert ds2.stats_count("alpha") == 2 and ds2.stats_count("beta") == 2
