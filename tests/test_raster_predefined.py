"""Raster tile store + predefined dataset converters (reference:
AccumuloRasterStore, geomesa-tools/conf/sfts — SURVEY.md §2.6/§2.16)."""

import numpy as np
import pytest

from geomesa_tpu.convert.predefined import (
    PREDEFINED,
    predefined_converter,
    predefined_sft,
)
from geomesa_tpu.raster import RasterStore


class TestRasterStore:
    def test_put_and_mosaic_single(self):
        rs = RasterStore()
        chip = np.arange(64, dtype=np.float64).reshape(8, 8)
        rs.put(chip, (0.0, 0.0, 1.40625, 1.40625))  # ~3-char geohash cell
        assert rs.count() == 1
        out = rs.mosaic((0.0, 0.0, 1.40625, 1.40625), 8, 8)
        np.testing.assert_array_equal(out, chip)

    def test_mosaic_of_adjacent_chips(self):
        rs = RasterStore()
        left = np.full((4, 4), 1.0)
        right = np.full((4, 4), 2.0)
        w = 1.40625
        rs.put(left, (0.0, 0.0, w, w))
        rs.put(right, (w, 0.0, 2 * w, w))
        out = rs.mosaic((0.0, 0.0, 2 * w, w), 8, 4)
        assert np.all(out[:, :4] == 1.0)
        assert np.all(out[:, 4:] == 2.0)

    def test_finer_chips_win(self):
        rs = RasterStore()
        coarse = np.full((4, 4), 9.0)
        fine = np.full((4, 4), 5.0)
        rs.put(coarse, (0.0, 0.0, 11.25, 11.25))  # 2-char cell
        rs.put(fine, (0.0, 0.0, 1.40625, 1.40625))  # 3-char cell inside it
        out = rs.mosaic((0.0, 0.0, 11.25, 11.25), 16, 16)
        # the fine chip covers the lower-left corner of the target
        assert out[0, 0] == 5.0
        assert out[-1, -1] == 9.0

    def test_empty_region(self):
        rs = RasterStore()
        rs.put(np.ones((2, 2)), (0.0, 0.0, 1.40625, 1.40625))
        out = rs.mosaic((100.0, 40.0, 101.0, 41.0), 4, 4)
        assert np.all(out == 0)


class TestPredefined:
    def test_all_specs_parse(self):
        for name in PREDEFINED:
            sft = predefined_sft(name)
            assert sft.geom_field == "geom"
            assert sft.dtg_field == "dtg"

    def test_tdrive_roundtrip(self):
        conv = predefined_converter("tdrive")
        t = conv.convert_frame(
            __import__("pandas").DataFrame(
                [
                    ["1131", "2008-02-02 13:33:52", "116.36", "39.88"],
                    ["1131", "2008-02-02 13:38:52", "116.37", "39.89"],
                ],
                dtype=str,
            )
        )
        assert len(t) == 2
        assert t.record(0)["taxiId"] == "1131"
        assert t.record(0)["geom"].x == pytest.approx(116.36)
        assert list(t.fids) == ["1131-0", "1131-1"]

    def test_twitter_converter(self):
        conv = predefined_converter("twitter")
        t = conv.convert_frame(
            __import__("pandas").DataFrame(
                [["42", "u1", "hello world", "2017-07-01T00:00:00Z", "-74.0", "40.7"]],
                dtype=str,
            )
        )
        assert t.record(0)["userId"] == "u1"
        assert t.record(0)["dtg"] == 1_498_867_200_000
        assert list(t.fids) == ["42"]
