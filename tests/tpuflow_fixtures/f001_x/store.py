"""The mutations: ``drop_schema`` purges only THROUGH
``HubRegistry.close_all`` (must count as reachable — the cross-module
half of F001); ``drop_schema_leaky`` never reaches the purge and must
still be flagged."""

from geomesa_tpu.analysis.contracts import mutation


@mutation(kind="delete_schema", invalidates=("shard-cache",))
def drop_schema(hub: "HubRegistry", cache, type_name):
    hub.close_all(cache, type_name)


@mutation(kind="rename", invalidates=("shard-cache",))
def drop_schema_leaky(hub: "HubRegistry", cache, type_name):
    # BUG: forgets the hub teardown — the shard cache outlives the name
    hub.members = []
