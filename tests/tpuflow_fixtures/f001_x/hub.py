"""The middle hop: nothing here is contract-decorated — reaching the
purge through ``close_all`` requires the cross-module call graph."""


class HubRegistry:
    def __init__(self):
        self.members = []

    def close_all(self, cache: "ShardCache", type_name):
        for m in self.members:
            m.close()
        cache.drop_all(type_name)
