"""Cross-module F001 fixture: the cache surface and its purge live
here; the mutation that must reach them lives in ``store.py``, two call
hops away through ``hub.HubRegistry.close_all``."""

from geomesa_tpu.analysis.contracts import cache_surface


@cache_surface(name="shard-cache", keyed_by="type_name",
               purge=("drop_all",))
class ShardCache:
    def __init__(self):
        self.by_type = {}

    def drop_all(self, type_name):
        self.by_type.pop(type_name, None)
