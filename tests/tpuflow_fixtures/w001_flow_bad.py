"""Stale tpuflow waivers: the flow prong judges F rules, so a waiver
naming one on a clean line is dead weight (W001) — same-line and
next-line forms."""

from geomesa_tpu.analysis.contracts import device_band


@device_band(certain=True)
def certain_step(xs):
    # tpuflow: disable-next-line=F003
    return xs * 2


def helper(xs):
    return certain_step(xs)  # tpuflow: disable=F001
