"""Seed regression (ISSUE 7): the recreate-serves-dead-cache shape.

The pre-fix buffer pool kept per-type device buffers fingerprinted by an
epoch tuple that RESTARTED on delete_schema + create_schema, so a
recreated type was served the dead table's staged buffers. The contract
shape below reproduces it: a type_name-keyed surface whose declared
mutations purge on every WRITE path but never on a death
(delete_schema/rename) — F001's death check must flag the surface."""

from geomesa_tpu.analysis.contracts import cache_surface, mutation


@cache_surface(name="staged-buffers", keyed_by="type_name",
               purge=("purge",))
class StagedPool:
    def __init__(self):
        self.live = {}

    def purge(self, type_name):
        self.live.pop(type_name, None)


@mutation(kind="write", invalidates=("staged-buffers",))
def write_rows(pool: "StagedPool", type_name, rows):
    pool.live.setdefault(type_name, []).extend(rows)
    pool.purge(type_name)


@mutation(kind="delete", invalidates=("staged-buffers",))
def delete_rows(pool: "StagedPool", type_name, fids):
    pool.purge(type_name)
