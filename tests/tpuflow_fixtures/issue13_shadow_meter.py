"""Seed regression (ISSUE 13): the shadow-meter shape.

Pre-fix, auditor-replayed queries flowed through the same completion
path as live traffic and TRAINED the cost table / billed the usage
meter — audit traffic steering the planner it audits. The contract
shape below reproduces it: the shadow-plane replay reaches the
``observe`` feedback sink through an unguarded shared helper — F002
must flag the sink call."""

from geomesa_tpu.analysis.contracts import feedback_sink, shadow_plane


class CostTable:
    @feedback_sink
    def observe(self, sig, ms):
        pass


def run_select(store, q, costs: "CostTable"):
    ms = store.execute(q)
    costs.observe("sig", ms)
    return ms


@shadow_plane
class Auditor:
    def replay_one(self, store, q, costs):
        return run_select(store, q, costs)
