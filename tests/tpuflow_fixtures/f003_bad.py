"""F003 bad: f64 inside the certain band (a dtype reference AND a call
into the refine), plus a cand-band superset whose decision is taken
without ever reaching the f64 refine."""

import numpy as np

from geomesa_tpu.analysis.contracts import device_band


@device_band(refine=True)
def refine_exact(xs, rows):
    return xs[rows].astype("float64") > 0.5


@device_band(certain=True)
def certain_step(xs):
    hi = np.float64(1.0)
    exact = refine_exact(xs, None)
    return (xs * hi) > 0.5, exact


@device_band(cand=True)
def cand_step(xs):
    return xs > 0.2


def alert_on_rows(xs, log):
    cand = cand_step(xs)
    if cand.any():
        # BUG: alerting on the widened superset ships false positives
        log.append("hit")
