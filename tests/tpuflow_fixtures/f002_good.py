"""F002 good twin: the sink-calling function consults the shadow guard,
so it is shadow-aware and trusted to gate its own feedback."""

from geomesa_tpu.analysis.contracts import (
    feedback_sink,
    shadow_guard,
    shadow_plane,
)

_IN_SHADOW = False


@shadow_guard
def in_shadow():
    return _IN_SHADOW


class Meter:
    @feedback_sink
    def observe(self, ms):
        pass


@shadow_plane
def run_audit(meter: "Meter"):
    replay(meter)


def replay(meter: "Meter"):
    if not in_shadow():
        meter.observe(1.0)
