"""F003 good twin: the certain band stays f32; the cand superset
narrows through the two-band merge (``out[band] |= exact`` — refine
output merged into the band retires the obligation); a returned band
hands the obligation to the caller, who refines it."""

import numpy as np

from geomesa_tpu.analysis.contracts import device_band


@device_band(refine=True)
def refine_exact(xs, rows):
    return xs[rows].astype("float64") > 0.5


@device_band(certain=True)
def certain_step(xs):
    return (xs.astype(np.float32) * np.float32(0.5)) > 0.25


@device_band(cand=True)
def cand_step(xs):
    return xs > 0.2, xs > 0.8


def select_rows(xs):
    cand, sure = cand_step(xs)
    out = sure.copy()
    band = cand & ~sure
    exact = refine_exact(xs, band)
    out[band] |= exact
    return out


def forward_band(xs):
    # returning the superset hands the refine obligation to the caller
    return cand_step(xs)


def caller_refines(xs):
    cand, sure = forward_band(xs)
    band = cand & ~sure
    return sure, refine_exact(xs, band)
