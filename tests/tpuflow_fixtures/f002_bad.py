"""F002 bad: shadow-plane code reaches a feedback sink with no guard
on the path — and a ROOT's own guard reference must not bless the sink
call below it (the auditor's ``with shadow():`` wrapper rule)."""

from geomesa_tpu.analysis.contracts import (
    feedback_sink,
    shadow_guard,
    shadow_plane,
)

_IN_SHADOW = False


@shadow_guard
def shadow():
    return _IN_SHADOW


class Meter:
    @feedback_sink
    def observe(self, ms):
        pass


@shadow_plane
def run_audit(meter: "Meter"):
    replay(meter)


def replay(meter: "Meter"):
    meter.observe(1.0)


@shadow_plane
def sweep(meter: "Meter"):
    # a root consulting the guard is NOT a barrier: its wrapper would
    # vacuously bless everything below it
    shadow()
    meter.observe(2.0)
