"""F001 good twin: every mutation reaches its declared purge, the
name-keyed surface dies on delete_schema, the epoch surface declares a
monotonic stamp, and the memo surface is immutable."""

from geomesa_tpu.analysis.contracts import cache_surface, mutation


@cache_surface(name="tile-cache-ok", keyed_by="type_name",
               purge=("invalidate",))
class TileCache:
    def __init__(self):
        self.entries = {}

    def invalidate(self, type_name):
        self.entries.pop(type_name, None)


@cache_surface(name="layout-cache-ok", keyed_by="epoch", epoch="monotonic")
class LayoutCache:
    def __init__(self):
        self.by_epoch = {}


@cache_surface(name="step-memo-ok", keyed_by="shape-bucket", immutable=True)
def cached_step(n_cap):
    return n_cap


@mutation(kind="write", invalidates=("tile-cache-ok",))
def write_rows(cache: "TileCache", rows):
    cache.entries.setdefault("t", []).extend(rows)
    cache.invalidate("t")


@mutation(kind="delete_schema", invalidates=("tile-cache-ok",))
def drop_type(cache: "TileCache", type_name):
    cache.invalidate(type_name)
