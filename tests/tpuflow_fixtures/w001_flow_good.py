"""A LIVE tpuflow waiver: it suppresses a real F003 finding, so the
stale-waiver scan stays silent and the file gates clean."""

import numpy as np

from geomesa_tpu.analysis.contracts import device_band


@device_band(certain=True)
def certain_step(xs):
    # reviewed: the constant feeds a host-side debug threshold only
    # tpuflow: disable-next-line=F003
    hi = np.float64(0.5)
    return xs > hi
