"""F001 bad: a mutation that never reaches its declared purge, a
name-keyed surface with no death mutation, a mutation naming an unknown
surface, and an epoch surface with no monotonic proof (never imported —
pure-AST fixture)."""

from geomesa_tpu.analysis.contracts import cache_surface, mutation


@cache_surface(name="tile-cache", keyed_by="type_name",
               purge=("invalidate",))
class TileCache:
    def __init__(self):
        self.entries = {}

    def invalidate(self, type_name):
        self.entries.pop(type_name, None)


@cache_surface(name="layout-cache", keyed_by="epoch")
class LayoutCache:
    def __init__(self):
        self.by_epoch = {}


@mutation(kind="write", invalidates=("tile-cache",))
def write_rows(cache: "TileCache", rows):
    # BUG: never calls TileCache.invalidate — the cache survives the write
    cache.entries.setdefault("t", []).extend(rows)


@mutation(kind="delete", invalidates=("missing-cache",))
def delete_rows(cache: "TileCache", fids):
    cache.invalidate("t")
