"""Cross-module F002 fixture: the feedback sink lives here; the shadow
root and the leaking call chain live two modules away."""

from geomesa_tpu.analysis.contracts import feedback_sink


class CostMeter:
    @feedback_sink
    def observe(self, sig, ms):
        pass
