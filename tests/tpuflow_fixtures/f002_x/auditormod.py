"""The shadow root: a @shadow_plane class whose replay path reaches the
sink in ``meters.py`` through ``pipelinemod.run_shard``."""

from geomesa_tpu.analysis.contracts import shadow_plane

from f002_x.pipelinemod import run_shard


@shadow_plane
class Auditor:
    def replay_one(self, store, q, costs):
        return run_shard(store, q, costs)
