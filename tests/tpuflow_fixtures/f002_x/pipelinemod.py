"""The middle hop: plain pipeline code, unaware it can run under the
auditor — its unguarded sink call is the cross-module F002 finding."""


def run_shard(store, q, costs: "CostMeter"):
    ms = store.execute(q)
    costs.observe("sig", ms)
    return ms
