"""tpuflow: fixture tests pin exact (rule, line) findings per F-rule
family, the cross-module fixtures prove findings ride the whole-program
call graph (a purge reachable only through ``HubRegistry.close_all``
must count), the seeded ISSUE-7 / ISSUE-13 regression fixtures pin the
pre-fix shapes, the package gate runs the contract analysis over the
live tree, and the CLI / inventory / waiver-parity / incremental-cache
/ exit-code surfaces are covered end-to-end.

Pure AST like the other prongs: fixtures under ``tpuflow_fixtures/``
are never imported, and everything runs with JAX gated off."""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from geomesa_tpu.analysis import LintConfig
from geomesa_tpu.analysis.core import AnalysisCrash, lint_paths
from geomesa_tpu.analysis.flow import (
    FLOW_RULE_IDS,
    analyze_flow_paths,
    contract_inventory,
)
from geomesa_tpu.analysis.race import analyze_race_paths
from geomesa_tpu.analysis.race.lockset import load_modules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "geomesa_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tpuflow_fixtures")


def _flow(name, config=None):
    vs = analyze_flow_paths([os.path.join(FIXTURES, name)],
                            config or LintConfig())
    return [(os.path.basename(v.path), v.line, v.rule)
            for v in vs if not v.suppressed]


def _run_cli(*argv, env_extra=None, cwd=None):
    env = dict(os.environ, GEOMESA_TPU_NO_JAX="1")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "geomesa_tpu.analysis", *argv],
        capture_output=True, text=True, env=env, cwd=cwd or REPO)


class TestRuleFixtures:
    """Each F-rule family flags its known-bad fixture at exact lines and
    stays silent on the known-good twin."""

    @pytest.mark.parametrize("name,expected", [
        # death (no delete_schema/rename), epoch non-monotonic + orphan,
        # unreachable purge, unknown surface name
        ("f001_bad.py", [
            ("f001_bad.py", 9, "F001"),
            ("f001_bad.py", 19, "F001"),
            ("f001_bad.py", 19, "F001"),
            ("f001_bad.py", 25, "F001"),
            ("f001_bad.py", 31, "F001"),
        ]),
        # unguarded sink via a helper, and a ROOT's own guard reference
        # must not bless the sink below it
        ("f002_bad.py", [
            ("f002_bad.py", 31, "F002"),
            ("f002_bad.py", 39, "F002"),
        ]),
        # f64 dtype in the certain band, certain calling the refine,
        # a cand superset decided on without refinement
        ("f003_bad.py", [
            ("f003_bad.py", 17, "F003"),
            ("f003_bad.py", 18, "F003"),
            ("f003_bad.py", 28, "F003"),
        ]),
        # stale tpuflow waivers, next-line and same-line forms
        ("w001_flow_bad.py", [
            ("w001_flow_bad.py", 10, "W001"),
            ("w001_flow_bad.py", 15, "W001"),
        ]),
    ])
    def test_bad_fixture_flagged(self, name, expected):
        assert _flow(name) == expected

    @pytest.mark.parametrize("name", [
        "f001_good.py", "f002_good.py", "f003_good.py",
        "w001_flow_good.py",
    ])
    def test_good_fixture_clean(self, name):
        assert _flow(name) == []

    def test_live_waiver_suppresses_f_rule(self):
        """The shared waiver tokenizer honors the tpuflow namespace: the
        good W001 fixture DOES contain a real F003, waived in source."""
        vs = analyze_flow_paths(
            [os.path.join(FIXTURES, "w001_flow_good.py")], LintConfig())
        waived = [v for v in vs if v.waived]
        assert [(v.rule, v.line) for v in waived] == [("F003", 13)]


class TestCrossModule:
    """The findings that REQUIRE the whole-program call graph."""

    def test_purge_through_hub_counts(self):
        """f001_x: ``drop_schema`` reaches the purge only through
        ``HubRegistry.close_all`` two modules away — reachable, so only
        the genuinely leaky mutation is flagged."""
        assert _flow("f001_x") == [("store.py", 14, "F001")]

    def test_shadow_taint_crosses_modules(self):
        """f002_x: root, pipeline helper, and sink live in three
        modules; the finding lands on the helper's sink call."""
        assert _flow("f002_x") == [("pipelinemod.py", 7, "F002")]


class TestSeedRegressions:
    """The ISSUE-7 and ISSUE-13 pre-fix shapes are the flow prong's seed
    corpus: each must be flagged at the exact (rule, line)."""

    def test_issue7_recreate_serves_dead_cache(self):
        vs = analyze_flow_paths(
            [os.path.join(FIXTURES, "issue7_recreate.py")], LintConfig())
        new = [v for v in vs if not v.suppressed]
        assert [(v.rule, v.line) for v in new] == [("F001", 13)]
        assert "death mutation" in new[0].message
        assert "deleted-then-recreated" in new[0].message

    def test_issue13_shadow_meter(self):
        vs = analyze_flow_paths(
            [os.path.join(FIXTURES, "issue13_shadow_meter.py")],
            LintConfig())
        new = [v for v in vs if not v.suppressed]
        assert [(v.rule, v.line) for v in new] == [("F002", 21)]
        assert "feedback sink CostTable.observe" in new[0].message


class TestPackageFlowGate:
    """The live tree holds its own contracts: zero unwaived F findings
    (fixes, not waivers — there are no F entries in the baseline), and
    the declared inventory covers the real cache/feedback planes."""

    def test_package_clean(self):
        vs = analyze_flow_paths([PKG], LintConfig())
        new = [v for v in vs if not v.suppressed]
        assert new == [], "\n".join(
            f"{v.path}:{v.line}: {v.rule} {v.message}" for v in new)

    def test_no_f_rule_waivers_in_tree(self):
        """The tentpole bar: live-tree F findings were FIXED, not waived
        — the tpuflow waiver namespace is unused inside the package."""
        out = subprocess.run(
            ["grep", "-rnE", r"# tpuflow: disable(-next-line)?=F[0-9]",
             PKG], capture_output=True, text=True)
        assert out.stdout == ""

    def test_contract_inventory_coverage(self):
        modules, errors = load_modules([PKG])
        assert errors == []
        inv = contract_inventory(modules, LintConfig())
        surfaces = {s["name"] for s in inv["cache_surfaces"]}
        assert len(surfaces) >= 10
        assert {"plan-cache", "agg-pyramids", "geoblocks-query-cache",
                "buffer-pool", "track-state-cache"} <= surfaces
        sinks = {d["fn"] for d in inv["feedback_sinks"]}
        assert len(sinks) >= 4
        assert {"CostTable.observe", "UsageMeter.observe",
                "SloEngine.observe"} <= sinks
        roots = {r["name"] for r in inv["shadow_planes"]}
        assert {"ContinuousAuditor", "InvariantSweeper"} <= roots
        roles = {(b["fn"], b["role"]) for b in inv["device_bands"]}
        assert ("trajectory.corridor:corridor_masks_f64",
                "refine") in roles
        assert ("parallel.query:cached_corridor_step", "cand") in roles

    def test_every_declared_purge_resolves(self):
        """Purge specs that fail to resolve are silent coverage holes —
        the inventory must show resolved keys for every non-immutable
        surface that declares a purge."""
        modules, _ = load_modules([PKG])
        inv = contract_inventory(modules, LintConfig())
        for s in inv["cache_surfaces"]:
            if s["purge"] and not s["immutable"]:
                assert s["purge"], s["name"]


class TestWaiverParity:
    """One tokenizer, three namespaces: each prong judges exactly its
    own waivers stale and leaves the other prongs' namespaces alone."""

    SRC = (
        "import threading\n"
        "x = 1  # tpulint: disable=C001\n"
        "y = 2  # tpurace: disable=R001\n"
        "z = 3  # tpuflow: disable=F001\n"
    )

    @pytest.fixture()
    def tree(self, tmp_path):
        p = tmp_path / "waivers.py"
        p.write_text(self.SRC)
        return str(p)

    def test_lint_judges_only_its_namespace(self, tree):
        vs = lint_paths([tree], LintConfig())
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 2)]

    def test_race_judges_only_its_namespace(self, tree):
        cfg = LintConfig(race_paths=("",), r003_paths=("",))
        vs = analyze_race_paths([tree], cfg)
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 3)]

    def test_flow_judges_only_its_namespace(self, tree):
        vs = analyze_flow_paths([tree], LintConfig())
        w = [(v.rule, v.line) for v in vs if v.rule == "W001"]
        assert w == [("W001", 4)]


class TestCli:
    """Exit codes, the contract inventory surface, and SARIF."""

    def test_flow_gate_exits_zero_on_package(self):
        out = _run_cli("--flow", PKG)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_flow_bad_fixture_exits_one(self):
        out = _run_cli("--flow", os.path.join(FIXTURES, "f003_bad.py"))
        assert out.returncode == 1
        assert "F003" in out.stdout

    def test_contracts_inventory_json(self):
        out = _run_cli("--flow", "--contracts", PKG)
        assert out.returncode == 0, out.stderr
        inv = json.loads(out.stdout)
        assert len(inv["cache_surfaces"]) >= 10
        assert len(inv["feedback_sinks"]) >= 4

    def test_contracts_requires_flow(self):
        out = _run_cli("--contracts", PKG)
        assert out.returncode == 2
        assert "--contracts requires --flow" in out.stderr

    def test_flow_rules_filter_validation(self):
        out = _run_cli("--flow", "--rules", "J001", PKG)
        assert out.returncode == 2
        out = _run_cli("--rules", "F001", PKG)
        assert out.returncode == 2
        assert "--flow" in out.stderr

    def test_list_rules_includes_flow(self):
        out = _run_cli("--list-rules")
        assert out.returncode == 0
        for rid in FLOW_RULE_IDS:
            assert rid in out.stdout


class TestExitCodeAudit:
    """A crashed or partial analysis must never read as a clean run."""

    def test_contracts_parse_error_exits_one(self, tmp_path):
        """A syntax error silently shrinks the inventory: incomplete,
        not clean — the same audit that fixed ``--guards``."""
        (tmp_path / "ok.py").write_text("x = 1\n")
        (tmp_path / "broken.py").write_text("def f(:\n")
        out = _run_cli("--flow", "--contracts", str(tmp_path))
        assert out.returncode == 1
        assert "broken.py" in out.stderr

    def test_guards_parse_error_exits_one(self, tmp_path):
        (tmp_path / "broken.py").write_text("def f(:\n")
        out = _run_cli("--race", "--guards", str(tmp_path))
        assert out.returncode == 1
        assert "broken.py" in out.stderr

    def test_crashed_prong_exits_three_naming_file(self, monkeypatch,
                                                   capsys):
        """AnalysisCrash → exit 3 with the failing file in the message
        (red leg: the pre-audit behavior was a clean exit 0)."""
        from geomesa_tpu.analysis import __main__ as cli
        from geomesa_tpu.analysis import flow

        target = os.path.join(FIXTURES, "f001_good.py")

        def boom(paths, config=None):
            raise AnalysisCrash(target, "rule F001",
                                RuntimeError("synthetic"))

        monkeypatch.setattr(flow, "analyze_flow_paths", boom)
        rc = cli.main(["--flow", target])
        assert rc == 3
        err = capsys.readouterr().err
        assert "f001_good.py" in err and "rule F001" in err

    def test_lint_rule_crash_exits_three(self, monkeypatch, capsys):
        """The raise site itself: a rule crashing mid-check surfaces as
        AnalysisCrash naming the rule and the file being linted."""
        from geomesa_tpu.analysis import __main__ as cli
        from geomesa_tpu.analysis.rules import all_rules

        rule = all_rules()["J001"]

        def boom(mod, config):
            raise RuntimeError("synthetic rule crash")

        monkeypatch.setattr(type(rule), "check", staticmethod(boom))
        target = os.path.join(FIXTURES, "f001_good.py")
        rc = cli.main([target])
        assert rc == 3
        err = capsys.readouterr().err
        assert "rule J001" in err and "f001_good.py" in err

    def test_internal_error_exits_three(self, monkeypatch, capsys):
        from geomesa_tpu.analysis import __main__ as cli
        from geomesa_tpu.analysis import flow

        def boom(paths, config=None):
            raise RuntimeError("unexpected")

        monkeypatch.setattr(flow, "analyze_flow_paths", boom)
        rc = cli.main(["--flow", os.path.join(FIXTURES, "f001_good.py")])
        assert rc == 3
        assert "internal error" in capsys.readouterr().err


class TestIncremental:
    """--changed-only content-hash caches: warm runs skip re-analysis,
    edits invalidate, and --full is the escape hatch."""

    def _cli(self, tmp_path, *argv):
        return _run_cli(*argv, env_extra={
            "TPULINT_CACHE_DIR": str(tmp_path / "cache")})

    def test_edit_invalidates_cache(self, tmp_path):
        """Red/green: a warm cache must not mask a NEW violation
        introduced by an edit (the content hash, not mtime, is the
        key)."""
        tree = tmp_path / "tree"
        tree.mkdir()
        shutil.copy(os.path.join(FIXTURES, "f001_good.py"),
                    tree / "mod.py")
        out = self._cli(tmp_path, "--flow", "--changed-only", str(tree))
        assert out.returncode == 0, out.stdout + out.stderr
        # warm hit on the unchanged tree stays clean
        out = self._cli(tmp_path, "--flow", "--changed-only", str(tree))
        assert out.returncode == 0
        # the edit introduces a certain-band f64: must be flagged
        src = (tree / "mod.py").read_text()
        src += (
            "\n\nfrom geomesa_tpu.analysis.contracts import device_band\n"
            "import numpy as np\n\n\n"
            "@device_band(certain=True)\n"
            "def bad_step(xs):\n"
            "    return xs.astype(np.float64)\n"
        )
        (tree / "mod.py").write_text(src)
        out = self._cli(tmp_path, "--flow", "--changed-only", str(tree))
        assert out.returncode == 1
        assert "F003" in out.stdout

    def test_full_escape_hatch_reanalyzes(self, tmp_path):
        tree = tmp_path / "tree"
        tree.mkdir()
        shutil.copy(os.path.join(FIXTURES, "f003_bad.py"),
                    tree / "mod.py")
        out = self._cli(tmp_path, "--flow", "--changed-only", str(tree))
        assert out.returncode == 1
        out = self._cli(tmp_path, "--flow", "--changed-only", "--full",
                        str(tree))
        assert out.returncode == 1
        assert "F003" in out.stdout

    def test_warm_changed_only_halves_wall_time(self, tmp_path):
        """The lint.sh acceptance bound: the three-prong analysis with
        --changed-only on an UNCHANGED tree must cost ≤50% of the full
        run (in practice it is <5% — one hash pass, zero re-analysis)."""
        from geomesa_tpu.analysis import __main__ as cli

        targets = [PKG, os.path.join(REPO, "scripts"),
                   os.path.join(REPO, "bench.py")]
        os.environ["TPULINT_CACHE_DIR"] = str(tmp_path / "cache")
        try:
            t0 = time.monotonic()
            rc = cli.main(["--all-prongs", *targets, "--baseline",
                           os.path.join(REPO, ".tpulint-baseline.json"),
                           "--changed-only", "--full"])
            full_s = time.monotonic() - t0
            assert rc == 0
            t0 = time.monotonic()
            rc = cli.main(["--all-prongs", *targets, "--baseline",
                           os.path.join(REPO, ".tpulint-baseline.json"),
                           "--changed-only"])
            warm_s = time.monotonic() - t0
            assert rc == 0
        finally:
            os.environ.pop("TPULINT_CACHE_DIR", None)
        assert warm_s <= 0.5 * full_s, (
            f"warm --changed-only took {warm_s:.2f}s vs full "
            f"{full_s:.2f}s — the incremental cache is not being hit")


class TestSarifMultiProng:
    """--all-prongs --format sarif: ONE log, one run per prong, each
    with its own driver and rule metadata; F-rule suppressions survive
    the round trip."""

    def test_one_log_per_prong_drivers(self):
        out = _run_cli("--all-prongs", "--format", "sarif",
                       os.path.join(FIXTURES, "f001_good.py"))
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        names = [r["tool"]["driver"]["name"] for r in doc["runs"]]
        assert names == ["tpulint", "tpurace", "tpuflow", "tpusync"]
        flow_rules = {r["id"] for r in
                      doc["runs"][2]["tool"]["driver"]["rules"]}
        assert {"F001", "F002", "F003"} <= flow_rules
        lint_rules = {r["id"] for r in
                      doc["runs"][0]["tool"]["driver"]["rules"]}
        assert not lint_rules & {"F001", "R001"}

    def test_f_rule_suppression_round_trip(self):
        out = _run_cli("--all-prongs", "--format", "sarif",
                       os.path.join(FIXTURES, "w001_flow_good.py"))
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        flow_run = doc["runs"][2]
        results = flow_run["results"]
        f003 = [r for r in results if r["ruleId"] == "F003"]
        assert len(f003) == 1
        assert f003[0]["suppressions"][0]["kind"] == "inSource"
