"""TWKB codec round-trips and size characteristics (reference:
TwkbSerialization — SURVEY.md §2.4)."""

import numpy as np
import pytest

from geomesa_tpu.geometry.twkb import from_twkb, to_twkb
from geomesa_tpu.geometry.types import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geometry.wkb import to_wkb


def _assert_close(a, b, tol):
    np.testing.assert_allclose(a, b, atol=tol)


GEOMS = [
    Point(12.3456789, -45.6789012),
    Point(-180.0, 90.0),
    LineString([[0, 0], [1.5, 2.25], [3.125, -4.0625], [3.125001, -4.0625]]),
    Polygon([[0, 0], [10, 0], [10, 10], [0, 10]],
            holes=(np.array([[2, 2], [4, 2], [4, 4], [2, 4]], dtype=float),)),
    MultiPoint([Point(1, 2), Point(3, 4), Point(-5, -6)]),
    MultiLineString([LineString([[0, 0], [1, 1]]), LineString([[5, 5], [6, 7], [8, 9]])]),
    MultiPolygon([
        Polygon([[0, 0], [2, 0], [2, 2], [0, 2]]),
        Polygon([[10, 10], [12, 10], [12, 12], [10, 12]]),
    ]),
]


class TestRoundTrip:
    @pytest.mark.parametrize("g", GEOMS, ids=[type(g).__name__ + str(i) for i, g in enumerate(GEOMS)])
    def test_roundtrip_p7(self, g):
        out = from_twkb(to_twkb(g, precision=7))
        assert type(out) is type(g)
        tol = 0.5 * 10**-7
        if isinstance(g, Point):
            _assert_close([out.x, out.y], [g.x, g.y], tol)
        elif isinstance(g, LineString):
            _assert_close(out.coords, g.coords, tol)
        elif isinstance(g, Polygon):
            for ra, rb in zip(out.rings, g.rings):
                _assert_close(ra, rb, tol)
        else:
            assert len(out.parts) == len(g.parts)

    def test_none_roundtrip(self):
        assert from_twkb(to_twkb(None)) is None

    def test_precision_controls_error(self):
        p = Point(12.3456789, -45.6789012)
        for prec in (0, 2, 5, 7):
            out = from_twkb(to_twkb(p, precision=prec))
            assert abs(out.x - p.x) <= 0.5 * 10**-prec
            assert abs(out.y - p.y) <= 0.5 * 10**-prec

    def test_negative_precision(self):
        # coarse (multiple-of-10) rounding is part of the spec
        p = Point(12345.0, -6789.0)
        out = from_twkb(to_twkb(p, precision=-2))
        assert out.x == pytest.approx(12300.0)
        assert out.y == pytest.approx(-6800.0)

    def test_invalid_precision(self):
        with pytest.raises(ValueError):
            to_twkb(Point(0, 0), precision=12)


class TestCompactness:
    def test_track_much_smaller_than_wkb(self):
        rng = np.random.default_rng(3)
        # dense GPS-like track: small deltas between consecutive fixes
        steps = rng.normal(0, 1e-4, (500, 2))
        coords = np.cumsum(steps, axis=0) + [10.0, 50.0]
        ls = LineString(coords)
        twkb = to_twkb(ls, precision=6)
        wkb = to_wkb(ls)
        assert len(twkb) < len(wkb) / 4  # delta varints beat 16B/vertex easily
        out = from_twkb(twkb)
        np.testing.assert_allclose(out.coords, ls.coords, atol=0.5 * 10**-6)

    def test_delta_continuity_across_parts(self):
        # deltas continue across parts/rings (shared `last` cursor) — decode
        # must mirror encode exactly
        mp = MultiPolygon([
            Polygon([[100, 100], [101, 100], [101, 101], [100, 101]]),
            Polygon([[100.5, 100.5], [100.6, 100.5], [100.6, 100.6], [100.5, 100.6]]),
        ])
        out = from_twkb(to_twkb(mp, precision=4))
        for pa, pb in zip(out.parts, mp.parts):
            for ra, rb in zip(pa.rings, pb.rings):
                np.testing.assert_allclose(ra, rb, atol=0.5 * 10**-4)
