"""Pallas kernel parity tests (interpret mode on the CPU test mesh).

The kernels are the TPU re-materialization of the reference's server-side hot
loops (``Z3Filter.inBounds`` int-domain compares, ``sfcurve`` Morton spreads —
SURVEY.md §2.9). Interpret mode runs the same kernel code the TPU compiles;
parity is asserted against independent numpy referees.
"""

import numpy as np
import pytest

import geomesa_tpu  # noqa: F401
from geomesa_tpu.curve import zorder
from geomesa_tpu.ops.pallas_kernels import batched_count, z2_encode, z3_encode
from geomesa_tpu.ops.refine import MAX_BOXES, MAX_TIMES, pack_boxes, pack_times


def _referee(x, y, b, o, boxes, times):
    q = len(boxes)
    out = np.zeros(q, np.int64)
    for qi in range(q):
        inb = np.zeros(len(x), bool)
        for k in range(MAX_BOXES):
            bx = boxes[qi, k]
            inb |= (x >= bx[0]) & (x <= bx[1]) & (y >= bx[2]) & (y <= bx[3])
        int_ = np.zeros(len(x), bool)
        for k in range(MAX_TIMES):
            tt = times[qi, k]
            after = (b > tt[0]) | ((b == tt[0]) & (o >= tt[1]))
            before = (b < tt[2]) | ((b == tt[2]) & (o <= tt[3]))
            int_ |= after & before
        out[qi] = (inb & int_).sum()
    return out


@pytest.fixture(scope="module")
def cols(rng):
    n = 4000
    return (
        rng.integers(0, 2**31 - 1, n).astype(np.int32),
        rng.integers(0, 2**31 - 1, n).astype(np.int32),
        rng.integers(0, 50, n).astype(np.int32),
        rng.integers(0, 86_400_000, n).astype(np.int32),
    )


def _payload(rng, q):
    boxes, times = [], []
    for _ in range(q):
        xs = np.sort(rng.integers(0, 2**31 - 1, 2).astype(np.int32))
        ys = np.sort(rng.integers(0, 2**31 - 1, 2).astype(np.int32))
        boxes.append(pack_boxes(np.array([[xs[0], xs[1], ys[0], ys[1]]], np.int32)))
        blo, bhi = np.sort(rng.integers(0, 50, 2).astype(np.int32))
        times.append(
            pack_times(np.array([[blo, 0, bhi, 50_000_000]], np.int32))
        )
    return np.stack(boxes), np.stack(times)


class TestBatchedCount:
    def test_parity(self, rng, cols):
        x, y, b, o = cols
        boxes, times = _payload(rng, 5)
        got = np.asarray(
            batched_count(x, y, b, o, 0, len(x), boxes, times, interpret=True)
        )
        ref = _referee(x, y, b, o, boxes, times)
        assert (got == ref).all()

    def test_base_offset_and_padding_masked(self, rng, cols):
        """Interior-shard tile padding must not alias the next shard's rows."""
        x, y, b, o = cols
        boxes = np.stack([pack_boxes(None)])  # whole world
        times = np.stack([pack_times(None)])
        # slice is 4000 rows at base 0 of a 3500-row "global" store: rows
        # >= 3500 are global-tail padding; tile pads (4000->4096) are local
        got = np.asarray(
            batched_count(x, y, b, o, 0, 3500, boxes, times, interpret=True)
        )
        assert got[0] == 3500
        # interior shard: base 4000, global n huge — every local row counts,
        # tile padding (rows 4000..4095) must NOT
        got = np.asarray(
            batched_count(x, y, b, o, 4000, 10**9, boxes, times, interpret=True)
        )
        assert got[0] == 4000

    def test_multi_slot_or_semantics(self, rng, cols):
        x, y, b, o = cols
        b1 = np.array([[0, 2**30, 0, 2**30], [2**30, 2**31 - 1, 0, 2**31 - 1]],
                      np.int32)
        boxes = np.stack([pack_boxes(b1)])
        times = np.stack([pack_times(np.array([[0, 0, 10, 0], [20, 0, 50, 10**8]],
                                              np.int32))])
        got = np.asarray(
            batched_count(x, y, b, o, 0, len(x), boxes, times, interpret=True)
        )
        ref = _referee(x, y, b, o, boxes, times)
        assert (got == ref).all()


class TestZEncode:
    def test_z3_matches_zorder(self, rng):
        n = 3000
        xs = rng.integers(0, 2**21, n).astype(np.uint32)
        ys = rng.integers(0, 2**21, n).astype(np.uint32)
        ts = rng.integers(0, 2**21, n).astype(np.uint32)
        hi, lo = z3_encode(xs, ys, ts, interpret=True)
        z = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
            lo
        ).astype(np.uint64)
        assert (z == zorder.encode3(xs, ys, ts)).all()

    def test_z2_matches_zorder(self, rng):
        n = 3000
        xs = rng.integers(0, 2**31, n).astype(np.uint32)
        ys = rng.integers(0, 2**31, n).astype(np.uint32)
        hi, lo = z2_encode(xs, ys, interpret=True)
        z = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
            lo
        ).astype(np.uint64)
        assert (z == zorder.encode2(xs, ys)).all()

    def test_edge_values(self):
        xs = np.array([0, 1, 2**21 - 1], np.uint32)
        hi, lo = z3_encode(xs, xs, xs, interpret=True)
        z = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
            lo
        ).astype(np.uint64)
        assert (z == zorder.encode3(xs, xs, xs)).all()
        assert z[2] == np.uint64(0x7FFFFFFFFFFFFFFF)
