"""Bench harness regressions: the official record must never read
parity-false for HARNESS reasons (VERDICT r2 weak #2 — `iso()` truncated
query windows to whole seconds while the f64 referee used exact
milliseconds, so one sub-second-boundary row went "missing")."""

import json
import subprocess
import sys

import numpy as np
import pytest

import geomesa_tpu  # noqa: F401
from geomesa_tpu.filter.cql import parse as parse_cql
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import Column, FeatureTable, point_column
from geomesa_tpu.schema.sft import AttributeType, parse_spec
from geomesa_tpu.store.datastore import DataStore


def _iso_ms(ms: int) -> str:
    """The bench's millisecond-precision ISO formatter, reproduced here so a
    drift in either copy fails the parity sweep below."""
    import datetime

    dt = datetime.datetime.fromtimestamp(ms / 1000, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{int(ms) % 1000:03d}Z"


def test_bench_iso_matches_local():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", "bench.py")
    # bench.py imports jax at module load; the conftest already pinned cpu.
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # grab the closure-free equivalent by calling bench_select's inner iso
    # indirectly: format a few stamps both ways through the CQL parser
    for ms in (1_499_481_020_001, 1_500_000_000_999, 1_500_000_000_000):
        ast = parse_cql(f"dtg DURING {_iso_ms(ms)}/{_iso_ms(ms + 86_400_000)}")
        assert ast.lo_millis == ms and ast.hi_millis == ms + 86_400_000


class TestSubSecondBoundaryParity:
    """Row-set parity between DataStore CQL select and the exact-ms f64
    referee, fuzzing timestamps ONTO window boundaries at ms offsets."""

    def _parity_sweep(self, seed: int):
        rng = np.random.default_rng(seed)
        n = 4_000
        t0 = 1_499_000_000_000
        span = 10 * 86_400_000
        t = t0 + rng.integers(0, span, n)
        # windows with sub-second endpoints, then rows planted EXACTLY on
        # and ±1 ms around both endpoints (the r02 failure was one row at
        # t=...020001 just inside a truncated window edge)
        windows = []
        for _ in range(8):
            lo = int(t0 + rng.integers(0, span - 86_400_000))
            hi = lo + int(rng.integers(3_600_000, 86_400_000))
            windows.append((lo, hi))
        planted = []
        for lo, hi in windows:
            planted += [lo - 1, lo, lo + 1, hi - 1, hi, hi + 1]
        t = np.concatenate([t, np.array(planted, dtype=np.int64)])
        n = len(t)
        lon = rng.uniform(-60, 60, n)
        lat = rng.uniform(-30, 30, n)

        sft = parse_spec("evt", "dtg:Date,*geom:Point")
        table = FeatureTable.from_columns(
            sft,
            np.arange(n).astype(str).astype(object),
            {"dtg": Column(AttributeType.DATE, t.astype(np.int64)),
             "geom": point_column(lon, lat)},
        )
        for backend in ("oracle", "tpu"):
            ds = DataStore(backend=backend)
            ds.create_schema(sft)
            ds.write("evt", table)
            ds.compact("evt")
            for lo, hi in windows:
                cql = (
                    f"BBOX(geom, -50, -25, 50, 25) AND "
                    f"dtg DURING {_iso_ms(lo)}/{_iso_ms(hi)}"
                )
                got = set(ds.query("evt", cql).table.fids.tolist())
                # referee: exact-ms f64 semantics (DURING is exclusive)
                m = (
                    (lon >= -50) & (lon <= 50) & (lat >= -25) & (lat <= 25)
                    & (t > lo) & (t < hi)
                )
                want = set(np.nonzero(m)[0].astype(str).tolist())
                assert got == want, (
                    backend, lo, hi,
                    sorted(want - got)[:3], sorted(got - want)[:3],
                )

    def test_boundary_rows_fuzz(self):
        for seed in (0, 1, 2):
            self._parity_sweep(seed)


def _bench_mod(name="bench_units_mod"):
    import importlib.util

    spec = importlib.util.spec_from_file_location(name, "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_config_units_round_trip_through_compact():
    """Every canonical config unit must survive the driver's compact
    emission intact (config 8's old prose unit truncated to
    'Grows/s/chip (each row m' in BENCH_DETAIL/BENCH_rNN records)."""
    mod = _bench_mod()
    assert set(mod.BENCHES) <= set(mod.UNITS)
    for cfg, unit in mod.UNITS.items():
        r = {"metric": f"m_{cfg}", "value": 1.0, "unit": unit,
             "vs_baseline": 1.0, "detail": {"row_set_parity": True}}
        c = mod._compact(r)
        assert c["u"] == unit, (cfg, unit, c["u"])
        # and through a full JSON round trip
        assert json.loads(json.dumps(c))["u"] == unit


class TestRegressGate:
    """Pure-function coverage of the perf-regression gate (the live
    red/green smoke runs in scripts/bench_gate.sh)."""

    def test_unit_direction(self):
        mod = _bench_mod()
        assert mod._unit_direction("ms/query") == "lower"
        assert mod._unit_direction("ms p99") == "lower"
        assert mod._unit_direction("Gpairs/s") == "higher"
        assert mod._unit_direction("Grows/s/chip") == "higher"

    def test_compare_lower_is_better(self):
        mod = _bench_mod()
        # 10% slower: inside the 15% threshold
        v = mod._regress_compare(10.0, 11.0, "ms/query", 15.0)
        assert not v["regressed"] and v["delta_pct"] == 10.0
        # 20% slower: regression
        v = mod._regress_compare(10.0, 12.0, "ms/query", 15.0)
        assert v["regressed"] and v["delta_pct"] == 20.0
        # faster is never a regression
        v = mod._regress_compare(10.0, 5.0, "ms/query", 15.0)
        assert not v["regressed"] and v["delta_pct"] < 0

    def test_compare_higher_is_better(self):
        mod = _bench_mod()
        v = mod._regress_compare(1.0, 0.8, "Grows/s/chip", 15.0)
        assert v["regressed"] and v["delta_pct"] == pytest.approx(20.0)
        v = mod._regress_compare(1.0, 1.2, "Grows/s/chip", 15.0)
        assert not v["regressed"]

    def test_injected_slowdown_trips_threshold(self):
        """The gate's self-test contract: identical measurements plus a
        synthetic 20% slowdown must regress at the 15% threshold, in
        BOTH unit directions."""
        mod = _bench_mod()
        v = mod._regress_compare(10.0, 10.0, "ms/query", 15.0, slowdown=1.2)
        assert v["regressed"] and v["delta_pct"] == pytest.approx(20.0)
        assert v["injected_slowdown"] == 1.2
        v = mod._regress_compare(2.0, 2.0, "Grows/s/chip", 15.0, slowdown=1.2)
        assert v["regressed"]
        # and must NOT trip without the injection
        v = mod._regress_compare(10.0, 10.0, "ms/query", 15.0)
        assert not v["regressed"]

    def test_parity_loss_always_gates(self):
        """Losing result-set parity on the fresh run fails the gate even
        at unchanged speed, and even on a config whose baseline had no
        parity referee (a wrong answer is worse than a slow one)."""
        mod = _bench_mod()
        b = {"value": 10.0, "unit": "ms/query", "parity": True}
        v = mod._regress_verdict(b, {"value": 10.0, "parity": False}, 15.0)
        assert v["regressed"] and v["gating"] and v["parity_failure"]
        b_noref = {"value": 10.0, "unit": "ms/query", "parity": None}
        v = mod._regress_verdict(b_noref, {"value": 10.0, "parity": False},
                                 15.0)
        assert v["regressed"] and v["gating"]
        # speed noise on a no-referee config reports but does not gate
        v = mod._regress_verdict(b_noref, {"value": 20.0, "parity": None},
                                 15.0)
        assert v["regressed"] and not v["gating"]
        # the ordinary case: parity config, speed regression, gates
        v = mod._regress_verdict(b, {"value": 20.0, "parity": True}, 15.0)
        assert v["regressed"] and v["gating"] and "parity_failure" not in v

    def test_baseline_loader_accepts_all_three_shapes(self, tmp_path):
        mod = _bench_mod()
        # 1. a --regress-capture file
        cap = tmp_path / "cap.json"
        cap.write_text(json.dumps({
            "kind": "bench-regress-baseline",
            "configs": {"2": {"value": 5.0, "unit": "ms/query",
                              "parity": True}},
        }))
        base = mod._load_regress_baseline(str(cap))
        assert base["2"] == {"value": 5.0, "unit": "ms/query", "parity": True}
        # 2. a BENCH_DETAIL.json sweep record (parity from detail flags)
        det = tmp_path / "detail.json"
        det.write_text(json.dumps({
            "backend": "tpu",
            "configs": {
                "2": {"value": 5.4, "unit": "ms/query",
                      "detail": {"int_domain_parity": True,
                                 "row_set_parity": True}},
                "8": {"value": None, "unit": "error"},
            },
        }))
        base = mod._load_regress_baseline(str(det))
        assert base["2"]["parity"] is True
        assert "8" not in base  # value-less configs never become baselines
        # 3. a --regress-report file: measured values become the baseline
        rep = tmp_path / "report.json"
        rep.write_text(json.dumps({
            "kind": "bench-regress-report",
            "configs": {"2": {"baseline": 5.0, "measured": 5.5,
                              "unit": "ms/query", "parity": True}},
        }))
        base = mod._load_regress_baseline(str(rep))
        assert base["2"]["value"] == 5.5

    def test_committed_detail_loads_as_baseline(self):
        """The committed real-chip sweep record must stay loadable — the
        production gate is `bench.py --regress BENCH_DETAIL.json`."""
        mod = _bench_mod()
        base = mod._load_regress_baseline("BENCH_DETAIL.json")
        assert base, "BENCH_DETAIL.json yielded no baseline configs"
        for cfg, b in base.items():
            assert b["value"] is not None and b["unit"], cfg


def test_driver_line_compact_and_parseable(tmp_path):
    """Driver-mode emission contract: the LAST stdout line parses as JSON,
    stays under the driver's ~4 KB tail capture, and carries per-config
    summaries (r02's parsed was null purely from overflow)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod2", "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # a worst-case configs dict: 8 configs with max-width fields + errors
    configs = {}
    for i in range(1, 9):
        configs[str(i)] = {
            "metric": "m" * 40, "value": 123.4567, "unit": "u" * 30,
            "vs_baseline": 99999.99,
            "error": "x" * 500,
            "detail": {"n_points": 10**9, "int_domain_parity": True,
                       "row_set_parity": True, "blob": "y" * 2000},
        }
    compact = {k: mod._compact(r) for k, r in configs.items()}
    line = json.dumps({
        "metric": "m" * 60, "value": 1.0, "unit": "ms/query",
        "vs_baseline": 12.3,
        "detail": {"backend": "tpu", "devices": 8, "configs_ok": 8,
                   "configs_total": 8, "configs": compact,
                   "full_detail": "BENCH_DETAIL.json"},
    })
    assert len(line) < 3500, len(line)
    parsed = json.loads(line)
    assert parsed["detail"]["configs"]["1"]["parity"] is True
    # errors truncate, parity flags AND together
    assert len(parsed["detail"]["configs"]["1"]["error"]) <= 120
    bad = dict(configs["2"])
    bad["detail"] = {"int_domain_parity": True, "row_set_parity": False}
    assert mod._compact(bad)["parity"] is False
