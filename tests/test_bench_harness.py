"""Bench harness regressions: the official record must never read
parity-false for HARNESS reasons (VERDICT r2 weak #2 — `iso()` truncated
query windows to whole seconds while the f64 referee used exact
milliseconds, so one sub-second-boundary row went "missing")."""

import json
import subprocess
import sys

import numpy as np
import pytest

import geomesa_tpu  # noqa: F401
from geomesa_tpu.filter.cql import parse as parse_cql
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import Column, FeatureTable, point_column
from geomesa_tpu.schema.sft import AttributeType, parse_spec
from geomesa_tpu.store.datastore import DataStore


def _iso_ms(ms: int) -> str:
    """The bench's millisecond-precision ISO formatter, reproduced here so a
    drift in either copy fails the parity sweep below."""
    import datetime

    dt = datetime.datetime.fromtimestamp(ms / 1000, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{int(ms) % 1000:03d}Z"


def test_bench_iso_matches_local():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", "bench.py")
    # bench.py imports jax at module load; the conftest already pinned cpu.
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # grab the closure-free equivalent by calling bench_select's inner iso
    # indirectly: format a few stamps both ways through the CQL parser
    for ms in (1_499_481_020_001, 1_500_000_000_999, 1_500_000_000_000):
        ast = parse_cql(f"dtg DURING {_iso_ms(ms)}/{_iso_ms(ms + 86_400_000)}")
        assert ast.lo_millis == ms and ast.hi_millis == ms + 86_400_000


class TestSubSecondBoundaryParity:
    """Row-set parity between DataStore CQL select and the exact-ms f64
    referee, fuzzing timestamps ONTO window boundaries at ms offsets."""

    def _parity_sweep(self, seed: int):
        rng = np.random.default_rng(seed)
        n = 4_000
        t0 = 1_499_000_000_000
        span = 10 * 86_400_000
        t = t0 + rng.integers(0, span, n)
        # windows with sub-second endpoints, then rows planted EXACTLY on
        # and ±1 ms around both endpoints (the r02 failure was one row at
        # t=...020001 just inside a truncated window edge)
        windows = []
        for _ in range(8):
            lo = int(t0 + rng.integers(0, span - 86_400_000))
            hi = lo + int(rng.integers(3_600_000, 86_400_000))
            windows.append((lo, hi))
        planted = []
        for lo, hi in windows:
            planted += [lo - 1, lo, lo + 1, hi - 1, hi, hi + 1]
        t = np.concatenate([t, np.array(planted, dtype=np.int64)])
        n = len(t)
        lon = rng.uniform(-60, 60, n)
        lat = rng.uniform(-30, 30, n)

        sft = parse_spec("evt", "dtg:Date,*geom:Point")
        table = FeatureTable.from_columns(
            sft,
            np.arange(n).astype(str).astype(object),
            {"dtg": Column(AttributeType.DATE, t.astype(np.int64)),
             "geom": point_column(lon, lat)},
        )
        for backend in ("oracle", "tpu"):
            ds = DataStore(backend=backend)
            ds.create_schema(sft)
            ds.write("evt", table)
            ds.compact("evt")
            for lo, hi in windows:
                cql = (
                    f"BBOX(geom, -50, -25, 50, 25) AND "
                    f"dtg DURING {_iso_ms(lo)}/{_iso_ms(hi)}"
                )
                got = set(ds.query("evt", cql).table.fids.tolist())
                # referee: exact-ms f64 semantics (DURING is exclusive)
                m = (
                    (lon >= -50) & (lon <= 50) & (lat >= -25) & (lat <= 25)
                    & (t > lo) & (t < hi)
                )
                want = set(np.nonzero(m)[0].astype(str).tolist())
                assert got == want, (
                    backend, lo, hi,
                    sorted(want - got)[:3], sorted(got - want)[:3],
                )

    def test_boundary_rows_fuzz(self):
        for seed in (0, 1, 2):
            self._parity_sweep(seed)


def test_driver_line_compact_and_parseable(tmp_path):
    """Driver-mode emission contract: the LAST stdout line parses as JSON,
    stays under the driver's ~4 KB tail capture, and carries per-config
    summaries (r02's parsed was null purely from overflow)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod2", "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # a worst-case configs dict: 8 configs with max-width fields + errors
    configs = {}
    for i in range(1, 9):
        configs[str(i)] = {
            "metric": "m" * 40, "value": 123.4567, "unit": "u" * 30,
            "vs_baseline": 99999.99,
            "error": "x" * 500,
            "detail": {"n_points": 10**9, "int_domain_parity": True,
                       "row_set_parity": True, "blob": "y" * 2000},
        }
    compact = {k: mod._compact(r) for k, r in configs.items()}
    line = json.dumps({
        "metric": "m" * 60, "value": 1.0, "unit": "ms/query",
        "vs_baseline": 12.3,
        "detail": {"backend": "tpu", "devices": 8, "configs_ok": 8,
                   "configs_total": 8, "configs": compact,
                   "full_detail": "BENCH_DETAIL.json"},
    })
    assert len(line) < 3500, len(line)
    parsed = json.loads(line)
    assert parsed["detail"]["configs"]["1"]["parity"] is True
    # errors truncate, parity flags AND together
    assert len(parsed["detail"]["configs"]["1"]["error"]) <= 120
    bad = dict(configs["2"])
    bad["detail"] = {"int_domain_parity": True, "row_set_parity": False}
    assert mod._compact(bad)["parity"] is False
