"""Density rasterization of extended geometries (RenderingGrid role —
SURVEY.md §2.3/§2.18): lines spread along their path, polygons fill."""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import LineString, Point, Polygon
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.store.reduce import density_grid

BBOX = (0.0, 0.0, 16.0, 16.0)
OPTS = {"bbox": BBOX, "width": 16, "height": 16}


def _table(geoms):
    sft = parse_spec("d", "name:String,*geom:Geometry")
    return FeatureTable.from_records(
        sft, [{"name": f"g{i}", "geom": g} for i, g in enumerate(geoms)]
    )


class TestRaster:
    def test_line_spreads_along_path(self):
        # horizontal line across the middle: one row of cells gets the mass
        t = _table([LineString([[0.5, 8.5], [15.5, 8.5]])])
        g = density_grid(t, OPTS)
        assert g.sum() == pytest.approx(1.0)  # mass conserved
        assert np.count_nonzero(g[8, :]) == 16
        assert np.count_nonzero(g) == 16  # only that row touched

    def test_diagonal_line(self):
        t = _table([LineString([[0.1, 0.1], [15.9, 15.9]])])
        g = density_grid(t, OPTS)
        assert g.sum() == pytest.approx(1.0)
        assert all(g[i, i] > 0 for i in range(16))  # the diagonal is covered

    def test_polygon_fills(self):
        t = _table([Polygon([[2, 2], [10, 2], [10, 10], [2, 10]])])
        g = density_grid(t, OPTS)
        assert g.sum() == pytest.approx(1.0)
        assert np.count_nonzero(g[2:10, 2:10]) == 64
        assert g[0, 0] == 0 and g[12, 12] == 0

    def test_mixed_points_and_lines(self):
        t = _table([
            Point(4.5, 4.5),
            LineString([[0.5, 1.5], [7.5, 1.5]]),
        ])
        g = density_grid(t, OPTS)
        assert g.sum() == pytest.approx(2.0)
        assert g[4, 4] == 1.0
        assert np.count_nonzero(g[1, :8]) == 8

    def test_thin_polygon_outline_fallback(self):
        # degenerate sliver missing every cell center still contributes mass
        t = _table([Polygon([[3.0, 3.01], [12.0, 3.01], [12.0, 3.02], [3.0, 3.02]])])
        g = density_grid(t, OPTS)
        assert g.sum() == pytest.approx(1.0)

    def test_store_density_hint_with_lines(self):
        ds = DataStore(backend="oracle")
        ds.create_schema("lines", "name:String,*geom:LineString")
        ds.write("lines", [
            {"name": "a", "geom": LineString([[1, 1], [14, 1]])},
            {"name": "b", "geom": LineString([[1, 5], [14, 5]])},
        ])
        r = ds.query("lines", Query(hints={"density": OPTS}))
        assert r.density.sum() == pytest.approx(2.0)
        assert np.count_nonzero(r.density[1, :]) > 10
