"""OGC WFS 2.0 KVP endpoint (the GeoServer-plugin protocol role —
VERDICT r2 missing #4; ``geomesa-accumulo-gs-plugin`` reference)."""

import xml.etree.ElementTree as ET

import numpy as np
import pytest

import geomesa_tpu  # noqa: F401
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore
from geomesa_tpu.web.app import GeoMesaApp


def _store():
    ds = DataStore(backend="oracle")
    ds.create_schema(parse_spec("evt", "name:String,dtg:Date,*geom:Point"))
    rng = np.random.default_rng(6)
    n = 200
    lon = rng.uniform(-50, 50, n)
    lat = rng.uniform(-50, 50, n)
    ds.write(
        "evt",
        [{"name": f"n{i}", "dtg": 1_600_000_000_000 + i,
          "geom": Point(float(lon[i]), float(lat[i]))} for i in range(n)],
        fids=[str(i) for i in range(n)],
    )
    return ds, lon, lat


class TestWfs:
    def setup_method(self):
        self.ds, self.lon, self.lat = _store()
        self.app = GeoMesaApp(self.ds)

    def _call(self, **params):
        return self.app._wfs({"service": "WFS", **params}, None)

    def test_get_capabilities(self):
        status, body, ctype = self._call(request="GetCapabilities")
        assert status == 200 and ctype == "text/xml"
        root = ET.fromstring(body)
        assert root.tag.endswith("WFS_Capabilities")
        names = [e.text for e in root.iter() if e.tag == "Name"]
        assert "evt" in names

    def test_describe_feature_type(self):
        status, body, _ = self._call(
            request="DescribeFeatureType", typeNames="evt"
        )
        assert status == 200
        root = ET.fromstring(body)
        elems = {
            e.get("name"): e.get("type")
            for e in root.iter()
            if e.tag.endswith("element") and e.get("name")
        }
        assert elems["geom"] == "gml:PointPropertyType"
        assert elems["dtg"] == "xsd:dateTime"
        assert elems["name"] == "xsd:string"

    def test_get_feature_gml_bbox(self):
        status, body, ctype = self._call(
            request="GetFeature", typeNames="evt", bbox="-10,-10,10,10"
        )
        assert status == 200 and ctype == "application/gml+xml"
        root = ET.fromstring(body)
        want = int(
            ((self.lon >= -10) & (self.lon <= 10)
             & (self.lat >= -10) & (self.lat <= 10)).sum()
        )
        members = [e for e in root.iter() if e.tag.endswith("featureMember")]
        assert len(members) == want

    def test_get_feature_json_and_cql(self):
        status, body, ctype = self._call(
            request="GetFeature", typeNames="evt",
            cql_filter="BBOX(geom, 0, 0, 50, 50) AND name = 'n3'",
            outputFormat="application/json",
        )
        assert status == 200 and ctype == "application/geo+json"
        feats = body["features"] if isinstance(body, dict) else None
        assert feats is not None
        assert all(f["properties"]["name"] == "n3" for f in feats)

    def test_result_type_hits(self):
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt",
            bbox="-10,-10,10,10", resultType="hits",
        )
        want = int(
            ((self.lon >= -10) & (self.lon <= 10)
             & (self.lat >= -10) & (self.lat <= 10)).sum()
        )
        root = ET.fromstring(body)
        assert root.get("numberMatched") == str(want)
        assert root.get("numberReturned") == "0"

    def test_paging_count_start_index(self):
        s1, b1, _ = self._call(
            request="GetFeature", typeNames="evt", count="5",
            sortBy="name", outputFormat="application/json",
        )
        s2, b2, _ = self._call(
            request="GetFeature", typeNames="evt", count="5",
            startIndex="5", sortBy="name", outputFormat="application/json",
        )
        page1 = [f["id"] for f in b1["features"]]
        page2 = [f["id"] for f in b2["features"]]
        assert len(page1) == 5 and len(page2) == 5
        assert not set(page1) & set(page2)

    def test_feature_id_lookup(self):
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt", featureID="7,9",
            outputFormat="application/json",
        )
        assert sorted(f["id"] for f in body["features"]) == ["7", "9"]

    def test_hits_reports_total_not_page(self):
        # WFS 2.0: numberMatched is the TOTAL match count; paging params
        # must not shrink it
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt", resultType="hits",
            count="3", startIndex="10",
        )
        root = ET.fromstring(body)
        assert root.get("numberMatched") == "200"

    def test_sortby_standard_forms(self):
        for spec in ("dtg DESC", "dtg+DESC", "dtg D"):
            _, body, _ = self._call(
                request="GetFeature", typeNames="evt", count="3",
                sortBy=spec, outputFormat="application/json",
            )
            dtgs = [f["properties"]["dtg"] for f in body["features"]]
            assert dtgs == sorted(dtgs, reverse=True), spec
        _, body, _ = self._call(
            request="GetFeature", typeNames="evt", count="3",
            sortBy="dtg ASC", outputFormat="application/json",
        )
        dtgs = [f["properties"]["dtg"] for f in body["features"]]
        assert dtgs == sorted(dtgs)

    def test_capabilities_hide_bounds_from_restricted_callers(self):
        sft = parse_spec("cap", "name:String,vis:String,dtg:Date,*geom:Point")
        sft.user_data["geomesa.vis.field"] = "vis"
        self.ds.create_schema(sft)
        self.ds.write(
            "cap",
            [{"name": "open", "vis": "", "dtg": 1, "geom": Point(1, 1)},
             {"name": "secret", "vis": "classified", "dtg": 2,
              "geom": Point(150.0, 80.0)}],
            fids=["a", "b"],
        )
        # restricted caller: bounds must NOT reveal the classified location
        _, body, _ = self.app._wfs(
            {"service": "WFS", "request": "GetCapabilities",
             "__auths__": []}, None,
        )
        text = body.decode()
        seg = text.split("<Name>cap</Name>")[1]
        assert "150" not in seg.split("</FeatureType>")[0]

    def test_errors_are_exception_reports(self):
        status, body, ctype = self._call(request="Nope")
        assert status == 400 and ctype == "text/xml"
        root = ET.fromstring(body)
        assert root.tag.endswith("ExceptionReport")
        status, body, _ = self._call(request="GetFeature")  # no typeNames
        assert status == 400
        assert b"MissingParameterValue" in body
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt", bbox="1,2,3"
        )
        assert status == 400
        # malformed paging params are protocol errors, not JSON 400s
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt", count="abc"
        )
        assert status == 400 and b"ExceptionReport" in body
        # malformed CQL is a protocol error too
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt", cql_filter="BBOX(geom,"
        )
        assert status == 400 and b"ExceptionReport" in body
        # an unsupported outputFormat must error, never silently serve GML
        status, body, _ = self._call(
            request="GetFeature", typeNames="evt", outputFormat="shape-zip"
        )
        assert status == 400 and b"InvalidParameterValue" in body

    def test_visibility_auths_enforced(self):
        sft = parse_spec("sec", "name:String,vis:String,dtg:Date,*geom:Point")
        sft.user_data["geomesa.vis.field"] = "vis"
        self.ds.create_schema(sft)
        self.ds.write(
            "sec",
            [{"name": "open", "vis": "", "dtg": 1, "geom": Point(0, 0)},
             {"name": "secret", "vis": "classified", "dtg": 2,
              "geom": Point(1, 1)}],
            fids=["a", "b"],
        )
        status, body, _ = self.app._wfs(
            {"service": "WFS", "request": "GetFeature", "typeNames": "sec",
             "outputFormat": "application/json", "__auths__": []},
            None,
        )
        names = {f["properties"]["name"] for f in body["features"]}
        assert names == {"open"}
