"""Regressions for the round-5 advisor findings (ADVICE.md r5).

Each test pins a specific fixed defect:
- multi-join WHERE conjuncts on a LEFT-JOIN alias must evaluate AFTER the
  join (pushdown silently kept failing matches as NULL-extended rows)
- a LEFT-joined EMPTY table must NULL-extend, not IndexError on slot 0
- RemoteDataStore.select_many must fail closed on mixed per-query auths
  (one header used to silently cover the whole batch, last query wins)
- select_many_positions' query-batch bucket must divide the mesh query
  axis (a pure power-of-two bucket broke query_parallel meshes)
"""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.sql import sql
from geomesa_tpu.store.datastore import DataStore


@pytest.fixture(scope="module")
def lj_ds():
    store = DataStore(backend="tpu")
    store.create_schema("ord", "cust:String,amount:Double,*geom:Point")
    orecs = [
        {"cust": c, "amount": float(a), "geom": Point(float(i), 0.0)}
        for i, (c, a) in enumerate([
            ("c0", 10.0), ("c0", 20.0), ("c1", 30.0), ("c2", 40.0),
            ("cX", 50.0),   # no matching customer: NULL-extended
            (None, 60.0),   # NULL key: never matches
        ])
    ]
    store.write("ord", orecs, fids=[f"o{i}" for i in range(len(orecs))])
    store.create_schema("cust", "cid:String,tier:Integer,*geom:Point")
    crecs = [
        {"cid": f"c{k}", "tier": k, "geom": Point(float(k), 0.0)}
        for k in range(3)
    ]
    store.write("cust", crecs, fids=[f"c{k}" for k in range(3)])
    store.create_schema("nobody", "cid:String,tier:Integer,*geom:Point")
    return store


class TestLeftJoinWherePostJoin:
    def test_where_on_left_alias_applies_after_join(self, lj_ds):
        """WHERE b.tier = 0 after LEFT JOIN: keeps only rows whose MATCHED
        customer has tier 0; NULL-extended rows and other tiers drop
        (pushdown used to keep cX/None rows as NULL-extended survivors)."""
        res = sql(lj_ds,
                  "SELECT a.cust, a.amount, b.tier FROM ord a "
                  "LEFT JOIN cust b ON a.cust = b.cid WHERE b.tier = 0")
        rows = sorted(zip(res.columns["a.cust"], res.columns["a.amount"]))
        assert rows == [("c0", 10.0), ("c0", 20.0)]
        assert all(int(v) == 0 for v in res.columns["b.tier"])

    def test_where_is_null_keeps_only_unmatched(self, lj_ds):
        """The anti-join spelling: IS NULL on the left alias's key keeps
        exactly the NULL-extended rows."""
        res = sql(lj_ds,
                  "SELECT a.cust, b.tier FROM ord a "
                  "LEFT JOIN cust b ON a.cust = b.cid WHERE b.cid IS NULL")
        got = list(res.columns["a.cust"])
        assert len(got) == 2 and None in got and "cX" in got
        assert all(v is None for v in res.columns["b.tier"])

    def test_inner_alias_where_still_pushes_down(self, lj_ds):
        """Conjuncts on the base/inner aliases keep their scan pushdown."""
        res = sql(lj_ds,
                  "SELECT a.cust, a.amount, b.tier FROM ord a "
                  "LEFT JOIN cust b ON a.cust = b.cid WHERE a.amount > 35")
        rows = sorted(zip(res.columns["a.cust"],
                          res.columns["a.amount"],
                          res.columns["b.tier"]),
                      key=lambda r: r[1])
        assert rows == [("c2", 40.0, 2), ("cX", 50.0, None),
                        (None, 60.0, None)]

    def test_left_join_empty_table_null_extends(self, lj_ds):
        """LEFT JOIN against a 0-row table: every bound row survives
        NULL-extended (used to IndexError indexing slot 0 of an empty
        column)."""
        res = sql(lj_ds,
                  "SELECT a.cust, b.tier FROM ord a "
                  "LEFT JOIN nobody b ON a.cust = b.cid")
        assert len(res) == 6
        assert all(v is None for v in res.columns["b.tier"])

    def test_where_on_empty_left_table_drops_all(self, lj_ds):
        res = sql(lj_ds,
                  "SELECT a.cust FROM ord a "
                  "LEFT JOIN nobody b ON a.cust = b.cid WHERE b.tier = 1")
        assert len(res) == 0


class TestRemoteSelectManyAuths:
    def _remote(self, header="X-Geomesa-Auths"):
        from geomesa_tpu.store.remote import RemoteDataStore

        remote = RemoteDataStore("http://unused.invalid",
                                 forward_auths_header=header)
        remote._schemas["ev"] = parse_spec("ev", "name:String,*geom:Point")
        return remote

    def test_mixed_auths_fail_closed(self):
        remote = self._remote()
        with pytest.raises(PermissionError, match="different auths"):
            remote.select_many(
                "ev", [Query(auths=("A",)), Query(auths=("B",))])

    def test_auths_mixed_with_unscoped_fail_closed(self):
        remote = self._remote()
        with pytest.raises(PermissionError, match="different auths"):
            remote.select_many("ev", [Query(auths=("A",)), "INCLUDE"])

    def test_auths_without_forward_header_fail_closed(self):
        remote = self._remote(header=None)
        with pytest.raises(PermissionError, match="forward_auths_header"):
            remote.select_many("ev", [Query(auths=("A",))])

    def test_same_scope_different_order_accepted(self):
        """auths are a set of labels: ('a','b') and ('b','a') are one
        scope, not a mixed batch."""
        remote = self._remote()
        seen = {}

        def fake_send(method, path, body=None, params=None, headers=None, **kw):
            seen["headers"] = headers
            return {"results": []}

        remote._send = fake_send
        remote.select_many(
            "ev", [Query(auths=("a", "b")), Query(auths=("b", "a"))])
        assert seen["headers"] == {"X-Geomesa-Auths": "a,b"}

    def test_uniform_auths_forward_one_header(self):
        remote = self._remote()
        seen = {}

        def fake_send(method, path, body=None, params=None, headers=None, **kw):
            seen["headers"] = headers
            return {"results": []}

        remote._send = fake_send
        out = remote.select_many(
            "ev", [Query(auths=("A", "B")), Query(auths=("A", "B"))])
        assert out == []
        assert seen["headers"] == {"X-Geomesa-Auths": "A,B"}

    def test_all_unscoped_sends_no_header(self):
        remote = self._remote(header=None)
        seen = {}

        def fake_send(method, path, body=None, params=None, headers=None, **kw):
            seen["headers"] = headers
            return {"results": []}

        remote._send = fake_send
        remote.select_many("ev", ["INCLUDE", None])
        assert seen["headers"] is None


class TestSelectManyQueryAxisPadding:
    def test_query_parallel_mesh_dispatches(self):
        """A query_parallel mesh whose axis exceeds the power-of-two bucket
        (1 query -> bucket 4, query axis 8) used to fail at dispatch; the
        bucket now rounds up to a multiple of the mesh query axis."""
        from geomesa_tpu.parallel.mesh import make_mesh
        from geomesa_tpu.store.backends import TpuBackend

        mesh = make_mesh(8, query_parallel=8)
        ds = DataStore(backend=TpuBackend(mesh=mesh))
        ds.create_schema("ev", "name:String,*geom:Point")
        rng = np.random.default_rng(3)
        n = 300
        lon = rng.uniform(-60, 60, n)
        lat = rng.uniform(-60, 60, n)
        ds.write(
            "ev",
            [{"name": f"p{i}", "geom": Point(float(lon[i]), float(lat[i]))}
             for i in range(n)],
            fids=[f"p{i}" for i in range(n)],
        )
        ds.compact("ev")
        [r] = ds.select_many("ev", ["BBOX(geom, -30, -30, 30, 30)"])
        want = set(np.nonzero(
            (lon > -30) & (lon < 30) & (lat > -30) & (lat < 30))[0])
        got = {int(f[1:]) for f in r.table.fids}
        assert got == want
