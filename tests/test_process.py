"""Geoprocess tests: KNN, unique, proximity, tube-select, point2point, joins
(reference: geomesa-process suites — SURVEY.md §2.15/§4)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point, Polygon, box, from_wkt
from geomesa_tpu.geometry import predicates as P
from geomesa_tpu.process.join import join_within, join_within_device
from geomesa_tpu.process.knn import knn
from geomesa_tpu.process.processes import point2point, proximity, tube_select, unique
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000
SPEC = "name:String,dtg:Date,*geom:Point"


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(21)
    n = 5000
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-60, 60, n)
    t = T0 + rng.integers(0, 10 * 86_400_000, n)
    recs = [
        {"name": f"trk{i % 12}", "dtg": int(t[i]), "geom": Point(float(lon[i]), float(lat[i]))}
        for i in range(n)
    ]
    store = DataStore(backend="tpu")
    store.create_schema("p", SPEC)
    store.write("p", recs, fids=[f"p.{i}" for i in range(n)])
    return store


class TestKNN:
    def test_knn_exact(self, ds):
        q = Point(10.0, 10.0)
        table, dists = knn(ds, "p", q, k=15)
        assert len(table) == 15
        # compare against brute force over everything
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        all_d = np.sqrt((col.x - q.x) ** 2 + (col.y - q.y) ** 2)
        expected = np.sort(all_d)[:15]
        np.testing.assert_allclose(np.sort(dists), expected)
        assert np.all(np.diff(dists) >= 0)

    def test_knn_with_filter(self, ds):
        table, _ = knn(ds, "p", Point(0.0, 0.0), k=5, filter="name = 'trk3'")
        assert len(table) == 5
        assert all(v == "trk3" for v in table.columns["name"].values)

    def test_knn_more_than_available(self, ds):
        table, _ = knn(ds, "p", Point(0.0, 0.0), k=3, filter="name = 'trk3' AND dtg BEFORE 2017-07-02T00:00:00Z")
        # may be fewer matches than k in total; returns what exists
        r = ds.query("p", "name = 'trk3' AND dtg BEFORE 2017-07-02T00:00:00Z")
        assert len(table) == min(3, r.count)


class TestUnique:
    def test_unique_counts(self, ds):
        vals = unique(ds, "p", "name")
        assert len(vals) == 12
        assert sum(c for _, c in vals) == 5000

    def test_unique_filtered(self, ds):
        vals = unique(ds, "p", "name", filter="BBOX(geom, 0, 0, 30, 30)")
        total = ds.query("p", "BBOX(geom, 0, 0, 30, 30)").count
        assert sum(c for _, c in vals) == total


class TestProximity:
    def test_proximity(self, ds):
        t = proximity(ds, "p", [Point(5.0, 5.0)], 3.0)
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        d = np.sqrt((col.x - 5) ** 2 + (col.y - 5) ** 2)
        assert len(t) == int((d <= 3.0).sum())


class TestTube:
    def test_tube_select(self, ds):
        track = [
            (-30.0, -30.0, T0 + 1 * 86_400_000),
            (0.0, 0.0, T0 + 3 * 86_400_000),
            (30.0, 30.0, T0 + 5 * 86_400_000),
        ]
        t = tube_select(ds, "p", track, buffer_deg=2.0, time_buffer_ms=86_400_000)
        # every result is within 2 deg of the path and inside the time corridor
        col = t.geom_column()
        pts = np.asarray([(x, y) for x, y, _ in track])
        from geomesa_tpu.geometry.types import LineString

        path = LineString(pts)
        d = np.sqrt(P.points_dist2_geom(col.x, col.y, path))
        assert len(t) > 0
        assert np.all(d <= 2.0 + 1e-9)
        ts = t.dtg_millis()
        assert ts.min() >= T0
        assert ts.max() <= T0 + 6 * 86_400_000

    def test_point2point(self, ds):
        r = ds.query("p", "name = 'trk5'")
        tracks = point2point(r.table, "dtg", "name")
        assert "trk5" in tracks
        line = tracks["trk5"]
        assert len(line.coords) == r.count


class TestJoin:
    POLYS = [
        box(0, 0, 20, 20),
        box(-50, -50, -30, -30),
        from_wkt("POLYGON ((30 30, 50 30, 50 50, 30 50, 30 30))"),
        box(100, 100, 110, 110),  # empty (outside data range)
    ]

    def test_join_exact(self, ds):
        out = join_within(ds, "p", self.POLYS)
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        for i, fids in out:
            expected = P.points_within_geom(col.x, col.y, self.POLYS[i]).sum()
            assert len(fids) == expected, f"polygon {i}"
        assert len(out[3][1]) == 0

    def test_join_device_matches_exact(self, ds):
        exact = join_within(ds, "p", self.POLYS)
        counts = join_within_device(ds, "p", self.POLYS)
        for (i, fids), c in zip(exact, counts):
            assert len(fids) == c, f"polygon {i}"  # data is far from edges (uniform random)

    def test_join_device_scales_vertices(self, ds):
        # a polygon with many vertices (circle approximation)
        theta = np.linspace(0, 2 * np.pi, 33)
        ring = np.stack([10 + 5 * np.cos(theta), 10 + 5 * np.sin(theta)], axis=1)
        poly = Polygon(ring)
        counts = join_within_device(ds, "p", [poly])
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        expected = int(P.points_within_geom(col.x, col.y, poly).sum())
        assert abs(int(counts[0]) - expected) <= 2  # f32 edge tolerance
