"""Geoprocess tests: KNN, unique, proximity, tube-select, point2point, joins
(reference: geomesa-process suites — SURVEY.md §2.15/§4)."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point, Polygon, box, from_wkt
from geomesa_tpu.geometry import predicates as P
from geomesa_tpu.process.join import join_within, join_within_device
from geomesa_tpu.process.knn import knn
from geomesa_tpu.process.processes import point2point, proximity, tube_select, unique
from geomesa_tpu.store.datastore import DataStore

T0 = 1_498_867_200_000
SPEC = "name:String,dtg:Date,*geom:Point"


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(21)
    n = 5000
    lon = rng.uniform(-60, 60, n)
    lat = rng.uniform(-60, 60, n)
    t = T0 + rng.integers(0, 10 * 86_400_000, n)
    recs = [
        {"name": f"trk{i % 12}", "dtg": int(t[i]), "geom": Point(float(lon[i]), float(lat[i]))}
        for i in range(n)
    ]
    store = DataStore(backend="tpu")
    store.create_schema("p", SPEC)
    store.write("p", recs, fids=[f"p.{i}" for i in range(n)])
    return store


class TestKNN:
    def test_knn_exact(self, ds):
        q = Point(10.0, 10.0)
        table, dists = knn(ds, "p", q, k=15)
        assert len(table) == 15
        # compare against brute force over everything
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        all_d = np.sqrt((col.x - q.x) ** 2 + (col.y - q.y) ** 2)
        expected = np.sort(all_d)[:15]
        np.testing.assert_allclose(np.sort(dists), expected)
        assert np.all(np.diff(dists) >= 0)

    def test_knn_with_filter(self, ds):
        table, _ = knn(ds, "p", Point(0.0, 0.0), k=5, filter="name = 'trk3'")
        assert len(table) == 5
        assert all(v == "trk3" for v in table.columns["name"].values)

    def test_knn_more_than_available(self, ds):
        table, _ = knn(ds, "p", Point(0.0, 0.0), k=3, filter="name = 'trk3' AND dtg BEFORE 2017-07-02T00:00:00Z")
        # may be fewer matches than k in total; returns what exists
        r = ds.query("p", "name = 'trk3' AND dtg BEFORE 2017-07-02T00:00:00Z")
        assert len(table) == min(3, r.count)


class TestUnique:
    def test_unique_counts(self, ds):
        vals = unique(ds, "p", "name")
        assert len(vals) == 12
        assert sum(c for _, c in vals) == 5000

    def test_unique_filtered(self, ds):
        vals = unique(ds, "p", "name", filter="BBOX(geom, 0, 0, 30, 30)")
        total = ds.query("p", "BBOX(geom, 0, 0, 30, 30)").count
        assert sum(c for _, c in vals) == total


class TestProximity:
    def test_proximity(self, ds):
        t = proximity(ds, "p", [Point(5.0, 5.0)], 3.0)
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        d = np.sqrt((col.x - 5) ** 2 + (col.y - 5) ** 2)
        assert len(t) == int((d <= 3.0).sum())


class TestTube:
    def test_tube_select(self, ds):
        track = [
            (-30.0, -30.0, T0 + 1 * 86_400_000),
            (0.0, 0.0, T0 + 3 * 86_400_000),
            (30.0, 30.0, T0 + 5 * 86_400_000),
        ]
        t = tube_select(ds, "p", track, buffer_deg=2.0, time_buffer_ms=86_400_000)
        # every result is within 2 deg of the path and inside the time corridor
        col = t.geom_column()
        pts = np.asarray([(x, y) for x, y, _ in track])
        from geomesa_tpu.geometry.types import LineString

        path = LineString(pts)
        d = np.sqrt(P.points_dist2_geom(col.x, col.y, path))
        assert len(t) > 0
        assert np.all(d <= 2.0 + 1e-9)
        ts = t.dtg_millis()
        assert ts.min() >= T0
        assert ts.max() <= T0 + 6 * 86_400_000

    def test_point2point(self, ds):
        r = ds.query("p", "name = 'trk5'")
        tracks = point2point(r.table, "dtg", "name")
        assert "trk5" in tracks
        line = tracks["trk5"]
        assert len(line.coords) == r.count


class TestJoin:
    POLYS = [
        box(0, 0, 20, 20),
        box(-50, -50, -30, -30),
        from_wkt("POLYGON ((30 30, 50 30, 50 50, 30 50, 30 30))"),
        box(100, 100, 110, 110),  # empty (outside data range)
    ]

    def test_join_exact(self, ds):
        out = join_within(ds, "p", self.POLYS)
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        for i, fids in out:
            expected = P.points_within_geom(col.x, col.y, self.POLYS[i]).sum()
            assert len(fids) == expected, f"polygon {i}"
        assert len(out[3][1]) == 0

    def test_join_device_matches_exact(self, ds):
        exact = join_within(ds, "p", self.POLYS)
        counts = join_within_device(ds, "p", self.POLYS)
        for (i, fids), c in zip(exact, counts):
            assert len(fids) == c, f"polygon {i}"  # data is far from edges (uniform random)

    def test_join_device_scales_vertices(self, ds):
        # a polygon with many vertices (circle approximation)
        theta = np.linspace(0, 2 * np.pi, 33)
        ring = np.stack([10 + 5 * np.cos(theta), 10 + 5 * np.sin(theta)], axis=1)
        poly = Polygon(ring)
        counts = join_within_device(ds, "p", [poly])
        r = ds.query("p", "INCLUDE")
        col = r.table.geom_column()
        expected = int(P.points_within_geom(col.x, col.y, poly).sum())
        assert abs(int(counts[0]) - expected) <= 2  # f32 edge tolerance


class TestBatchedKnn:
    def test_knn_many_matches_f32_referee(self):
        import numpy as np

        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.process.knn import knn_many
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(21)
        n = 5000
        lon = rng.uniform(-120, 120, n)
        lat = rng.uniform(-60, 60, n)
        recs = [
            {"dtg": 1_500_000_000_000 + int(i), "geom": Point(float(lon[i]), float(lat[i]))}
            for i in range(n)
        ]
        ds = DataStore(backend="tpu")
        ds.create_schema("k", "dtg:Date,*geom:Point")
        ds.write("k", recs, fids=[str(i) for i in range(n)])
        pts = [Point(float(x), float(y))
               for x, y in rng.uniform(-50, 50, (5, 2))]
        res = knn_many(ds, "k", pts, k=7)
        assert len(res) == 5
        # referee in the SAME f32 int-rounded coordinate math as the kernel
        from geomesa_tpu.curve.normalize import lat as nlat, lon as nlon

        xi = nlon(31).normalize(lon).astype(np.int32)
        yi = nlat(31).normalize(lat).astype(np.int32)
        xf = xi.astype(np.float32) * np.float32(360.0 / 2**31) - np.float32(180.0)
        yf = yi.astype(np.float32) * np.float32(180.0 / 2**31) - np.float32(90.0)
        for qi, p in enumerate(pts):
            d2 = (xf - np.float32(p.x)) ** 2 + (yf - np.float32(p.y)) ** 2
            best = np.sort(d2)[:7].astype(np.float64)
            got, dist = res[qi]
            assert len(got) == 7
            # device math uses f32 FMA: ~1e-5 relative drift vs numpy f32
            np.testing.assert_allclose(dist**2, best, rtol=1e-4)
            # fids are the true nearest set (allow ties at the k-th distance)
            kth = best[-1]
            must = set(np.nonzero(d2 < kth * (1 - 1e-4))[0].astype(str))
            assert must.issubset(set(got.fids.tolist()))

    def test_knn_many_live_store_delta_merge(self):
        """VERDICT r2 item 5: pending hot-tier writes must NOT drop the
        batched device path — delta candidates merge into the heaps and the
        result matches a full referee over main ∪ delta."""
        import numpy as np

        import geomesa_tpu.process.knn as knn_mod
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.process.knn import knn_many
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(33)
        n = 3000
        lon = rng.uniform(-120, 120, n)
        lat = rng.uniform(-60, 60, n)
        ds = DataStore(backend="tpu")
        ds.create_schema("kl", "dtg:Date,*geom:Point")
        ds.write(
            "kl",
            [{"dtg": 1_500_000_000_000 + i,
              "geom": Point(float(lon[i]), float(lat[i]))} for i in range(n)],
            fids=[str(i) for i in range(n)],
        )
        ds.compact("kl")
        # pending writes land VERY close to the query points, so the true
        # top-k MUST include them (a main-only answer would be wrong)
        pts = [Point(float(x), float(y))
               for x, y in rng.uniform(-50, 50, (4, 2))]
        extra = []
        for i, p in enumerate(pts):
            extra.append({"dtg": 1_500_000_500_000 + i,
                          "geom": Point(p.x + 1e-4, p.y + 1e-4)})
        ds.write("kl", extra, fids=[f"hot{i}" for i in range(len(extra))])
        assert ds._state("kl").delta.rows > 0, "delta unexpectedly compacted"

        # must NOT fall back to the per-point path
        orig = knn_mod.knn
        knn_mod.knn = lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("live store fell back to per-point knn")
        )
        try:
            res = knn_many(ds, "kl", pts, k=5)
        finally:
            knn_mod.knn = orig
        for qi, p in enumerate(pts):
            got, dist = res[qi]
            assert f"hot{qi}" in set(got.fids.tolist()), (qi, got.fids)
            assert len(got) == 5
            assert (np.diff(dist) >= 0).all()
            assert dist[0] <= 2e-4  # the planted neighbor ranks first

    def test_knn_many_live_store_ttl_mask(self):
        """TTL stores stay on the device path: expired rows are masked on
        device and never surface as neighbors."""
        import numpy as np

        import geomesa_tpu.process.knn as knn_mod
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.process.knn import knn_many
        from geomesa_tpu.schema.sft import parse_spec
        from geomesa_tpu.store.datastore import DataStore

        rng = np.random.default_rng(34)
        n = 2000
        t0 = 1_500_000_000_000
        sft = parse_spec("kt", "dtg:Date,*geom:Point")
        sft.user_data["geomesa.age.off"] = 3_600_000  # 1h TTL
        ds = DataStore(backend="tpu")
        ds.create_schema(sft)
        lon = rng.uniform(-100, 100, n)
        lat = rng.uniform(-50, 50, n)
        # half fresh, half expired; expired rows sit ON the query points so
        # an unmasked scan would rank them first
        recs = []
        q = Point(10.0, 10.0)
        for i in range(n):
            fresh = i % 2 == 0
            g = (Point(float(lon[i]), float(lat[i])) if fresh
                 else Point(q.x + 1e-5 * i, q.y))
            recs.append({"dtg": t0 if fresh else t0 - 7_200_000, "geom": g})
        ds.write("kt", recs, fids=[str(i) for i in range(n)])
        ds.compact("kt")

        orig = knn_mod.knn
        knn_mod.knn = lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("TTL store fell back to per-point knn")
        )
        try:
            res = knn_many(ds, "kt", [q], k=8, now_ms=t0 + 60_000)
        finally:
            knn_mod.knn = orig
        got, dist = res[0]
        expired = {str(i) for i in range(n) if i % 2 == 1}
        assert not (set(got.fids.tolist()) & expired), got.fids
        # parity with the query-path TTL semantics: same fresh nearest set
        xf = lon[0::2].astype(np.float32)
        yf = lat[0::2].astype(np.float32)
        d2 = (xf - np.float32(q.x)) ** 2 + (yf - np.float32(q.y)) ** 2
        kth = np.sort(d2)[7]
        must = {
            str(2 * j) for j in np.nonzero(d2 < kth * (1 - 1e-4))[0]
        }
        assert must.issubset(set(got.fids.tolist()))

    def test_knn_many_falls_back_on_oracle(self):
        import numpy as np

        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.process.knn import knn_many
        from geomesa_tpu.store.datastore import DataStore

        ds = DataStore(backend="oracle")
        ds.create_schema("k2", "dtg:Date,*geom:Point")
        recs = [{"dtg": i, "geom": Point(i * 0.1, 0.0)} for i in range(50)]
        ds.write("k2", recs, fids=[str(i) for i in range(50)])
        res = knn_many(ds, "k2", [Point(0.0, 0.0)], k=3)
        assert len(res) == 1 and len(res[0][0]) == 3
        assert set(res[0][0].fids.tolist()) == {"0", "1", "2"}


class TestBlockSparseJoin:
    """Index-pruned block-sparse ST_Within join == brute-force f32 kernel."""

    def test_block_join_matches_brute_force(self):
        import numpy as np
        import jax.numpy as jnp

        import geomesa_tpu  # noqa: F401
        from geomesa_tpu import native
        from geomesa_tpu.curve.sfc import Z2SFC
        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.ops.join import (
            make_block_join_step,
            pack_polygons,
            pack_polygons_bucketed,
            points_in_polygons_count,
            polygon_block_plan,
        )
        from geomesa_tpu.parallel.mesh import data_shards, make_mesh, shard_columns

        rng = np.random.default_rng(33)
        n = 40_000
        lon = np.concatenate([rng.normal(10, 5, n // 2), rng.uniform(-170, 170, n - n // 2)])
        lat = np.concatenate([rng.normal(20, 4, n // 2), rng.uniform(-80, 80, n - n // 2)])
        sfc = Z2SFC()
        z = sfc.index(lon, lat)
        perm = native.sort_u64(z)
        z_sorted = z[perm]
        xs = lon[perm].astype(np.float32)
        ys = lat[perm].astype(np.float32)

        polys = []
        for _ in range(23):  # odd count exercises padding
            cx, cy = rng.uniform(-20, 40), rng.uniform(0, 40)
            ang = np.sort(rng.uniform(0, 2 * np.pi, rng.integers(8, 90)))
            rad = rng.uniform(0.5, 1.0, len(ang))
            w, h = rng.uniform(1, 6, 2)
            ring = np.stack([cx + w * rad * np.cos(ang), cy + h * rad * np.sin(ang)], 1)
            polys.append(Polygon(ring))

        mesh = make_mesh()
        shards = data_shards(mesh)
        block = 512
        # pad rows so every shard is a whole number of blocks
        mult = shards * block
        pad_n = ((n + mult - 1) // mult) * mult
        padz = np.concatenate([z_sorted, np.full(pad_n - n, 2**63, np.uint64)])
        cols, padded, rows_per_shard = shard_columns(
            mesh, {"x": np.concatenate([xs, np.zeros(pad_n - n, np.float32)]),
                   "y": np.concatenate([ys, np.zeros(pad_n - n, np.float32)])}
        )
        assert rows_per_shard % block == 0

        step = make_block_join_step(mesh, block)
        total_expected = []
        for ids, verts, bbox, nverts in pack_polygons_bucketed(polys):
            blk, nblk = polygon_block_plan(
                padz, bbox.astype(np.float64), block, rows_per_shard, shards
            )
            counts = np.asarray(step(
                cols["x"], cols["y"], jnp.int32(n),
                jnp.asarray(blk), jnp.asarray(nblk),
                jnp.asarray(verts), jnp.asarray(bbox),
            ))
            # brute force with the identical f32 membership kernel
            vb, bb, _ = pack_polygons([polys[i] for i in ids],
                                      max_vertices=verts.shape[1])
            brute = np.asarray(points_in_polygons_count(
                jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(vb), jnp.asarray(bb)
            ))
            np.testing.assert_array_equal(counts, brute)
            total_expected.append(int(brute.sum()))
        assert sum(total_expected) > 100  # non-vacuous

    def test_bucketing_rejects_oversize(self):
        import numpy as np
        import pytest

        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.ops.join import pack_polygons_bucketed

        ang = np.linspace(0, 2 * np.pi, 600)
        ring = np.stack([np.cos(ang), np.sin(ang)], 1)
        with pytest.raises(ValueError, match="vertices"):
            pack_polygons_bucketed([Polygon(ring)])
