"""JSON-path attribute queries (KryoJsonSerialization role)."""

import numpy as np
import pytest

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.cql import CQLError, parse
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore


@pytest.fixture(scope="module")
def ds():
    store = DataStore()
    store.create_schema(parse_spec("ev", "props:String,*geom:Point"))
    rows = [
        {"props": '{"kind": "bus", "speed": 12.5, "tags": ["a", "b"]}',
         "geom": Point(1.0, 1.0)},
        {"props": '{"kind": "car", "speed": 33.0, "tags": ["c"]}',
         "geom": Point(2.0, 2.0)},
        {"props": '{"kind": "car", "nested": {"depth": 2}}',
         "geom": Point(3.0, 3.0)},
        {"props": "not json at all", "geom": Point(4.0, 4.0)},
        {"props": None, "geom": Point(5.0, 5.0)},
    ]
    store.write("ev", rows, fids=["bus", "car1", "car2", "bad", "null"])
    return store


class TestJsonPath:
    def test_equality(self, ds):
        r = ds.query("ev", "jsonPath('$.kind', props) = 'car'")
        assert set(r.table.fids) == {"car1", "car2"}

    def test_numeric_compare(self, ds):
        r = ds.query("ev", "jsonPath('$.speed', props) > 20")
        assert set(r.table.fids) == {"car1"}

    def test_nested_and_array(self, ds):
        assert set(
            ds.query("ev", "jsonPath('$.nested.depth', props) = 2").table.fids
        ) == {"car2"}
        assert set(
            ds.query("ev", "jsonPath('$.tags[1]', props) = 'b'").table.fids
        ) == {"bus"}

    def test_missing_path_never_matches(self, ds):
        # <> on a missing path is still no-match (absence, not difference)
        r = ds.query("ev", "jsonPath('$.missing', props) <> 'x'")
        assert r.count == 0

    def test_combines_with_spatial(self, ds):
        r = ds.query(
            "ev",
            "BBOX(geom, 0, 0, 2.5, 2.5) AND jsonPath('$.kind', props) = 'car'",
        )
        assert set(r.table.fids) == {"car1"}

    def test_argument_orders_and_roundtrip(self, ds):
        f1 = parse("jsonPath('$.kind', props) = 'bus'")
        f2 = parse("jsonPath(props, '$.kind') = 'bus'")
        assert f1 == f2
        assert parse(ast.to_cql(f1)) == f1  # remote-shipping round-trip

    def test_bad_path_errors(self, ds):
        with pytest.raises(CQLError):
            parse("jsonPath('nopath', props) = 1")
        f = parse("jsonPath('$.a..b', props) = 1")
        with pytest.raises(ValueError):
            ds.query("ev", f)

    def test_cross_type_never_matches(self, ds):
        # string literal vs numeric json value: no match, no crash
        assert ds.query("ev", "jsonPath('$.speed', props) = '12.5'").count == 0

    def test_bool_does_not_match_int(self, ds2=None):
        store = DataStore()
        store.create_schema(parse_spec("b", "props:String,*geom:Point"))
        store.write("b", [
            {"props": '{"flag": true, "n": 1}', "geom": Point(1.0, 1.0)},
        ], fids=["r"])
        assert store.query("b", "jsonPath('$.flag', props) = 1").count == 0
        assert store.query("b", "jsonPath('$.n', props) = 1").count == 1

    def test_explain_and_merged_accept_filters(self, ds):
        from geomesa_tpu.store.merged import MergedDataStoreView

        f = parse("jsonPath('$.kind', props) = 'car'")
        assert "JsonPathCompare" in ds.explain("ev", f)
        view = MergedDataStoreView([ds])
        assert set(view.query("ev", f).table.fids) == {"car1", "car2"}
