"""HBM buffer pool (store/bufferpool.py): SLO-weighted eviction under the
GEOMESA_TPU_HBM budget, pin-protected dispatches, donated-buffer reuse, and
ledger/residency agreement (the devmon ledger is the accounting source of
truth). ISSUE 7 satellite: eviction + budget interplay."""

import numpy as np
import pytest

from geomesa_tpu.geometry import Point
from geomesa_tpu.obs import devmon
from geomesa_tpu.obs.devmon import ResidencyLedger
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.backends import TpuBackend
from geomesa_tpu.store.bufferpool import BufferPool
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000
SPEC = "dtg:Date,*geom:Point"
Q = "BBOX(geom, -60, -45, 60, 45)"


def fill(ds, name, n=800, seed=0):
    rng = np.random.default_rng(seed)
    recs = [
        {
            "dtg": T0 + int(rng.integers(0, 86_400_000)),
            "geom": Point(float(rng.uniform(-60, 60)),
                          float(rng.uniform(-45, 45))),
        }
        for _ in range(n)
    ]
    ds.write(name, recs, fids=[f"{name}{i}" for i in range(n)])
    ds.compact(name)


class _Owner:
    """Weakref-able stand-in for a backend state object."""


class TestPoolUnit:
    """Pure pool mechanics against an isolated ledger."""

    def setup_method(self):
        from geomesa_tpu.obs.devmon import CostTable

        self.prev = devmon.install(new_ledger=ResidencyLedger(),
                                   new_costs=CostTable())

    def teardown_method(self):
        devmon.install(new_ledger=self.prev[0], new_costs=self.prev[1])

    def _entry(self, pool, t, i, nbytes=100):
        owner = _Owner()
        devmon.ledger().register(t, i, "spatial", nbytes, owner=owner)
        pool.register(t, i, "spatial", nbytes, owner=owner, fingerprint=1)
        return owner

    def test_eviction_order_slo_weighted_then_frequency(self):
        pool = BufferPool(max_total_bytes=250)
        self._entry(pool, "burning", "z3")
        self._entry(pool, "idle", "z3")
        self._entry(pool, "hot", "z3")
        # hot gets accesses; burning gets SLO protection despite 0 hits
        for _ in range(5):
            pool.touch("hot", "z3")
        pool.note_slo("burning", 0.0)   # budget exhausted → weight 2.0
        pool.note_slo("idle", 1.0)      # untroubled → weight 1.0
        pool.note_slo("hot", 1.0)
        assert pool.ensure_room(50)     # must evict exactly one: idle
        types = {e["type"] for e in pool.snapshot()["entries"]}
        assert types == {"burning", "hot"}
        assert pool.evictions == 1

    def test_pinned_entries_are_never_victims(self):
        pool = BufferPool(max_total_bytes=150)
        self._entry(pool, "a", "z3")
        with pool.pinned("a", "z3"):
            # the only candidate is pinned: room cannot be made
            assert not pool.ensure_room(100)
            assert pool.snapshot()["entries"][0]["pinned"]
        # unpinned again: eviction may proceed
        assert pool.ensure_room(100)
        assert pool.evictions == 1

    def test_eviction_lands_in_spill_report_with_group(self):
        pool = BufferPool(max_total_bytes=100)
        self._entry(pool, "t", "z3")
        assert pool.ensure_room(80)
        spilled = devmon.ledger().snapshot()["spilled"]
        assert "t.z3:spatial" in spilled

    def test_release_donates_matching_fingerprint_only(self):
        pool = BufferPool()
        self._entry(pool, "t", "z3")  # fingerprint=1
        pool.release("t", keep_fingerprint=1)
        assert pool.donated_bytes("t") == 100
        assert pool.take_donated("t", "z3", 1) is not None
        # a second take misses (already re-admitted)
        assert pool.take_donated("t", "z3", 1) is None
        # stale fingerprint drops instead of donating
        pool.release("t", keep_fingerprint=2)
        assert pool.donated_bytes("t") == 0

    def test_env_budget_parse(self, monkeypatch):
        monkeypatch.setenv("GEOMESA_TPU_HBM", "12345")
        assert BufferPool().max_total_bytes == 12345
        monkeypatch.setenv("GEOMESA_TPU_HBM", "8G")
        with pytest.raises(ValueError, match="GEOMESA_TPU_HBM"):
            BufferPool()

    def test_usage_scoped_to_own_entries(self):
        # foreign ledger entries (another store's types) never count
        # against this pool's budget
        pool = BufferPool(max_total_bytes=200)
        foreign = _Owner()
        devmon.ledger().register("other", "z3", "spatial", 10_000,
                                 owner=foreign)
        self._entry(pool, "mine", "z3")
        assert pool.ensure_room(100)  # 100 used of 200 — no eviction
        assert pool.evictions == 0


class TestPoolIntegration:
    """Pool behavior through real TpuBackend loads (tight budgets)."""

    def setup_method(self):
        from geomesa_tpu.obs.devmon import CostTable

        self.prev = devmon.install(new_ledger=ResidencyLedger(),
                                   new_costs=CostTable())

    def teardown_method(self):
        devmon.install(new_ledger=self.prev[0], new_costs=self.prev[1])

    def _two_types(self):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=1)
        t1_bytes = ds.device_residency("t1")["total_bytes"]
        assert t1_bytes > 0
        # budget fits ONE type (plus slack): loading t2 must evict t1
        ds.backend.pool.max_total_bytes = t1_bytes + 1024
        ds.create_schema(parse_spec("t2", SPEC))
        fill(ds, "t2", seed=2)
        return ds, t1_bytes

    def test_cold_type_evicted_ledger_agreement_and_exactness(self):
        ds, t1_bytes = self._two_types()
        assert not ds.device_residency("t1")["resident"]
        assert ds.device_residency("t2")["resident"]
        # ledger vs TpuBackend.residency() agreement, both types
        for t in ("t1", "t2"):
            with ds._types[t].lock:
                state = ds._types[t].backend_state
            per_index = TpuBackend.residency(state)
            assert (devmon.ledger().type_bytes(t)
                    == sum(per_index.values())
                    + ds.backend.pool.donated_bytes(t))
        # evicted groups in the spill report
        spilled = devmon.ledger().snapshot()["spilled"]
        assert any(k.startswith("t1.") and ":spatial" in k for k in spilled)
        # host fallback stays exact for the evicted type
        oracle = DataStore(backend="oracle")
        oracle.create_schema(parse_spec("t1", SPEC))
        fill(oracle, "t1", seed=1)
        assert set(ds.query("t1", Q).table.fids.tolist()) == set(
            oracle.query("t1", Q).table.fids.tolist()
        )

    def test_delete_schema_purges_pool_no_stale_readmission(self):
        import gc

        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=6)
        ds.query("t1", Q)
        assert any(e["type"] == "t1"
                   for e in ds.backend.pool.snapshot()["entries"])
        ds.delete_schema("t1")
        # nothing of the dead table survives in the pool or (after its
        # owners collect) the ledger — no budget-invisible HBM leak
        snap = ds.backend.pool.snapshot()
        assert not any(e["type"] == "t1" for e in snap["entries"])
        assert ds.backend.pool.donated_bytes("t1") == 0
        gc.collect()
        assert devmon.ledger().type_bytes("t1") == 0
        # a recreated same-name type restarts epoch/fingerprint at the
        # SAME values: without the purge, release() would donate the dead
        # table's state and take_donated re-admit it as the new backend
        # state (stale device columns under a fresh index.perm)
        reuses0 = ds.backend.pool.reuses
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", n=200, seed=7)
        assert ds.backend.pool.reuses == reuses0
        assert ds.query("t1", Q).count == 200

    def test_rename_purges_old_name_and_rebuilds_under_new(self):
        import gc

        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", n=300, seed=8)
        ds.query("t1", Q)
        ds.update_schema("t1", rename_to="t2")
        # residency is keyed by type NAME: the old key must not leak
        # (strong pool refs would hold the pre-rename device arrays —
        # and their ledger bytes — forever)
        assert not any(e["type"] == "t1"
                       for e in ds.backend.pool.snapshot()["entries"])
        gc.collect()
        assert devmon.ledger().type_bytes("t1") == 0
        assert ds.query("t2", Q).count == 300  # rebuilds under new name
        assert any(e["type"] == "t2"
                   for e in ds.backend.pool.snapshot()["entries"])

    def test_load_never_evicts_its_own_higher_priority_index(self):
        # budget fits ONE index: the load must keep the FIRST-priority
        # index (z3) and spill the later one. A later index's ensure_room
        # evicting the just-staged z3 (hits=0 = coldest candidate) would
        # invert _LOAD_PRIORITY and waste the h2d staging it just paid —
        # load-staged buffers stay pinned until the load completes.
        probe = DataStore(backend="tpu")
        probe.create_schema(parse_spec("t1", SPEC))
        fill(probe, "t1", seed=5)
        with probe._types["t1"].lock:
            per_index = TpuBackend.residency(
                probe._types["t1"].backend_state)
        assert per_index.get("z3", 0) > 0 and per_index.get("z2", 0) > 0
        ds = DataStore(backend="tpu")
        ds.backend.pool.max_total_bytes = per_index["z3"] + 1024
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=5)
        with ds._types["t1"].lock:
            state = ds._types["t1"].backend_state
        assert state["z3"] is not None, "priority index lost its residency"
        assert state["z2"] is None
        spilled = devmon.ledger().snapshot()["spilled"]
        assert any(k.startswith("t1.") and "z2" in k for k in spilled)
        # nothing stays pinned once the load is done: pressure from a
        # second type can still claim the budget afterwards
        snap = ds.backend.pool.snapshot()
        assert not any(e["pinned"] for e in snap["entries"])

    def test_recover_readmits_donated_buffers_without_h2d(self):
        from geomesa_tpu.obs import jaxmon

        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=3)
        want = ds.query("t1", Q).count
        reuses0 = ds.backend.pool.reuses
        ds.recover("t1")  # same fingerprint: donation round-trip
        assert ds.backend.pool.reuses > reuses0
        assert ds.device_residency("t1")["resident"]
        # no residency staging crosses host→device on the donated path
        mid = jaxmon.registry().counter("jax.transfer.h2d_bytes").count
        ds.recover("t1")
        after = jaxmon.registry().counter("jax.transfer.h2d_bytes").count
        assert after == mid
        assert ds.query("t1", Q).count == want

    def test_evict_device_purges_pool_and_pyramid(self):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=4)
        # build a pyramid so its device count mirror is ledgered too
        out = ds.aggregate_many("t1", ["INCLUDE"], group_by=None,
                                value_cols=[])
        assert out[0] is not None
        assert devmon.ledger().index_bytes("t1", "geoblocks") > 0
        ds.evict_device("t1")
        assert ds.backend.pool.donated_bytes("t1") == 0
        assert not ds.device_residency("t1")["resident"]
        # NOTHING of the type survives in HBM — pyramid mirror included
        assert devmon.ledger().type_bytes("t1") == 0

    def test_two_pyramid_shapes_both_pool_accounted(self):
        # two aggregation shapes on ONE type build two pyramids, each
        # with its own device mirror: the pool must hold one entry per
        # shape (a shared key would let the second REPLACE the first —
        # resident bytes invisible to the budget, evictor lost)
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec(
            "t1", "name:String,val:Double,dtg:Date,*geom:Point"))
        rng = np.random.default_rng(9)
        recs = [
            {
                "name": f"g{i % 3}",
                "val": float(i % 10),
                "dtg": T0 + int(rng.integers(0, 86_400_000)),
                "geom": Point(float(rng.uniform(-60, 60)),
                              float(rng.uniform(-45, 45))),
            }
            for i in range(600)
        ]
        ds.write("t1", recs, fids=[f"p{i}" for i in range(600)])
        ds.compact("t1")
        a = ds.aggregate_many("t1", ["INCLUDE"], group_by=None,
                              value_cols=[])
        b = ds.aggregate_many("t1", ["INCLUDE"], group_by=["name"],
                              value_cols=["val"])
        assert a[0] is not None and b[0] is not None
        snap = ds.backend.pool.snapshot()
        pyr_entries = [e for e in snap["entries"]
                       if e["index"].startswith("geoblocks")]
        assert len(pyr_entries) == 2
        assert len({e["index"] for e in pyr_entries}) == 2
        # pool bytes for the mirrors == ledgered pyramid bytes: nothing
        # resident escapes the budget's accounting
        assert (sum(e["bytes"] for e in pyr_entries)
                == devmon.ledger().index_bytes("t1", "geoblocks"))

    def test_pinned_dispatch_survives_concurrent_pressure(self):
        """A dispatch holding a pin keeps its buffers: ensure_room from
        another thread must refuse to evict them (never evict a buffer
        mid-dispatch)."""
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=5)
        pool = ds.backend.pool
        pool.max_total_bytes = 1  # everything is over budget now
        with pool.pinned("t1", "z3"):
            assert not pool.ensure_room(10**9)
            # the pinned entry is still pooled and the state still serves
            assert any(e["index"] == "z3"
                       for e in pool.snapshot()["entries"])
        # after the pin releases, pressure may take it
        assert pool.ensure_room(0) or True
        assert ds.query("t1", Q).count >= 0  # host fallback stays exact

    def test_touch_and_miss_counters(self):
        ds = DataStore(backend="tpu")
        ds.create_schema(parse_spec("t1", SPEC))
        fill(ds, "t1", seed=6)
        pool = ds.backend.pool
        h0 = pool.hits
        ds.query("t1", Q)
        assert pool.hits > h0
        # pressure-evict every buffer: the state dict survives with
        # cleared slots, so the next scan is a wanted-resident MISS
        pool.max_total_bytes = 1
        pool.ensure_room(10**9)
        m0 = pool.misses
        assert ds.query("t1", Q).count >= 0  # host fallback, still exact
        assert pool.misses > m0
