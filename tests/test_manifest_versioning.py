"""Versioned catalog manifests + per-schema index layouts (VERDICT r2
item 9, the reference's legacy key-space back-compat role:
``geomesa-index-api/.../index/z3/legacy/``, ``AttributeIndexV7.scala:1``)."""

import json
import os

import numpy as np
import pytest

import geomesa_tpu  # noqa: F401
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store import persistence
from geomesa_tpu.store.datastore import DataStore

SPEC = "name:String,dtg:Date,*geom:Point"


def _fill(ds, name="evt", n=400, seed=2):
    rng = np.random.default_rng(seed)
    lon = rng.uniform(-170, 170, n)
    lat = rng.uniform(-80, 80, n)
    # plant rows EXACTLY on legacy bin edges (the legacy curve's ceil
    # rounding differs from the current floor binning precisely there)
    lon[:8] = np.linspace(-180, 180, 8)
    lat[:8] = np.linspace(-90, 90, 8)
    t = 1_500_000_000_000 + rng.integers(0, 6 * 86_400_000, n)
    ds.write(
        name,
        [{"name": f"n{i}", "dtg": int(t[i]),
          "geom": Point(float(lon[i]), float(lat[i]))} for i in range(n)],
        fids=[str(i) for i in range(n)],
    )
    return lon, lat, t


class TestManifestVersions:
    def test_v1_manifest_still_loads(self, tmp_path):
        """A round-1/2-era catalog (version 1, no index_layout stamps)
        round-trips through the current loader."""
        ds = DataStore(backend="oracle")
        ds.create_schema(parse_spec("evt", SPEC))
        lon, lat, t = _fill(ds)
        persistence.save(ds, str(tmp_path))
        # rewrite the manifest back to the v1 shape
        mpath = tmp_path / persistence.MANIFEST
        m = json.loads(mpath.read_text())
        assert m["version"] == persistence.FORMAT_VERSION
        m["version"] = 1
        for meta in m["types"].values():
            meta.pop("index_layout", None)
        mpath.write_text(json.dumps(m))

        ds2 = persistence.load(str(tmp_path), backend="oracle")
        q = "BBOX(geom, -60, -40, 60, 40)"
        assert set(ds2.query("evt", q).table.fids.tolist()) == set(
            ds.query("evt", q).table.fids.tolist()
        )

    def test_unknown_version_rejected(self, tmp_path):
        ds = DataStore(backend="oracle")
        ds.create_schema(parse_spec("evt", SPEC))
        _fill(ds)
        persistence.save(ds, str(tmp_path))
        mpath = tmp_path / persistence.MANIFEST
        m = json.loads(mpath.read_text())
        m["version"] = 99
        mpath.write_text(json.dumps(m))
        with pytest.raises(ValueError, match="unsupported catalog version"):
            persistence.load(str(tmp_path))

    def test_upgrade_v1_to_current(self, tmp_path):
        ds = DataStore(backend="oracle")
        ds.create_schema(parse_spec("evt", SPEC))
        _fill(ds)
        persistence.save(ds, str(tmp_path))
        mpath = tmp_path / persistence.MANIFEST
        m = json.loads(mpath.read_text())
        m["version"] = 1
        for meta in m["types"].values():
            meta.pop("index_layout", None)
        mpath.write_text(json.dumps(m))

        assert persistence.upgrade(str(tmp_path)) == 1
        m2 = json.loads(mpath.read_text())
        assert m2["version"] == persistence.FORMAT_VERSION
        assert m2["types"]["evt"]["index_layout"] == "current"
        # idempotent
        assert persistence.upgrade(str(tmp_path)) == persistence.FORMAT_VERSION
        assert persistence.load(str(tmp_path), backend="oracle").query(
            "evt"
        ).count == 400


class TestLegacyIndexLayout:
    def test_legacy_layout_parity_and_roundtrip(self, tmp_path):
        """A schema on the LEGACY index layout (old curve rounding) must
        answer queries identically to the oracle — including rows planted
        on legacy bin edges — and the layout must survive save/load."""
        sft = parse_spec("evt", SPEC)
        sft.user_data["geomesa.index.layout"] = "legacy"
        results = {}
        for backend in ("tpu", "oracle"):
            s = parse_spec("evt", SPEC)
            s.user_data["geomesa.index.layout"] = "legacy"
            ds = DataStore(backend=backend)
            ds.create_schema(s)
            lon, lat, t = _fill(ds)
            ds.compact("evt")
            # verify the index really is on the legacy curves
            from geomesa_tpu.curve.legacy import LegacyZ2SFC, LegacyZ3SFC

            idx = ds._state("evt").indices
            assert isinstance(idx["z3"].sfc, LegacyZ3SFC)
            assert isinstance(idx["z2"].sfc, LegacyZ2SFC)
            qs = [
                "BBOX(geom, -180, -90, -90, 0)",   # includes edge plants
                "BBOX(geom, -1, -1, 1, 1)",
                "BBOX(geom, 100, 20, 180, 90) AND dtg DURING "
                "2017-07-14T00:00:00.000Z/2017-07-18T12:30:00.500Z",
            ]
            results[backend] = [
                set(ds.query("evt", q).table.fids.tolist()) for q in qs
            ]
            if backend == "oracle":
                persistence.save(ds, str(tmp_path))
        assert results["tpu"] == results["oracle"]

        # the manifest stamps the layout and the reload keeps it
        m = json.loads((tmp_path / persistence.MANIFEST).read_text())
        assert m["types"]["evt"]["index_layout"] == "legacy"
        ds2 = persistence.load(str(tmp_path), backend="oracle")
        from geomesa_tpu.curve.legacy import LegacyZ3SFC

        assert isinstance(ds2._state("evt").indices["z3"].sfc, LegacyZ3SFC)
        assert ds2.query("evt", "BBOX(geom, -1, -1, 1, 1)").count == len(
            results["oracle"][1]
        )
