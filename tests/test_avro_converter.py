"""Avro ingest converter: inference, rename, evolution, store round-trip."""

import io

import numpy as np

from geomesa_tpu.convert.avro_converter import AvroConverter, infer_sft_from_avro
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.io.avro import avro_schema, write_avro
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec


def _sample_table(n=20, name="evt"):
    sft = parse_spec(
        name, "name:String,count:Integer,dtg:Date,*geom:Point;geomesa.z3.interval='week'"
    )
    rng = np.random.default_rng(1)
    recs = [
        {
            "name": f"n{i}",
            "count": int(rng.integers(0, 100)),
            "dtg": 1_600_000_000_000 + i * 60_000,
            "geom": Point(float(rng.uniform(-170, 170)), float(rng.uniform(-80, 80))),
        }
        for i in range(n)
    ]
    return FeatureTable.from_records(sft, recs, [f"n{i}" for i in range(n)])


def _avro_bytes(table) -> bytes:
    buf = io.BytesIO()
    write_avro(table, buf)
    return buf.getvalue()


class TestAvroConverter:
    def test_resolved_fast_path(self):
        t = _sample_table()
        conv = AvroConverter(sft=t.sft)
        out = conv.convert_bytes(_avro_bytes(t))
        assert len(out) == len(t)
        assert list(out.fids) == list(t.fids)
        np.testing.assert_allclose(out.geom_column().x, t.geom_column().x)

    def test_inferred_schema(self):
        t = _sample_table()
        conv = AvroConverter()  # no SFT: infer from writer schema
        out = conv.convert_bytes(_avro_bytes(t))
        assert conv.sft is not None
        got = {a.name: a.type.name for a in conv.sft.attributes}
        assert got["name"] == "STRING"
        assert got["count"] == "INT"
        assert got["dtg"] == "DATE"
        assert conv.sft.geom_field == "geom"
        assert len(out) == len(t)
        # geometry decoded from WKB bytes (generic Geometry column: bbox SoA)
        g = out.geom_column()
        assert g.bounds is not None
        np.testing.assert_allclose(g.bounds[:, 0], t.geom_column().x)

    def test_infer_sft_mapping(self):
        schema = avro_schema(_sample_table().sft)
        sft = infer_sft_from_avro(schema, "inferred")
        assert sft.name == "inferred"
        assert sft.dtg_field == "dtg"

    def test_rename(self):
        t = _sample_table()
        target = parse_spec(
            "evt2",
            "label:String,count:Integer,dtg:Date,*geom:Point",
        )
        conv = AvroConverter(sft=target, rename={"name": "label"})
        out = conv.convert_bytes(_avro_bytes(t))
        assert list(out.columns["label"].values) == [f"n{i}" for i in range(20)]

    def test_evolution_reader_adds_field(self):
        t = _sample_table()
        evolved = parse_spec(
            "evt",
            "name:String,count:Integer,flag:Boolean,dtg:Date,*geom:Point",
        )
        conv = AvroConverter(sft=evolved)
        out = conv.convert_bytes(_avro_bytes(t))
        assert len(out) == len(t)
        col = out.columns["flag"]
        assert col.valid is not None and not col.valid.any()  # all null

    def test_header_only_inference(self, tmp_path):
        t = _sample_table()
        p = tmp_path / "e.avro"
        write_avro(t, str(p))
        conv = AvroConverter()
        sft = conv.infer_from(str(p))
        assert sft.dtg_field == "dtg" and sft.geom_field == "geom"

    def test_embedded_fids_detected(self):
        t = _sample_table()
        conv = AvroConverter(sft=t.sft)
        conv.convert_bytes(_avro_bytes(t))
        # write_avro embeds __fid__: ids are stable, no renumber needed
        assert conv.id_field == "__fid__"

    def test_foreign_file_without_fids(self):
        # hand-build a container whose writer schema has NO __fid__ field
        import json
        import os

        from geomesa_tpu.io import avro as A

        schema = {
            "type": "record",
            "name": "ext",
            "fields": [
                {"name": "name", "type": "string"},
                {"name": "dtg", "type": "long"},
                {"name": "geom", "type": "bytes"},
            ],
        }
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.geometry.wkb import to_wkb

        buf = io.BytesIO()
        buf.write(A.MAGIC)
        mb = io.BytesIO()
        A._write_long(mb, 2)
        for k, v in (
            ("avro.schema", json.dumps(schema).encode()),
            ("avro.codec", b"null"),
        ):
            A._write_bytes(mb, k.encode())
            A._write_bytes(mb, v)
        A._write_long(mb, 0)
        buf.write(mb.getvalue())
        sync = os.urandom(16)
        buf.write(sync)
        block = io.BytesIO()
        for i in range(3):
            A._encode_record(
                block, schema,
                {"name": f"x{i}", "dtg": 1_600_000_000_000 + i,
                 "geom": to_wkb(Point(float(i), 1.0))},
            )
        A._write_long(buf, 3)
        A._write_long(buf, len(block.getvalue()))
        buf.write(block.getvalue())
        buf.write(sync)

        conv = AvroConverter()
        out = conv.convert_bytes(buf.getvalue())
        assert conv.id_field is None  # synthesized row-number fids
        assert len(out) == 3
        assert list(out.fids) == ["0", "1", "2"]

    def test_store_ingest_roundtrip(self, tmp_path):
        from geomesa_tpu.store.datastore import DataStore

        t = _sample_table()
        p = tmp_path / "events.avro"
        write_avro(t, str(p))
        conv = AvroConverter()
        table = conv.convert_path(str(p))
        ds = DataStore()
        ds.create_schema(conv.sft)
        ds.write(conv.sft.name, table)
        r = ds.query(conv.sft.name, "count >= 0")
        assert len(r.table) == len(t)
