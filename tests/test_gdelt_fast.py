"""Native GDELT fast ingest: parity with the expression converter
(reference converter config + data-loader hot path — SURVEY.md §2.16/§2.9)."""

import numpy as np
import pytest

from geomesa_tpu.convert.gdelt import gdelt_converter, gdelt_fast_table, gdelt_sft


def synth_gdelt_tsv(n=500, seed=4, with_bad_rows=True):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        f = [""] * 57
        f[0] = str(400_000_000 + i)
        f[1] = f"2017{rng.integers(1, 13):02d}{rng.integers(1, 29):02d}"
        f[5] = "USA"
        f[6] = f"ACTOR{i % 9}"
        f[7] = "US"
        f[15] = "CHN"
        f[16] = f"OTHER{i % 5}"
        f[17] = "CN"
        f[25] = str(i % 2)
        f[26] = "043"
        f[27] = "043"
        f[28] = "04"
        f[29] = str(1 + i % 4)
        f[30] = f"{rng.uniform(-10, 10):.1f}"
        f[31] = str(int(rng.integers(1, 100)))
        f[32] = str(int(rng.integers(1, 10)))
        f[33] = str(int(rng.integers(1, 50)))
        f[34] = f"{rng.uniform(-20, 20):.6f}"
        f[39] = f"{rng.uniform(-90, 90):.4f}"
        f[40] = f"{rng.uniform(-180, 180):.4f}"
        lines.append("\t".join(f))
    if with_bad_rows:
        bad = [""] * 57
        bad[0] = "badrow"
        bad[1] = "20170701"
        # no coordinates -> dropped by both paths
        lines.append("\t".join(bad))
    return ("\n".join(lines) + "\n").encode()


class TestGdeltFast:
    def test_parity_with_converter(self, tmp_path):
        data = synth_gdelt_tsv()
        p = tmp_path / "gdelt.tsv"
        p.write_bytes(data)
        fast = gdelt_fast_table(data)
        conv = gdelt_converter().convert_path(str(p))
        assert len(fast) == len(conv) == 500
        np.testing.assert_array_equal(fast.fids, conv.fids)
        np.testing.assert_array_equal(fast.dtg_millis(), conv.dtg_millis())
        np.testing.assert_allclose(fast.geom_column().x, conv.geom_column().x)
        np.testing.assert_allclose(fast.geom_column().y, conv.geom_column().y)
        for attr in ("actor1Name", "eventCode", "quadClass", "goldsteinScale",
                     "numMentions", "avgTone", "isRootEvent"):
            a = fast.columns[attr].values
            b = conv.columns[attr].values
            if a.dtype.kind == "f":
                np.testing.assert_allclose(a, b.astype(a.dtype))
            else:
                np.testing.assert_array_equal(a.astype(str), b.astype(str))

    def test_path_input(self, tmp_path):
        p = tmp_path / "g.tsv"
        p.write_bytes(synth_gdelt_tsv(50, with_bad_rows=False))
        t = gdelt_fast_table(str(p))
        assert len(t) == 50

    def test_store_roundtrip(self):
        from geomesa_tpu.store.datastore import DataStore

        t = gdelt_fast_table(synth_gdelt_tsv(300, with_bad_rows=False))
        ds = DataStore(backend="tpu")
        ds.create_schema(gdelt_sft())
        ds.write("gdelt", t)
        r = ds.query("gdelt", "BBOX(geom, -90, -45, 90, 45)")
        gx = t.geom_column().x
        gy = t.geom_column().y
        exp = int(((gx >= -90) & (gx <= 90) & (gy >= -45) & (gy <= 45)).sum())
        assert r.count == exp
