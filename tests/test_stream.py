"""Streaming store: message codec, spatial index, live cache, bus, queries.

Mirrors the reference's kafka-datastore test strategy (SURVEY.md §2.10, §4):
change messages round-trip; consumers replay the log; caches expire by event
time; queries over the live cache match brute force.
"""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import LineString, Point, box
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.stream import (
    Clear,
    Delete,
    GeoMessageSerializer,
    MessageBus,
    Put,
    StreamingDataStore,
)
from geomesa_tpu.utils.spatial_index import BucketIndex, SizeSeparatedBucketIndex

SFT = parse_spec("adsb", "dtg:Date,*geom:Point:srid=4326,callsign:String,alt:Integer")


class TestGeoMessageSerializer:
    def test_put_round_trip(self):
        ser = GeoMessageSerializer(SFT)
        rec = {"dtg": 1_600_000_000_000, "geom": Point(1.5, -2.5), "callsign": "UAL123", "alt": 35000}
        msg = Put("f1", rec, 42)
        out = ser.deserialize(ser.serialize(msg))
        assert out == Put("f1", rec, 42)

    def test_put_with_nulls(self):
        ser = GeoMessageSerializer(SFT)
        rec = {"dtg": 5, "geom": Point(0, 0), "callsign": None, "alt": None}
        out = ser.deserialize(ser.serialize(Put("x", rec, 1)))
        assert out.record["callsign"] is None and out.record["alt"] is None

    def test_delete_clear_round_trip(self):
        ser = GeoMessageSerializer(SFT)
        assert ser.deserialize(ser.serialize(Delete("f9", 7))) == Delete("f9", 7)
        assert ser.deserialize(ser.serialize(Clear(3))) == Clear(3)

    def test_line_geometry(self):
        sft = parse_spec("trk", "dtg:Date,*geom:LineString:srid=4326")
        ser = GeoMessageSerializer(sft)
        rec = {"dtg": 1, "geom": LineString([[0, 0], [1, 1], [2, 0]])}
        out = ser.deserialize(ser.serialize(Put("t", rec, 1)))
        assert out.record["geom"] == rec["geom"]


class TestSpatialIndexes:
    @pytest.mark.parametrize("cls", [BucketIndex, SizeSeparatedBucketIndex])
    def test_insert_query_remove(self, cls):
        idx = cls()
        idx.insert((10, 10, 10, 10), "a", "A")
        idx.insert((20, 20, 20, 20), "b", "B")
        assert sorted(idx.query((5, 5, 15, 15))) == ["A"]
        assert sorted(idx.query((0, 0, 30, 30))) == ["A", "B"]
        assert idx.size() == 2
        assert idx.remove((10, 10, 10, 10), "a") == "A"
        assert idx.size() == 1 and list(idx.query((5, 5, 15, 15))) == []

    def test_bucket_index_no_duplicates_for_spanning_entry(self):
        idx = BucketIndex()
        idx.insert((-10, -10, 10, 10), "big", "BIG")  # spans many cells
        assert list(idx.query((-20, -20, 20, 20))) == ["BIG"]
        assert idx.size() == 1

    def test_distinct_entries_with_equal_values(self):
        idx = BucketIndex()
        idx.insert((0, 0, 0, 0), "a", "X")
        idx.insert((5, 5, 5, 5), "b", "X")  # same (interned) value object
        assert list(idx.query((-1, -1, 6, 6))) == ["X", "X"]
        assert len(list(idx.values())) == 2

    def test_size_separated_tiers(self):
        idx = SizeSeparatedBucketIndex()
        idx.insert((0, 0, 0.5, 0.5), "small", "S")
        idx.insert((-90, -45, 90, 45), "huge", "H")
        assert sorted(idx.query((0, 0, 1, 1))) == ["H", "S"]

    def test_brute_force_parity(self):
        rng = np.random.default_rng(5)
        pts = rng.uniform(-170, 170, size=(300, 2))
        idx = BucketIndex()
        for i, (x, y) in enumerate(pts):
            idx.insert((x, y, x, y), f"f{i}", i)
        qbox = (-50.0, -30.0, 40.0, 60.0)
        got = sorted(idx.query(qbox))
        # bucket query is a candidate superset; exact check via coordinates
        exact = [
            i
            for i, (x, y) in enumerate(pts)
            if qbox[0] <= x <= qbox[2] and qbox[1] <= y <= qbox[3]
        ]
        assert set(exact) <= set(got)


def _store(expiry_ms=None):
    ds = StreamingDataStore(expiry_ms=expiry_ms)
    ds.create_schema(SFT)
    return ds


class TestStreamingDataStore:
    def test_put_query(self):
        ds = _store()
        for i in range(10):
            ds.put("adsb", f"f{i}", {"dtg": 1000 + i, "geom": Point(i * 10 - 45, 0), "callsign": f"CS{i}", "alt": 1000 * i}, ts=1000 + i)
        res = ds.query("adsb", "BBOX(geom, -50, -10, 0, 10)")
        assert res.count == 5
        res = ds.query("adsb", "alt > 7000")
        assert res.count == 2

    def test_upsert_moves_feature(self):
        ds = _store()
        ds.put("adsb", "f1", {"dtg": 1, "geom": Point(0, 0), "callsign": "A", "alt": 1}, ts=1)
        ds.put("adsb", "f1", {"dtg": 2, "geom": Point(100, 50), "callsign": "A", "alt": 2}, ts=2)
        assert ds.query("adsb").count == 1
        assert ds.query("adsb", "BBOX(geom, -1, -1, 1, 1)").count == 0
        assert ds.query("adsb", "BBOX(geom, 99, 49, 101, 51)").count == 1

    def test_delete_and_clear(self):
        ds = _store()
        for i in range(3):
            ds.put("adsb", f"f{i}", {"dtg": i, "geom": Point(i, i), "callsign": "X", "alt": i}, ts=i)
        ds.delete("adsb", "f1")
        assert ds.query("adsb").count == 2
        ds.clear("adsb")
        assert ds.query("adsb").count == 0

    def test_event_time_expiry(self):
        ds = _store(expiry_ms=1000)
        ds.put("adsb", "old", {"dtg": 1, "geom": Point(0, 0), "callsign": "O", "alt": 0}, ts=10_000)
        ds.put("adsb", "new", {"dtg": 2, "geom": Point(1, 1), "callsign": "N", "alt": 0}, ts=11_500)
        res = ds.query("adsb", now_ms=11_800)
        assert res.count == 1 and res.table.fids[0] == "new"

    def test_late_consumer_replays_log(self):
        bus = MessageBus()
        ds = StreamingDataStore(bus=bus)
        ds.create_schema(SFT)
        ds.put("adsb", "f1", {"dtg": 1, "geom": Point(5, 5), "callsign": "A", "alt": 1}, ts=1)
        # a second store (consumer group) joining later sees the same state
        ds2 = StreamingDataStore(bus=bus)
        ds2.create_schema(SFT)
        assert ds2.query("adsb").count == 1
        # and stays live for subsequent messages
        ds.put("adsb", "f2", {"dtg": 2, "geom": Point(6, 6), "callsign": "B", "alt": 2}, ts=2)
        assert ds2.query("adsb").count == 2

    def test_late_consumer_replay_preserves_clear_ordering(self):
        bus = MessageBus()
        ds = StreamingDataStore(bus=bus)
        ds.create_schema(SFT)
        ds.put("adsb", "f1", {"dtg": 1, "geom": Point(5, 5), "callsign": "A", "alt": 1}, ts=1)
        ds.clear("adsb")
        ds.put("adsb", "f2", {"dtg": 2, "geom": Point(6, 6), "callsign": "B", "alt": 2}, ts=2)
        late = StreamingDataStore(bus=bus)
        late.create_schema(SFT)
        # replay must apply Clear after f1 and before f2: only f2 survives
        assert [s.fid for s in late.cache("adsb").states()] == ["f2"]

    def test_streaming_visibility_enforced(self):
        sft = parse_spec(
            "sec", "dtg:Date,*geom:Point:srid=4326,vis:String;geomesa.vis.field='vis'"
        )
        ds = StreamingDataStore()
        ds.create_schema(sft)
        ds.put("sec", "open", {"dtg": 1, "geom": Point(0, 0), "vis": ""}, ts=1)
        ds.put("sec", "secret", {"dtg": 2, "geom": Point(1, 1), "vis": "secret"}, ts=2)
        assert ds.query("sec").count == 2  # no auths given: unrestricted
        assert ds.query("sec", Query(auths=[])).count == 1
        assert ds.query("sec", Query(auths=["secret"])).count == 2

    def test_streaming_aggregation_hints(self):
        ds = _store()
        for i in range(10):
            ds.put("adsb", f"f{i}", {"dtg": i, "geom": Point(i, 0), "callsign": "X", "alt": i}, ts=i)
        res = ds.query("adsb", Query(hints={"stats": "Count()"}))
        assert res.stats["Count()"].count == 10

    def test_query_parity_vs_brute_force(self):
        ds = _store()
        rng = np.random.default_rng(11)
        xs = rng.uniform(-180, 180, 500)
        ys = rng.uniform(-90, 90, 500)
        alts = rng.integers(0, 40000, 500)
        for i in range(500):
            ds.put("adsb", f"f{i}", {"dtg": i, "geom": Point(xs[i], ys[i]), "callsign": "C", "alt": int(alts[i])}, ts=i)
        res = ds.query("adsb", "BBOX(geom, -30, -30, 30, 30) AND alt < 20000")
        exact = ((xs >= -30) & (xs <= 30) & (ys >= -30) & (ys <= 30) & (alts < 20000)).sum()
        assert res.count == exact

    def test_sort_and_limit(self):
        ds = _store()
        for i in range(5):
            ds.put("adsb", f"f{i}", {"dtg": i, "geom": Point(i, i), "callsign": "Z", "alt": 100 - i}, ts=i)
        res = ds.query("adsb", Query(filter=None, sort_by=("alt", False), limit=2))
        assert list(res.table.columns["alt"].values[:2]) == [96, 97]


class TestThreadedConsumers:
    def test_async_consumers_apply_all_messages(self):
        from geomesa_tpu.stream.datastore import MessageBus, StreamingDataStore

        sds = StreamingDataStore(bus=MessageBus(partitions=4), async_consumers=3)
        sds.create_schema("a", "name:String,dtg:Date,*geom:Point")
        from geomesa_tpu.geometry.types import Point

        for i in range(500):
            sds.put("a", f"f{i}", {"name": "x", "dtg": i, "geom": Point(i % 90, 0)}, ts=i)
        assert sds.drain("a", timeout_s=10)
        assert sds.cache("a").size() == 500
        sds.close()

    def test_clear_barrier_across_partitions(self):
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.stream.datastore import MessageBus, StreamingDataStore

        sds = StreamingDataStore(bus=MessageBus(partitions=4), async_consumers=2)
        sds.create_schema("b", "dtg:Date,*geom:Point")
        for i in range(100):
            sds.put("b", f"f{i}", {"dtg": i, "geom": Point(0, 0)}, ts=i)
        sds.clear("b")
        # puts AFTER the clear must survive it
        for i in range(40):
            sds.put("b", f"g{i}", {"dtg": i, "geom": Point(1, 1)}, ts=i)
        assert sds.drain("b", timeout_s=10)
        fids = {s.fid for s in sds.cache("b").states()}
        assert fids == {f"g{i}" for i in range(40)}
        sds.close()


class TestLambdaStore:
    def test_persist_moves_aged_features(self):
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lds = LambdaDataStore(persist_age_ms=1000, persist_interval_s=None,
                              consumers=2)
        lds.create_schema("t", "name:String,dtg:Date,*geom:Point")
        now = 1_500_000_000_000
        for i in range(60):
            ts = now - (5000 if i < 40 else 0)  # 40 old, 20 fresh
            lds.write("t", f"f{i}", {"name": f"n{i}", "dtg": ts,
                                     "geom": Point(i % 90, i % 45)}, ts=ts)
        assert lds.stream.drain("t")
        moved = lds.persist_once("t", now_ms=now)
        assert moved == 40
        assert lds.hot_count("t") == 20
        assert lds.cold.query("t", "INCLUDE").count == 40
        # merged query sees everything exactly once
        r = lds.query("t", "INCLUDE")
        assert sorted(r.table.fids.tolist()) == sorted(f"f{i}" for i in range(60))
        lds.close()

    def test_update_racing_persist_stays_hot(self):
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lds = LambdaDataStore(persist_age_ms=1000, persist_interval_s=None,
                              consumers=1)
        lds.create_schema("t", "name:String,dtg:Date,*geom:Point")
        now = 1_500_000_000_000
        lds.write("t", "f0", {"name": "old", "dtg": now - 5000,
                              "geom": Point(0, 0)}, ts=now - 5000)
        assert lds.stream.drain("t")
        # persist the old generation, then update — newer state stays hot
        assert lds.persist_once("t", now_ms=now) == 1
        lds.write("t", "f0", {"name": "new", "dtg": now, "geom": Point(1, 1)},
                  ts=now)
        assert lds.stream.drain("t")
        r = lds.query("t", "INCLUDE")
        assert r.count == 1
        assert r.table.record(0)["name"] == "new"
        # a later persist supersedes the cold copy instead of duplicating
        assert lds.persist_once("t", now_ms=now + 5000) == 1
        r2 = lds.query("t", "INCLUDE")
        assert r2.count == 1 and r2.table.record(0)["name"] == "new"
        lds.close()

    def test_soak_concurrent_ingest_query_persist(self):
        """Writers + queriers + the persister thread all running: no feature
        lost, none duplicated (VERDICT r1 item 8 'done' criterion)."""
        import threading
        import time as _time

        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lds = LambdaDataStore(persist_age_ms=50, persist_interval_s=0.05,
                              consumers=3)
        lds.create_schema("s", "name:String,dtg:Date,*geom:Point")
        n_writers, per_writer = 4, 150
        errs = []

        def writer(w):
            try:
                for i in range(per_writer):
                    ts = int(_time.time() * 1000)
                    lds.write("s", f"w{w}-{i}",
                              {"name": f"n{w}", "dtg": ts,
                               "geom": Point((w * 37 + i) % 170 - 80, i % 80 - 40)},
                              ts=ts)
                    if i % 25 == 0:
                        _time.sleep(0.002)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        stop = threading.Event()

        def querier():
            try:
                while not stop.is_set():
                    lds.query("s", "BBOX(geom, -90, -45, 90, 45)")
                    _time.sleep(0.005)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(w,)) for w in range(n_writers)]
        qt = threading.Thread(target=querier)
        qt.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert lds.stream.drain("s", timeout_s=15)
        _time.sleep(0.3)  # a few persister passes
        stop.set()
        qt.join()
        assert not errs, errs
        r = lds.query("s", "INCLUDE")
        expect = {f"w{w}-{i}" for w in range(n_writers) for i in range(per_writer)}
        got = r.table.fids.tolist()
        assert len(got) == len(set(got)), "duplicated features"
        assert set(got) == expect, (
            f"lost {len(expect - set(got))} / extra {len(set(got) - expect)}"
        )
        lds.close()


class TestLambdaDelete:
    def test_delete_spans_both_tiers(self):
        from geomesa_tpu.geometry.types import Point
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lds = LambdaDataStore(persist_age_ms=1000, persist_interval_s=None,
                              consumers=2)
        lds.create_schema("t", "name:String,dtg:Date,*geom:Point")
        now = 1_500_000_000_000
        for i in range(10):
            lds.write("t", f"f{i}", {"name": f"n{i}", "dtg": now - 5000,
                                     "geom": Point(i, i)}, ts=now - 5000)
        assert lds.stream.drain("t")
        assert lds.persist_once("t", now_ms=now) == 10  # all cold now
        lds.delete("t", "f3")
        assert lds.stream.drain("t")
        r = lds.query("t", "INCLUDE")
        assert sorted(r.table.fids.tolist()) == sorted(
            f"f{i}" for i in range(10) if i != 3
        )
        # a persist pass cannot resurrect the deleted feature
        lds.persist_once("t", now_ms=now + 10_000)
        assert "f3" not in set(lds.query("t", "INCLUDE").table.fids.tolist())
        # re-put after delete revives it
        lds.write("t", "f3", {"name": "back", "dtg": now, "geom": Point(3, 3)},
                  ts=now)
        assert lds.stream.drain("t")
        got = lds.query("t", "INCLUDE")
        assert "f3" in set(got.table.fids.tolist())
        lds.close()
