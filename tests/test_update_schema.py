"""Schema evolution: append attributes, keywords, rename (updateSchema role)."""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec
from geomesa_tpu.store.datastore import DataStore

T0 = 1_600_000_000_000
SPEC = "name:String,dtg:Date,*geom:Point;geomesa.z3.interval='week'"


def _store(n=200):
    rng = np.random.default_rng(7)
    ds = DataStore()
    sft = parse_spec("evt", SPEC)
    ds.create_schema(sft)
    recs = [
        {"name": f"n{i}", "dtg": T0 + i,
         "geom": Point(float(rng.uniform(-170, 170)), float(rng.uniform(-80, 80)))}
        for i in range(n)
    ]
    ds.write("evt", FeatureTable.from_records(sft, recs, [f"n{i}" for i in range(n)]))
    return ds


class TestUpdateSchema:
    def test_append_attribute_nulls_existing(self):
        ds = _store()
        before = ds.query("evt", "BBOX(geom, -180, -90, 180, 90)").count
        sft = ds.update_schema("evt", add="severity:Integer")
        assert [a.name for a in sft.attributes] == ["name", "dtg", "geom", "severity"]
        r = ds.query("evt", "BBOX(geom, -180, -90, 180, 90)")
        assert r.count == before
        col = r.table.columns["severity"]
        assert col.valid is not None and not col.valid.any()
        # new writes can populate the new attribute, old rows stay null
        ds.write("evt", [{"name": "x", "severity": 7, "dtg": T0,
                          "geom": Point(1.0, 2.0)}], fids=["new1"])
        got = ds.query("evt", "severity = 7")
        assert list(got.table.fids) == ["new1"]

    def test_added_indexed_attribute_planned(self):
        ds = _store()
        ds.update_schema("evt", add="code:String:index=true")
        ds.write("evt", [{"name": "y", "code": "abc", "dtg": T0,
                          "geom": Point(3.0, 4.0)}], fids=["c1"])
        ds.compact("evt")
        plan = ds.explain("evt", "code = 'abc'")
        assert "attr" in plan.lower()
        assert list(ds.query("evt", "code = 'abc'").table.fids) == ["c1"]

    def test_keywords_and_rename(self):
        ds = _store(20)
        sft = ds.update_schema("evt", keywords=["gdelt", "test"],
                               rename_to="events2")
        assert sft.name == "events2"
        assert sft.user_data["geomesa.keywords"] == "gdelt,test"
        assert "events2" in ds.list_schemas() and "evt" not in ds.list_schemas()
        assert ds.query("events2", "BBOX(geom, -180, -90, 180, 90)").count == 20

    def test_restrictions(self):
        ds = _store(10)
        with pytest.raises(ValueError, match="geometry"):
            ds.update_schema("evt", add="g2:Point")
        with pytest.raises(ValueError, match="exists"):
            ds.update_schema("evt", add="name:String")
        ds2 = DataStore()
        ds2.create_schema(parse_spec("other", SPEC))
        with pytest.raises(KeyError):
            ds2.update_schema("missing", add="x:Integer")

    def test_persistence_roundtrip(self, tmp_path):
        from geomesa_tpu.store import persistence

        ds = _store(50)
        ds.update_schema("evt", add="severity:Integer")
        ds.write("evt", [{"name": "z", "severity": 3, "dtg": T0,
                          "geom": Point(5.0, 5.0)}], fids=["z1"])
        persistence.save(ds, str(tmp_path / "cat"))
        ds2 = persistence.load(str(tmp_path / "cat"))
        sft2 = ds2.get_schema("evt")
        assert any(a.name == "severity" for a in sft2.attributes)
        assert ds2.query("evt", "severity = 3").count == 1
        assert ds2.stats_count("evt", exact=True) == 51

    def test_empty_store_evolution(self):
        ds = DataStore()
        ds.create_schema(parse_spec("evt", SPEC))
        sft = ds.update_schema("evt", add="severity:Integer")
        assert any(a.name == "severity" for a in sft.attributes)
        ds.write("evt", [{"name": "a", "severity": 1, "dtg": T0,
                          "geom": Point(0.0, 0.0)}], fids=["a"])
        assert ds.query("evt", "severity = 1").count == 1

    def test_cli_update_schema(self, tmp_path):
        from geomesa_tpu.cli.__main__ import main
        from geomesa_tpu.store import persistence

        ds = _store(10)
        cat = tmp_path / "cat"
        persistence.save(ds, str(cat))
        main(["update-schema", "-c", str(cat), "-n", "evt",
              "--add", "severity:Integer", "--keywords", "a,b"])
        ds2 = persistence.load(str(cat))
        sft = ds2.get_schema("evt")
        assert any(a.name == "severity" for a in sft.attributes)
        assert sft.user_data["geomesa.keywords"] == "a,b"

    def test_added_date_does_not_become_dtg(self):
        ds = DataStore()
        ds.create_schema(parse_spec("nodtg", "name:String,*geom:Point"))
        ds.write("nodtg", [{"name": "a", "geom": Point(1.0, 1.0)}], fids=["a"])
        sft = ds.update_schema("nodtg", add="seen:Date")
        assert sft.dtg_field is None  # pinned: no retroactive temporal axis
        # writes without the new date still validate
        ds.write("nodtg", [{"name": "b", "geom": Point(2.0, 2.0)}], fids=["b"])
        assert ds.query("nodtg", "BBOX(geom, 0, 0, 3, 3)").count == 2

    def test_existing_dtg_pinned_when_date_added(self):
        ds = _store(10)
        sft = ds.update_schema("evt", add="seen:Date")
        assert sft.dtg_field == "dtg"  # not the appended all-null column

    def test_failed_evolution_leaves_state_intact(self, monkeypatch):
        ds = _store(20)
        import geomesa_tpu.store.datastore as dsmod

        def boom(sft):
            raise RuntimeError("index build exploded")

        monkeypatch.setattr(dsmod, "build_indices", boom)
        with pytest.raises(RuntimeError):
            ds.update_schema("evt", add="severity:Integer")
        sft = ds.get_schema("evt")
        assert all(a.name != "severity" for a in sft.attributes)
        monkeypatch.undo()
        # store still fully functional on the old schema
        assert ds.query("evt", "BBOX(geom, -180, -90, 180, 90)").count == 20

    def test_rename_keeps_interceptors(self):
        ds = _store(10)
        calls = []

        def icp(sft, q):
            calls.append(1)
            return q

        ds.register_interceptor("evt", icp)
        ds.update_schema("evt", rename_to="evt2")
        ds.query("evt2", "BBOX(geom, -180, -90, 180, 90)")
        assert calls  # interceptor followed the rename

    def test_evolution_with_pending_delta(self):
        ds = _store(50)
        # unsorted hot-tier rows pending at evolution time
        ds.write("evt", [{"name": "hot", "dtg": T0, "geom": Point(9.0, 9.0)}],
                 fids=["hot1"])
        ds.update_schema("evt", add="severity:Integer")
        r = ds.query("evt", "BBOX(geom, -180, -90, 180, 90)")
        assert r.count == 51
        assert "hot1" in set(r.table.fids)
