"""CQL property-function predicates (FastFilterFactory function-expression
role — SURVEY.md §2.2): func(attr) <op> literal."""

import numpy as np
import pytest

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.cql import CQLError, parse as parse_cql
from geomesa_tpu.geometry import Point
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import parse_spec

SPEC = "name:String,age:Integer,score:Double,dtg:Date,*geom:Point"
T0 = 1_498_867_200_000


def table():
    sft = parse_spec("t", SPEC)
    recs = [
        {"name": "Alpha", "age": -5, "score": 1.6, "dtg": T0, "geom": Point(0, 0)},
        {"name": "beta ", "age": 3, "score": -2.4, "dtg": T0 + 1000, "geom": Point(1, 1)},
        {"name": None, "age": 10, "score": 0.5, "dtg": T0 + 2000, "geom": Point(2, 2)},
    ]
    return FeatureTable.from_records(sft, recs, ["a", "b", "c"])


class TestFuncCompare:
    def test_str_functions(self):
        t = table()
        assert parse_cql("strToUpperCase(name) = 'ALPHA'").mask(t).tolist() == [True, False, False]
        assert parse_cql("strToLowerCase(name) = 'alpha'").mask(t).tolist() == [True, False, False]
        assert parse_cql("strTrim(name) = 'beta'").mask(t).tolist() == [False, True, False]
        assert parse_cql("strLength(name) = 5").mask(t).tolist() == [True, True, False]

    def test_numeric_functions(self):
        t = table()
        assert parse_cql("abs(age) = 5").mask(t).tolist() == [True, False, False]
        assert parse_cql("floor(score) = 1").mask(t).tolist() == [True, False, False]
        assert parse_cql("ceil(score) = -2").mask(t).tolist() == [False, True, False]
        assert parse_cql("abs(score) > 2").mask(t).tolist() == [False, True, False]

    def test_date_to_long(self):
        t = table()
        m = parse_cql(f"dateToLong(dtg) >= {T0 + 1000}").mask(t)
        assert m.tolist() == [False, True, True]

    def test_null_never_matches(self):
        t = table()
        # name is null in row c: no function comparison may match it
        assert not parse_cql("strLength(name) < 100").mask(t)[2]

    def test_round_trip(self):
        f1 = parse_cql("strToLowerCase(name) <> 'x'")
        f2 = parse_cql(ast.to_cql(f1))
        assert f1 == f2

    def test_composes_with_planning(self):
        from geomesa_tpu.store.datastore import DataStore

        for backend in ("oracle", "tpu"):
            ds = DataStore(backend=backend)
            ds.create_schema(parse_spec("t", SPEC))
            rng = np.random.default_rng(4)
            recs = [
                {"name": f"N{i % 7}", "age": int(rng.integers(-50, 50)),
                 "score": 0.0, "dtg": T0 + i,
                 "geom": Point(float(i % 30), float(i % 15))}
                for i in range(500)
            ]
            ds.write("t", recs, fids=[str(i) for i in range(500)])
            r = ds.query(
                "t", "BBOX(geom, 0, 0, 10, 10) AND strToLowerCase(name) = 'n3'"
            )
            want = {
                str(i) for i in range(500)
                if i % 30 <= 10 and i % 15 <= 10 and i % 7 == 3
            }
            assert set(r.table.fids.tolist()) == want

    def test_parse_error(self):
        with pytest.raises(CQLError):
            parse_cql("strLength(name) LIKE 'x'")

    def test_property_named_like_function(self):
        # an attribute literally named 'abs' still parses as a plain compare
        f = parse_cql("abs > 3")
        assert isinstance(f, ast.Compare) and f.prop == "abs"
        f = parse_cql("floor BETWEEN 1 AND 2")
        assert isinstance(f, ast.Between) and f.prop == "floor"
